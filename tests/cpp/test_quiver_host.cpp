// Native host-runtime unit tests (plain asserts; no gtest in the image).
// Mirrors the reference's C++ test tier (SURVEY §4: tests/cpp/ property
// tests): CSR round-trip, sample validity, reindex first-occurrence order.
//
// Build + run:  cmake -S . -B build -G Ninja && cmake --build build && ./build/test_quiver_host

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <random>
#include <set>
#include <vector>

// the library is a single TU with C linkage — include it directly
#include "../../quiver_tpu/native/quiver_host.cpp"

static void test_csr_roundtrip() {
  // random COO -> CSR -> expand back; multiset equality per row
  std::mt19937_64 rng(0);
  const int64_t N = 57, E = 700;
  std::vector<int64_t> rows(E), cols(E);
  std::uniform_int_distribution<int64_t> d(0, N - 1);
  for (int64_t i = 0; i < E; ++i) { rows[i] = d(rng); cols[i] = d(rng); }

  std::vector<int64_t> indptr(N + 1), eid(E);
  std::vector<int32_t> indices(E);
  csr_from_coo_i64(rows.data(), cols.data(), E, N, indptr.data(),
                   indices.data(), eid.data());

  assert(indptr[0] == 0 && indptr[N] == E);
  std::multiset<std::pair<int64_t, int64_t>> in, out;
  for (int64_t i = 0; i < E; ++i) in.emplace(rows[i], cols[i]);
  for (int64_t v = 0; v < N; ++v)
    for (int64_t j = indptr[v]; j < indptr[v + 1]; ++j)
      out.emplace(v, indices[j]);
  assert(in == out);
  // eid maps each CSR slot back to its COO position
  for (int64_t v = 0; v < N; ++v)
    for (int64_t j = indptr[v]; j < indptr[v + 1]; ++j) {
      assert(rows[eid[j]] == v);
      assert(cols[eid[j]] == indices[j]);
    }
  std::puts("csr_roundtrip ok");
}

static void test_sample_validity() {
  // node v's neighbors are exactly {(j+1)*N + v mod N variations}: use a
  // deterministic graph, check every sample is a real neighbor, counts
  // == min(deg, k), and deg > k rows have no duplicate CSR slots (ids
  // distinct here because rows have distinct ids)
  const int64_t N = 40;
  std::vector<int64_t> indptr(N + 1, 0);
  std::vector<int32_t> indices;
  for (int64_t v = 0; v < N; ++v) {
    int64_t deg = v % 13;
    indptr[v + 1] = indptr[v] + deg;
    for (int64_t j = 0; j < deg; ++j)
      indices.push_back((int32_t)((v + j + 1) % N));
  }
  const int32_t k = 5;
  std::vector<int32_t> seeds(N);
  for (int64_t v = 0; v < N; ++v) seeds[v] = (int32_t)v;
  std::vector<int32_t> out(N * k), counts(N);
  sample_neighbors_cpu(indptr.data(), indices.data(), seeds.data(), N, k, 42,
                       out.data(), counts.data());
  for (int64_t v = 0; v < N; ++v) {
    int64_t deg = indptr[v + 1] - indptr[v];
    assert(counts[v] == (deg < k ? deg : k));
    std::set<int32_t> legal(indices.begin() + indptr[v],
                            indices.begin() + indptr[v + 1]);
    std::set<int32_t> seen;
    for (int32_t j = 0; j < k; ++j) {
      int32_t s = out[v * k + j];
      if (j < counts[v]) {
        assert(legal.count(s));
        assert(seen.insert(s).second);  // distinct
      } else {
        assert(s == -1);
      }
    }
  }
  // determinism under the same seed
  std::vector<int32_t> out2(N * k), counts2(N);
  sample_neighbors_cpu(indptr.data(), indices.data(), seeds.data(), N, k, 42,
                       out2.data(), counts2.data());
  assert(out == out2 && counts == counts2);
  std::puts("sample_validity ok");
}

static void test_reindex_order() {
  // seeds force distinct slots even when duplicated; neighbors map to the
  // first occurrence; -1 lanes stay -1
  std::vector<int32_t> seeds = {7, 7, 3};
  std::vector<int32_t> nbr = {7, 3, 9, -1, 7, 9};  // (3, 2)
  std::vector<int32_t> frontier(3 * 3), col(6);
  int64_t m = reindex_cpu(seeds.data(), 3, nbr.data(), 2, frontier.data(),
                          col.data());
  assert(m == 4);
  int32_t ef[] = {7, 7, 3, 9};
  int32_t ec[] = {0, 2, 3, -1, 0, 3};
  assert(std::memcmp(frontier.data(), ef, sizeof ef) == 0);
  assert(std::memcmp(col.data(), ec, sizeof ec) == 0);
  std::puts("reindex_order ok");
}

static void test_gather_rows() {
  const int64_t R = 20, F = 3;
  std::vector<float> table(R * F);
  for (int64_t i = 0; i < R * F; ++i) table[i] = (float)i;
  std::vector<int64_t> ids = {3, -1, 19, 0};
  std::vector<float> out(ids.size() * F);
  gather_rows_bytes((const uint8_t*)table.data(), R, F * sizeof(float),
                    ids.data(), (int64_t)ids.size(), (uint8_t*)out.data());
  for (size_t i = 0; i < ids.size(); ++i)
    for (int64_t f = 0; f < F; ++f)
      assert(out[i * F + f] ==
             (ids[i] < 0 ? 0.0f : table[ids[i] * F + f]));
  std::puts("gather_rows ok");
}

int main() {
  test_csr_roundtrip();
  test_sample_validity();
  test_reindex_order();
  test_gather_rows();
  std::puts("ALL C++ TESTS PASSED");
  return 0;
}
