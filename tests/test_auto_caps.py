"""Auto frontier-cap planning tests: tightening, equivalence with
worst-case caps, overflow-triggered regrow, monotone caps invariant."""

import numpy as np
import pytest

import jax.numpy as jnp

from quiver_tpu import CSRTopo, GraphSageSampler


@pytest.fixture(scope="module")
def topo():
    rng = np.random.default_rng(0)
    ei = rng.integers(0, 5000, size=(2, 30000)).astype(np.int64)
    return CSRTopo(edge_index=ei)


def _valid_edges(out):
    edges = set()
    for li, adj in enumerate(out.adjs):
        src, dst = np.asarray(adj.edge_index)
        for s, d in zip(src, dst):
            if s >= 0:
                edges.add((li, int(s), int(d)))
    return edges


def test_auto_tightens_after_first_call(topo):
    s = GraphSageSampler(topo, [5, 5], seed_capacity=64, frontier_caps="auto", seed=1)
    worst = s._worst_caps(64)
    assert s._frontier_caps is None
    out1 = s.sample(np.arange(64))
    assert s._frontier_caps is not None
    assert all(c <= w for c, w in zip(s._frontier_caps, worst))
    assert s._frontier_caps[-1] < worst[-1]  # genuinely tighter deep cap
    # caps are monotone non-decreasing (forced-lane requirement)
    assert list(s._frontier_caps) == sorted(s._frontier_caps)
    # second call runs under the tight plan with smaller output width
    out2 = s.sample(np.arange(64))
    assert out2.n_id.shape[0] == s._frontier_caps[-1] < out1.n_id.shape[0]
    assert int(out2.overflow) == 0


def test_auto_matches_worst_case_results(topo):
    """Same base seed => same per-call keys => identical valid samples,
    regardless of cap width."""
    a = GraphSageSampler(topo, [4, 3], seed_capacity=32, seed=9)
    b = GraphSageSampler(topo, [4, 3], seed_capacity=32, frontier_caps="auto", seed=9)
    seeds = np.random.default_rng(5).integers(0, topo.node_count, 32)
    for _ in range(3):  # incl. calls after b's plan tightened
        oa, ob = a.sample(seeds), b.sample(seeds)
        na, nb = int(oa.n_count), int(ob.n_count)
        assert na == nb
        np.testing.assert_array_equal(
            np.asarray(oa.n_id[:na]), np.asarray(ob.n_id[:nb])
        )
        assert _valid_edges(oa) == _valid_edges(ob)


def test_auto_regrows_on_overflow(topo):
    """Plan on a degenerate batch (all-duplicate seeds -> tiny frontier),
    then feed a diverse batch that must overflow and regrow."""
    s = GraphSageSampler(
        topo, [4, 3], seed_capacity=32, frontier_caps="auto", seed=2,
        auto_margin=1.0,
    )
    s.sample(np.full(32, 7))  # tiny observed frontier
    tiny = s._frontier_caps
    out = s.sample(np.random.default_rng(0).integers(0, topo.node_count, 32))
    assert s._frontier_caps != tiny  # regrew
    assert int(out.overflow) == 0  # resample under grown caps is exact
    # equivalence with a fixed-caps sampler at the same call count
    ref = GraphSageSampler(topo, [4, 3], seed_capacity=32, seed=2)
    ref.sample(np.full(32, 7))
    oref = ref.sample(np.random.default_rng(0).integers(0, topo.node_count, 32))
    n = int(oref.n_count)
    assert int(out.n_count) == n
    np.testing.assert_array_equal(np.asarray(out.n_id[:n]), np.asarray(oref.n_id[:n]))
    assert _valid_edges(out) == _valid_edges(oref)


def test_auto_margin_validation(topo):
    with pytest.raises(ValueError, match="auto_margin"):
        GraphSageSampler(topo, [3], frontier_caps="auto", auto_margin=0.5)


def test_edge_and_frontier_counts_reported(topo):
    s = GraphSageSampler(topo, [4, 3], seed_capacity=32, seed=0)
    out = s.sample(np.arange(32))
    assert len(out.edge_counts) == 2 and len(out.frontier_counts) == 2
    # deepest-first: edge_counts[i] == valid edges of adjs[i]
    for c, adj in zip(out.edge_counts, out.adjs):
        assert int(c) == int(jnp.sum(adj.edge_index[0] >= 0))
    # unclipped frontier count of the deepest layer == n_count when no overflow
    assert int(out.overflow) == 0
    assert int(out.frontier_counts[0]) == int(out.n_count)
