"""Serving fleet scale-out over the persisted AOT-executable cache
(ISSUE 17): program fingerprinting, the warm-replica zero-compile +
bitwise-parity contract, corrupt-entry fail-safe, SLO-class admission
control (bronze sheds before gold) with per-class counters in the obs
registry, least-depth fleet routing, and the refresh-after-commit cache
re-check (one replica pays the mutation epoch's compiles, the next
replica deserializes them)."""

import logging

import numpy as np
import pytest

from quiver_tpu import (
    DeltaBatch,
    InferenceServer,
    ServeQueueFull,
    ServingFleet,
    StreamingGraph,
    VersionMismatchError,
)
from quiver_tpu.obs.registry import (
    SERVE_AOT_LOADS,
    SERVE_CLASS_MISSES,
    SERVE_SHED,
)
from quiver_tpu.serving import DeadlineBatcher
from quiver_tpu.serving.aot import program_fingerprint
from test_serving import FakeClock, _graph, _stack


@pytest.fixture(scope="module")
def warm_stack(tmp_path_factory):
    """One shared graph/model stack + one disk AOT cache populated by a
    first replica (4 programs: sample+forward at buckets 1 and 2)."""
    cache_dir = str(tmp_path_factory.mktemp("aot") / "executables")
    topo = _graph(n=160, e=900, seed=2)
    _x, feat, sampler, model, params = _stack(
        topo, feature_dim=8, hidden=8, classes=3, sizes=(3, 2), seed=2)
    server = InferenceServer(sampler, model, params, feat, max_batch=2,
                             clock=FakeClock(), seed=7, aot_cache=cache_dir)
    first = server.warm_from_cache()

    def replica(**kw):
        kw.setdefault("max_batch", 2)
        kw.setdefault("clock", FakeClock())
        kw.setdefault("seed", 7)
        kw.setdefault("aot_cache", cache_dir)
        return InferenceServer(sampler, model, params, feat, **kw)

    return {"server": server, "first": first, "cache_dir": cache_dir,
            "replica": replica, "stack": (sampler, model, params, feat)}


# -- program fingerprint -----------------------------------------------------


def test_fingerprint_keying(warm_stack):
    """Same program -> same fingerprint; any keyed component moving
    (bucket, target, committed CSR version) -> a different one. The hash
    is over canonical JSON, so dict insertion order is irrelevant."""
    lad = warm_stack["server"]._ladder
    assert lad.fingerprint("sample", 2) == lad.fingerprint("sample", 2)
    assert lad.fingerprint("sample", 1) != lad.fingerprint("sample", 2)
    assert lad.fingerprint("forward", 2) != lad.fingerprint("sample", 2)
    comp = lad.fingerprint_components("sample", 2)
    bumped = dict(comp, csr_version=comp["csr_version"] + 1)
    assert program_fingerprint(bumped) != program_fingerprint(comp)
    shuffled = dict(reversed(list(comp.items())))
    assert program_fingerprint(shuffled) == program_fingerprint(comp)


# -- compile-free cold start -------------------------------------------------


def test_warm_replica_zero_compiles_bitwise(warm_stack):
    """The acceptance contract: a second replica warming from the cache
    performs ZERO compiles and answers every (node, seq) bitwise
    identically to the replica that compiled."""
    a = warm_stack["server"]
    assert warm_stack["first"]["compiled"] > 0  # cache-cold first replica
    b = warm_stack["replica"]()
    ws = b.warm_from_cache()
    assert ws == {"loaded": warm_stack["first"]["compiled"], "compiled": 0}
    assert b.recompiles == 0
    assert b.aot_loads == ws["loaded"]
    assert int(b.metrics.value(SERVE_AOT_LOADS)) == ws["loaded"]

    nodes = [3, 11, 19]  # batches of 2 + a forced tail of 1
    out_a = a.serve(nodes)
    out_b = b.serve(nodes)
    assert b.recompiles == 0  # steady state stays compile-free
    for ra, rb in zip(out_a, out_b):
        assert (ra.node, ra.seq) == (rb.node, rb.seq)
        np.testing.assert_array_equal(ra.result, rb.result)
        np.testing.assert_array_equal(rb.result, b.oracle(rb.node, rb.seq))


def test_corrupt_aot_entry_recovers(warm_stack, caplog):
    """A truncated cache entry degrades to compile-and-republish with a
    single WARNING (the election cache's tolerant loader); the republish
    heals the entry so the NEXT replica is compile-free again."""
    import pathlib

    cache_path = pathlib.Path(warm_stack["server"].aot_cache.path)
    entries = sorted(cache_path.glob("*.aotx"))
    assert len(entries) == warm_stack["first"]["compiled"]
    victim = entries[0]
    victim.write_bytes(victim.read_bytes()[:20])

    c = warm_stack["replica"]()
    with caplog.at_level(logging.WARNING, logger="quiver_tpu"):
        ws = c.warm_from_cache()
    assert ws == {"loaded": len(entries) - 1, "compiled": 1}
    assert c.recompiles == 1
    warns = [r for r in caplog.records if "unreadable" in r.getMessage()]
    assert len(warns) == 1, [r.getMessage() for r in caplog.records]

    # the fallback compile republished over the corrupt entry — the next
    # replica is compile-free again, and the atomic publish left no residue
    d = warm_stack["replica"]()
    assert d.warm_from_cache() == {"loaded": len(entries), "compiled": 0}
    residue = [p.name for p in cache_path.iterdir() if ".tmp." in p.name]
    assert not residue, residue


def test_batcher_priority_shedding():
    """Under a full queue bronze sheds before any gold request — newest
    bronze first (least sunk wait) — and only with nothing lower-class
    pending does admission raise; shed counts land per class."""
    clock = FakeClock()
    b = DeadlineBatcher(buckets=(1, 2), default_deadline_s=1.0,
                        max_queue=2, clock=clock,
                        class_deadlines={"bronze": 4.0})
    r0 = b.submit(0, priority="bronze")
    r1 = b.submit(1, priority="bronze")
    assert (r0.deadline_s, r1.deadline_s) == (4.0, 4.0)  # per-class default
    g2 = b.submit(2)  # gold; queue full -> newest bronze shed
    assert g2.deadline_s == 1.0
    assert r1.shed and r1.done and r1.result is None
    assert not r0.shed
    assert b.shed_by_class == {"gold": 0, "bronze": 1}
    b.submit(3)  # gold; sheds the remaining bronze
    assert r0.shed
    assert b.shed_by_class["bronze"] == 2
    with pytest.raises(ServeQueueFull):
        b.submit(4)  # all-gold queue: nothing below gold to shed
    assert b.shed_by_class["gold"] == 1
    with pytest.raises(ServeQueueFull):
        b.submit(5, priority="bronze")  # bronze never evicts gold
    assert b.shed_by_class["bronze"] == 3
    reqs, bucket = b.pop(force=True)
    assert bucket == 2 and [r.node for r in reqs] == [2, 3]

    # mixed-class pop packs gold first (FIFO within a class)
    b2 = DeadlineBatcher(buckets=(1, 2), max_queue=4, clock=clock)
    b2.submit(10, priority="bronze")
    b2.submit(11, priority="gold")
    reqs, bucket = b2.pop(force=True)
    assert bucket == 2 and [r.node for r in reqs] == [11, 10]

    with pytest.raises(ValueError, match="priority"):
        b2.submit(12, priority="silver")
    with pytest.raises(ValueError, match="class_deadlines"):
        DeadlineBatcher(class_deadlines={"silver": 1.0})


def test_server_shed_and_class_miss_metrics(warm_stack):
    """Shed and deadline-miss counts are attributed per class on the
    server's obs registry (vectors in PRIORITIES order: gold, bronze)."""
    clock = FakeClock()
    e = warm_stack["replica"](clock=clock, max_queue=2,
                              class_deadlines={"gold": 1.0, "bronze": 0.5})
    assert e.warm_from_cache()["compiled"] == 0
    e.submit(1, priority="bronze")
    e.submit(2, priority="bronze")
    e.submit(3, priority="gold")  # sheds bronze node 2
    np.testing.assert_array_equal(
        np.asarray(e.metrics.value(SERVE_SHED)), [0, 1])
    clock.advance(5.0)  # both survivors blow their class deadline
    out = e.pump(force=True)
    assert sorted(r.node for r in out) == [1, 3]
    np.testing.assert_array_equal(
        np.asarray(e.metrics.value(SERVE_CLASS_MISSES)), [1, 1])
    st = e.stats()
    assert st["shed"] == {"gold": 0, "bronze": 1}
    assert st["class_deadline_misses"] == {"gold": 1, "bronze": 1}
    assert st["deadline_misses"] == 2


# -- fleet -------------------------------------------------------------------


def test_fleet_two_replicas_share_cache(warm_stack):
    """A 2-replica fleet over the populated cache joins compile-free,
    routes by least queue depth, and every response matches the shared
    deterministic oracle bitwise."""
    sampler, model, params, feat = warm_stack["stack"]
    fleet = ServingFleet(sampler, model, params, feat, replicas=2,
                         aot_cache=warm_stack["cache_dir"], seed=7,
                         max_batch=2, clock=FakeClock())
    assert [c["compiled"] for c in fleet.cold_starts] == [0, 0]
    assert fleet.recompiles == 0
    assert len(fleet.aot_cache) == warm_stack["first"]["compiled"]
    out = fleet.serve(range(6))
    assert all(r.done and not r.shed for r in out)
    for r in out:
        np.testing.assert_array_equal(r.result, fleet.oracle(r.node, r.seq))
    st = fleet.stats()
    assert st["requests"] == 6 and st["recompiles"] == 0
    assert st["replicas"] == 2


def test_refresh_after_commit_rechecks_cache(tmp_path):
    """A streaming commit invalidates every fingerprint (csr_version is
    keyed); the FIRST replica to refresh pays the epoch's compiles and
    publishes — the second replica's refresh deserializes them, staying
    at zero lifetime compiles with bitwise parity."""
    topo = _graph(n=60, e=400, seed=4)
    _x, feat, sampler, model, params = _stack(
        topo, feature_dim=6, hidden=8, classes=3, sizes=(3, 2), seed=4)
    cd = str(tmp_path / "aot")
    f = InferenceServer(sampler, model, params, feat, max_batch=1,
                        clock=FakeClock(), seed=5, aot_cache=cd)
    first = f.warm_from_cache()
    assert first["compiled"] > 0
    g = InferenceServer(sampler, model, params, feat, max_batch=1,
                        clock=FakeClock(), seed=5, aot_cache=cd)
    assert g.warm_from_cache() == {"loaded": first["compiled"],
                                   "compiled": 0}

    sg = StreamingGraph(topo)
    src = np.repeat(np.arange(topo.node_count), topo.degree)
    dst = np.asarray(topo.indices)[: src.size]
    live = set((src * topo.node_count + dst).tolist())
    k = next(k for k in range(topo.node_count ** 2) if k not in live)
    assert sg.ingest(DeltaBatch(edge_inserts=np.array(
        [[k // topo.node_count], [k % topo.node_count]])))
    sg.commit()

    with pytest.raises(VersionMismatchError):
        g.pump(force=True)
    f.refresh()  # pays the epoch's compiles, publishes the new programs
    assert f.recompiles == 2 * first["compiled"]
    loads_before = g.aot_loads
    g.refresh()  # re-checks the cache: hands over f's programs
    assert g.recompiles == 0
    assert g.aot_loads == loads_before + first["compiled"]
    rf = f.serve([7])[0]
    rg = g.serve([7])[0]
    assert (rf.node, rf.seq) == (rg.node, rg.seq)
    np.testing.assert_array_equal(rf.result, rg.result)
    np.testing.assert_array_equal(rg.result, g.oracle(rg.node, rg.seq))
