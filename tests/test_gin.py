"""GIN model family: dense-oracle exactness, training, layer-wise inference.

GIN uses raw SUM aggregation with a (1+eps) self term — no degree
normalization — so the dense oracle is ``MLP((1+eps)·x + A·x)``. Exactness
oracles seed EVERY node with full fanout so block sums equal global sums.
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax

from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.models import GIN, gin_layerwise_inference
from quiver_tpu.parallel.train import init_model, make_train_step
from quiver_tpu.utils.graphgen import generate_pareto_graph


def _graph(n, seed):
    ei = generate_pareto_graph(n, 4.0, seed=seed)
    return np.concatenate([ei, ei[::-1]], axis=1)


def _adj(topo, n):
    A = np.zeros((n, n))
    indptr, indices = np.asarray(topo.indptr), np.asarray(topo.indices)
    for i in range(n):
        for j in indices[indptr[i]:indptr[i + 1]]:
            A[i, j] += 1.0  # row i sums its CSR neighbors
    return A


def _dense_gin_layer(A, x, conv_params, eps=0.0):
    z = (1.0 + eps) * x + A @ x
    h = z @ np.asarray(conv_params["lin1"]["kernel"]) + np.asarray(
        conv_params["lin1"]["bias"])
    h = np.maximum(h, 0.0)
    return h @ np.asarray(conv_params["lin2"]["kernel"]) + np.asarray(
        conv_params["lin2"]["bias"])


def test_gin_conv_matches_dense_full_graph():
    n = 60
    topo = CSRTopo(edge_index=_graph(n, 0))
    x_all = np.random.default_rng(1).normal(size=(n, 7)).astype(np.float32)
    model = GIN(hidden=5, num_classes=4, num_layers=1, dropout=0.0)

    sampler = GraphSageSampler(topo, [-1], seed=0)
    out = sampler.sample(np.arange(n))
    assert int(out.overflow) == 0
    n_id = np.asarray(out.n_id)
    assert np.array_equal(n_id[:n], np.arange(n))  # identity frontier
    x = jnp.asarray(np.where((n_id >= 0)[:, None],
                             x_all[np.maximum(n_id, 0)], 0))
    params = init_model(model, jax.random.PRNGKey(2), x, out.adjs)
    got = np.asarray(
        model.apply({"params": params}, x, out.adjs, train=False)
    )[:n]

    dense = _dense_gin_layer(_adj(topo, n), x_all, params["conv0"])
    want = np.asarray(jax.nn.log_softmax(jnp.asarray(dense), axis=-1))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_gin_training_learns():
    rng = np.random.default_rng(0)
    n, classes = 300, 4
    labels = rng.integers(0, classes, n)
    feat = np.eye(classes, dtype=np.float32)[labels] * 2.0
    feat += rng.normal(scale=0.6, size=(n, classes)).astype(np.float32)
    rows, cols = [], []
    for c in range(classes):
        members = np.where(labels == c)[0]
        rows.extend(rng.choice(members, 5 * len(members)))
        cols.extend(rng.choice(members, 5 * len(members)))
    ei = np.stack([np.asarray(rows), np.asarray(cols)])
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count

    sampler = GraphSageSampler(topo, [5, 5], seed=1)
    model = GIN(hidden=32, num_classes=classes, num_layers=2)
    out = sampler.sample(rng.integers(0, n, 64))
    x = jnp.asarray(np.where(
        (np.asarray(out.n_id) >= 0)[:, None],
        feat[np.maximum(np.asarray(out.n_id), 0)], 0))
    params = init_model(model, jax.random.PRNGKey(0), x, out.adjs)
    tx = optax.adam(5e-3)
    opt_state = tx.init(params)
    step = jax.jit(make_train_step(model, tx))
    losses = []
    for i in range(30):
        seeds = rng.integers(0, n, 64)
        out = sampler.sample(seeds)
        n_id = np.asarray(out.n_id)
        x = jnp.asarray(np.where((n_id >= 0)[:, None],
                                 feat[np.maximum(n_id, 0)], 0))
        cap = out.adjs[-1].size[1]
        lab = np.full(cap, -1, np.int32)
        lab[:64] = labels[seeds]
        mask = np.zeros(cap, bool)
        mask[:64] = True
        params, opt_state, loss = step(
            params, opt_state, x, out.adjs, jnp.asarray(lab),
            jnp.asarray(mask), jax.random.PRNGKey(i)
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.6, losses


def test_gin_layerwise_matches_sampled_full_cover():
    """Two-layer oracle: all nodes seeded, full fanout — the sampled
    model's predictions must equal the whole-graph layer-wise pass (block
    sums == global sums in this regime)."""
    n = 80
    topo = CSRTopo(edge_index=_graph(n, 3))
    x_all = np.random.default_rng(4).normal(size=(n, 6)).astype(np.float32)
    model = GIN(hidden=10, num_classes=3, num_layers=2, dropout=0.0)

    sampler = GraphSageSampler(topo, [-1, -1], seed=0)
    out = sampler.sample(np.arange(n))
    assert int(out.overflow) == 0
    n_id = np.asarray(out.n_id)
    x = jnp.asarray(np.where((n_id >= 0)[:, None],
                             x_all[np.maximum(n_id, 0)], 0))
    params = init_model(model, jax.random.PRNGKey(5), x, out.adjs)
    sampled = np.asarray(
        model.apply({"params": params}, x, out.adjs, train=False)
    )[:n]

    full = np.asarray(
        gin_layerwise_inference(model, params, topo, x_all, chunk=97)
    )
    np.testing.assert_allclose(sampled, full, rtol=1e-4, atol=1e-5)


def test_gin_train_eps_learnable():
    """train_eps=True registers a scalar eps that the layer-wise pass
    honors; dense oracle with the learned eps value must match."""
    n = 40
    topo = CSRTopo(edge_index=_graph(n, 7))
    x_all = np.random.default_rng(8).normal(size=(n, 5)).astype(np.float32)
    model = GIN(hidden=6, num_classes=3, num_layers=1, dropout=0.0,
                train_eps=True)

    sampler = GraphSageSampler(topo, [-1], seed=0)
    out = sampler.sample(np.arange(n))
    n_id = np.asarray(out.n_id)
    x = jnp.asarray(np.where((n_id >= 0)[:, None],
                             x_all[np.maximum(n_id, 0)], 0))
    params = init_model(model, jax.random.PRNGKey(9), x, out.adjs)
    assert "eps" in params["conv0"]
    # give eps a non-trivial value and check both paths track it
    params = jax.tree_util.tree_map(lambda v: v, params)
    params["conv0"]["eps"] = jnp.asarray(0.37, jnp.float32)
    sampled = np.asarray(
        model.apply({"params": params}, x, out.adjs, train=False))[:n]
    full = np.asarray(
        gin_layerwise_inference(model, params, topo, x_all, chunk=53))
    np.testing.assert_allclose(sampled, full, rtol=1e-4, atol=1e-5)

    dense = _dense_gin_layer(_adj(topo, n), x_all, params["conv0"], eps=0.37)
    want = np.asarray(jax.nn.log_softmax(jnp.asarray(dense), axis=-1))
    np.testing.assert_allclose(sampled, want, rtol=1e-4, atol=1e-5)
