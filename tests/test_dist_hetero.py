"""Heterogeneous distributed sampling differentials (quiver-hetero-dist).

Parity bar: ``DistHeteroSampler`` — per-relation CSR slices partitioned
across the mesh's feature axis, ONE shared BucketRoute plan per (hop,
destination type) — must be BIT-IDENTICAL per worker block to the
replicated ``HeteroGraphSampler`` with key ``fold_in(key, worker)``, at
every mesh width, uniform and weighted, with and without forced bucket
overflow (fallback-served lanes included). Routed overflow surfaces per
(hop, edge type) through ``last_sample_overflow_by_rel``. End-to-end, an
R-GCN trained off the dist sampler's per-worker blocks must reproduce
the replicated loss trajectory bit-for-bit (slow lane).
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from quiver_tpu import (
    DistHeteroSampler,
    HeteroCSRTopo,
    HeteroFeature,
    HeteroGraphSampler,
)
from quiver_tpu.models.rgcn import RGCN
from quiver_tpu.parallel.mesh import make_mesh


def _toy_schema(seed=0, n_paper=120, n_author=60, n_inst=20):
    rng = np.random.default_rng(seed)
    cites = np.stack([
        rng.integers(0, n_paper, 400), rng.integers(0, n_paper, 400)
    ])
    writes = np.stack([
        rng.integers(0, n_author, 300), rng.integers(0, n_paper, 300)
    ])
    affil = np.stack([
        rng.integers(0, n_inst, 100), rng.integers(0, n_author, 100)
    ])
    num_nodes = {"paper": n_paper, "author": n_author, "inst": n_inst}
    edges = {
        ("paper", "cites", "paper"): cites,
        ("author", "writes", "paper"): writes,
        ("inst", "employs", "author"): affil,
    }
    return HeteroCSRTopo(num_nodes, edges), edges, num_nodes


def _weighted_topo(seed=0):
    topo, _, num_nodes = _toy_schema(seed=seed)
    rng = np.random.default_rng(1)
    for et in topo.relations:
        topo.set_edge_weight(et, rng.random(topo.relations[et].edge_count)
                             + 0.1)
    return topo, num_nodes


def _assert_hetero_parity(F, weighted, alpha, seeds=None, seed=0,
                          sizes=(3, 2)):
    """Every worker's dist HeteroSampleOutput equals the replicated
    oracle's on that worker's seed block with key fold_in(key, worker):
    per-type n_id and every relation's edge_index, bitwise."""
    if weighted:
        topo, _ = _weighted_topo(seed=seed)
    else:
        topo, _, _ = _toy_schema(seed=seed)
    if seeds is None:
        seeds = np.arange(48)
    mesh = make_mesh(n_devices=F, data=1, feature=F)
    dist = DistHeteroSampler(topo, list(sizes), input_type="paper",
                             mesh=mesh, routed_alpha=alpha,
                             weighted=weighted, seed=0)
    base_key = jax.random.PRNGKey(7)
    per = dist.sample_per_worker(seeds, key=base_key)
    cap = per[0].batch_size

    oracle = HeteroGraphSampler(topo, list(sizes), input_type="paper",
                                seed_capacity=cap, weighted=weighted,
                                seed=0)
    run = oracle._compiled(cap)
    for w, blk in enumerate(np.array_split(seeds, F)):
        padded = np.full(cap, -1, np.int32)
        padded[: len(blk)] = blk
        frontier, _, layers, _, _ = run(
            oracle.dev_topos, jnp.asarray(padded), jnp.int32(len(blk)),
            jax.random.fold_in(base_key, w),
        )
        d = per[w]
        assert set(frontier) == set(d.n_id)
        for t in frontier:
            assert np.array_equal(
                np.asarray(frontier[t]), np.asarray(d.n_id[t])
            ), f"n_id[{t}] diverged on worker {w}/{F}"
        assert len(layers) == len(d.adjs)
        for li, (la, lb) in enumerate(zip(layers, d.adjs)):
            assert set(la.adjs) == set(lb.adjs)
            for et in la.adjs:
                assert np.array_equal(
                    np.asarray(la.adjs[et].edge_index),
                    np.asarray(lb.adjs[et].edge_index),
                ), f"edge_index diverged: worker {w} layer {li} {et}"
                assert la.adjs[et].size == lb.adjs[et].size
    return dist


# -- bit-parity differentials (fast lane: F=2) ------------------------------


def test_dist_hetero_parity_uniform():
    dist = _assert_hetero_parity(2, weighted=False, alpha=2.0)
    # per-(hop, edge type) telemetry: one slot per active relation per hop
    ov = dist.last_sample_overflow_by_rel
    assert ov is not None and set(ov) == set(dist.overflow_slots)
    assert all(li in (0, 1) for li, _ in ov) and all(v >= 0
                                                     for v in ov.values())


def test_dist_hetero_parity_weighted():
    dist = _assert_hetero_parity(2, weighted=True, alpha=2.0)
    assert dist.last_sample_overflow_by_rel is not None


# -- forced overflow + width sweep (slow lane) ------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("F", [1, 4, 8])
@pytest.mark.parametrize("weighted", [False, True])
def test_dist_hetero_parity_widths(F, weighted):
    _assert_hetero_parity(F, weighted=weighted, alpha=2.0)


@pytest.mark.slow
@pytest.mark.parametrize("weighted", [False, True])
def test_dist_hetero_forced_overflow_exact(weighted):
    """Tiny routing budget: buckets overflow, the psum fallback serves the
    overflowed lanes, results stay bit-identical, and the per-(hop, edge
    type) counts surface."""
    dist = _assert_hetero_parity(4, weighted=weighted, alpha=0.25)
    ov = dist.last_sample_overflow_by_rel
    assert sum(ov.values()) > 0, ov


@pytest.mark.slow
def test_dist_hetero_uncapped_alpha_none():
    _assert_hetero_parity(2, weighted=True, alpha=None)


# -- constructor guards -----------------------------------------------------


def test_dist_hetero_constructor_guards():
    topo, _, _ = _toy_schema()
    mesh = make_mesh(n_devices=2, data=1, feature=2)
    with pytest.raises(ValueError, match="requires mesh="):
        DistHeteroSampler(topo, [3], input_type="paper")
    with pytest.raises(ValueError, match="with_eid over a sharded"):
        DistHeteroSampler(topo, [3], input_type="paper", mesh=mesh,
                          with_eid=True)
    with pytest.raises(ValueError, match="HBM"):
        DistHeteroSampler(topo, [3], input_type="paper", mesh=mesh,
                          mode="HOST")
    with pytest.raises(ValueError, match="routed_alpha"):
        DistHeteroSampler(topo, [3], input_type="paper", mesh=mesh,
                          routed_alpha=0.0)
    # weighted needs the relations to actually carry weights
    with pytest.raises(ValueError, match="weight"):
        DistHeteroSampler(topo, [3], input_type="paper", mesh=mesh,
                          weighted=True)


# -- end-to-end R-GCN parity (slow lane) ------------------------------------


@pytest.mark.slow
def test_dist_hetero_rgcn_loss_parity():
    """R-GCN trained off the dist sampler's per-worker blocks (grads
    averaged across workers) reproduces the replicated trajectory
    BIT-FOR-BIT — and still converges."""
    topo, _, num_nodes = _toy_schema(seed=5)
    F = 2
    mesh = make_mesh(n_devices=F, data=1, feature=F)
    cap = 16  # per-worker block == capacity: no padded label lanes
    dist = DistHeteroSampler(topo, [4, 3], input_type="paper", mesh=mesh,
                             seed_capacity=cap, seed=2)
    rep = HeteroGraphSampler(topo, [4, 3], input_type="paper",
                             seed_capacity=cap, seed=2)
    rng = np.random.default_rng(0)
    feats = {
        t: rng.normal(size=(n, 16)).astype(np.float32)
        for t, n in num_nodes.items()
    }
    feature = HeteroFeature.from_cpu_tensors(feats, device_cache_size="64M")
    labels_all = rng.integers(0, 4, num_nodes["paper"]).astype(np.int32)
    model = RGCN(hidden=32, num_classes=4, target_type="paper",
                 num_layers=2)
    tx = optax.adam(5e-3)

    @jax.jit
    def grad_step(params, x_dict, layers, labels, rng_key):
        def loss_fn(p):
            logp = model.apply({"params": p}, x_dict, layers, train=True,
                               rngs={"dropout": rng_key})
            ll = jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
            return -ll.mean()

        return jax.value_and_grad(loss_fn)(params)

    def train(sample_fn, steps=20):
        out0 = sample_fn(np.arange(F * cap), jax.random.PRNGKey(0))[0]
        params = model.init(
            {"params": jax.random.PRNGKey(0)}, feature[out0.n_id], out0.adjs
        )["params"]
        opt_state = tx.init(params)
        losses = []
        for i in range(steps):
            seeds = np.random.default_rng(i).integers(
                0, num_nodes["paper"], F * cap
            )
            outs = sample_fn(seeds, jax.random.PRNGKey(i))
            grads_acc, loss_acc = None, 0.0
            for o, blk in zip(outs, np.array_split(seeds, F)):
                loss, grads = grad_step(
                    params, feature[o.n_id], o.adjs,
                    jnp.asarray(labels_all[blk]), jax.random.PRNGKey(i),
                )
                loss_acc += float(loss)
                grads_acc = grads if grads_acc is None else jax.tree.map(
                    jnp.add, grads_acc, grads
                )
            grads_acc = jax.tree.map(lambda g: g / F, grads_acc)
            updates, opt_state = tx.update(grads_acc, opt_state, params)
            params = optax.apply_updates(params, updates)
            losses.append(loss_acc / F)
        return losses

    def dist_sample(seeds, key):
        return dist.sample_per_worker(seeds, key=key)

    run = rep._compiled(cap)

    class _Out:
        def __init__(self, n_id, adjs):
            self.n_id, self.adjs = n_id, adjs

    def rep_sample(seeds, key):
        outs = []
        for w, blk in enumerate(np.array_split(seeds, F)):
            padded = np.full(cap, -1, np.int32)
            padded[: len(blk)] = blk
            frontier, _, layers, _, _ = run(
                rep.dev_topos, jnp.asarray(padded), jnp.int32(len(blk)),
                jax.random.fold_in(key, w),
            )
            outs.append(_Out(frontier, layers))
        return outs

    dist_losses = train(dist_sample)
    rep_losses = train(rep_sample)
    assert dist_losses == rep_losses, (dist_losses, rep_losses)
    assert dist_losses[-1] < dist_losses[0], dist_losses
