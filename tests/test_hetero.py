"""Heterogeneous topology, sampler, and R-GCN tests (BASELINE config 5:
hetero R-GCN — the reference has no hetero support; this is capability
the TPU framework adds on top of parity).
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from quiver_tpu import HeteroCSRTopo, HeteroFeature, HeteroGraphSampler
from quiver_tpu.models.rgcn import RGCN


def _toy_schema(seed=0, n_paper=120, n_author=60, n_inst=20):
    """paper<-cites-paper, paper<-writes-author... stored incoming.

    Edge convention: (src, rel, dst) with edge_index=[src_ids, dst_ids];
    sampling from dst draws src messages.
    """
    rng = np.random.default_rng(seed)
    cites = np.stack([
        rng.integers(0, n_paper, 400), rng.integers(0, n_paper, 400)
    ])
    writes = np.stack([
        rng.integers(0, n_author, 300), rng.integers(0, n_paper, 300)
    ])
    affil = np.stack([
        rng.integers(0, n_inst, 100), rng.integers(0, n_author, 100)
    ])
    num_nodes = {"paper": n_paper, "author": n_author, "inst": n_inst}
    edges = {
        ("paper", "cites", "paper"): cites,
        ("author", "writes", "paper"): writes,
        ("inst", "employs", "author"): affil,
    }
    return HeteroCSRTopo(num_nodes, edges), edges, num_nodes


def test_topo_construction_and_validation():
    topo, edges, num_nodes = _toy_schema()
    assert set(topo.node_types) == {"paper", "author", "inst"}
    assert len(topo.edge_types) == 3
    rel = topo.relations[("author", "writes", "paper")]
    assert rel.node_count == num_nodes["paper"]  # rows = dst
    assert rel.src_node_count == num_nodes["author"]
    assert rel.edge_count == 300
    # incoming CSR: row d holds all authors a with (a -> d) in writes
    src, dst = edges[("author", "writes", "paper")]
    for d in range(0, 120, 17):
        expect = sorted(src[dst == d])
        got = sorted(rel.indices[rel.indptr[d]:rel.indptr[d + 1]])
        assert got == expect


def test_topo_rejects_bad_ids():
    with pytest.raises(ValueError, match="src node"):
        HeteroCSRTopo(
            {"a": 5, "b": 5},
            {("a", "r", "b"): np.array([[7], [0]])},
        )
    with pytest.raises(ValueError, match="dst id"):
        HeteroCSRTopo(
            {"a": 5, "b": 5},
            {("a", "r", "b"): np.array([[0], [9]])},
        )
    with pytest.raises(ValueError, match="unknown node type"):
        HeteroCSRTopo({"a": 5}, {("a", "r", "zzz"): np.zeros((2, 0))})


def test_hetero_sampler_contract():
    topo, edges, _ = _toy_schema()
    sampler = HeteroGraphSampler(topo, [3, 2], input_type="paper", seed=0)
    seeds = np.arange(32)
    out = sampler.sample(seeds)

    # seeds-first contract on the input type
    assert np.asarray(out.n_id["paper"])[:32].tolist() == seeds.tolist()
    assert out.batch_size == 32
    assert int(out.overflow) == 0
    # two hops -> two layers, deepest first
    assert len(out.adjs) == 2
    # hop 1 (deepest in list position 0) has all three relations active
    # (paper and author both have frontiers after hop 1)
    assert len(out.adjs[0].adjs) == 3
    # hop 0 (position 1): only relations into 'paper' are active
    assert set(out.adjs[1].adjs) == {
        ("paper", "cites", "paper"), ("author", "writes", "paper")
    }


@pytest.mark.slow  # 15s hetero 3-way dedup differential
def test_hetero_dedup_alternatives_match_sort():
    """dedup='map' and dedup='scan' must reproduce dedup='sort' exactly
    across every node type's frontier and every relation's edge_index
    (same seed path)."""
    topo, edges, _ = _toy_schema(seed=9)
    seeds = np.arange(24)
    outs = {}
    for dedup in ("sort", "map", "scan"):
        s = HeteroGraphSampler(topo, [3, 2], input_type="paper", seed=4,
                               dedup=dedup)
        outs[dedup] = s.sample(seeds)
    a = outs["sort"]
    for other in ("map", "scan"):
        b = outs[other]
        assert set(a.n_id) == set(b.n_id)
        for t in a.n_id:
            assert np.array_equal(
                np.asarray(a.n_id[t]), np.asarray(b.n_id[t])
            ), (other, t)
        for la, lb in zip(a.adjs, b.adjs):
            assert set(la.adjs) == set(lb.adjs)
            for et in la.adjs:
                assert np.array_equal(
                    np.asarray(la.adjs[et].edge_index),
                    np.asarray(lb.adjs[et].edge_index),
                ), (other, et)


def test_hetero_sampled_edges_are_real():
    topo, edges, _ = _toy_schema(seed=3)
    sampler = HeteroGraphSampler(topo, [4, 3], input_type="paper", seed=1)
    out = sampler.sample(np.arange(24))

    adj_sets = {
        et: {(int(s), int(d)) for s, d in zip(*edges[et])} for et in edges
    }
    # walk layers from seeds outward: position 1 is hop 0 (targets = seeds
    # frontier), position 0 is hop 1
    checked = 0
    for layer in reversed(out.adjs):
        for et, adj in layer.adjs.items():
            s_t, _, d_t = et
            src, dst = np.asarray(adj.edge_index)
            # n_id holds the DEEPEST frontier; for intermediate hops the
            # forced-first property means target ids are a prefix of it
            for sl, dl in zip(src, dst):
                if sl < 0:
                    continue
                u = int(np.asarray(out.n_id[s_t])[sl])
                v = int(np.asarray(out.n_id[d_t])[dl])
                assert (u, v) in adj_sets[et], f"{et}: ({u},{v}) not an edge"
                checked += 1
    assert checked > 50


def test_fanout_dict_disables_relation():
    topo, _, _ = _toy_schema()
    sampler = HeteroGraphSampler(
        topo,
        [{("paper", "cites", "paper"): 3}],
        input_type="paper",
    )
    out = sampler.sample(np.arange(16))
    assert set(out.adjs[0].adjs) == {("paper", "cites", "paper")}
    assert "author" not in out.n_id


def test_rgcn_trains():
    topo, edges, num_nodes = _toy_schema(seed=5)
    sampler = HeteroGraphSampler(topo, [4, 3], input_type="paper",
                                 seed_capacity=32, seed=2)
    rng = np.random.default_rng(0)
    feats = {
        t: rng.normal(size=(n, 16)).astype(np.float32)
        for t, n in num_nodes.items()
    }
    feature = HeteroFeature.from_cpu_tensors(feats, device_cache_size="64M")
    labels_all = rng.integers(0, 4, num_nodes["paper"]).astype(np.int32)

    model = RGCN(hidden=32, num_classes=4, target_type="paper", num_layers=2)
    out = sampler.sample(np.arange(32))
    x_dict = feature[out.n_id]
    params = model.init({"params": jax.random.PRNGKey(0)}, x_dict, out.adjs)[
        "params"
    ]
    tx = optax.adam(5e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, x_dict, layers, labels, rng):
        def loss_fn(p):
            logp = model.apply({"params": p}, x_dict, layers, train=True,
                               rngs={"dropout": rng})
            ll = jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
            return -ll.mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for i in range(30):
        seeds = np.random.default_rng(i).integers(0, num_nodes["paper"], 32)
        out = sampler.sample(seeds)
        x_dict = feature[out.n_id]
        y = jnp.asarray(labels_all[seeds])
        params, opt_state, loss = step(
            params, opt_state, x_dict, out.adjs, y, jax.random.PRNGKey(i)
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, f"no convergence: {losses[:3]} -> {losses[-3:]}"


def test_full_fanout_minus_one():
    topo, edges, _ = _toy_schema()
    sampler = HeteroGraphSampler(topo, [-1], input_type="paper",
                                 seed_capacity=16)
    out = sampler.sample(np.arange(8))
    # -1 = full neighborhood: every incoming edge of every seed appears
    adj = out.adjs[0].adjs[("author", "writes", "paper")]
    src_ids, dst_ids = edges[("author", "writes", "paper")]
    n_edges_expected = sum(int((dst_ids == s).sum()) for s in range(8))
    src = np.asarray(adj.edge_index[0])
    dst = np.asarray(adj.edge_index[1])
    got = int(((src >= 0) & (dst < 8) & (dst >= 0)).sum())
    assert got == n_edges_expected


def test_duplicate_seeds_keep_capacity():
    # more (duplicate) seeds than the input type has nodes: the frontier
    # must still hold every forced seed lane
    topo, _, _ = _toy_schema(n_paper=10, n_author=8, n_inst=4)
    sampler = HeteroGraphSampler(topo, [2], input_type="paper",
                                 seed_capacity=64)
    seeds = np.zeros(50, dtype=np.int64)  # 50 copies of node 0
    out = sampler.sample(seeds)
    nid = np.asarray(out.n_id["paper"])
    assert nid.shape[0] >= 50
    assert (nid[:50] == 0).all()
    assert int(out.overflow) == 0


def test_bad_fanout_rejected():
    topo, _, _ = _toy_schema()
    with pytest.raises(ValueError, match="fanout"):
        HeteroGraphSampler(topo, [-3], input_type="paper")


def test_rgcn_mixed_feature_dims_with_bases():
    topo, _, num_nodes = _toy_schema()
    sampler = HeteroGraphSampler(topo, [3, 2], input_type="paper",
                                 seed_capacity=16)
    rng = np.random.default_rng(2)
    dims = {"paper": 24, "author": 8, "inst": 4}
    feats = {
        t: rng.normal(size=(n, dims[t])).astype(np.float32)
        for t, n in num_nodes.items()
    }
    feature = HeteroFeature.from_cpu_tensors(feats, device_cache_size="64M")
    model = RGCN(hidden=16, num_classes=3, target_type="paper",
                 num_layers=2, num_bases=2)
    out = sampler.sample(np.arange(16))
    x_dict = feature[out.n_id]
    params = model.init({"params": jax.random.PRNGKey(0)}, x_dict, out.adjs)[
        "params"
    ]
    logp = model.apply({"params": params}, x_dict, out.adjs)
    assert np.isfinite(np.asarray(logp)[:16]).all()


def test_rgcn_basis_decomposition():
    topo, _, num_nodes = _toy_schema()
    sampler = HeteroGraphSampler(topo, [3, 2], input_type="paper",
                                 seed_capacity=16)
    rng = np.random.default_rng(1)
    feats = {
        t: rng.normal(size=(n, 8)).astype(np.float32)
        for t, n in num_nodes.items()
    }
    feature = HeteroFeature.from_cpu_tensors(feats, device_cache_size="64M")
    model = RGCN(hidden=16, num_classes=3, target_type="paper",
                 num_layers=2, num_bases=2)
    out = sampler.sample(np.arange(16))
    x_dict = feature[out.n_id]
    params = model.init({"params": jax.random.PRNGKey(0)}, x_dict, out.adjs)[
        "params"
    ]
    logp = model.apply({"params": params}, x_dict, out.adjs)
    assert logp.shape[-1] == 3
    assert np.isfinite(np.asarray(logp)[:16]).all()
    # basis params exist, per-relation dense kernels don't
    flat = jax.tree_util.tree_leaves_with_path(params)
    names = ["/".join(str(k) for k in path) for path, _ in flat]
    assert any("bases" in n for n in names)
    assert not any("rel_" in n and "kernel" in n for n in names)


def _powerlaw_schema(seed=0, n_paper=3000, n_author=1200):
    """Power-law hetero graph: worst-case caps overshoot badly here."""
    from quiver_tpu.utils.graphgen import generate_pareto_graph

    rng = np.random.default_rng(seed)
    cites = generate_pareto_graph(n_paper, 8.0, seed=seed)
    m = 4 * n_paper
    writes = np.stack([
        rng.integers(0, n_author, m), rng.integers(0, n_paper, m)
    ])
    return HeteroCSRTopo(
        {"paper": n_paper, "author": n_author},
        {
            ("paper", "cites", "paper"): cites,
            ("author", "writes", "paper"): writes,
        },
    )


@pytest.mark.slow  # 19s auto-caps sweep; overflow guards stay fast
def test_hetero_auto_caps_right_size(  ):
    """VERDICT r1 item 7: auto caps within 1.5x of observed uniques on a
    power-law hetero graph, no overflow, and strictly tighter than the
    worst-case plan."""
    topo = _powerlaw_schema()
    batch = 128
    auto = HeteroGraphSampler(
        topo, [10, 5], input_type="paper", seed_capacity=batch,
        frontier_caps="auto", seed=7,
    )
    worst = HeteroGraphSampler(
        topo, [10, 5], input_type="paper", seed_capacity=batch, seed=7,
    )
    seeds = np.random.default_rng(1).integers(0, 3000, batch)
    auto.sample(seeds)  # first call plans from worst case, then tightens
    out = auto.sample(seeds)
    out_w = worst.sample(seeds)
    assert int(out.overflow) == 0

    # per-layer, per-type: planned cap <= 1.5x observed uniques (+ padding
    # slack for tiny frontiers) and <= the worst-case cap
    for layer_i, (layer, layer_w) in enumerate(zip(out.adjs, out_w.adjs)):
        obs = {t: int(v) for t, v in out.frontier_counts[::-1][layer_i].items()}
        for t, cap in layer.src_caps.items():
            w_cap = layer_w.src_caps[t]
            assert cap <= w_cap
            if t in obs and obs[t] >= 512:  # rounding slack irrelevant
                assert cap <= 1.5 * obs[t] + 128, (
                    f"layer {layer_i} type {t}: cap {cap} vs observed {obs[t]}"
                )
    # the deepest frontier must be meaningfully tighter than worst case
    deep_auto = sum(out.adjs[0].src_caps.values())
    deep_worst = sum(out_w.adjs[0].src_caps.values())
    assert deep_auto < 0.8 * deep_worst, (deep_auto, deep_worst)

    # later batches reuse the plan without replanning (no overflow)
    out2 = auto.sample(np.random.default_rng(2).integers(0, 3000, batch))
    assert int(out2.overflow) == 0


def test_hetero_auto_caps_results_valid():
    """Auto-capped samples still satisfy the validity oracle: every sampled
    edge exists in the relation's adjacency."""
    topo = _powerlaw_schema(seed=3, n_paper=500, n_author=200)
    s = HeteroGraphSampler(
        topo, [6, 4], input_type="paper", seed_capacity=64,
        frontier_caps="auto", seed=11,
    )
    out = s.sample(np.arange(40))
    assert int(out.overflow) == 0
    n_id = {t: np.asarray(v) for t, v in out.n_id.items()}
    for layer in out.adjs:
        for et, adj in layer.adjs.items():
            s_t, _, d_t = et
            rel = topo.relations[et]
            col, row = np.asarray(adj.edge_index)
            valid = col >= 0
            src = n_id[s_t][col[valid]]
            # row indexes the PREVIOUS dst frontier == prefix of final n_id
            dst = n_id[d_t][row[valid]]
            indptr, indices = rel.indptr, rel.indices
            for sg, dg in zip(src[:200], dst[:200]):
                assert sg in indices[indptr[dg]:indptr[dg + 1]]


def test_hetero_eid_maps_edges_to_coo_positions():
    """VERDICT r2 item 8: hetero analogue of the homogeneous e_id oracle
    (tests/test_sampler_api.py::test_eid_threading_maps_edges_to_coo_positions)
    — with_eid=True must thread relation-local COO edge positions through
    every Adj: the COO edge at position e_id is exactly (src_global,
    dst_global)."""
    topo, edges, _ = _toy_schema(seed=5)
    sampler = HeteroGraphSampler(
        topo, [4, 3], input_type="paper", seed=2, with_eid=True
    )
    out = sampler.sample(np.arange(24))
    assert int(out.overflow) == 0
    n_id = {t: np.asarray(v) for t, v in out.n_id.items()}
    checked = 0
    for layer in out.adjs:
        for et, adj in layer.adjs.items():
            s_t, _, d_t = et
            assert adj.e_id is not None
            e_id = np.asarray(adj.e_id)
            col, row = np.asarray(adj.edge_index)
            valid = col >= 0
            assert np.array_equal(e_id >= 0, valid)
            ei = edges[et]
            src_global = n_id[s_t][col[valid]]
            dst_global = n_id[d_t][row[valid]]
            assert np.array_equal(ei[0, e_id[valid]], src_global)
            assert np.array_equal(ei[1, e_id[valid]], dst_global)
            checked += int(valid.sum())
    assert checked > 50


def test_hetero_weighted_relation_biases_draws():
    """VERDICT r2 item 8: weighted relations must thread through the typed
    sampler. Construction: one dst paper with many cite-sources where a
    single source holds ~all the weight — weighted draws must concentrate on
    it; an unweighted control must not."""
    n_paper, n_author = 40, 8
    hub_dst, hot_src = 0, 7
    src = np.arange(1, 31)  # papers 1..30 all cite paper 0
    cites = np.stack([src, np.zeros_like(src)])
    writes = np.stack([
        np.random.default_rng(0).integers(0, n_author, 60),
        np.random.default_rng(1).integers(0, n_paper, 60),
    ])
    topo = HeteroCSRTopo(
        {"paper": n_paper, "author": n_author},
        {("paper", "cites", "paper"): cites,
         ("author", "writes", "paper"): writes},
    )
    w = np.full(cites.shape[1], 1e-4, np.float32)
    w[src == hot_src] = 1.0
    topo.set_edge_weight(("paper", "cites", "paper"), w)
    assert topo.weighted_edge_types == [("paper", "cites", "paper")]

    def hot_rate(weighted):
        s = HeteroGraphSampler(
            topo, [1], input_type="paper", seed=3, weighted=weighted,
            seed_capacity=128,
        )
        hits = draws = 0
        for i in range(60):
            out = s.sample(np.asarray([hub_dst]))
            adj = out.adjs[0].adjs[("paper", "cites", "paper")]
            col, row = np.asarray(adj.edge_index)
            ids = np.asarray(out.n_id["paper"])[col[(col >= 0) & (row == 0)]]
            hits += int((ids == hot_src).sum())
            draws += int(((col >= 0) & (row == 0)).sum())
        return hits / max(draws, 1)

    assert hot_rate(True) > 0.9  # ~all weight on the hot edge
    assert hot_rate(False) < 0.3  # uniform control: 1/30 expected


def test_hetero_weighted_validation():
    topo, _, _ = _toy_schema()
    with pytest.raises(ValueError, match="edge weights"):
        HeteroGraphSampler(topo, [2], input_type="paper", weighted=True)
    with pytest.raises(ValueError, match="edge weights"):
        HeteroGraphSampler(
            topo, [2], input_type="paper",
            weighted=[("paper", "cites", "paper")],
        )
    with pytest.raises(ValueError, match="unknown relation"):
        topo.set_edge_weight(("x", "y", "z"), np.ones(3))
