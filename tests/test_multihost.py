"""Multi-host smoke: 2 separate processes form a jax.distributed job.

VERDICT r2 item 6: ``init_distributed`` (parallel/mesh.py) was an untested
wrapper and the native CSR builder's cross-host byte-identical claim
(native/quiver_host.cpp) was asserted, never exercised in a multi-process
setting. Here two real OS processes rendezvous over a localhost
coordinator (CPU backend, 4 virtual devices each), independently build the
same graph, allgather their CSR digests, and run a jitted reduction over a
mesh spanning both processes. See tests/distributed_worker.py for the
checks each worker performs.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

# multihost: minutes of multi-process rendezvous, and the jax CPU backend
# must support multiprocess collectives — out of the tier-1
# `-m 'not slow'` budget (VERDICT r5 weak #5)
pytestmark = pytest.mark.slow

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("nprocs", [
    2,
    pytest.param(3, marks=pytest.mark.skipif(
        (os.cpu_count() or 1) < 4,
        reason="3-process rendezvous thrashes below 4 cores",
    )),
])
def test_multi_process_distributed_job(nprocs):
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers set their own device count
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(i), str(nprocs), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        )
        for i in range(nprocs)
    ]
    results = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
            line = [l for l in out.splitlines() if l.startswith("{")][-1]
            results.append(json.loads(line))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    assert len(results) == nprocs
    for r in results:
        assert r["ok_csr"], "CSR builds diverged across hosts"
        assert r["ok_sum"], "cross-process sharded reduction wrong"
        assert r["process_count"] == nprocs
        assert r["global_devices"] == 4 * nprocs
