"""Prefetcher tests: determinism vs the sequential loop, depth semantics,
error propagation, early exit, and the resilience layer's bounded
retry/backoff/skip behavior under a deterministic FaultPlan."""

import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from quiver_tpu import CSRTopo, Feature, GraphSageSampler
from quiver_tpu.parallel.pipeline import Batch, Prefetcher


@pytest.fixture(scope="module")
def setup():
    rng = np.random.default_rng(0)
    ei = rng.integers(0, 200, size=(2, 2000)).astype(np.int64)
    topo = CSRTopo(edge_index=ei)
    feat = rng.normal(size=(topo.node_count, 16)).astype(np.float32)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    return topo, feature


def _seed_stream(n_batches, batch, n_nodes, seed=7):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, n_nodes, batch) for _ in range(n_batches)]


def test_prefetch_matches_sequential(setup):
    topo, feature = setup
    seeds = _seed_stream(6, 32, topo.node_count)

    seq_sampler = GraphSageSampler(topo, [4, 3], seed_capacity=32, seed=3)
    seq = [(seq_sampler.sample(s), s) for s in seeds]
    seq_x = [feature[out.n_id] for out, _ in seq]

    pre_sampler = GraphSageSampler(topo, [4, 3], seed_capacity=32, seed=3)
    batches = list(Prefetcher(pre_sampler, feature, depth=3).run(seeds))

    assert len(batches) == len(seq)
    for (out, s), x, b in zip(seq, seq_x, batches):
        np.testing.assert_array_equal(np.asarray(b.seeds), np.asarray(s))
        np.testing.assert_array_equal(np.asarray(b.out.n_id), np.asarray(out.n_id))
        for a_seq, a_pre in zip(out.adjs, b.out.adjs):
            np.testing.assert_array_equal(
                np.asarray(a_seq.edge_index), np.asarray(a_pre.edge_index)
            )
        np.testing.assert_array_equal(np.asarray(b.x), np.asarray(x))


def test_sampler_only_mode(setup):
    topo, _ = setup
    sampler = GraphSageSampler(topo, [3], seed_capacity=16, seed=0)
    batches = list(Prefetcher(sampler, None).run(_seed_stream(3, 16, topo.node_count)))
    assert all(b.x is None for b in batches)
    assert all(int(b.out.n_count) >= 16 for b in batches)


def test_transform_runs_on_worker(setup):
    topo, feature = setup
    sampler = GraphSageSampler(topo, [3], seed_capacity=16, seed=0)
    labels = jnp.arange(topo.node_count, dtype=jnp.int32)

    def with_labels(seeds, out, x):
        return Batch(seeds, out, (x, labels[jnp.clip(out.n_id[:16], 0)]))

    batches = list(
        Prefetcher(sampler, feature, transform=with_labels).run(
            _seed_stream(2, 16, topo.node_count)
        )
    )
    for b in batches:
        x, lab = b.x
        np.testing.assert_array_equal(
            np.asarray(lab), np.clip(np.asarray(b.out.n_id[:16]), 0, None)
        )


def test_depth_validation(setup):
    topo, _ = setup
    sampler = GraphSageSampler(topo, [3], seed_capacity=16)
    with pytest.raises(ValueError, match="depth"):
        Prefetcher(sampler, depth=0)


def test_worker_exception_propagates(setup):
    topo, _ = setup
    sampler = GraphSageSampler(topo, [3], seed_capacity=16, seed=0)
    streams = [
        np.arange(16),
        np.full(16, topo.node_count + 5),  # out-of-range -> ValueError
        np.arange(16),
    ]
    got = []
    with pytest.raises(ValueError, match="seed ids"):
        for b in Prefetcher(sampler, None, depth=1).run(streams):
            got.append(b)
    assert len(got) == 1  # first batch delivered before the failure surfaced


def test_early_exit_cancels_cleanly(setup):
    topo, _ = setup
    sampler = GraphSageSampler(topo, [3], seed_capacity=16, seed=0)
    gen = Prefetcher(sampler, None, depth=2).run(
        _seed_stream(10, 16, topo.node_count)
    )
    next(gen)
    gen.close()  # no hang, no exception


def test_early_exit_returns_promptly_despite_inflight_dispatch(setup):
    """A consumer ``break`` must not wait for the in-flight sample+gather:
    the worker blocks on an event the test only releases AFTER close()
    returns — with executor-``with`` semantics (shutdown(wait=True)) this
    would deadlock until the 20s deadman fires."""
    topo, _ = setup
    inner = GraphSageSampler(topo, [3], seed_capacity=16, seed=0)
    release = threading.Event()
    calls = []

    class SlowSampler:
        def sample(self, seeds):
            calls.append(1)
            if len(calls) > 1:  # first batch fast, second blocks
                release.wait(20)
            return inner.sample(seeds)

    gen = Prefetcher(SlowSampler(), None, depth=2).run(
        _seed_stream(6, 16, topo.node_count)
    )
    next(gen)  # batch 1 delivered; batch 2 now blocked in flight
    t0 = time.perf_counter()
    gen.close()
    dt = time.perf_counter() - t0
    release.set()  # let the background worker finish and exit
    assert dt < 5.0, f"early exit blocked {dt:.1f}s on the in-flight batch"


# -- retry / skip resilience (resilience/faults.py is the fault source) -------


def _fresh_sampler(topo):
    return GraphSageSampler(topo, [3], seed_capacity=16, seed=0)


def test_retry_recovers_transient_faults_bit_identically(setup):
    """Two injected transient failures on batch 1: with retries the stream
    completes AND matches a fault-free sequential run bitwise — a failed
    call never reaches the wrapped sampler, so PRNG call order holds."""
    from quiver_tpu.obs import StepTimeline
    from quiver_tpu.resilience import FaultPlan

    topo, _ = setup
    seeds = _seed_stream(4, 16, topo.node_count)
    oracle = _fresh_sampler(topo)  # one sampler: PRNG advances per batch
    clean = [oracle.sample(s) for s in seeds]

    faulty = FaultPlan(sampler_faults={1: 2}).wrap_sampler(
        _fresh_sampler(topo)
    )
    tl = StepTimeline()
    pf = Prefetcher(faulty, None, depth=2, retries=3, backoff=1e-4,
                    timeline=tl)
    batches = list(pf.run(seeds))
    assert len(batches) == 4
    assert pf.retries_total == 2 and pf.skips_total == 0
    assert tl.stats("prefetch.retry_wait").count == 2
    assert tl.stats("prefetch.dispatch").count == 4
    for c, b in zip(clean, batches):
        np.testing.assert_array_equal(
            np.asarray(c.n_id), np.asarray(b.out.n_id)
        )


def test_retry_skip_counters_land_on_registry(setup):
    """Satellite: the lifetime retry/skip totals ride a graftscope
    MetricsRegistry (not just the StepTimeline), so metrics_report shows
    pipeline health alongside the in-program resilience counters."""
    from quiver_tpu.obs.registry import (
        PREFETCH_RETRIES,
        PREFETCH_SKIPS,
        MetricsRegistry,
    )
    from quiver_tpu.resilience import FaultPlan

    topo, _ = setup
    seeds = _seed_stream(4, 16, topo.node_count)
    # batch 1: two transients (recovered); batch 3: poisoned past retries
    faulty = FaultPlan(sampler_faults={1: 2, 3: 5}).wrap_sampler(
        _fresh_sampler(topo)
    )
    reg = MetricsRegistry()
    pf = Prefetcher(faulty, None, depth=1, retries=2, backoff=0.0,
                    skip_policy="skip", metrics=reg)
    batches = list(pf.run(seeds))
    assert len(batches) == 3  # batch 3 dropped
    assert pf.retries_total == 4 and pf.skips_total == 1
    assert int(np.asarray(reg.value(PREFETCH_RETRIES))) == 4
    assert int(np.asarray(reg.value(PREFETCH_SKIPS))) == 1


def test_retry_exhaustion_raises_in_order(setup):
    from quiver_tpu.resilience import FaultPlan, TransientFault

    topo, _ = setup
    seeds = _seed_stream(4, 16, topo.node_count)
    faulty = FaultPlan(sampler_faults={1: 3}).wrap_sampler(
        _fresh_sampler(topo)
    )
    got = []
    with pytest.raises(TransientFault, match="batch 1"):
        for b in Prefetcher(faulty, None, depth=1, retries=1,
                            backoff=0.0).run(seeds):
            got.append(b)
    assert len(got) == 1  # batch 0 delivered before the failure surfaced


def test_skip_policy_drops_poisoned_batch_keeps_order(setup):
    """A permanently-failing batch under skip_policy="skip": dropped and
    counted; the survivors match a clean run over the surviving seed list
    (the skipped batch never consumed a sampler draw)."""
    from quiver_tpu.obs import StepTimeline
    from quiver_tpu.resilience import FaultPlan

    topo, _ = setup
    seeds = _seed_stream(4, 16, topo.node_count)
    faulty = FaultPlan(sampler_faults={1: 10**9}).wrap_sampler(
        _fresh_sampler(topo)
    )
    tl = StepTimeline()
    pf = Prefetcher(faulty, None, depth=2, retries=1, backoff=0.0,
                    skip_policy="skip", timeline=tl)
    batches = list(pf.run(seeds))
    assert len(batches) == 3
    assert pf.skips_total == 1 and pf.retries_total == 1
    assert tl.stats("prefetch.skip").count == 1
    survivor = _fresh_sampler(topo)
    for s, b in zip((seeds[0], seeds[2], seeds[3]), batches):
        np.testing.assert_array_equal(
            np.asarray(survivor.sample(s).n_id), np.asarray(b.out.n_id)
        )


def test_retry_knob_validation(setup):
    topo, _ = setup
    sampler = _fresh_sampler(topo)
    with pytest.raises(ValueError, match="retries"):
        Prefetcher(sampler, retries=-1)
    with pytest.raises(ValueError, match="skip_policy"):
        Prefetcher(sampler, skip_policy="drop")
    with pytest.raises(ValueError, match="backoff"):
        Prefetcher(sampler, backoff=-0.1)


def test_retry_backoff_is_bounded_and_jitter_deterministic(setup):
    """Backoff doubles then caps; the jitter PRNG is seeded, so two
    prefetchers with the same retry_seed observe identical sleeps."""
    from quiver_tpu.obs import StepTimeline
    from quiver_tpu.resilience import FaultPlan

    topo, _ = setup
    seeds = _seed_stream(2, 16, topo.node_count)

    def waits(retry_seed):
        faulty = FaultPlan(sampler_faults={0: 4}).wrap_sampler(
            _fresh_sampler(topo)
        )
        tl = StepTimeline()
        pf = Prefetcher(faulty, None, retries=4, backoff=1e-3,
                        backoff_cap=2e-3, jitter=0.5, timeline=tl,
                        retry_seed=retry_seed)
        assert len(list(pf.run(seeds))) == 2
        st = tl.stats("prefetch.retry_wait")
        return st.count, st.max

    count_a, max_a = waits(5)
    count_b, max_b = waits(5)
    assert count_a == count_b == 4
    assert max_a == max_b  # same seed, same jitter draws
    assert max_a <= 2e-3 * 1.5 + 1e-9  # cap * (1 + jitter)


def test_queue_depth_gauge_tracks_inflight(setup):
    """Satellite: prefetch.queue_depth rides the registry — pinned at
    `depth` while the pipeline keeps up, and drained back to 0 by the
    end-of-stream flush."""
    from quiver_tpu.obs.registry import PREFETCH_QUEUE_DEPTH, MetricsRegistry

    topo, _ = setup
    seeds = _seed_stream(6, 16, topo.node_count)
    reg = MetricsRegistry()
    pf = Prefetcher(_fresh_sampler(topo), None, depth=2, metrics=reg)

    observed = []
    for _ in pf.run(seeds):
        observed.append(int(np.asarray(reg.value(PREFETCH_QUEUE_DEPTH))))
    # mid-stream the gauge saw the configured depth at least once, and
    # never exceeded depth + 1 (the transient before the blocking pop)
    assert max(observed) >= 2
    assert max(observed) <= 3
    # the drain loop pops without refilling: the last yield leaves 0
    assert observed[-1] == 0
