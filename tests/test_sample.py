"""Sample-validity oracle tests for the XLA neighbor sampler.

The oracle (reference test_quiver_cpu.cpp:9-75 pattern): every sampled
neighbor must be a member of the seed's adjacency list, counts must equal
min(deg, k), and rows with deg > k must have no duplicates. Plus a
distributional check on inclusion frequency (the stratified+rotation scheme
guarantees first-order inclusion probability k/deg).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from quiver_tpu import CSRTopo, SampleMode
from quiver_tpu.ops.sample import sample_layer
from quiver_tpu.utils.graphgen import generate_pareto_graph


def _simple_graph(n, deg):
    """Node i's neighbors are exactly {(j+1)*n + i | j in range(deg)} % V.

    Deterministic membership check (reference test_quiver_cpu.cpp simple_graph).
    """
    row = np.repeat(np.arange(n), deg)
    col = (np.arange(deg)[None, :] + 1) * n + np.arange(n)[:, None]
    v = n * (deg + 1)
    return np.stack([row, col.reshape(-1) % v]), v


@pytest.mark.parametrize("n,deg,k", [(32, 3, 5), (32, 8, 8), (64, 12, 4)])
def test_sample_validity(n, deg, k):
    ei, v = _simple_graph(n, deg)
    # pad indptr out to v+1 nodes so every id is a valid seed
    topo = CSRTopo(edge_index=ei)
    indptr = np.concatenate([topo.indptr, np.full(v - topo.node_count, topo.edge_count)])
    topo = CSRTopo(indptr=indptr, indices=topo.indices)
    dev = topo.to_device()

    S = 48
    seeds = np.full(S, -1, np.int32)
    num = 40
    seeds[:num] = np.random.default_rng(0).integers(0, n, num)
    nbr, counts = sample_layer(dev, jnp.asarray(seeds), jnp.int32(num), k, jax.random.PRNGKey(0))
    nbr, counts = np.asarray(nbr), np.asarray(counts)

    adj = {i: set(((np.arange(deg) + 1) * n + i) % v) for i in range(n)}
    for r in range(S):
        if r >= num:
            assert counts[r] == 0 and np.all(nbr[r] == -1)
            continue
        s = seeds[r]
        expect = min(deg, k)
        assert counts[r] == expect
        got = nbr[r][nbr[r] >= 0]
        assert len(got) == expect
        assert set(got.tolist()) <= adj[s]
        if deg > k:
            assert len(set(got.tolist())) == k  # distinct when subsampling


def test_sample_take_all_exact():
    # deg <= k rows must return the full neighborhood (intra-row order is
    # unspecified — the native CSR scatter is unordered across threads)
    ei, v = _simple_graph(16, 4)
    topo = CSRTopo(edge_index=ei).to_device()
    seeds = jnp.arange(10, dtype=jnp.int32)
    nbr, counts = sample_layer(topo, seeds, jnp.int32(10), 6, jax.random.PRNGKey(1))
    nbr = np.asarray(nbr)
    for r in range(10):
        expect = sorted((((np.arange(4) + 1) * 16 + r) % v).tolist())
        assert sorted(nbr[r, :4].tolist()) == expect
        assert np.all(nbr[r, 4:] == -1)


def test_sample_zero_degree_and_padding():
    indptr = np.array([0, 0, 2, 2])
    indices = np.array([0, 2])
    topo = CSRTopo(indptr=indptr, indices=indices).to_device()
    seeds = jnp.array([0, 1, 2, -1], dtype=jnp.int32)
    nbr, counts = sample_layer(topo, seeds, jnp.int32(3), 3, jax.random.PRNGKey(2))
    assert list(np.asarray(counts)) == [0, 2, 0, 0]
    assert np.all(np.asarray(nbr)[0] == -1)
    assert np.all(np.asarray(nbr)[3] == -1)


def test_inclusion_probability_uniform():
    # one node with degree 20, fanout 5: each neighbor should appear with
    # frequency ~ k/deg = 0.25 over many trials
    deg, k, trials = 20, 5, 400
    # node 0 has `deg` neighbors (ids 100..119); nodes 1..119 are isolated
    indptr = np.concatenate([[0], np.full(120, deg)])
    indices = np.arange(100, 100 + deg)
    topo = CSRTopo(indptr=indptr, indices=indices).to_device()
    seeds = jnp.zeros(1, jnp.int32)
    counts = np.zeros(deg)
    for t in range(trials):
        nbr, _ = sample_layer(topo, seeds, jnp.int32(1), k, jax.random.PRNGKey(t))
        got = np.asarray(nbr)[0]
        got = got[got >= 0] - 100
        assert len(set(got.tolist())) == k
        counts[got] += 1
    freq = counts / trials
    # expected 0.25; binomial std ≈ sqrt(.25*.75/400) ≈ 0.0217 → 5 sigma
    assert np.all(np.abs(freq - k / deg) < 0.11), freq


def test_sample_with_eid():
    ei, v = _simple_graph(8, 3)
    topo = CSRTopo(edge_index=ei)
    dev = topo.to_device(with_eid=True)
    seeds = jnp.arange(5, dtype=jnp.int32)
    nbr, counts, eids = sample_layer(dev, seeds, jnp.int32(5), 2, jax.random.PRNGKey(0), with_eid=True)
    nbr, eids = np.asarray(nbr), np.asarray(eids)
    # each returned eid must point at the COO edge (seed -> neighbor)
    for r in range(5):
        for c in range(2):
            if eids[r, c] >= 0:
                assert ei[0, eids[r, c]] == r
                assert ei[1, eids[r, c]] == nbr[r, c]


def test_host_mode_matches_hbm_mode():
    ei = generate_pareto_graph(500, 6.0, seed=3)
    topo = CSRTopo(edge_index=ei)
    hbm = topo.to_device(SampleMode.HBM)
    host = topo.to_device(SampleMode.HOST)
    seeds = jnp.asarray(np.random.default_rng(0).integers(0, 500, 64), dtype=jnp.int32)
    key = jax.random.PRNGKey(9)
    a, ca = sample_layer(hbm, seeds, jnp.int32(64), 4, key)
    b, cb = sample_layer(host, seeds, jnp.int32(64), 4, key)
    assert np.array_equal(np.asarray(a), np.asarray(b))
    assert np.array_equal(np.asarray(ca), np.asarray(cb))


def test_duplicate_seeds_exceeding_node_count_keep_capacity():
    # regression: caps were clamped to node_count, dropping forced duplicate
    # seed lanes when batch > number of nodes
    from quiver_tpu import GraphSageSampler

    ei = np.stack([np.arange(10), (np.arange(10) + 1) % 10])
    topo = CSRTopo(edge_index=ei)
    sampler = GraphSageSampler(topo, [2], seed_capacity=64)
    seeds = np.zeros(50, dtype=np.int64)
    out = sampler.sample(seeds)
    nid = np.asarray(out.n_id)
    assert nid.shape[0] >= 50
    assert (nid[:50] == 0).all()
    assert int(out.overflow) == 0
