"""End-to-end GraphSageSampler contract tests (PyG-compat output)."""

import numpy as np
import jax.numpy as jnp

from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.utils.graphgen import generate_pareto_graph


def _sampler(n=400, avg_deg=8.0, sizes=(5, 3), **kw):
    ei = generate_pareto_graph(n, avg_deg, seed=0)
    topo = CSRTopo(edge_index=ei)
    return topo, GraphSageSampler(topo, sizes, **kw)


def test_sample_output_shapes_and_seed_prefix():
    topo, sampler = _sampler()
    seeds = np.arange(10, 74)
    out = sampler.sample(seeds)
    assert out.batch_size == 64
    n_id = np.asarray(out.n_id)
    # n_id[:batch_size] == seeds (PyG label contract)
    assert np.array_equal(n_id[:64], seeds)
    assert len(out.adjs) == 2
    # deepest layer first: adjs[0] target count == layer-1 frontier cap
    assert out.adjs[0].size[1] == out.adjs[1].size[0]
    assert int(out.overflow) == 0


def test_sampled_edges_exist_in_graph():
    ei = generate_pareto_graph(300, 5.0, seed=2)
    topo = CSRTopo(edge_index=ei)
    sampler = GraphSageSampler(topo, [4, 3])
    edge_set = set(zip(ei[0].tolist(), ei[1].tolist()))

    seeds = np.random.default_rng(0).choice(300, 32, replace=False)
    out = sampler.sample(seeds)
    n_id = np.asarray(out.n_id)

    # walk adjs from deepest to shallowest, reconstructing global edges
    # adjs[-1] is the layer sampled directly from the seeds
    for li, adj in enumerate(reversed(out.adjs)):
        edge_index = np.asarray(adj.edge_index)
        src, dst = edge_index
        valid = src >= 0
        assert np.array_equal(valid, dst >= 0)
        gsrc = n_id[src[valid]]
        gdst = n_id[dst[valid]]
        for s, d in zip(gdst.tolist(), gsrc.tolist()):
            # target (seed-side) -> source (neighbor) must be a real edge
            assert (s, d) in edge_set


def test_full_neighborhood_fanout():
    ei = np.stack([np.array([0, 0, 0, 1, 2]), np.array([1, 2, 3, 2, 3])])
    topo = CSRTopo(edge_index=ei)
    sampler = GraphSageSampler(topo, [-1])
    out = sampler.sample(np.array([0, 1, 2, 3]))
    adj = out.adjs[0]
    src = np.asarray(adj.edge_index[0])
    dst = np.asarray(adj.edge_index[1])
    n_id = np.asarray(out.n_id)
    # node 0 (seed-local id 0) has 3 neighbors; all must be present
    got = sorted(n_id[src[(src >= 0) & (dst == 0)]].tolist())
    assert got == [1, 2, 3]


def test_determinism_under_seed():
    topo, s1 = _sampler(seed=42)
    _, s2 = _sampler(seed=42)
    seeds = np.arange(32)
    a = s1.sample(seeds)
    b = s2.sample(seeds)
    assert np.array_equal(np.asarray(a.n_id), np.asarray(b.n_id))
    for x, y in zip(a.adjs, b.adjs):
        assert np.array_equal(np.asarray(x.edge_index), np.asarray(y.edge_index))
    # and successive calls differ (fresh key per call)
    c = s1.sample(seeds)
    assert not np.array_equal(np.asarray(a.adjs[0].edge_index), np.asarray(c.adjs[0].edge_index))


def test_multilayer_frontier_growth_and_reuse():
    topo, sampler = _sampler(sizes=(6, 4, 2))
    out = sampler.sample(np.arange(16))
    assert len(out.adjs) == 3
    n_id = np.asarray(out.n_id)
    n_count = int(out.n_count)
    # all ids valid in prefix, -1 after
    assert np.all(n_id[:n_count] >= 0)
    assert np.all(n_id[n_count:] == -1)
    # no duplicate node ids in frontier
    vals = n_id[:n_count]
    assert len(np.unique(vals)) == len(vals)


def test_share_ipc_roundtrip():
    topo, sampler = _sampler()
    rebuilt = GraphSageSampler.lazy_from_ipc_handle(sampler.share_ipc())
    assert rebuilt.sizes == sampler.sizes


def test_duplicate_seeds_keep_positions():
    # PyG contract: n_id[:batch_size] == seeds verbatim, duplicates included
    topo, sampler = _sampler()
    seeds = np.array([7, 7, 3, 9, 3])
    out = sampler.sample(seeds)
    assert np.array_equal(np.asarray(out.n_id)[:5], seeds)
    # later frontier ids still unique apart from the forced dups
    n_id = np.asarray(out.n_id)[: int(out.n_count)]
    rest = n_id[5:]
    assert len(np.unique(rest)) == len(rest)


def test_out_of_range_seeds_rejected():
    import pytest

    topo, sampler = _sampler(n=100)
    with pytest.raises(ValueError, match="seed ids"):
        sampler.sample(np.array([5, 100]))
    with pytest.raises(ValueError, match="seed ids"):
        sampler.sample(np.array([-2, 5]))


def test_eid_threading_maps_edges_to_coo_positions():
    """VERDICT r1 item 4: with_eid=True must populate Adj.e_id end-to-end.

    Oracle (reference sage_sampler.py:100-109 parity): for every valid
    sampled edge, the COO edge at position e_id is exactly
    (seed_global, neighbor_global). Frontiers are nested (seeds are forced
    first), so both locals of every layer index into the final n_id.
    """
    n = 400
    ei = generate_pareto_graph(n, 8.0, seed=1)
    topo = CSRTopo(edge_index=ei)
    sampler = GraphSageSampler(topo, [5, 3], with_eid=True, seed=3)
    out = sampler.sample(np.arange(40, 104))
    assert int(out.overflow) == 0
    n_id = np.asarray(out.n_id)
    checked = 0
    for adj in out.adjs:
        assert adj.e_id is not None
        e_id = np.asarray(adj.e_id)
        col, row = np.asarray(adj.edge_index)
        valid = col >= 0
        # e_id valid exactly where the edge is valid
        assert np.array_equal(e_id >= 0, valid)
        src_global = n_id[row[valid]]
        nbr_global = n_id[col[valid]]
        assert np.array_equal(ei[0, e_id[valid]], src_global)
        assert np.array_equal(ei[1, e_id[valid]], nbr_global)
        checked += int(valid.sum())
    assert checked > 100


def test_eid_none_without_flag():
    _, sampler = _sampler()
    out = sampler.sample(np.arange(16))
    assert all(adj.e_id is None for adj in out.adjs)


def test_eid_with_pallas_kernel():
    # with_eid + pallas rides the fused engine now (PR 16): the eid lane
    # comes back aligned with edge_index (bitwise differentials vs the
    # XLA oracle live in test_fused_sampler.py)
    ei = generate_pareto_graph(300, 6.0, seed=2)
    topo = CSRTopo(edge_index=ei)
    s = GraphSageSampler(topo, [4], kernel="pallas", with_eid=True,
                         seed_capacity=16)
    out = s.sample(np.arange(16))
    for adj in out.adjs:
        assert adj.e_id is not None
        src = np.asarray(adj.edge_index)[0]
        eids = np.asarray(adj.e_id)
        assert np.array_equal(eids >= 0, src >= 0)
