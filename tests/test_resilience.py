"""Resilience-layer tests: non-finite step guard, deterministic fault
injection, checkpoint/auto-resume.

Fast lane: FaultPlan semantics, guard skip/counter behavior over eager
steps, constructor validation, empty-checkpoint resume passthrough.
Slow lane: the epoch-level differentials — guard on/off bit-parity with
zero faults, and the preemption drill (kill at step k via FaultPlan,
resume, compare the remaining loss trajectory bitwise).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from quiver_tpu import CSRTopo, FaultPlan, GraphSageSampler, Preemption
from quiver_tpu.feature.shard import ShardedFeature
from quiver_tpu.models.sage import GraphSAGE
from quiver_tpu.obs.registry import GUARD_NONFINITE, GUARD_SKIPPED
from quiver_tpu.parallel.mesh import make_mesh
from quiver_tpu.parallel.trainer import DistributedTrainer
from quiver_tpu.resilience import TransientFault
from quiver_tpu.resilience.guard import nonfinite_count


def _tree_bitwise_equal(a, b) -> bool:
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


def _build_trainer(guard=False, plan=None, checkpoint_dir=None,
                   checkpoint_every=0):
    rng = np.random.default_rng(0)
    n = 96
    topo = CSRTopo(
        edge_index=rng.integers(0, n, size=(2, 800)).astype(np.int64)
    )
    feat = rng.normal(size=(n, 8)).astype(np.float32)
    mesh = make_mesh(data=2, feature=4)
    store = ShardedFeature(
        mesh, device_cache_size=n * 8, csr_topo=topo
    ).from_cpu_tensor(feat)
    sampler = GraphSageSampler(topo, [3, 2], seed=0, seed_capacity=8)
    model = GraphSAGE(hidden=8, num_classes=4, num_layers=2)
    kw = {}
    if checkpoint_dir is not None:
        kw = dict(checkpoint_dir=checkpoint_dir,
                  checkpoint_every=checkpoint_every)
    trainer = DistributedTrainer(
        mesh, sampler, store, model, optax.sgd(1e-2), local_batch=8,
        seed_sharding="all", nonfinite_guard=guard, fault_plan=plan, **kw
    )
    params, opt = trainer.init(jax.random.PRNGKey(0))
    labels = jnp.asarray(rng.integers(0, 4, n).astype(np.int32))
    return trainer, params, opt, labels


# -- FaultPlan (host-side, no compile) ----------------------------------------


def test_fault_plan_masks_and_queries():
    plan = FaultPlan(nan_feature_steps=(1, 3), nan_rows=2,
                     preempt_at_step=5)
    assert plan.injects_nan() and plan.nan_at(3) and not plan.nan_at(2)
    np.testing.assert_array_equal(
        plan.nan_mask(5), [False, True, False, True, False]
    )
    assert plan.preempts_in(3, 6) and not plan.preempts_in(0, 5)
    assert not FaultPlan().injects_nan()
    assert not FaultPlan().preempts_in(0, 10**6)


def test_fault_plan_chaos_is_seed_deterministic():
    a = FaultPlan.chaos(seed=7, steps=50, nan_p=0.2, transient_p=0.3)
    b = FaultPlan.chaos(seed=7, steps=50, nan_p=0.2, transient_p=0.3)
    assert a == b
    c = FaultPlan.chaos(seed=8, steps=50, nan_p=0.2, transient_p=0.3)
    assert a != c
    assert a.nan_feature_steps  # p=0.2 over 50 steps: drew something


def test_fault_plan_validation():
    with pytest.raises(ValueError, match="nan_rows"):
        FaultPlan(nan_rows=0)
    with pytest.raises(ValueError, match="sampler_faults"):
        FaultPlan(sampler_faults={-1: 2})
    with pytest.raises(ValueError, match="feature_faults"):
        FaultPlan(feature_faults={0: 0})


def test_faulty_feature_injects_nan_and_faults():
    feat = np.ones((10, 4), np.float32)

    class Store:
        def __getitem__(self, ids):
            return feat[ids]

    plan = FaultPlan(feature_faults={1: 2}, nan_feature_steps=(1,),
                     nan_rows=2)
    wrapped = plan.wrap_feature(Store())
    ids = np.arange(3)
    assert np.isfinite(wrapped[ids]).all()  # lookup 0: clean
    for _ in range(2):  # lookups 1-2 planned transient failures
        with pytest.raises(TransientFault, match="feature"):
            wrapped[ids]
    rows = wrapped[ids]  # successful lookup #1: NaN-poisoned rows
    assert np.isnan(rows[:2]).all() and np.isfinite(rows[2:]).all()


def test_nonfinite_count_ignores_integer_leaves():
    tree = {
        "f": jnp.array([1.0, jnp.nan, jnp.inf]),
        "i": jnp.arange(3),
        "b": jnp.float32(0.0),
    }
    assert int(nonfinite_count(tree)) == 2


# -- non-finite step guard (eager steps; the fast-lane guard unit) ------------


def test_guard_skips_poisoned_step_and_counts():
    """Acceptance (fast half): with a NaN batch injected, params/opt_state
    after the poisoned step equal the ones before it bit-for-bit, the skip
    counter reads 1 (replicated — every chip agrees), and the next clean
    step trains normally."""
    plan = FaultPlan(nan_feature_steps=(1,), nan_rows=4)
    trainer, params, opt, labels = _build_trainer(guard=True, plan=plan)
    rng = np.random.default_rng(3)

    params, opt, loss0 = trainer.step(
        params, opt, rng.integers(0, 96, 64), labels, jax.random.PRNGKey(0)
    )
    assert np.isfinite(float(loss0))
    assert int(np.asarray(trainer.metrics.value(GUARD_SKIPPED))) == 0

    p_before, o_before = params, opt
    params, opt, loss1 = trainer.step(
        params, opt, rng.integers(0, 96, 64), labels, jax.random.PRNGKey(1)
    )
    # the poisoned step's loss is honestly NaN, but nothing was applied
    assert not np.isfinite(float(loss1))
    assert _tree_bitwise_equal(params, p_before)
    assert _tree_bitwise_equal(opt, o_before)
    assert int(np.asarray(trainer.metrics.value(GUARD_SKIPPED))) == 1
    assert int(np.asarray(trainer.metrics.value(GUARD_NONFINITE))) > 0

    params, opt, loss2 = trainer.step(
        params, opt, rng.integers(0, 96, 64), labels, jax.random.PRNGKey(2)
    )
    assert np.isfinite(float(loss2))
    assert not _tree_bitwise_equal(params, p_before)
    assert int(np.asarray(trainer.metrics.value(GUARD_SKIPPED))) == 0
    rep = trainer.metrics_report()
    assert GUARD_SKIPPED in rep and GUARD_NONFINITE in rep


def test_guard_off_registers_no_guard_metrics():
    trainer, *_ = _build_trainer(guard=False)
    assert GUARD_SKIPPED not in trainer.metrics.names()
    assert GUARD_NONFINITE not in trainer.metrics.names()


# -- checkpoint knobs ---------------------------------------------------------


def test_checkpoint_knob_validation(tmp_path):
    with pytest.raises(ValueError, match="checkpoint_every"):
        _build_trainer(checkpoint_dir=tmp_path / "ck", checkpoint_every=0)
    rng = np.random.default_rng(0)
    topo = CSRTopo(
        edge_index=rng.integers(0, 96, size=(2, 800)).astype(np.int64)
    )
    mesh = make_mesh(data=2, feature=4)
    store = ShardedFeature(mesh, device_cache_size=96 * 8).from_cpu_tensor(
        rng.normal(size=(96, 8)).astype(np.float32)
    )
    with pytest.raises(ValueError, match="nothing to write"):
        DistributedTrainer(
            mesh, GraphSageSampler(topo, [3], seed=0, seed_capacity=8),
            store, GraphSAGE(hidden=8, num_classes=4, num_layers=1),
            optax.sgd(1e-2), local_batch=8, seed_sharding="all",
            checkpoint_every=4,
        )


def test_resume_without_checkpointing_raises():
    trainer, params, opt, _ = _build_trainer()
    with pytest.raises(ValueError, match="resume"):
        trainer.resume(params, opt)


def test_resume_empty_directory_passes_through(tmp_path):
    trainer, params, opt, _ = _build_trainer(
        checkpoint_dir=tmp_path / "ck", checkpoint_every=2
    )
    p, o, key, step, epoch = trainer.resume(params, opt)
    assert step == 0 and epoch == 0 and key is None
    assert p is params and o is opt
    trainer.checkpointer.close()


# -- epoch-level differentials (slow lane) ------------------------------------


@pytest.mark.slow
def test_guard_on_off_loss_bitwise_identical():
    """Acceptance: with the guard enabled and ZERO injected faults, the
    epoch_scan loss trajectory is bit-identical to the guard-off path —
    the verdict psum and cond ride alongside the training math, never
    inside it."""
    losses = {}
    for guard in (False, True):
        trainer, params, opt, labels = _build_trainer(guard=guard)
        seed_mat = trainer.pack_epoch(np.tile(np.arange(96), 4), seed=0)
        _, _, ls = trainer.epoch_scan(
            params, opt, seed_mat, labels, jax.random.PRNGKey(7)
        )
        losses[guard] = np.asarray(ls)
    np.testing.assert_array_equal(
        losses[True].view(np.uint32), losses[False].view(np.uint32)
    )


@pytest.mark.slow
def test_guarded_epoch_scan_skips_injected_nan_step():
    """A NaN batch inside the SCANNED epoch: the per-step skip vector
    marks exactly the poisoned step, and the final params equal those of
    a run over the same seeds with the poisoned step's update elided —
    i.e. the poison never touched the optimizer."""
    plan = FaultPlan(nan_feature_steps=(2,), nan_rows=4)
    trainer, params, opt, labels = _build_trainer(guard=True, plan=plan)
    seed_mat = trainer.pack_epoch(np.tile(np.arange(96), 4), seed=0)
    _, _, ls = trainer.epoch_scan(
        params, opt, seed_mat, labels, jax.random.PRNGKey(7)
    )
    skipped = np.asarray(trainer.metrics.value(GUARD_SKIPPED))
    assert skipped.shape == (seed_mat.shape[0],)
    expect = np.zeros(seed_mat.shape[0], np.int32)
    expect[2] = 1
    np.testing.assert_array_equal(skipped, expect)
    ls = np.asarray(ls)
    assert not np.isfinite(ls[2]) and np.isfinite(np.delete(ls, 2)).all()


@pytest.mark.slow
def test_preemption_drill_resume_bit_parity(tmp_path):
    """Acceptance e2e: crash at step k (FaultPlan preemption) + resume()
    reproduces the uninterrupted run's remaining loss trajectory and
    final params bit-identically. Both runs checkpoint every 3 steps so
    chunk boundaries (and therefore compiled programs) align."""
    trainer_a, pa, oa, labels = _build_trainer(
        checkpoint_dir=tmp_path / "a", checkpoint_every=3
    )
    seed_mat = trainer_a.pack_epoch(np.tile(np.arange(96), 6), seed=0)
    assert seed_mat.shape[0] == 9
    key = jax.random.PRNGKey(7)
    pa, oa, losses_a = trainer_a.epoch_scan(pa, oa, seed_mat, labels, key)
    losses_a = np.asarray(losses_a)

    trainer_b, pb, ob, _ = _build_trainer(
        checkpoint_dir=tmp_path / "b", checkpoint_every=3,
        plan=FaultPlan(preempt_at_step=4),
    )
    p0, o0 = pb, ob
    with pytest.raises(Preemption, match="step 4"):
        trainer_b.epoch_scan(pb, ob, seed_mat, labels, key)
    pr, orr, key_r, step, epoch = trainer_b.resume(p0, o0)
    assert step == 3 and epoch == 0  # chunk [3, 6) died un-checkpointed
    # seed-stream replay: same packed matrix, same key0, start at step 3
    pr, orr, losses_r = trainer_b.epoch_scan(
        pr, orr, seed_mat, labels, key_r, epoch=epoch, start_step=step
    )
    losses_r = np.asarray(losses_r)
    np.testing.assert_array_equal(
        losses_r.view(np.uint32), losses_a[step:].view(np.uint32)
    )
    assert _tree_bitwise_equal(pa, pr)
    # a finished epoch resumes to a no-op
    pr2, or2, key2, step2, _ = trainer_b.resume(p0, o0)
    assert step2 == seed_mat.shape[0]
    _, _, empty = trainer_b.epoch_scan(
        pr2, or2, seed_mat, labels, key2, start_step=step2
    )
    assert np.asarray(empty).shape == (0,)
    trainer_a.checkpointer.close()
    trainer_b.checkpointer.close()
