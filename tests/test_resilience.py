"""Resilience-layer tests: non-finite step guard, deterministic fault
injection, checkpoint/auto-resume, and the elastic layer (circuit
breaker, replan seams, cross-mesh resume).

Fast lane: FaultPlan semantics, guard skip/counter behavior over eager
steps, constructor validation, empty-checkpoint resume passthrough,
circuit-breaker state machine, degraded-feature fallback, replan shrink
math at F=8->4->2, and the elastic-resume validation errors.
Slow lane: the epoch-level differentials — guard on/off bit-parity with
zero faults, the preemption drill, and the cross-mesh elastic resume
(kill at F=8, resume(mesh=F4), remaining trajectory bitwise identical).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from quiver_tpu import CSRTopo, FaultPlan, GraphSageSampler, Preemption
from quiver_tpu.core.sharded_topology import ShardedTopology
from quiver_tpu.feature.shard import ShardedFeature
from quiver_tpu.models.sage import GraphSAGE
from quiver_tpu.obs.registry import GUARD_NONFINITE, GUARD_SKIPPED
from quiver_tpu.parallel.mesh import make_mesh
from quiver_tpu.parallel.trainer import DistributedTrainer
from quiver_tpu.resilience import (
    CircuitBreaker,
    DegradedFeature,
    TransientFault,
)
from quiver_tpu.resilience.guard import nonfinite_count


def _tree_bitwise_equal(a, b) -> bool:
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


def _build_trainer(guard=False, plan=None, checkpoint_dir=None,
                   checkpoint_every=0):
    rng = np.random.default_rng(0)
    n = 96
    topo = CSRTopo(
        edge_index=rng.integers(0, n, size=(2, 800)).astype(np.int64)
    )
    feat = rng.normal(size=(n, 8)).astype(np.float32)
    mesh = make_mesh(data=2, feature=4)
    store = ShardedFeature(
        mesh, device_cache_size=n * 8, csr_topo=topo
    ).from_cpu_tensor(feat)
    sampler = GraphSageSampler(topo, [3, 2], seed=0, seed_capacity=8)
    model = GraphSAGE(hidden=8, num_classes=4, num_layers=2)
    kw = {}
    if checkpoint_dir is not None:
        kw = dict(checkpoint_dir=checkpoint_dir,
                  checkpoint_every=checkpoint_every)
    trainer = DistributedTrainer(
        mesh, sampler, store, model, optax.sgd(1e-2), local_batch=8,
        seed_sharding="all", nonfinite_guard=guard, fault_plan=plan, **kw
    )
    params, opt = trainer.init(jax.random.PRNGKey(0))
    labels = jnp.asarray(rng.integers(0, 4, n).astype(np.int32))
    return trainer, params, opt, labels


# -- FaultPlan (host-side, no compile) ----------------------------------------


def test_fault_plan_masks_and_queries():
    plan = FaultPlan(nan_feature_steps=(1, 3), nan_rows=2,
                     preempt_at_step=5)
    assert plan.injects_nan() and plan.nan_at(3) and not plan.nan_at(2)
    np.testing.assert_array_equal(
        plan.nan_mask(5), [False, True, False, True, False]
    )
    assert plan.preempts_in(3, 6) and not plan.preempts_in(0, 5)
    assert not FaultPlan().injects_nan()
    assert not FaultPlan().preempts_in(0, 10**6)


def test_fault_plan_chaos_is_seed_deterministic():
    a = FaultPlan.chaos(seed=7, steps=50, nan_p=0.2, transient_p=0.3)
    b = FaultPlan.chaos(seed=7, steps=50, nan_p=0.2, transient_p=0.3)
    assert a == b
    c = FaultPlan.chaos(seed=8, steps=50, nan_p=0.2, transient_p=0.3)
    assert a != c
    assert a.nan_feature_steps  # p=0.2 over 50 steps: drew something


def test_fault_plan_validation():
    with pytest.raises(ValueError, match="nan_rows"):
        FaultPlan(nan_rows=0)
    with pytest.raises(ValueError, match="sampler_faults"):
        FaultPlan(sampler_faults={-1: 2})
    with pytest.raises(ValueError, match="feature_faults"):
        FaultPlan(feature_faults={0: 0})


def test_faulty_feature_injects_nan_and_faults():
    feat = np.ones((10, 4), np.float32)

    class Store:
        def __getitem__(self, ids):
            return feat[ids]

    plan = FaultPlan(feature_faults={1: 2}, nan_feature_steps=(1,),
                     nan_rows=2)
    wrapped = plan.wrap_feature(Store())
    ids = np.arange(3)
    assert np.isfinite(wrapped[ids]).all()  # lookup 0: clean
    for _ in range(2):  # lookups 1-2 planned transient failures
        with pytest.raises(TransientFault, match="feature"):
            wrapped[ids]
    rows = wrapped[ids]  # successful lookup #1: NaN-poisoned rows
    assert np.isnan(rows[:2]).all() and np.isfinite(rows[2:]).all()


def test_nonfinite_count_ignores_integer_leaves():
    tree = {
        "f": jnp.array([1.0, jnp.nan, jnp.inf]),
        "i": jnp.arange(3),
        "b": jnp.float32(0.0),
    }
    assert int(nonfinite_count(tree)) == 2


# -- non-finite step guard (eager steps; the fast-lane guard unit) ------------


def test_guard_skips_poisoned_step_and_counts():
    """Acceptance (fast half): with a NaN batch injected, params/opt_state
    after the poisoned step equal the ones before it bit-for-bit, the skip
    counter reads 1 (replicated — every chip agrees), and the next clean
    step trains normally."""
    plan = FaultPlan(nan_feature_steps=(1,), nan_rows=4)
    trainer, params, opt, labels = _build_trainer(guard=True, plan=plan)
    rng = np.random.default_rng(3)

    params, opt, loss0 = trainer.step(
        params, opt, rng.integers(0, 96, 64), labels, jax.random.PRNGKey(0)
    )
    assert np.isfinite(float(loss0))
    assert int(np.asarray(trainer.metrics.value(GUARD_SKIPPED))) == 0

    p_before, o_before = params, opt
    params, opt, loss1 = trainer.step(
        params, opt, rng.integers(0, 96, 64), labels, jax.random.PRNGKey(1)
    )
    # the poisoned step's loss is honestly NaN, but nothing was applied
    assert not np.isfinite(float(loss1))
    assert _tree_bitwise_equal(params, p_before)
    assert _tree_bitwise_equal(opt, o_before)
    assert int(np.asarray(trainer.metrics.value(GUARD_SKIPPED))) == 1
    assert int(np.asarray(trainer.metrics.value(GUARD_NONFINITE))) > 0

    params, opt, loss2 = trainer.step(
        params, opt, rng.integers(0, 96, 64), labels, jax.random.PRNGKey(2)
    )
    assert np.isfinite(float(loss2))
    assert not _tree_bitwise_equal(params, p_before)
    assert int(np.asarray(trainer.metrics.value(GUARD_SKIPPED))) == 0
    rep = trainer.metrics_report()
    assert GUARD_SKIPPED in rep and GUARD_NONFINITE in rep


def test_guard_off_registers_no_guard_metrics():
    trainer, *_ = _build_trainer(guard=False)
    assert GUARD_SKIPPED not in trainer.metrics.names()
    assert GUARD_NONFINITE not in trainer.metrics.names()


# -- checkpoint knobs ---------------------------------------------------------


def test_checkpoint_knob_validation(tmp_path):
    with pytest.raises(ValueError, match="checkpoint_every"):
        _build_trainer(checkpoint_dir=tmp_path / "ck", checkpoint_every=0)
    rng = np.random.default_rng(0)
    topo = CSRTopo(
        edge_index=rng.integers(0, 96, size=(2, 800)).astype(np.int64)
    )
    mesh = make_mesh(data=2, feature=4)
    store = ShardedFeature(mesh, device_cache_size=96 * 8).from_cpu_tensor(
        rng.normal(size=(96, 8)).astype(np.float32)
    )
    with pytest.raises(ValueError, match="nothing to write"):
        DistributedTrainer(
            mesh, GraphSageSampler(topo, [3], seed=0, seed_capacity=8),
            store, GraphSAGE(hidden=8, num_classes=4, num_layers=1),
            optax.sgd(1e-2), local_batch=8, seed_sharding="all",
            checkpoint_every=4,
        )


def test_resume_without_checkpointing_raises():
    trainer, params, opt, _ = _build_trainer()
    with pytest.raises(ValueError, match="resume"):
        trainer.resume(params, opt)


def test_resume_empty_directory_passes_through(tmp_path):
    trainer, params, opt, _ = _build_trainer(
        checkpoint_dir=tmp_path / "ck", checkpoint_every=2
    )
    p, o, key, step, epoch = trainer.resume(params, opt)
    assert step == 0 and epoch == 0 and key is None
    assert p is params and o is opt
    trainer.checkpointer.close()


# -- circuit breaker / degraded feature serving -------------------------------


def test_circuit_breaker_state_machine():
    """closed -> open after N consecutive failures -> count-based
    half-open probes; a failed probe reopens, a success closes."""
    br = CircuitBreaker(failures=2, probe_every=3)
    assert br.state == "closed" and br.allow()
    br.record_failure()
    assert br.state == "closed"  # under threshold: caller still sees it
    br.record_failure()
    assert br.state == "open"
    assert not br.allow() and not br.allow()  # short-circuited
    assert br.allow() and br.state == "half-open"  # 3rd call probes
    br.record_failure()
    assert br.state == "open"  # failed probe reopens
    assert not br.allow() and not br.allow()
    assert br.allow()
    br.record_success()
    assert br.state == "closed"
    # a success resets the consecutive count in closed state too
    br.record_failure()
    br.record_success()
    br.record_failure()
    assert br.state == "closed"
    with pytest.raises(ValueError, match="failures"):
        CircuitBreaker(failures=0)


class _FlakyStore:
    """ids -> rows store whose lookups fail while ``outage`` is set."""

    def __init__(self, feat):
        self.feat = feat
        self.shape = feat.shape
        self.dtype = feat.dtype
        self.outage = False

    def __getitem__(self, ids):
        if self.outage:
            raise TransientFault("cold tier down")
        return self.feat[np.clip(np.asarray(ids), 0, None)]


def test_degraded_feature_fallback_and_counter():
    feat = np.arange(40, dtype=np.float32).reshape(10, 4)
    store = _FlakyStore(feat)
    wrapped = DegradedFeature(store, failures=2, probe_every=2,
                              fallback="zeros")
    ids = np.arange(3)
    np.testing.assert_array_equal(wrapped[ids], feat[:3])  # healthy
    store.outage = True
    with pytest.raises(TransientFault):  # closed: failure 1 propagates
        wrapped[ids]
    rows = wrapped[ids]  # failure 2 opens the breaker -> fallback, no raise
    np.testing.assert_array_equal(rows, np.zeros((3, 4), np.float32))
    assert wrapped.breaker.state == "open"
    rows = wrapped[ids]  # short-circuited
    np.testing.assert_array_equal(rows, 0)
    store.outage = False
    np.testing.assert_array_equal(wrapped[ids], feat[:3])  # probe closes
    assert wrapped.breaker.state == "closed"
    assert wrapped.degraded_total == 2
    from quiver_tpu.obs.registry import DEGRADED_LOOKUPS

    assert int(np.asarray(wrapped.metrics.value(DEGRADED_LOOKUPS))) == 2


def test_degraded_feature_last_good_rows():
    feat = np.arange(40, dtype=np.float32).reshape(10, 4)
    store = _FlakyStore(feat)
    wrapped = DegradedFeature(store, failures=1, probe_every=100,
                              fallback="last-good")
    wrapped[np.array([2, 5])]  # caches rows 2 and 5
    store.outage = True
    rows = wrapped[np.array([5, 7, 2, -1])]  # opens on first failure
    np.testing.assert_array_equal(rows[0], feat[5])  # last-good
    np.testing.assert_array_equal(rows[1], 0)  # never seen -> zeros
    np.testing.assert_array_equal(rows[2], feat[2])
    np.testing.assert_array_equal(rows[3], 0)  # invalid lane -> zeros
    with pytest.raises(ValueError, match="fallback"):
        DegradedFeature(store, fallback="nonsense")


# -- elastic replan seams (shrink math; host-side, no compile) ----------------


def _line_topo(n=96, seed=0):
    rng = np.random.default_rng(seed)
    return CSRTopo(
        edge_index=rng.integers(0, n, size=(2, 800)).astype(np.int64)
    )


def test_sharded_topology_replan_shrink_math():
    """F=8 -> 4 -> 2: rows_per_shard doubles, the partition stays a full
    cover of the same graph, and per-chip bytes grow as shards widen."""
    topo = _line_topo()
    t8 = ShardedTopology(make_mesh(data=1, feature=8), topo)
    t4 = t8.replan(make_mesh(n_devices=4, data=1, feature=4))
    t2 = t4.replan(make_mesh(n_devices=2, data=1, feature=2))
    for t, f in ((t8, 8), (t4, 4), (t2, 2)):
        assert t.num_shards == f
        assert t.node_count == topo.node_count
        assert t.edge_count == int(topo.indptr[-1])
        assert t.rows_per_shard == -(-topo.node_count // f)
        assert sum(t.plan["shard_edges"]) == t.edge_count  # full cover
        assert np.asarray(t.indptr).shape == (f, t.rows_per_shard + 1)
    assert t2.rows_per_shard == 2 * t4.rows_per_shard == 4 * t8.rows_per_shard
    assert (t8.plan["per_chip_bytes"] < t4.plan["per_chip_bytes"]
            < t2.plan["per_chip_bytes"])
    assert t8.plan["shrink_factor"] > t4.plan["shrink_factor"] > 1.0


def test_sharded_feature_replan_preserves_rows():
    """F=8 -> 4 -> 2: the same per-device budget buys half the sharded
    rows each halving (spill to cold), but the translated row space and
    every row's bytes are reused verbatim — gathers stay bit-identical."""
    topo = _line_topo()
    n, d = 96, 8
    feat = np.random.default_rng(1).normal(size=(n, d)).astype(np.float32)
    row_bytes = d * 4
    store = ShardedFeature(
        make_mesh(data=1, feature=8), device_cache_size=6 * row_bytes,
        replicate_budget=8 * row_bytes, csr_topo=topo,
    ).from_cpu_tensor(feat)

    def reassemble(s):
        parts = []
        if s.rep is not None:
            parts.append(np.asarray(s.rep))
        if s.hot is not None:
            parts.append(np.asarray(s.hot.table)[: s.hot_rows])
        if s.cold is not None:
            parts.append(np.asarray(s.cold))
        return np.concatenate(parts)

    order = np.asarray(store.feature_order)
    baseline = reassemble(store)
    # the translated space IS the original rows, permuted by the order
    np.testing.assert_array_equal(baseline[order], feat)
    assert store.rep_rows == 8 and store.hot_rows == 6 * 8
    for f in (4, 2):
        store.replan(make_mesh(n_devices=f, data=1, feature=f))
        assert store.rep_rows == 8  # replication cost is per device
        assert store.hot_rows == 6 * f  # budget x fewer shards
        assert store.mesh.shape["feature"] == f
        np.testing.assert_array_equal(
            np.asarray(store.feature_order), order
        )
        np.testing.assert_array_equal(reassemble(store), baseline)


# -- elastic resume validation (fast; no step compiles) -----------------------


def _build_elastic(mesh, workers, checkpoint_dir=None, topo=None,
                   feat=None, plan=None):
    topo = _line_topo() if topo is None else topo
    n = topo.node_count
    if feat is None:
        feat = np.random.default_rng(1).normal(size=(n, 8)).astype(
            np.float32
        )
    store = ShardedFeature(
        mesh, device_cache_size=6 * 8 * 4, replicate_budget=8 * 8 * 4,
        csr_topo=topo,
    ).from_cpu_tensor(feat)
    sampler = GraphSageSampler(topo, [3, 2], seed=0, seed_capacity=8,
                               topo_sharding="mesh", mesh=mesh)
    model = GraphSAGE(hidden=8, num_classes=4, num_layers=2)
    kw = {}
    if checkpoint_dir is not None:
        kw = dict(checkpoint_dir=checkpoint_dir, checkpoint_every=3)
    return DistributedTrainer(
        mesh, sampler, store, model, optax.sgd(1e-2), local_batch=8,
        seed_sharding="all", logical_workers=workers, fault_plan=plan, **kw
    )


def test_logical_workers_validation():
    mesh = make_mesh(data=2, feature=4)
    with pytest.raises(ValueError, match="multiple"):
        _build_elastic(mesh, workers=12)  # not a multiple of 8
    with pytest.raises(ValueError, match="seed_sharding"):
        topo = _line_topo()
        store = ShardedFeature(mesh, device_cache_size=96 * 8 * 4)
        store = store.from_cpu_tensor(
            np.zeros((96, 8), np.float32)
        )
        DistributedTrainer(
            mesh, GraphSageSampler(topo, [3], seed=0, seed_capacity=8),
            store, GraphSAGE(hidden=8, num_classes=4, num_layers=1),
            optax.sgd(1e-2), local_batch=8, seed_sharding="data",
            logical_workers=8,
        )


def test_resume_mesh_mismatch_requires_elastic_opt_in(tmp_path):
    """Satellite: a checkpoint written on another mesh shape must not be
    device_put blindly — resume() raises unless resume(mesh=) opts in,
    and the metadata validation catches worker/step mismatches."""
    topo = _line_topo()
    mesh8 = make_mesh(data=1, feature=8)
    writer = _build_elastic(mesh8, workers=8, checkpoint_dir=tmp_path / "ck",
                            topo=topo)
    params, opt = writer.init(jax.random.PRNGKey(0))
    writer._save_checkpoint(params, opt, jax.random.PRNGKey(7), 0, 3,
                            steps_per_epoch=9)
    writer.checkpointer.close()

    mesh4 = make_mesh(n_devices=4, data=1, feature=4)
    # the real process-death flow: a FRESH trainer on the smaller mesh
    reader = _build_elastic(mesh4, workers=8,
                            checkpoint_dir=tmp_path / "ck", topo=topo)
    with pytest.raises(ValueError, match="resume\\(mesh="):
        reader.resume(params, opt)  # shape changed; no opt-in
    p, o, key, step, epoch = reader.resume(params, opt, mesh=reader.mesh)
    assert step == 3 and epoch == 0 and key is not None
    assert reader.blocks_per_device == 2

    # a wrong logical worker count is caught by the manifest metadata
    wrong = _build_elastic(mesh4, workers=4,
                           checkpoint_dir=tmp_path / "ck", topo=topo)
    with pytest.raises(ValueError, match="logical workers"):
        wrong.resume(params, opt, mesh=wrong.mesh)
    wrong.checkpointer.close()

    # a step outside the saved epoch's geometry is rejected
    writer2 = _build_elastic(mesh8, workers=8,
                             checkpoint_dir=tmp_path / "ck2", topo=topo)
    writer2._save_checkpoint(params, opt, jax.random.PRNGKey(7), 0, 99,
                             steps_per_epoch=9)
    writer2.checkpointer.wait_until_finished()
    with pytest.raises(ValueError, match="outside"):
        writer2.resume(params, opt)
    writer2.checkpointer.close()
    reader.checkpointer.close()


# -- epoch-level differentials (slow lane) ------------------------------------


@pytest.mark.slow
def test_guard_on_off_loss_bitwise_identical():
    """Acceptance: with the guard enabled and ZERO injected faults, the
    epoch_scan loss trajectory is bit-identical to the guard-off path —
    the verdict psum and cond ride alongside the training math, never
    inside it."""
    losses = {}
    for guard in (False, True):
        trainer, params, opt, labels = _build_trainer(guard=guard)
        seed_mat = trainer.pack_epoch(np.tile(np.arange(96), 4), seed=0)
        _, _, ls = trainer.epoch_scan(
            params, opt, seed_mat, labels, jax.random.PRNGKey(7)
        )
        losses[guard] = np.asarray(ls)
    np.testing.assert_array_equal(
        losses[True].view(np.uint32), losses[False].view(np.uint32)
    )


@pytest.mark.slow
def test_guarded_epoch_scan_skips_injected_nan_step():
    """A NaN batch inside the SCANNED epoch: the per-step skip vector
    marks exactly the poisoned step, and the final params equal those of
    a run over the same seeds with the poisoned step's update elided —
    i.e. the poison never touched the optimizer."""
    plan = FaultPlan(nan_feature_steps=(2,), nan_rows=4)
    trainer, params, opt, labels = _build_trainer(guard=True, plan=plan)
    seed_mat = trainer.pack_epoch(np.tile(np.arange(96), 4), seed=0)
    _, _, ls = trainer.epoch_scan(
        params, opt, seed_mat, labels, jax.random.PRNGKey(7)
    )
    skipped = np.asarray(trainer.metrics.value(GUARD_SKIPPED))
    assert skipped.shape == (seed_mat.shape[0],)
    expect = np.zeros(seed_mat.shape[0], np.int32)
    expect[2] = 1
    np.testing.assert_array_equal(skipped, expect)
    ls = np.asarray(ls)
    assert not np.isfinite(ls[2]) and np.isfinite(np.delete(ls, 2)).all()


@pytest.mark.slow
def test_preemption_drill_resume_bit_parity(tmp_path):
    """Acceptance e2e: crash at step k (FaultPlan preemption) + resume()
    reproduces the uninterrupted run's remaining loss trajectory and
    final params bit-identically. Both runs checkpoint every 3 steps so
    chunk boundaries (and therefore compiled programs) align."""
    trainer_a, pa, oa, labels = _build_trainer(
        checkpoint_dir=tmp_path / "a", checkpoint_every=3
    )
    seed_mat = trainer_a.pack_epoch(np.tile(np.arange(96), 6), seed=0)
    assert seed_mat.shape[0] == 9
    key = jax.random.PRNGKey(7)
    pa, oa, losses_a = trainer_a.epoch_scan(pa, oa, seed_mat, labels, key)
    losses_a = np.asarray(losses_a)

    trainer_b, pb, ob, _ = _build_trainer(
        checkpoint_dir=tmp_path / "b", checkpoint_every=3,
        plan=FaultPlan(preempt_at_step=4),
    )
    p0, o0 = pb, ob
    with pytest.raises(Preemption, match="step 4"):
        trainer_b.epoch_scan(pb, ob, seed_mat, labels, key)
    pr, orr, key_r, step, epoch = trainer_b.resume(p0, o0)
    assert step == 3 and epoch == 0  # chunk [3, 6) died un-checkpointed
    # seed-stream replay: same packed matrix, same key0, start at step 3
    pr, orr, losses_r = trainer_b.epoch_scan(
        pr, orr, seed_mat, labels, key_r, epoch=epoch, start_step=step
    )
    losses_r = np.asarray(losses_r)
    np.testing.assert_array_equal(
        losses_r.view(np.uint32), losses_a[step:].view(np.uint32)
    )
    assert _tree_bitwise_equal(pa, pr)
    # a finished epoch resumes to a no-op
    pr2, or2, key2, step2, _ = trainer_b.resume(p0, o0)
    assert step2 == seed_mat.shape[0]
    _, _, empty = trainer_b.epoch_scan(
        pr2, or2, seed_mat, labels, key2, start_step=step2
    )
    assert np.asarray(empty).shape == (0,)
    trainer_a.checkpointer.close()
    trainer_b.checkpointer.close()


@pytest.mark.slow
def test_elastic_resume_cross_mesh_bit_parity(tmp_path):
    """Acceptance e2e (the tentpole): checkpoint at step k on an F=8 mesh,
    kill, resume(mesh=F4) — the sharded topology and three-tier feature
    store re-plan onto half the devices, each device picks up two logical
    seed blocks, and the remaining loss trajectory AND final params are
    bit-identical to the uninterrupted F=8 run. A second resume onto F=2
    (quartered mesh) reproduces the same tail."""
    topo = _line_topo()
    labels = jnp.asarray(
        np.random.default_rng(0).integers(0, 4, topo.node_count).astype(
            np.int32
        )
    )
    mesh8 = make_mesh(data=1, feature=8)
    trainer_a = _build_elastic(mesh8, workers=8,
                               checkpoint_dir=tmp_path / "a", topo=topo)
    seed_mat = trainer_a.pack_epoch(np.tile(np.arange(96), 6), seed=0)
    assert seed_mat.shape[0] == 9
    key = jax.random.PRNGKey(7)
    pa, oa = trainer_a.init(jax.random.PRNGKey(0))
    pa, oa, losses_a = trainer_a.epoch_scan(pa, oa, seed_mat, labels, key)
    losses_a = np.asarray(losses_a)

    trainer_b = _build_elastic(mesh8, workers=8,
                               checkpoint_dir=tmp_path / "b", topo=topo,
                               plan=FaultPlan(preempt_at_step=4))
    p0, o0 = trainer_b.init(jax.random.PRNGKey(0))
    with pytest.raises(Preemption, match="step 4"):
        trainer_b.epoch_scan(p0, o0, seed_mat, labels, key)
    mesh4 = make_mesh(n_devices=4, data=1, feature=4)
    pr, orr, key_r, step, epoch = trainer_b.resume(p0, o0, mesh=mesh4)
    assert step == 3 and trainer_b.blocks_per_device == 2
    assert trainer_b.feature.mesh is mesh4
    assert trainer_b.sampler.topo.num_shards == 4
    pr, orr, losses_r = trainer_b.epoch_scan(
        pr, orr, seed_mat, labels, key_r, epoch=epoch, start_step=step
    )
    losses_r = np.asarray(losses_r)
    np.testing.assert_array_equal(
        losses_r.view(np.uint32), losses_a[step:].view(np.uint32)
    )
    assert _tree_bitwise_equal(pa, pr)

    # shrink AGAIN: F=4 -> F=2, pinning the ORIGINAL pre-kill checkpoint
    # (the resumed F=4 epoch checkpointed its own later chunks on top)
    mesh2 = make_mesh(n_devices=2, data=1, feature=2)
    first_seq = trainer_b.checkpointer.all_steps()[0]
    pr2, or2, key_r2, step2, epoch2 = trainer_b.resume(
        p0, o0, mesh=mesh2, checkpoint_step=first_seq
    )
    assert step2 == 3 and trainer_b.blocks_per_device == 4
    pr2, or2, losses_r2 = trainer_b.epoch_scan(
        pr2, or2, seed_mat, labels, key_r2, epoch=epoch2, start_step=step2
    )
    np.testing.assert_array_equal(
        np.asarray(losses_r2).view(np.uint32),
        losses_a[step2:].view(np.uint32),
    )
    assert _tree_bitwise_equal(pa, pr2)
    trainer_a.checkpointer.close()
    trainer_b.checkpointer.close()
