"""Checkpoint/resume tests: round-trip, retention, latest-step,
resume-training, and the integrity layer (manifest checksums, atomic
COMMIT, uncommitted-dir skipping, corrupt-checkpoint quarantine+fallback)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from quiver_tpu.resilience.integrity import FORMAT, CorruptCheckpoint
from quiver_tpu.utils.checkpoint import Checkpointer


def _flip_byte(path, where=0.5):
    """Flip one payload byte (the corrupt-checkpoint drill's fault)."""
    with open(path, "r+b") as fh:
        fh.seek(int(os.path.getsize(path) * where))
        b = fh.read(1)
        fh.seek(-1, os.SEEK_CUR)
        fh.write(bytes([b[0] ^ 0xFF]))


def _tree_equal(a, b):
    fa, ta = jax.tree_util.tree_flatten(a)
    fb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_save_restore_roundtrip(tmp_path):
    state = {
        "params": {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.zeros(3)},
        "step": jnp.int32(7),
    }
    with Checkpointer(tmp_path / "ck") as ckpt:
        ckpt.save(7, state, wait=True)
        _tree_equal(ckpt.restore(), state)
        _tree_equal(ckpt.restore(template=state), state)


def test_latest_and_retention(tmp_path):
    with Checkpointer(tmp_path / "ck", max_to_keep=2) as ckpt:
        for s in (1, 2, 3):
            ckpt.save(s, {"x": jnp.full(2, float(s))}, wait=True)
        assert ckpt.latest_step() == 3
        assert ckpt.all_steps() == [2, 3]
        _tree_equal(ckpt.restore(), {"x": jnp.full(2, 3.0)})
        _tree_equal(ckpt.restore(step=2), {"x": jnp.full(2, 2.0)})


def test_restore_empty_raises(tmp_path):
    with Checkpointer(tmp_path / "ck") as ckpt:
        with pytest.raises(FileNotFoundError):
            ckpt.restore()


def test_resume_training_continues_identically(tmp_path):
    """Save at step k, keep training; restore and retrain — same result."""
    tx = optax.adam(1e-2)
    params = {"w": jnp.ones((4, 4))}
    opt_state = tx.init(params)

    def step(params, opt_state, i):
        grads = jax.tree.map(lambda p: p * 0.01 * (i + 1), params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    for i in range(3):
        params, opt_state = step(params, opt_state, i)
    with Checkpointer(tmp_path / "ck") as ckpt:
        ckpt.save(3, {"params": params, "opt_state": opt_state}, wait=True)
        for i in range(3, 6):
            params, opt_state = step(params, opt_state, i)

        restored = ckpt.restore(
            template={"params": params, "opt_state": opt_state}
        )
        p2, o2 = restored["params"], restored["opt_state"]
        for i in range(3, 6):
            p2, o2 = step(p2, o2, i)
    _tree_equal(p2, params)


def test_save_rejection_surfaces_as_false(tmp_path, caplog):
    """orbax rejects a re-save of an already-checkpointed step: save()
    must return False (and log once) instead of silently dropping it."""
    import logging

    with Checkpointer(tmp_path / "ck") as ckpt:
        assert ckpt.save(5, {"x": jnp.zeros(2)}, wait=True) is True
        with caplog.at_level(logging.INFO, logger="quiver_tpu"):
            assert ckpt.save(5, {"x": jnp.ones(2)}, wait=True) is False
            assert ckpt.save(5, {"x": jnp.ones(2)}, wait=True) is False
        rejections = [r for r in caplog.records if "REJECTED" in r.message]
        assert len(rejections) == 1  # one-shot log
        _tree_equal(ckpt.restore(), {"x": jnp.zeros(2)})  # original stands


def test_close_waits_for_inflight_async_save(tmp_path):
    """close() must flush the pending async save — a reopened manager sees
    the step that was still committing at close time."""
    ckpt = Checkpointer(tmp_path / "ck")
    ckpt.save(1, {"x": jnp.full(3, 7.0)})  # async, no wait
    ckpt.close()
    with Checkpointer(tmp_path / "ck") as reopened:
        assert reopened.latest_step() == 1
        _tree_equal(
            reopened.restore(template={"x": jnp.zeros(3)}),
            {"x": jnp.full(3, 7.0)},
        )


# -- integrity: manifest, atomic commit, quarantine + fallback ----------------


def test_manifest_roundtrip_and_verify(tmp_path):
    """The manifest is mesh-agnostic and complete: per-leaf key path,
    GLOBAL shape, dtype, content checksum, plus writer metadata — and a
    committed save passes full verification."""
    state = {
        "params": {"w": jnp.arange(6.0).reshape(2, 3)},
        "step": np.asarray(7, np.int32),
        "opt": (jnp.zeros(2), jnp.ones(2)),  # tuple survives untemplated
    }
    with Checkpointer(tmp_path / "ck") as ckpt:
        assert ckpt.save(7, state, wait=True,
                         metadata={"workers": 8, "local_batch": 16})
        manifest = ckpt.verify(7)
        assert manifest["format"] == FORMAT and manifest["step"] == 7
        by_path = {rec["path"]: rec for rec in manifest["leaves"]}
        w = by_path["['params']['w']"]
        assert w["shape"] == [2, 3] and w["dtype"] == "float32"
        assert by_path["['step']"]["shape"] == []  # 0-d stays 0-d
        assert ckpt.metadata(7) == {"workers": 8, "local_batch": 16}
        restored = ckpt.restore()
        assert isinstance(restored["opt"], tuple)
        _tree_equal(restored, state)


def test_uncommitted_partial_directory_is_invisible(tmp_path):
    """A crash mid-save leaves a directory without the COMMIT marker —
    latest_step/all_steps/restore must never see it."""
    with Checkpointer(tmp_path / "ck") as ckpt:
        ckpt.save(1, {"x": jnp.full(2, 1.0)}, wait=True)
        partial = tmp_path / "ck" / "step-9"
        partial.mkdir()
        (partial / "arrays.bin").write_bytes(b"\x00" * 16)  # no COMMIT
        assert ckpt.latest_step() == 1
        assert ckpt.all_steps() == [1]
        _tree_equal(ckpt.restore(), {"x": jnp.full(2, 1.0)})


def test_corrupt_newest_quarantines_and_falls_back(tmp_path, caplog):
    """Acceptance: flipped manifest-covered bytes in the newest checkpoint
    -> one-shot log, quarantine rename, automatic fallback to the newest
    VALID checkpoint — no manual intervention, no garbage restore."""
    import logging

    with Checkpointer(tmp_path / "ck") as ckpt:
        ckpt.save(1, {"x": jnp.full(2, 1.0)}, wait=True)
        ckpt.save(2, {"x": jnp.full(2, 2.0)}, wait=True)
        _flip_byte(tmp_path / "ck" / "step-2" / "arrays.bin")
        with caplog.at_level(logging.INFO, logger="quiver_tpu"):
            _tree_equal(
                ckpt.restore(template={"x": jnp.zeros(2)}),
                {"x": jnp.full(2, 1.0)},
            )
        assert any("quarantined" in r.message for r in caplog.records)
        assert ckpt.all_steps() == [1]  # the corrupt dir left the scan
        assert any(
            name.startswith("quarantine-")
            for name in os.listdir(tmp_path / "ck")
        )


def test_explicit_corrupt_step_raises(tmp_path):
    """An explicitly-pinned step that fails verification raises instead
    of silently serving a different step."""
    with Checkpointer(tmp_path / "ck") as ckpt:
        ckpt.save(1, {"x": jnp.zeros(2)}, wait=True)
        ckpt.save(2, {"x": jnp.ones(2)}, wait=True)
        _flip_byte(tmp_path / "ck" / "step-2" / "arrays.bin")
        with pytest.raises(CorruptCheckpoint, match="checksum"):
            ckpt.restore(step=2)


def test_integrity_enforces_retention_floor(tmp_path):
    """checkpoint_keep >= 2 while integrity is on: a window of one leaves
    nothing to fall back to."""
    with pytest.raises(ValueError, match="max_to_keep"):
        Checkpointer(tmp_path / "ck", max_to_keep=1)
    # opting out of integrity opts out of the floor
    Checkpointer(tmp_path / "ck2", max_to_keep=1, integrity=False).close()


def test_template_mismatch_raises(tmp_path):
    with Checkpointer(tmp_path / "ck") as ckpt:
        ckpt.save(1, {"x": jnp.zeros(2)}, wait=True)
        with pytest.raises(ValueError, match="template"):
            ckpt.restore(template={"x": jnp.zeros(3)})
