"""Checkpoint/resume tests: round-trip, retention, latest-step, resume-training."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from quiver_tpu.utils.checkpoint import Checkpointer


def _tree_equal(a, b):
    fa, ta = jax.tree_util.tree_flatten(a)
    fb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_save_restore_roundtrip(tmp_path):
    state = {
        "params": {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.zeros(3)},
        "step": jnp.int32(7),
    }
    with Checkpointer(tmp_path / "ck") as ckpt:
        ckpt.save(7, state, wait=True)
        _tree_equal(ckpt.restore(), state)
        _tree_equal(ckpt.restore(template=state), state)


def test_latest_and_retention(tmp_path):
    with Checkpointer(tmp_path / "ck", max_to_keep=2) as ckpt:
        for s in (1, 2, 3):
            ckpt.save(s, {"x": jnp.full(2, float(s))}, wait=True)
        assert ckpt.latest_step() == 3
        assert ckpt.all_steps() == [2, 3]
        _tree_equal(ckpt.restore(), {"x": jnp.full(2, 3.0)})
        _tree_equal(ckpt.restore(step=2), {"x": jnp.full(2, 2.0)})


def test_restore_empty_raises(tmp_path):
    with Checkpointer(tmp_path / "ck") as ckpt:
        with pytest.raises(FileNotFoundError):
            ckpt.restore()


def test_resume_training_continues_identically(tmp_path):
    """Save at step k, keep training; restore and retrain — same result."""
    tx = optax.adam(1e-2)
    params = {"w": jnp.ones((4, 4))}
    opt_state = tx.init(params)

    def step(params, opt_state, i):
        grads = jax.tree.map(lambda p: p * 0.01 * (i + 1), params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    for i in range(3):
        params, opt_state = step(params, opt_state, i)
    with Checkpointer(tmp_path / "ck") as ckpt:
        ckpt.save(3, {"params": params, "opt_state": opt_state}, wait=True)
        for i in range(3, 6):
            params, opt_state = step(params, opt_state, i)

        restored = ckpt.restore(
            template={"params": params, "opt_state": opt_state}
        )
        p2, o2 = restored["params"], restored["opt_state"]
        for i in range(3, 6):
            p2, o2 = step(p2, o2, i)
    _tree_equal(p2, params)


def test_save_rejection_surfaces_as_false(tmp_path, caplog):
    """orbax rejects a re-save of an already-checkpointed step: save()
    must return False (and log once) instead of silently dropping it."""
    import logging

    with Checkpointer(tmp_path / "ck") as ckpt:
        assert ckpt.save(5, {"x": jnp.zeros(2)}, wait=True) is True
        with caplog.at_level(logging.INFO, logger="quiver_tpu"):
            assert ckpt.save(5, {"x": jnp.ones(2)}, wait=True) is False
            assert ckpt.save(5, {"x": jnp.ones(2)}, wait=True) is False
        rejections = [r for r in caplog.records if "REJECTED" in r.message]
        assert len(rejections) == 1  # one-shot log
        _tree_equal(ckpt.restore(), {"x": jnp.zeros(2)})  # original stands


def test_close_waits_for_inflight_async_save(tmp_path):
    """close() must flush the pending async save — a reopened manager sees
    the step that was still committing at close time."""
    ckpt = Checkpointer(tmp_path / "ck")
    ckpt.save(1, {"x": jnp.full(3, 7.0)})  # async, no wait
    ckpt.close()
    with Checkpointer(tmp_path / "ck") as reopened:
        assert reopened.latest_step() == 1
        # template restore: a freshly-opened manager has no handler
        # registry yet, so an untemplated restore cannot infer the tree
        _tree_equal(
            reopened.restore(template={"x": jnp.zeros(3)}),
            {"x": jnp.full(3, 7.0)},
        )
