"""Worker process for the 2-host jax.distributed test (test_multihost.py).

Each worker joins the job via quiver_tpu.parallel.mesh.init_distributed
(VERDICT r2 item 6 — previously an untested wrapper), then proves:

1. the job formed: process_count == N, global device count == 4*N;
2. the CSR builder's cross-host determinism claim
   (native/quiver_host.cpp — stable counting-sort scatter): independent
   builds of the same COO on each host hash byte-identical, verified by
   allgathering the digests;
3. a real cross-process collective works: a jitted global-mesh reduction
   over an array sharded across both processes' devices.

Prints ONE JSON line with the results; exit 0 iff all checks pass.
"""

import hashlib
import json
import os
import sys


def main():
    pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4"
        ).strip()
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from quiver_tpu.parallel.mesh import init_distributed

    init_distributed(f"localhost:{port}", nprocs, pid)
    assert jax.process_count() == nprocs, jax.process_count()
    assert len(jax.devices()) == 4 * nprocs, len(jax.devices())
    assert len(jax.local_devices()) == 4

    # -- cross-host deterministic CSR build --------------------------------
    from quiver_tpu import CSRTopo
    from quiver_tpu.utils.graphgen import generate_pareto_graph

    ei = generate_pareto_graph(5000, 8.0, seed=7)
    topo = CSRTopo(edge_index=ei)
    h = hashlib.sha256()
    for arr in (topo.indptr, topo.indices, topo.eid):
        h.update(np.ascontiguousarray(arr).tobytes())
    # ship the digest as uint32 words: jax default-32-bit silently truncates
    # uint64 payloads in the allgather
    digest_words = np.frombuffer(h.digest(), dtype=np.uint32)

    from jax.experimental import multihost_utils

    gathered = np.asarray(
        multihost_utils.process_allgather(digest_words)
    ).reshape(nprocs, -1)
    ok_csr = bool((gathered == digest_words[None, :]).all())

    # -- cross-process sharded reduction -----------------------------------
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from quiver_tpu.parallel.mesh import make_mesh

    mesh = make_mesh()  # (data=4*nprocs, feature=1) spanning both processes
    n = 4 * nprocs
    data = np.arange(n, dtype=np.float32)
    sharding = NamedSharding(mesh, P("data"))
    x = jax.make_array_from_callback((n,), sharding, lambda idx: data[idx])
    total = jax.jit(
        jnp.sum, out_shardings=NamedSharding(mesh, P())
    )(x)
    ok_sum = float(total) == float(data.sum())

    print(json.dumps({
        "pid": pid,
        "ok_csr": ok_csr,
        "ok_sum": ok_sum,
        "process_count": jax.process_count(),
        "global_devices": len(jax.devices()),
    }))
    sys.exit(0 if (ok_csr and ok_sum) else 1)


if __name__ == "__main__":
    main()
