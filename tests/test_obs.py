"""graftscope (quiver_tpu/obs) subsystem tests.

Covers: the MetricsRegistry/MetricsTape discipline (registration, tape
feeding through shard_map with per-metric psum placement, the
enabled/disabled program-level switch), the StepTimeline's streaming P²
percentiles and stage timing, Timer's registry hookup, both exporters'
round trips (JSONL and Prometheus exposition, including epoch_scan-shaped
``(steps, k)`` metrics), profile_epoch bracketing, and the acceptance
differential: metrics collection disabled vs enabled yields a bit-identical
loss trajectory over an ``epoch_scan`` epoch.
"""

import io

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from quiver_tpu.obs import (
    MetricSnapshot,
    MetricsRegistry,
    P2Quantile,
    StepTimeline,
    from_prometheus,
    profile_epoch,
    read_jsonl,
    to_prometheus,
    write_jsonl,
)
from quiver_tpu.parallel.mesh import DATA_AXIS, FEATURE_AXIS, make_mesh, shard_map
from quiver_tpu.utils import trace


# -- registry -----------------------------------------------------------------


def test_registry_register_and_record():
    reg = MetricsRegistry()
    reg.counter("a.count", doc="a counter")
    reg.gauge("b.vec", shape=(3,), doc="a gauge")
    reg.record({"a.count": jnp.int32(4), "b.vec": jnp.arange(3, dtype=jnp.int32)})
    assert int(reg.value("a.count")) == 4
    snap = reg.snapshot("b.vec")
    assert snap.kind == "gauge" and snap.steps is None
    assert snap.numpy.tolist() == [0, 1, 2]
    # epoch_scan-stacked values are detected by shape against the spec
    reg.record({"b.vec": jnp.ones((5, 3), jnp.int32)})
    assert reg.snapshot("b.vec").steps == 5
    reg.set("a.count", None)  # clear
    assert reg.value("a.count") is None
    assert [s.name for s in reg.snapshots()] == ["b.vec"]


def test_registry_spec_conflicts_and_unknown():
    reg = MetricsRegistry()
    reg.counter("x")
    reg.counter("x")  # idempotent re-register is fine
    with pytest.raises(ValueError, match="different spec"):
        reg.gauge("x")
    with pytest.raises(KeyError, match="not registered"):
        reg.spec("nope")
    tape = reg.tape()
    with pytest.raises(ValueError, match="is a counter"):
        tape.set("x", jnp.int32(1))


def test_tape_through_shard_map_psum():
    """The tape's metrics pytree rides shard_map out and psums once at the
    declared axes — the generalized last_routed_overflow discipline."""
    mesh = make_mesh(data=2, feature=4)
    reg = MetricsRegistry()
    reg.counter("ov", doc="per-device overflow, mesh-summed")
    reg.gauge("hits", shape=(2,))

    def body(x):
        tape = reg.tape()
        tape.add("ov", jnp.sum(x).astype(jnp.int32),
                 psum=(DATA_AXIS, FEATURE_AXIS))
        tape.set("hits", jnp.stack([jnp.sum(x), jnp.sum(x)]).astype(jnp.int32),
                 psum=DATA_AXIS)
        return tape.finalize()

    f = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P((DATA_AXIS, FEATURE_AXIS)),),
        out_specs={"ov": P(), "hits": P()}, check_vma=False,
    ))
    out = f(jnp.ones(16, jnp.int32))
    reg.record(out)
    assert int(reg.value("ov")) == 16  # all 8 devices' lanes, mesh total
    # hits psum'd over data only: 2 data groups x 2 lanes each... each
    # device holds 2 lanes -> per-device sum 2, data-psum = 4
    assert reg.value("hits").tolist() == [4, 4]


def test_tape_disabled_is_program_level_noop():
    reg = MetricsRegistry(enabled=False)
    reg.counter("ov")
    tape = reg.tape()
    tape.add("ov", jnp.int32(3))
    assert tape.finalize() == {}
    reg.record({})
    assert reg.value("ov") is None


def test_bucket_route_feeds_tape():
    """BucketRoute(tape=...) lands its overflow count on the tape — the
    shared comm core reports through the same registry discipline."""
    from quiver_tpu.parallel.routing import BucketRoute

    mesh = make_mesh(data=1, feature=8)
    reg = MetricsRegistry()
    reg.counter("route.ov")
    L, F = 16, 8

    def body(ids):
        tape = reg.tape()
        route = BucketRoute(
            ids, ids >= 0, ids, axis=FEATURE_AXIS, num_shards=F, cap=1,
            tape=tape, metric="route.ov",
        )
        rows = route.exchange(
            lambda req: jnp.where(req >= 0, req, 0).astype(jnp.int32)
        )
        return rows, tape.finalize()

    f = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P(FEATURE_AXIS),),
        out_specs=(P(FEATURE_AXIS), {"route.ov": P()}), check_vma=False,
    ))
    # every lane owned by shard 0 -> cap=1 buckets overflow heavily
    ids = jnp.zeros(F * L, jnp.int32)
    _, mtree = f(ids)
    reg.record(mtree)
    assert int(reg.value("route.ov")) == F * (L - 1)


# -- timeline -----------------------------------------------------------------


def test_p2_quantile_tracks_percentiles():
    rng = np.random.default_rng(0)
    xs = rng.uniform(0.0, 1.0, 5000)
    est = P2Quantile(0.95)
    for x in xs:
        est.update(float(x))
    assert est.count == 5000
    assert abs(est.value - np.percentile(xs, 95)) < 0.02


def test_p2_quantile_small_samples_exact():
    est = P2Quantile(0.5)
    for x in (3.0, 1.0, 2.0):
        est.update(x)
    assert est.value == 2.0


def test_p2_quantile_tiny_n_exact_nearest_rank():
    """Below the 5-marker warmup the estimator must return the exact
    nearest-rank order statistic (ceil(q*n), 1-based) — not an
    interpolated pick that undersells tail quantiles."""
    # n=1: every quantile IS the single sample
    for q in (0.01, 0.5, 0.99):
        est = P2Quantile(q)
        est.update(7.0)
        assert est.value == 7.0
    # n=2: p99 must be the max, p50 the lower sample (ceil(.5*2)=1)
    hi = P2Quantile(0.99)
    lo = P2Quantile(0.5)
    for x in (1.0, 2.0):
        hi.update(x)
        lo.update(x)
    assert hi.value == 2.0
    assert lo.value == 1.0
    # n=4: p50 -> 2nd order stat, p95 -> 4th
    med, tail = P2Quantile(0.5), P2Quantile(0.95)
    for x in (40.0, 10.0, 30.0, 20.0):
        med.update(x)
        tail.update(x)
    assert med.value == 20.0
    assert tail.value == 40.0


def test_p2_quantile_large_n_accuracy():
    rng = np.random.default_rng(7)
    xs = rng.normal(5.0, 2.0, 1000)
    for q in (0.5, 0.95, 0.99):
        est = P2Quantile(q)
        for x in xs:
            est.update(float(x))
        exact = float(np.percentile(xs, 100 * q))
        assert abs(est.value - exact) < 0.25, (q, est.value, exact)


def test_timeline_stage_and_report():
    tl = StepTimeline()
    for i in range(20):
        tl.observe("sample", 0.001 * (i + 1))
    with tl.stage("gather", sync=jnp.ones(8)):
        pass
    st = tl.stats("sample")
    assert st.count == 20
    assert st.max == pytest.approx(0.020)
    assert tl.stats("gather").count == 1
    rep = tl.report()
    assert "sample" in rep and "gather" in rep and "p95" in rep
    d = st.as_dict()
    assert d["count"] == 20 and d["p50_ms"] is not None


def test_timer_feeds_timeline():
    tl = StepTimeline()
    with trace.Timer("sample", quiet=True, registry=tl):
        pass
    with trace.Timer("sample", quiet=True, registry=tl, metric="renamed"):
        pass
    assert tl.stats("sample").count == 1
    assert tl.stats("renamed").count == 1
    assert tl.stats("sample").total >= 0.0


# -- exporters ----------------------------------------------------------------


def _sample_snapshots():
    return [
        MetricSnapshot("feature.routed_overflow", "counter",
                       np.int32(7), None, "lanes", "fallback lanes"),
        MetricSnapshot("feature.tier_hits", "gauge",
                       np.arange(12, dtype=np.int32).reshape(4, 3), 4,
                       "hits", "per-tier hits"),
        MetricSnapshot("loss.gauge", "gauge",
                       np.asarray([0.5, 0.25], np.float32), 2),
    ]


def _assert_same(a: MetricSnapshot, b: MetricSnapshot):
    assert a.name == b.name and a.kind == b.kind and a.steps == b.steps
    assert a.numpy.shape == b.numpy.shape
    assert a.numpy.dtype == b.numpy.dtype
    np.testing.assert_array_equal(a.numpy, b.numpy)


def test_jsonl_round_trip():
    snaps = _sample_snapshots()
    buf = io.StringIO()
    assert write_jsonl(snaps, buf, extra={"job": "t"}) == 3
    back = read_jsonl(buf.getvalue())
    assert len(back) == 3
    for a, b in zip(snaps, back):
        _assert_same(a, b)


def test_jsonl_file_round_trip(tmp_path):
    path = tmp_path / "metrics.jsonl"
    write_jsonl(_sample_snapshots(), str(path))
    write_jsonl(_sample_snapshots()[:1], str(path))  # append mode
    back = read_jsonl(str(path))
    assert len(back) == 4
    _assert_same(_sample_snapshots()[1], back[1])


def test_prometheus_round_trip():
    snaps = _sample_snapshots()
    text = to_prometheus(snaps)
    # scrapable exposition shape: HELP/TYPE lines + labeled samples
    assert "# TYPE quiver_feature_tier_hits gauge" in text
    assert "# HELP quiver_feature_tier_hits" in text
    assert ('quiver_feature_tier_hits'
            '{name="feature.tier_hits",idx="3,2"} 11') in text
    assert "# TYPE quiver_feature_routed_overflow counter" in text
    back = from_prometheus(text)
    assert len(back) == 3
    for a, b in zip(snaps, back):
        _assert_same(a, b)


def test_prometheus_hostile_names_round_trip():
    """Label-injection hygiene: names containing backslash, quote and
    newline survive the exposition round trip; distinct dotted names that
    sanitize to the same exposition name get numeric suffixes instead of
    silently merging; a hostile name cannot spoof the idx label."""
    snaps = [
        MetricSnapshot('evil\\name."quoted"\nline', "counter",
                       np.int32(3), None, "", 'doc with "quotes"\nand line'),
        # idx-spoof attempt: name label ends with what looks like idx=
        MetricSnapshot('spoof",idx="9,9', "gauge",
                       np.asarray([1.0, 2.0], np.float32), None),
        # collision pair: both sanitize to quiver_a_b
        MetricSnapshot("a.b", "counter", np.int32(1), None),
        MetricSnapshot("a_b", "counter", np.int32(2), None),
    ]
    text = to_prometheus(snaps)
    # every sample line stays one line (no raw newline broke out)
    for line in text.splitlines():
        assert line.startswith("#") or " " in line
    assert "quiver_a_b_2" in text  # collision got a suffix, not a merge
    back = from_prometheus(text)
    assert len(back) == 4
    for a, b in zip(snaps, back):
        _assert_same(a, b)
    # the spoofed gauge kept its true shape — idx wasn't hijacked
    assert back[1].numpy.shape == (2,)
    np.testing.assert_array_equal(back[1].numpy, [1.0, 2.0])


def test_exporters_agree_on_registry_output():
    """JSONL and Prometheus round trips reproduce the SAME values for a
    registry recording of an epoch_scan-shaped (steps, k) metric."""
    reg = MetricsRegistry()
    reg.counter("sample.hop_overflow", shape=(2,))
    reg.record({"sample.hop_overflow": jnp.asarray(
        [[1, 2], [3, 4], [5, 6]], jnp.int32)})
    snaps = reg.snapshots()
    assert snaps[0].steps == 3
    via_jsonl = read_jsonl(
        (lambda b: (write_jsonl(snaps, b), b.getvalue())[1])(io.StringIO())
    )
    via_prom = from_prometheus(to_prometheus(snaps))
    _assert_same(via_jsonl[0], via_prom[0])
    np.testing.assert_array_equal(
        via_jsonl[0].numpy, np.asarray([[1, 2], [3, 4], [5, 6]])
    )


def test_ledger_metrics_artifact(tmp_path, monkeypatch):
    """benchmarks.ledger append_metrics/read_metrics honor the env-pointed
    artifact path and round-trip snapshots."""
    from benchmarks import ledger

    path = tmp_path / "metrics.jsonl"
    monkeypatch.setenv("QUIVER_METRICS_JSONL", str(path))
    n = ledger.append_metrics(_sample_snapshots(), extra={"lane": "t"})
    assert n == 3 and path.exists()
    back = ledger.read_metrics()
    assert len(back) == 3
    monkeypatch.setenv("QUIVER_METRICS_JSONL", "")
    assert ledger.append_metrics(_sample_snapshots()) == 0  # disabled


# -- profiler bracketing ------------------------------------------------------


@pytest.mark.slow  # 15s profiled epoch
def test_profile_epoch_brackets_and_restores(tmp_path):
    prev = trace._enabled
    trace.disable_trace()
    with profile_epoch(str(tmp_path / "prof")):
        assert trace.trace_enabled()  # stage scopes annotate the capture
        jnp.arange(4).block_until_ready()
    assert not trace.trace_enabled()  # prior state restored
    trace._enabled = prev


# -- acceptance differential --------------------------------------------------


def _tiny_trainer(collect_metrics: bool):
    import optax

    from quiver_tpu import (
        CSRTopo,
        DistributedTrainer,
        GraphSageSampler,
        ShardedFeature,
    )
    from quiver_tpu.models.sage import GraphSAGE

    rng = np.random.default_rng(0)
    n = 96
    ei = rng.integers(0, n, size=(2, 800)).astype(np.int64)
    topo = CSRTopo(edge_index=ei)
    feat = rng.normal(size=(n, 8)).astype(np.float32)
    mesh = make_mesh(data=2, feature=4)
    store = ShardedFeature(
        mesh, device_cache_size=n * 8, csr_topo=topo
    ).from_cpu_tensor(feat)
    sampler = GraphSageSampler(topo, [3, 2], seed=0, seed_capacity=8)
    model = GraphSAGE(hidden=8, num_classes=4, num_layers=2)
    trainer = DistributedTrainer(
        mesh, sampler, store, model, optax.sgd(1e-2), local_batch=8,
        seed_sharding="all", collect_metrics=collect_metrics,
    )
    params, opt = trainer.init(jax.random.PRNGKey(0))
    labels = jnp.asarray(rng.integers(0, 4, n).astype(np.int32))
    return trainer, params, opt, labels


@pytest.mark.slow  # IR-proven fast: graftaudit's metrics-strip rule
# diffs the lowered on/off step programs every tier-1 run — identical
# data-movement collectives, exactly the declared metric psums stripped
# (tests/test_audit.py); this execution differential is the slow-lane
# end-to-end witness
def test_metrics_on_off_loss_bitwise_identical():
    """Acceptance: metrics collection disabled vs enabled yields a
    bit-identical loss trajectory over an epoch_scan epoch (the metric
    psums ride alongside the training math, never inside it)."""
    losses = {}
    for collect in (True, False):
        trainer, params, opt, labels = _tiny_trainer(collect)
        seed_mat = trainer.pack_epoch(np.arange(96), seed=0)
        _, _, ls = trainer.epoch_scan(
            params, opt, seed_mat, labels, jax.random.PRNGKey(7)
        )
        losses[collect] = np.asarray(ls)
        if collect:
            # telemetry present: per-step vectors in the registry views
            assert trainer.last_routed_overflow is not None
            assert np.asarray(trainer.last_tier_hits).shape == (
                seed_mat.shape[0], 3)
            rep = trainer.metrics_report()
            assert "feature.tier_hits" in rep and "timeline:" in rep
        else:
            assert trainer.last_routed_overflow is None
            assert trainer.last_tier_hits is None
            assert "collect_metrics=False" in trainer.metrics_report()
    assert losses[True].dtype == losses[False].dtype
    np.testing.assert_array_equal(
        losses[True].view(np.uint32), losses[False].view(np.uint32)
    )


def test_step_metrics_match_legacy_views():
    """One eager step: the registry snapshots ARE the legacy attributes
    (thin views), and the store receives the batch's tier hits."""
    from quiver_tpu.obs.registry import ROUTED_OVERFLOW, TIER_HITS

    trainer, params, opt, labels = _tiny_trainer(True)
    rng = np.random.default_rng(3)
    trainer.step(params, opt, rng.integers(0, 96, 32), labels,
                 jax.random.PRNGKey(1))
    assert int(np.asarray(trainer.last_routed_overflow)) == int(
        np.asarray(trainer.metrics.value(ROUTED_OVERFLOW)))
    np.testing.assert_array_equal(
        np.asarray(trainer.last_tier_hits),
        np.asarray(trainer.metrics.value(TIER_HITS)))
    # the store's own registry saw the fused batch totals
    np.testing.assert_array_equal(
        np.asarray(trainer.feature.last_tier_hits),
        np.asarray(trainer.last_tier_hits))
    assert trainer.timeline.stats("step").count == 1
