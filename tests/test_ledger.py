"""TPU-evidence ledger: durable records, stale re-emission (VERDICT r3 #1).

The contract under test: a successful ``platform: tpu`` record written once
can never be erased by a later dead tunnel — ``bench.py`` re-emits it,
labeled stale, whenever a fresh attempt degrades.
"""

import importlib
import json
import os

import pytest

from benchmarks import ledger


@pytest.fixture()
def tmp_ledger(tmp_path, monkeypatch):
    p = tmp_path / "tpu_ledger.jsonl"
    monkeypatch.setenv("QUIVER_TPU_LEDGER", str(p))
    return p


TPU_REC = {
    "metric": "sampled-edges/sec/chip", "value": 12.0e6, "unit": "SEPS",
    "vs_baseline": 0.35, "platform": "tpu", "dispatch": "stream",
    "nodes": 2_450_000,
}


def test_append_accepts_only_clean_tpu_records(tmp_ledger):
    assert not ledger.append({**TPU_REC, "platform": "cpu"})
    assert not ledger.append({**TPU_REC, "degraded": "fallback"})
    assert not ledger.append({**TPU_REC, "stale": "2026-01-01T00:00:00Z"})
    assert not tmp_ledger.exists()

    assert ledger.append(TPU_REC)
    rows = [json.loads(x) for x in tmp_ledger.read_text().splitlines()]
    assert len(rows) == 1
    assert rows[0]["value"] == 12.0e6
    assert "ts" in rows[0]  # stamped at append time


def test_last_good_returns_newest_matching(tmp_ledger):
    assert ledger.last_good("sampled-edges/sec/chip") is None
    ledger.append(TPU_REC)
    ledger.append({**TPU_REC, "value": 15.0e6})
    ledger.append({**TPU_REC, "metric": "feature-gather", "unit": "GB/s",
                   "value": 3.0})
    got = ledger.last_good("sampled-edges/sec/chip")
    assert got["value"] == 15.0e6
    # field filters narrow the match
    assert ledger.last_good("sampled-edges/sec/chip",
                            dispatch="percall") is None


def test_best_good_selection(tmp_ledger):
    # a --dedup both run ledgers the winner FIRST, the loser LAST (sorted
    # reverse emit order); best-by-value must resurface the winner
    ledger.append({**TPU_REC, "value": 9.7e6, "dedup": "map"})
    ledger.append({**TPU_REC, "value": 7.1e6, "dedup": "sort"})
    # smoke sanity rows and sub-scale graphs never become the headline
    ledger.append({**TPU_REC, "value": 50.0e6, "smoke": True})
    ledger.append({**TPU_REC, "value": 60.0e6, "nodes": 200_000})
    got = ledger.best_good("sampled-edges/sec/chip", min_nodes=2_000_000,
                           dispatch="stream")
    assert got["value"] == 9.7e6 and got["dedup"] == "map"
    # rows without a nodes stamp are rejected under min_nodes
    bare = {k: v for k, v in TPU_REC.items() if k != "nodes"}
    ledger.append({**bare, "value": 80.0e6})
    got = ledger.best_good("sampled-edges/sec/chip", min_nodes=2_000_000)
    assert got["value"] == 9.7e6


def test_bench_stale_reemission(tmp_ledger):
    ledger.append(TPU_REC)
    # a later per-call record must NOT displace the stream headline: the
    # headline methodology is fused-stream dispatch
    ledger.append({**TPU_REC, "value": 99.0e6, "dispatch": "percall"})
    bench = importlib.import_module("bench")
    out = bench._stale_headline("probe hung > 240s")
    assert out["dispatch"] == "stream"
    assert out["platform"] == "tpu"
    assert out["value"] == 12.0e6
    assert "ts" not in out and out["stale"]  # ts renamed to stale
    assert "probe hung" in out["stale_reason"]
    # and the stale copy can never be re-ledgered as fresh evidence
    assert not ledger.append(out)


def test_bench_stale_headline_absent_without_ledger(tmp_ledger):
    bench = importlib.import_module("bench")
    assert bench._stale_headline("any") is None


def test_committed_seed_ledger_has_round3_headline():
    """The repo ships the round-3 real-TPU headline as the initial ledger."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rows = []
    with open(os.path.join(here, "docs", "tpu_ledger.jsonl")) as f:
        for line in f:
            rows.append(json.loads(line))
    heads = [r for r in rows if r["metric"] == "sampled-edges/sec/chip"
             and r.get("dispatch") == "stream"]
    assert heads and all(r["platform"] == "tpu" for r in rows)
