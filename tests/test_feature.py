"""Feature store tests: gather-vs-dense differential (the reference's oracle
pattern, test_features.py:338-339 `np.array_equal(res, tensor[indices])`),
budget parsing, reorder integration, cold-tier correctness."""

import numpy as np
import jax
import jax.numpy as jnp

from quiver_tpu import CSRTopo
from quiver_tpu.feature.feature import Feature
from quiver_tpu.utils.graphgen import generate_pareto_graph


def _table(n=200, f=8, seed=0):
    return np.random.default_rng(seed).normal(size=(n, f)).astype(np.float32)


def test_all_hot_matches_dense():
    t = _table()
    feat = Feature(device_cache_size="1G").from_cpu_tensor(t)
    assert feat.hot_rows == 200 and feat.cold is None
    ids = np.random.default_rng(1).integers(0, 200, 64)
    out = np.asarray(feat[jnp.asarray(ids)])
    assert np.allclose(out, t[ids])


def test_all_cold_matches_dense():
    t = _table()
    feat = Feature(device_cache_size=0).from_cpu_tensor(t)
    assert feat.hot is None and feat.cold is not None
    ids = np.random.default_rng(2).integers(0, 200, 50)
    out = np.asarray(feat[jnp.asarray(ids)])
    assert np.allclose(out, t[ids])


def test_mixed_tiers_match_dense():
    t = _table()
    row_bytes = 8 * 4
    feat = Feature(device_cache_size=60 * row_bytes).from_cpu_tensor(t)
    assert feat.hot_rows == 60
    assert feat.hot.shape == (60, 8) and feat.cold.shape == (140, 8)
    ids = np.random.default_rng(3).integers(0, 200, 100)
    out = np.asarray(feat[jnp.asarray(ids)])
    assert np.allclose(out, t[ids])


def test_invalid_ids_zero_rows():
    t = _table()
    feat = Feature(device_cache_size="1M").from_cpu_tensor(t)
    ids = jnp.array([3, -1, 7, -1])
    out = np.asarray(feat[ids])
    assert np.allclose(out[0], t[3]) and np.allclose(out[2], t[7])
    assert np.all(out[1] == 0) and np.all(out[3] == 0)


def test_degree_reorder_transparent():
    # with csr_topo, Feature reorders rows hot-first but lookups by original
    # id must still return the original rows (feature_order translation,
    # reference feature.py:184-195)
    ei = generate_pareto_graph(200, 6.0, seed=5)
    topo = CSRTopo(edge_index=ei)
    t = _table(topo.node_count, 8)
    row_bytes = 8 * 4
    feat = Feature(device_cache_size=50 * row_bytes, csr_topo=topo).from_cpu_tensor(t)
    assert topo.feature_order is not None
    ids = np.random.default_rng(4).integers(0, topo.node_count, 80)
    out = np.asarray(feat[jnp.asarray(ids)])
    assert np.allclose(out, t[ids])
    # hot tier actually holds the high-degree nodes
    deg = topo.degree
    hot_nodes = np.where(np.asarray(feat.feature_order) < feat.hot_rows)[0]
    cold_nodes = np.where(np.asarray(feat.feature_order) >= feat.hot_rows)[0]
    assert deg[hot_nodes].min() >= deg[cold_nodes].max()


def test_lookup_inside_jit():
    t = _table()
    feat = Feature(device_cache_size=100 * 8 * 4).from_cpu_tensor(t)

    @jax.jit
    def f(feat, ids):
        return feat[ids].sum(axis=1)

    ids = jnp.array([1, 5, 150, -1])
    out = np.asarray(f(feat, ids))
    expect = t[[1, 5, 150]].sum(axis=1)
    assert np.allclose(out[:3], expect, rtol=1e-5)
    assert out[3] == 0


def test_feature_delete_frees_buffers():
    """shard_tensor.delete parity (SURVEY §2.5): buffers freed, object inert."""
    import pytest as _pytest

    feat = np.random.default_rng(0).normal(size=(100, 8)).astype(np.float32)
    f = Feature(device_cache_size=50 * 8 * 4).from_cpu_tensor(feat)
    hot = f.hot
    f.delete()
    assert f.hot is None and f.cold is None and f.hot_rows == 0
    with _pytest.raises(RuntimeError):
        _ = np.asarray(hot)  # buffer really gone


def test_pallas_kernel_switch_matches_xla():
    """VERDICT r1 item 2: the Pallas gather must be reachable through the
    Feature store, not just as a dangling unit-tested kernel. Differential
    oracle: kernel="pallas" (interpret mode on CPU) == kernel="xla" == dense
    take, including the mixed hot/cold tier split and -1 lanes."""
    t = _table(n=300, f=16, seed=3)
    row_bytes = 16 * 4
    ids = jnp.asarray(
        np.concatenate([np.random.default_rng(4).integers(0, 300, 60), [-1, -1]])
    )
    fx = Feature(device_cache_size=100 * row_bytes, kernel="xla").from_cpu_tensor(t)
    fp = Feature(device_cache_size=100 * row_bytes, kernel="pallas").from_cpu_tensor(t)
    assert fx.kernel == "xla" and fp.kernel == "pallas"
    ox, op = np.asarray(fx[ids]), np.asarray(fp[ids])
    assert np.allclose(ox, op)
    assert np.allclose(op[:60], t[np.asarray(ids)[:60]])
    assert np.all(op[60:] == 0)


def test_kernel_auto_resolves_off_tpu():
    f = Feature(device_cache_size="1G", kernel="auto")
    assert f.kernel == "xla"  # CPU test mesh — pallas only auto-selected on TPU


def test_bf16_storage_doubles_cache_rows_and_stays_close():
    """dtype="bfloat16": half the row bytes => twice the hot rows for the
    same budget; gathered values match f32 within bf16 precision."""
    t = _table(n=400, f=16, seed=5)
    row_bytes_f32 = 16 * 4
    budget = 100 * row_bytes_f32
    f32 = Feature(device_cache_size=budget).from_cpu_tensor(t)
    bf16 = Feature(device_cache_size=budget, dtype="bf16").from_cpu_tensor(t)
    assert f32.hot_rows == 100 and bf16.hot_rows == 200
    ids = jnp.asarray(np.random.default_rng(6).integers(0, 400, 64))
    out = np.asarray(bf16[ids], dtype=np.float32)
    assert out.dtype == np.float32
    np.testing.assert_allclose(out, t[np.asarray(ids)], rtol=1e-2, atol=1e-2)


def test_bf16_model_learns():
    """Mixed-precision GraphSAGE (bf16 compute, f32 params) must train: the
    TPU recipe the fp32-only reference has no analogue of."""
    import optax

    from quiver_tpu import GraphSageSampler
    from quiver_tpu.models.sage import GraphSAGE
    from quiver_tpu.parallel.train import init_model, make_train_step
    from quiver_tpu.utils.graphgen import generate_pareto_graph

    ei = generate_pareto_graph(600, 8.0, seed=7)
    topo = CSRTopo(edge_index=ei)
    feat = _table(n=600, f=12, seed=8)
    labels = np.random.default_rng(9).integers(0, 4, 600)
    feat[np.arange(600), labels % 12] += 2.0  # learnable signal
    feature = Feature(device_cache_size="1G", dtype="bf16").from_cpu_tensor(feat)
    sampler = GraphSageSampler(topo, [5, 3], seed=0)
    model = GraphSAGE(hidden=32, num_classes=4, num_layers=2, dtype="bfloat16")
    out = sampler.sample(np.arange(128))
    x = feature[out.n_id]
    assert x.dtype == jnp.bfloat16
    params = init_model(model, jax.random.PRNGKey(0), x, out.adjs)
    # params stay f32 (mixed precision, not half-precision weights)
    assert all(
        p.dtype == jnp.float32 for p in jax.tree_util.tree_leaves(params)
    )
    tx = optax.adam(1e-2)
    opt_state = tx.init(params)
    step = jax.jit(make_train_step(model, tx))
    labels_all = jnp.asarray(labels)
    losses = []
    for i in range(15):
        seeds = np.random.default_rng(i).integers(0, 600, 128)
        out = sampler.sample(seeds)
        seed_ids = out.n_id[:128]
        params, opt_state, loss = step(
            params, opt_state, feature[out.n_id], out.adjs,
            labels_all[jnp.clip(seed_ids, 0)], seed_ids >= 0,
            jax.random.PRNGKey(i),
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0] and np.isfinite(losses).all()


def test_int8_quantized_storage_accuracy_and_budget():
    """dtype="int8": ~4x the rows of f32 per budget (the WHOLE (N,) f32
    scale array is HBM-resident — both tiers dequantize on device — so all
    N*4 scale bytes are charged up front); every gathered element within
    the absmax/254 quantization bound; -1 lanes still zero."""
    t = _table(n=400, f=16, seed=10)
    row_bytes_f32 = 16 * 4
    budget = 100 * row_bytes_f32
    q = Feature(device_cache_size=budget, dtype="int8").from_cpu_tensor(t)
    assert q.hot_rows == (budget - 4 * 400) // 16  # 300
    assert q.cold is not None  # mixed tiers exercised
    ids = np.concatenate(
        [np.random.default_rng(11).integers(0, 400, 80), [-1, -1]]
    )
    out = np.asarray(q[jnp.asarray(ids)])
    assert out.dtype == np.float32
    bound = (np.abs(t).max(axis=1) / 254.0 + 1e-7)[ids[:80]][:, None]
    assert np.all(np.abs(out[:80] - t[ids[:80]]) <= bound)
    assert np.all(out[80:] == 0)


def test_int8_zero_rows_exact():
    t = _table(n=50, f=8, seed=12)
    t[7] = 0.0
    q = Feature(device_cache_size="1G", dtype="int8").from_cpu_tensor(t)
    out = np.asarray(q[jnp.asarray([7])])
    assert np.all(out == 0)


def test_kernel_auto_degrades_when_pallas_broken(monkeypatch):
    """VERDICT r2 item 2: kernel="auto" must be fail-safe — a Pallas kernel
    that cannot compile degrades auto to xla instead of taking down every
    TPU feature gather."""
    from quiver_tpu.feature import feature as feature_mod
    from quiver_tpu.ops.pallas import gather as gather_mod

    def boom(*a, **k):
        raise RuntimeError("simulated Mosaic compile failure")

    monkeypatch.setattr(gather_mod, "gather_rows", boom)
    monkeypatch.setattr(feature_mod, "_PALLAS_GATHER_OK", None)
    monkeypatch.setattr(feature_mod.jax, "default_backend", lambda: "tpu")
    assert feature_mod.resolve_gather_kernel("auto") == "xla"
    # explicit pallas request bypasses the smoke (fail loudly on request)
    assert feature_mod.resolve_gather_kernel("pallas") == "pallas"
    # cached verdict: a second resolution must not re-run the smoke
    calls = []
    monkeypatch.setattr(
        gather_mod, "gather_rows", lambda *a, **k: calls.append(1) or boom()
    )
    assert feature_mod.resolve_gather_kernel("auto") == "xla"
    assert not calls
