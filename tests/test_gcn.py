"""GCN model family: dense-oracle exactness, training, layer-wise inference.

GCN's symmetric normalization uses in-block degrees (the DGL
``norm='both'`` mini-batch convention), so exactness oracles seed EVERY
node (block degrees == global degrees) on a symmetrized graph
(in-degree == out-degree, which both GCNConv's two-sided scaling and the
layer-wise pass's single degree vector assume).
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax

from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.models import GCN, gcn_layerwise_inference
from quiver_tpu.parallel.train import init_model, make_train_step
from quiver_tpu.utils.graphgen import generate_pareto_graph


def _sym_graph(n, seed):
    ei = generate_pareto_graph(n, 4.0, seed=seed)
    return np.concatenate([ei, ei[::-1]], axis=1)


def _dense_gcn_layer(A_hat, x, kernel, bias):
    return A_hat @ x @ kernel + bias


def _a_hat(topo, n):
    A = np.zeros((n, n))
    indptr, indices = np.asarray(topo.indptr), np.asarray(topo.indices)
    for i in range(n):
        for j in indices[indptr[i]:indptr[i + 1]]:
            A[i, j] += 1.0  # row i aggregates its CSR neighbors
    A += np.eye(n)
    d = A.sum(axis=1)
    inv_s = 1.0 / np.sqrt(d)
    return inv_s[:, None] * A * inv_s[None, :]


def test_gcn_conv_matches_dense_full_graph():
    n = 60
    topo = CSRTopo(edge_index=_sym_graph(n, 0))
    x_all = np.random.default_rng(1).normal(size=(n, 7)).astype(np.float32)
    model = GCN(hidden=5, num_classes=4, num_layers=1, dropout=0.0)

    sampler = GraphSageSampler(topo, [-1], seed=0)
    out = sampler.sample(np.arange(n))
    assert int(out.overflow) == 0
    n_id = np.asarray(out.n_id)
    assert np.array_equal(n_id[:n], np.arange(n))  # identity frontier
    x = jnp.asarray(np.where((n_id >= 0)[:, None],
                             x_all[np.maximum(n_id, 0)], 0))
    params = init_model(model, jax.random.PRNGKey(2), x, out.adjs)
    got = np.asarray(
        model.apply({"params": params}, x, out.adjs, train=False)
    )[:n]

    conv = params["conv0"]
    dense = _dense_gcn_layer(
        _a_hat(topo, n), x_all,
        np.asarray(conv["lin"]["kernel"]), np.asarray(conv["bias"]),
    )
    want = np.asarray(jax.nn.log_softmax(jnp.asarray(dense), axis=-1))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_gcn_training_learns():
    rng = np.random.default_rng(0)
    n, classes = 300, 4
    labels = rng.integers(0, classes, n)
    feat = np.eye(classes, dtype=np.float32)[labels] * 2.0
    feat += rng.normal(scale=0.6, size=(n, classes)).astype(np.float32)
    rows, cols = [], []
    for c in range(classes):
        members = np.where(labels == c)[0]
        rows.extend(rng.choice(members, 5 * len(members)))
        cols.extend(rng.choice(members, 5 * len(members)))
    ei = np.stack([np.asarray(rows), np.asarray(cols)])
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count

    sampler = GraphSageSampler(topo, [5, 5], seed=1)
    model = GCN(hidden=32, num_classes=classes, num_layers=2)
    out = sampler.sample(rng.integers(0, n, 64))
    x = jnp.asarray(np.where(
        (np.asarray(out.n_id) >= 0)[:, None],
        feat[np.maximum(np.asarray(out.n_id), 0)], 0))
    params = init_model(model, jax.random.PRNGKey(0), x, out.adjs)
    tx = optax.adam(5e-3)
    opt_state = tx.init(params)
    step = jax.jit(make_train_step(model, tx))
    losses = []
    for i in range(30):
        seeds = rng.integers(0, n, 64)
        out = sampler.sample(seeds)
        n_id = np.asarray(out.n_id)
        x = jnp.asarray(np.where((n_id >= 0)[:, None],
                                 feat[np.maximum(n_id, 0)], 0))
        # labels/mask at logits width (= padded seed capacity)
        cap = out.adjs[-1].size[1]
        lab = np.full(cap, -1, np.int32)
        lab[:64] = labels[seeds]
        mask = np.zeros(cap, bool)
        mask[:64] = True
        params, opt_state, loss = step(
            params, opt_state, x, out.adjs, jnp.asarray(lab),
            jnp.asarray(mask), jax.random.PRNGKey(i)
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.6, losses


def test_gcn_layerwise_matches_sampled_full_cover():
    """Two-layer oracle: all nodes seeded, full fanout, symmetric graph —
    the sampled model's predictions must equal the whole-graph layer-wise
    pass (block degrees == global degrees in this regime)."""
    n = 80
    topo = CSRTopo(edge_index=_sym_graph(n, 3))
    x_all = np.random.default_rng(4).normal(size=(n, 6)).astype(np.float32)
    model = GCN(hidden=10, num_classes=3, num_layers=2, dropout=0.0)

    sampler = GraphSageSampler(topo, [-1, -1], seed=0)
    out = sampler.sample(np.arange(n))
    assert int(out.overflow) == 0
    n_id = np.asarray(out.n_id)
    x = jnp.asarray(np.where((n_id >= 0)[:, None],
                             x_all[np.maximum(n_id, 0)], 0))
    params = init_model(model, jax.random.PRNGKey(5), x, out.adjs)
    sampled = np.asarray(
        model.apply({"params": params}, x, out.adjs, train=False)
    )[:n]

    full = np.asarray(
        gcn_layerwise_inference(model, params, topo, x_all, chunk=97)
    )
    np.testing.assert_allclose(sampled, full, rtol=1e-4, atol=1e-5)
