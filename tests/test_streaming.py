"""Transactional streaming graph mutation (quiver_tpu/streaming).

Fast lane: admission/quarantine semantics, the merge-vs-rebuild bitwise
oracle, rollback on injected commit failures, versioned invalidation in
the samplers and the fused trainer, three-tier feature row updates
(including the no-stale-L0 contract and the replan interaction), and the
CSRTopo save/load hardening satellites.

Slow lane: the end-to-end differential — train N epochs with deltas
committed at epoch boundaries vs a full rebuild from the equivalent final
graph (same sampled batches and loss trajectory, bitwise), plus the
mid-commit-crash continuation.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from quiver_tpu import (
    CommitAborted,
    CSRTopo,
    DeltaBatch,
    GraphSageSampler,
    StreamingGraph,
    VersionMismatchError,
)
from quiver_tpu.obs.registry import (
    DELTAS_COMMITTED,
    DELTAS_QUARANTINED,
    STREAMING_COMMITS,
)
from quiver_tpu.streaming import DeltaRejected, merge_csr, verify_merged_csr


def _graph(n=200, e=2000, seed=0):
    rng = np.random.default_rng(seed)
    coo = rng.integers(0, n, size=(2, e)).astype(np.int64)
    return CSRTopo(edge_index=coo), coo


def _first_live_edge(topo):
    src = int(np.repeat(np.arange(topo.node_count), topo.degree)[0])
    dst = int(np.asarray(topo.indices)[
        int(np.asarray(topo.indptr, dtype=np.int64)[src])])
    return src, dst


def _missing_pair(topo, coo):
    """A (src, dst) pair guaranteed absent from the graph."""
    n = topo.node_count
    live = set((coo[0] * n + coo[1]).tolist())
    for k in range(n * n):
        if k not in live:
            return k // n, k % n
    raise AssertionError("graph is complete")


# -- admission ---------------------------------------------------------------


def test_admission_rejects_and_quarantines():
    topo, coo = _graph()
    n = topo.node_count
    sg = StreamingGraph(topo)
    ms, md = _missing_pair(topo, coo)
    bad = [
        (DeltaBatch(edge_inserts=np.array([[0], [n + 3]])), "outside"),
        (DeltaBatch(edge_inserts=np.array([[0, 1]])), "(2, E)"),
        (DeltaBatch(edge_inserts=np.array([[0.5], [1.5]])), "integer"),
        (DeltaBatch(edge_deletes=np.array([[ms], [md]])), "live edge"),
        (DeltaBatch(update_ids=np.array([0]),
                    update_rows=np.ones((1, 4), np.float32)),
         "no feature store"),
        (DeltaBatch(edge_inserts=np.array([[2, 2], [3, 3]])), "duplicate"),
    ]
    for i, (delta, needle) in enumerate(bad):
        assert sg.ingest(delta) is False
        assert needle in sg.quarantined[-1].reason
        assert sg.quarantined[-1].stage == "ingest"
    assert not sg.staged
    assert int(np.asarray(sg.metrics.value(DELTAS_QUARANTINED))) == len(bad)
    assert topo.version == 0


def test_admission_update_validation():
    topo, _ = _graph()
    import types

    store = types.SimpleNamespace(shape=(topo.node_count, 4),
                                  apply_row_updates=lambda ids, rows: None)
    sg = StreamingGraph(topo, feature=store)
    nan_rows = np.ones((1, 4), np.float32)
    nan_rows[0, 2] = np.nan
    assert not sg.ingest(
        DeltaBatch(update_ids=np.array([1]), update_rows=nan_rows))
    assert "non-finite" in sg.quarantined[-1].reason
    assert not sg.ingest(
        DeltaBatch(update_ids=np.array([1]),
                   update_rows=np.ones((1, 3), np.float32)))
    assert "feature dim" in sg.quarantined[-1].reason
    assert not sg.ingest(DeltaBatch(update_ids=np.array([1])))
    assert "together" in sg.quarantined[-1].reason
    assert not sg.ingest(
        DeltaBatch(update_ids=np.array([1, 1]),
                   update_rows=np.ones((2, 4), np.float32)))
    assert "duplicate update_ids" in sg.quarantined[-1].reason


def test_duplicates_allow_policy():
    topo, _ = _graph()
    import types

    seen = {}
    store = types.SimpleNamespace(
        shape=(topo.node_count, 2),
        apply_row_updates=lambda ids, rows: seen.update(
            {"ids": ids.copy(), "rows": rows.copy()}),
        note_degree_update=lambda deg: None,
    )
    sg = StreamingGraph(topo, feature=store, duplicates="allow")
    # parallel edges admitted; duplicate update ids collapse last-wins
    rows = np.stack([np.full(2, 1.0, np.float32),
                     np.full(2, 2.0, np.float32),
                     np.full(2, 3.0, np.float32)])
    assert sg.ingest(DeltaBatch(
        edge_inserts=np.array([[2, 2], [3, 3]]),
        update_ids=np.array([7, 9, 7]), update_rows=rows))
    res = sg.commit()
    assert res.edges_inserted == 2 and res.rows_updated == 2
    order = np.argsort(seen["ids"])
    assert np.array_equal(seen["ids"][order], [7, 9])
    assert np.array_equal(seen["rows"][order][:, 0], [3.0, 2.0])


def test_delete_existence_is_multiset_aware():
    topo, coo = _graph()
    sg = StreamingGraph(topo)
    ms, md = _missing_pair(topo, coo)
    # deleting an edge staged-inserted earlier in the window is legal
    assert sg.ingest(DeltaBatch(edge_inserts=np.array([[ms], [md]])))
    assert sg.ingest(DeltaBatch(edge_deletes=np.array([[ms], [md]])))
    # but a SECOND delete of the same (now spent) pair is not
    assert not sg.ingest(DeltaBatch(edge_deletes=np.array([[ms], [md]])))
    assert "live edge" in sg.quarantined[-1].reason


# -- commit / rollback -------------------------------------------------------


def test_commit_matches_full_rebuild_bitwise():
    topo, coo = _graph(n=300, e=4000, seed=1)
    n = topo.node_count
    rng = np.random.default_rng(7)
    ins = rng.integers(0, n, size=(2, 57)).astype(np.int64)
    # delete a sample of live edges (first occurrences)
    del_pos = rng.choice(coo.shape[1], size=23, replace=False)
    dele = coo[:, del_pos]
    sg = StreamingGraph(topo, duplicates="allow")
    assert sg.ingest(DeltaBatch(edge_inserts=ins, edge_deletes=dele))
    res = sg.commit()
    assert res.version == 1
    assert res.edge_count == coo.shape[1] + 57 - 23
    # oracle: rebuild from the equivalent final COO — original edges with
    # the deleted occurrences removed, inserts appended in order
    n_enc = coo[0] * n + coo[1]
    remove = np.zeros(coo.shape[1], bool)
    from collections import Counter

    want = Counter((dele[0] * n + dele[1]).tolist())
    order = np.argsort(coo[0], kind="stable")  # CSR slot order
    for pos in order.tolist():
        k = int(n_enc[pos])
        if want.get(k, 0) > 0:
            want[k] -= 1
            remove[pos] = True
    final = np.concatenate([coo[:, ~remove], ins], axis=1)
    oracle = CSRTopo(edge_index=final)
    assert np.array_equal(np.asarray(topo.indptr, np.int64),
                          np.asarray(oracle.indptr, np.int64))
    assert np.array_equal(np.asarray(topo.indices, np.int64),
                          np.asarray(oracle.indices, np.int64))
    assert int(np.asarray(sg.metrics.value(DELTAS_COMMITTED))) == 1
    assert int(np.asarray(sg.metrics.value(STREAMING_COMMITS))) == 1


@pytest.mark.parametrize("stage", ["merge", "verify", "features"])
def test_commit_rollback_on_injected_failure(stage):
    topo, _ = _graph()
    src, dst = _first_live_edge(topo)
    old_ip = np.asarray(topo.indptr).copy()
    old_ix = np.asarray(topo.indices).copy()
    sg = StreamingGraph(topo)
    assert sg.ingest(DeltaBatch(
        edge_inserts=np.array([[1, 2], [3, 4]]),
        edge_deletes=np.array([[src], [dst]])))
    with pytest.raises(CommitAborted, match=stage):
        sg.commit(inject_failure=stage)
    # pre-commit state is bit-identical; the batch is quarantined whole
    assert topo.version == 0
    assert np.array_equal(old_ip, np.asarray(topo.indptr))
    assert np.array_equal(old_ix, np.asarray(topo.indices))
    assert not sg.staged
    assert sg.quarantined[-1].stage == "commit"
    assert int(np.asarray(sg.metrics.value(DELTAS_QUARANTINED))) == 1


def test_commit_empty_is_noop():
    topo, _ = _graph()
    sg = StreamingGraph(topo)
    assert sg.commit() is None
    assert topo.version == 0


def test_verify_catches_untouched_corruption():
    topo, _ = _graph(n=50, e=400, seed=3)
    indptr = np.asarray(topo.indptr, np.int64)
    indices = np.asarray(topo.indices, np.int64)
    ins = np.array([[0], [1]])
    new_ip, new_ix, touched = merge_csr(indptr, indices, ins, None)
    verify_merged_csr(indptr, indices, new_ip, new_ix, touched, 1, 0)
    # corrupt a neighbor of an UNTOUCHED row: the checksum must catch it
    victim = int(np.flatnonzero(~touched & (np.diff(new_ip) > 0))[0])
    bad = new_ix.copy()
    pos = int(new_ip[victim])
    bad[pos] = (bad[pos] + 1) % topo.node_count
    with pytest.raises(DeltaRejected, match="checksum"):
        verify_merged_csr(indptr, indices, new_ip, bad, touched, 1, 0)


def _attr_graph(n=50, e=200, seed=0):
    rng = np.random.default_rng(seed)
    ei = np.stack([rng.integers(0, n, e), rng.integers(0, n, e)])
    topo = CSRTopo(edge_index=ei)
    topo.set_edge_weight((rng.random(e) + 0.1))
    topo.set_edge_time(rng.random(e))
    return topo, ei


def test_attributed_admission_named_rejections():
    """Inserts into a weighted/timestamped topology must carry matching
    attribute columns or the WHOLE batch is rejected with a named reason
    and quarantined — a half-attributed commit would silently corrupt the
    sampler's CDF/window searches."""
    topo, _ = _attr_graph()
    sg = StreamingGraph(topo)
    ins = np.array([[1], [2]])
    bad = [
        (DeltaBatch(edge_inserts=ins), "missing-edge-weights"),
        (DeltaBatch(edge_inserts=ins, edge_weights=np.array([1.0])),
         "missing-edge-times"),
        (DeltaBatch(edge_inserts=ins, edge_weights=np.array([-1.0]),
                    edge_times=np.array([0.5])), "bad-edge-weights"),
        (DeltaBatch(edge_inserts=ins, edge_weights=np.array([1.0]),
                    edge_times=np.array([np.nan])), "bad-edge-times"),
        (DeltaBatch(edge_inserts=ins, edge_weights=np.array([1.0, 2.0]),
                    edge_times=np.array([0.5, 0.5])), "bad-edge-weights"),
    ]
    for delta, needle in bad:
        assert sg.ingest(delta) is False
        assert needle in sg.quarantined[-1].reason
        assert sg.quarantined[-1].stage == "ingest"
    assert not sg.staged
    assert int(np.asarray(sg.metrics.value(DELTAS_QUARANTINED))) == len(bad)
    assert topo.version == 0


def test_attributed_commit_publishes_slot_aligned_attrs():
    """A good attributed batch commits: inserted edges land with their
    weights/timestamps slot-aligned, rows stay time-nondecreasing, and
    the weight prefix sums re-derive over the merged slot order."""
    from quiver_tpu.core.topology import _row_prefix_weights

    topo, _ = _attr_graph()
    E = topo.edge_count
    sg = StreamingGraph(topo)
    row = 7
    dsrc, ddst = _first_live_edge(topo)
    assert sg.ingest(DeltaBatch(
        edge_inserts=np.array([[row, row, 3], [11, 12, 13]]),
        edge_weights=np.array([0.7, 0.9, 1.1]),
        edge_times=np.array([0.05, 0.95, 0.4]),
    )), sg.quarantined and sg.quarantined[-1].reason
    assert sg.ingest(DeltaBatch(edge_deletes=np.array([[dsrc], [ddst]])))
    assert sg.commit() is not None
    ip, ix = np.asarray(topo.indptr), np.asarray(topo.indices)
    wt, tm = np.asarray(topo.edge_weight), np.asarray(topo.edge_time)
    assert wt.shape == ix.shape == tm.shape
    assert int(ip[-1]) == E + 3 - 1
    for r in range(topo.node_count):
        assert (np.diff(tm[ip[r]:ip[r + 1]]) >= 0).all(), r
    seg = slice(ip[row], ip[row + 1])
    for d, dw, dt in [(11, 0.7, 0.05), (12, 0.9, 0.95)]:
        pos = np.flatnonzero((ix[seg] == d) & np.isclose(tm[seg], dt))
        assert pos.size == 1 and np.isclose(wt[seg][pos[0]], dw), d
    assert np.array_equal(
        np.asarray(topo.cum_weights),
        _row_prefix_weights(wt.astype(np.float64), ip),
    )
    assert topo.version == 1


def test_unattributed_topo_rejects_attr_deltas():
    topo, _ = _graph()
    sg = StreamingGraph(topo)
    assert not sg.ingest(DeltaBatch(edge_inserts=np.array([[1], [2]]),
                                    edge_weights=np.array([1.0])))
    assert "unexpected-edge-weights" in sg.quarantined[-1].reason
    assert not sg.ingest(DeltaBatch(edge_inserts=np.array([[1], [2]]),
                                    edge_times=np.array([1.0])))
    assert "unexpected-edge-times" in sg.quarantined[-1].reason
    assert sg.ingest(DeltaBatch(edge_inserts=np.array([[1], [2]])))
    assert sg.commit() is not None


def test_weighted_only_topo_streaming_flow():
    """Weights-only topology: times rejected, weights required, and a
    deletes-only batch needs no attribute columns at all."""
    topo, ei = _graph(n=50, e=200)
    topo.set_edge_weight(np.ones(200))
    sg = StreamingGraph(topo)
    assert not sg.ingest(DeltaBatch(
        edge_inserts=np.array([[1], [2]]), edge_weights=np.array([1.0]),
        edge_times=np.array([0.5])))
    assert "unexpected-edge-times" in sg.quarantined[-1].reason
    assert sg.ingest(DeltaBatch(edge_inserts=np.array([[1], [2]]),
                                edge_weights=np.array([2.5])))
    assert sg.commit() is not None
    assert np.asarray(topo.edge_weight).shape[0] == 201
    s, d = _first_live_edge(topo)
    assert sg.ingest(DeltaBatch(edge_deletes=np.array([[s], [d]])))
    assert sg.commit() is not None


# -- versioned invalidation --------------------------------------------------


def test_sampler_stale_raise_and_refresh_parity():
    topo, coo = _graph(n=256, e=2500, seed=5)
    sampler = GraphSageSampler(topo, [3, 3], seed=3, seed_capacity=32)
    seeds = np.arange(16)
    sampler.sample(seeds)
    sg = StreamingGraph(topo)
    assert sg.ingest(DeltaBatch(edge_inserts=np.array([[1], [2]])))
    sg.commit()
    with pytest.raises(VersionMismatchError, match="refresh_topology"):
        sampler.sample(seeds)
    sampler.refresh_topology()
    out = sampler.sample(seeds)
    # parity with a FRESH sampler over the rebuilt final graph: same seed
    # stream position, same draws, bit-identical output
    final = np.concatenate([coo, np.array([[1], [2]])], axis=1)
    fresh = GraphSageSampler(CSRTopo(edge_index=final), [3, 3], seed=3,
                             seed_capacity=32)
    fresh._call = sampler._call - 1  # align the per-call key fold
    ref = fresh.sample(seeds)
    assert np.array_equal(np.asarray(out.n_id), np.asarray(ref.n_id))
    for a, b in zip(out.adjs, ref.adjs):
        assert np.array_equal(np.asarray(a.edge_index),
                              np.asarray(b.edge_index))


# -- feature tiers -----------------------------------------------------------


def _mesh(data, feature):
    from quiver_tpu.parallel.mesh import make_mesh

    return make_mesh(n_devices=data * feature, data=data, feature=feature)


def _store(topo, feat, mesh, dtype=None, auto_split=False):
    from quiver_tpu.feature.shard import ShardedFeature

    f = feat.shape[1]
    return ShardedFeature(
        mesh, device_cache_size=16 * f * 4, replicate_budget=8 * f * 4,
        csr_topo=topo, dtype=dtype, auto_split=auto_split,
    ).from_cpu_tensor(feat)


def test_row_updates_serve_fresh_in_every_tier():
    topo, _ = _graph(n=256, e=3000, seed=6)
    rng = np.random.default_rng(6)
    feat = rng.normal(size=(256, 16)).astype(np.float32)
    store = _store(topo, feat, _mesh(1, 8))
    assert store.rep_rows > 0 and store.hot_rows > 0 and store.cold is not None
    order = np.asarray(store.feature_order)
    inv = np.empty(256, np.int64)
    inv[order] = np.arange(256)
    ids = np.array([
        int(inv[0]),                                # pinned in L0
        int(inv[store.rep_rows]),                   # first L1 row
        int(inv[store.rep_rows + store.hot_rows]),  # first cold row
    ])
    rows = rng.normal(size=(3, 16)).astype(np.float32) + 50.0
    sg = StreamingGraph(topo, feature=store)
    assert sg.ingest(DeltaBatch(update_ids=ids, update_rows=rows))
    sg.commit()
    assert store.version == 1
    assert np.array_equal(np.asarray(store.gather(ids)), rows)
    # the no-stale-L0 contract: the pinned row serves the NEW value from
    # every chip's replica
    for shard in store.rep.addressable_shards:
        assert np.array_equal(np.asarray(shard.data)[0], rows[0])
    others = np.setdiff1d(np.arange(256), ids)[:32]
    assert np.array_equal(np.asarray(store.gather(others)), feat[others])


def test_row_updates_quantized_store_requantizes():
    from quiver_tpu.feature.shard import ShardedFeature

    topo, _ = _graph(n=256, e=3000, seed=8)
    rng = np.random.default_rng(8)
    feat = rng.normal(size=(256, 8)).astype(np.float32)
    # int8 budgets must clear the replicated 4n-byte scale array floor
    store = ShardedFeature(
        _mesh(1, 8), device_cache_size=4 * 256 + 16 * 8,
        replicate_budget=8 * 8, csr_topo=topo, dtype="int8",
    ).from_cpu_tensor(feat)
    assert store.rep_rows > 0 and store.hot_rows > 0
    order = store.feature_order
    ids = np.array([0, 100])
    rows = np.array([np.full(8, 3.0), np.full(8, -1.5)], np.float32)
    store.apply_row_updates(ids, rows)
    got = np.asarray(store.gather(ids))
    # int8 storage: values round-trip through per-row absmax quantization
    assert np.allclose(got, rows, atol=np.abs(rows).max() / 127 + 1e-6)
    t = np.asarray(order)[ids] if order is not None else ids
    assert np.allclose(np.asarray(store.scale)[t],
                       np.abs(rows).max(axis=1) / 127.0)


def test_row_update_then_replan_keeps_fresh_values():
    # the satellite: ShardedFeature.replan + L0 interaction after a row
    # update — the updated pinned row must serve the new value on every
    # chip of the NEW mesh too
    topo, _ = _graph(n=256, e=3000, seed=9)
    rng = np.random.default_rng(9)
    feat = rng.normal(size=(256, 16)).astype(np.float32)
    store = _store(topo, feat, _mesh(1, 8))
    order = np.asarray(store.feature_order)
    inv = np.empty(256, np.int64)
    inv[order] = np.arange(256)
    pinned = int(inv[0])
    cold_id = int(inv[store.rep_rows + store.hot_rows])
    rows = rng.normal(size=(2, 16)).astype(np.float32) + 9.0
    store.apply_row_updates(np.array([pinned, cold_id]), rows)
    store.replan(_mesh(1, 4))
    got = np.asarray(store.gather(np.array([pinned, cold_id])))
    assert np.array_equal(got, rows)
    for shard in store.rep.addressable_shards:
        assert np.array_equal(np.asarray(shard.data)[0], rows[0])
    assert store.version == 1  # replan is placement, not mutation


def test_row_update_rejects_bad_input_bit_identically():
    topo, _ = _graph(n=128, e=1500, seed=10)
    rng = np.random.default_rng(10)
    feat = rng.normal(size=(128, 8)).astype(np.float32)
    store = _store(topo, feat, _mesh(1, 8))
    before = np.asarray(store.gather(np.arange(128)))
    bad_rows = np.ones((1, 8), np.float32)
    bad_rows[0, 0] = np.inf
    for ids, rows, match in [
        (np.array([1]), bad_rows, "non-finite"),
        (np.array([200]), np.ones((1, 8), np.float32), "in \\[0, 128\\)"),
        (np.array([1, 1]), np.ones((2, 8), np.float32), "duplicate"),
        (np.array([1]), np.ones((1, 5), np.float32), "feature dim"),
    ]:
        with pytest.raises(ValueError, match=match):
            store.apply_row_updates(ids, rows)
    assert store.version == 0
    assert np.array_equal(np.asarray(store.gather(np.arange(128))), before)


def test_degree_update_feeds_split_tuner():
    topo, _ = _graph(n=256, e=3000, seed=11)
    rng = np.random.default_rng(11)
    feat = rng.normal(size=(256, 8)).astype(np.float32)
    store = _store(topo, feat, _mesh(1, 8), auto_split=True)
    rep0 = store.rep_rows
    assert rep0 > 0
    # post-mutation degrees concentrate ALL heat outside L0: the tuner's
    # existing shrink rule must hand the replicated rows back
    order = np.asarray(store.feature_order)
    inv = np.empty(256, np.int64)
    inv[order] = np.arange(256)
    deg = np.zeros(256, np.int64)
    deg[inv[rep0: rep0 + store.hot_rows]] = 100
    store.note_degree_update(deg)
    assert store.rep_rows == rep0 // 2


def test_trainer_stale_raise_and_refresh():
    import optax

    from quiver_tpu.models.sage import GraphSAGE
    from quiver_tpu.parallel.trainer import DistributedTrainer

    topo, _ = _graph(n=128, e=1200, seed=12)
    rng = np.random.default_rng(12)
    feat = rng.normal(size=(128, 4)).astype(np.float32)
    labels = jnp.asarray(rng.integers(0, 3, 128).astype(np.int32))
    mesh = _mesh(2, 4)
    store = _store(topo, feat, mesh)
    sampler = GraphSageSampler(topo, [2, 2], seed=3, seed_capacity=8,
                               topo_sharding="mesh", mesh=mesh)
    tr = DistributedTrainer(
        mesh, sampler, store, GraphSAGE(hidden=4, num_classes=3,
                                        num_layers=2),
        optax.sgd(1e-2), local_batch=8, seed_sharding="all",
    )
    params, opt = tr.init(jax.random.PRNGKey(0))
    idx = rng.integers(0, 128, tr.global_batch)
    params, opt, _ = tr.step(params, opt, idx, labels, jax.random.PRNGKey(1))
    sg = StreamingGraph(topo, feature=store)
    assert sg.ingest(DeltaBatch(
        edge_inserts=np.array([[1], [2]]),
        update_ids=np.array([5]),
        update_rows=np.full((1, 4), 2.5, np.float32)))
    sg.commit()
    with pytest.raises(VersionMismatchError, match="refresh"):
        tr.step(params, opt, idx, labels, jax.random.PRNGKey(2))
    with pytest.raises(VersionMismatchError, match="refresh"):
        tr.epoch_scan(params, opt, tr.pack_epoch(idx, seed=0), labels,
                      jax.random.PRNGKey(2))
    tr.refresh()
    params, opt, loss = tr.step(params, opt, idx, labels,
                                jax.random.PRNGKey(2))
    assert np.isfinite(float(loss))


# -- CSRTopo hardening satellites --------------------------------------------


def test_save_is_atomic(tmp_path, monkeypatch):
    topo, _ = _graph(n=64, e=400, seed=13)
    path = str(tmp_path / "topo.npz")
    topo.save(path)
    good = open(path, "rb").read()
    # a crash mid-save (np.savez dies) must leave the published file
    # intact and no temp litter
    def boom(*a, **k):
        raise OSError("disk full")

    monkeypatch.setattr(np, "savez", boom)
    with pytest.raises(OSError, match="disk full"):
        topo.save(path)
    monkeypatch.undo()
    assert open(path, "rb").read() == good
    assert os.listdir(tmp_path) == ["topo.npz"]
    back = CSRTopo.load(path)
    assert np.array_equal(np.asarray(back.indptr), np.asarray(topo.indptr))


def test_load_truncated_raises_clearly(tmp_path):
    topo, _ = _graph(n=64, e=400, seed=14)
    path = str(tmp_path / "topo.npz")
    topo.save(path)
    blob = open(path, "rb").read()
    trunc = str(tmp_path / "trunc.npz")
    with open(trunc, "wb") as fh:
        fh.write(blob[: len(blob) // 3])
    with pytest.raises(ValueError, match="truncated|corrupt|readable"):
        CSRTopo.load(trunc)
    junk = str(tmp_path / "junk.npz")
    with open(junk, "wb") as fh:
        fh.write(b"not a zip at all")
    with pytest.raises(ValueError, match="truncated|corrupt|readable"):
        CSRTopo.load(junk)
    # a real .npz that is not a topology artifact names what's missing
    partial = str(tmp_path / "partial.npz")
    np.savez(partial, indptr=np.asarray(topo.indptr))
    with pytest.raises(ValueError, match="indices"):
        CSRTopo.load(partial)


def test_ctor_rejects_negative_indices():
    with pytest.raises(ValueError, match="negative"):
        CSRTopo(indptr=np.array([0, 2]), indices=np.array([0, -1]))


# -- slow differentials ------------------------------------------------------


def _build_diff_trainer(topo, feat_arr, mesh, local_batch):
    import optax

    from quiver_tpu.feature.shard import ShardedFeature
    from quiver_tpu.models.sage import GraphSAGE
    from quiver_tpu.parallel.trainer import DistributedTrainer

    f = feat_arr.shape[1]
    # csr_topo=None: no degree reorder, so the incremental store and the
    # rebuilt store share one (identity) row order — the differential
    # compares graph content, not placement policy
    store = ShardedFeature(
        mesh, device_cache_size=24 * f * 4, replicate_budget=8 * f * 4,
    ).from_cpu_tensor(feat_arr)
    sampler = GraphSageSampler(topo, [3, 3], seed=3,
                               seed_capacity=local_batch,
                               topo_sharding="mesh", mesh=mesh)
    tr = DistributedTrainer(
        mesh, sampler, store, GraphSAGE(hidden=8, num_classes=4,
                                        num_layers=2),
        optax.sgd(1e-2), local_batch=local_batch, seed_sharding="all",
    )
    return tr, store


@pytest.mark.slow
def test_epoch_differential_incremental_vs_rebuild():
    """Train with deltas committed at the epoch boundary vs a full
    rebuild from the equivalent final graph: same sampled batches and
    loss trajectory, bitwise (same seed)."""
    n, f, lb = 384, 8, 16
    rng = np.random.default_rng(42)
    coo = rng.integers(0, n, size=(2, 4000)).astype(np.int64)
    feat0 = rng.normal(size=(n, f)).astype(np.float32)
    labels = jnp.asarray(rng.integers(0, 4, n).astype(np.int32))
    mesh = _mesh(2, 4)

    ins = rng.integers(0, n, size=(2, 64)).astype(np.int64)
    del_pos = rng.choice(coo.shape[1], size=40, replace=False)
    upd_ids = rng.choice(n, size=24, replace=False)
    upd_rows = rng.normal(size=(24, f)).astype(np.float32)

    # ---- incremental path: epoch 0 on G0, commit at the boundary,
    # refresh, epoch 1 on the mutated resident state ----
    topo_inc = CSRTopo(edge_index=coo)
    tr_inc, store_inc = _build_diff_trainer(topo_inc, feat0, mesh, lb)
    params, opt = tr_inc.init(jax.random.PRNGKey(0))
    idx = rng.integers(0, n, 4 * tr_inc.global_batch)
    seed_mat = tr_inc.pack_epoch(idx, seed=0)
    params, opt, losses0 = tr_inc.epoch_scan(
        params, opt, seed_mat, labels, jax.random.PRNGKey(7))
    sg = StreamingGraph(topo_inc, feature=store_inc, duplicates="allow")
    assert sg.ingest(DeltaBatch(
        edge_inserts=ins, edge_deletes=coo[:, del_pos],
        update_ids=upd_ids, update_rows=upd_rows))
    res = sg.commit()
    assert res.version == 1
    tr_inc.refresh()
    p1, o1, losses1_inc = tr_inc.epoch_scan(
        params, opt, seed_mat, labels, jax.random.PRNGKey(21))

    # ---- rebuild path: the equivalent final graph from scratch, fed the
    # SAME post-epoch-0 state, seed matrix, and key ----
    n_enc = coo[0] * n + coo[1]
    from collections import Counter

    want = Counter((coo[0, del_pos] * n + coo[1, del_pos]).tolist())
    remove = np.zeros(coo.shape[1], bool)
    for pos in np.argsort(coo[0], kind="stable").tolist():
        k = int(n_enc[pos])
        if want.get(k, 0) > 0:
            want[k] -= 1
            remove[pos] = True
    final_coo = np.concatenate([coo[:, ~remove], ins], axis=1)
    feat_final = feat0.copy()
    feat_final[upd_ids] = upd_rows
    topo_reb = CSRTopo(edge_index=final_coo)
    assert np.array_equal(np.asarray(topo_inc.indptr, np.int64),
                          np.asarray(topo_reb.indptr, np.int64))
    assert np.array_equal(np.asarray(topo_inc.indices, np.int64),
                          np.asarray(topo_reb.indices, np.int64))
    tr_reb, store_reb = _build_diff_trainer(topo_reb, feat_final, mesh, lb)
    p1r, o1r, losses1_reb = tr_reb.epoch_scan(
        params, opt, seed_mat, labels, jax.random.PRNGKey(21))

    # the epoch-1 SAMPLED BATCHES are bit-identical: same sampler seed
    # stream over byte-identical CSR partitions
    s_inc, s_reb = tr_inc.sampler, tr_reb.sampler
    key = jax.random.PRNGKey(33)
    out_i = s_inc.sample(idx[: lb * s_inc.workers], key=key)
    out_r = s_reb.sample(idx[: lb * s_reb.workers], key=key)
    assert np.array_equal(np.asarray(out_i.n_id), np.asarray(out_r.n_id))
    for a, b in zip(out_i.adjs, out_r.adjs):
        assert np.array_equal(np.asarray(a.edge_index),
                              np.asarray(b.edge_index))
    # and the loss trajectory + final params match bitwise
    assert np.array_equal(
        np.asarray(losses1_inc).view(np.uint32),
        np.asarray(losses1_reb).view(np.uint32))
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p1r)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_mid_commit_crash_training_continues_unperturbed():
    """A commit that dies before publish must leave the run EXACTLY as if
    the commit were never attempted: epoch 1 proceeds on the old version
    with a bit-identical trajectory."""
    n, f, lb = 256, 8, 16
    rng = np.random.default_rng(43)
    coo = rng.integers(0, n, size=(2, 3000)).astype(np.int64)
    feat0 = rng.normal(size=(n, f)).astype(np.float32)
    labels = jnp.asarray(rng.integers(0, 4, n).astype(np.int32))
    mesh = _mesh(2, 4)

    def run(crash: bool):
        topo = CSRTopo(edge_index=coo)
        tr, store = _build_diff_trainer(topo, feat0, mesh, lb)
        params, opt = tr.init(jax.random.PRNGKey(0))
        idx = np.random.default_rng(5).integers(0, n, 3 * tr.global_batch)
        seed_mat = tr.pack_epoch(idx, seed=0)
        params, opt, _ = tr.epoch_scan(
            params, opt, seed_mat, labels, jax.random.PRNGKey(7))
        if crash:
            sg = StreamingGraph(topo, feature=store)
            assert sg.ingest(DeltaBatch(
                edge_inserts=np.array([[1, 2], [3, 4]])))
            with pytest.raises(CommitAborted):
                sg.commit(inject_failure="verify")
            assert topo.version == 0 and store.version == 0
        # NO refresh needed — nothing was published
        params, opt, losses = tr.epoch_scan(
            params, opt, seed_mat, labels, jax.random.PRNGKey(21))
        return np.asarray(losses), params

    losses_a, params_a = run(crash=False)
    losses_b, params_b = run(crash=True)
    assert np.array_equal(losses_a.view(np.uint32),
                          losses_b.view(np.uint32))
    for a, b in zip(jax.tree_util.tree_leaves(params_a),
                    jax.tree_util.tree_leaves(params_b)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
