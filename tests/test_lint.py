"""graftlint self-tests: one positive + one negative fixture per rule,
suppression semantics, CLI contract, and the repo-wide clean gate.

The fixtures under ``tests/lint_fixtures/`` are PARSED, never imported —
graftlint is pure-ast. The positive env-at-trace fixture reproduces the
pre-PR-3 ``models/layers.py`` QUIVER_COUNTS pattern verbatim in miniature
(acceptance criterion: the shipped bug class is demonstrably caught)."""

import json
import os
import textwrap

import pytest

from quiver_tpu.tools.lint import RULES, lint_paths, main

HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURES = os.path.join(HERE, "lint_fixtures")
REPO = os.path.dirname(HERE)


def fx(name: str) -> str:
    return os.path.join(FIXTURES, name)


def rules_hit(result):
    return {f.rule for f in result.findings}


# -- per-rule fixtures (positive must fire, negative must stay clean) -------

def test_env_at_trace_fixtures():
    """The QUIVER_COUNTS bug class: env read inside a function called from
    a jitted model body fires; the resolve-once idiom does not."""
    pos = lint_paths([fx("env_at_trace_pos.py")])
    hits = [f for f in pos.findings if f.rule == "env-at-trace"]
    assert len(hits) == 1
    assert "os.environ.get" in hits[0].message
    assert "occurrence_counts" in hits[0].message  # the traced chain names

    neg = lint_paths([fx("env_at_trace_neg.py")])
    assert "env-at-trace" not in rules_hit(neg)


def test_axis_name_consistency_fixtures():
    pos = lint_paths([fx("axis_name_pos.py")])
    hits = [f for f in pos.findings if f.rule == "axis-name-consistency"]
    # psum("feature"), axis_index("features"), P("feature", ...),
    # mesh.shape["data"]
    assert len(hits) == 4
    unknown = [f for f in hits if "matches no declared mesh axis" in f.message]
    assert len(unknown) == 1 and "'features'" in unknown[0].message

    neg = lint_paths([fx("axis_name_neg.py")])
    assert "axis-name-consistency" not in rules_hit(neg)


def test_cond_branch_parity_fixtures():
    pos = lint_paths([fx("cond_parity_pos.py")])
    hits = [f for f in pos.findings if f.rule == "cond-branch-parity"]
    assert len(hits) == 1
    assert "mismatched structures" in hits[0].message

    neg = lint_paths([fx("cond_parity_neg.py")])
    assert "cond-branch-parity" not in rules_hit(neg)


def test_host_op_on_tracer_fixtures():
    pos = lint_paths([fx("host_op_pos.py")])
    hits = [f for f in pos.findings if f.rule == "host-op-on-tracer"]
    # int(x[0]), float(sum), range(len(xs)), x.item()
    assert len(hits) == 4
    assert any("unrolls" in f.message for f in hits)
    assert any(".item()" in f.message for f in hits)

    neg = lint_paths([fx("host_op_neg.py")])
    assert "host-op-on-tracer" not in rules_hit(neg)


def test_per_call_logging_fixtures():
    pos = lint_paths([fx("logging_pos.py")])
    hits = [f for f in pos.findings if f.rule == "per-call-logging-in-jit"]
    # print(), get_logger().info, logger.warning (traced via call graph)
    assert len(hits) == 3

    neg = lint_paths([fx("logging_neg.py")])
    assert "per-call-logging-in-jit" not in rules_hit(neg)


def _mini_pkg(tmp_path, exports, documented):
    pkg = tmp_path / "mypkg"
    pkg.mkdir(parents=True)
    body = "\n".join(f"{n} = None" for n in exports)
    (pkg / "__init__.py").write_text(
        f"{body}\n__all__ = {list(exports)!r}\n")
    docs = tmp_path / "docs"
    docs.mkdir()
    rows = "\n".join(f"| `{n}` | doc |" for n in documented)
    (docs / "API.md").write_text(f"# API index\n\n{rows}\n")
    return pkg / "__init__.py"


def test_export_doc_drift_fixtures(tmp_path):
    init = _mini_pkg(tmp_path, ["alpha", "beta", "gamma"], ["alpha", "beta"])
    pos = lint_paths([str(init)])
    hits = [f for f in pos.findings if f.rule == "export-doc-drift"]
    assert len(hits) == 1 and "'gamma'" in hits[0].message

    init2 = _mini_pkg(tmp_path / "ok", ["alpha", "beta"], ["alpha", "beta"])
    neg = lint_paths([str(init2)])
    assert "export-doc-drift" not in rules_hit(neg)


# -- suppressions ------------------------------------------------------------

def test_suppression_with_reason_silences(tmp_path):
    src = textwrap.dedent("""\
        import os
        import jax


        @jax.jit
        def step(x):
            # graftlint: disable=env-at-trace -- fixture: frozen by design
            flag = os.environ.get("FLAG", "0")
            return x if flag == "0" else -x
    """)
    p = tmp_path / "m.py"
    p.write_text(src)
    res = lint_paths([str(p)])
    assert not res.findings
    assert len(res.suppressed) == 1
    assert res.suppressed[0].rule == "env-at-trace"
    assert res.exit_code == 0


def test_suppression_without_reason_is_a_finding(tmp_path):
    src = textwrap.dedent("""\
        import os
        import jax


        @jax.jit
        def step(x):
            flag = os.environ.get("FLAG")  # graftlint: disable=env-at-trace
            return x if flag else -x
    """)
    p = tmp_path / "m.py"
    p.write_text(src)
    res = lint_paths([str(p)])
    rules = [f.rule for f in res.findings]
    # the reasonless suppression is rejected AND the original finding stands
    assert "bad-suppression" in rules
    assert "env-at-trace" in rules
    assert res.exit_code == 1


def test_suppression_unknown_rule_is_a_finding(tmp_path):
    p = tmp_path / "m.py"
    p.write_text("# graftlint: disable=not-a-rule -- whatever\nx = 1\n")
    res = lint_paths([str(p)])
    assert [f.rule for f in res.findings] == ["bad-suppression"]
    assert "unknown rule" in res.findings[0].message


def test_eager_pin_requires_reason(tmp_path):
    src = textwrap.dedent("""\
        import os
        import jax


        # graftlint: eager
        def tuner(store):
            return os.environ.get("K")


        @jax.jit
        def step(x, store):
            tuner(store)
            return x
    """)
    p = tmp_path / "m.py"
    p.write_text(src)
    res = lint_paths([str(p)])
    rules = [f.rule for f in res.findings]
    # reasonless pin rejected -> pin inactive -> env finding stands too
    assert "bad-suppression" in rules and "env-at-trace" in rules
    # with a reason, the pin is a trace barrier
    p.write_text(src.replace("# graftlint: eager",
                             "# graftlint: eager -- eager-only tuner"))
    res = lint_paths([str(p)])
    assert not res.findings


def test_parse_error_is_a_finding(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    res = lint_paths([str(p)])
    assert [f.rule for f in res.findings] == ["parse-error"]
    assert res.exit_code == 1


# -- CLI contract ------------------------------------------------------------

def test_cli_json_and_exit_codes(tmp_path, capsys):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert main([str(clean), "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["version"] == 2 and out["findings"] == []

    assert main([fx("host_op_pos.py"), "--json"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["counts"]["host-op-on-tracer"] == 4
    assert {f["rule"] for f in out["findings"]} == {"host-op-on-tracer"}

    # usage errors are exit 2, distinct from findings
    assert main([str(tmp_path / "missing_dir")]) == 2
    assert main([str(clean), "--select", "bogus-rule"]) == 2


def test_cli_select_and_ignore(capsys):
    assert main([fx("host_op_pos.py"), "--select", "env-at-trace"]) == 0
    capsys.readouterr()
    assert main([fx("host_op_pos.py"), "--ignore", "host-op-on-tracer"]) == 0
    capsys.readouterr()


def test_list_rules_covers_registry(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in RULES:
        assert rule in out


# -- the merge gate: the repo itself lints clean -----------------------------

def test_repo_lints_clean():
    """Acceptance criterion: ``python -m quiver_tpu.tools.lint quiver_tpu/
    scripts/ benchmarks/`` exits 0 on the merged tree, with every
    suppression carrying a reason (reasonless ones surface as
    bad-suppression findings and fail this)."""
    res = lint_paths([os.path.join(REPO, d)
                      for d in ("quiver_tpu", "scripts", "benchmarks")])
    assert res.findings == [], [
        f"{f.path}:{f.line}: {f.rule}: {f.message}" for f in res.findings
    ]
    # the tree exercises the suppression machinery for real
    assert res.suppressed, "expected reasoned suppressions in the tree"
