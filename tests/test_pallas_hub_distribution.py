"""Distributional test for the windowed Pallas sampler on hub rows.

The windowed kernel (ops/pallas/sample.py) is exact for rows with
deg <= window, but hub rows (deg > window) sample from a uniformly-placed
contiguous window. VERDICT r4 flagged that this branch had never been
exercised distributionally — and the power-law tail is exactly where cache
policy concentrates reads. This test pins the hub branch to its analytic
model and quantifies the deviation from the exact XLA sampler.

Analytic model (deg > window): with T = deg - window + 1 uniform window
placements and an exactly-uniform k/window in-window marginal (the
stratify+rotate construction, tested in test_sampler_distribution), slot
p's inclusion probability is

    P(p) = n(p)/T * k/window,   n(p) = min(p, T-1) - max(p-window+1, 0) + 1

i.e. interior slots (window-1 <= p <= T-1) are boosted by deg/T over the
exact sampler's k/deg, and the first/last window-1 slots attenuate linearly
toward n(0)/T * k/window at the row ends.

Policy (documented here and in ops/pallas/sample.py): the attenuation is
ACCEPTED. kernel='pallas' is an explicit opt-in whose hub-row marginals are
near-uniform only when window << deg or deg >> window is rare (the default
window 2048 covers >99.9% of power-law rows exactly); the XLA path stays
the exactness reference and the default. Reference exactness standard:
torch-quiver cuda_random.cu.hpp:41-57 (reservoir, exact at any degree).
"""

import numpy as np
import pytest

from quiver_tpu import CSRTopo

DEG = 256  # hub degree
WINDOW = 64
K = 8
TRIALS = 8192  # rows per batch x batches
ROWS = 1024
T = DEG - WINDOW + 1  # 193 window placements


@pytest.fixture(scope="module")
def hub_topo():
    # node 0 is the hub: neighbors 1..DEG in CSR order, so sampled neighbor
    # id - 1 IS the CSR slot position (the quantity the model is over)
    indptr = np.zeros(DEG + 2, dtype=np.int64)
    indptr[1:] = DEG
    indices = np.arange(1, DEG + 1, dtype=np.int64)
    return CSRTopo(indptr=indptr, indices=indices)


def _analytic_marginal():
    p = np.arange(DEG)
    n = np.minimum(p, T - 1) - np.maximum(p - WINDOW + 1, 0) + 1
    return n / T * (K / WINDOW)


@pytest.fixture(scope="module")
def windowed_counts(hub_topo):
    import jax
    import jax.numpy as jnp

    from quiver_tpu.ops.pallas.sample import sample_layer_windowed

    seeds = jnp.zeros(ROWS, dtype=jnp.int32)
    counts = np.zeros(DEG, dtype=np.int64)
    key = jax.random.PRNGKey(7)
    for _ in range(TRIALS // ROWS):
        key, sub = jax.random.split(key)
        nbr, cnt = sample_layer_windowed(
            hub_topo, seeds, ROWS, K, sub, window=WINDOW, interpret=True
        )
        nbr = np.asarray(nbr)
        assert np.all(np.asarray(cnt) == K)
        # every draw valid and per-row distinct (distinct CSR slots)
        assert nbr.min() >= 1 and nbr.max() <= DEG
        assert all(len(set(r.tolist())) == K for r in nbr)
        np.add.at(counts, nbr.ravel() - 1, 1)
    return counts


def test_hub_marginals_match_analytic_model(windowed_counts):
    counts = windowed_counts
    exp = _analytic_marginal() * TRIALS
    assert counts.sum() == TRIALS * K
    # per-slot: 5-sigma binomial band (distinct-slot draws within a row are
    # negatively correlated, so the independent-binomial sigma is an upper
    # bound on the true one)
    sigma = np.sqrt(TRIALS * _analytic_marginal() * (1 - _analytic_marginal()))
    dev = np.abs(counts - exp)
    worst = int(np.argmax(dev - 5 * sigma - 3))
    assert np.all(dev <= 5 * sigma + 3), (
        f"slot {worst}: observed {counts[worst]}, expected {exp[worst]:.1f}"
    )
    # aggregate shape: the interior mass must match the model's boosted
    # level (deg/T over uniform), clearly separated from the flat
    # k/deg the exact sampler would give (model 0.6736 vs flat 0.5078)
    interior = slice(WINDOW - 1, T)
    frac = counts[interior].sum() / (TRIALS * K)
    model_frac = _analytic_marginal()[interior].sum() / K
    assert abs(frac - model_frac) < 0.02
    # boundary attenuation is real: the end slots see ~T/deg of the flat
    # rate; slot 0's expectation is ~5.3 draws vs 256 flat
    assert counts[0] < 40 and counts[-1] < 40


def test_hub_deviation_from_exact_sampler_is_bounded(windowed_counts):
    """Total-variation distance to the exact (flat k/deg) marginal equals
    the analytic TV of the window scheme — the accepted-policy bound."""
    counts = windowed_counts
    emp = counts / counts.sum()  # normalized draw distribution over slots
    flat = np.full(DEG, 1.0 / DEG)
    model = _analytic_marginal() / K
    tv_emp = 0.5 * np.abs(emp - flat).sum()
    tv_model = 0.5 * np.abs(model - flat).sum()
    # empirical TV within noise of the analytic TV, and both far below 1
    assert abs(tv_emp - tv_model) < 0.03
    assert tv_model < 0.25  # deg/window = 4: worst-case-ish config
    # with the production window (2048) and the same deg/window ratio the
    # bound is identical — the policy accepts exactly this much skew on
    # hub rows, nothing more
