"""Capped-bucket routed gather: differential tests on the 8-device mesh.

The comm-volume fix (VERDICT r5 weak #3): destination buckets capped at
ceil(alpha*L/F) lanes so each all_to_all hop moves ~alpha*L lanes instead
of F*L. Parity bar (ISSUE 1): bit-identical to the uncapped path on
non-overflow workloads, still-correct (fallback-served) under adversarial
skew, overflow observable as batch metadata. Oracle: the dense table.
"""

import logging

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.feature.shard import ShardedFeature, ShardedTensor
from quiver_tpu.models.sage import GraphSAGE
from quiver_tpu.parallel.mesh import make_mesh
from quiver_tpu.parallel.trainer import DistributedTrainer


def _table(n=800, f=12, seed=0):
    return np.random.default_rng(seed).normal(size=(n, f)).astype(np.float32)


def test_capped_bit_identical_to_uncapped_no_overflow():
    """Spread ids (every shard hit roughly evenly) with the default alpha:
    zero overflow, and capped output must equal uncapped BIT-FOR-BIT."""
    mesh = make_mesh(data=2, feature=4)
    t = _table()
    st = ShardedTensor(mesh, kernel="xla").from_cpu_tensor(t)
    rng = np.random.default_rng(1)
    for n in (64, 301, 777):
        ids = rng.integers(0, t.shape[0], n).astype(np.int32)
        uncapped = np.asarray(st.gather(jnp.asarray(ids), routed=True,
                                        routed_cap=None))
        capped = np.asarray(st.gather(jnp.asarray(ids), routed=True))
        assert np.array_equal(uncapped, t[ids])
        assert np.array_equal(capped, uncapped)  # bit-identical


def test_capped_explicit_cap_and_invalid_lanes():
    """Explicit per-bucket capacity + -1 sentinel lanes: invalid lanes
    return zero rows and never eat bucket capacity."""
    mesh = make_mesh(data=2, feature=4)
    t = _table()
    st = ShardedTensor(mesh, kernel="xla").from_cpu_tensor(t)
    ids = np.concatenate([
        np.random.default_rng(2).integers(0, t.shape[0], 90),
        [-1] * 6,
    ]).astype(np.int32)
    out = np.asarray(st.gather(jnp.asarray(ids), routed=True, routed_cap=8))
    assert np.array_equal(out[:90], t[ids[:90]])
    assert np.all(out[90:] == 0)


def test_forced_overflow_served_by_fallback():
    """Adversarial skew — every id owned by shard 0 and a tiny cap: the
    buckets overflow massively, the fallback serves the overflowed lanes
    exactly, and the count is observable as batch metadata."""
    mesh = make_mesh(data=2, feature=4)
    t = _table()
    st = ShardedTensor(mesh, kernel="xla").from_cpu_tensor(t)
    rng = np.random.default_rng(3)
    # rows_per_shard = 200: ids < 200 all live on shard 0
    ids = rng.integers(0, st.rows_per_shard, 256).astype(np.int32)
    out = np.asarray(st.gather(jnp.asarray(ids), routed=True, routed_cap=4))
    assert np.array_equal(out, t[ids])  # fallback-served, still exact
    ov = int(st.last_routed_overflow)
    # per device: 32 lanes, bucket 0 keeps 4 => 28 overflow x 8 devices
    assert ov == 8 * (32 - 4)


def test_no_overflow_on_clean_batch_metadata_zero():
    mesh = make_mesh(data=2, feature=4)
    t = _table()
    st = ShardedTensor(mesh, kernel="xla").from_cpu_tensor(t)
    # round-robin over the 4 owning shards: every device's 32-lane slice
    # sends 8 requests per bucket, well under cap=ceil(2*32/4)=16
    lanes = np.arange(256)
    ids = ((lanes % 4) * st.rows_per_shard
           + (lanes // 4) % st.rows_per_shard).astype(np.int32)
    out = np.asarray(st.gather(jnp.asarray(ids), routed=True))
    assert np.array_equal(out, t[ids])
    assert int(st.last_routed_overflow) == 0


def test_auto_tuner_grows_alpha_until_overflow_stops():
    """gather(routed_cap="auto") doubles routed_alpha on the call AFTER an
    overflowed batch, saturating at alpha=F (the uncapped program)."""
    mesh = make_mesh(data=2, feature=4)
    t = _table()
    st = ShardedTensor(mesh, kernel="xla").from_cpu_tensor(t)
    st.routed_alpha = 1.0
    ids = np.random.default_rng(4).integers(
        0, st.rows_per_shard, 256).astype(np.int32)  # all on shard 0
    out = np.asarray(st.gather(jnp.asarray(ids), routed=True))
    assert np.array_equal(out, t[ids])
    assert int(st.last_routed_overflow) > 0
    out = np.asarray(st.gather(jnp.asarray(ids), routed=True))
    assert np.array_equal(out, t[ids])
    assert st.routed_alpha == 2.0  # grew after the overflowed batch
    out = np.asarray(st.gather(jnp.asarray(ids), routed=True))
    assert np.array_equal(out, t[ids])
    assert st.routed_alpha == 4.0  # == F: cap == L, uncapped program
    assert int(st.last_routed_overflow) == 0


def test_routed_cap_planning():
    mesh = make_mesh(data=2, feature=4)
    st = ShardedTensor(mesh)
    assert st.routed_cap(128) == 64  # ceil(2*128/4)
    assert st.routed_cap(128, alpha=1.0) == 32
    assert st.routed_cap(128, alpha=100.0) == 128  # clamped to L
    assert st.routed_cap(2, alpha=0.001) == 1  # never below 1
    with pytest.raises(ValueError):
        st.routed_cap(128, alpha=0)


def test_sharded_feature_capped_with_reorder_and_skew():
    """ShardedFeature: feature_order translation (degree reorder
    concentrates hot ids on shard 0 — the REAL skew source) through the
    capped routed gather, exact vs the dense oracle."""
    rng = np.random.default_rng(5)
    ei = np.stack([rng.integers(0, 400, 3000), rng.integers(0, 400, 3000)])
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    feat = rng.normal(size=(n, 8)).astype(np.float32)
    mesh = make_mesh(data=2, feature=4)
    store = ShardedFeature(mesh, device_cache_size="1G", csr_topo=topo,
                           routed_alpha=1.0).from_cpu_tensor(feat)
    # degree-skewed draw: the sampler's access law, hits shard 0 hardest
    deg = topo.degree.astype(np.float64)
    ids = rng.choice(n, size=96, p=deg / deg.sum()).astype(np.int32)
    a = np.asarray(store[jnp.asarray(ids)])
    b = np.asarray(store.gather(jnp.asarray(ids), routed=True))
    assert np.array_equal(a, feat[ids])
    assert np.array_equal(b, a)
    assert int(store.last_routed_overflow) >= 0  # observable either way


def test_sharded_feature_int8_capped_routed_dequant():
    """int8 rows through capped routing + forced overflow must dequantize
    identically to the psum gather (fallback carries int8 codes too)."""
    rng = np.random.default_rng(8)
    ei = np.stack([rng.integers(0, 300, 2000), rng.integers(0, 300, 2000)])
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    feat = rng.normal(size=(n, 16)).astype(np.float32)
    mesh = make_mesh(data=2, feature=4)
    store = ShardedFeature(mesh, device_cache_size="1G", csr_topo=topo,
                           dtype="int8").from_cpu_tensor(feat)
    hot_rows = store.hot.rows_per_shard  # force everything onto shard 0
    ids = rng.integers(0, min(hot_rows, n), 64).astype(np.int32)
    a = np.asarray(store[jnp.asarray(ids)])
    b = np.asarray(store.gather(jnp.asarray(ids), routed=True, routed_cap=2))
    assert np.array_equal(a, b)


@pytest.mark.slow  # IR-proven fast: graftaudit collective-parity +
# comm-budget walk the capped gather's lowered fallback cond and lane
# shapes every tier-1 run (tests/test_audit.py); this execution
# differential stays as the slow-lane end-to-end witness
def test_trainer_capped_loss_bit_identical_and_overflow_observable():
    """DistributedTrainer(seed_sharding="all"): the capped-bucket gather
    must not change the training math at all — losses bit-identical to the
    uncapped trainer on the same seeds/keys — and the per-step overflow
    count must surface via last_routed_overflow."""
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 4, 400)
    feat = np.eye(4, dtype=np.float32)[labels] * 2.0
    feat += rng.normal(scale=0.8, size=(400, 4)).astype(np.float32)
    ei = np.stack([rng.integers(0, 400, 4000), rng.integers(0, 400, 4000)])
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    mesh = make_mesh(data=2, feature=4)
    labels_dev = jnp.asarray(labels[:n].astype(np.int32))
    model = GraphSAGE(hidden=16, num_classes=4, num_layers=2)

    losses = {}
    for alpha in (None, 1.0):  # alpha=1: tightest cap, likeliest overflow
        sampler = GraphSageSampler(topo, [5, 5], seed=3)
        feature = ShardedFeature(
            mesh, device_cache_size="1G", csr_topo=topo
        ).from_cpu_tensor(feat[:n])
        trainer = DistributedTrainer(
            mesh, sampler, feature, model, optax.adam(5e-3), local_batch=32,
            seed_sharding="all", routed_alpha=alpha,
        )
        params, opt = trainer.init(jax.random.PRNGKey(0))
        srng = np.random.default_rng(0)
        ls = []
        for step in range(3):
            seeds = srng.integers(0, n, trainer.global_batch)
            params, opt, loss = trainer.step(
                params, opt, seeds, labels_dev, jax.random.PRNGKey(step)
            )
            ov = int(trainer.last_routed_overflow)
            assert ov == 0 if alpha is None else ov >= 0
            ls.append(float(loss))
        losses[alpha] = ls
    assert losses[None] == losses[1.0], losses  # bit-identical trajectories


def test_trainer_epoch_scan_overflow_vector():
    """epoch_scan surfaces a per-step overflow vector (batch metadata for
    the tuner/scoreboard)."""
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 4, 300)
    feat = rng.normal(size=(300, 6)).astype(np.float32)
    ei = np.stack([rng.integers(0, 300, 2500), rng.integers(0, 300, 2500)])
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    mesh = make_mesh(data=2, feature=4)
    sampler = GraphSageSampler(topo, [4, 3], seed=1)
    feature = ShardedFeature(
        mesh, device_cache_size="1G", csr_topo=topo
    ).from_cpu_tensor(feat[:n])
    model = GraphSAGE(hidden=8, num_classes=4, num_layers=2)
    trainer = DistributedTrainer(
        mesh, sampler, feature, model, optax.adam(5e-3), local_batch=16,
        seed_sharding="all", routed_alpha=1.0,
    )
    params, opt = trainer.init(jax.random.PRNGKey(0))
    seed_mat = trainer.pack_epoch(
        np.arange(3 * trainer.global_batch) % n, seed=0)
    params, opt, losses = trainer.epoch_scan(
        params, opt, seed_mat, jnp.asarray(labels[:n].astype(np.int32)),
        jax.random.PRNGKey(1),
    )
    ovs = np.asarray(trainer.last_routed_overflow)
    assert ovs.shape == (3,) and np.all(ovs >= 0)
    assert np.all(np.isfinite(np.asarray(losses)))


def test_bench_comm_model_reduction():
    """The benchmark's lanes-per-hop model: >= (F/alpha)x reduction at
    F=4 (acceptance criterion), exact bucket arithmetic."""
    import argparse

    from benchmarks.bench_feature import _routed_comm_model

    class _Store:
        pass

    class _Hot:
        num_shards = 4

        @staticmethod
        def routed_cap(length, alpha):
            st = ShardedTensor(make_mesh(data=2, feature=4))
            return st.routed_cap(length, alpha)

    store = _Store()
    store.hot = _Hot()
    args = argparse.Namespace(routed=True, routed_alpha=1.0,
                              gather_batch=4096)
    cap, model = _routed_comm_model(args, store)
    F, alpha = 4, 1.0
    assert model["lanes_per_hop_uncapped"] / model["lanes_per_hop"] >= F / alpha
    assert model["comm_reduction"] >= F / alpha
    assert cap == model["routed_cap"]
    # uncapped run still records the model (reduction 1.0)
    args = argparse.Namespace(routed=True, routed_alpha=0.0,
                              gather_batch=4096)
    cap, model = _routed_comm_model(args, store)
    assert cap is None and model["comm_reduction"] == 1.0
