"""Online serving (quiver_tpu/serving) + the sampler compiled-cache LRU.

Fast lane: ladder-bucket math, deadline-batcher flush decisions and
determinism under a fake clock, bounded-queue backpressure, the bitwise
ladder==oracle parity differential at every bucket size and padded tail,
deadline-miss accounting, the stale-serve drill (streaming commit ->
VersionMismatchError -> refresh -> serve the mutated graph), the
embedding-refresher version drill, and the GraphSageSampler LRU bound.

Slow lane: an open-loop run on the real clock through the deadline
coalescer's own flush decisions.
"""

import numpy as np
import pytest

import jax

from quiver_tpu import (
    CSRTopo,
    DeltaBatch,
    Feature,
    GraphSageSampler,
    InferenceServer,
    ServeQueueFull,
    StreamingGraph,
    VersionMismatchError,
)
from quiver_tpu.models.sage import GraphSAGE
from quiver_tpu.parallel.train import empty_adjs, init_model
from quiver_tpu.serving import DeadlineBatcher, EmbeddingRefresher
from quiver_tpu.serving.coalesce import ladder_buckets


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += float(dt)


def _graph(n=240, e=1600, seed=0):
    rng = np.random.default_rng(seed)
    coo = rng.integers(0, n, size=(2, e)).astype(np.int64)
    return CSRTopo(edge_index=coo)


def _stack(topo, feature_dim=12, hidden=16, classes=5, sizes=(4, 3), seed=1):
    rng = np.random.default_rng(seed)
    x_all = rng.normal(size=(topo.node_count, feature_dim)).astype(np.float32)
    feat = Feature(device_cache_size="1G").from_cpu_tensor(x_all)
    sampler = GraphSageSampler(topo, list(sizes), seed=seed)
    model = GraphSAGE(hidden=hidden, num_classes=classes,
                      num_layers=len(sizes))
    adjs = empty_adjs(list(sizes), batch=4, node_count=topo.node_count)
    params = init_model(
        model, jax.random.PRNGKey(seed),
        np.zeros((adjs[0].size[0], feature_dim), np.float32), adjs,
    )
    return x_all, feat, sampler, model, params


@pytest.fixture(scope="module")
def served():
    """One warm max_batch=4 server shared by the fast serving tests."""
    topo = _graph()
    _x, feat, sampler, model, params = _stack(topo)
    clock = FakeClock()
    server = InferenceServer(sampler, model, params, feat,
                             max_batch=4, clock=clock, seed=3)
    server.warmup()
    return server, clock


# -- ladder buckets ----------------------------------------------------------


def test_ladder_buckets():
    assert ladder_buckets(1) == (1,)
    assert ladder_buckets(8) == (1, 2, 4, 8)
    for bad in (0, 3, 6, -4):
        with pytest.raises(ValueError):
            ladder_buckets(bad)


# -- deadline batcher (no jax; pure host logic under a fake clock) -----------


def test_batcher_flush_decisions():
    clock = FakeClock()
    b = DeadlineBatcher(buckets=(1, 2, 4), default_deadline_s=1.0,
                        budget_fraction=0.5, clock=clock)
    # a full top bucket flushes regardless of deadlines
    for n in range(4):
        b.submit(n)
    assert b.ready()
    reqs, bucket = b.pop()
    assert bucket == 4 and [r.node for r in reqs] == [0, 1, 2, 3]
    # a partial bucket waits until the oldest burns its queue-wait budget
    b.submit(7)
    assert not b.ready() and b.pop() is None
    clock.advance(0.49)
    assert not b.ready()
    clock.advance(0.01)  # 0.5 = budget_fraction * deadline
    assert b.ready()
    reqs, bucket = b.pop()
    assert bucket == 1 and reqs[0].node == 7
    # force flushes a partial bucket immediately (closed-loop drain)
    b.submit(8)
    b.submit(9)
    b.submit(10)
    reqs, bucket = b.pop(force=True)
    assert bucket == 4 and len(reqs) == 3  # smallest bucket holding 3


def test_batcher_determinism_under_fake_clock():
    """Same arrival sequence on a fake clock -> same packing decisions."""
    script = [(0.000, 5), (0.004, 9), (0.004, 2), (0.030, 11), (0.040, 3)]

    def run():
        clock = FakeClock()
        b = DeadlineBatcher(buckets=(1, 2, 4), default_deadline_s=0.05,
                            budget_fraction=0.5, clock=clock)
        out, t = [], 0.0
        for dt, node in script:
            clock.advance(dt)
            t += dt
            b.submit(node)
            while b.ready():
                reqs, bucket = b.pop()
                out.append((round(t, 6), bucket,
                            tuple((r.node, r.seq) for r in reqs)))
        clock.advance(1.0)
        while b.depth:
            reqs, bucket = b.pop()
            out.append(("drain", bucket,
                        tuple((r.node, r.seq) for r in reqs)))
        return out

    assert run() == run()


def test_batcher_backpressure_and_validation():
    b = DeadlineBatcher(buckets=(1, 2), max_queue=4, clock=FakeClock())
    for n in range(4):
        b.submit(n)
    with pytest.raises(ServeQueueFull):
        b.submit(99)
    with pytest.raises(ValueError):
        b.submit(0, deadline_s=0.0)
    with pytest.raises(ValueError):
        DeadlineBatcher(buckets=(2, 1))
    with pytest.raises(ValueError):
        DeadlineBatcher(buckets=(3,))
    with pytest.raises(ValueError):
        DeadlineBatcher(buckets=(1, 2), max_queue=1)
    with pytest.raises(ValueError):
        DeadlineBatcher(budget_fraction=0.0)


# -- serving parity ----------------------------------------------------------


def test_serve_parity_every_bucket_and_padded_tail(served):
    """Ladder responses are BITWISE equal to the direct single-query
    oracle at every bucket size, including padded tails — a response is a
    function of (node, seq) alone, not of its co-batched neighbors."""
    server, _clock = served
    compiles_after_warmup = server.recompiles
    rng = np.random.default_rng(0)
    n = server.sampler.csr_topo.node_count
    for group in (1, 2, 3, 4):  # buckets 1, 2, 4 (padded), 4 (full)
        reqs = server.serve(rng.integers(0, n, group))
        assert len(reqs) == group
        for r in reqs:
            assert r.done and r.result.ndim == 1
            np.testing.assert_array_equal(
                r.result, server.oracle(r.node, r.seq)
            )
    # the steady-state contract: replay only, zero recompiles
    assert server.recompiles == compiles_after_warmup
    assert server.stats()["requests"] >= 10


def test_deadline_miss_accounting(served):
    server, clock = served
    misses0 = server.stats()["deadline_misses"]
    r_hit = server.submit(1, deadline_s=1000.0)
    r_miss = server.submit(2, deadline_s=0.01)
    clock.advance(0.5)  # r_miss is past its deadline before the flush
    done = server.pump(force=True)
    assert {id(r) for r in done} == {id(r_hit), id(r_miss)}
    assert r_hit.missed is False and r_miss.missed is True
    assert r_miss.latency_s() >= 0.5
    stats = server.stats()
    assert stats["deadline_misses"] == misses0 + 1
    assert set(InferenceServer.STAGES) <= set(stats["stages"])


def test_two_servers_bitwise_identical(served):
    """Same seed + same admission sequence -> bitwise-identical responses
    and identical packing, across two independently compiled servers."""
    server, _clock = served
    s = server.sampler
    mk = lambda: InferenceServer(  # noqa: E731
        s, server.model, server.params, server.feature,
        buckets=(2,), clock=FakeClock(), seed=11,
    )
    a, b = mk(), mk()
    nodes = [3, 17, 4, 4]
    out_a = a.serve(nodes)
    out_b = b.serve(nodes)
    for ra, rb in zip(out_a, out_b):
        assert (ra.node, ra.seq) == (rb.node, rb.seq)
        np.testing.assert_array_equal(ra.result, rb.result)


# -- stale-serve drill -------------------------------------------------------


def test_stale_serve_drill():
    """Commit a DeltaBatch -> every serve path raises -> refresh() ->
    the server serves the mutated graph (and still matches its oracle)."""
    topo = _graph(n=60, e=400, seed=4)
    _x, feat, sampler, model, params = _stack(topo, sizes=(3, 2), seed=4)
    server = InferenceServer(sampler, model, params, feat,
                             max_batch=1, clock=FakeClock(), seed=5)
    server.warmup()
    before = server.serve([7])[0]
    np.testing.assert_array_equal(before.result, server.oracle(7, before.seq))

    sg = StreamingGraph(topo)
    src = np.repeat(np.arange(topo.node_count), topo.degree)
    dst = np.asarray(topo.indices)[: src.size]
    live = set((src * topo.node_count + dst).tolist())
    k = next(k for k in range(topo.node_count ** 2) if k not in live)
    assert sg.ingest(DeltaBatch(edge_inserts=np.array(
        [[k // topo.node_count], [k % topo.node_count]])))
    sg.commit()

    with pytest.raises(VersionMismatchError):
        server.pump(force=True)
    with pytest.raises(VersionMismatchError):
        server.warmup()
    with pytest.raises(VersionMismatchError):
        server.oracle(7, 0)

    compiles = server.recompiles
    server.refresh()
    # a mutation epoch pays its recompiles at the commit boundary
    assert server.recompiles > compiles
    after = server.serve([7])[0]
    assert after.done
    np.testing.assert_array_equal(after.result, server.oracle(7, after.seq))


# -- embedding refresher -----------------------------------------------------


def test_embedding_refresher_version_drill():
    topo = _graph(n=60, e=400, seed=6)
    x, _feat, _sampler, model, params = _stack(topo, sizes=(3, 2), seed=6)
    r = EmbeddingRefresher(model, params, topo, x)
    with pytest.raises(VersionMismatchError):
        r.lookup([0])  # no table published yet
    v0 = r.refresh()
    assert r.version == v0 and r.refreshes == 1
    rows = r.lookup([0, 5, 59])
    assert rows.shape == (3, 5)

    sg = StreamingGraph(topo)
    src = int(np.repeat(np.arange(topo.node_count), topo.degree)[0])
    dst = int(np.asarray(topo.indices)[
        int(np.asarray(topo.indptr, dtype=np.int64)[src])])
    assert sg.ingest(DeltaBatch(edge_deletes=np.array([[src], [dst]])))
    sg.commit()

    with pytest.raises(VersionMismatchError):
        r.lookup([0])
    v1 = r.refresh()
    assert v1 > v0 and r.refreshes == 2
    assert r.lookup([0, 5, 59]).shape == (3, 5)


# -- sampler compiled-cache LRU (satellite) ----------------------------------


def test_sampler_compiled_cache_lru():
    topo = _graph(n=60, e=400, seed=8)
    s = GraphSageSampler(topo, [3, 2], compiled_cache_size=2)
    run8, _ = s._compiled(8)
    run16, _ = s._compiled(16)
    assert len(s._compiled_cache) == 2 and s.compiled_cache_evictions == 0
    # a hit returns the SAME program object and refreshes recency
    assert s._compiled(8)[0] is run8
    s._compiled(24)  # evicts 16 (least recent), not the just-touched 8
    assert len(s._compiled_cache) == 2 and s.compiled_cache_evictions == 1
    assert s._compiled(8)[0] is run8
    assert s._compiled(16)[0] is not run16  # rebuilt after eviction
    assert s.compiled_cache_evictions >= 2
    with pytest.raises(ValueError):
        GraphSageSampler(topo, [3, 2], compiled_cache_size=0)


# -- open loop on the real clock --------------------------------------------


@pytest.mark.slow
def test_open_loop_real_clock():
    """Fixed-rate arrivals on the real clock, flushes decided by the
    coalescer itself — all requests complete, within deadline, with zero
    steady-state recompiles."""
    import time

    topo = _graph()
    _x, feat, sampler, model, params = _stack(topo)
    server = InferenceServer(sampler, model, params, feat, max_batch=4,
                             default_deadline_s=5.0, seed=9)
    server.warmup()
    server.serve([0, 1, 2, 3])  # flush first-touch costs
    compiles = server.recompiles
    rng = np.random.default_rng(9)
    reqs, done = [], []
    for node in rng.integers(0, topo.node_count, 32):
        reqs.append(server.submit(int(node)))
        time.sleep(0.002)
        if server.batcher.ready():
            done += server.pump()
    while server.batcher.depth:
        done += server.pump(force=True)
    assert len(done) == 32 and all(r.done for r in reqs)
    assert sum(r.missed for r in done) == 0
    assert server.recompiles == compiles
    for r in done[::7]:
        np.testing.assert_array_equal(r.result, server.oracle(r.node, r.seq))
