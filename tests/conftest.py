"""Test configuration: force an 8-device virtual CPU mesh.

The image's sitecustomize registers the axon TPU plugin at interpreter
startup and pins jax to it, so an env-var override is too late by the time
conftest runs; ``jax.config.update`` after import still works because backend
initialization is lazy. XLA_FLAGS must be set before the first backend touch.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_info_once():
    """Each test starts with a clean ``info_once`` memory — otherwise
    one-shot log state leaks across tests in the same process and
    log-assertion tests become order-dependent."""
    from quiver_tpu.utils.trace import reset_once

    reset_once()
    yield
