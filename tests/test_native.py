"""Native host runtime tests: differential against numpy (CSR builder,
row gather) and validity oracle (reservoir sampler) — the same pattern the
reference uses for its CPU tier (test_quiver_cpu.cpp:9-75)."""

import numpy as np
import pytest

from quiver_tpu import native
from quiver_tpu.core.topology import CSRTopo

pytestmark = pytest.mark.skipif(
    not native.available, reason="native toolchain unavailable"
)


def test_csr_from_coo_matches_numpy():
    rng = np.random.default_rng(0)
    n, e = 200, 2000
    rows = rng.integers(0, n, e)
    cols = rng.integers(0, n, e)
    indptr, indices, eid = native.csr_from_coo(rows, cols, n)
    # indptr identical to bincount-cumsum
    expect_ptr = np.zeros(n + 1, np.int64)
    np.cumsum(np.bincount(rows, minlength=n), out=expect_ptr[1:])
    assert np.array_equal(indptr, expect_ptr)
    # stable: slots within a row follow COO order exactly (not just as a
    # multiset) — the cross-host determinism guarantee
    for v in range(n):
        lo, hi = indptr[v], indptr[v + 1]
        assert indices[lo:hi].tolist() == cols[rows == v].tolist()
    assert np.array_equal(rows[eid], np.repeat(np.arange(n), np.diff(indptr)))
    assert np.array_equal(cols[eid], indices)
    # and deterministic across repeated builds
    indptr2, indices2, eid2 = native.csr_from_coo(rows, cols, n)
    assert np.array_equal(indices, indices2) and np.array_equal(eid, eid2)


def test_csr_int32_entry_point():
    rng = np.random.default_rng(1)
    rows = rng.integers(0, 50, 300).astype(np.int32)
    cols = rng.integers(0, 50, 300).astype(np.int32)
    indptr, indices, eid = native.csr_from_coo(rows, cols, 50)
    assert indptr[-1] == 300
    assert np.array_equal(cols[eid], indices)


def test_gather_rows_matches_numpy():
    rng = np.random.default_rng(2)
    table = rng.normal(size=(500, 64)).astype(np.float32)
    ids = rng.integers(0, 500, 200)
    out = native.gather_rows(table, ids)
    assert np.array_equal(out, table[ids])


def test_gather_rows_sentinels():
    table = np.arange(20, dtype=np.float32).reshape(10, 2)
    out = native.gather_rows(table, np.array([3, -1, 9, 100]))
    assert np.array_equal(out[0], table[3])
    assert np.all(out[1] == 0)
    assert np.array_equal(out[2], table[9])
    assert np.all(out[3] == 0)  # out of range -> zero row, not UB


def test_gather_rows_dtypes():
    for dtype in (np.float32, np.float64, np.int32):
        table = np.arange(24).reshape(6, 4).astype(dtype)
        out = native.gather_rows(table, np.array([5, 0]))
        assert np.array_equal(out, table[[5, 0]])


def test_native_sampler_validity():
    rng = np.random.default_rng(3)
    n, e = 100, 1500
    rows = rng.integers(0, n, e)
    cols = rng.integers(0, n, e)
    indptr, indices, _ = native.csr_from_coo(rows, cols, n)
    seeds = rng.integers(0, n, 64).astype(np.int32)
    k = 5
    out, counts = native.sample_neighbors(indptr, indices, seeds, k, seed=7)
    for i, s in enumerate(seeds):
        deg = indptr[s + 1] - indptr[s]
        assert counts[i] == min(deg, k)
        row = set(indices[indptr[s]:indptr[s + 1]].tolist())
        got = out[i][out[i] >= 0]
        assert len(got) == counts[i]
        assert set(got.tolist()) <= row
        if deg > k:
            # reservoir samples distinct positions
            assert len(got) == k
    # padding seed
    out, counts = native.sample_neighbors(indptr, indices, np.array([-1], np.int32), k)
    assert counts[0] == 0 and np.all(out[0] == -1)


def test_csrtopo_uses_native_builder():
    rng = np.random.default_rng(4)
    ei = np.stack([rng.integers(0, 30, 200), rng.integers(0, 30, 200)])
    t_native = CSRTopo(edge_index=ei, use_native=True)
    t_numpy = CSRTopo(edge_index=ei, use_native=False)
    assert np.array_equal(t_native.indptr, t_numpy.indptr)
    # both builders are stable, so the arrays are byte-identical
    assert np.array_equal(t_native.indices, t_numpy.indices)


def test_csrtopo_rejects_negative_ids():
    ei = np.array([[0, 1, -1], [1, 2, 0]])
    with pytest.raises(ValueError, match="negative"):
        CSRTopo(edge_index=ei)


def test_native_reindex_matches_xla_masked_unique():
    """Differential: native hash reindex == XLA sort-based masked_unique
    (same first-occurrence order, forced seed lanes, -1 handling)."""
    import jax.numpy as jnp

    from quiver_tpu.ops.reindex import reindex_layer

    if not native.available:
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(7)
    S, K = 40, 6
    seeds = rng.integers(0, 50, S).astype(np.int32)
    seeds[35:] = -1  # padding tail
    nbr = rng.integers(0, 50, (S, K)).astype(np.int32)
    nbr[rng.random((S, K)) < 0.3] = -1
    nbr[35:] = -1  # no neighbors for padded seeds

    nf, ncol = native.reindex(seeds, nbr)

    cap = S * (K + 1)
    f, nfr, col, ov = reindex_layer(
        jnp.asarray(seeds), jnp.int32(35), jnp.asarray(nbr), cap
    )
    m = int(nfr)
    assert int(ov) == 0
    assert m == nf.shape[0]
    np.testing.assert_array_equal(np.asarray(f)[:m], nf)
    np.testing.assert_array_equal(np.asarray(col), ncol)


def test_native_reindex_duplicate_seeds_forced():
    if not native.available:
        pytest.skip("native library unavailable")
    seeds = np.array([7, 7, 3], np.int32)
    nbr = np.array([[7, 3], [9, -1], [7, 9]], np.int32)
    f, col = native.reindex(seeds, nbr)
    # both 7-lanes kept; neighbors resolve to FIRST occurrence (slot 0)
    np.testing.assert_array_equal(f, [7, 7, 3, 9])
    np.testing.assert_array_equal(col, [[0, 2], [3, -1], [0, 3]])
