"""Sharded-topology sampling differentials (ISSUE 3 tentpole).

Parity bar: a ``topo_sharding="mesh"`` sampler — the CSR partitioned across
the mesh's feature axis, per-hop frontier routing over capped-bucket
all_to_all — must be BIT-IDENTICAL to the replicated ``GraphSageSampler``
per worker block for the same seeds/PRNG keys, at every mesh width, with
and without forced bucket overflow (fallback-served lanes included). The
partition plan must shrink per-chip topology bytes ~1/F. End-to-end, a
``DistributedTrainer`` driving the dist sampler must reproduce the
replicated trainer's loss trajectory bit-for-bit (slow lane).
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.core.sharded_topology import ShardedTopology
from quiver_tpu.feature.shard import ShardedFeature
from quiver_tpu.models.sage import GraphSAGE
from quiver_tpu.parallel.mesh import make_mesh
from quiver_tpu.parallel.trainer import DistributedTrainer
from quiver_tpu.sampling.dist import DistGraphSageSampler, routed_sample_cap
from quiver_tpu.utils.graphgen import generate_pareto_graph


def _graph(n=400, deg=6.0, seed=0):
    return CSRTopo(edge_index=generate_pareto_graph(n, deg, seed=seed))


def _assert_worker_parity(dist, rep, seeds, key, seed_cap=32):
    """Each worker's dist SampleOutput must equal the replicated sampler's
    on that worker's seed block with key fold_in(key, worker)."""
    W = dist.workers
    outs = dist.sample_per_worker(seeds, key=key)
    run, _ = rep._compiled(seed_cap)
    for w, (o, blk) in enumerate(zip(outs, np.array_split(seeds, W))):
        padded = np.full(seed_cap, -1, np.int32)
        padded[: len(blk)] = blk
        n_id, _, adjs, _, _, _ = run(
            rep.topo, jnp.asarray(padded), jnp.int32(len(blk)),
            jax.random.fold_in(key, w),
        )
        assert np.array_equal(np.asarray(n_id), np.asarray(o.n_id)), (
            f"n_id diverged on worker {w}/{W}"
        )
        for l, (ra, da) in enumerate(zip(adjs, o.adjs)):
            assert np.array_equal(
                np.asarray(ra.edge_index), np.asarray(da.edge_index)
            ), f"edge_index diverged on worker {w} layer {l}"
            assert ra.size == da.size and ra.fanout == da.fanout


# -- partition plan ---------------------------------------------------------


def test_partition_plan_covers_csr_and_shrinks_bytes():
    """The row-range partition must cover the CSR exactly — every shard's
    rebased slice reconstructs the original — and per-chip bytes must
    shrink ~1/F vs the replicated placement (the acceptance criterion the
    dryrun asserts too)."""
    topo = _graph(n=500)
    mesh = make_mesh(data=1, feature=8)
    st = ShardedTopology(mesh, topo)
    plan = st.plan
    F, rps = plan["num_shards"], plan["rows_per_shard"]
    assert F == 8 and rps * F >= topo.node_count
    assert sum(plan["shard_edges"]) == topo.edge_count
    ip = np.asarray(st.indptr)
    ix = np.asarray(st.indices)
    gip = np.asarray(topo.indptr)
    gix = np.asarray(topo.indices)
    for d in range(F):
        lo, hi = min(d * rps, topo.node_count), min((d + 1) * rps,
                                                    topo.node_count)
        # rebased indptr reconstructs the global slice
        assert np.array_equal(
            ip[d, : hi - lo + 1] + gip[lo], gip[lo : hi + 1]
        )
        # padding rows stay degree-0
        assert np.all(ip[d, hi - lo:] == ip[d, hi - lo])
        e = plan["shard_edges"][d]
        assert np.array_equal(ix[d, :e], gix[gip[lo] : gip[lo] + e])
    assert plan["per_chip_bytes"] * F <= plan["replicated_bytes"] * 2, plan
    assert plan["shrink_factor"] >= F / 2


def test_routed_sample_cap_schedule():
    assert routed_sample_cap(128, 8, 2.0) == 32  # ceil(2*128/8)
    assert routed_sample_cap(128, 8, None) is None  # uncapped
    assert routed_sample_cap(128, 8, 100.0) is None  # cap >= L => uncapped
    assert routed_sample_cap(8, 8, 0.01) == 1  # floor at 1 lane
    with pytest.raises(ValueError):
        routed_sample_cap(128, 8, -1.0)


# -- bit-parity differentials ----------------------------------------------


def test_dist_parity_mesh8():
    """Full-width mesh (F=8): bit-identical to the replicated sampler for
    the same seeds/keys, telemetry surfaced."""
    topo = _graph(n=500)
    mesh = make_mesh(data=1, feature=8)
    dist = GraphSageSampler(topo, [4, 3], seed=7, seed_capacity=32,
                            dedup="sort", topo_sharding="mesh", mesh=mesh)
    assert isinstance(dist, DistGraphSageSampler)
    rep = GraphSageSampler(topo, [4, 3], seed=7, seed_capacity=32,
                           dedup="sort")
    seeds = np.random.default_rng(1).integers(
        0, topo.node_count, 32 * dist.workers - 5
    )
    _assert_worker_parity(dist, rep, seeds, jax.random.PRNGKey(3))
    ov = np.asarray(dist.last_sample_overflow)
    assert ov.shape == (2,) and np.all(ov >= 0)


def test_dist_parity_weighted_mesh2():
    """Weighted draws over the sharded path: the owner's inverse-CDF
    search against its routed prefix-weight segment is bit-identical to
    the replicated weighted sampler."""
    topo = _graph(n=500)
    topo.set_edge_weight(
        np.random.default_rng(5).random(topo.edge_count) + 0.1
    )
    mesh = make_mesh(n_devices=2, data=1, feature=2)
    dist = GraphSageSampler(topo, [4, 3], seed=7, seed_capacity=32,
                            dedup="sort", topo_sharding="mesh", mesh=mesh,
                            weighted=True)
    rep = GraphSageSampler(topo, [4, 3], seed=7, seed_capacity=32,
                           dedup="sort", weighted=True)
    seeds = np.random.default_rng(6).integers(0, topo.node_count, 61)
    _assert_worker_parity(dist, rep, seeds, jax.random.PRNGKey(11))


def test_dist_parity_temporal_mesh2():
    """Temporal windowed draws over the sharded path: owner-answered
    (first, deg_t) in-window slot ranges, bit-identical to the replicated
    time_window sampler."""
    topo = _graph(n=500)
    topo.set_edge_time(np.random.default_rng(8).random(topo.edge_count))
    mesh = make_mesh(n_devices=2, data=1, feature=2)
    win = (0.2, 0.8)
    dist = GraphSageSampler(topo, [4, 3], seed=7, seed_capacity=32,
                            dedup="sort", topo_sharding="mesh", mesh=mesh,
                            time_window=win)
    rep = GraphSageSampler(topo, [4, 3], seed=7, seed_capacity=32,
                           dedup="sort", time_window=win)
    seeds = np.random.default_rng(9).integers(0, topo.node_count, 61)
    _assert_worker_parity(dist, rep, seeds, jax.random.PRNGKey(13))


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["weighted", "temporal"])
@pytest.mark.parametrize("F", [1, 4, 8])
def test_dist_parity_attr_widths(kind, F):
    """Weighted/temporal differential at the wider mesh widths, capped
    tight enough to force routed overflow — the fallback must serve the
    attributed hops exactly too."""
    topo = _graph(n=500)
    kw = {}
    if kind == "weighted":
        topo.set_edge_weight(
            np.random.default_rng(5).random(topo.edge_count) + 0.1
        )
        kw["weighted"] = True
    else:
        topo.set_edge_time(np.random.default_rng(8).random(topo.edge_count))
        kw["time_window"] = (0.2, 0.8)
    mesh = make_mesh(n_devices=F, data=1, feature=F)
    dist = GraphSageSampler(topo, [4, 3], seed=7, seed_capacity=32,
                            dedup="sort", topo_sharding="mesh", mesh=mesh,
                            routed_alpha=0.25, **kw)
    rep = GraphSageSampler(topo, [4, 3], seed=7, seed_capacity=32,
                           dedup="sort", **kw)
    seeds = np.random.default_rng(F).integers(0, topo.node_count,
                                              32 * F - 3)
    _assert_worker_parity(dist, rep, seeds, jax.random.PRNGKey(F))
    assert int(np.asarray(dist.last_sample_overflow).sum()) > 0


@pytest.mark.slow
@pytest.mark.parametrize("F", [1, 2, 4])
def test_dist_parity_other_mesh_widths(F):
    """Same differential at the narrower mesh widths {1, 2, 4}."""
    topo = _graph(n=500)
    mesh = make_mesh(n_devices=F, data=1, feature=F)
    dist = GraphSageSampler(topo, [4, 3], seed=7, seed_capacity=32,
                            dedup="sort", topo_sharding="mesh", mesh=mesh)
    rep = GraphSageSampler(topo, [4, 3], seed=7, seed_capacity=32,
                           dedup="sort")
    seeds = np.random.default_rng(F).integers(
        0, topo.node_count, 32 * F - 3
    )
    _assert_worker_parity(dist, rep, seeds, jax.random.PRNGKey(F))


def test_forced_overflow_exact():
    """Adversarial skew: every seed owned by shard 0 and a tiny routing
    budget — buckets overflow, the cond-gated psum fallback serves the
    overflowed lanes, results stay bit-identical, and the per-hop count
    surfaces as last_sample_overflow."""
    topo = _graph(n=500)
    mesh = make_mesh(n_devices=4, data=1, feature=4)
    dist = GraphSageSampler(topo, [4, 3], seed=7, seed_capacity=32,
                            dedup="sort", topo_sharding="mesh", mesh=mesh,
                            routed_alpha=0.01)
    rep = GraphSageSampler(topo, [4, 3], seed=7, seed_capacity=32,
                           dedup="sort")
    # all seeds on shard 0's row range
    seeds = np.random.default_rng(2).integers(
        0, dist.topo.rows_per_shard, 32 * 4
    )
    _assert_worker_parity(dist, rep, seeds, jax.random.PRNGKey(9))
    ov = np.asarray(dist.last_sample_overflow)
    assert ov.shape == (2,) and int(ov.sum()) > 0, ov


# -- constructor guards -----------------------------------------------------


def test_mesh_sharding_constructor_guards():
    topo = _graph(n=200)
    mesh = make_mesh(data=1, feature=8)
    with pytest.raises(ValueError, match="requires mesh="):
        GraphSageSampler(topo, [4], topo_sharding="mesh")
    with pytest.raises(ValueError, match="topo_sharding"):
        GraphSageSampler(topo, [4], topo_sharding="nope")
    # weighted over mesh is SUPPORTED now — but only when the topology
    # actually carries weights (the shard partition needs cum_weights)
    with pytest.raises(ValueError, match="requires edge weights"):
        GraphSageSampler(topo, [4], topo_sharding="mesh", mesh=mesh,
                         weighted=True)
    w = np.ones(topo.edge_count, np.float32)
    t2 = _graph(n=200)
    t2.set_edge_weight(w)
    assert isinstance(
        GraphSageSampler(t2, [4], topo_sharding="mesh", mesh=mesh,
                         weighted=True),
        DistGraphSageSampler,
    )
    # temporal over mesh likewise needs timestamps on the topology
    with pytest.raises(ValueError, match="requires edge timestamps"):
        GraphSageSampler(topo, [4], topo_sharding="mesh", mesh=mesh,
                         time_window=(0.0, 1.0))
    with pytest.raises(ValueError, match="with_eid over a sharded"):
        GraphSageSampler(topo, [4], topo_sharding="mesh", mesh=mesh,
                         with_eid=True)
    # kernel='pallas' over mesh now rides the fused engine (PR 16); only
    # an unknown kernel name still raises
    with pytest.raises(ValueError, match="kernel"):
        GraphSageSampler(topo, [4], topo_sharding="mesh", mesh=mesh,
                         kernel="cuda")
    with pytest.raises(ValueError, match="HBM"):
        GraphSageSampler(topo, [4], topo_sharding="mesh", mesh=mesh,
                         mode="HOST")
    with pytest.raises(ValueError, match="routed_alpha"):
        GraphSageSampler(topo, [4], topo_sharding="mesh", mesh=mesh,
                         routed_alpha=-2.0)
    # the replicated path is untouched by the dispatch
    rep = GraphSageSampler(topo, [4])
    assert rep.topo_sharding == "replicated"
    assert not isinstance(rep, DistGraphSageSampler)


def test_trainer_requires_all_seed_sharding():
    topo = _graph(n=200)
    mesh = make_mesh(data=2, feature=4)
    dist = GraphSageSampler(topo, [4, 3], topo_sharding="mesh", mesh=mesh)
    feat = np.random.default_rng(0).normal(size=(topo.node_count, 8))
    feature = ShardedFeature(mesh, device_cache_size="1G").from_cpu_tensor(
        feat.astype(np.float32)
    )
    model = GraphSAGE(hidden=8, num_classes=3, num_layers=2)
    with pytest.raises(ValueError, match="seed_sharding"):
        DistributedTrainer(mesh, dist, feature, model, optax.adam(1e-3),
                           local_batch=8)  # default seed_sharding="data"
    other = make_mesh(data=1, feature=8)
    with pytest.raises(ValueError, match="mesh"):
        DistributedTrainer(other, dist, feature, model, optax.adam(1e-3),
                           local_batch=8, seed_sharding="all")


# -- end-to-end trainer parity (slow lane) ----------------------------------


@pytest.mark.slow
def test_trainer_loss_trajectory_parity():
    """DistributedTrainer over the dist sampler reproduces the replicated
    trainer's loss trajectory BIT-FOR-BIT on the 8-device mesh — capped
    tight (forced per-hop overflow) included — and surfaces the per-hop
    overflow vector per step of an epoch_scan."""
    ei = generate_pareto_graph(400, 6.0, seed=0)
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    feat = np.random.default_rng(0).normal(size=(n, 8)).astype(np.float32)
    labels = jnp.asarray(
        np.random.default_rng(0).integers(0, 4, n).astype(np.int32)
    )
    mesh = make_mesh(data=2, feature=4)
    model = GraphSAGE(hidden=16, num_classes=4, num_layers=2)

    losses = {}
    for mode, alpha in (("replicated", 1.0), ("mesh", 1.0),
                        ("mesh-tight", 0.25)):
        if mode == "replicated":
            sampler = GraphSageSampler(topo, [4, 3], seed=3)
        else:
            sampler = GraphSageSampler(topo, [4, 3], seed=3,
                                       topo_sharding="mesh", mesh=mesh)
        feature = ShardedFeature(
            mesh, device_cache_size="1G", csr_topo=CSRTopo(edge_index=ei)
        ).from_cpu_tensor(feat)
        trainer = DistributedTrainer(
            mesh, sampler, feature, model, optax.adam(5e-3),
            local_batch=16, seed_sharding="all", routed_alpha=alpha,
        )
        params, opt = trainer.init(jax.random.PRNGKey(0))
        srng = np.random.default_rng(0)
        ls = []
        for step in range(3):
            seeds = srng.integers(0, n, trainer.global_batch)
            params, opt, loss = trainer.step(
                params, opt, seeds, labels, jax.random.PRNGKey(step)
            )
            ls.append(float(loss))
        losses[mode] = ls
        if mode == "mesh-tight":
            # the tight budget must actually exercise the fallback
            assert int(np.asarray(trainer.last_sample_overflow).sum()) > 0
    assert losses["replicated"] == losses["mesh"], losses
    assert losses["replicated"] == losses["mesh-tight"], losses

    # fused epoch: per-step (steps, num_layers) overflow vector
    sampler = GraphSageSampler(topo, [4, 3], seed=3, topo_sharding="mesh",
                               mesh=mesh)
    feature = ShardedFeature(
        mesh, device_cache_size="1G", csr_topo=CSRTopo(edge_index=ei)
    ).from_cpu_tensor(feat)
    trainer = DistributedTrainer(
        mesh, sampler, feature, model, optax.adam(5e-3), local_batch=16,
        seed_sharding="all", routed_alpha=0.25,
    )
    params, opt = trainer.init(jax.random.PRNGKey(0))
    seed_mat = trainer.pack_epoch(np.arange(3 * trainer.global_batch) % n,
                                  seed=0)
    params, opt, el = trainer.epoch_scan(params, opt, seed_mat, labels,
                                         jax.random.PRNGKey(1))
    assert np.all(np.isfinite(np.asarray(el)))
    sov = np.asarray(trainer.last_sample_overflow)
    assert sov.shape == (3, 2) and int(sov.sum()) > 0


@pytest.mark.slow
def test_trainer_shared_auto_alpha_tuner():
    """auto_alpha=True: one tuner reads BOTH overflow telemetries (feature
    gather + sampler hops) and doubles the shared routing budget after an
    overflowed eager batch."""
    ei = generate_pareto_graph(400, 6.0, seed=0)
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    feat = np.random.default_rng(0).normal(size=(n, 8)).astype(np.float32)
    labels = jnp.asarray(
        np.random.default_rng(0).integers(0, 4, n).astype(np.int32)
    )
    mesh = make_mesh(data=2, feature=4)
    sampler = GraphSageSampler(topo, [4, 3], seed=3, topo_sharding="mesh",
                               mesh=mesh)
    feature = ShardedFeature(
        mesh, device_cache_size="1G", csr_topo=CSRTopo(edge_index=ei)
    ).from_cpu_tensor(feat)
    trainer = DistributedTrainer(
        mesh, sampler, feature, GraphSAGE(hidden=16, num_classes=4,
                                          num_layers=2),
        optax.adam(5e-3), local_batch=16, seed_sharding="all",
        routed_alpha=0.25, auto_alpha=True,
    )
    params, opt = trainer.init(jax.random.PRNGKey(0))
    srng = np.random.default_rng(0)
    alphas = []
    for step in range(3):
        seeds = srng.integers(0, n, trainer.global_batch)
        params, opt, _ = trainer.step(params, opt, seeds, labels,
                                      jax.random.PRNGKey(step))
        alphas.append(trainer.routed_alpha)
    assert alphas[-1] > 0.25, alphas  # grew after the overflowed batch
