"""Dataset ingestion + accuracy acceptance (VERDICT r1 item 6).

The reference's acceptance test is real-Reddit training with ~0.93 test
accuracy (examples/pyg/reddit_quiver.py:20-34). Downloads are impossible in
this image, so the acceptance oracle is the planted-partition SBM whose
*feature-only Bayes accuracy is computable*: the full sampler → tiered
feature → GraphSAGE stack must clear it by a wide margin (the class signal
lives in neighborhoods, so a broken sampler or gather collapses to — or
below — feature-only Bayes). The on-disk loaders (reddit npz, ogb raw csv)
are round-trip-tested on written-out miniature copies of the real layouts.
"""

import gzip
import os

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from quiver_tpu.datasets import (
    feature_bayes_accuracy,
    load_dataset,
    load_ogb_raw,
    load_reddit,
    planted_partition,
)


def test_planted_partition_shapes_and_splits():
    ds = planted_partition(n=2000, num_classes=5, seed=1)
    assert ds.node_count == 2000 and ds.num_classes == 5
    assert ds.features.shape == (2000, 5)
    assert ds.labels.shape == (2000,)
    all_idx = np.concatenate([ds.train_idx, ds.val_idx, ds.test_idx])
    assert len(np.unique(all_idx)) == len(all_idx)  # disjoint splits
    assert 0 < ds.meta["feature_bayes_acc"] < 1


def test_planted_partition_homophily():
    ds = planted_partition(n=3000, num_classes=4, homophily=0.9, seed=2)
    lab = ds.labels
    indptr, indices = ds.topo.indptr, ds.topo.indices
    src = np.repeat(np.arange(ds.node_count), np.diff(indptr))
    agree = (lab[src] == lab[indices]).mean()
    # expected agreement = h + (1-h)/C = 0.9 + 0.1/4 = 0.925
    assert 0.88 < agree < 0.96


def test_acceptance_sage_beats_feature_bayes():
    """The full stack must recover the planted structure: test accuracy
    >= 0.85 absolute AND >= feature-Bayes + 0.15."""
    from examples.train_sage import main

    acc, ds = main([
        "--dataset", "planted:4000:6",
        "--epochs", "8",
        "--batch", "256",
        "--hidden", "64",
        "--fanout", "10", "5",
        "--feature-dim", "6",
    ])
    bayes = ds.meta["feature_bayes_acc"]
    assert acc >= 0.85, f"test acc {acc} below acceptance bar"
    assert acc >= bayes + 0.15, f"acc {acc} does not clear feature Bayes {bayes}"


def _write_reddit_fixture(root, n=60, f=9, classes=4, seed=0):
    import scipy.sparse as sp

    rng = np.random.default_rng(seed)
    feat = rng.normal(size=(n, f)).astype(np.float32)
    label = rng.integers(0, classes, n)
    types = rng.choice([1, 2, 3], n, p=[0.6, 0.2, 0.2])
    np.savez(os.path.join(root, "reddit_data.npz"),
             feature=feat, label=label, node_types=types)
    m = 300
    adj = sp.coo_matrix(
        (np.ones(m), (rng.integers(0, n, m), rng.integers(0, n, m))),
        shape=(n, n),
    ).tocsr()
    sp.save_npz(os.path.join(root, "reddit_graph.npz"), adj)
    return feat, label, types, adj


def test_load_reddit_roundtrip(tmp_path):
    feat, label, types, adj = _write_reddit_fixture(str(tmp_path))
    ds = load_reddit(str(tmp_path))
    assert np.allclose(ds.features, feat)
    assert np.array_equal(ds.labels, label)
    assert np.array_equal(ds.train_idx, np.where(types == 1)[0])
    assert np.array_equal(ds.test_idx, np.where(types == 3)[0])
    assert ds.topo.edge_count == adj.nnz
    # CSR row 0's neighbors match scipy's
    assert np.array_equal(
        np.sort(ds.topo.indices[: ds.topo.indptr[1]]),
        np.sort(adj.indices[: adj.indptr[1]]),
    )


def _write_csv_gz(path, arr):
    with gzip.open(path, "wt") as fh:
        for row in np.atleast_2d(arr.T if arr.ndim == 1 else arr):
            fh.write(",".join(str(v) for v in np.atleast_1d(row)) + "\n")


def test_load_ogb_raw_roundtrip(tmp_path):
    n, f, e = 40, 5, 120
    rng = np.random.default_rng(3)
    base = tmp_path / "ogbn_toy"
    (base / "raw").mkdir(parents=True)
    (base / "split" / "sales").mkdir(parents=True)
    edges = rng.integers(0, n, (e, 2))
    feat = rng.normal(size=(n, f)).astype(np.float32)
    labels = rng.integers(0, 3, n)
    _write_csv_gz(base / "raw" / "edge.csv.gz", edges)
    _write_csv_gz(base / "raw" / "node-feat.csv.gz", feat)
    _write_csv_gz(base / "raw" / "node-label.csv.gz", labels[:, None])
    perm = rng.permutation(n)
    _write_csv_gz(base / "split" / "sales" / "train.csv.gz", perm[:20][:, None])
    _write_csv_gz(base / "split" / "sales" / "valid.csv.gz", perm[20:30][:, None])
    _write_csv_gz(base / "split" / "sales" / "test.csv.gz", perm[30:][:, None])

    ds = load_ogb_raw("ogbn-toy", str(base))
    assert ds.node_count == n
    assert ds.topo.edge_count == 2 * e  # symmetrized
    assert np.allclose(ds.features, feat, atol=1e-5)
    assert np.array_equal(ds.train_idx, perm[:20])
    assert ds.num_classes == int(labels.max()) + 1
    assert ds.meta["split_scheme"] == "sales"
    # loader also resolves from the parent directory by name
    ds2 = load_dataset("ogbn-toy", root=str(tmp_path))
    assert ds2.topo.edge_count == ds.topo.edge_count


def test_feature_bayes_accuracy_monotone():
    hi = feature_bayes_accuracy(4, 0.3)
    lo = feature_bayes_accuracy(4, 3.0)
    assert hi > 0.8 > lo > 1 / 4 - 0.02
