"""Dataset ingestion + accuracy acceptance (VERDICT r1 item 6).

The reference's acceptance test is real-Reddit training with ~0.93 test
accuracy (examples/pyg/reddit_quiver.py:20-34). Downloads are impossible in
this image, so the acceptance oracle is the planted-partition SBM whose
*feature-only Bayes accuracy is computable*: the full sampler → tiered
feature → GraphSAGE stack must clear it by a wide margin (the class signal
lives in neighborhoods, so a broken sampler or gather collapses to — or
below — feature-only Bayes). The on-disk loaders (reddit npz, ogb raw csv)
are round-trip-tested on written-out miniature copies of the real layouts.
"""

import gzip
import os

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from quiver_tpu.datasets import (
    feature_bayes_accuracy,
    load_dataset,
    load_ogb_raw,
    load_reddit,
    planted_partition,
)


def test_planted_partition_shapes_and_splits():
    ds = planted_partition(n=2000, num_classes=5, seed=1)
    assert ds.node_count == 2000 and ds.num_classes == 5
    assert ds.features.shape == (2000, 5)
    assert ds.labels.shape == (2000,)
    all_idx = np.concatenate([ds.train_idx, ds.val_idx, ds.test_idx])
    assert len(np.unique(all_idx)) == len(all_idx)  # disjoint splits
    assert 0 < ds.meta["feature_bayes_acc"] < 1


def test_planted_partition_homophily():
    ds = planted_partition(n=3000, num_classes=4, homophily=0.9, seed=2)
    lab = ds.labels
    indptr, indices = ds.topo.indptr, ds.topo.indices
    src = np.repeat(np.arange(ds.node_count), np.diff(indptr))
    agree = (lab[src] == lab[indices]).mean()
    # expected agreement = h + (1-h)/C = 0.9 + 0.1/4 = 0.925
    assert 0.88 < agree < 0.96


def test_acceptance_sage_beats_feature_bayes():
    """The full stack must recover the planted structure: test accuracy
    >= 0.85 absolute AND >= feature-Bayes + 0.15."""
    from examples.train_sage import main

    acc, ds = main([
        "--dataset", "planted:4000:6",
        "--epochs", "8",
        "--batch", "256",
        "--hidden", "64",
        "--fanout", "10", "5",
        "--feature-dim", "6",
    ])
    bayes = ds.meta["feature_bayes_acc"]
    assert acc >= 0.85, f"test acc {acc} below acceptance bar"
    assert acc >= bayes + 0.15, f"acc {acc} does not clear feature Bayes {bayes}"


def _write_reddit_fixture(root, n=60, f=9, classes=4, seed=0):
    import scipy.sparse as sp

    rng = np.random.default_rng(seed)
    feat = rng.normal(size=(n, f)).astype(np.float32)
    label = rng.integers(0, classes, n)
    types = rng.choice([1, 2, 3], n, p=[0.6, 0.2, 0.2])
    np.savez(os.path.join(root, "reddit_data.npz"),
             feature=feat, label=label, node_types=types)
    m = 300
    adj = sp.coo_matrix(
        (np.ones(m), (rng.integers(0, n, m), rng.integers(0, n, m))),
        shape=(n, n),
    ).tocsr()
    sp.save_npz(os.path.join(root, "reddit_graph.npz"), adj)
    return feat, label, types, adj


def test_load_reddit_roundtrip(tmp_path):
    feat, label, types, adj = _write_reddit_fixture(str(tmp_path))
    ds = load_reddit(str(tmp_path))
    assert np.allclose(ds.features, feat)
    assert np.array_equal(ds.labels, label)
    assert np.array_equal(ds.train_idx, np.where(types == 1)[0])
    assert np.array_equal(ds.test_idx, np.where(types == 3)[0])
    assert ds.topo.edge_count == adj.nnz
    # CSR row 0's neighbors match scipy's
    assert np.array_equal(
        np.sort(ds.topo.indices[: ds.topo.indptr[1]]),
        np.sort(adj.indices[: adj.indptr[1]]),
    )


def _write_csv_gz(path, arr):
    with gzip.open(path, "wt") as fh:
        for row in np.atleast_2d(arr.T if arr.ndim == 1 else arr):
            fh.write(",".join(str(v) for v in np.atleast_1d(row)) + "\n")


def test_load_ogb_raw_roundtrip(tmp_path):
    n, f, e = 40, 5, 120
    rng = np.random.default_rng(3)
    base = tmp_path / "ogbn_toy"
    (base / "raw").mkdir(parents=True)
    (base / "split" / "sales").mkdir(parents=True)
    edges = rng.integers(0, n, (e, 2))
    feat = rng.normal(size=(n, f)).astype(np.float32)
    labels = rng.integers(0, 3, n)
    _write_csv_gz(base / "raw" / "edge.csv.gz", edges)
    _write_csv_gz(base / "raw" / "node-feat.csv.gz", feat)
    _write_csv_gz(base / "raw" / "node-label.csv.gz", labels[:, None])
    perm = rng.permutation(n)
    _write_csv_gz(base / "split" / "sales" / "train.csv.gz", perm[:20][:, None])
    _write_csv_gz(base / "split" / "sales" / "valid.csv.gz", perm[20:30][:, None])
    _write_csv_gz(base / "split" / "sales" / "test.csv.gz", perm[30:][:, None])

    ds = load_ogb_raw("ogbn-toy", str(base))
    assert ds.node_count == n
    assert ds.topo.edge_count == 2 * e  # symmetrized
    assert np.allclose(ds.features, feat, atol=1e-5)
    assert np.array_equal(ds.train_idx, perm[:20])
    assert ds.num_classes == int(labels.max()) + 1
    assert ds.meta["split_scheme"] == "sales"
    # loader also resolves from the parent directory by name
    ds2 = load_dataset("ogbn-toy", root=str(tmp_path))
    assert ds2.topo.edge_count == ds.topo.edge_count


def test_feature_bayes_accuracy_monotone():
    hi = feature_bayes_accuracy(4, 0.3)
    lo = feature_bayes_accuracy(4, 3.0)
    assert hi > 0.8 > lo > 1 / 4 - 0.02


def _write_reddit_shaped(root, n, avg_deg, seed=0):
    """Reddit's exact dtype/dim surface (602-dim float32 features, 41
    classes, int64 npz labels, scipy CSR adjacency), node count scaled."""
    import scipy.sparse as sp

    from quiver_tpu.utils.graphgen import generate_pareto_graph

    rng = np.random.default_rng(seed)
    label = rng.integers(0, 41, n)
    # plant a weak label signal in the features (one-hot into the first 41 of
    # 602 dims, under noise): the "loss is falling" assertion needs something
    # learnable — labels independent of features would leave only step noise
    feat = rng.normal(size=(n, 602)).astype(np.float32)
    feat[np.arange(n), label] += 2.0
    types = rng.choice([1, 2, 3], n, p=[0.66, 0.10, 0.24])  # real split ratios
    np.savez(os.path.join(root, "reddit_data.npz"),
             feature=feat, label=label, node_types=types)
    ei = generate_pareto_graph(n, avg_deg, seed=seed)
    adj = sp.coo_matrix(
        (np.ones(ei.shape[1], np.float32), (ei[0], ei[1])), shape=(n, n)
    ).tocsr()
    sp.save_npz(os.path.join(root, "reddit_graph.npz"), adj)


def _drive_reddit_shaped(root, n, avg_deg, steps, batch):
    """VERDICT r2 missing #2: the 602-dim/41-class Reddit surface has never
    flowed through the stack. Drive loader → [25,10] sampler → 20%-cached
    Feature → 2-layer SAGE exactly like the reference's reddit_quiver.py
    config and assert shapes/dtypes survive and the loss is finite+falling."""
    import optax as _optax

    from quiver_tpu import Feature, GraphSageSampler
    from quiver_tpu.models.sage import GraphSAGE
    from quiver_tpu.parallel.train import init_model, make_train_step

    _write_reddit_shaped(root, n=n, avg_deg=avg_deg)
    ds = load_reddit(root)
    assert ds.features.shape == (n, 602) and ds.features.dtype == np.float32
    assert ds.num_classes == 41 and ds.labels.dtype == np.int32

    sampler = GraphSageSampler(ds.topo, [25, 10], mode="UVA",
                               seed_capacity=batch, frontier_caps="auto")
    budget = int(0.2 * n) * 602 * 4
    feature = Feature(device_cache_size=budget,
                      csr_topo=ds.topo).from_cpu_tensor(ds.features)
    assert 0.15 < feature.cache_ratio <= 0.25
    labels_all = jnp.asarray(ds.labels)

    model = GraphSAGE(hidden=128, num_classes=41, num_layers=2)
    out = sampler.sample(ds.train_idx[:batch])
    x = feature[out.n_id]
    assert x.shape[1] == 602 and x.dtype == jnp.float32
    params = init_model(model, jax.random.PRNGKey(0), x, out.adjs)
    tx = _optax.adam(1e-3)
    opt_state = tx.init(params)
    step = jax.jit(make_train_step(model, tx))
    rng = np.random.default_rng(1)
    losses = []
    for i in range(steps):
        seeds = rng.choice(ds.train_idx, batch)
        out = sampler.sample(seeds)
        seed_ids = out.n_id[:batch]
        params, opt_state, loss = step(
            params, opt_state, feature[out.n_id], out.adjs,
            labels_all[jnp.clip(seed_ids, 0)], seed_ids >= 0,
            jax.random.PRNGKey(i),
        )
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], f"no learning on reddit shape: {losses}"
    return losses


@pytest.mark.slow  # 14s reddit-shaped end-to-end flow
def test_reddit_shaped_dims_flow_through_stack(tmp_path):
    """CI-scale: true feature dim / class count / npz dtypes, node count
    scaled to 12k so the suite stays fast."""
    _drive_reddit_shaped(str(tmp_path), n=12_000, avg_deg=12.0,
                         steps=6, batch=256)


@pytest.mark.skipif(
    not os.environ.get("QUIVER_FULL_SCALE"),
    reason="full Reddit scale (233k x 602 features, ~25M edges) is a "
    "multi-GB opt-in run: set QUIVER_FULL_SCALE=1",
)
def test_reddit_shaped_full_scale(tmp_path):
    """The real Reddit scale (232,965 nodes, 602 dims, 41 classes): run when
    an operator (or the TPU bench image) can afford the memory."""
    _drive_reddit_shaped(str(tmp_path), n=232_965, avg_deg=110.0,
                         steps=4, batch=1024)
