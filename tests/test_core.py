"""Core container tests: byte-size parser, CSRTopo round-trip, reorder invariant.

Mirrors the reference's test strategy (SURVEY §4): CSR construction
round-trip property tests and the reorder invariant of
test_graph_reindex.py:35-70.
"""

import numpy as np
import pytest

from quiver_tpu import CSRTopo, parse_size_bytes, reorder_by_degree
from quiver_tpu.core.config import CachePolicy, SampleMode
from quiver_tpu.utils.graphgen import generate_pareto_graph


def test_parse_size_bytes():
    assert parse_size_bytes("1K") == 1024
    assert parse_size_bytes("0.9M") == int(0.9 * 2**20)
    assert parse_size_bytes("3GB") == 3 * 2**30
    assert parse_size_bytes("2g") == 2 * 2**30
    assert parse_size_bytes(4096) == 4096
    assert parse_size_bytes("512") == 512
    with pytest.raises(ValueError):
        parse_size_bytes("12X")
    with pytest.raises(ValueError):
        parse_size_bytes("abc")


def test_policy_and_mode_parsing():
    assert CachePolicy.parse("p2p_clique_replicate") is CachePolicy.MESH_SHARD
    assert CachePolicy.parse("device_replicate") is CachePolicy.DEVICE_REPLICATE
    assert SampleMode.parse("UVA") is SampleMode.HOST
    assert SampleMode.parse("GPU") is SampleMode.HBM
    with pytest.raises(ValueError):
        SampleMode.parse("nope")


def test_csr_from_coo_roundtrip():
    # property test: build CSR from COO, export edge set back, compare
    # (reference tests/cpp/test_quiver.cu:122-165)
    rng = np.random.default_rng(0)
    n, e = 50, 400
    row = rng.integers(0, n, e)
    col = rng.integers(0, n, e)
    topo = CSRTopo(edge_index=np.stack([row, col]))
    assert topo.node_count >= max(row.max(), col.max()) + 1
    assert topo.edge_count == e
    # reconstruct COO from CSR
    re_row = np.repeat(np.arange(topo.node_count), topo.degree)
    re_edges = set(zip(re_row.tolist(), topo.indices.tolist()))
    orig_edges = set(zip(row.tolist(), col.tolist()))
    assert re_edges == orig_edges
    # eid maps CSR slots back to original COO positions
    assert np.all(row[topo.eid] == re_row)
    assert np.all(col[topo.eid] == topo.indices)


def test_csr_from_indptr_indices():
    indptr = np.array([0, 2, 2, 5])
    indices = np.array([1, 2, 0, 1, 2])
    topo = CSRTopo(indptr=indptr, indices=indices)
    assert topo.node_count == 3
    assert topo.edge_count == 5
    assert list(topo.degree) == [2, 0, 3]
    assert topo.max_degree == 3


def test_csr_degree_matches_bincount():
    ei = generate_pareto_graph(1000, 8.0, seed=1)
    topo = CSRTopo(edge_index=ei)
    expect = np.bincount(ei[0], minlength=topo.node_count)
    assert np.array_equal(topo.degree, expect)


def test_feature_order_slot():
    topo = CSRTopo(indptr=np.array([0, 1, 2]), indices=np.array([1, 0]))
    order = np.array([1, 0])
    topo.feature_order = order
    assert np.array_equal(topo.feature_order, order)
    with pytest.raises(ValueError):
        topo.feature_order = np.array([0, 1, 2])


def test_reorder_invariant():
    # original_feature[ids] == new_feature[new_order[ids]]
    rng = np.random.default_rng(0)
    n, f = 300, 16
    feat = rng.normal(size=(n, f)).astype(np.float32)
    deg = rng.integers(0, 100, n)
    new_feat, new_order = reorder_by_degree(feat, deg, hot_ratio=0.3, seed=7)
    ids = rng.integers(0, n, 64)
    assert np.allclose(feat[ids], new_feat[new_order[ids]])
    # hot prefix owns the highest-degree nodes
    hot = int(n * 0.3)
    hot_nodes = np.where(new_order < hot)[0]
    cold_nodes = np.where(new_order >= hot)[0]
    assert deg[hot_nodes].min() >= deg[cold_nodes].max() - 0  # sorted split


def test_csr_save_load_roundtrip(tmp_path):
    """save/load preserves CSR arrays, eid, CSR-ordered weights (and their
    prefix sums), and feature_order."""
    rng = np.random.default_rng(5)
    ei = rng.integers(0, 50, (2, 400))
    topo = CSRTopo(edge_index=ei)
    topo.set_edge_weight(rng.random(400).astype(np.float32), coo_order=True)
    topo.feature_order = np.asarray(rng.permutation(topo.node_count))

    p = str(tmp_path / "topo.npz")
    topo.save(p)
    back = CSRTopo.load(p)

    np.testing.assert_array_equal(topo.indptr, back.indptr)
    np.testing.assert_array_equal(topo.indices, back.indices)
    np.testing.assert_array_equal(topo.eid, back.eid)
    np.testing.assert_array_equal(topo.feature_order, back.feature_order)
    np.testing.assert_allclose(topo.edge_weight, back.edge_weight)
    np.testing.assert_allclose(topo.cum_weights, back.cum_weights)


def test_csr_save_load_minimal(tmp_path):
    """A weightless, orderless topology round-trips too (optional arrays
    absent from the npz, not stored as empties)."""
    ei = np.array([[0, 1, 2], [1, 2, 0]])
    topo = CSRTopo(edge_index=ei)
    p = str(tmp_path / "t.npz")
    topo.save(p)
    back = CSRTopo.load(p)
    np.testing.assert_array_equal(topo.indptr, back.indptr)
    np.testing.assert_array_equal(topo.indices, back.indices)
    assert back.edge_weight is None and back.feature_order is None


def test_resolve_platform_strategy_edge_cases(monkeypatch):
    """The shared env-override resolver behind every strategy knob
    (QUIVER_COUNTS/QUIVER_DEDUP/QUIVER_INFER_AGG...): graftlint's
    env-at-trace rule points users at this helper, so its contract is
    pinned here — empty/whitespace fall through to the platform default,
    values are case/whitespace-normalized, and a typo'd FORCE raises with
    an actionable message instead of silently measuring the default."""
    import pytest

    from quiver_tpu.core.config import resolve_platform_strategy

    choices = ("scan", "scatter")

    def resolve():
        return resolve_platform_strategy(
            "QUIVER_TEST_STRAT", choices, tpu_default="scan",
            other_default="scatter",
        )

    # unset / empty / whitespace-only -> platform default (cpu here)
    monkeypatch.delenv("QUIVER_TEST_STRAT", raising=False)
    assert resolve() == "scatter"
    monkeypatch.setenv("QUIVER_TEST_STRAT", "")
    assert resolve() == "scatter"
    monkeypatch.setenv("QUIVER_TEST_STRAT", "   ")
    assert resolve() == "scatter"

    # case and surrounding whitespace are normalized, not rejected
    monkeypatch.setenv("QUIVER_TEST_STRAT", "  SCAN  ")
    assert resolve() == "scan"
    monkeypatch.setenv("QUIVER_TEST_STRAT", "Scatter")
    assert resolve() == "scatter"

    # a typo'd force must raise, naming the var, the value, and the menu
    monkeypatch.setenv("QUIVER_TEST_STRAT", "scann")
    with pytest.raises(ValueError) as ei:
        resolve()
    msg = str(ei.value)
    assert "QUIVER_TEST_STRAT" in msg and "scann" in msg
    assert "scan" in msg and "scatter" in msg
