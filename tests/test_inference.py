"""Full-neighbor layer-wise inference tests (reference reddit_quiver.py:68-92
capability). Oracles: numpy mean-aggregation for the chunked segment pass,
and the full-fanout sampled model for end-to-end equivalence."""

import numpy as np
import jax
import jax.numpy as jnp

from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.models.inference import (
    full_neighbor_mean,
    gat_layerwise_inference,
    sage_layerwise_inference,
)
from quiver_tpu.models.sage import GraphSAGE
from quiver_tpu.parallel.train import init_model
from quiver_tpu.utils.graphgen import generate_pareto_graph


def _numpy_neighbor_mean(topo, x):
    out = np.zeros_like(x)
    for v in range(topo.node_count):
        nbrs = topo.indices[topo.indptr[v]:topo.indptr[v + 1]]
        if len(nbrs):
            out[v] = x[nbrs].mean(axis=0)
    return out


def test_full_neighbor_mean_matches_numpy():
    ei = generate_pareto_graph(300, 6.0, seed=0)
    topo = CSRTopo(edge_index=ei)
    x = np.random.default_rng(1).normal(size=(300, 7)).astype(np.float32)
    got = np.asarray(full_neighbor_mean(topo, x))
    np.testing.assert_allclose(got, _numpy_neighbor_mean(topo, x), rtol=2e-5,
                               atol=1e-6)


def test_full_neighbor_mean_chunk_boundaries():
    """Chunk smaller than E: accumulation across chunk boundaries and the
    masked tail lane must not corrupt rows."""
    ei = generate_pareto_graph(200, 5.0, seed=2)
    topo = CSRTopo(edge_index=ei)
    x = np.random.default_rng(3).normal(size=(200, 4)).astype(np.float32)
    whole = np.asarray(full_neighbor_mean(topo, x, chunk=1 << 21))
    small = np.asarray(full_neighbor_mean(topo, x, chunk=97))
    np.testing.assert_allclose(small, whole, rtol=1e-6)


def test_zero_degree_rows_aggregate_to_zero():
    # node 3 has no incoming neighbors
    ei = np.array([[0, 1], [1, 2]])
    topo = CSRTopo(indptr=np.array([0, 0, 1, 2, 2]),
                   indices=np.array([0, 1]))
    x = np.ones((4, 3), np.float32)
    got = np.asarray(full_neighbor_mean(topo, x))
    assert np.all(got[0] == 0) and np.all(got[3] == 0)
    assert np.allclose(got[1], 1) and np.allclose(got[2], 1)


def test_full_neighbor_mean_host_mode_matches_hbm():
    """Beyond-HBM placement: pinned-host edge array + staged chunk gathers
    must agree exactly with the HBM path."""
    ei = generate_pareto_graph(250, 6.0, seed=6)
    topo = CSRTopo(edge_index=ei)
    x = np.random.default_rng(7).normal(size=(250, 6)).astype(np.float32)
    hbm = np.asarray(full_neighbor_mean(topo, x, chunk=101))
    host = np.asarray(full_neighbor_mean(topo, x, chunk=101, mode="HOST"))
    np.testing.assert_allclose(host, hbm, rtol=1e-6)


def test_gat_layerwise_matches_full_fanout_sampled_model():
    """GAT analogue of the SAGE oracle: whole-graph chunked attention
    (3-pass segment softmax) must match the sampled GAT at full fanout."""
    from quiver_tpu.models.gat import GAT

    n = 200
    ei = generate_pareto_graph(n, 5.0, seed=8)
    topo = CSRTopo(edge_index=ei)
    x_all = np.random.default_rng(9).normal(size=(n, 10)).astype(np.float32)
    model = GAT(hidden=8, num_classes=4, num_layers=2, heads=3)

    sampler = GraphSageSampler(topo, [-1, -1], seed=1)
    seeds = np.arange(48)
    out = sampler.sample(seeds)
    assert int(out.overflow) == 0
    n_id = np.asarray(out.n_id)
    x = jnp.asarray(
        np.where((n_id >= 0)[:, None], x_all[np.maximum(n_id, 0)], 0)
    )
    params = init_model(model, jax.random.PRNGKey(2), x, out.adjs)
    sampled_logp = np.asarray(
        model.apply({"params": params}, x, out.adjs, train=False)
    )[: len(seeds)]

    # chunk smaller than E exercises cross-chunk max/denom/accumulate
    full_logp = np.asarray(
        gat_layerwise_inference(model, params, topo, x_all, chunk=257)
    )[seeds]
    np.testing.assert_allclose(sampled_logp, full_logp, rtol=2e-4, atol=2e-5)


def test_gat_layerwise_host_mode_matches_hbm():
    from quiver_tpu.models.gat import GAT

    ei = generate_pareto_graph(150, 5.0, seed=10)
    topo = CSRTopo(edge_index=ei)
    x_all = np.random.default_rng(11).normal(size=(150, 8)).astype(np.float32)
    model = GAT(hidden=6, num_classes=3, num_layers=2, heads=2)
    sampler = GraphSageSampler(topo, [2, 2], seed=0)
    out = sampler.sample(np.arange(16))
    n_id = np.asarray(out.n_id)
    x = jnp.asarray(
        np.where((n_id >= 0)[:, None], x_all[np.maximum(n_id, 0)], 0)
    )
    params = init_model(model, jax.random.PRNGKey(3), x, out.adjs)
    hbm = np.asarray(gat_layerwise_inference(model, params, topo, x_all,
                                             chunk=131))
    host = np.asarray(gat_layerwise_inference(model, params, topo, x_all,
                                              chunk=131, mode="HOST"))
    np.testing.assert_allclose(host, hbm, rtol=1e-6)


def _rgcn_oracle(num_bases):
    from quiver_tpu import HeteroCSRTopo, HeteroGraphSampler
    from quiver_tpu.models.inference import rgcn_layerwise_inference
    from quiver_tpu.models.rgcn import RGCN

    rng = np.random.default_rng(12)
    n_paper, n_author = 120, 50
    topo = HeteroCSRTopo(
        {"paper": n_paper, "author": n_author},
        {
            ("paper", "cites", "paper"): np.stack([
                rng.integers(0, n_paper, 300),
                rng.integers(0, n_paper, 300),
            ]),
            ("author", "writes", "paper"): np.stack([
                rng.integers(0, n_author, 200),
                rng.integers(0, n_paper, 200),
            ]),
            ("paper", "by", "author"): np.stack([
                rng.integers(0, n_paper, 150),
                rng.integers(0, n_author, 150),
            ]),
        },
    )
    x_full = {
        "paper": rng.normal(size=(n_paper, 9)).astype(np.float32),
        "author": rng.normal(size=(n_author, 7)).astype(np.float32),
    }
    model = RGCN(hidden=12, num_classes=4, target_type="paper",
                 num_layers=2, num_bases=num_bases)

    sampler = HeteroGraphSampler(topo, [-1, -1], input_type="paper", seed=0)
    seeds = np.arange(32)
    out = sampler.sample(seeds)
    assert int(out.overflow) == 0
    x_dict = {
        t: jnp.asarray(np.where(
            (np.asarray(ids) >= 0)[:, None],
            x_full[t][np.maximum(np.asarray(ids), 0)], 0,
        ))
        for t, ids in out.n_id.items()
    }
    params = model.init(
        {"params": jax.random.PRNGKey(4)}, x_dict, out.adjs
    )["params"]
    sampled = np.asarray(
        model.apply({"params": params}, x_dict, out.adjs, train=False)
    )[: len(seeds)]

    full = np.asarray(
        rgcn_layerwise_inference(model, params, topo, x_full, chunk=67)
    )[seeds]
    np.testing.assert_allclose(sampled, full, rtol=2e-4, atol=2e-5)


def test_rgcn_layerwise_matches_full_fanout_sampled_model():
    """R-GCN analogue of the SAGE/GAT oracles, full per-relation weights."""
    _rgcn_oracle(num_bases=0)


def test_rgcn_layerwise_matches_with_basis_decomposition():
    _rgcn_oracle(num_bases=3)


def test_layerwise_inference_matches_full_fanout_sampled_model():
    """End-to-end oracle: with fanout -1 (every neighbor taken) the sampled
    model's seed predictions equal the whole-graph layer-wise pass."""
    n = 250
    ei = generate_pareto_graph(n, 5.0, seed=4)
    topo = CSRTopo(edge_index=ei)
    x_all = np.random.default_rng(5).normal(size=(n, 12)).astype(np.float32)
    model = GraphSAGE(hidden=16, num_classes=5, num_layers=2)

    sampler = GraphSageSampler(topo, [-1, -1], seed=0)
    seeds = np.arange(64)
    out = sampler.sample(seeds)
    assert int(out.overflow) == 0
    n_id = np.asarray(out.n_id)
    x = jnp.asarray(
        np.where((n_id >= 0)[:, None], x_all[np.maximum(n_id, 0)], 0)
    )
    params = init_model(model, jax.random.PRNGKey(0), x, out.adjs)
    sampled_logp = np.asarray(
        model.apply({"params": params}, x, out.adjs, train=False)
    )[: len(seeds)]

    full_logp = np.asarray(
        sage_layerwise_inference(model, params, topo, x_all)
    )[seeds]
    np.testing.assert_allclose(sampled_logp, full_logp, rtol=1e-4, atol=1e-5)


def test_scan_aggregation_matches_scatter(monkeypatch):
    """The zero-scatter chunked aggregation (cumsum + prefix differences at
    CSR row boundaries, the TPU path) must reproduce the scatter path on
    graphs with hubs, zero-degree runs, and a ragged final chunk."""
    import numpy as np
    import jax.numpy as jnp

    from quiver_tpu import CSRTopo
    from quiver_tpu.models.inference import full_neighbor_mean

    rng = np.random.default_rng(9)
    # hub row 0 (deg 500), a zero-degree run (rows 40-79), ragged tail
    srcs, dsts = [], []
    dsts += [0] * 500
    srcs += rng.integers(0, 200, 500).tolist()
    for v in range(1, 40):
        d = int(rng.integers(1, 9))
        dsts += [v] * d
        srcs += rng.integers(0, 200, d).tolist()
    for v in range(80, 200):
        d = int(rng.integers(0, 5))
        dsts += [v] * d
        srcs += rng.integers(0, 200, d).tolist()
    ei = np.stack([np.array(dsts), np.array(srcs)])  # rows = dst
    topo = CSRTopo(indptr=None, indices=None, edge_index=ei)
    x = rng.normal(size=(200, 24)).astype(np.float32)

    monkeypatch.setenv("QUIVER_INFER_AGG", "scatter")
    want = np.asarray(full_neighbor_mean(topo, x, chunk=128))
    monkeypatch.setenv("QUIVER_INFER_AGG", "scan")
    got = np.asarray(full_neighbor_mean(topo, x, chunk=128))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_scan_aggregation_layerwise_parity(monkeypatch):
    """sage_layerwise_inference end-to-end under both strategies."""
    import numpy as np
    import jax

    from quiver_tpu import CSRTopo
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.models.inference import sage_layerwise_inference

    rng = np.random.default_rng(3)
    ei = rng.integers(0, 150, size=(2, 2000)).astype(np.int64)
    topo = CSRTopo(edge_index=ei)
    x = rng.normal(size=(150, 16)).astype(np.float32)
    model = GraphSAGE(hidden=8, num_classes=3, num_layers=2)
    # params via a quick init on a tiny sampled block
    from quiver_tpu import GraphSageSampler

    s = GraphSageSampler(topo, [3, 3], seed_capacity=16)
    out = s.sample(np.arange(16))
    params = model.init(
        jax.random.PRNGKey(0), x[np.asarray(out.n_id) % 150], out.adjs
    )["params"]
    monkeypatch.setenv("QUIVER_INFER_AGG", "scatter")
    want = np.asarray(sage_layerwise_inference(model, params, topo, x,
                                               chunk=256))
    monkeypatch.setenv("QUIVER_INFER_AGG", "scan")
    got = np.asarray(sage_layerwise_inference(model, params, topo, x,
                                              chunk=256))
    np.testing.assert_allclose(got, want, rtol=5e-5, atol=5e-5)


def test_scan_aggregation_same_sign_precision(monkeypatch):
    """Regression for the prefix-cancellation hazard: ALL-POSITIVE
    (post-ReLU-like) features through a large chunk must still match the
    scatter path tightly — the mean-centering keeps the prefix at
    random-walk magnitude instead of chunk*mean."""
    import numpy as np

    from quiver_tpu import CSRTopo
    from quiver_tpu.models.inference import full_neighbor_mean

    rng = np.random.default_rng(11)
    n, e = 3000, 1 << 17  # one big chunk covers most edges
    ei = np.stack([rng.integers(0, n, e), rng.integers(0, n, e)])
    topo = CSRTopo(edge_index=ei)
    x = np.abs(rng.normal(size=(n, 8))).astype(np.float32) + 1.0  # same sign

    monkeypatch.setenv("QUIVER_INFER_AGG", "scatter")
    want = np.asarray(full_neighbor_mean(topo, x, chunk=1 << 17))
    monkeypatch.setenv("QUIVER_INFER_AGG", "scan")
    got = np.asarray(full_neighbor_mean(topo, x, chunk=1 << 17))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
