"""Guardrail satellites (ISSUE 1): QUIVER_CHECK layout assertion, honest
QUIVER_DEDUP contract, inert-parity-arg signals, and the DataParallelTrainer
auto-cap pinning that removes the mid-epoch _stack raise."""

import logging

import numpy as np
import jax.numpy as jnp
import optax
import pytest

from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.feature.feature import Feature
from quiver_tpu.models.layers import segment_mean_aggregate
from quiver_tpu.parallel.mesh import make_mesh
from quiver_tpu.utils import trace as trace_mod


@pytest.fixture(autouse=True)
def _fresh_once_keys():
    """info_once is once-per-process; tests need a fresh slate."""
    saved = set(trace_mod._ONCE_KEYS)
    trace_mod._ONCE_KEYS.clear()
    yield
    trace_mod._ONCE_KEYS.clear()
    trace_mod._ONCE_KEYS.update(saved)


# -- QUIVER_CHECK dense-layout assertion (ADVICE layers.py:93) -------------

def _regular_adj(num_dst=4, fanout=3, dim=2):
    msgs = np.arange(num_dst * fanout * dim, dtype=np.float32).reshape(
        num_dst * fanout, dim)
    dst = np.repeat(np.arange(num_dst), fanout)
    valid = np.ones(num_dst * fanout, bool)
    return jnp.asarray(msgs), jnp.asarray(dst), jnp.asarray(valid)


def _reset_check_cache(monkeypatch):
    # QUIVER_CHECK is resolved once per process (env-before-first-use —
    # the gate runs inside traced aggregation code, graftlint env-at-trace)
    from quiver_tpu.models import layers

    monkeypatch.setattr(layers, "_check_cache", None)


def test_quiver_check_passes_on_regular_layout(monkeypatch):
    _reset_check_cache(monkeypatch)
    monkeypatch.setenv("QUIVER_CHECK", "1")
    msgs, dst, valid = _regular_adj()
    out = segment_mean_aggregate(msgs, dst, valid, 4, fanout=3)
    assert out.shape == (4, 2)


def test_quiver_check_catches_layout_violation(monkeypatch):
    """A shape-coincident but WRONG fanout claim must fail loudly under
    QUIVER_CHECK instead of silently mis-aggregating."""
    _reset_check_cache(monkeypatch)
    monkeypatch.setenv("QUIVER_CHECK", "1")
    msgs, dst, valid = _regular_adj()
    bad_dst = jnp.asarray(np.roll(np.asarray(dst), 1))  # breaks regularity
    with pytest.raises(Exception, match="QUIVER_CHECK"):
        np.asarray(segment_mean_aggregate(msgs, bad_dst, valid, 4, fanout=3))


def test_quiver_check_off_by_default(monkeypatch):
    _reset_check_cache(monkeypatch)
    monkeypatch.delenv("QUIVER_CHECK", raising=False)
    msgs, dst, valid = _regular_adj()
    bad_dst = jnp.asarray(np.roll(np.asarray(dst), 1))
    # dense path trusts the claim (documented); no error without the flag
    out = segment_mean_aggregate(msgs, bad_dst, valid, 4, fanout=3)
    assert out.shape == (4, 2)


def test_dense_gate_shape_fallback_logged(caplog):
    """fanout set but E != num_dst*fanout: the silent revert to the
    segment-scatter path now logs once."""
    msgs, dst, valid = _regular_adj(num_dst=4, fanout=3)
    with caplog.at_level(logging.INFO, logger="quiver_tpu"):
        out = segment_mean_aggregate(msgs, dst, valid, 4, fanout=5)  # wrong
    assert out.shape == (4, 2)
    assert any("segment-scatter" in r.message for r in caplog.records)


# -- QUIVER_DEDUP honesty (ADVICE reindex.py:31) ---------------------------

def test_dedup_env_applies_to_auto_only_and_logs(monkeypatch, caplog):
    from quiver_tpu.ops import reindex as R

    # the force is read once per process (env-before-first-use); reset the
    # caches so this test's env value is the one resolved
    monkeypatch.setattr(R, "_forced_dedup", None)
    monkeypatch.setattr(R, "_auto_dedup", None)
    monkeypatch.setenv("QUIVER_DEDUP", "scan")
    assert R.resolve_dedup("auto") == "scan"  # env wins for auto
    with caplog.at_level(logging.INFO, logger="quiver_tpu"):
        assert R.resolve_dedup("sort") == "sort"  # explicit wins over env
    assert any("QUIVER_DEDUP" in r.message and "ignored" in r.message
               for r in caplog.records)
    monkeypatch.setattr(R, "_forced_dedup", None)
    monkeypatch.setattr(R, "_auto_dedup", None)  # leave no pin


# -- inert parity-arg signals (VERDICT r5 weak #7) -------------------------

def test_feature_inert_args_log_once(caplog):
    with caplog.at_level(logging.INFO, logger="quiver_tpu"):
        Feature(rank=1, device_list=[0, 1], device_cache_size="1M")
        Feature(rank=2, device_list=[2], device_cache_size="1M")
    inert = [r for r in caplog.records if "INERT" in r.message]
    assert len(inert) == 1  # one-shot


def test_feature_default_args_stay_silent(caplog):
    with caplog.at_level(logging.INFO, logger="quiver_tpu"):
        Feature(device_cache_size="1M")
    assert not any("INERT" in r.message for r in caplog.records)


def test_sampler_inert_device_logs_once(caplog):
    rng = np.random.default_rng(0)
    ei = np.stack([rng.integers(0, 50, 300), rng.integers(0, 50, 300)])
    topo = CSRTopo(edge_index=ei)
    with caplog.at_level(logging.INFO, logger="quiver_tpu"):
        GraphSageSampler(topo, [3], device=0)
        GraphSageSampler(topo, [3], device=1)
    inert = [r for r in caplog.records if "INERT" in r.message]
    assert len(inert) == 1


# -- DataParallelTrainer auto-cap pinning (VERDICT r5 weak #6) -------------

def _dp_setup(frontier_caps):
    from quiver_tpu.models.sage import GraphSAGE
    from quiver_tpu.parallel.trainer import DataParallelTrainer

    rng = np.random.default_rng(0)
    n = 300
    labels = rng.integers(0, 4, n)
    feat = rng.normal(size=(n, 6)).astype(np.float32)
    ei = np.stack([rng.integers(0, n, 2500), rng.integers(0, n, 2500)])
    topo = CSRTopo(edge_index=ei)
    sampler = GraphSageSampler(topo, [4, 3], seed_capacity=16, seed=2,
                               frontier_caps=frontier_caps)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    mesh = make_mesh(data=8, feature=1)
    model = GraphSAGE(hidden=8, num_classes=4, num_layers=2)
    trainer = DataParallelTrainer(mesh, sampler, feature, model,
                                  optax.adam(1e-3), local_batch=16)
    return trainer, topo, labels


def test_dp_trainer_pins_auto_caps_no_midepoch_raise():
    """auto caps + skewed blocks: construction pins the plan, so a whole
    epoch of diverse blocks stacks without the mid-epoch ValueError."""
    import jax

    trainer, topo, labels = _dp_setup("auto")
    assert trainer.sampler._auto_caps is False  # pinned at construction
    assert trainer.sampler._frontier_caps is not None
    params, opt = trainer.init(jax.random.PRNGKey(0))
    params, opt, loss, steps = trainer.train_epoch(
        params, opt, np.arange(topo.node_count), jnp.asarray(labels),
        jax.random.PRNGKey(1),
    )
    assert steps >= 1 and np.isfinite(loss)


def test_dp_trainer_fixed_caps_untouched():
    trainer, _, _ = _dp_setup(None)
    assert trainer.sampler._auto_caps is False


def test_dp_stack_carries_fanout_from_batches():
    """_stack reads per-layer fanout off the blocks' own Adjs (ADVICE
    trainer.py:446) — metadata agrees with the sampler's sizes."""
    import jax
    from quiver_tpu.parallel.pipeline import Batch

    trainer, topo, labels = _dp_setup(None)
    blocks = trainer.seed_blocks(np.arange(trainer.global_batch))
    batches = []
    for b in blocks:
        out = trainer.sampler.sample(b)
        batches.append(Batch(b, out, trainer.feature[out.n_id]))
    caps, fanouts, x, n_id, eis, bsz = trainer._stack(batches)
    # deepest-first, matching the step body's eis order
    assert fanouts == tuple(trainer.sampler.sizes)[::-1]
    assert len(caps) == 2
    # the carried metadata must keep the dense-path regression green: a
    # data=1 step through these batches must run (dense gate satisfied)
    assert all(f is not None for f in fanouts)
