"""graftlint v2 self-tests: the CFG/dominator engine, the interprocedural
guard propagation, the staleness/transaction/concurrency rule families,
and the v2 CLI surface (--select families, --changed, --sarif, --debt).

Everything here is pure-ast on tiny sources/fixtures — the whole module
runs in about a second and lives in the fast lane.
"""

import ast
import json
import os
import shutil
import subprocess
import textwrap

import pytest

from quiver_tpu.tools.lint import FAMILIES, RULES, lint_paths, main
from quiver_tpu.tools.lint.analysis import SourceFile, analyze
from quiver_tpu.tools.lint.cfg import (
    build_cfg,
    propagate_guard_establishers,
)

HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURES = os.path.join(HERE, "lint_fixtures")


def fx(name: str) -> str:
    return os.path.join(FIXTURES, name)


def rules_hit(result):
    return {f.rule for f in result.findings}


def _cfg_and_node(src: str, call_name: str):
    """Build the CFG of the first function in ``src`` and return it with
    the first call to ``call_name`` in that function."""
    tree = ast.parse(textwrap.dedent(src))
    func = tree.body[0]
    node = next(
        n for n in ast.walk(func)
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
        and n.func.id == call_name
    )
    return build_cfg(func), node


# -- dominance engine --------------------------------------------------------

def test_guard_before_read_dominates():
    cfg, read = _cfg_and_node(
        """
        def f(x):
            guard()
            return read(x)
        """, "read")
    assert "guard" in cfg.calls_dominating(read)


def test_guard_in_one_branch_does_not_dominate():
    cfg, read = _cfg_and_node(
        """
        def f(x):
            if x:
                guard()
            return read(x)
        """, "read")
    assert "guard" not in cfg.calls_dominating(read)


def test_guard_after_read_does_not_dominate():
    cfg, read = _cfg_and_node(
        """
        def f(x):
            v = read(x)
            guard()
            return v
        """, "read")
    assert "guard" not in cfg.calls_dominating(read)


def test_guard_inside_loop_does_not_dominate_after():
    # the loop body may run zero times
    cfg, read = _cfg_and_node(
        """
        def f(xs):
            for x in xs:
                guard()
            return read(xs)
        """, "read")
    assert "guard" not in cfg.calls_dominating(read)


def test_guard_in_try_body_does_not_dominate_after_handler():
    # the handler path reaches the read without running the guard's
    # successor statements; the guard ITSELF (first try statement) still
    # dominates because the exception can only fire at/after it
    cfg, read = _cfg_and_node(
        """
        def f(x):
            try:
                guard()
                other()
            except ValueError:
                pass
            return read(x)
        """, "read")
    assert "other" not in cfg.calls_dominating(read)


def test_exit_dominating_calls_establishes_guard():
    cfg, _ = _cfg_and_node(
        """
        def f(x):
            guard()
            return read(x)
        """, "read")
    assert "guard" in cfg.exit_dominating_calls()

    cfg2, _ = _cfg_and_node(
        """
        def f(x):
            if x:
                guard()
            return read(x)
        """, "read")
    assert "guard" not in cfg2.exit_dominating_calls()


def test_propagate_guard_establishers_interprocedural():
    src = textwrap.dedent("""
        class VersionMismatchError(RuntimeError):
            pass


        def check(v):
            if v:
                raise VersionMismatchError("stale")


        def ensure(v):
            check(v)


        def branch_only(v):
            if v:
                check(v)
    """)
    project = analyze([SourceFile(path="m.py", text=src,
                                  tree=ast.parse(src))])
    names = propagate_guard_establishers(project, {"check"})
    assert "ensure" in names  # guards on every exit -> is a guard
    assert "branch_only" not in names  # one branch only -> is not


# -- staleness family --------------------------------------------------------

def test_staleness_fixtures():
    pos = lint_paths([fx("staleness_pos.py")])
    hits = [f for f in pos.findings if f.rule == "stale-version-read"]
    # guard in one branch + guard after the read
    assert len(hits) == 2
    assert {("lookup" in f.message or "lookup_late" in f.message)
            for f in hits} == {True}
    assert all("dominating version check" in f.message for f in hits)

    neg = lint_paths([fx("staleness_neg.py")])
    assert "stale-version-read" not in rules_hit(neg)


def test_staleness_pos_is_invisible_to_v1_rules():
    """The acceptance seed: the PR-8 version-guard violation that v1
    graftlint (reachability only, no dominance) cannot catch but v2
    does."""
    v1_rules = list(FAMILIES["trace"]) + list(FAMILIES["consistency"])
    v1 = lint_paths([fx("staleness_pos.py")], select=v1_rules)
    assert not v1.findings  # v1 is blind to it
    v2 = lint_paths([fx("staleness_pos.py")], select=["staleness"])
    assert len(v2.findings) == 2  # v2 catches both shapes


# -- transaction family ------------------------------------------------------

def test_transaction_fixtures():
    pos = lint_paths([fx("txn_checkpoint_pos.py")])
    assert rules_hit(pos) == {"non-atomic-publish", "commit-marker-order",
                              "replace-without-fsync"}
    assert len(pos.findings) == 3

    neg = lint_paths([fx("txn_checkpoint_neg.py")])
    assert not neg.findings  # helper + temp + fsync + marker-last + append


def test_transaction_scope_is_limited():
    """A module outside the transactional scope (no save-path name, no
    os.replace) may write bare paths freely — ledgers and reports are a
    different idiom."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "report.py")
        with open(p, "w") as fh:
            fh.write("def dump(path, text):\n"
                     "    with open(path, 'w') as out:\n"
                     "        out.write(text)\n")
        res = lint_paths([p])
        assert "non-atomic-publish" not in rules_hit(res)


# -- concurrency family ------------------------------------------------------

def test_executor_fixtures():
    pos = lint_paths([fx("executor_pos.py")])
    hits = [f for f in pos.findings if f.rule == "executor-lifecycle"]
    assert len(hits) == 2  # class-owned without close + local never shut
    assert any("Leaky._pool" in f.message for f in hits)
    assert any("run_batch" in f.message for f in hits)

    neg = lint_paths([fx("executor_neg.py")])
    assert "executor-lifecycle" not in rules_hit(neg)


def test_lock_fixtures():
    pos = lint_paths([fx("lock_pos.py")])
    hits = [f for f in pos.findings if f.rule == "lock-held-across-call"]
    assert len(hits) == 2  # direct re-entry + one call deep
    assert any("self.flush()" in f.message for f in hits)
    assert any("self.helper()" in f.message for f in hits)

    neg = lint_paths([fx("lock_neg.py")])
    assert "lock-held-across-call" not in rules_hit(neg)


def test_metric_name_fixtures():
    pos = lint_paths([fx("metric_name_pos.py")])
    hits = [f for f in pos.findings if f.rule == "metric-name-constant"]
    assert len(hits) == 2
    assert any("ROUTED_OVERFLOW" in f.message for f in hits)  # use const
    assert any("matches no declared" in f.message for f in hits)  # drift

    neg = lint_paths([fx("metric_name_neg.py")])
    assert "metric-name-constant" not in rules_hit(neg)


# -- family selection --------------------------------------------------------

def test_family_select_and_ignore():
    pos = lint_paths([fx("txn_checkpoint_pos.py")], select=["transaction"])
    assert len(pos.findings) == 3
    none = lint_paths([fx("txn_checkpoint_pos.py")], select=["staleness"])
    assert not none.findings
    ignored = lint_paths([fx("txn_checkpoint_pos.py")],
                         ignore=["transaction"])
    assert not ignored.findings
    with pytest.raises(ValueError):
        lint_paths([fx("txn_checkpoint_pos.py")], select=["bogus-family"])


def test_families_cover_registry_exactly():
    members = [r for fam in FAMILIES.values() for r in fam]
    assert sorted(members) == sorted(RULES)  # no orphans, no dupes


def test_cli_list_rules_groups_by_family(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for fam in FAMILIES:
        assert f"[{fam}]" in out
    for rule in RULES:
        assert rule in out


# -- SARIF output ------------------------------------------------------------

def test_sarif_output(tmp_path, capsys):
    sarif_path = tmp_path / "lint.sarif"
    rc = main([fx("txn_checkpoint_pos.py"), "--sarif", str(sarif_path)])
    capsys.readouterr()
    assert rc == 1  # findings still fail the run
    doc = json.loads(sarif_path.read_text())
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "graftlint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert rule_ids == set(RULES)
    results = run["results"]
    assert {r["ruleId"] for r in results} == {
        "non-atomic-publish", "commit-marker-order",
        "replace-without-fsync"}
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("txn_checkpoint_pos.py")
    assert loc["region"]["startLine"] >= 1


def test_sarif_marks_suppressed_results(tmp_path, capsys):
    src = textwrap.dedent("""\
        import os
        import jax


        @jax.jit
        def step(x):
            # graftlint: disable=env-at-trace -- fixture: frozen by design
            flag = os.environ.get("FLAG", "0")
            return x if flag == "0" else -x
    """)
    p = tmp_path / "m.py"
    p.write_text(src)
    sarif_path = tmp_path / "out.sarif"
    assert main([str(p), "--sarif", str(sarif_path)]) == 0
    capsys.readouterr()
    doc = json.loads(sarif_path.read_text())
    results = doc["runs"][0]["results"]
    assert len(results) == 1
    assert results[0]["suppressions"][0]["kind"] == "inSource"


# -- debt report -------------------------------------------------------------

def test_debt_report(tmp_path, capsys):
    src = textwrap.dedent("""\
        import os
        import jax


        # graftlint: eager -- fixture: between-batch tuner
        def tuner(store):
            return os.environ.get("K")


        @jax.jit
        def step(x):
            # graftlint: disable=env-at-trace -- fixture: frozen by design
            flag = os.environ.get("FLAG", "0")
            return x if flag == "0" else -x
    """)
    p = tmp_path / "m.py"
    p.write_text(src)
    assert main([str(p), "--json", "--debt"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["version"] == 2
    debt = out["debt"]
    assert debt["total"] == 2
    kinds = {rec["kind"] for rec in debt["annotations"]}
    assert kinds == {"disable", "eager"}
    reasons = {rec["reason"] for rec in debt["annotations"]}
    assert "fixture: frozen by design" in reasons
    # text mode renders the table
    assert main([str(p), "--debt"]) == 0
    text = capsys.readouterr().out
    assert "graftlint debt: 2 reasoned annotation(s)" in text
    assert "env-at-trace" in text


def test_annotations_ride_lint_result():
    res = lint_paths([fx("env_at_trace_neg.py"), fx("staleness_neg.py")])
    # no annotations in these fixtures; the field exists and is a list
    assert res.annotations == []
    assert res.to_dict()["annotations"] == []


# -- --changed mode ----------------------------------------------------------

@pytest.mark.skipif(shutil.which("git") is None, reason="git unavailable")
def test_changed_mode_reports_only_diffed_files(tmp_path, monkeypatch,
                                                capsys):
    repo = tmp_path / "repo"
    repo.mkdir()

    def git(*args):
        subprocess.run(["git", *args], cwd=repo, check=True,
                       capture_output=True)

    git("init", "-q")
    git("config", "user.email", "t@example.com")
    git("config", "user.name", "t")
    violation = ("import concurrent.futures\n\n\n"
                 "def leak(items):\n"
                 "    pool = concurrent.futures.ThreadPoolExecutor()\n"
                 "    return [pool.submit(it) for it in items]\n")
    (repo / "a.py").write_text(violation)
    (repo / "b.py").write_text("x = 1\n")
    git("add", ".")
    git("commit", "-q", "-m", "base")
    # b.py grows a violation in the worktree; a.py's is pre-existing
    (repo / "b.py").write_text(violation.replace("leak", "leak_b"))
    monkeypatch.chdir(repo)
    assert main([str(repo), "--changed", "HEAD", "--json"]) == 1
    out = json.loads(capsys.readouterr().out)
    paths = {f["path"] for f in out["findings"]}
    assert all(p.endswith("b.py") for p in paths), paths
    assert out["findings"]  # b's finding IS reported
    # full run still sees both
    assert main([str(repo), "--json"]) == 1
    full = json.loads(capsys.readouterr().out)
    assert len(full["findings"]) == 2


def test_changed_mode_bad_base_is_usage_error(tmp_path, capsys):
    p = tmp_path / "clean.py"
    p.write_text("x = 1\n")
    rc = main([str(p), "--changed", "no-such-base-ref-xyz"])
    capsys.readouterr()
    assert rc == 2


# -- the fixed true positive stays fixed -------------------------------------

def test_build_graph_cache_publish_is_fsynced():
    """Regression for the PR-9 true positive: benchmarks/common.py's
    graph-cache publish fsyncs before its os.replace (a crash must not
    surface a torn cache at the final name)."""
    repo = os.path.dirname(HERE)
    res = lint_paths([os.path.join(repo, "benchmarks", "common.py")],
                     select=["transaction"])
    assert res.findings == [], [
        f"{f.path}:{f.line}: {f.rule}" for f in res.findings]
