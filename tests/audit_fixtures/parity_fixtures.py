"""collective-parity fixtures: a de-synced cond fallback (positive) and
the repo's psum-gated discipline (negative)."""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from quiver_tpu.parallel.mesh import FEATURE_AXIS, make_mesh, shard_map
from quiver_tpu.tools.audit.audit_targets import Target


def _traced(body):
    mesh = make_mesh(2, data=1, feature=2)
    fn = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(FEATURE_AXIS),), out_specs=P(FEATURE_AXIS),
        check_vma=False,
    ))
    return fn.trace(jax.ShapeDtypeStruct((8,), jnp.float32))


def _pos():
    def body(x):
        # the bug graftaudit exists for: the predicate is a LOCAL value
        # (never reduced over 'feature'), so mesh members can disagree —
        # one enters the psum, its peer does not, and the mesh deadlocks
        pred = x[0] > 0.0
        return jax.lax.cond(
            pred,
            lambda v: jax.lax.psum(v, FEATURE_AXIS),
            lambda v: v * 2.0,
            x,
        )

    return _traced(body)


def _neg():
    def body(x):
        # routing.py's fallback discipline: psum the predicate first, so
        # every member of the axis takes the same branch
        pred = jax.lax.psum(jnp.sum(x), FEATURE_AXIS) > 0.0
        return jax.lax.cond(
            pred,
            lambda v: jax.lax.psum(v, FEATURE_AXIS),
            lambda v: v * 2.0,
            x,
        )

    return _traced(body)


def targets():
    src = ("tests/audit_fixtures/parity_fixtures.py",)
    return [
        (Target("parity_pos", "de-synced cond fallback", _pos, src), True),
        (Target("parity_neg", "psum-gated cond fallback", _neg, src), False),
    ]
