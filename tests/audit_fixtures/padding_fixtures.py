"""padding-waste fixtures: a routed all_to_all whose bucket cap is 8x
the declared demand — 87.5% of the shipped lanes are padding bought with
real HBM and wire bytes (positive) — vs the exact analytic cap, where
every lane is payload (negative)."""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from quiver_tpu.control.cost import routed_lanes_per_hop
from quiver_tpu.parallel.mesh import FEATURE_AXIS, make_mesh, shard_map
from quiver_tpu.tools.audit.audit_targets import Target

_F = 2
_LOCAL = 16
_ALPHA = 1.0
_FEAT = 4


def _program(cap):
    mesh = make_mesh(2, data=1, feature=2)

    def body(ids, rows):
        routed = jax.lax.all_to_all(
            ids.reshape(_F, cap), FEATURE_AXIS, 0, 0)
        payload = jax.lax.all_to_all(
            rows.reshape(_F, cap, _FEAT), FEATURE_AXIS, 0, 0)
        return payload.reshape(_F * cap, _FEAT)[
            jnp.clip(routed.reshape(-1), 0, _F * cap - 1)
        ]

    fn = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(FEATURE_AXIS), P(FEATURE_AXIS, None)),
        out_specs=P(FEATURE_AXIS, None),
        check_vma=False,
    ))
    return fn.trace(
        jax.ShapeDtypeStruct((2 * _F * cap,), jnp.int32),
        jax.ShapeDtypeStruct((2 * _F * cap, _FEAT), jnp.float32),
    )


def targets():
    src = ("tests/audit_fixtures/padding_fixtures.py",)
    meta = {"comm": {"feature_shards": _F, "local_len": _LOCAL,
                     "alpha": _ALPHA, "feature_dim": _FEAT}}
    model_cap = int(routed_lanes_per_hop(_LOCAL, _F, _ALPHA)["cap"])
    return [
        # 8x the analytic cap: waste = 1 - 16/128 = 0.875 > 0.6
        (Target("padding_overcap", "cap over-provisioned 8x for the route",
                lambda: _program(8 * model_cap), src, meta=meta), True),
        # the exact cap: waste = 1 - 16/16 = 0
        (Target("padding_exact", "every shipped lane is payload",
                lambda: _program(model_cap), src, meta=meta), False),
    ]
