"""vmem-budget fixtures: a Pallas block spec whose resident VMEM blocks
overflow a TPU core's ~16 MB (positive) vs a tile that fits (negative).
Interpret-mode, trace-only — the block *shapes* are the invariant."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from quiver_tpu.tools.audit.audit_targets import Target


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def _program(n):
    def fn(x):
        return pl.pallas_call(
            _kernel,
            grid=(1,),
            in_specs=[pl.BlockSpec((n, n), lambda i: (0, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((n, n), lambda i: (0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((n, n), jnp.float32),
            interpret=True,
        )(x)

    return jax.jit(fn).trace(jax.ShapeDtypeStruct((n, n), jnp.float32))


def targets():
    src = ("tests/audit_fixtures/vmem_fixtures.py",)
    return [
        # in + out blocks are 16 MiB EACH: 32 MiB resident > the 16 MiB
        # per-core budget — this block spec cannot schedule on a TPU core
        (Target("vmem_overrun", "resident Pallas blocks overflow VMEM",
                lambda: _program(2048), src), True),
        (Target("vmem_within", "tile fits the per-core VMEM budget",
                lambda: _program(64), src), False),
    ]
