"""dtype-discipline fixtures: an f64 leak and an int8-path upcast
(positives); the disciplined int8 wire (negative)."""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from quiver_tpu.parallel.mesh import FEATURE_AXIS, make_mesh, shard_map
from quiver_tpu.tools.audit.audit_targets import Target


def _f64_leak():
    def run(x):
        # constant-free f64 region (convert/add only): lowers consistently
        # even when the audit process itself runs x64-disabled
        wide = jnp.asarray(x, jnp.float64)
        return (wide + wide).astype(jnp.float32)

    # trace under x64 so the f64 actually lands in the jaxpr — the leak
    # an accidentally-enabled flag (or a numpy f64 operand) produces
    with jax.experimental.enable_x64():
        return jax.jit(run).trace(jax.ShapeDtypeStruct((8,), jnp.float32))


def _a2a(dtype):
    mesh = make_mesh(2, data=1, feature=2)

    def body(codes):
        # codes is the (4,) local block of the int8 id/row stream
        routed = jax.lax.all_to_all(
            codes.astype(dtype).reshape(2, 2), FEATURE_AXIS, 0, 0
        )
        return routed.reshape(4).astype(jnp.float32)

    fn = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(FEATURE_AXIS),), out_specs=P(FEATURE_AXIS),
        check_vma=False,
    ))
    return fn.trace(jax.ShapeDtypeStruct((8,), jnp.int8))


def targets():
    src = ("tests/audit_fixtures/dtype_fixtures.py",)
    return [
        (Target("dtype_f64_leak", "x64 value inside the program",
                _f64_leak, src), True),
        # int8 tier path whose codes were dequantized BEFORE routing —
        # the wire carries f32, 4x the bytes
        (Target("dtype_int8_upcast", "f32 all_to_all on the int8 path",
                lambda: _a2a(jnp.float32), src,
                meta={"int8_path": True}), True),
        (Target("dtype_int8_wire", "int8 codes ride the all_to_all",
                lambda: _a2a(jnp.int8), src,
                meta={"int8_path": True}), False),
    ]
