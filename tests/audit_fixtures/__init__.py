"""Seeded positive/negative programs for the graftaudit rule families.

Each module exposes ``targets()`` returning
``[(Target, should_fire: bool)]`` — real traced programs (not mocked
IR) so the fixtures break loudly if jax's lowering of the audited
construct ever changes shape. ``tests/test_audit.py`` builds each with
``audit_targets.build_from`` and asserts every positive is caught and
every negative stays clean.
"""
