"""no-silent-replication fixtures: a feature-sharded table gathered to
full replication on every device (positive) vs the same traffic routed
through all_to_all, which keeps per-device bytes constant (negative)."""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from quiver_tpu.parallel.mesh import FEATURE_AXIS, make_mesh, shard_map
from quiver_tpu.tools.audit.audit_targets import Target

_N, _F = 64, 32  # global table: (64, 32) f32, feature-sharded to (32, 32)


def _gather_program():
    mesh = make_mesh(2, data=1, feature=2)

    def body(x):
        # the silent-replication cliff: every device materializes the
        # FULL (64, 32) table — 8192 bytes, F x the sharded footprint
        g = jax.lax.all_gather(x, FEATURE_AXIS, tiled=True)
        return g.sum(axis=0)

    fn = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P(FEATURE_AXIS, None),),
        out_specs=P(), check_vma=False,
    ))
    return fn.trace(jax.ShapeDtypeStruct((_N, _F), jnp.float32))


def _routed_program():
    mesh = make_mesh(2, data=1, feature=2)

    def body(x):
        # same bytes exchanged, but per-device residency stays (32, 32)
        r = jax.lax.all_to_all(x.reshape(2, _N // 4, _F), FEATURE_AXIS,
                               0, 0)
        return r.reshape(_N // 2, _F).sum(axis=0)

    fn = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P(FEATURE_AXIS, None),),
        out_specs=P(), check_vma=False,
    ))
    return fn.trace(jax.ShapeDtypeStruct((_N, _F), jnp.float32))


def targets():
    src = ("tests/audit_fixtures/replication_fixtures.py",)
    return [
        (Target("replication_gather",
                "feature-axis all_gather replicates the table",
                _gather_program, src), True),
        (Target("replication_routed",
                "all_to_all keeps per-device bytes constant",
                _routed_program, src), False),
    ]
