"""constant-bloat fixtures: a closure-folded table (positive) vs the same
table passed as an operand (negative)."""

import jax
import jax.numpy as jnp
import numpy as np

from quiver_tpu.tools.audit.audit_targets import Target

_TABLE = np.arange(8192, dtype=np.float32).reshape(1024, 8)  # 32 KiB
_LIMIT = 16 * 1024


def _folded():
    table = jnp.asarray(_TABLE)

    def run(ids):
        return table[ids]  # table rides the closure -> a program constant

    return jax.jit(run).trace(jax.ShapeDtypeStruct((4,), jnp.int32))


def _operand():
    def run(table, ids):
        return table[ids]

    return jax.jit(run).trace(
        jax.ShapeDtypeStruct(_TABLE.shape, jnp.float32),
        jax.ShapeDtypeStruct((4,), jnp.int32),
    )


def targets():
    src = ("tests/audit_fixtures/constant_fixtures.py",)
    meta = {"const_bytes_limit": _LIMIT}  # keep the fixture table small
    return [
        (Target("const_folded", "closure-captured feature table",
                _folded, src, meta=meta), True),
        (Target("const_operand", "table passed as an argument",
                _operand, src, meta=meta), False),
    ]
