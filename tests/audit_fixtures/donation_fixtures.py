"""donation-audit fixtures: an unusable donation and an unclaimed-donation
mismatch (positives); an honest donated accumulator (negative)."""

import jax
import jax.numpy as jnp

from quiver_tpu.tools.audit.audit_targets import Target


def _unusable():
    # (8,) can never alias the (2,) output: jax warns at lower time and
    # the donation lowers to no attr at all — the serve-forward bug shape
    def run(x, y):
        return jnp.sum(x.reshape(2, 4), axis=1) + y

    return jax.jit(run, donate_argnums=0).trace(
        jax.ShapeDtypeStruct((8,), jnp.float32),
        jax.ShapeDtypeStruct((2,), jnp.float32),
    )


def _honest():
    # same-shape accumulate: the donated arg aliases the output
    def run(acc, upd):
        return acc + upd

    return jax.jit(run, donate_argnums=0).trace(
        jax.ShapeDtypeStruct((8,), jnp.float32),
        jax.ShapeDtypeStruct((8,), jnp.float32),
    )


def targets():
    src = ("tests/audit_fixtures/donation_fixtures.py",)
    return [
        (Target("donation_unusable", "warning-only donation", _unusable,
                src, meta={"donation": "none"}), True),
        # claims one donated leaf but donates nothing
        (Target("donation_unclaimed", "claimed leaf never donated",
                lambda: jax.jit(lambda x: x * 2.0).trace(
                    jax.ShapeDtypeStruct((8,), jnp.float32)),
                src, meta={"donated_leaves": 1}), True),
        (Target("donation_honest", "aliased accumulator donation",
                _honest, src, meta={"donated_leaves": 1}), False),
    ]
