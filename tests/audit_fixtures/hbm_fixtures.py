"""peak-hbm-budget fixtures: the same matmul program over-budget
(positive), entering the registry unpriced (positive — a missing
``hbm_budget`` is itself the finding), and honestly priced (negative)."""

import jax
import jax.numpy as jnp

from quiver_tpu.tools.audit.audit_targets import Target


def _program():
    def body(x):
        y = jnp.tanh(x @ x.T)  # (64, 64) f32 intermediate live with x
        return y.sum(axis=1)

    return jax.jit(body).trace(
        jax.ShapeDtypeStruct((64, 32), jnp.float32))


def targets():
    src = ("tests/audit_fixtures/hbm_fixtures.py",)
    return [
        # args 8192 B + the (64,64) intermediate 16384 B dwarf the budget
        (Target("hbm_overrun", "liveness peak exceeds hbm_budget",
                _program, src, meta={"hbm_budget": 1024}), True),
        (Target("hbm_unpriced", "no hbm_budget declared",
                _program, src, meta={}), True),
        (Target("hbm_within", "liveness peak fits hbm_budget",
                _program, src, meta={"hbm_budget": 1 << 20}), False),
    ]
