"""metrics-strip fixtures: a psum surviving ``collect_metrics=False``
(positive) and a correctly stripped pair (negative)."""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from quiver_tpu.parallel.mesh import DATA_AXIS, FEATURE_AXIS, make_mesh, \
    shard_map
from quiver_tpu.tools.audit.audit_targets import Target


def _traced(body):
    mesh = make_mesh(2, data=1, feature=2)
    fn = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(FEATURE_AXIS),), out_specs=P(FEATURE_AXIS),
        check_vma=False,
    ))
    return fn.trace(jax.ShapeDtypeStruct((8,), jnp.float32))


def _step(metric_psums):
    def body(x):
        # x is the (4,) LOCAL block; the "training math" is one
        # data-movement collective that must be identical on/off
        y = jax.lax.all_to_all(x.reshape(2, 2), FEATURE_AXIS, 0, 0)
        out = y.reshape(4) * 2.0
        for _ in range(metric_psums):
            # a telemetry reduction riding alongside the math
            out = out + 0.0 * jax.lax.psum(jnp.sum(x), DATA_AXIS)
        return out

    return body


def targets():
    src = ("tests/audit_fixtures/metrics_fixtures.py",)
    on = Target("metrics_fix_on", "metrics-on half of the pair",
                lambda: _traced(_step(1)), src)
    # positive: the "off" program kept a psum the on program doesn't even
    # have (a metric collective survived the strip — and worse, drifted)
    off_leaky = Target(
        "metrics_fix_off_leaky", "psum survives collect_metrics=False",
        lambda: _traced(_step(2)), src,
        meta={"metrics_pair": "metrics_fix_on",
              "expected_metric_reductions": 1},
    )
    # negative: off == on minus exactly the declared telemetry reduction
    off_clean = Target(
        "metrics_fix_off_clean", "correctly stripped program",
        lambda: _traced(_step(0)), src,
        meta={"metrics_pair": "metrics_fix_on",
              "expected_metric_reductions": 1},
    )
    return [(on, False), (off_leaky, True), (off_clean, False)]
