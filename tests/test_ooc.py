"""quiver-ooc tests: raw format durability, mmap/pread stores bitwise-
identical to the in-RAM Feature, staged reads under faults, and the
disk-tier control loop.

The contract under test is the tentpole's: moving the cold tier from
host RAM to disk changes WHERE bytes come from and nothing else — same
translated row space, same gathers, same sampled batches, same losses,
at f32 and int8, single-device and through the 2-device data-parallel
trainer."""

import os
import shutil

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.feature.feature import Feature
from quiver_tpu.ooc import (
    AsyncStager,
    CorruptRawDir,
    MmapFeatureStore,
    load_raw_dir,
    quarantine_raw_dir,
    save_raw_dir,
    verify_raw_dir,
)


def _graph(n=200, deg=6, seed=0):
    rng = np.random.default_rng(seed)
    ei = rng.integers(0, n, size=(2, deg * n)).astype(np.int64)
    feat = rng.standard_normal((n, 16)).astype(np.float32)
    return ei, feat


# -- raw format ---------------------------------------------------------------


def test_raw_dir_roundtrip_and_verify(tmp_path):
    p = str(tmp_path / "raw")
    arrays = {
        "a": np.arange(100, dtype=np.int64),
        "b": np.random.default_rng(0).random((10, 4)).astype(np.float32),
    }
    manifest = save_raw_dir(p, arrays, meta={"k": "v"})
    assert set(manifest["arrays"]) == {"a", "b"}
    for mmap in (False, True):
        loaded, meta = load_raw_dir(p, mmap=mmap)
        assert meta == {"k": "v"}
        for name in arrays:
            np.testing.assert_array_equal(np.asarray(loaded[name]),
                                          arrays[name])
    verify_raw_dir(p)  # full CRC sweep passes


def test_raw_dir_replaces_existing_atomically(tmp_path):
    p = str(tmp_path / "raw")
    save_raw_dir(p, {"a": np.zeros(4)})
    save_raw_dir(p, {"a": np.ones(4)})
    loaded, _ = load_raw_dir(p, mmap=False)
    np.testing.assert_array_equal(np.asarray(loaded["a"]), np.ones(4))
    # no stray temp/old dirs survive the replace
    assert sorted(os.listdir(tmp_path)) == ["raw"]


def test_torn_raw_dir_detected_and_quarantined(tmp_path):
    p = str(tmp_path / "raw")
    save_raw_dir(p, {"a": np.arange(1000, dtype=np.float64)})
    fpath = os.path.join(p, "a.npy")
    with open(fpath, "r+b") as fh:
        fh.truncate(os.path.getsize(fpath) - 16)  # torn write
    with pytest.raises(CorruptRawDir, match="truncated or torn"):
        load_raw_dir(p, mmap=True)
    dest = quarantine_raw_dir(p)
    assert not os.path.exists(p)
    assert os.path.basename(dest).startswith("quarantine-")


def test_raw_dir_crc_catches_flipped_bytes(tmp_path):
    p = str(tmp_path / "raw")
    save_raw_dir(p, {"a": np.arange(1000, dtype=np.float64)})
    fpath = os.path.join(p, "a.npy")
    size = os.path.getsize(fpath)
    with open(fpath, "r+b") as fh:  # same size, different bytes
        fh.seek(size // 2)
        fh.write(b"\xff\xfe")
    load_raw_dir(p, mmap=True)  # structural checks alone can't see it
    with pytest.raises(CorruptRawDir, match="checksum mismatch"):
        verify_raw_dir(p)


def test_uncommitted_raw_dir_rejected(tmp_path):
    p = str(tmp_path / "raw")
    save_raw_dir(p, {"a": np.zeros(4)})
    os.unlink(os.path.join(p, "COMMIT"))
    with pytest.raises(CorruptRawDir, match="COMMIT"):
        load_raw_dir(p)


# -- CSRTopo raw persistence --------------------------------------------------


def test_topology_raw_save_load_bitwise(tmp_path):
    ei, _ = _graph()
    topo = CSRTopo(edge_index=ei)
    topo.set_edge_weight(np.random.default_rng(1).random(ei.shape[1]))
    topo.feature_order = np.random.default_rng(2).permutation(
        topo.node_count
    )
    p = str(tmp_path / "topo.raw")
    topo.save(p, format="raw")
    for mmap in (False, True):
        t = CSRTopo.load(p, mmap=mmap)
        np.testing.assert_array_equal(np.asarray(t.indptr), topo.indptr)
        np.testing.assert_array_equal(np.asarray(t.indices), topo.indices)
        np.testing.assert_array_equal(np.asarray(t.eid), topo.eid)
        # cum_weights persisted, not recomputed: bitwise, not just close
        np.testing.assert_array_equal(
            np.asarray(t.cum_weights), topo.cum_weights
        )
        np.testing.assert_array_equal(
            np.asarray(t.feature_order), topo.feature_order
        )
        assert t.max_degree == topo.max_degree  # manifest-cached
        assert t.node_count == topo.node_count
        assert t.edge_count == topo.edge_count
    t = CSRTopo.load(p, mmap=True)
    assert isinstance(t.indices, np.memmap)  # genuinely lazy residency


def test_topology_mmap_load_samples_identically(tmp_path):
    """A sampler driven off the mmap-loaded topology draws the same
    batches as one on the in-RAM original."""
    ei, _ = _graph(n=150)
    topo = CSRTopo(edge_index=ei)
    p = str(tmp_path / "topo.raw")
    topo.save(p, format="raw")
    mtopo = CSRTopo.load(p, mmap=True)
    seeds = np.random.default_rng(3).integers(0, 150, 32)
    a = GraphSageSampler(topo, [4, 3], seed_capacity=32, seed=7).sample(seeds)
    b = GraphSageSampler(mtopo, [4, 3], seed_capacity=32, seed=7).sample(seeds)
    np.testing.assert_array_equal(np.asarray(a.n_id), np.asarray(b.n_id))
    for adj_a, adj_b in zip(a.adjs, b.adjs):
        np.testing.assert_array_equal(
            np.asarray(adj_a.edge_index), np.asarray(adj_b.edge_index)
        )


def test_topology_mmap_on_npz_raises_clear_error(tmp_path):
    ei, _ = _graph(n=50)
    topo = CSRTopo(edge_index=ei)
    p = str(tmp_path / "topo.npz")
    topo.save(p)
    with pytest.raises(ValueError, match='format="raw"'):
        CSRTopo.load(p, mmap=True)


# -- legacy .npz integrity (satellite) ---------------------------------------


def test_npz_save_embeds_crc_and_load_verifies(tmp_path):
    ei, _ = _graph(n=80)
    topo = CSRTopo(edge_index=ei)
    p = str(tmp_path / "topo.npz")
    topo.save(p)
    with np.load(p) as z:
        assert "_integrity" in z.files  # CRC record rides the archive
    t = CSRTopo.load(p)  # verifies silently
    np.testing.assert_array_equal(t.indices, topo.indices)


def test_npz_corrupt_bytes_rejected(tmp_path):
    """Regression: a byte flip inside a member must fail the load with a
    clear error naming the artifact — not surface as silently wrong
    samples three layers later."""
    ei, _ = _graph(n=80)
    topo = CSRTopo(edge_index=ei)
    p = str(tmp_path / "topo.npz")
    topo.save(p)
    with open(p, "r+b") as fh:
        fh.seek(os.path.getsize(p) // 2)
        fh.write(b"\xff\xfe\xfd\xfc")
    with pytest.raises(ValueError, match="corrupt"):
        CSRTopo.load(p)


def test_npz_truncated_file_rejected(tmp_path):
    ei, _ = _graph(n=80)
    topo = CSRTopo(edge_index=ei)
    p = str(tmp_path / "topo.npz")
    topo.save(p)
    with open(p, "r+b") as fh:
        fh.truncate(os.path.getsize(p) // 2)
    with pytest.raises(ValueError, match=p):
        CSRTopo.load(p)


def test_npz_without_integrity_record_still_loads(tmp_path):
    """Pre-record archives (no ``_integrity`` member) load unverified —
    backward compatibility with every artifact saved before this PR."""
    ei, _ = _graph(n=60)
    topo = CSRTopo(edge_index=ei)
    p = str(tmp_path / "legacy.npz")
    np.savez(p, indptr=topo.indptr, indices=topo.indices)
    t = CSRTopo.load(p)
    np.testing.assert_array_equal(t.indices, topo.indices)


# -- MmapFeatureStore bitwise parity -----------------------------------------


def _ids(n, seed=11):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, n, 64).astype(np.int32)
    ids[5] = -1  # padding lanes
    ids[40] = -1
    return ids


@pytest.mark.parametrize("access", ["mmap", "pread"])
@pytest.mark.parametrize("dtype", [None, "int8"])
def test_store_gathers_bitwise_equal_feature(tmp_path, access, dtype):
    """The core differential: every gather from the disk-backed store is
    bit-for-bit the in-RAM Feature's, at f32 and int8, in both access
    modes — hot rows, cold rows, padding lanes, repeated ids."""
    ei, feat = _graph()
    n = feat.shape[0]
    topo_a = CSRTopo(edge_index=ei)
    topo_b = CSRTopo(edge_index=ei)
    budget = (4 * n + 50 * feat.shape[1]) if dtype == "int8" \
        else 50 * feat.shape[1] * 4  # 50 hot rows either way
    feature = Feature(
        device_cache_size=budget, csr_topo=topo_a, dtype=dtype
    ).from_cpu_tensor(feat.copy())
    p = str(tmp_path / "rows")
    MmapFeatureStore.write(p, feat.copy(), device_cache_size=budget,
                           csr_topo=topo_b, dtype=dtype)
    store = MmapFeatureStore(p, access=access, window_rows=16,
                             cache_windows=8)
    assert store.hot_rows == feature.hot_rows == 50
    np.testing.assert_array_equal(
        np.asarray(topo_a.feature_order), np.asarray(topo_b.feature_order)
    )
    for seed in range(3):
        ids = _ids(n, seed)
        a = np.asarray(feature[jnp.asarray(ids)])
        b = np.asarray(store[jnp.asarray(ids)])
        np.testing.assert_array_equal(a, b)
        assert a.dtype == b.dtype
    store.close()


def test_store_parity_survives_restage(tmp_path):
    """Promoting rows into the host cold cache (and spilling them back)
    must never change a gather's bytes — the cache is a copy, not a
    variant."""
    ei, feat = _graph()
    n = feat.shape[0]
    topo = CSRTopo(edge_index=ei)
    feature = Feature(
        device_cache_size=50 * 16 * 4, csr_topo=topo
    ).from_cpu_tensor(feat.copy())
    topo2 = CSRTopo(edge_index=ei)
    p = str(tmp_path / "rows")
    MmapFeatureStore.write(p, feat.copy(), device_cache_size=50 * 16 * 4,
                           csr_topo=topo2)
    store = MmapFeatureStore(p, window_rows=16, cache_windows=8,
                             host_cache_rows=24)
    ids = _ids(n)
    ref = np.asarray(feature[jnp.asarray(ids)])
    np.testing.assert_array_equal(np.asarray(store[jnp.asarray(ids)]), ref)
    assert store.restage(np.arange(24)) == 24
    np.testing.assert_array_equal(np.asarray(store[jnp.asarray(ids)]), ref)
    assert store.cold_cache_hits_total > 0  # the cache actually served
    assert store.restage([]) == 0  # full spill-back
    np.testing.assert_array_equal(np.asarray(store[jnp.asarray(ids)]), ref)
    store.close()


def test_store_prefetch_overlaps_and_counts_hits(tmp_path):
    from quiver_tpu.obs.registry import (
        OOC_PAGE_READS,
        OOC_READAHEAD_HITS,
        MetricsRegistry,
    )
    from quiver_tpu.obs.timeline import StepTimeline

    ei, feat = _graph()
    topo = CSRTopo(edge_index=ei)
    p = str(tmp_path / "rows")
    MmapFeatureStore.write(p, feat, device_cache_size=50 * 16 * 4,
                           csr_topo=topo)
    reg, tl = MetricsRegistry(), StepTimeline()
    store = MmapFeatureStore(p, window_rows=16, cache_windows=8,
                             metrics=reg, timeline=tl)
    ids = jnp.asarray(_ids(feat.shape[0]))
    assert store.prefetch(ids) > 0  # background reads dispatched
    store[ids]  # same batch: every window staged or in flight
    assert store.stager.readahead_hits_total > 0
    assert int(np.asarray(reg.value(OOC_PAGE_READS))) == \
        store.stager.page_reads_total
    assert int(np.asarray(reg.value(OOC_READAHEAD_HITS))) == \
        store.stager.readahead_hits_total
    assert "ooc.stage_wait" in tl.summary()
    store.close()


# -- AsyncStager resilience ---------------------------------------------------


def _flaky_reader(fail_times):
    """A window reader that raises ``fail_times`` times per window, then
    serves the window's index pattern."""
    failures = {}

    def read(window):
        failures.setdefault(window, 0)
        if failures[window] < fail_times:
            failures[window] += 1
            raise OSError(f"injected read fault on window {window}")
        return np.full((4, 2), window, np.int32)

    return read


def test_stager_retries_transient_faults(tmp_path):
    from quiver_tpu.obs.timeline import StepTimeline

    tl = StepTimeline()
    with AsyncStager(_flaky_reader(2), num_windows=8, window_rows=4,
                     retries=3, backoff=1e-4, timeline=tl) as st:
        out = st.fetch(np.array([0, 5, 9]))  # windows 0, 1, 2
        np.testing.assert_array_equal(out[:, 0], [0, 1, 2])
        assert st.read_retries_total == 6  # 2 faults x 3 windows
        assert tl.stats("ooc.retry_wait").count == 6


def test_stager_exhausted_retries_surface(tmp_path):
    with AsyncStager(_flaky_reader(5), num_windows=4, window_rows=4,
                     retries=1, backoff=0.0) as st:
        with pytest.raises(OSError, match="injected read fault"):
            st.fetch(np.array([0]))


def test_stager_backoff_jitter_deterministic():
    from quiver_tpu.obs.timeline import StepTimeline

    def waits(seed):
        tl = StepTimeline()
        with AsyncStager(_flaky_reader(3), num_windows=2, window_rows=4,
                         retries=3, backoff=1e-3, backoff_cap=2e-3,
                         jitter=0.5, retry_seed=seed, timeline=tl) as st:
            st.fetch(np.array([0]))
        stats = tl.stats("ooc.retry_wait")
        return stats.count, stats.max

    assert waits(5) == waits(5)  # same seed, same jitter stream
    count, mx = waits(5)
    assert count == 3
    assert mx <= 2e-3 * 1.5 + 1e-9  # cap * (1 + jitter)


def test_stager_lru_bounds_resident_windows():
    reads = []

    def read(window):
        reads.append(window)
        return np.zeros((4, 1), np.int8)

    with AsyncStager(read, num_windows=100, window_rows=4,
                     cache_windows=3) as st:
        for w in range(6):
            st.fetch(np.array([w * 4]))
        assert len(st._cache) <= 3
        st.fetch(np.array([5 * 4]))  # still cached: no new read
        assert reads.count(5) == 1
        st.fetch(np.array([0]))  # evicted long ago: re-read
        assert reads.count(0) == 2


# -- 2-device trainer differential -------------------------------------------


def test_data_parallel_epoch_bitwise_vs_in_ram(tmp_path):
    """The flagship differential: a 2-device DataParallelTrainer epoch
    driven off the disk-backed store produces the SAME loss trajectory,
    bit for bit, as one off the in-RAM Feature — and steady state adds
    zero recompiles."""
    from quiver_tpu.models.sage import GraphSAGE
    from quiver_tpu.parallel.mesh import make_mesh
    from quiver_tpu.parallel.trainer import DataParallelTrainer

    rng = np.random.default_rng(0)
    n, classes = 300, 4
    labels = rng.integers(0, classes, n)
    feat = (np.eye(classes, dtype=np.float32)[labels] * 2.0
            + rng.normal(scale=0.8, size=(n, classes)).astype(np.float32))
    ei = rng.integers(0, n, size=(2, 6 * n)).astype(np.int64)

    budget = 60 * classes * 4  # 20% hot — the cold tier carries real load

    def run(kind):
        topo = CSRTopo(edge_index=ei)
        if kind == "ram":
            feature = Feature(
                device_cache_size=budget, csr_topo=topo
            ).from_cpu_tensor(feat.copy())
        else:
            p = str(tmp_path / "rows")
            MmapFeatureStore.write(p, feat.copy(),
                                   device_cache_size=budget, csr_topo=topo)
            feature = MmapFeatureStore(p, window_rows=32, cache_windows=8)
        sampler = GraphSageSampler(topo, [4, 3], seed_capacity=32, seed=5)
        mesh = make_mesh(data=2, feature=1, devices=jax.devices()[:2])
        model = GraphSAGE(hidden=16, num_classes=classes, num_layers=2)
        trainer = DataParallelTrainer(mesh, sampler, feature, model,
                                      optax.adam(5e-3), local_batch=32)
        params, opt_state = trainer.init(jax.random.PRNGKey(0))
        lab = jnp.asarray(labels)
        losses, cache_sizes = [], []
        key = jax.random.PRNGKey(1)
        for epoch in range(2):
            key, sub = jax.random.split(key)
            params, opt_state, loss, _ = trainer.train_epoch(
                params, opt_state, np.arange(n), lab, sub,
                rng=np.random.default_rng(epoch),
            )
            losses.append(float(loss))
            cache_sizes.append(len(trainer._step_cache))
        if kind == "disk":
            assert feature.stager.readahead_hits_total > 0
            feature.close()
        return losses, cache_sizes

    ram_losses, _ = run("ram")
    disk_losses, disk_cache = run("disk")
    assert ram_losses == disk_losses  # bitwise trajectory
    assert disk_cache[0] == disk_cache[-1]  # zero steady-state recompiles


# -- quiver-ctl over the disk tier -------------------------------------------


def test_controller_promotes_measured_hot_disk_rows(tmp_path):
    import json

    from quiver_tpu.control.controller import CacheController
    from quiver_tpu.control.freq import FreqSketch
    from quiver_tpu.obs.export import read_jsonl
    from quiver_tpu.obs.registry import CTRL_OOC_PROMOTIONS

    ei, feat = _graph()
    n = feat.shape[0]
    p = str(tmp_path / "rows")
    MmapFeatureStore.write(p, feat, device_cache_size=40 * 16 * 4)
    store = MmapFeatureStore(p, window_rows=16, cache_windows=16,
                             host_cache_rows=12)
    log = str(tmp_path / "decisions.jsonl")
    ctl = CacheController(sketch=FreqSketch(n), decision_log=log)
    ctl.attach(store)
    hot_disk = np.arange(100, 112)  # translated rows past hot_rows=40
    for _ in range(4):
        ctl.observe_ids(hot_disk)
    ctl.end_epoch(feature=store)  # branches to maybe_promote
    np.testing.assert_array_equal(store.staged_ids,
                                  hot_disk - store.hot_rows)
    assert ctl.stats()["ooc_promotions"] == 1
    recs = read_jsonl(log)  # round-trippable metric snapshots
    assert [r.name for r in recs] == [CTRL_OOC_PROMOTIONS]
    lines = [json.loads(s) for s in open(log).read().splitlines()]
    assert lines[-1]["decision"] == "ooc_promote"
    assert lines[-1]["staged"] == 12
    # frozen controller: observes but never restages (parity mode)
    store2 = MmapFeatureStore(p, window_rows=16, cache_windows=16,
                              host_cache_rows=12)
    fz = CacheController(sketch=FreqSketch(n), frozen=True).attach(store2)
    fz.observe_ids(hot_disk)
    fz.end_epoch(feature=store2)
    assert store2.staged_ids.size == 0
    store.close()
    store2.close()


def test_cost_model_disk_term_calibrates(tmp_path):
    from quiver_tpu.control.cost import CostModel
    from quiver_tpu.control.freq import FreqSketch, row_heat_histogram
    from quiver_tpu.obs.timeline import StepTimeline

    ei, feat = _graph()
    n = feat.shape[0]
    p = str(tmp_path / "rows")
    MmapFeatureStore.write(p, feat, device_cache_size=40 * 16 * 4)
    tl = StepTimeline()
    store = MmapFeatureStore(p, window_rows=16, cache_windows=8,
                             timeline=tl)
    cost = CostModel(local_len=64, num_shards=1)
    assert not cost.calibrate_disk(tl, store.stager)  # nothing measured
    store[jnp.asarray(_ids(n))]
    assert cost.calibrate_disk(tl, store.stager)
    sk = FreqSketch(n, num_bins=n)  # 1 row per bin: exact masses
    sk.observe_histogram(np.asarray(
        row_heat_histogram(jnp.arange(n), None, n, n)
    ))
    zero = cost.predict_disk(sk, n, 0)  # everything resident
    half = cost.predict_disk(sk, store.hot_rows, 0)
    assert zero["hit_disk"] == 0.0
    assert half["hit_disk"] == pytest.approx((n - store.hot_rows) / n)
    assert half["est_disk_s_per_obs"] >= 0.0
    store.close()


# -- chaos-drill building block ----------------------------------------------


def test_raw_fallback_to_legacy_npz(tmp_path):
    """The chaos 'ooc' drill's recovery path, unit-level: a torn raw dir
    is quarantined and the loader falls back to the legacy .npz of the
    same topology."""
    ei, _ = _graph(n=120)
    topo = CSRTopo(edge_index=ei)
    raw = str(tmp_path / "topo.raw")
    npz = str(tmp_path / "topo.npz")
    topo.save(raw, format="raw")
    topo.save(npz)
    shutil.rmtree(os.path.join(raw))
    os.makedirs(raw)  # empty dir: no COMMIT -> corrupt
    try:
        loaded = CSRTopo.load(raw, mmap=True)
    except CorruptRawDir:
        quarantine_raw_dir(raw)
        loaded = CSRTopo.load(npz)
    np.testing.assert_array_equal(loaded.indices, topo.indices)
    assert not os.path.exists(raw)  # quarantined aside
