"""Dense (fanout) vs segment aggregation parity.

Every sampler-built Adj now carries its static ``fanout``, switching the
model convs to dense masked (num_dst, fanout) reductions — zero scatters,
because XLA serializes general scatters on TPU (the same diagnosis behind
dedup="scan", docs/TPU_MEASUREMENTS_R3.md). These tests pin the invariant
that the dense path is numerically the segment path: same Adj, same
params, fanout set vs stripped, outputs must agree to float tolerance for
all four homogeneous conv families plus the layer primitives.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.sampling.sampler import Adj


@pytest.fixture(scope="module")
def sampled():
    rng = np.random.default_rng(5)
    ei = rng.integers(0, 300, size=(2, 4500)).astype(np.int64)
    topo = CSRTopo(edge_index=ei)
    s = GraphSageSampler(topo, [7, 5], seed_capacity=64, seed=3)
    out = s.sample(rng.integers(0, 300, 64))
    x = rng.normal(size=(out.n_id.shape[0], 32)).astype(np.float32)
    return out, jnp.asarray(x)


def _strip_fanout(adjs):
    return [Adj(a.edge_index, a.e_id, a.size, fanout=None) for a in adjs]


def test_sampler_adjs_carry_fanout(sampled):
    out, _ = sampled
    assert [a.fanout for a in out.adjs] == [5, 7]  # deepest first
    for a in out.adjs:
        assert a.edge_index.shape[1] == a.size[1] * a.fanout


def test_adj_pytree_roundtrip_preserves_fanout(sampled):
    out, _ = sampled
    leaves, treedef = jax.tree_util.tree_flatten(out.adjs)
    rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
    assert [a.fanout for a in rebuilt] == [5, 7]
    assert [a.size for a in rebuilt] == [a.size for a in out.adjs]


@pytest.mark.parametrize("family", ["sage", "gcn", "gin", "gat"])
def test_dense_matches_segment(sampled, family):
    from quiver_tpu.models import GAT, GCN, GIN, GraphSAGE

    out, x = sampled
    model = {
        "sage": lambda: GraphSAGE(hidden=16, num_classes=4, num_layers=2),
        "gcn": lambda: GCN(hidden=16, num_classes=4, num_layers=2),
        "gin": lambda: GIN(hidden=16, num_classes=4, num_layers=2),
        "gat": lambda: GAT(hidden=16, num_classes=4, num_layers=2, heads=2),
    }[family]()
    params = model.init(jax.random.PRNGKey(0), x, out.adjs)
    y_dense = model.apply(params, x, out.adjs)
    y_seg = model.apply(params, x, _strip_fanout(out.adjs))
    np.testing.assert_allclose(
        np.asarray(y_dense), np.asarray(y_seg), rtol=2e-4, atol=2e-5
    )


def test_fanout_softmax_matches_segment_softmax():
    from quiver_tpu.models.layers import fanout_softmax, segment_softmax

    rng = np.random.default_rng(0)
    S, K, H = 12, 6, 3
    logits = jnp.asarray(rng.normal(size=(S * K, H)).astype(np.float32))
    valid = jnp.asarray(rng.random(S * K) < 0.7)
    dst = jnp.repeat(jnp.arange(S), K)
    seg = jnp.where(valid, dst, S)
    a_seg = segment_softmax(logits, seg, valid, S)
    a_dense = fanout_softmax(logits, valid, S, K)
    # compare on valid lanes only (invalid lanes: dense gives 0, segment
    # gives exp(min)/tiny garbage that callers mask anyway)
    m = np.asarray(valid)
    np.testing.assert_allclose(
        np.asarray(a_dense)[m], np.asarray(a_seg)[m], rtol=1e-5, atol=1e-6
    )
    # each target's valid weights sum to 1 (or 0 for all-invalid rows)
    sums = np.zeros(S)
    np.add.at(sums, np.asarray(dst)[m], np.asarray(a_dense)[m].sum(-1)[...] / H)
    assert np.all((np.abs(sums - 1) < 1e-5) | (sums == 0))


def test_zero_scatter_counts_matches_bincount():
    from quiver_tpu.models.layers import zero_scatter_counts

    rng = np.random.default_rng(1)
    ids = rng.integers(0, 50, 1000)
    valid = rng.random(1000) < 0.8
    got = np.asarray(zero_scatter_counts(
        jnp.asarray(ids), jnp.asarray(valid), 50))
    want = np.bincount(ids[valid], minlength=50)
    np.testing.assert_array_equal(got, want.astype(np.float32))


def test_trainer_fused_step_rebuilds_fanout_correctly():
    """The fused-step Adj rebuild must restore each layer's OWN fanout
    (regression: stacked arrays lose the static metadata, and a wrong
    pairing silently falls back to the scatter path)."""
    rng = np.random.default_rng(7)
    topo = CSRTopo(edge_index=rng.integers(0, 400, (2, 6000)).astype(np.int64))
    sampler = GraphSageSampler(topo, [9, 4], seed_capacity=32, seed=0)
    out = sampler.sample(np.arange(32))
    caps = tuple(a.size[0] for a in out.adjs)[::-1]  # seeds-outward order

    # replicate _compiled_step's rebuild: deepest-first sizes + caps
    from quiver_tpu.parallel.trainer import DataParallelTrainer

    adj_sizes = DataParallelTrainer._adj_sizes(
        type("T", (), {"local_batch": 32})(), caps
    )
    fanouts = tuple(sampler.sizes)[::-1]
    for a, sz, f in zip(out.adjs, adj_sizes, fanouts):
        rebuilt = Adj(a.edge_index, None, sz, fanout=f)
        # the dense-path gate must hold for every rebuilt layer
        assert rebuilt.edge_index.shape[1] == rebuilt.size[1] * rebuilt.fanout
        assert rebuilt.size == a.size and rebuilt.fanout == a.fanout


def test_occurrence_counts_strategies_agree(monkeypatch):
    from quiver_tpu.models import layers

    rng = np.random.default_rng(2)
    ids = jnp.asarray(rng.integers(0, 40, 500))
    valid = jnp.asarray(rng.random(500) < 0.6)
    # the strategy is pinned once per process (ADVICE #1: no trace-time env
    # reads inside jitted model code), so flipping QUIVER_COUNTS requires
    # resetting the cache — which is exactly what a live model can NOT do
    monkeypatch.setenv("QUIVER_COUNTS", "scan")
    monkeypatch.setattr(layers, "_counts_strategy", None)
    a = np.asarray(layers.occurrence_counts(ids, valid, 40))
    assert layers.resolve_counts_strategy() == "scan"
    monkeypatch.setenv("QUIVER_COUNTS", "scatter")
    # without a reset the pinned strategy stays — env after first trace is
    # inert by contract
    assert layers.resolve_counts_strategy() == "scan"
    monkeypatch.setattr(layers, "_counts_strategy", None)
    b = np.asarray(layers.occurrence_counts(ids, valid, 40))
    assert layers.resolve_counts_strategy() == "scatter"
    np.testing.assert_array_equal(a, b)
    monkeypatch.setattr(layers, "_counts_strategy", None)  # leave no pin
