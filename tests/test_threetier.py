"""Three-tier feature store: differential tests on the 8-device CPU mesh.

The L0 replicated super-hot tier (ISSUE 2): top-degree rows replicated in
every chip's HBM and served with zero interconnect lanes, in front of the
mesh-sharded hot tier and the host cold tier. Parity bars: bit-identical to
the two-tier path at ``replicate_budget=0``, bit-identical to the dense
numpy oracle at every budget split (f32 AND int8), per-tier hits observable
in-program, and the eager auto-split tuner moving the boundary toward the
measured hit distribution.
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.feature.feature import Feature
from quiver_tpu.feature.shard import ShardedFeature
from quiver_tpu.models.sage import GraphSAGE
from quiver_tpu.parallel.mesh import make_mesh
from quiver_tpu.parallel.trainer import DistributedTrainer
from quiver_tpu.utils.reorder import reorder_by_degree


def _graph(n=400, e=3000, seed=5):
    rng = np.random.default_rng(seed)
    ei = np.stack([rng.integers(0, n, e), rng.integers(0, n, e)])
    return CSRTopo(edge_index=ei)


def _skewed_ids(topo, count, seed=1, invalid=4):
    rng = np.random.default_rng(seed)
    deg = topo.degree.astype(np.float64)
    ids = rng.choice(
        topo.node_count, size=count, p=deg / deg.sum()
    ).astype(np.int32)
    if invalid:
        ids[rng.choice(count, invalid, replace=False)] = -1
    return ids


def _oracle(feat, ids):
    ref = feat[np.where(ids >= 0, ids, 0)].copy()
    ref[ids < 0] = 0
    return ref


ROW_B = 8 * 4  # float32 rows, dim 8


def test_budget_zero_bit_identical_to_two_tier():
    """replicate_budget=0 must reproduce the two-tier store exactly —
    same split, no L0, and bit-identical gathers (psum AND routed)."""
    topo = _graph()
    n = topo.node_count
    feat = np.random.default_rng(0).normal(size=(n, 8)).astype(np.float32)
    mesh = make_mesh(data=2, feature=4)
    budget = (n // 4 // 4) * ROW_B
    two = ShardedFeature(
        mesh, device_cache_size=budget, csr_topo=_graph()
    ).from_cpu_tensor(feat)
    three = ShardedFeature(
        mesh, device_cache_size=budget, csr_topo=_graph(),
        replicate_budget=0,
    ).from_cpu_tensor(feat)
    assert three.rep_rows == 0 and three.rep is None
    assert three.hot_rows == two.hot_rows
    ids = _skewed_ids(topo, 96)
    a = np.asarray(two[jnp.asarray(ids)])
    b = np.asarray(three[jnp.asarray(ids)])
    assert np.array_equal(a, _oracle(feat, ids))
    assert np.array_equal(b, a)  # bit-identical
    ar = np.asarray(two.gather(jnp.asarray(ids), routed=True))
    br = np.asarray(three.gather(jnp.asarray(ids), routed=True))
    assert np.array_equal(br, ar)


@pytest.mark.parametrize("rep_rows", [0, 16, 100, 400])
def test_matches_dense_oracle_at_every_split_f32(rep_rows):
    """Every replicated/sharded/cold split serves the dense oracle's rows
    exactly, through both the psum and the routed gather, including -1
    lanes and the feature_order translation."""
    topo = _graph()
    n = topo.node_count
    feat = np.random.default_rng(1).normal(size=(n, 8)).astype(np.float32)
    mesh = make_mesh(data=2, feature=4)
    store = ShardedFeature(
        mesh, device_cache_size=(n // 4 // 4) * ROW_B, csr_topo=topo,
        replicate_budget=rep_rows * ROW_B,
    ).from_cpu_tensor(feat)
    assert store.rep_rows == min(rep_rows, n)
    ids = _skewed_ids(topo, 96)
    ref = _oracle(feat, ids)
    a = np.asarray(store[jnp.asarray(ids)])
    b = np.asarray(store.gather(jnp.asarray(ids), routed=True))
    assert np.array_equal(a, ref)
    assert np.array_equal(b, ref)


@pytest.mark.parametrize("rep_rows", [0, 24, 300])
def test_int8_dequantizes_identically_across_tiers(rep_rows):
    """int8 storage: the same row must dequantize bit-identically no
    matter which tier serves it — the (N,) scale array is indexed in the
    shared translated row space, so moving the split must not change a
    single output bit."""
    topo = _graph(n=300, e=2000, seed=8)
    n = topo.node_count
    feat = np.random.default_rng(8).normal(size=(n, 16)).astype(np.float32)
    mesh = make_mesh(data=2, feature=4)
    row_b = 16  # int8: 1 byte/element
    stores = [
        ShardedFeature(
            mesh, device_cache_size="1M", csr_topo=_graph(n=300, e=2000, seed=8),
            dtype="int8", replicate_budget=r * row_b,
        ).from_cpu_tensor(feat)
        for r in (0, rep_rows)
    ]
    ids = _skewed_ids(topo, 64, seed=9)
    outs = [np.asarray(s[jnp.asarray(ids)]) for s in stores]
    assert np.array_equal(outs[0], outs[1])
    routed = [
        np.asarray(s.gather(jnp.asarray(ids), routed=True, routed_cap=4))
        for s in stores
    ]
    assert np.array_equal(routed[0], outs[0])
    assert np.array_equal(routed[1], outs[0])
    # dequantization bound vs the raw features (sanity that rows are real)
    ref = _oracle(feat, ids)
    absmax = np.abs(feat).max(axis=1)
    bound = (absmax[np.where(ids >= 0, ids, 0)] / 127.0)[:, None] + 1e-7
    assert np.all(np.abs(outs[0] - ref) <= bound)


def test_tier_hit_telemetry_exact_counts():
    """Hit counts [replicated, sharded, cold] are exact per-boundary lane
    tallies of VALID lanes (no csr_topo => translated ids == raw ids)."""
    n, f = 512, 8
    feat = np.random.default_rng(3).normal(size=(n, f)).astype(np.float32)
    mesh = make_mesh(data=2, feature=4)
    store = ShardedFeature(
        mesh, device_cache_size=(128 // 4) * ROW_B,
        replicate_budget=64 * ROW_B,
    ).from_cpu_tensor(feat)
    assert (store.rep_rows, store.hot_rows) == (64, 128)
    ids = np.concatenate([
        np.arange(10),            # L0
        64 + np.arange(20),       # sharded
        192 + np.arange(30),      # cold
        [-1, -1],                 # invalid — counted nowhere
    ]).astype(np.int32)
    out = np.asarray(store[jnp.asarray(ids)])
    assert np.array_equal(out, _oracle(feat, ids))
    assert np.asarray(store.last_tier_hits).tolist() == [10, 20, 30]
    # routed flavor counts identically
    out = np.asarray(store.gather(jnp.asarray(ids), routed=True))
    assert np.array_equal(out, _oracle(feat, ids))
    assert np.asarray(store.last_tier_hits).tolist() == [10, 20, 30]


def test_l0_lanes_cost_zero_routed_bucket_capacity():
    """Replicated-tier lanes enter the routed gather as invalid: a batch
    whose skew would overflow the two-tier capped buckets stops
    overflowing once the hot rows are replicated — the zero-comm tier is
    visible in the overflow metadata, not just the hit counts."""
    n, f = 512, 8
    feat = np.random.default_rng(4).normal(size=(n, f)).astype(np.float32)
    mesh = make_mesh(data=2, feature=4)
    ids = np.random.default_rng(5).integers(0, 64, 256).astype(np.int32)
    two = ShardedFeature(
        mesh, device_cache_size=(n // 4) * ROW_B,
    ).from_cpu_tensor(feat)
    out = np.asarray(two.gather(jnp.asarray(ids), routed=True, routed_cap=4))
    assert np.array_equal(out, feat[ids])
    assert int(two.last_routed_overflow) > 0  # every id on shard 0
    three = ShardedFeature(
        mesh, device_cache_size=(n // 4) * ROW_B,
        replicate_budget=64 * ROW_B,
    ).from_cpu_tensor(feat)
    out = np.asarray(three.gather(jnp.asarray(ids), routed=True, routed_cap=4))
    assert np.array_equal(out, feat[ids])
    assert int(three.last_routed_overflow) == 0  # all lanes served by L0
    assert np.asarray(three.last_tier_hits).tolist() == [256, 0, 0]


def test_int8_budget_below_scale_degrades_to_cold_only():
    """Budget-edge: an int8 store whose combined budget cannot hold the
    replicated (N,) f32 scale array must degrade to a cold-only store —
    exact results, no crash, no silent wrong split — with a one-shot INFO
    log."""
    import logging

    topo = _graph(n=300, e=2000, seed=11)
    n = topo.node_count
    feat = np.random.default_rng(11).normal(size=(n, 16)).astype(np.float32)
    mesh = make_mesh(data=2, feature=4)
    logger = logging.getLogger("quiver_tpu")
    from quiver_tpu.utils.trace import _ONCE_KEYS

    _ONCE_KEYS.discard("sharded-int8-budget-below-scale")
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    h = _Capture(level=logging.INFO)
    logger.addHandler(h)
    old_level = logger.level
    logger.setLevel(logging.INFO)
    try:
        store = ShardedFeature(
            mesh, device_cache_size=4 * n - 1, csr_topo=topo, dtype="int8",
        ).from_cpu_tensor(feat)
    finally:
        logger.removeHandler(h)
        logger.setLevel(old_level)
    assert store.rep_rows == 0 and store.hot_rows == 0
    assert store.hot is None and store.rep is None
    assert store.cold is not None
    assert any("cold-only" in m for m in records), records
    # still exact (host-served int8 + on-device dequant)
    ids = _skewed_ids(topo, 48, seed=12)
    out = np.asarray(store[jnp.asarray(ids)])
    ref = _oracle(feat, ids)
    absmax = np.abs(feat).max(axis=1)
    bound = (absmax[np.where(ids >= 0, ids, 0)] / 127.0)[:, None] + 1e-7
    assert np.all(np.abs(out - ref) <= bound)


def test_auto_split_shrinks_unearned_l0_and_regrows():
    """The eager tuner consumes the measured hit distribution: traffic
    that never touches L0 shrinks the boundary to 0 (replication not
    earning its F x bytes); skewed traffic mid-band regrows it toward the
    budget ceiling. Every gather along the way stays exact."""
    n, f = 512, 8
    feat = np.random.default_rng(6).normal(size=(n, f)).astype(np.float32)
    mesh = make_mesh(data=2, feature=4)
    store = ShardedFeature(
        mesh, device_cache_size=(n // 4) * ROW_B,
        replicate_budget=64 * ROW_B, auto_split=True,
    ).from_cpu_tensor(feat)
    assert store.rep_rows == 64
    rng = np.random.default_rng(7)
    cold_ids = rng.integers(64, n, 128).astype(np.int32)
    for _ in range(10):
        out = np.asarray(store[jnp.asarray(cold_ids)])
        assert np.array_equal(out, feat[cold_ids])
    assert store.rep_rows == 0  # halved away batch by batch
    store.resplit(8)
    hot_ids = np.concatenate([
        rng.integers(0, 8, 32), rng.integers(64, n, 96)
    ]).astype(np.int32)
    for _ in range(6):
        out = np.asarray(store[jnp.asarray(hot_ids)])
        assert np.array_equal(out, feat[hot_ids])
    assert store.rep_rows == 64  # doubled back to the budget ceiling
    ids = rng.integers(0, n, 96).astype(np.int32)
    assert np.array_equal(np.asarray(store[jnp.asarray(ids)]), feat[ids])


def test_resplit_requires_host_region():
    n, f = 128, 8
    feat = np.random.default_rng(0).normal(size=(n, f)).astype(np.float32)
    mesh = make_mesh(data=2, feature=4)
    store = ShardedFeature(mesh, device_cache_size="1G").from_cpu_tensor(feat)
    with pytest.raises(ValueError, match="replicate_budget"):
        store.resplit(16)


def test_pin_top_keeps_top_degree_rows_in_order():
    """reorder_by_degree(pin_top=k): rows [0, k) are the top-k nodes in
    strict descending-degree order (the L0 contract), the invariant
    original[ids] == new[new_order[ids]] holds, and the shuffled span
    still covers the remaining hot prefix."""
    rng = np.random.default_rng(2)
    n = 200
    degree = rng.integers(0, 1000, n)
    feat = rng.normal(size=(n, 4)).astype(np.float32)
    new_feat, order = reorder_by_degree(feat, degree, 0.5, seed=3, pin_top=16)
    assert np.array_equal(new_feat[order], feat)
    top = np.argsort(-degree.astype(np.int64), kind="stable")[:16]
    assert np.array_equal(new_feat[:16], feat[top])
    hot = set(np.argsort(-degree.astype(np.int64), kind="stable")[:100])
    placed = {int(np.where(order == r)[0][0]) for r in range(100)}
    assert placed == hot  # shuffle stayed within the hot prefix


def test_trainer_threetier_loss_bit_identical_and_hits_observable():
    """DistributedTrainer(seed_sharding='all') over a three-tier store:
    the L0 tier must not change the training math at all — losses
    bit-identical to the two-tier trainer on the same seeds/keys — and
    the per-tier hit vector must surface on the trainer."""
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 4, 400)
    feat = np.eye(4, dtype=np.float32)[labels] * 2.0
    feat += rng.normal(scale=0.8, size=(400, 4)).astype(np.float32)
    ei = np.stack([rng.integers(0, 400, 4000), rng.integers(0, 400, 4000)])
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    mesh = make_mesh(data=2, feature=4)
    labels_dev = jnp.asarray(labels[:n].astype(np.int32))
    model = GraphSAGE(hidden=16, num_classes=4, num_layers=2)

    losses, hits = {}, {}
    for rep_budget in (0, 64 * 4 * 4):
        sampler = GraphSageSampler(topo, [5, 5], seed=3)
        feature = ShardedFeature(
            mesh, device_cache_size="1G", csr_topo=CSRTopo(edge_index=ei),
            replicate_budget=rep_budget,
        ).from_cpu_tensor(feat[:n])
        trainer = DistributedTrainer(
            mesh, sampler, feature, model, optax.adam(5e-3), local_batch=32,
            seed_sharding="all", routed_alpha=1.0,
        )
        params, opt = trainer.init(jax.random.PRNGKey(0))
        srng = np.random.default_rng(0)
        ls = []
        for step in range(3):
            seeds = srng.integers(0, n, trainer.global_batch)
            params, opt, loss = trainer.step(
                params, opt, seeds, labels_dev, jax.random.PRNGKey(step)
            )
            ls.append(float(loss))
        losses[rep_budget] = ls
        hits[rep_budget] = np.asarray(trainer.last_tier_hits)
    assert losses[0] == losses[64 * 4 * 4], losses
    assert hits[0][0] == 0  # no L0 tier, no L0 hits
    assert hits[64 * 4 * 4][0] > 0  # top-degree rows caught traffic
    assert hits[64 * 4 * 4].sum() == hits[0].sum()  # same lanes, re-tiered


def test_trainer_epoch_scan_tier_hits_vector():
    """epoch_scan surfaces a per-step (steps, 3) hit matrix — batch
    metadata for the split tuner and scoreboard."""
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 4, 300)
    feat = rng.normal(size=(300, 6)).astype(np.float32)
    ei = np.stack([rng.integers(0, 300, 2500), rng.integers(0, 300, 2500)])
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    mesh = make_mesh(data=2, feature=4)
    sampler = GraphSageSampler(topo, [4, 3], seed=1)
    feature = ShardedFeature(
        mesh, device_cache_size="1G", csr_topo=CSRTopo(edge_index=ei),
        replicate_budget=32 * 6 * 4,
    ).from_cpu_tensor(feat[:n])
    model = GraphSAGE(hidden=8, num_classes=4, num_layers=2)
    trainer = DistributedTrainer(
        mesh, sampler, feature, model, optax.adam(5e-3), local_batch=16,
        seed_sharding="all", routed_alpha=1.0,
    )
    params, opt = trainer.init(jax.random.PRNGKey(0))
    seed_mat = trainer.pack_epoch(
        np.arange(3 * trainer.global_batch) % n, seed=0)
    params, opt, losses = trainer.epoch_scan(
        params, opt, seed_mat, jnp.asarray(labels[:n].astype(np.int32)),
        jax.random.PRNGKey(1),
    )
    th = np.asarray(trainer.last_tier_hits)
    assert th.shape == (3, 3)
    assert np.all(th >= 0) and th[:, 0].sum() > 0
    assert np.all(np.isfinite(np.asarray(losses)))


def test_trainer_replicate_budget_override_and_auto_split_consumption():
    """The trainer's replicate_budget= re-splits the store before the
    program is built, and with auto_split=True the trainer-fed hit totals
    move the boundary between eager steps."""
    rng = np.random.default_rng(1)
    labels = rng.integers(0, 4, 300)
    feat = rng.normal(size=(300, 6)).astype(np.float32)
    ei = np.stack([rng.integers(0, 300, 2500), rng.integers(0, 300, 2500)])
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    mesh = make_mesh(data=2, feature=4)
    feature = ShardedFeature(
        mesh, device_cache_size="1G", csr_topo=CSRTopo(edge_index=ei),
        replicate_budget=8 * 6 * 4, auto_split=True,
    ).from_cpu_tensor(feat[:n])
    trainer = DistributedTrainer(
        mesh, GraphSageSampler(topo, [4, 3], seed=1), feature,
        GraphSAGE(hidden=8, num_classes=4, num_layers=2),
        optax.adam(5e-3), local_batch=16, seed_sharding="all",
        routed_alpha=1.0, replicate_budget=1 * 6 * 4,
    )
    assert feature.rep_rows == 1  # override re-split before build
    params, opt = trainer.init(jax.random.PRNGKey(0))
    labels_dev = jnp.asarray(labels[:n].astype(np.int32))
    srng = np.random.default_rng(0)
    seen = set()
    for step in range(4):
        seeds = srng.integers(0, n, trainer.global_batch)
        params, opt, loss = trainer.step(
            params, opt, seeds, labels_dev, jax.random.PRNGKey(step)
        )
        assert np.isfinite(float(loss))
        seen.add(feature.rep_rows)
    # the tuner consumed the trainer's hit totals: a 1-row L0 serves far
    # under 1/8 of the device traffic on this near-uniform graph, so the
    # boundary must shrink away between steps
    assert seen == {1, 0}, seen


def test_trainer_replicate_budget_inert_on_plain_feature():
    """replicate_budget on a device_replicate Feature is accepted-and-INERT
    (its hot tier is already a per-device replica): no crash, a working
    trainer, and hits counted against the two real boundaries."""
    rng = np.random.default_rng(2)
    labels = rng.integers(0, 4, 200)
    feat = rng.normal(size=(200, 6)).astype(np.float32)
    ei = np.stack([rng.integers(0, 200, 1500), rng.integers(0, 200, 1500)])
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    mesh = make_mesh(data=2, feature=4)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat[:n])
    trainer = DistributedTrainer(
        mesh, GraphSageSampler(topo, [3, 3], seed=0), feature,
        GraphSAGE(hidden=8, num_classes=4, num_layers=2),
        optax.adam(5e-3), local_batch=16, replicate_budget="1M",
    )
    params, opt = trainer.init(jax.random.PRNGKey(0))
    params, opt, loss = trainer.step(
        params, opt, np.arange(trainer.global_batch) % n,
        jnp.asarray(labels[:n].astype(np.int32)), jax.random.PRNGKey(0),
    )
    assert np.isfinite(float(loss))
    th = np.asarray(trainer.last_tier_hits)
    assert th[0] == 0 and th[1] > 0  # all device-resident rows are "hot"


def test_feature_replicate_budget_folds_into_cache():
    """Feature(device_replicate): the L0 budget buys plain hot rows (one
    zero-comm tier already); the split math must reflect the sum."""
    feat = np.random.default_rng(0).normal(size=(100, 8)).astype(np.float32)
    a = Feature(device_cache_size=50 * ROW_B).from_cpu_tensor(feat)
    b = Feature(
        device_cache_size=30 * ROW_B, replicate_budget=20 * ROW_B
    ).from_cpu_tensor(feat)
    assert a.hot_rows == b.hot_rows == 50
    ids = np.arange(100).astype(np.int32)
    assert np.array_equal(np.asarray(a[ids]), np.asarray(b[ids]))


def test_bench_effective_lanes_model_strictly_below_capped():
    """The benchmark comm model with a measured L0 hit rate: the tightened
    cap and the effective-lanes column sit strictly below the PR 1 capped
    path's alpha*L, by exactly the (1-h0) factor."""
    import argparse

    from benchmarks.bench_feature import _routed_comm_model, _tier_hit_rates
    from quiver_tpu.feature.shard import ShardedTensor

    class _Store:
        pass

    class _Hot:
        num_shards = 4

        @staticmethod
        def routed_cap(length, alpha):
            st = ShardedTensor(make_mesh(data=2, feature=4))
            return st.routed_cap(length, alpha)

    store = _Store()
    store.hot = _Hot()
    args = argparse.Namespace(routed=True, routed_alpha=2.0,
                              gather_batch=4096)
    cap_two, model_two = _routed_comm_model(args, store)
    cap_three, model_three = _routed_comm_model(args, store, h0=0.5)
    assert cap_three < cap_two
    assert model_three["lanes_per_hop"] < model_two["lanes_per_hop"]
    assert model_three["effective_lanes_per_hop"] == pytest.approx(
        args.routed_alpha * (4096 // 8) * 0.5
    )
    assert model_three["l0_hit_rate"] == 0.5
    # hit-rate helper: exact normalization + absent-telemetry no-op
    store.last_tier_hits = jnp.asarray([10, 30, 60], jnp.int32)
    rates = _tier_hit_rates(store)
    assert rates == {"hit_rep": 0.1, "hit_sharded": 0.3, "hit_cold": 0.6}
    assert _tier_hit_rates(object()) == {}
