"""GAT model tests (BASELINE config 4: attention aggregation).

Checks: attention rows sum to one per destination, padding-lane invariance
(extra -1 edges change nothing), forward shapes, and that end-to-end training
on the synthetic labeled graph learns — the same acceptance pattern as the
SAGE tests."""

import numpy as np
import jax
import jax.numpy as jnp
import optax

from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.feature.feature import Feature
from quiver_tpu.models.gat import GAT, GATConv
from quiver_tpu.parallel.train import init_model, make_train_step

from test_models_train import _labeled_graph


def _tiny_block(num_src=8, num_dst=4, e=16, seed=0):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, num_src, e).astype(np.int32)
    dst = rng.integers(0, num_dst, e).astype(np.int32)
    return np.stack([src, dst])


def test_gatconv_forward_shapes_and_finite():
    ei = _tiny_block()
    x = jnp.asarray(np.random.default_rng(1).normal(size=(8, 6)).astype(np.float32))
    conv = GATConv(features=5, heads=3, concat=True)
    params = conv.init(jax.random.PRNGKey(0), x, jnp.asarray(ei), 4)
    out = conv.apply(params, x, jnp.asarray(ei), 4)
    assert out.shape == (4, 15)
    assert np.all(np.isfinite(np.asarray(out)))

    conv_avg = GATConv(features=5, heads=3, concat=False)
    params = conv_avg.init(jax.random.PRNGKey(0), x, jnp.asarray(ei), 4)
    out = conv_avg.apply(params, x, jnp.asarray(ei), 4)
    assert out.shape == (4, 5)


def test_gatconv_padding_invariance():
    """Appending -1 sentinel edges must not change the output."""
    ei = _tiny_block()
    x = jnp.asarray(np.random.default_rng(1).normal(size=(8, 6)).astype(np.float32))
    conv = GATConv(features=4, heads=2)
    params = conv.init(jax.random.PRNGKey(0), x, jnp.asarray(ei), 4)
    out1 = conv.apply(params, x, jnp.asarray(ei), 4)

    pad = np.full((2, 7), -1, np.int32)
    ei_padded = np.concatenate([ei, pad], axis=1)
    out2 = conv.apply(params, x, jnp.asarray(ei_padded), 4)
    assert np.allclose(np.asarray(out1), np.asarray(out2), atol=1e-6)


def test_gatconv_isolated_dst_gets_bias_only():
    """A destination with no incoming edges receives only the bias."""
    # all 6 edges target dst 0; dst 1 is isolated
    ei = np.stack([np.arange(6, dtype=np.int32), np.zeros(6, np.int32)])
    x = jnp.asarray(np.random.default_rng(2).normal(size=(6, 3)).astype(np.float32))
    conv = GATConv(features=4, heads=2)
    variables = conv.init(jax.random.PRNGKey(0), x, jnp.asarray(ei), 2)
    out = np.asarray(conv.apply(variables, x, jnp.asarray(ei), 2))
    bias = np.asarray(variables["params"]["bias"])
    assert np.allclose(out[1], bias, atol=1e-6)


def test_gat_end_to_end_learns():
    ei, feat, labels = _labeled_graph()
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    sampler = GraphSageSampler(topo, [5, 5], seed=1)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat[:n])

    model = GAT(hidden=8, num_classes=4, num_layers=2, heads=4)
    tx = optax.adam(5e-3)

    out0 = sampler.sample(np.arange(128) % n)
    x0 = feature[out0.n_id]
    params = init_model(model, jax.random.PRNGKey(0), x0, out0.adjs)
    opt_state = tx.init(params)
    train_step = jax.jit(make_train_step(model, tx))

    rng = np.random.default_rng(0)
    losses = []
    for step in range(30):
        seeds = rng.integers(0, n, 128)
        out = sampler.sample(seeds)
        x = feature[out.n_id]
        cap = out.adjs[-1].size[1]
        lab = np.full(cap, -1, np.int32)
        lab[:128] = labels[seeds]
        mask = np.zeros(cap, bool)
        mask[:128] = True
        params, opt_state, loss = train_step(
            params, opt_state, x, out.adjs,
            jnp.asarray(lab), jnp.asarray(mask), jax.random.PRNGKey(step),
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses
