"""Fused Pallas megakernel differentials vs the XLA oracle (ISSUE 16).

Every cell of the old capability matrix that used to raise — pallas ×
{weighted, temporal, with_eid}, replicated AND sharded — is now a BITWISE
differential against the retained XLA path under the same PRNG key: the
fused kernel moves the windowed copy + select (+ weighted CDF walk + eid
lane) on-chip but consumes identical PRNG bits over identical shapes, so
any divergence is a real regression, not noise. Runs in interpret mode on
the CPU test mesh; the same programs compile unchanged on TPU.
"""

import logging

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from quiver_tpu import CSRTopo, DistHeteroSampler, GraphSageSampler, HeteroCSRTopo
from quiver_tpu.ops.pallas.fused import DEFAULT_WINDOW, fused_sample_layer
from quiver_tpu.ops.sample import sample_layer
from quiver_tpu.parallel.mesh import make_mesh
from quiver_tpu.utils.trace import reset_once


def _topo(n=400, e=6000, seed=3, weights=False, times=False):
    rng = np.random.default_rng(seed)
    # src >= 1 leaves node 0 isolated: deg-0 rows must stay bit-identical
    # (all -1 lanes) through the fused path's window arithmetic
    ei = np.stack([rng.integers(1, n, e), rng.integers(0, n, e)])
    ei[1, 0] = n - 1  # pin node_count
    t = CSRTopo(edge_index=ei.astype(np.int64))
    if weights:
        t.set_edge_weight(rng.random(e).astype(np.float32) + 0.1)
    if times:
        t.set_edge_time(rng.random(e))
    return t


def _assert_hop_bitwise(dev, *, k=5, weighted=False, time_window=None,
                        with_eid=False, num=50, cap=64, key_seed=7):
    n = int(dev.indptr.shape[0]) - 1
    rng = np.random.default_rng(11)
    seeds = np.full(cap, -1, np.int32)
    seeds[:num] = rng.integers(0, n, num)
    seeds[0] = 0  # the isolated (deg-0) row rides every variant
    seeds = jnp.asarray(seeds)
    key = jax.random.PRNGKey(key_seed)
    oracle = sample_layer(dev, seeds, jnp.int32(num), k, key,
                          with_eid=with_eid, weighted=weighted,
                          time_window=time_window)
    fused = fused_sample_layer(dev, seeds, jnp.int32(num), k, key,
                               weighted=weighted, time_window=time_window,
                               with_eid=with_eid)
    assert len(oracle) == len(fused)
    for i, (x, y) in enumerate(zip(oracle, fused)):
        assert np.array_equal(np.asarray(x), np.asarray(y)), (
            f"fused output {i} diverged from the XLA oracle"
        )


# -- hop-level bitwise differentials (the parity contract itself) -----------


@pytest.mark.parametrize("variant", [
    "uniform", "eid", "weighted", "weighted_eid", "temporal", "temporal_eid",
])
def test_hop_bitwise_differential(variant):
    weighted = variant.startswith("weighted")
    temporal = variant.startswith("temporal")
    with_eid = "eid" in variant
    t = _topo(weights=weighted, times=temporal)
    dev = t.to_device(with_eid=with_eid, with_weights=weighted,
                      with_times=temporal)
    assert t.edge_count >= DEFAULT_WINDOW  # the fused path must be live
    _assert_hop_bitwise(
        dev, weighted=weighted,
        time_window=(0.25, 0.8) if temporal else None, with_eid=with_eid,
    )


def test_hop_bitwise_full_batch_and_wide_fanout():
    """No padded tail (num == cap) and a fanout above most degrees (the
    take-all override path dominates): still bitwise."""
    t = _topo()
    dev = t.to_device(with_eid=True)
    _assert_hop_bitwise(dev, k=17, num=64, cap=64, with_eid=True)
    wt = _topo(weights=True, seed=9)
    wdev = wt.to_device(with_weights=True)
    _assert_hop_bitwise(wdev, k=17, num=64, cap=64, weighted=True)


# -- sampler-level parity across dedup modes --------------------------------


@pytest.mark.parametrize("dedup", ["sort", "map", "scan"])
def test_sampler_parity_across_dedup_modes(dedup):
    """Full GraphSageSampler outputs (n_id, every layer's edge_index and
    e_id) are bitwise identical between kernel='pallas' and 'xla' — the
    reindex stage downstream sees identical draws, whatever the dedup."""
    t = _topo()
    kw = dict(seed=5, seed_capacity=64, dedup=dedup, with_eid=True)
    sp = GraphSageSampler(t, [5, 3], kernel="pallas", **kw)
    sx = GraphSageSampler(t, [5, 3], kernel="xla", **kw)
    seeds = np.random.default_rng(2).integers(0, t.node_count, 60)
    a, b = sp.sample(seeds), sx.sample(seeds)
    assert np.array_equal(np.asarray(a.n_id), np.asarray(b.n_id))
    assert int(a.n_count) == int(b.n_count)
    assert int(a.overflow) == int(b.overflow)
    for la, lb in zip(a.adjs, b.adjs):
        assert np.array_equal(np.asarray(la.edge_index),
                              np.asarray(lb.edge_index))
        assert np.array_equal(np.asarray(la.e_id), np.asarray(lb.e_id))


# -- sharded (2-device mesh) parity, fast lane ------------------------------


def _dist_pair(topo, sizes, F=2, **kw):
    mesh = make_mesh(n_devices=F, data=1, feature=F)
    mk = dict(seed=7, seed_capacity=32, dedup="sort",
              topo_sharding="mesh", mesh=mesh, **kw)
    return (GraphSageSampler(topo, sizes, kernel="pallas", **mk),
            GraphSageSampler(topo, sizes, kernel="xla", **mk))


def _assert_dist_parity(dp, dx, seeds, key, caplog):
    reset_once()
    with caplog.at_level(logging.INFO, logger="quiver_tpu"):
        per_p = dp.sample_per_worker(seeds, key=key)
    # the parity must come from the FUSED engine, not a silent degrade
    assert not [r for r in caplog.records
                if "falls back to the XLA path" in r.getMessage()]
    per_x = dx.sample_per_worker(seeds, key=key)
    for w, (a, b) in enumerate(zip(per_p, per_x)):
        assert np.array_equal(np.asarray(a.n_id), np.asarray(b.n_id)), (
            f"n_id diverged on worker {w}"
        )
        for la, lb in zip(a.adjs, b.adjs):
            assert np.array_equal(np.asarray(la.edge_index),
                                  np.asarray(lb.edge_index))


def _dist_graph(n=500, e=5000, seed=0, weights=False, times=False):
    rng = np.random.default_rng(seed)
    ei = np.stack([rng.integers(0, n, e), rng.integers(0, n, e)])
    t = CSRTopo(edge_index=ei.astype(np.int64))
    if weights:
        t.set_edge_weight(rng.random(e) + 0.1)
    if times:
        t.set_edge_time(rng.random(e))
    return t


def test_dist_parity_pallas_mesh2(caplog):
    t = _dist_graph()
    dp, dx = _dist_pair(t, [4, 3])
    seeds = np.random.default_rng(6).integers(0, t.node_count, 61)
    _assert_dist_parity(dp, dx, seeds, jax.random.PRNGKey(11), caplog)


def test_dist_parity_pallas_weighted_mesh2(caplog):
    t = _dist_graph(weights=True, seed=4)
    dp, dx = _dist_pair(t, [4, 3], weighted=True)
    seeds = np.random.default_rng(6).integers(0, t.node_count, 61)
    _assert_dist_parity(dp, dx, seeds, jax.random.PRNGKey(13), caplog)


def test_dist_parity_pallas_temporal_mesh2(caplog):
    t = _dist_graph(times=True, seed=8)
    dp, dx = _dist_pair(t, [4, 3], time_window=(0.2, 0.8))
    seeds = np.random.default_rng(9).integers(0, t.node_count, 61)
    _assert_dist_parity(dp, dx, seeds, jax.random.PRNGKey(17), caplog)


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["uniform", "weighted"])
@pytest.mark.parametrize("F", [4, 8])
def test_dist_parity_pallas_widths(kind, F, caplog):
    """Wider meshes: each shard's slice must still host the DMA window
    (E/F >= window), and the fused owner-side path must stay bitwise."""
    t = _dist_graph(n=800, e=20000, seed=1, weights=kind == "weighted")
    kw = {"weighted": True} if kind == "weighted" else {}
    dp, dx = _dist_pair(t, [4, 3], F=F, **kw)
    seeds = np.random.default_rng(3).integers(0, t.node_count, 97)
    _assert_dist_parity(dp, dx, seeds, jax.random.PRNGKey(19), caplog)


def test_dist_pallas_degrades_on_small_shards(caplog):
    """Shards too small for the DMA window: kernel='pallas' degrades to
    the XLA path at compile time with ONE info log — and the outputs are
    still exactly the XLA sampler's."""
    reset_once()
    t = _dist_graph(n=200, e=1200, seed=2)  # 600/shard < DEFAULT_WINDOW
    dp, dx = _dist_pair(t, [3])
    seeds = np.arange(40)
    key = jax.random.PRNGKey(23)
    with caplog.at_level(logging.INFO, logger="quiver_tpu"):
        per_p = dp.sample_per_worker(seeds, key=key)
        dp.sample_per_worker(seeds, key=key)  # no repeat log
    hits = [r for r in caplog.records
            if "falls back to the XLA path" in r.getMessage()]
    assert len(hits) == 1 and "DMA window" in hits[0].getMessage()
    per_x = dx.sample_per_worker(seeds, key=key)
    for a, b in zip(per_p, per_x):
        assert np.array_equal(np.asarray(a.n_id), np.asarray(b.n_id))


def test_dist_sample_layer_explicit_pallas_raises():
    """Direct dist_sample_layer callers that break the window contract get
    a loud ValueError (only DistGraphSageSampler degrades silently — it
    owns the compile-time gate)."""
    from quiver_tpu.parallel.mesh import FEATURE_AXIS
    from quiver_tpu.sampling.dist import dist_sample_layer

    indptr = jnp.arange(101, dtype=jnp.int32) * 4
    indices = jnp.zeros(400, jnp.int32)  # E_local=400 < DEFAULT_WINDOW

    def body(seeds):
        return dist_sample_layer(
            indptr, indices, 100, seeds, jnp.int32(4), 3,
            jax.random.PRNGKey(0), axis=FEATURE_AXIS, num_shards=2,
            cap=None, kernel="pallas",
        )

    with pytest.raises(ValueError, match="use kernel='xla'"):
        jax.vmap(body, axis_name=FEATURE_AXIS)(
            jnp.zeros((2, 8), jnp.int32)
        )


# -- heterogeneous sharded parity -------------------------------------------


def _hetero_schema(seed=0, n_paper=300, n_author=80, e_cites=12000):
    rng = np.random.default_rng(seed)
    cites = np.stack([rng.integers(0, n_paper, e_cites),
                      rng.integers(0, n_paper, e_cites)])
    writes = np.stack([rng.integers(0, n_author, 600),
                       rng.integers(0, n_paper, 600)])
    return HeteroCSRTopo(
        {"paper": n_paper, "author": n_author},
        {("paper", "cites", "paper"): cites,
         ("author", "writes", "paper"): writes},
    )


def test_dist_hetero_parity_pallas_mesh2(caplog):
    """Mixed engines in ONE compiled program: the big relation's per-shard
    slice hosts the window (fused owner-side hop), the small one degrades
    per relation — outputs bitwise equal to the all-XLA sampler either
    way, and the degrade names only the small relation."""
    reset_once()
    topo = _hetero_schema()
    mesh = make_mesh(n_devices=2, data=1, feature=2)
    mk = dict(input_type="paper", mesh=mesh, seed=0)
    dp = DistHeteroSampler(topo, [3, 2], kernel="pallas", **mk)
    dx = DistHeteroSampler(topo, [3, 2], kernel="xla", **mk)
    seeds = np.arange(48)
    key = jax.random.PRNGKey(7)
    with caplog.at_level(logging.INFO, logger="quiver_tpu"):
        per_p = dp.sample_per_worker(seeds, key=key)
    hits = [r for r in caplog.records
            if "falls back to the XLA path" in r.getMessage()]
    assert len(hits) == 1
    assert "writes" in hits[0].getMessage()   # small rel degrades...
    assert "cites" not in hits[0].getMessage()  # ...the big one rides fused
    per_x = dx.sample_per_worker(seeds, key=key)
    for w, (a, b) in enumerate(zip(per_p, per_x)):
        assert set(a.n_id) == set(b.n_id)
        for t in a.n_id:
            assert np.array_equal(np.asarray(a.n_id[t]),
                                  np.asarray(b.n_id[t])), (
                f"n_id[{t}] diverged on worker {w}"
            )
        for la, lb in zip(a.adjs, b.adjs):
            assert set(la.adjs) == set(lb.adjs)
            for et in la.adjs:
                assert np.array_equal(
                    np.asarray(la.adjs[et].edge_index),
                    np.asarray(lb.adjs[et].edge_index),
                ), f"edge_index[{et}] diverged on worker {w}"
