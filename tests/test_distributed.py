"""Fused SPMD training tests on the 8-device virtual mesh — the multi-chip
data-parallel + sharded-feature configuration (SURVEY §7.2 step 7), which the
reference could only test on real multi-GPU boxes (SURVEY §4)."""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.feature.feature import Feature
from quiver_tpu.feature.shard import ShardedFeature
from quiver_tpu.models.sage import GraphSAGE
from quiver_tpu.parallel.mesh import make_mesh
from quiver_tpu.parallel.trainer import DistributedTrainer


def _labeled_graph(n=400, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, classes, n)
    feat = np.eye(classes, dtype=np.float32)[labels] * 2.0
    feat += rng.normal(scale=0.8, size=(n, classes)).astype(np.float32)
    rows, cols = [], []
    for c in range(classes):
        members = np.where(labels == c)[0]
        rows.extend(rng.choice(members, 6 * len(members)))
        cols.extend(rng.choice(members, 6 * len(members)))
    ei = np.stack([np.asarray(rows), np.asarray(cols)])
    return ei, feat, labels


@pytest.mark.parametrize("feature_kind", ["replicate", "shard"])
def test_fused_training_learns(feature_kind):
    ei, feat, labels = _labeled_graph()
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    mesh = make_mesh(data=4, feature=2)
    sampler = GraphSageSampler(topo, [5, 5], seed=3)
    if feature_kind == "replicate":
        feature = Feature(device_cache_size="1G").from_cpu_tensor(feat[:n])
    else:
        feature = ShardedFeature(mesh, device_cache_size="1G").from_cpu_tensor(feat[:n])

    model = GraphSAGE(hidden=32, num_classes=4, num_layers=2)
    tx = optax.adam(5e-3)
    trainer = DistributedTrainer(mesh, sampler, feature, model, tx, local_batch=64)
    params, opt_state = trainer.init(jax.random.PRNGKey(0))

    labels_dev = jnp.asarray(labels[:n].astype(np.int32))
    rng = np.random.default_rng(0)
    losses = []
    for step in range(25):
        seeds = rng.integers(0, n, 256)  # 4 data shards x 64
        params, opt_state, loss = trainer.step(
            params, opt_state, seeds, labels_dev, jax.random.PRNGKey(step)
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.75, losses


def test_fused_rejects_cold_tier():
    ei, feat, labels = _labeled_graph(n=100)
    topo = CSRTopo(edge_index=ei)
    mesh = make_mesh(data=4, feature=2)
    sampler = GraphSageSampler(topo, [3], seed=0)
    feature = Feature(device_cache_size=10 * 16).from_cpu_tensor(feat[: topo.node_count])
    model = GraphSAGE(hidden=8, num_classes=4, num_layers=1)
    with pytest.raises(ValueError, match="device-resident"):
        DistributedTrainer(mesh, sampler, feature, model, optax.sgd(0.1))


def test_shard_seeds_packing():
    ei, feat, labels = _labeled_graph(n=100)
    topo = CSRTopo(edge_index=ei)
    mesh = make_mesh(data=4, feature=2)
    sampler = GraphSageSampler(topo, [3], seed=0)
    feature = Feature(device_cache_size="1M").from_cpu_tensor(feat[: topo.node_count])
    model = GraphSAGE(hidden=8, num_classes=4, num_layers=1)
    trainer = DistributedTrainer(mesh, sampler, feature, model, optax.sgd(0.1), local_batch=8)
    packed = trainer.shard_seeds(np.arange(20))
    blocks = packed.reshape(4, 8)
    # valid-prefix blocks, -1 padded
    for b in blocks:
        valid = b[b >= 0]
        assert np.all(b[: len(valid)] == valid)
    assert np.array_equal(np.sort(packed[packed >= 0]), np.arange(20))