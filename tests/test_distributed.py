"""Fused SPMD training tests on the 8-device virtual mesh — the multi-chip
data-parallel + sharded-feature configuration (SURVEY §7.2 step 7), which the
reference could only test on real multi-GPU boxes (SURVEY §4)."""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.feature.feature import Feature
from quiver_tpu.feature.shard import ShardedFeature
from quiver_tpu.models.sage import GraphSAGE
from quiver_tpu.parallel.mesh import make_mesh
from quiver_tpu.parallel.trainer import DistributedTrainer


def _labeled_graph(n=400, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, classes, n)
    feat = np.eye(classes, dtype=np.float32)[labels] * 2.0
    feat += rng.normal(scale=0.8, size=(n, classes)).astype(np.float32)
    rows, cols = [], []
    for c in range(classes):
        members = np.where(labels == c)[0]
        rows.extend(rng.choice(members, 6 * len(members)))
        cols.extend(rng.choice(members, 6 * len(members)))
    ei = np.stack([np.asarray(rows), np.asarray(cols)])
    return ei, feat, labels


@pytest.mark.parametrize(
    "feature_kind,seed_sharding",
    [("replicate", "data"), ("shard", "data"), ("shard", "all")],
)
def test_fused_training_learns(feature_kind, seed_sharding):
    ei, feat, labels = _labeled_graph()
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    mesh = make_mesh(data=4, feature=2)
    sampler = GraphSageSampler(topo, [5, 5], seed=3)
    if feature_kind == "replicate":
        feature = Feature(device_cache_size="1G").from_cpu_tensor(feat[:n])
    else:
        feature = ShardedFeature(mesh, device_cache_size="1G").from_cpu_tensor(feat[:n])

    model = GraphSAGE(hidden=32, num_classes=4, num_layers=2)
    tx = optax.adam(5e-3)
    trainer = DistributedTrainer(mesh, sampler, feature, model, tx,
                                 local_batch=64, seed_sharding=seed_sharding)
    # "all": every device a worker -> global batch spans 8 blocks
    assert trainer.global_batch == (512 if seed_sharding == "all" else 256)
    params, opt_state = trainer.init(jax.random.PRNGKey(0))

    labels_dev = jnp.asarray(labels[:n].astype(np.int32))
    rng = np.random.default_rng(0)
    losses = []
    for step in range(25):
        seeds = rng.integers(0, n, trainer.global_batch)
        params, opt_state, loss = trainer.step(
            params, opt_state, seeds, labels_dev, jax.random.PRNGKey(step)
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.75, losses


def test_fused_beyond_hbm_epoch_scan_learns():
    """The papers100M-class config — HOST-mode topology AND a cold-tier
    feature table — trains through ONE compiled epoch program (epoch_scan),
    staged host gathers composed inside the shard_map step (VERDICT r3
    task 6; reference equivalent: UVA training,
    dist_sampling_ogb_paper100M_quiver.py:120-165)."""
    ei, feat, labels = _labeled_graph()
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    mesh = make_mesh(data=4, feature=2)
    sampler = GraphSageSampler(topo, [5, 5], seed=3, mode="HOST")
    # budget covers ~half the rows: real hot AND cold traffic every batch
    feature = Feature(
        device_cache_size=(n // 2) * feat.shape[1] * 4, csr_topo=topo
    ).from_cpu_tensor(feat[:n])
    assert feature.cold is not None and 0.3 < feature.cache_ratio < 0.7
    assert sampler.topo.host_indices or not jax.devices()[0].platform == "tpu"

    model = GraphSAGE(hidden=32, num_classes=4, num_layers=2)
    trainer = DistributedTrainer(
        mesh, sampler, feature, model, optax.adam(5e-3), local_batch=64
    )
    params, opt_state = trainer.init(jax.random.PRNGKey(0))
    labels_dev = jnp.asarray(labels[:n].astype(np.int32))

    train_idx = np.random.default_rng(0).integers(
        0, n, 8 * trainer.global_batch)
    seed_mat = trainer.pack_epoch(train_idx, seed=7)
    params, opt_state, losses = trainer.epoch_scan(
        params, opt_state, seed_mat, labels_dev, jax.random.PRNGKey(42)
    )
    losses = np.asarray(losses)
    assert losses.shape == (8,)
    assert losses[-1] < losses[0] * 0.75, losses


def test_fused_cold_tier_matches_full_hbm():
    """Tiering must not change math: a cold-tier fused step returns the
    same loss trajectory as the all-HBM step on identical seeds/keys."""
    ei, feat, labels = _labeled_graph()
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    mesh = make_mesh(data=4, feature=2)
    labels_dev = jnp.asarray(labels[:n].astype(np.int32))
    model = GraphSAGE(hidden=16, num_classes=4, num_layers=2)
    results = []
    for budget in ("1G", (n // 2) * feat.shape[1] * 4):
        sampler = GraphSageSampler(topo, [5, 5], seed=3)
        feature = Feature(device_cache_size=budget).from_cpu_tensor(feat[:n])
        trainer = DistributedTrainer(
            mesh, sampler, feature, model, optax.adam(5e-3), local_batch=32
        )
        params, opt_state = trainer.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        losses = []
        for step in range(3):
            seeds = rng.integers(0, n, trainer.global_batch)
            params, opt_state, loss = trainer.step(
                params, opt_state, seeds, labels_dev, jax.random.PRNGKey(step)
            )
            losses.append(float(loss))
        results.append(losses)
    np.testing.assert_allclose(results[0], results[1], rtol=1e-5, atol=1e-6)


@pytest.mark.slow  # 31s pair; the replicated cold-tier parity stays fast
@pytest.mark.parametrize("seed_sharding", ["data", "all"])
def test_fused_sharded_cold_tier_matches_full(seed_sharding):
    """Mesh-sharded hot tier + pinned-host cold tier through the fused
    step: the psum/routed hot gather and the staged cold gather compose in
    one shard_map program, and tiering must not change the math."""
    ei, feat, labels = _labeled_graph()
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    mesh = make_mesh(data=4, feature=2)
    labels_dev = jnp.asarray(labels[:n].astype(np.int32))
    model = GraphSAGE(hidden=16, num_classes=4, num_layers=2)
    results = []
    for budget in ("1G", (n // 2) * feat.shape[1] * 4 // 2):
        sampler = GraphSageSampler(topo, [5, 5], seed=3)
        feature = ShardedFeature(
            mesh, device_cache_size=budget
        ).from_cpu_tensor(feat[:n])
        if budget != "1G":
            assert feature.cold is not None, feature.cache_ratio
        trainer = DistributedTrainer(
            mesh, sampler, feature, model, optax.adam(5e-3), local_batch=32,
            seed_sharding=seed_sharding,
        )
        params, opt_state = trainer.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        losses = []
        for step in range(3):
            seeds = rng.integers(0, n, trainer.global_batch)
            params, opt_state, loss = trainer.step(
                params, opt_state, seeds, labels_dev, jax.random.PRNGKey(step)
            )
            losses.append(float(loss))
        results.append(losses)
    assert results[1][0] > 0 and np.all(np.isfinite(results[1]))
    np.testing.assert_allclose(results[0], results[1], rtol=1e-5, atol=1e-6)


def test_fused_int8_feature_dequantizes():
    """ADVICE r3: the fused gather must dequantize int8 storage (scale is
    applied inside the shard_map program), not train on raw codes. With
    absmax/row quantization the first-step loss must track the f32 run
    closely; raw int8 codes (~127x scale) would blow it apart."""
    ei, feat, labels = _labeled_graph()
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    mesh = make_mesh(data=4, feature=2)
    labels_dev = jnp.asarray(labels[:n].astype(np.int32))
    model = GraphSAGE(hidden=16, num_classes=4, num_layers=2)
    first_losses = {}
    for dtype in (None, "int8"):
        sampler = GraphSageSampler(topo, [5, 5], seed=3)
        feature = ShardedFeature(
            mesh, device_cache_size="1G", dtype=dtype
        ).from_cpu_tensor(feat[:n])
        trainer = DistributedTrainer(
            mesh, sampler, feature, model, optax.adam(5e-3), local_batch=32,
            seed_sharding="all",
        )
        params, opt_state = trainer.init(jax.random.PRNGKey(0))
        seeds = np.random.default_rng(0).integers(0, n, trainer.global_batch)
        _, _, loss = trainer.step(
            params, opt_state, seeds, labels_dev, jax.random.PRNGKey(1)
        )
        first_losses[dtype] = float(loss)
    assert abs(first_losses["int8"] - first_losses[None]) < 0.05 * abs(
        first_losses[None]
    ), first_losses


def test_shard_seeds_packing():
    ei, feat, labels = _labeled_graph(n=100)
    topo = CSRTopo(edge_index=ei)
    mesh = make_mesh(data=4, feature=2)
    sampler = GraphSageSampler(topo, [3], seed=0)
    feature = Feature(device_cache_size="1M").from_cpu_tensor(feat[: topo.node_count])
    model = GraphSAGE(hidden=8, num_classes=4, num_layers=1)
    trainer = DistributedTrainer(mesh, sampler, feature, model, optax.sgd(0.1), local_batch=8)
    packed = trainer.shard_seeds(np.arange(20))
    blocks = packed.reshape(4, 8)
    # valid-prefix blocks, -1 padded
    for b in blocks:
        valid = b[b >= 0]
        assert np.all(b[: len(valid)] == valid)
    assert np.array_equal(np.sort(packed[packed >= 0]), np.arange(20))

def test_epoch_scan_matches_step_loop():
    """epoch_scan (whole epoch in ONE program) must reproduce the per-step
    loop exactly: same packed blocks + same per-step keys through the same
    _step program, so losses and final params agree."""
    ei, feat, labels = _labeled_graph()
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    mesh = make_mesh(data=4, feature=2)
    sampler = GraphSageSampler(topo, [5, 5], seed=3)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat[:n])
    model = GraphSAGE(hidden=16, num_classes=4, num_layers=2)
    trainer = DistributedTrainer(
        mesh, sampler, feature, model, optax.adam(5e-3), local_batch=32
    )
    params0, opt0 = trainer.init(jax.random.PRNGKey(0))
    labels_dev = jnp.asarray(labels[:n].astype(np.int32))

    train_idx = np.random.default_rng(0).integers(0, n, 5 * trainer.global_batch)
    seed_mat = trainer.pack_epoch(train_idx, key=7)
    assert seed_mat.shape == (5, trainer.global_batch)
    assert np.array_equal(
        np.sort(seed_mat[seed_mat >= 0]), np.sort(train_idx)
    )

    key0 = jax.random.PRNGKey(42)
    p_scan, _, losses = trainer.epoch_scan(
        params0, opt0, seed_mat, labels_dev, key0
    )
    assert losses.shape == (5,)

    # replay: same packed rows through the public per-step path
    keys = jax.random.split(key0, 5)
    p, o = params0, opt0
    loop_losses = []
    for s in range(5):
        row = seed_mat[s]
        p, o, loss = trainer.step(
            p, o, row[row >= 0], labels_dev, keys[s]
        )
        loop_losses.append(float(loss))
    np.testing.assert_allclose(
        np.asarray(losses), np.asarray(loop_losses), rtol=1e-4, atol=1e-5
    )
    flat_scan = jax.tree_util.tree_leaves(p_scan)
    flat_loop = jax.tree_util.tree_leaves(p)
    for a, b in zip(flat_scan, flat_loop):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_host_offload_multichip_training_learns():
    """VERDICT r1 item 5: the beyond-HBM configuration (HOST topology +
    cold feature tier) must have a multi-chip path. DataParallelTrainer on
    the full 8-device mesh, papers100M-architecture: per-worker sample +
    tiered gather, one SPMD step with gradient pmean, prefetch overlap."""
    from quiver_tpu.parallel.trainer import DataParallelTrainer

    ei, feat, labels = _labeled_graph(n=600)
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    mesh = make_mesh(data=8, feature=1)
    local_batch = 32
    sampler = GraphSageSampler(
        topo, [5, 5], mode="HOST", seed_capacity=local_batch, seed=5
    )
    # 30% hot, remainder cold (host tier where the platform supports it)
    row_bytes = feat.shape[1] * 4
    feature = Feature(
        device_cache_size=int(0.3 * n) * row_bytes, csr_topo=topo
    ).from_cpu_tensor(feat)
    assert feature.cold is not None  # genuinely beyond-"HBM" config

    model = GraphSAGE(hidden=32, num_classes=4, num_layers=2)
    trainer = DataParallelTrainer(
        mesh, sampler, feature, model, optax.adam(5e-3), local_batch=local_batch
    )
    params, opt_state = trainer.init(jax.random.PRNGKey(0))
    lab = jnp.asarray(labels)
    train_idx = np.arange(n)

    key = jax.random.PRNGKey(1)
    losses = []
    for epoch in range(6):
        key, sub = jax.random.split(key)
        params, opt_state, mean_loss, steps = trainer.train_epoch(
            params, opt_state, train_idx, lab, sub,
            rng=np.random.default_rng(epoch),
        )
        assert steps == max(n // trainer.global_batch, 1)
        losses.append(mean_loss)
    assert losses[-1] < losses[0] * 0.7, losses


def test_data_parallel_trainer_rejects_sharded_feature():
    from quiver_tpu.parallel.trainer import DataParallelTrainer

    ei, feat, labels = _labeled_graph()
    topo = CSRTopo(edge_index=ei)
    mesh = make_mesh(data=4, feature=2)
    sampler = GraphSageSampler(topo, [3], seed=0)
    sf = ShardedFeature(mesh, device_cache_size="1G", csr_topo=topo)
    model = GraphSAGE(hidden=8, num_classes=4, num_layers=1)
    with pytest.raises(ValueError, match="fused DistributedTrainer"):
        DataParallelTrainer(mesh, sampler, sf, model, optax.adam(1e-3))
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    with pytest.raises(ValueError, match="feature=1"):
        DataParallelTrainer(mesh, sampler, feature, model, optax.adam(1e-3))


def test_data_parallel_short_blocks_mask_frontier_lanes():
    """Regression: for a seed block shorter than local_batch, n_id lanes
    past batch_size hold FRONTIER nodes (not -1); they must not contribute
    to the loss. Oracle: a data=1 step on a short block must equal the
    single-device train step masked to the true batch."""
    from quiver_tpu.parallel.trainer import DataParallelTrainer
    from quiver_tpu.parallel.train import make_train_step

    ei, feat, labels = _labeled_graph(n=300)
    topo = CSRTopo(edge_index=ei)
    local_batch = 32
    sampler = GraphSageSampler(topo, [4, 3], seed_capacity=local_batch, seed=9)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    model = GraphSAGE(hidden=16, num_classes=4, num_layers=2)
    tx = optax.sgd(0.0)  # lr 0: params unchanged, loss comparable
    mesh = make_mesh(data=1, feature=1, devices=jax.devices()[:1])
    trainer = DataParallelTrainer(mesh, sampler, feature, model, tx,
                                  local_batch=local_batch)
    params, opt_state = trainer.init(jax.random.PRNGKey(0))
    lab = jnp.asarray(labels)

    short = np.arange(10)  # batch 10 << local_batch 32
    out = sampler.sample(short)
    from quiver_tpu.parallel.pipeline import Batch

    batch = Batch(short, out, feature[out.n_id])
    _, _, dp_loss = trainer.step(params, opt_state, [batch], lab,
                                 jax.random.PRNGKey(5))

    # oracle: plain train step with the correct short mask
    step = jax.jit(make_train_step(model, tx))
    seed_ids = out.n_id[:local_batch]
    labels_b = lab[jnp.clip(seed_ids, 0)]
    mask = (jnp.arange(local_batch) < 10) & (seed_ids >= 0)
    # same dropout key derivation as the DP body (fold_in axis index 0)
    key = jax.random.fold_in(jax.random.PRNGKey(5), 0)
    _, _, ref_loss = step(params, opt_state, batch.x, out.adjs, labels_b,
                          mask, key)
    assert np.isclose(float(dp_loss), float(ref_loss), rtol=1e-5), (
        float(dp_loss), float(ref_loss))


def test_data_parallel_epoch_smaller_than_global_batch():
    """train_epoch with fewer train nodes than one global batch (uneven
    short blocks on every shard) must run and stay finite."""
    from quiver_tpu.parallel.trainer import DataParallelTrainer

    ei, feat, labels = _labeled_graph(n=300)
    topo = CSRTopo(edge_index=ei)
    mesh = make_mesh(data=8, feature=1)
    sampler = GraphSageSampler(topo, [4, 3], seed_capacity=32, seed=2)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    model = GraphSAGE(hidden=16, num_classes=4, num_layers=2)
    trainer = DataParallelTrainer(mesh, sampler, feature, model,
                                  optax.adam(1e-3), local_batch=32)
    params, opt_state = trainer.init(jax.random.PRNGKey(0))
    params, opt_state, loss, steps = trainer.train_epoch(
        params, opt_state, np.arange(100), jnp.asarray(labels),
        jax.random.PRNGKey(1),
    )
    assert steps == 1 and np.isfinite(loss)


def test_epoch_scan_gcn():
    """The whole-epoch program must also serve the GCN family (in-block
    symmetric normalization inside the scan body)."""
    from quiver_tpu.models.gcn import GCN

    ei, feat, labels = _labeled_graph()
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    mesh = make_mesh(data=4, feature=2)
    sampler = GraphSageSampler(topo, [5, 5], seed=3)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat[:n])
    model = GCN(hidden=16, num_classes=4, num_layers=2)
    trainer = DistributedTrainer(
        mesh, sampler, feature, model, optax.adam(5e-3), local_batch=32
    )
    params, opt = trainer.init(jax.random.PRNGKey(0))
    labels_dev = jnp.asarray(labels[:n].astype(np.int32))
    idx = np.random.default_rng(1).integers(0, n, 4 * trainer.global_batch)
    seed_mat = trainer.pack_epoch(idx, seed=0)
    first = last = None
    for e in range(3):
        seed_mat = trainer.pack_epoch(idx, seed=e)
        params, opt, losses = trainer.epoch_scan(
            params, opt, seed_mat, labels_dev, jax.random.PRNGKey(e)
        )
        losses = np.asarray(losses)
        assert np.all(np.isfinite(losses))
        if first is None:
            first = losses[0]
        last = losses[-1]
    assert last < first, (first, last)


def test_epoch_scan_gin():
    """The whole-epoch program must also serve the GIN family (sum
    aggregation + MLP inside the scan body)."""
    from quiver_tpu.models.gin import GIN

    ei, feat, labels = _labeled_graph()
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    mesh = make_mesh(data=4, feature=2)
    sampler = GraphSageSampler(topo, [5, 5], seed=3)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat[:n])
    model = GIN(hidden=16, num_classes=4, num_layers=2)
    trainer = DistributedTrainer(
        mesh, sampler, feature, model, optax.adam(5e-3), local_batch=32
    )
    params, opt = trainer.init(jax.random.PRNGKey(0))
    labels_dev = jnp.asarray(labels[:n].astype(np.int32))
    idx = np.random.default_rng(1).integers(0, n, 4 * trainer.global_batch)
    first = last = None
    for e in range(3):
        seed_mat = trainer.pack_epoch(idx, seed=e)
        params, opt, losses = trainer.epoch_scan(
            params, opt, seed_mat, labels_dev, jax.random.PRNGKey(e)
        )
        losses = np.asarray(losses)
        assert np.all(np.isfinite(losses))
        if first is None:
            first = losses[0]
        last = losses[-1]
    assert last < first, (first, last)


def test_epoch_scan_gat():
    """The whole-epoch program must also serve the GAT family (attention
    aggregation inside the scan body)."""
    from quiver_tpu.models.gat import GAT

    ei, feat, labels = _labeled_graph()
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    mesh = make_mesh(data=4, feature=2)
    sampler = GraphSageSampler(topo, [5, 5], seed=3)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat[:n])
    model = GAT(hidden=16, num_classes=4, num_layers=2, heads=2)
    trainer = DistributedTrainer(
        mesh, sampler, feature, model, optax.adam(5e-3), local_batch=32
    )
    params, opt = trainer.init(jax.random.PRNGKey(0))
    labels_dev = jnp.asarray(labels[:n].astype(np.int32))
    idx = np.random.default_rng(1).integers(0, n, 4 * trainer.global_batch)
    first = last = None
    for e in range(3):
        seed_mat = trainer.pack_epoch(idx, key=e)
        params, opt, losses = trainer.epoch_scan(
            params, opt, seed_mat, labels_dev, jax.random.PRNGKey(e)
        )
        losses = np.asarray(losses)
        assert np.all(np.isfinite(losses))
        if first is None:
            first = losses[0]
        last = losses[-1]
    assert last < first, (first, last)


def test_train_epoch_empty_seed_set_raises():
    """An empty train_idx used to silently return a float("nan") mean loss
    (trainer.py train_epoch) — it must fail loudly instead."""
    from quiver_tpu.parallel.trainer import DataParallelTrainer

    ei, feat, _ = _labeled_graph(n=200)
    topo = CSRTopo(edge_index=ei)
    mesh = make_mesh(data=8, feature=1)
    sampler = GraphSageSampler(topo, [3], seed_capacity=8, seed=0)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(
        feat[: topo.node_count]
    )
    model = GraphSAGE(hidden=8, num_classes=4, num_layers=1)
    trainer = DataParallelTrainer(
        mesh, sampler, feature, model, optax.sgd(1e-2), local_batch=8
    )
    params, opt_state = trainer.init(jax.random.PRNGKey(0))
    labels = jnp.zeros(topo.node_count, jnp.int32)
    with pytest.raises(ValueError, match="empty seed set"):
        trainer.train_epoch(
            params, opt_state, np.array([], np.int64), labels,
            jax.random.PRNGKey(1),
        )
