"""Unit tests for the benchmark supervision plumbing (VERDICT r2 item 1).

The repo-root ``bench.py`` supervisor and ``benchmarks.scoreboard`` runner
are the round's guarantee that a measurement always survives — their
record-parsing and fallback-selection logic gets direct coverage here
(the end-to-end behavior is exercised by running them; these tests pin the
corner cases that e2e runs hit rarely: stage rows after the headline,
timeout-harvested stdout, malformed lines).
"""

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_supervisor", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench = _load_bench()


def _rec(metric, **kw):
    return json.dumps({"metric": metric, "value": 1.0, **kw})


def test_split_records_prefers_headline_over_trailing_stage_rows():
    text = "\n".join([
        "noise line",
        _rec("sampled-edges/sec/chip", value=5.0),
        _rec("sampler-stage-ms", layer=0, stage="sample"),
        _rec("sampler-stage-ms", layer=0, stage="reindex"),
    ])
    rec, extras = bench._split_records(text)
    assert rec["metric"] == "sampled-edges/sec/chip"
    assert len(extras) == 2
    assert all(x["metric"] == "sampler-stage-ms" for x in extras)


def test_split_records_headline_never_in_extras():
    text = _rec("sampled-edges/sec/chip")
    rec, extras = bench._split_records(text)
    assert rec is not None and extras == []


def test_split_records_falls_back_to_last_record():
    text = "\n".join([
        _rec("something-else", a=1),
        _rec("another-metric", b=2),
    ])
    rec, extras = bench._split_records(text)
    assert rec["metric"] == "another-metric"
    assert [x["metric"] for x in extras] == ["something-else"]


def test_split_records_ignores_malformed_lines():
    text = "\n".join([
        "{not json",
        json.dumps({"no_metric": 1}),
        "",
        _rec("sampled-edges/sec/chip"),
    ])
    rec, extras = bench._split_records(text)
    assert rec["metric"] == "sampled-edges/sec/chip" and extras == []


def test_split_records_empty():
    assert bench._split_records("") == (None, [])
    assert bench._split_records("no json here") == (None, [])


def test_probe_src_forces_cpu_workaround():
    """The probe must re-apply JAX_PLATFORMS=cpu via jax.config — the
    image's sitecustomize pins the TPU plugin before env vars are read, so
    a probe without the workaround hangs on a dead tunnel even when the
    caller asked for CPU."""
    assert "jax.config.update" in bench._PROBE_SRC
    assert "JAX_PLATFORMS" in bench._PROBE_SRC


def test_scoreboard_harvest_and_merge_order():
    sys.path.insert(0, REPO)
    from benchmarks.scoreboard import JOBS, _harvest

    recs = _harvest("\n".join([
        "garbage", _rec("m1"), "{bad", _rec("m2", x=1),
    ]))
    assert [r["metric"] for r in recs] == ["m1", "m2"]
    # job keys stay unique (the --only validation and merge rely on it)
    keys = [k for k, *_ in JOBS]
    assert len(keys) == len(set(keys))


def test_supervised_child_contract():
    """benchmarks.common helpers honor QUIVER_BENCH_SUPERVISED: no probe,
    fail fast (exit 3) instead of self-healing."""
    sys.path.insert(0, REPO)
    import pytest

    from benchmarks import common

    class _Args:
        backend_retries = 0
        backend_retry_delay = 0.0

    os.environ["QUIVER_BENCH_SUPERVISED"] = "1"
    try:
        assert common._supervised()
        with pytest.raises(SystemExit) as e:
            common.run_guarded(
                lambda: (_ for _ in ()).throw(RuntimeError("boom")), _Args()
            )
        assert e.value.code == 3
    finally:
        del os.environ["QUIVER_BENCH_SUPERVISED"]
    assert not common._supervised()


def test_stream_seps_int32_guard():
    """The shared fused-stream helper must refuse configs whose single-batch
    worst-case edge count wraps int32, and clamp oversized stream lengths."""
    sys.path.insert(0, REPO)
    import numpy as np
    import jax.numpy as jnp

    from benchmarks import common

    class _StubSampler:
        """caps/sizes chosen so max_edges_per_batch ~= 4.2e9 > 2^31-1."""
        sizes = (1000, 1000, 1000)
        topo = jnp.zeros(4, jnp.int32)

        def _compiled(self, batch):
            def run(topo, seeds, n, key):
                raise AssertionError("run must not execute when guarded out")
            return run, (2**21, 2**21, 2**21)

    rng = np.random.default_rng(0)
    assert common.stream_seps(_StubSampler(), 100, 2048, 64, rng) is None

    class _SmallSampler:
        """max_edges_per_batch = 8*2 + 16*2 + 16*2 = 80 -> max_stream huge;
        a tiny real-ish run validates the tally path end to end."""
        sizes = (2, 2)
        topo = jnp.zeros(4, jnp.int32)

        def _compiled(self, batch):
            S = batch

            def run(topo, seeds, n, key):
                ec = (jnp.int32(3), jnp.int32(5))
                return (seeds, n, (), jnp.int32(0), ec, (n, n))
            return run, (16, 16)

    res = common.stream_seps(_SmallSampler(), 100, 8, 4, rng, reps=2)
    assert res is not None
    seps, oflo, stream = res
    assert stream == 4 and oflo == 0 and seps > 0


def test_scoreboard_run_job_retry_and_fallback(monkeypatch):
    """run_job retries once on a fast error, then degrades to the labeled
    CPU smoke; timeouts skip the retry (a hung tunnel must not burn a
    second full budget)."""
    sys.path.insert(0, REPO)
    from benchmarks import scoreboard

    calls = []

    def fake_run_once(module, extra, env, timeout_s):
        calls.append((tuple(extra), env.get("JAX_PLATFORMS")))
        if len(calls) <= 2:
            return [], "boom rc=1"
        return [{"metric": "m", "value": 1}], None

    monkeypatch.setattr(scoreboard, "_run_once", fake_run_once)
    monkeypatch.setattr(scoreboard.time, "sleep", lambda s: None)
    recs, err, _ = scoreboard.run_job("mod", ["--x"], smoke=False, timeout_s=5)
    assert recs and err is None
    # attempt, retry, then CPU-smoke fallback with the degraded label
    assert len(calls) == 3
    assert calls[2][1] == "cpu" and "--smoke" in calls[2][0]

    calls.clear()

    def fake_timeout(module, extra, env, timeout_s):
        calls.append((tuple(extra), env.get("JAX_PLATFORMS")))
        if len(calls) == 1:
            return [], "timeout>5s"
        return [{"metric": "m", "value": 2}], None

    monkeypatch.setattr(scoreboard, "_run_once", fake_timeout)
    recs, err, _ = scoreboard.run_job("mod", [], smoke=False, timeout_s=5)
    assert recs and err is None
    # no same-backend retry after a hang: straight to the CPU fallback
    assert len(calls) == 2 and calls[1][1] == "cpu"


def test_scoreboard_timeout_keeps_partial_records(monkeypatch):
    """A job killed at its timeout must keep records already flushed to
    stdout (the round-3 lesson: emit flushes exactly so this works)."""
    sys.path.insert(0, REPO)
    from benchmarks import scoreboard

    def fake_run_once(module, extra, env, timeout_s):
        return [{"metric": "sampled-edges/sec/chip", "value": 3}], "timeout>5s"

    monkeypatch.setattr(scoreboard, "_run_once", fake_run_once)
    recs, err, _ = scoreboard.run_job("mod", [], smoke=False, timeout_s=5)
    assert recs == [{"metric": "sampled-edges/sec/chip", "value": 3}]
    assert str(err).startswith("timeout")


def test_microbench_emits_all_primitives():
    """The primitive microbench must produce one record per building block
    (the chip-window diagnosis depends on all six being present)."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    r = subprocess.run(
        [sys.executable, "-m", "benchmarks.microbench", "--smoke"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    recs = [json.loads(l) for l in r.stdout.splitlines()
            if l.strip().startswith("{")]
    ops = {x["op"] for x in recs if x["metric"] == "primitive-Melem/s"}
    assert ops == {"sort", "argsort-pair", "gather", "scatter-set",
                   "scatter-min", "cummax"}, r.stderr[-400:]
    assert all(x["value"] > 0 for x in recs)


@pytest.mark.slow
def test_dedup_both_emits_fastest_stream_first():
    """--dedup both must emit its stream records fastest-first (the
    supervisor headlines the FIRST SEPS record), with all three strategies
    present and the per-call record last.

    slow: a full-scale bench-harness subprocess — compiles three dedup
    variants end-to-end (~35 s); the emit-ordering logic it pins is
    host-side and changes rarely."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    r = subprocess.run(
        [sys.executable, "-m", "benchmarks.bench_sampler", "--smoke",
         "--stream", "2", "--dedup", "both"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    recs = [json.loads(l) for l in r.stdout.splitlines()
            if l.strip().startswith("{")]
    streams = [x for x in recs if x.get("dispatch") == "stream"]
    assert len(streams) == 3, r.stdout + r.stderr[-500:]
    assert {x["dedup"] for x in streams} == {"sort", "map", "scan"}
    vals = [x["value"] for x in streams]
    assert vals == sorted(vals, reverse=True)
    assert recs[-1]["dispatch"] == "percall"
