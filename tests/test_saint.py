"""GraphSAINT sampler tests (reference planned qv.saint_subgraph but never
landed it, SURVEY §2.5 — here it must actually work).

Oracle: numpy induced-subgraph construction.
"""

import pytest
import numpy as np
import jax
import jax.numpy as jnp

from quiver_tpu import CSRTopo
from quiver_tpu.sampling.saint import (
    SAINTEdgeSampler,
    SAINTNodeSampler,
    SAINTRandomWalkSampler,
    estimate_saint_norm,
    random_walk,
    saint_subgraph,
)
from quiver_tpu.utils.graphgen import generate_pareto_graph


def _induced_edges_ref(topo, nodes):
    """All (u, v) with u, v in nodes and v in N(u), as a set of global pairs."""
    ns = set(int(x) for x in nodes if x >= 0)
    out = set()
    for u in ns:
        for v in topo.indices[topo.indptr[u]:topo.indptr[u + 1]]:
            if int(v) in ns:
                out.add((u, int(v)))
    return out


def test_saint_subgraph_matches_oracle():
    ei = generate_pareto_graph(300, 6.0, seed=0)
    topo = CSRTopo(edge_index=ei)
    dev = topo.to_device()
    rng = np.random.default_rng(1)
    nodes = np.unique(rng.integers(0, 300, 80)).astype(np.int32)
    C = 96
    padded = np.full(C, -1, np.int32)
    padded[: len(nodes)] = nodes

    sub = saint_subgraph(dev, jnp.asarray(padded), jnp.int32(len(nodes)),
                         deg_cap=topo.max_degree)
    src, dst = np.asarray(sub.edge_index)
    nid = np.asarray(sub.node_id)
    got = {(int(nid[s]), int(nid[d])) for s, d in zip(src, dst) if s >= 0}
    expect = _induced_edges_ref(topo, nodes)
    assert got == expect
    assert int(sub.num_nodes) == len(nodes)
    assert int(sub.num_edges) == len(expect)


def test_saint_subgraph_deg_cap_truncates():
    # star: node 0 -> 1..20
    ei = np.stack([np.zeros(20, np.int64), np.arange(1, 21)])
    topo = CSRTopo(edge_index=ei)
    dev = topo.to_device()
    padded = np.full(32, -1, np.int32)
    padded[:21] = np.arange(21)
    sub = saint_subgraph(dev, jnp.asarray(padded), jnp.int32(21), deg_cap=5)
    # only the first 5 CSR-order edges of node 0 survive the window
    assert int(sub.num_edges) == 5


def test_node_sampler_end_to_end():
    ei = generate_pareto_graph(500, 8.0, seed=2)
    topo = CSRTopo(edge_index=ei)
    s = SAINTNodeSampler(topo, budget=64, seed=0)
    sub1 = s.sample()
    sub2 = s.sample()
    assert 0 < int(sub1.num_nodes) <= 64
    # different draws
    assert not np.array_equal(np.asarray(sub1.node_id), np.asarray(sub2.node_id))
    # all emitted edges are real graph edges
    src, dst = np.asarray(sub1.edge_index)
    nid = np.asarray(sub1.node_id)
    for sL, dL in zip(src, dst):
        if sL >= 0:
            u, v = int(nid[sL]), int(nid[dL])
            assert v in topo.indices[topo.indptr[u]:topo.indptr[u + 1]]


def test_edge_sampler_endpoints_present():
    ei = generate_pareto_graph(400, 5.0, seed=3)
    topo = CSRTopo(edge_index=ei)
    s = SAINTEdgeSampler(topo, budget=32, seed=1)
    sub = s.sample()
    assert int(sub.num_nodes) > 0
    assert int(sub.num_nodes) <= 64  # 2 * budget


def test_random_walk_validity():
    ei = generate_pareto_graph(300, 6.0, seed=4)
    topo = CSRTopo(edge_index=ei)
    dev = topo.to_device()
    starts = jnp.asarray(np.arange(16, dtype=np.int32))
    walks = np.asarray(random_walk(dev, starts, 4, jax.random.PRNGKey(0)))
    assert walks.shape == (16, 5)
    indptr, indices = topo.indptr, topo.indices
    for r in range(16):
        assert walks[r, 0] == r
        for t in range(1, 5):
            u, v = int(walks[r, t - 1]), int(walks[r, t])
            # either a real step or a dead-end self-stay
            assert v == u or v in indices[indptr[u]:indptr[u + 1]]


def test_rw_sampler_end_to_end():
    ei = generate_pareto_graph(400, 6.0, seed=5)
    topo = CSRTopo(edge_index=ei)
    s = SAINTRandomWalkSampler(topo, roots=8, walk_length=3, seed=2)
    sub = s.sample()
    assert 0 < int(sub.num_nodes) <= 8 * 4


def test_sample_has_no_host_round_trip(monkeypatch):
    """VERDICT r2 item 5: sample() must be one compiled program — no host
    np.unique, no host numpy RNG per batch. Guard by making both explode."""
    ei = generate_pareto_graph(300, 6.0, seed=7)
    topo = CSRTopo(edge_index=ei)
    samplers = [
        SAINTNodeSampler(topo, budget=32, seed=0),
        SAINTEdgeSampler(topo, budget=16, seed=1),
        SAINTRandomWalkSampler(topo, roots=4, walk_length=3, seed=2),
    ]
    # warm the jit caches first (tracing may legitimately touch numpy)
    for s in samplers:
        s.sample()

    def boom(*a, **k):
        raise AssertionError("host round-trip inside sample()")

    monkeypatch.setattr(np, "unique", boom)
    monkeypatch.setattr(np.random, "default_rng", boom)
    for s in samplers:
        sub = s.sample()
        assert int(sub.num_nodes) > 0


def test_device_node_draw_matches_host_distribution():
    """Differential oracle for the devicified degree-proportional draw:
    empirical node frequencies from the device path (uniform edge position →
    searchsorted on the degree CDF) must match the host
    rng.choice(p=deg/deg.sum()) law."""
    from quiver_tpu.sampling.saint import _degree_proportional_nodes

    ei = generate_pareto_graph(60, 4.0, seed=8)
    topo = CSRTopo(edge_index=ei)
    dev = topo.to_device()
    n = topo.node_count
    deg = topo.degree.astype(np.float64)
    expect = deg / deg.sum()

    counts = np.zeros(n)
    draws = 0
    for i in range(200):
        # count raw draws, pre-dedup: reconstruct from the edge positions law
        key = jax.random.PRNGKey(i)
        nodes, num = _degree_proportional_nodes(dev, key, 64)
        ids = np.asarray(nodes)[: int(num)]
        counts[ids] += 1
        draws += 1
    # every degree>0 node with P(appearing in 64 draws) ~ 1 should show up;
    # zero-degree nodes must NEVER be drawn (P=0 under both laws)
    assert counts[deg == 0].sum() == 0
    # appearance frequency must rank-correlate with degree
    seen_rate = counts / draws
    hi = seen_rate[deg > np.median(deg)].mean()
    lo = seen_rate[(deg > 0) & (deg <= np.median(deg))].mean()
    assert hi > lo


@pytest.mark.slow  # 15s end-to-end training witness
def test_saint_training_beats_feature_bayes():
    """End-to-end acceptance (the SAINT analogue of
    test_datasets.test_acceptance_sage_beats_feature_bayes): SAINT-subgraph
    training + layer-wise inference must recover the planted structure."""
    from examples.train_saint import main

    acc, ds = main([
        "--dataset", "planted:4000:6",
        "--steps", "150",
        "--budget", "512",
        "--norm-iters", "15",
    ])
    bayes = ds.meta["feature_bayes_acc"]
    assert acc >= 0.85, f"SAINT test acc {acc} below acceptance bar"
    assert acc >= bayes + 0.15, f"acc {acc} does not clear Bayes {bayes}"


def test_estimate_saint_norm():
    ei = generate_pareto_graph(200, 6.0, seed=6)
    topo = CSRTopo(edge_index=ei)
    s = SAINTNodeSampler(topo, budget=50, seed=3)
    norm, counts = estimate_saint_norm(s, num_iters=20)
    seen = counts > 0
    assert seen.any()
    assert (norm[~seen] == 0).all()
    # mean-1 scaling over appearing nodes
    np.testing.assert_allclose(norm[seen].mean(), 1.0, rtol=1e-5)
    # high-degree nodes appear more often => smaller norm on average
    deg = topo.degree
    hi, lo = norm[seen & (deg > np.median(deg))], norm[seen & (deg <= np.median(deg))]
    if len(hi) and len(lo):
        assert hi.mean() < lo.mean()
