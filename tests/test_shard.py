"""Sharded tensor/feature tests on the 8-device virtual mesh.

Oracle: gather-vs-dense differential, exactly like the reference's
multi-GPU ShardTensor tests (test_shard_tensor.py:70-71) but on a simulated
mesh the reference never had (SURVEY §4 closing note)."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
import pytest

from quiver_tpu import CSRTopo
from quiver_tpu.feature.shard import ShardedFeature, ShardedTensor
from quiver_tpu.parallel.mesh import MeshTopo, make_mesh, can_device_access_peer
from quiver_tpu.utils.graphgen import generate_pareto_graph


def _mesh(data=4, feature=2):
    return make_mesh(data=data, feature=feature)


def test_sharded_tensor_matches_dense():
    mesh = _mesh()
    t = np.random.default_rng(0).normal(size=(1000, 32)).astype(np.float32)
    st = ShardedTensor(mesh).from_cpu_tensor(t)
    assert st.rows_per_shard == 500
    ids = np.random.default_rng(1).integers(0, 1000, 64)
    out = np.asarray(st[jnp.asarray(ids)])
    assert np.allclose(out, t[ids])


def test_sharded_tensor_data_sharded_ids():
    mesh = _mesh()
    t = np.random.default_rng(0).normal(size=(640, 16)).astype(np.float32)
    st = ShardedTensor(mesh).from_cpu_tensor(t)
    ids = np.random.default_rng(2).integers(0, 640, 128)
    ids_sharded = jax.device_put(
        jnp.asarray(ids), NamedSharding(mesh, P("data"))
    )
    out = np.asarray(st[ids_sharded])
    assert np.allclose(out, t[ids])


def test_sharded_tensor_uneven_rows():
    mesh = _mesh(data=2, feature=4)
    t = np.random.default_rng(3).normal(size=(37, 8)).astype(np.float32)
    st = ShardedTensor(mesh).from_cpu_tensor(t)
    ids = np.arange(37)
    out = np.asarray(st[jnp.asarray(ids)])
    assert np.allclose(out, t)


def test_sharded_feature_hot_only():
    mesh = _mesh()
    t = np.random.default_rng(4).normal(size=(500, 16)).astype(np.float32)
    feat = ShardedFeature(mesh, device_cache_size="1G").from_cpu_tensor(t)
    assert feat.hot_rows == 500 and feat.cold is None
    ids = np.random.default_rng(5).integers(0, 500, 64)
    out = np.asarray(feat[jnp.asarray(ids)])
    assert np.allclose(out, t[ids])


def test_sharded_feature_mixed_tiers():
    mesh = _mesh()
    t = np.random.default_rng(6).normal(size=(400, 8)).astype(np.float32)
    row_bytes = 8 * 4
    # per-device budget of 30 rows x 2 shards = 60 hot rows
    feat = ShardedFeature(mesh, device_cache_size=30 * row_bytes).from_cpu_tensor(t)
    assert feat.hot_rows == 60
    ids = np.random.default_rng(7).integers(0, 400, 100)
    out = np.asarray(feat[jnp.asarray(ids)])
    assert np.allclose(out, t[ids])


def test_sharded_feature_int8_quantized():
    """int8 over the mesh: psum'd int8 gather + on-device dequant must land
    within the per-row quantization bound; budget charges the replicated
    scale array first."""
    mesh = _mesh()
    n, f = 400, 8
    t = np.random.default_rng(8).normal(size=(n, f)).astype(np.float32)
    budget = 4 * n + 30 * f  # scale bytes + 30 int8 rows per device
    feat = ShardedFeature(
        mesh, device_cache_size=budget, dtype="int8"
    ).from_cpu_tensor(t)
    assert feat.hot_rows == 60  # 30 rows x 2 feature shards
    assert feat.cold is not None
    ids = np.concatenate(
        [np.random.default_rng(9).integers(0, n, 80), [-1, -1]]
    )
    out = np.asarray(feat[jnp.asarray(ids)])
    assert out.dtype == np.float32
    bound = (np.abs(t).max(axis=1) / 254.0 + 1e-7)[ids[:80]][:, None]
    assert np.all(np.abs(out[:80] - t[ids[:80]]) <= bound)
    assert np.all(out[80:] == 0)


def test_sharded_feature_bf16():
    mesh = _mesh()
    t = np.random.default_rng(10).normal(size=(300, 8)).astype(np.float32)
    feat = ShardedFeature(
        mesh, device_cache_size="1G", dtype="bf16"
    ).from_cpu_tensor(t)
    ids = np.random.default_rng(11).integers(0, 300, 64)
    out = np.asarray(feat[jnp.asarray(ids)], dtype=np.float32)
    np.testing.assert_allclose(out, t[ids], rtol=1e-2, atol=1e-2)


def test_sharded_feature_reorder_and_invalid():
    ei = generate_pareto_graph(300, 6.0, seed=8)
    topo = CSRTopo(edge_index=ei)
    mesh = _mesh()
    t = np.random.default_rng(8).normal(size=(topo.node_count, 8)).astype(np.float32)
    feat = ShardedFeature(mesh, device_cache_size=20 * 32, csr_topo=topo).from_cpu_tensor(t)
    ids = np.array([5, -1, 17, 200])
    out = np.asarray(feat[jnp.asarray(ids)])
    assert np.allclose(out[0], t[5]) and np.allclose(out[2], t[17]) and np.allclose(out[3], t[200])
    assert np.all(out[1] == 0)


def test_mesh_topo_cliques():
    topo = MeshTopo()
    assert sum(len(c) for c in topo.cliques) == len(jax.devices())
    # virtual CPU devices share slice 0 -> one clique
    assert len(topo.cliques) == 1
    assert can_device_access_peer(0, 7)
    assert "Clique 0" in topo.info


def test_sharded_tensor_routed_standalone_matches_psum_and_dense():
    """gather(routed=True) — ids sharded over every axis, owner-routed via
    all_to_all — must equal the psum gather and the dense oracle, across
    odd (padded) lengths."""
    import numpy as np
    import jax.numpy as jnp

    from quiver_tpu.feature.shard import ShardedTensor
    from quiver_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(data=2, feature=4)
    rng = np.random.default_rng(3)
    table = rng.normal(size=(777, 12)).astype(np.float32)
    st = ShardedTensor(mesh, kernel="xla").from_cpu_tensor(table)
    for n in (8, 301, 777):
        ids = rng.integers(0, 777, n).astype(np.int32)
        a = np.asarray(st.gather(jnp.asarray(ids)))
        b = np.asarray(st.gather(jnp.asarray(ids), routed=True))
        assert np.array_equal(a, table[ids])
        assert np.array_equal(b, table[ids])


def test_sharded_feature_routed_matches_psum():
    """ShardedFeature.gather(routed=True) must equal the psum gather and
    the dense oracle, including through feature_order translation."""
    import numpy as np
    import jax.numpy as jnp

    from quiver_tpu import CSRTopo
    from quiver_tpu.feature.shard import ShardedFeature
    from quiver_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(5)
    ei = np.stack([rng.integers(0, 400, 3000), rng.integers(0, 400, 3000)])
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    feat = rng.normal(size=(n, 8)).astype(np.float32)
    mesh = make_mesh(data=2, feature=4)
    store = ShardedFeature(mesh, device_cache_size="1G",
                           csr_topo=topo).from_cpu_tensor(feat)
    ids = rng.integers(0, n, 96).astype(np.int32)
    a = np.asarray(store[jnp.asarray(ids)])
    b = np.asarray(store.gather(jnp.asarray(ids), routed=True))
    assert np.array_equal(a, feat[ids])
    assert np.array_equal(b, feat[ids])


def test_sharded_feature_int8_routed_dequant():
    """int8 quantized rows through the routed gather must dequantize the
    same as through the psum gather (scale indexing uses original ids)."""
    import numpy as np
    import jax.numpy as jnp

    from quiver_tpu import CSRTopo
    from quiver_tpu.feature.shard import ShardedFeature
    from quiver_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(8)
    ei = np.stack([rng.integers(0, 300, 2000), rng.integers(0, 300, 2000)])
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    feat = rng.normal(size=(n, 16)).astype(np.float32)
    mesh = make_mesh(data=2, feature=4)
    store = ShardedFeature(mesh, device_cache_size="1G", csr_topo=topo,
                           dtype="int8").from_cpu_tensor(feat)
    ids = rng.integers(0, n, 64).astype(np.int32)
    a = np.asarray(store[jnp.asarray(ids)])
    b = np.asarray(store.gather(jnp.asarray(ids), routed=True))
    assert np.array_equal(a, b)
    # dequant error bounded by absmax/254 per row
    err = np.abs(a - feat[ids]).max(axis=1)
    bound = np.abs(feat[ids]).max(axis=1) / 254 + 1e-7
    assert np.all(err <= bound)
