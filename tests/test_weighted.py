"""Weighted neighbor sampling tests.

The reference plumbed inverse-CDF weighted sampling (weight_sample,
cuda_random.cu.hpp:143-186) but left it unreachable (weighted ctor commented
out, quiver.cu.hpp:240-272). Here it is a real feature; these tests cover:
validity (samples come from the adjacency), the copy-all branch, empirical
frequency against the weight distribution, zero-weight-row uniform fallback,
and end-to-end GraphSageSampler(weighted=True).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.ops.sample import sample_layer


def _star_graph(deg, weights):
    """Node 0 has `deg` neighbors 1..deg with the given weights."""
    row = np.zeros(deg, dtype=np.int64)
    col = np.arange(1, deg + 1, dtype=np.int64)
    ei = np.stack([row, col])
    return CSRTopo(edge_index=ei, edge_weight=weights)


def test_prefix_weights_computed():
    w = np.array([1.0, 2.0, 3.0, 4.0])
    topo = _star_graph(4, w)
    assert topo.edge_weight is not None
    np.testing.assert_allclose(topo.cum_weights, np.cumsum(w), rtol=1e-6)


def test_prefix_weights_zero_total_uniform_fallback():
    # two rows: row 0 all-zero weights, row 1 normal
    ei = np.array([[0, 0, 1, 1], [2, 3, 2, 3]])
    topo = CSRTopo(edge_index=ei, edge_weight=np.array([0.0, 0.0, 1.0, 3.0]))
    # zero-total row gets uniform prefix 1..deg
    np.testing.assert_allclose(topo.cum_weights[:2], [1.0, 2.0])
    np.testing.assert_allclose(topo.cum_weights[2:], [1.0, 4.0])


def test_negative_weights_rejected():
    ei = np.array([[0], [1]])
    with pytest.raises(ValueError, match="non-negative"):
        CSRTopo(edge_index=ei, edge_weight=np.array([-1.0]))


def test_weight_count_mismatch_rejected():
    ei = np.array([[0, 0], [1, 2]])
    with pytest.raises(ValueError, match="entries"):
        CSRTopo(edge_index=ei, edge_weight=np.array([1.0]))


def test_weighted_validity_and_copy_all():
    rng = np.random.default_rng(0)
    n = 64
    deg = 12
    row = np.repeat(np.arange(n), deg)
    col = rng.integers(0, n, n * deg)
    w = rng.random(n * deg).astype(np.float32) + 0.01
    topo = CSRTopo(edge_index=np.stack([row, col]), edge_weight=w)
    dev = topo.to_device(with_weights=True)

    # k < deg: every sample must be a member of the row's adjacency
    k = 5
    seeds = jnp.asarray(np.arange(32, dtype=np.int32))
    nbr, counts = sample_layer(dev, seeds, jnp.int32(32), k,
                               jax.random.PRNGKey(0), weighted=True)
    nbr, counts = np.asarray(nbr), np.asarray(counts)
    adj = {s: set(col[row == s]) for s in range(32)}
    for r in range(32):
        assert counts[r] == k
        for c in range(k):
            assert nbr[r, c] in adj[r]

    # k >= deg: copy-all in CSR order
    nbr2, counts2 = sample_layer(dev, seeds, jnp.int32(32), deg + 3,
                                 jax.random.PRNGKey(1), weighted=True)
    nbr2 = np.asarray(nbr2)
    for r in range(32):
        np.testing.assert_array_equal(
            nbr2[r, :deg], topo.indices[topo.indptr[r]:topo.indptr[r + 1]]
        )
        assert (nbr2[r, deg:] == -1).all()


def test_weighted_distribution():
    """Empirical pick frequency tracks the weights (inverse-CDF property)."""
    w = np.array([1.0, 1.0, 2.0, 4.0, 8.0], dtype=np.float32)
    topo = _star_graph(5, w)
    dev = topo.to_device(with_weights=True)
    seeds = jnp.zeros(256, dtype=jnp.int32)

    counts = np.zeros(6)
    trials = 40
    for t in range(trials):
        nbr, _ = sample_layer(dev, seeds, jnp.int32(256), 2,
                              jax.random.PRNGKey(t), weighted=True)
        ids, c = np.unique(np.asarray(nbr), return_counts=True)
        for i, cc in zip(ids, c):
            counts[i] += cc
    total = counts[1:].sum()
    freq = counts[1:] / total
    expect = w / w.sum()
    # 256*2*40 = 20480 draws; 3-sigma multinomial tolerance
    tol = 3 * np.sqrt(expect * (1 - expect) / total)
    np.testing.assert_allclose(freq, expect, atol=float(tol.max()))


def test_weighted_zero_row_uniform():
    w = np.zeros(4, dtype=np.float32)
    topo = _star_graph(4, w)
    dev = topo.to_device(with_weights=True)
    seeds = jnp.zeros(128, dtype=jnp.int32)
    nbr, _ = sample_layer(dev, seeds, jnp.int32(128), 2,
                          jax.random.PRNGKey(0), weighted=True)
    ids, c = np.unique(np.asarray(nbr), return_counts=True)
    assert set(ids).issubset({1, 2, 3, 4})
    # all four neighbors appear under the uniform fallback
    assert len(ids) == 4


def test_sampler_weighted_end_to_end():
    rng = np.random.default_rng(1)
    n = 200
    deg = 8
    row = np.repeat(np.arange(n), deg)
    col = rng.integers(0, n, n * deg)
    w = rng.random(n * deg).astype(np.float32)
    topo = CSRTopo(edge_index=np.stack([row, col]), edge_weight=w)
    sampler = GraphSageSampler(topo, [4, 3], weighted=True, seed=0)
    out = sampler.sample(np.arange(16))
    assert np.asarray(out.n_id)[:16].tolist() == list(range(16))
    # structure identical to unweighted: adjs deepest first, valid edges point
    # into the frontier
    n_id = np.asarray(out.n_id)
    for adj in out.adjs:
        src = np.asarray(adj.edge_index[0])
        valid = src >= 0
        assert (src[valid] < adj.size[0]).all()
    assert int(out.n_count) > 16


def test_sampler_weighted_requires_weights():
    ei = np.array([[0, 1], [1, 0]])
    topo = CSRTopo(edge_index=ei)
    with pytest.raises(ValueError, match="weighted"):
        GraphSageSampler(topo, [2], weighted=True)
