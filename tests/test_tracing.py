"""grafttrace: causal span tracing, the flight recorder, and the live
telemetry endpoint (quiver_tpu/obs/{tracing,recorder,endpoint}.py).

Fast lane: tracer id/ring/disabled-path semantics and the Chrome
trace-event export (no jax); flight-recorder ring + atomic bundle
publish, the kill-mid-dump and torn-bundle drills (no jax); the
endpoint's three routes over a plain registry; the serving path's
six-stage request traces + the fleet failover single-trace-id contract;
the disabled-tracing bitwise differential over a shared AOT cache; and
the trainer's preempt/resume span stitching + nonfinite-guard postmortem
bundle on the 8-virtual-device mesh.
"""

import json
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from quiver_tpu import (
    CSRTopo,
    FaultPlan,
    FlightRecorder,
    InferenceServer,
    Preemption,
    ServingFleet,
    TelemetryEndpoint,
    Tracer,
    TransientFault,
)
from quiver_tpu.obs import MetricsRegistry
from quiver_tpu.obs.recorder import TornBundle, list_bundles, verify_bundle
from quiver_tpu.obs.registry import GUARD_SKIPPED
from quiver_tpu.obs.tracing import to_chrome_trace, write_chrome_trace
from quiver_tpu.resilience.elastic import DegradedFeature
from test_serving import FakeClock, _graph, _stack

SERVE_STAGES = ("queue_wait", "pad", "sample", "gather", "forward",
                "readback")


# -- tracer core (no jax) ----------------------------------------------------


def test_tracer_ids_nesting_and_ring():
    tr = Tracer(max_spans=4)
    assert tr.trace() == "t1" and tr.trace() == "t2"
    # explicit names are deterministic (preempt/resume stitching)
    assert tr.trace("train.epoch.3") == "train.epoch.3"
    with tr.span("outer", trace="t1", subsystem="test", k=1) as outer:
        outer.set("extra", 2)
        with tr.span("inner", trace="t1", parent=outer):
            pass
    inner_s, outer_s = tr.spans()  # inner exits (records) first
    assert inner_s.name == "inner" and outer_s.name == "outer"
    assert inner_s.parent_id == outer_s.span_id
    assert outer_s.parent_id == "" and outer_s.attrs["extra"] == 2
    assert outer_s.dur >= inner_s.dur >= 0.0
    assert tr.subsystems() == {"test"}
    for i in range(10):  # bounded ring: oldest evicted
        tr.event(f"e{i}", trace="t2")
    assert len(tr.spans()) == 4
    assert [s.name for s in tr.spans()] == ["e6", "e7", "e8", "e9"]
    assert tr.spans_total == 12


def test_tracer_span_records_on_raise():
    tr = Tracer()
    with pytest.raises(ValueError):
        with tr.span("failing", subsystem="test"):
            raise ValueError("boom")
    (s,) = tr.spans()
    assert s.name == "failing" and s.attrs["error"] == "ValueError"


def test_tracer_disabled_is_structurally_noop():
    tr = Tracer(enabled=False)
    assert tr.trace() == "" and tr.trace("named") == ""
    # one shared null scope/span: nothing allocated per call
    assert tr.span("a") is tr.span("b")
    with tr.span("a") as s:
        s.set("k", 1)  # accepted, dropped
    assert s.attrs == {}
    assert tr.record("a", 0.0, 1.0) is None
    assert tr.observe("a", 1.0) is None
    assert tr.event("a") is None
    assert tr.spans() == [] and tr.spans_total == 0
    assert tr.to_chrome() == {"traceEvents": [], "displayTimeUnit": "ms"}


def test_chrome_trace_export_parses(tmp_path):
    tr = Tracer()
    tid = tr.trace()
    root = tr.record("req", 0.5, 2.0, trace=tid, subsystem="serve",
                     node=np.int64(7))
    tr.record("stage", 1.0, 0.25, trace=tid, parent=root,
              subsystem="serve")
    path = tmp_path / "trace.json"
    assert write_chrome_trace(tr.spans(), path) == 2
    doc = json.loads(path.read_text())  # what Perfetto will parse
    assert doc["displayTimeUnit"] == "ms"
    ev_root, ev_child = doc["traceEvents"]
    for ev in (ev_root, ev_child):
        assert ev["ph"] == "X" and ev["pid"] == 1 and ev["tid"] >= 1
        assert ev["args"]["trace_id"] == tid
    assert ev_root["ts"] == 0.5e6 and ev_root["dur"] == 2.0e6
    assert ev_root["args"]["node"] == 7  # numpy scalars jsonified
    assert ev_child["args"]["parent_id"] == ev_root["args"]["span_id"]
    assert ev_child["cat"] == "serve"


# -- flight recorder (no jax) ------------------------------------------------


def test_recorder_ring_bundle_and_retention(tmp_path):
    reg = MetricsRegistry()
    reg.counter("demo.count", doc="a demo counter")
    reg.set("demo.count", np.int32(5))
    tr = Tracer()
    tr.event("decision", subsystem="control")
    rec = FlightRecorder(tmp_path / "pm", capacity=3, keep=2, tracer=tr)
    rec.attach_registry(reg)
    rec.attach_registry(reg)  # idempotent
    for i in range(5):
        rec.note("ctrl.repin", row=i)
    assert [e["seq"] for e in rec.events()] == [3, 4, 5]  # bounded ring
    path = rec.trigger("breaker_open", stage="gather", fallback="zeros")
    manifest = verify_bundle(path)
    assert manifest["reason"] == "breaker_open"
    assert manifest["stage"] == "gather"
    assert manifest["attrs"] == {"fallback": "zeros"}
    assert manifest["spans"] == 1
    with open(f"{path}/spans.json") as fh:
        assert len(json.load(fh)["traceEvents"]) == 1
    with open(f"{path}/metrics.json") as fh:
        snaps = {s["name"]: s for s in json.load(fh)}
    assert snaps["demo.count"]["value"] == 5
    with open(f"{path}/events.json") as fh:
        assert [e["kind"] for e in json.load(fh)] == ["ctrl.repin"] * 3
    # retention: only the newest `keep` committed bundles survive
    rec.dump()
    rec.dump()
    kept = rec.bundles()
    assert len(kept) == 2
    assert [m["reason"] for _p, m in kept] == ["manual", "manual"]
    assert rec.bundles_total == 3


def test_recorder_survives_kill_mid_dump(tmp_path):
    """A crash before COMMIT leaves only an invisible temp dir; a torn
    published dir is quarantined — the previous bundle stays intact
    either way."""
    rec = FlightRecorder(tmp_path / "pm", tracer=Tracer())
    good = rec.trigger("nonfinite_guard", stage="train")
    with pytest.raises(RuntimeError, match="injected recorder crash"):
        rec.trigger("crash_drill", stage="train", inject_failure="crash")
    assert [p for p, _m in rec.bundles()] == [good]
    torn = rec.trigger("torn_drill", stage="train", inject_failure="torn")
    with pytest.raises(TornBundle, match="no COMMIT marker"):
        verify_bundle(torn)
    assert [p for p, _m in rec.bundles()] == [good]  # quarantined away
    quarantined = [p.name for p in (tmp_path / "pm").iterdir()
                   if p.name.startswith("quarantine-")]
    assert len(quarantined) == 1 and "torn_drill" in quarantined[0]
    verify_bundle(good)  # previous bundle still byte-perfect
    # a new recorder over the same directory continues the seq past both
    rec2 = FlightRecorder(tmp_path / "pm", tracer=Tracer())
    again = rec2.trigger("manual")
    assert verify_bundle(again)["seq"] > verify_bundle(good)["seq"]


def test_recorder_detects_payload_corruption(tmp_path):
    rec = FlightRecorder(tmp_path / "pm")
    path = rec.trigger("manual")
    epath = f"{path}/events.json"
    with open(epath, "r+b") as fh:
        b = fh.read(1)
        fh.seek(0)
        fh.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(TornBundle, match="checksum mismatch"):
        verify_bundle(path)
    assert list_bundles(rec.directory, quarantine=False) == []


# -- telemetry endpoint (no jax) ---------------------------------------------


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.headers.get("Content-Type"), resp.read()


def test_endpoint_routes():
    reg = MetricsRegistry()
    reg.counter("demo.count", doc="a demo counter")
    reg.set("demo.count", np.int32(3))
    tr = Tracer()
    tr.event("serve.enqueue", trace=tr.trace(), subsystem="serve")
    with TelemetryEndpoint(metrics=reg, tracer=tr,
                           health=lambda: {"depth": 0}) as ep:
        assert ep.running and ep.port > 0
        code, ctype, body = _get(f"{ep.url}/metrics")
        assert code == 200 and ctype.startswith("text/plain")
        assert "quiver_demo_count" in body.decode()
        code, ctype, body = _get(f"{ep.url}/traces")
        assert code == 200 and ctype == "application/json"
        doc = json.loads(body)
        assert len(doc["traceEvents"]) == 1
        code, _ctype, body = _get(f"{ep.url}/healthz")
        assert code == 200
        assert json.loads(body) == {"status": "ok", "depth": 0}
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{ep.url}/nope")
        assert ei.value.code == 404
    assert not ep.running
    ep.stop()  # idempotent


def test_breaker_open_dumps_bundle(tmp_path):
    """The cold-tier outage fault class: the breaker-open transition
    triggers a bundle naming the gather stage."""
    rec = FlightRecorder(tmp_path / "pm")
    rng = np.random.default_rng(0)
    rows = rng.normal(size=(16, 4)).astype(np.float32)
    plan = FaultPlan(feature_faults={0: 5})
    degraded = DegradedFeature(plan.wrap_feature(_ArrayStore(rows)),
                               failures=3, probe_every=2,
                               fallback="zeros", recorder=rec)
    ids = np.array([1, 2])
    for _ in range(2):  # closed: failures propagate
        with pytest.raises(TransientFault):
            degraded[ids]
    out = degraded[ids]  # third failure opens: fallback rows, no raise
    assert degraded.breaker.state == "open"
    assert np.array_equal(out, np.zeros_like(out))
    (bundle,) = rec.bundles()
    assert bundle[1]["reason"] == "breaker_open"
    assert bundle[1]["stage"] == "gather"


class _ArrayStore:
    """Minimal ids->rows store for the breaker drill."""

    def __init__(self, rows):
        self.rows = rows
        self.shape = rows.shape
        self.dtype = rows.dtype

    def __getitem__(self, ids):
        return self.rows[np.asarray(ids)]


def test_commit_abort_dumps_bundle(tmp_path):
    """The streaming fault class: an aborted commit triggers a bundle
    naming the commit stage (and carrying the abort cause)."""
    from quiver_tpu import CommitAborted, DeltaBatch, StreamingGraph

    rng = np.random.default_rng(5)
    topo = CSRTopo(
        edge_index=rng.integers(0, 64, size=(2, 256)).astype(np.int64)
    )
    rec = FlightRecorder(tmp_path / "pm")
    sg = StreamingGraph(topo, recorder=rec)
    assert sg.ingest(DeltaBatch(
        edge_inserts=rng.integers(0, 64, size=(2, 8))
    ))
    with pytest.raises(CommitAborted):
        sg.commit(inject_failure="merge")
    (bundle,) = rec.bundles()
    assert bundle[1]["reason"] == "commit_abort"
    assert bundle[1]["stage"] == "commit"
    assert bundle[1]["attrs"]["cause"]
    verify_bundle(bundle[0])


# -- serving traces ----------------------------------------------------------


@pytest.fixture(scope="module")
def traced(tmp_path_factory):
    """One warm traced server + recorder over a shared disk AOT cache
    (the differential test reuses the cache to stay compile-free)."""
    cache_dir = str(tmp_path_factory.mktemp("aot") / "executables")
    topo = _graph()
    _x, feat, sampler, model, params = _stack(topo)
    clock = FakeClock()
    tracer = Tracer()
    rec = FlightRecorder(str(tmp_path_factory.mktemp("pm")), tracer=tracer)
    server = InferenceServer(sampler, model, params, feat, max_batch=4,
                             clock=clock, seed=3, aot_cache=cache_dir,
                             tracer=tracer, recorder=rec)
    server.warm_from_cache()
    return {"server": server, "clock": clock, "tracer": tracer,
            "recorder": rec, "cache_dir": cache_dir,
            "stack": (sampler, model, params, feat)}


def test_serve_six_stage_request_traces(traced):
    server, tracer = traced["server"], traced["tracer"]
    tracer.clear()
    reqs = server.serve([3, 11, 19, 42])
    by_trace = {}
    for s in tracer.spans():
        by_trace.setdefault(s.trace_id, []).append(s)
    for r in reqs:
        assert r.trace_id and r.trace_id in by_trace
        spans = by_trace[r.trace_id]
        (root,) = [s for s in spans if s.name == "serve.request"]
        assert root.parent_id == "" and root.attrs["node"] == r.node
        children = {s.name: s for s in spans if s.parent_id == root.span_id}
        for stage in SERVE_STAGES:
            assert f"serve.{stage}" in children, \
                f"missing serve.{stage} under {r.trace_id}"
        # the enqueue marker rides the same trace
        assert any(s.name == "serve.enqueue" for s in spans)
    assert tracer.subsystems() == {"serve"}


def test_serve_trace_endpoint_perfetto(traced):
    server, tracer = traced["server"], traced["tracer"]
    tracer.clear()
    server.serve([5, 9])
    with TelemetryEndpoint(metrics=server.metrics, tracer=tracer,
                           health=lambda: {"depth": server.batcher.depth
                                           }) as ep:
        _code, _ct, body = _get(f"{ep.url}/traces")
        doc = json.loads(body)
        assert {e["name"] for e in doc["traceEvents"]} >= {
            "serve.request", "serve.sample", "serve.forward"}
        for ev in doc["traceEvents"]:  # the Perfetto complete-event shape
            assert ev["ph"] == "X"
            assert {"name", "cat", "ts", "dur", "pid", "tid",
                    "args"} <= ev.keys()
        _code, _ct, body = _get(f"{ep.url}/metrics")
        assert "quiver_serve_requests" in body.decode()
        _code, _ct, body = _get(f"{ep.url}/healthz")
        assert json.loads(body)["status"] == "ok"


def test_serve_disabled_tracing_bitwise(traced):
    """The collect_metrics=False discipline applied to tracing: a traced
    server and an untraced server answer every (node, seq) bitwise
    identically (both warm from the shared cache — zero compiles)."""
    sampler, model, params, feat = traced["stack"]

    def replica(**kw):
        return InferenceServer(sampler, model, params, feat, max_batch=4,
                               clock=FakeClock(), seed=3,
                               aot_cache=traced["cache_dir"], **kw)

    plain = replica()
    traced_srv = replica(tracer=Tracer())
    assert plain.warm_from_cache()["compiled"] == 0
    assert traced_srv.warm_from_cache()["compiled"] == 0
    nodes = [3, 11, 19, 42, 7]  # full bucket + forced tail
    out_a = plain.serve(nodes)
    out_b = traced_srv.serve(nodes)
    assert plain.tracer.enabled is False and not plain.tracer.spans()
    assert traced_srv.tracer.spans()  # tracing actually ran on B
    for ra, rb in zip(out_a, out_b):
        assert (ra.node, ra.seq) == (rb.node, rb.seq)
        np.testing.assert_array_equal(
            np.asarray(ra.result).view(np.uint8),
            np.asarray(rb.result).view(np.uint8),
        )


def test_fleet_failover_single_trace_id(traced):
    """A failover request's spans on the rejecting AND the accepting
    replica share one trace id (admission-only: warm=False, no pump —
    zero compiles)."""
    sampler, model, params, feat = traced["stack"]
    tracer = Tracer()
    fleet = ServingFleet(sampler, model, params, feat, replicas=2,
                         aot_cache=None, warm=False, tracer=tracer,
                         max_batch=2, max_queue=2, clock=FakeClock())
    # replica 0 full of gold (rejects gold), replica 1 full of bronze
    # (sheds a bronze to admit gold) — depths tie, so routing tries 0 first
    for srv, pri in ((fleet.servers[0], "gold"),
                     (fleet.servers[1], "bronze")):
        for n in (1, 2):
            srv.submit(n, priority=pri)
    req = fleet.submit(7, priority="gold")
    tid = req.trace_id
    assert tid
    spans = [s for s in tracer.spans() if s.trace_id == tid]
    hops = {s.name: s.attrs["replica"] for s in spans
            if s.name in ("fleet.route", "fleet.failover")}
    assert hops == {"fleet.route": 0, "fleet.failover": 1}
    (enq,) = [s for s in spans if s.name == "serve.enqueue"]
    assert enq.attrs["subsystem"] == "serve"
    assert fleet.recompiles == 0
    assert {s.attrs["subsystem"] for s in spans} == {"fleet", "serve"}


# -- trainer traces ----------------------------------------------------------


def _traced_trainer(tmp_path, plan=None, guard=False):
    import optax

    from quiver_tpu import GraphSageSampler
    from quiver_tpu.feature.shard import ShardedFeature
    from quiver_tpu.models.sage import GraphSAGE
    from quiver_tpu.parallel.mesh import make_mesh
    from quiver_tpu.parallel.trainer import DistributedTrainer

    rng = np.random.default_rng(0)
    n = 96
    topo = CSRTopo(
        edge_index=rng.integers(0, n, size=(2, 800)).astype(np.int64)
    )
    feat = rng.normal(size=(n, 8)).astype(np.float32)
    mesh = make_mesh(data=2, feature=4)
    store = ShardedFeature(
        mesh, device_cache_size=n * 8, csr_topo=topo
    ).from_cpu_tensor(feat)
    sampler = GraphSageSampler(topo, [3, 2], seed=0, seed_capacity=8)
    model = GraphSAGE(hidden=8, num_classes=4, num_layers=2)
    tracer = Tracer()
    rec = FlightRecorder(tmp_path / "pm", tracer=tracer)
    trainer = DistributedTrainer(
        mesh, sampler, store, model, optax.sgd(1e-2), local_batch=8,
        seed_sharding="all", nonfinite_guard=guard, fault_plan=plan,
        checkpoint_dir=tmp_path / "ck", checkpoint_every=3,
        tracer=tracer, recorder=rec,
    )
    params, opt = trainer.init(jax.random.PRNGKey(0))
    labels = jnp.asarray(rng.integers(0, 4, n).astype(np.int32))
    return trainer, params, opt, labels, tracer, rec


@pytest.mark.slow
def test_trainer_preempt_resume_stitch_and_guard_bundle(tmp_path):
    """One epoch under chaos: the NaN-poisoned step trips the guard and
    dumps a verified bundle naming the train stage; the preemption kills
    chunk [3, 6); resume re-enters the SAME deterministic epoch trace, so
    the chunk spans stitch across the restart under one trace id."""
    plan = FaultPlan(nan_feature_steps=(1,), nan_rows=4, preempt_at_step=4)
    trainer, p0, o0, labels, tracer, rec = _traced_trainer(
        tmp_path, plan=plan, guard=True
    )
    seed_mat = trainer.pack_epoch(np.tile(np.arange(96), 6), seed=0)
    assert seed_mat.shape[0] == 9
    key = jax.random.PRNGKey(7)
    with pytest.raises(Preemption, match="step 4"):
        trainer.epoch_scan(p0, o0, seed_mat, labels, key)
    pr, orr, key_r, step, epoch = trainer.resume(p0, o0)
    assert step == 3 and epoch == 0
    trainer.epoch_scan(pr, orr, seed_mat, labels, key_r,
                       epoch=epoch, start_step=step)
    spans = tracer.spans()
    # deterministic epoch trace: both halves carry train.epoch.0
    chunks = [s for s in spans
              if s.name == "train.chunk" and s.trace_id == "train.epoch.0"]
    starts = sorted(s.attrs["start_step"] for s in chunks)
    assert 0 in starts, "pre-preempt chunk missing from the epoch trace"
    assert {3, 6} <= set(starts), "resumed chunks did not stitch"
    (pre,) = [s for s in spans if s.name == "train.preempt"]
    assert pre.trace_id == "train.epoch.0" and pre.attrs["step"] == 4
    # checkpoint saves ride the same trace (subsystem resilience)
    trainer.checkpointer.wait_until_finished()
    saves = [s for s in tracer.spans() if s.name == "ckpt.save"]
    assert saves and all(s.trace_id == "train.epoch.0" for s in saves)
    assert {"trainer", "resilience"} <= tracer.subsystems()
    # the guard trip dumped an integrity-verified bundle naming train
    reasons = {m["reason"]: m for _p, m in rec.bundles()}
    assert "nonfinite_guard" in reasons
    assert reasons["nonfinite_guard"]["stage"] == "train"
    assert reasons["nonfinite_guard"]["attrs"]["skipped_total"] >= 1
    # registry holds the LATEST scan's vector: the resumed run re-enters at
    # step 3 (past the NaN at step 1), so its 6 steps are all clean
    resumed = np.asarray(trainer.metrics.value(GUARD_SKIPPED))
    assert resumed.shape == (6,) and int(resumed.sum()) == 0
    # the preemption landed in the black-box ring
    assert any(e["kind"] == "preemption" for e in rec.events())
    # health + telemetry ride the trainer too
    health = trainer.health()
    assert health["workers"] == trainer.workers
    assert health["guard_trips"] >= 1
    ep = trainer.serve_telemetry()
    try:
        _code, _ct, body = _get(f"{ep.url}/healthz")
        assert json.loads(body)["status"] == "ok"
    finally:
        ep.stop()
    trainer.checkpointer.close()


@pytest.mark.slow
def test_trainer_disabled_tracing_bitwise(tmp_path):
    """Tracing off vs on: identical losses bit-for-bit (the tracer rides
    outside the compiled epoch program)."""
    import optax

    from quiver_tpu import Feature, GraphSageSampler
    from quiver_tpu.models.sage import GraphSAGE
    from quiver_tpu.parallel.mesh import make_mesh
    from quiver_tpu.parallel.trainer import DistributedTrainer

    rng = np.random.default_rng(1)
    n = 96
    topo = CSRTopo(
        edge_index=rng.integers(0, n, size=(2, 800)).astype(np.int64)
    )
    feat = rng.normal(size=(n, 8)).astype(np.float32)
    labels = jnp.asarray(rng.integers(0, 4, n).astype(np.int32))
    losses = []
    for tracer in (None, Tracer()):
        mesh = make_mesh()
        store = Feature(device_cache_size="1G").from_cpu_tensor(feat)
        sampler = GraphSageSampler(topo, [3, 2], seed=0, seed_capacity=8)
        trainer = DistributedTrainer(
            mesh, sampler, store,
            GraphSAGE(hidden=8, num_classes=4, num_layers=2),
            optax.sgd(1e-2), local_batch=8, tracer=tracer,
        )
        params, opt = trainer.init(jax.random.PRNGKey(0))
        seed_mat = trainer.pack_epoch(np.tile(np.arange(96), 6), seed=0)
        _p, _o, ls = trainer.epoch_scan(params, opt, seed_mat, labels,
                                        jax.random.PRNGKey(7))
        losses.append(np.asarray(ls))
    np.testing.assert_array_equal(
        losses[0].view(np.uint32), losses[1].view(np.uint32)
    )
