"""Chip-window runner harness: graph cache equivalence, scoreboard merge
discipline, and job-table drift checks (round-4 window postmortem).

The contracts under test:

* a ``build_graph`` cache hit is EQUIVALENT to a fresh build (same indptr/
  indices/eid), and a stale pre-eid cache file is regenerated, not loaded;
* ``scoreboard.write_outputs(merge=True)`` never lets a failed re-run
  clobber a prior good row, and labels kept/smoke rows in the table;
* ``mega_session.job_table()`` fails loudly on drift between its ORDER
  list and ``scoreboard.JOBS`` in BOTH directions.
"""

import argparse
import importlib.util
import json
import os

import numpy as np
import pytest

from benchmarks import common, scoreboard

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _args(nodes=5000, deg=8.0, seed=3):
    return argparse.Namespace(
        nodes=nodes, avg_degree=deg, seed=seed, smoke=False,
        backend_retries=0, backend_retry_delay=0.1,
    )


@pytest.fixture()
def cache_dir(tmp_path, monkeypatch):
    # supervised mode: init_backend touches the (conftest-forced CPU)
    # backend directly instead of spawning a probe subprocess that would
    # block on the image's pinned TPU plugin
    monkeypatch.setenv("QUIVER_BENCH_SUPERVISED", "1")
    monkeypatch.setattr(
        common, "_graph_cache_path",
        lambda nodes, avg_degree, seed: str(
            tmp_path / f"pareto_n{nodes}_d{avg_degree:g}_s{seed}.npz"),
    )
    return tmp_path


class TestGraphCache:
    def test_hit_is_equivalent_to_fresh_build(self, cache_dir):
        fresh = common.build_graph(_args())
        files = list(cache_dir.glob("*.npz"))
        assert len(files) == 1
        cached = common.build_graph(_args())
        np.testing.assert_array_equal(fresh.indptr, cached.indptr)
        np.testing.assert_array_equal(fresh.indices, cached.indices)
        assert fresh.eid is not None and cached.eid is not None
        np.testing.assert_array_equal(fresh.eid, cached.eid)

    def test_stale_no_eid_cache_regenerates(self, cache_dir):
        fresh = common.build_graph(_args())
        path = next(cache_dir.glob("*.npz"))
        with open(path, "wb") as fh:
            np.savez(fh, indptr=fresh.indptr, indices=fresh.indices)
        again = common.build_graph(_args())
        assert again.eid is not None
        np.testing.assert_array_equal(fresh.eid, again.eid)
        # and the stale file was replaced with a complete one
        assert "eid" in np.load(path).files

    def test_corrupt_cache_regenerates(self, cache_dir):
        common.build_graph(_args())
        path = next(cache_dir.glob("*.npz"))
        path.write_bytes(b"not an npz")
        topo = common.build_graph(_args())
        assert topo.node_count == 5000


class TestPrngSelection:
    def _restore(self):
        import jax

        jax.config.update("jax_default_prng_impl", "threefry2x32")

    def test_tpu_defaults_to_rbg(self, monkeypatch):
        monkeypatch.delenv("QUIVER_PRNG", raising=False)
        try:
            assert common._select_prng("tpu") == "rbg"
        finally:
            self._restore()

    def test_cpu_defaults_to_none(self, monkeypatch):
        monkeypatch.delenv("QUIVER_PRNG", raising=False)
        assert common._select_prng("cpu") is None

    def test_explicit_threefry_means_default(self, monkeypatch):
        monkeypatch.setenv("QUIVER_PRNG", "threefry")
        assert common._select_prng("tpu") is None

    def test_override_applies_on_cpu(self, monkeypatch):
        monkeypatch.setenv("QUIVER_PRNG", "rbg")
        try:
            assert common._select_prng("cpu") == "rbg"
        finally:
            self._restore()

    def test_typod_force_raises(self, monkeypatch):
        import pytest

        monkeypatch.setenv("QUIVER_PRNG", "rgb")  # the classic transposition
        with pytest.raises(ValueError, match="QUIVER_PRNG"):
            common._select_prng("tpu")


def _job(key, value=1.0, error=None, smoke=False, records=None):
    if records is None:
        records = [] if error else [
            {"metric": "m", "value": value, "unit": "u", "vs_baseline": None,
             "platform": "tpu", **({"smoke": True} if smoke else {})}
        ]
    return {"key": key, "note": "n", "records": records, "error": error,
            "seconds": 1.0, "smoke": smoke}


class TestScoreboardMerge:
    @pytest.fixture(autouse=True)
    def _scratch_trajectory(self, tmp_path, monkeypatch):
        # the default ledger is the repo-root round-over-round history;
        # no test run may ever append fixture rows to it
        monkeypatch.setattr(scoreboard, "TRAJECTORY",
                            str(tmp_path / "BENCH_TRAJECTORY.jsonl"))

    def test_failed_rerun_keeps_prior_good_row(self, tmp_path, capsys):
        scoreboard.write_outputs([_job("sampler-hbm", 5.0)], str(tmp_path),
                                 smoke=False)
        scoreboard.write_outputs([_job("sampler-hbm", error="timeout>1s")],
                                 str(tmp_path), smoke=False, merge=True)
        data = json.loads((tmp_path / "tpu_results.json").read_text())
        jobs = {j["key"]: j for j in data["jobs"]}
        assert jobs["sampler-hbm"]["records"][0]["value"] == 5.0
        assert jobs["sampler-hbm"]["retry_error"] == "timeout>1s"
        md = (tmp_path / "TPU_RESULTS.md").read_text()
        assert "kept: newer retry failed" in md

    def test_trajectory_path_param_overrides_ledger(self, tmp_path, capsys):
        ledger = tmp_path / "elsewhere.jsonl"
        scoreboard.write_outputs([_job("sampler-hbm", 5.0)], str(tmp_path),
                                 smoke=False,
                                 trajectory_path=str(ledger))
        rows = [json.loads(ln) for ln in ledger.read_text().splitlines()]
        assert len(rows) == 1 and rows[0]["source"] == "scoreboard"
        assert not (tmp_path / "BENCH_TRAJECTORY.jsonl").exists()

    def test_good_rerun_replaces_prior(self, tmp_path, capsys):
        scoreboard.write_outputs([_job("sampler-hbm", 5.0)], str(tmp_path),
                                 smoke=False)
        scoreboard.write_outputs([_job("sampler-hbm", 9.0)], str(tmp_path),
                                 smoke=False, merge=True)
        data = json.loads((tmp_path / "tpu_results.json").read_text())
        jobs = {j["key"]: j for j in data["jobs"]}
        assert jobs["sampler-hbm"]["records"][0]["value"] == 9.0
        assert "retry_error" not in jobs["sampler-hbm"]

    def test_smoke_records_labeled_in_table(self, tmp_path, capsys):
        scoreboard.write_outputs([_job("sampler-hbm", 5.0, smoke=True)],
                                 str(tmp_path), smoke=True)
        md = (tmp_path / "TPU_RESULTS.md").read_text()
        assert "(smoke)" in md


def _load_mega_session():
    # the module sets QUIVER_BENCH_SUPERVISED and prepends to sys.path at
    # import time (it is a script, not a library) — keep both out of the
    # rest of the pytest session
    import sys

    env_before = os.environ.get("QUIVER_BENCH_SUPERVISED")
    path_before = list(sys.path)
    try:
        spec = importlib.util.spec_from_file_location(
            "mega_session", os.path.join(REPO, "scripts", "mega_session.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    finally:
        sys.path[:] = path_before
        if env_before is None:
            os.environ.pop("QUIVER_BENCH_SUPERVISED", None)
        else:
            os.environ["QUIVER_BENCH_SUPERVISED"] = env_before
    return mod


class TestBenchInitWatchdog:
    """bench.py's measured-child supervision: a child that never reaches
    backend init is killed fast (grant starvation), while initialized
    children keep the full budget."""

    @pytest.fixture()
    def bench_mod(self, monkeypatch):
        import importlib.util
        import sys

        spec = importlib.util.spec_from_file_location(
            "bench", os.path.join(REPO, "bench.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        monkeypatch.setattr(sys, "argv", ["bench.py"])
        return mod

    def test_starved_child_killed_at_init_timeout(self, bench_mod, monkeypatch):
        monkeypatch.setattr(
            bench_mod, "CHILD", ["-c", "import time; time.sleep(120)"])
        t0 = __import__("time").time()
        rec, err, hung = bench_mod._attempt(
            [], {}, timeout_s=60, label="t", init_timeout=3)
        assert rec is None
        assert "starved" in err
        assert not hung  # starvation is retryable, not a mid-run hang
        assert __import__("time").time() - t0 < 30

    def test_initialized_child_record_harvested(self, bench_mod, monkeypatch):
        src = (
            "import sys, json;"
            "print('backend ok: cpu', file=sys.stderr);"
            "print(json.dumps({'metric': 'sampled-edges/sec/chip',"
            " 'value': 1.0, 'unit': 'SEPS', 'vs_baseline': None}))"
        )
        monkeypatch.setattr(bench_mod, "CHILD", ["-c", src])
        rec, err, hung = bench_mod._attempt(
            [], {}, timeout_s=60, label="t", init_timeout=30)
        assert err is None and not hung
        assert rec["metric"] == "sampled-edges/sec/chip"

    @pytest.mark.slow  # 15s of real watchdog wall-clock by design
    def test_post_init_hang_is_a_timeout(self, bench_mod, monkeypatch):
        src = (
            "import sys, time;"
            "print('backend ok: cpu', file=sys.stderr, flush=True);"
            "time.sleep(120)"
        )
        monkeypatch.setattr(bench_mod, "CHILD", ["-c", src])
        rec, err, hung = bench_mod._attempt(
            [], {}, timeout_s=12, label="t", init_timeout=6)
        assert rec is None
        assert err.startswith("timeout")
        assert hung


class TestJobTableDrift:
    def test_table_covers_scoreboard_jobs(self):
        ms = _load_mega_session()
        table = ms.job_table()
        keys = [k for k, *_ in table]
        assert len(keys) == len(set(keys))
        assert set(k for k, *_ in scoreboard.JOBS) <= set(keys)

    def test_both_drift_directions_raise(self, monkeypatch):
        ms = _load_mega_session()
        with monkeypatch.context() as m:
            m.setattr(ms, "ORDER", ms.ORDER + [("brand-new-job", 100)])
            with pytest.raises(SystemExit, match="missing from scoreboard"):
                ms.job_table()
        with monkeypatch.context() as m:
            m.setattr(scoreboard, "JOBS", scoreboard.JOBS + [
                ("unordered-job", "benchmarks.microbench", [], "note")])
            with pytest.raises(SystemExit, match="missing from ORDER"):
                ms.job_table()
