"""Pallas kernel tests (interpret mode on CPU; compiled on real TPU).

Differential oracles: dense take for the gather kernel, the sample-validity
invariants (membership/counts/distinctness) for the windowed sampler — the
same oracles the XLA paths are held to (SURVEY §4)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from quiver_tpu import CSRTopo
from quiver_tpu.ops.pallas.gather import gather_rows
from quiver_tpu.ops.pallas.sample import sample_layer_windowed
from quiver_tpu.ops.sample import sample_layer, stratified_offsets
from quiver_tpu.utils.graphgen import generate_pareto_graph


def test_gather_rows_matches_dense():
    t = np.random.default_rng(0).normal(size=(300, 128)).astype(np.float32)
    ids = np.random.default_rng(1).integers(0, 300, 77)  # non-multiple of tile
    out = np.asarray(gather_rows(jnp.asarray(t), jnp.asarray(ids, jnp.int32)))
    assert np.allclose(out, t[ids])


def test_gather_rows_narrow_features():
    t = np.random.default_rng(2).normal(size=(100, 32)).astype(np.float32)
    ids = np.arange(100)
    out = np.asarray(gather_rows(jnp.asarray(t), jnp.asarray(ids, jnp.int32), tile=8))
    assert np.allclose(out, t)


def test_stratified_offsets_distinct_and_bounded():
    deg = jnp.array([0, 1, 3, 10, 100, 2000])
    off, mask = stratified_offsets(jax.random.PRNGKey(0), deg, 5)
    off, mask = np.asarray(off), np.asarray(mask)
    for r, d in enumerate([0, 1, 3, 10, 100, 2000]):
        m = mask[r]
        assert m.sum() == min(d, 5)
        sel = off[r][m]
        assert np.all(sel < max(d, 1))
        assert len(set(sel.tolist())) == len(sel)


def test_windowed_sampler_validity():
    ei = generate_pareto_graph(800, 12.0, seed=0)
    topo = CSRTopo(edge_index=ei)
    dev = topo.to_device()
    adj = {}
    indptr, indices = topo.indptr, topo.indices
    S, k = 64, 6
    seeds = np.random.default_rng(0).integers(0, 800, S).astype(np.int32)
    nbr, counts = sample_layer_windowed(
        dev, jnp.asarray(seeds), jnp.int32(S), k, jax.random.PRNGKey(1), window=512
    )
    nbr, counts = np.asarray(nbr), np.asarray(counts)
    for r in range(S):
        s = seeds[r]
        row = set(indices[indptr[s]:indptr[s + 1]].tolist())
        deg = len(indices[indptr[s]:indptr[s + 1]])
        assert counts[r] == min(deg, k)
        got = nbr[r][nbr[r] >= 0]
        assert len(got) == counts[r]
        assert set(got.tolist()) <= row
        if deg > k:
            # distinct positions; values can repeat only if the row has
            # duplicate neighbor entries
            assert len(got) == k


def test_windowed_sampler_take_all_matches_xla():
    # rows with deg <= k must return the full CSR-ordered neighborhood in
    # both implementations
    ei = generate_pareto_graph(400, 3.0, seed=2)
    topo = CSRTopo(edge_index=ei)
    dev = topo.to_device()
    seeds = jnp.asarray(np.arange(50), jnp.int32)
    key = jax.random.PRNGKey(3)
    a, ca = sample_layer(dev, seeds, jnp.int32(50), 8, key)
    b, cb = sample_layer_windowed(dev, seeds, jnp.int32(50), 8, key, window=512)
    a, b = np.asarray(a), np.asarray(b)
    deg = np.asarray(topo.degree)[:50]
    full = deg <= 8
    assert np.array_equal(np.asarray(ca), np.asarray(cb))
    assert np.array_equal(a[full], b[full])


def test_windowed_sampler_small_graph_rejected():
    ei = np.stack([np.zeros(4, np.int64), np.arange(4)])
    topo = CSRTopo(edge_index=ei).to_device()
    with pytest.raises(ValueError, match="window"):
        sample_layer_windowed(
            topo, jnp.zeros(8, jnp.int32), jnp.int32(1), 2, jax.random.PRNGKey(0)
        )


# -- jitted-lowering smoke (the QUIVER_GATHER_KERNEL election contract) -------
#
# The election (feature._hot_gather_fn / resolve_gather_kernel) can route
# EVERY hot-tier gather through the Pallas kernels inside jitted trainer
# and serving programs — where the kernels run under jax.jit tracing, not
# eagerly. These smokes pin that lowering path: sample_layer_windowed once
# indexed a host-numpy indptr with a tracer and broke ONLY under jit,
# which no eager test could see. graftaudit's pallas_* targets keep the
# trace/lower half checked statically; these keep interpret-mode execution
# bitwise-equal to eager.


def test_gather_rows_jitted_matches_eager():
    t = np.random.default_rng(5).normal(size=(120, 16)).astype(np.float32)
    ids = np.random.default_rng(6).integers(0, 120, 33).astype(np.int32)
    fn = lambda tbl, i: gather_rows(tbl, i, interpret=True)  # noqa: E731
    eager = np.asarray(fn(jnp.asarray(t), jnp.asarray(ids)))
    jitted = np.asarray(jax.jit(fn)(jnp.asarray(t), jnp.asarray(ids)))
    assert np.array_equal(eager, jitted)
    assert np.array_equal(eager, t[ids])


def test_hot_gather_election_int8_jitted():
    # the int8 tier stores codes; the elected pallas gather must move them
    # un-upcast under jit exactly as the xla take does
    from quiver_tpu.feature.feature import _hot_gather_fn

    codes = np.random.default_rng(7).integers(
        -128, 128, size=(90, 8)).astype(np.int8)
    ids = np.random.default_rng(8).integers(0, 90, 40).astype(np.int32)
    tbl = jnp.asarray(codes)
    for kernel in ("pallas", "xla"):
        out = jax.jit(_hot_gather_fn(tbl, kernel))(jnp.asarray(ids))
        assert out.dtype == jnp.int8, kernel
        assert np.array_equal(np.asarray(out), codes[ids]), kernel


def test_windowed_sampler_jitted_matches_eager():
    ei = generate_pareto_graph(400, 6.0, seed=9)
    topo = CSRTopo(edge_index=ei)  # host-numpy arrays: the regression shape
    seeds = jnp.asarray(np.random.default_rng(10).integers(0, 400, 24),
                        jnp.int32)
    key = jax.random.PRNGKey(11)
    fn = lambda s, k: sample_layer_windowed(  # noqa: E731
        topo, s, jnp.int32(24), 5, k, window=256)
    nbr_e, cnt_e = fn(seeds, key)
    nbr_j, cnt_j = jax.jit(fn)(seeds, key)
    assert np.array_equal(np.asarray(nbr_e), np.asarray(nbr_j))
    assert np.array_equal(np.asarray(cnt_e), np.asarray(cnt_j))
