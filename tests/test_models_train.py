"""Model + end-to-end training tests: the minimum end-to-end slice of
SURVEY §7.3 — sampler + feature + SAGE + optax on a synthetic labeled graph,
asserting the loss actually falls and accuracy beats chance by a wide margin
(the reference's acceptance criterion is a running Reddit training loop,
examples/pyg/reddit_quiver.py / README.md:76-78)."""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.feature.feature import Feature
from quiver_tpu.models.sage import GraphSAGE
from quiver_tpu.models.layers import segment_softmax
from quiver_tpu.parallel.train import (
    init_model,
    make_eval_step,
    make_train_step,
)


def _labeled_graph(n=300, classes=4, seed=0):
    """Features carry a noisy one-hot of the label; edges mostly intra-class
    so neighborhood aggregation denoises."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, classes, n)
    feat = np.eye(classes, dtype=np.float32)[labels] * 2.0
    feat = feat + rng.normal(scale=1.0, size=(n, classes)).astype(np.float32)
    rows, cols = [], []
    for c in range(classes):
        members = np.where(labels == c)[0]
        for _ in range(6 * len(members)):
            rows.append(rng.choice(members))
            cols.append(rng.choice(members))
    ei = np.stack([np.asarray(rows), np.asarray(cols)])
    return ei, feat, labels


def test_segment_softmax_matches_dense():
    logits = jnp.array([1.0, 2.0, 0.5, 3.0, -1.0])
    seg = jnp.array([0, 0, 1, 1, 1])
    valid = jnp.array([True, True, True, True, False])
    out = np.asarray(segment_softmax(logits, seg, valid, 2))
    a = np.exp([1.0, 2.0])
    a /= a.sum()
    b = np.exp([0.5, 3.0])
    b /= b.sum()
    assert np.allclose(out[:2], a, rtol=1e-5)
    assert np.allclose(out[2:4], b, rtol=1e-5)
    assert out[4] == 0


def test_sage_forward_shapes():
    ei, feat, labels = _labeled_graph()
    topo = CSRTopo(edge_index=ei)
    sampler = GraphSageSampler(topo, [5, 3])
    out = sampler.sample(np.arange(64))
    model = GraphSAGE(hidden=16, num_classes=4, num_layers=2)
    x = jnp.asarray(feat)[jnp.clip(out.n_id, 0)]
    params = init_model(model, jax.random.PRNGKey(0), x, out.adjs)
    logits = model.apply({"params": params}, x, out.adjs)
    assert logits.shape == (out.adjs[-1].size[1], 4)
    assert np.all(np.isfinite(np.asarray(logits)))


def test_end_to_end_training_learns():
    ei, feat, labels = _labeled_graph()
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    sampler = GraphSageSampler(topo, [5, 5], seed=1)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat[:n])

    model = GraphSAGE(hidden=32, num_classes=4, num_layers=2)
    tx = optax.adam(5e-3)

    seeds0 = np.arange(128) % n
    out0 = sampler.sample(seeds0)
    x0 = feature[out0.n_id]
    params = init_model(model, jax.random.PRNGKey(0), x0, out0.adjs)
    opt_state = tx.init(params)

    train_step = jax.jit(make_train_step(model, tx))
    eval_step = jax.jit(make_eval_step(model))

    rng = np.random.default_rng(0)
    losses = []
    for step in range(30):
        seeds = rng.integers(0, n, 128)
        out = sampler.sample(seeds)
        x = feature[out.n_id]
        cap = out.adjs[-1].size[1]
        lab = np.full(cap, -1, np.int32)
        lab[:128] = labels[seeds]
        mask = np.zeros(cap, bool)
        mask[:128] = True
        params, opt_state, loss = train_step(
            params,
            opt_state,
            x,
            out.adjs,
            jnp.asarray(lab),
            jnp.asarray(mask),
            jax.random.PRNGKey(step),
        )
        losses.append(float(loss))

    assert losses[-1] < losses[0] * 0.7, losses

    # eval accuracy well above chance (0.25)
    seeds = rng.integers(0, n, 256)
    out = sampler.sample(seeds)
    x = feature[out.n_id]
    cap = out.adjs[-1].size[1]
    lab = np.full(cap, -1, np.int32)
    lab[:256] = labels[seeds]
    mask = np.zeros(cap, bool)
    mask[:256] = True
    correct, total = eval_step(params, x, out.adjs, jnp.asarray(lab), jnp.asarray(mask))
    acc = float(correct) / float(total)
    assert acc > 0.6, acc
