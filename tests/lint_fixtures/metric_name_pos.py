"""Positive fixture: registry metric names bypassing the constants.

One literal that duplicates a declared constant's value (spelling drift
waiting to happen), one dotted literal matching NO declared constant
(drift that already happened — note the missing 'o').
"""

ROUTED_OVERFLOW = "feature.routed_overflow"


def report(registry, tape, x):
    tape.add("feature.routed_overflow", x)
    registry.counter("feature.routed_overflw")
    return registry.value(ROUTED_OVERFLOW)
