"""POSITIVE fixture: the psum-fallback cond pattern with drifted branch
structure — one branch returns (rows, count), the other bare rows."""

import jax
import jax.numpy as jnp
from jax import lax


def routed_with_fallback(ids, table, overflow):
    def _fallback(args):
        rows = table[jnp.clip(args, 0, table.shape[0] - 1)]
        count = jnp.sum((args >= 0).astype(jnp.int32))
        return rows, count  # arity 2

    def _clean(args):
        return jnp.zeros((args.shape[0], table.shape[1]), table.dtype)

    return lax.cond(overflow > 0, _fallback, _clean, ids)  # LINT: parity


@jax.jit
def step(ids, table, overflow):
    return routed_with_fallback(ids, table, overflow)
