"""Negative fixture: metric names through the declared constants.

Timeline stage names are NOT registry metrics (different namespace), and
non-literal name arguments are out of a static linter's reach.
"""

ROUTED_OVERFLOW = "feature.routed_overflow"


def report(registry, tape, timeline, x, name):
    tape.add(ROUTED_OVERFLOW, x, psum="data")
    timeline.observe("prefetch.dispatch", 0.1)
    registry.set(name, x)
    return registry.value(ROUTED_OVERFLOW)
