"""NEGATIVE fixture: the sanctioned trace-time logging idioms."""

import logging

import jax
import jax.numpy as jnp

_ONCE = set()


def info_once(key, msg, *args):
    if key in _ONCE:
        return
    _ONCE.add(key)
    logging.getLogger("fixture").info(msg, *args)


@jax.jit
def quiet_step(x):
    info_once("step-traced", "step traced at width %d", x.shape[0])
    jax.debug.print("in-program value: {}", jnp.sum(x))
    return x * 2


def eager_driver(x):
    # logging in EAGER code is fine — only traced bodies are flagged
    logging.getLogger("fixture").info("running batch %s", x.shape)
    return quiet_step(x)
