"""Negative fixture: every executor has a shutdown path.

Class pools reachable from close()/shutdown() (directly or through a
private helper), a generator's try/finally shutdown (the Prefetcher
shape), a with-block, and an explicit ownership transfer.
"""

import concurrent.futures
from concurrent.futures import ThreadPoolExecutor


class Owned:
    def __init__(self):
        self._pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)

    def close(self):
        self._pool.shutdown(wait=True)


class Indirect:
    def __init__(self):
        self._pool = ThreadPoolExecutor(max_workers=1)

    def shutdown(self):
        self._stop()

    def _stop(self):
        self._pool.shutdown(wait=False)


def stream(items):
    pool = ThreadPoolExecutor(max_workers=1)
    try:
        for it in items:
            yield pool.submit(it)
    finally:
        pool.shutdown(wait=False, cancel_futures=True)


def scoped(items):
    with concurrent.futures.ThreadPoolExecutor() as pool:
        return list(pool.map(str, items))


def make_pool():
    pool = ThreadPoolExecutor(max_workers=1)
    return pool
