"""Negative fixture: the PR-8 version-guard pattern, honored.

Same class shape as ``staleness_pos.py``; every public read of the
placed state is dominated by the version guard — directly, via a callee
that establishes the guard on every exit (interprocedural propagation),
or via the rebind seam itself (a dominating ``refresh()`` makes the
state fresh by construction).
"""


class VersionMismatchError(RuntimeError):
    pass


class PlacedFeature:
    def __init__(self, host):
        self.host = host
        self._rows = dict(host.rows)
        self._host_version = int(host.version)

    def check_version(self):
        if int(self.host.version) != self._host_version:
            raise VersionMismatchError("placement is stale; refresh()")

    def refresh(self):
        self._rows = dict(self.host.rows)
        self._host_version = int(self.host.version)

    def _ensure_fresh(self):
        self.check_version()

    def lookup(self, idx):
        self.check_version()
        return self._rows[idx]

    def lookup_via_callee(self, idx):
        self._ensure_fresh()
        return self._rows[idx]

    def lookup_after_refresh(self, idx):
        self.refresh()
        return self._rows[idx]
