"""POSITIVE fixture: host scalar ops on traced values."""

import jax
import jax.numpy as jnp


@jax.jit
def bad_readback(x, y):
    thresh = int(x[0])  # LINT: host-op-on-tracer
    total = float(jnp.sum(y))  # LINT: host-op-on-tracer
    return jnp.where(y > thresh, y, total)


@jax.jit
def bad_unroll(xs):
    acc = jnp.zeros((), xs.dtype)
    for i in range(len(xs)):  # LINT: host-op-on-tracer (unroll)
        acc = acc + xs[i]
    return acc


@jax.jit
def bad_item(x):
    return x.item()  # LINT: host-op-on-tracer
