"""NEGATIVE fixture: cond branches with matching return structure."""

import jax
import jax.numpy as jnp
from jax import lax


def routed_with_fallback(ids, table, overflow):
    def _fallback(args):
        rows = table[jnp.clip(args, 0, table.shape[0] - 1)]
        count = jnp.sum((args >= 0).astype(jnp.int32))
        return rows, count

    def _clean(args):
        rows = jnp.zeros((args.shape[0], table.shape[1]), table.dtype)
        return rows, jnp.int32(0)

    return lax.cond(overflow > 0, _fallback, _clean, ids)


@jax.jit
def step(ids, table, overflow):
    # lambdas with matching scalar returns are fine too
    return lax.cond(
        overflow > 0,
        lambda x: x + 1,
        lambda x: x - 1,
        jnp.sum(table[ids]),
    )
