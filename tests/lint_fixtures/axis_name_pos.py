"""POSITIVE fixture: hardcoded and drifted axis-name literals."""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from quiver_tpu.parallel.mesh import shard_map

DATA_AXIS = "data"
FEATURE_AXIS = "feature"


def make_gather(mesh):
    def body(table, ids):
        part = jnp.where(ids[:, None] >= 0, table[ids], 0)
        # literal matches a declared axis but bypasses the constant
        total = jax.lax.psum(part, "feature")  # LINT: hardcoded
        # literal matches NO declared axis — string drift
        my = jax.lax.axis_index("features")  # LINT: unknown axis
        return total, my

    return shard_map(
        body, mesh=mesh,
        in_specs=(P("feature", None), P(DATA_AXIS)),  # LINT: hardcoded
        out_specs=(P(DATA_AXIS), P()),
    )


def worker_count(mesh):
    return mesh.shape["data"]  # LINT: hardcoded shape key
