"""Negative fixture: reentrancy-safe lock usage.

The ``_locked`` split keeps the lock acquisition at the public boundary;
the RLock-backed class is exempt (reentrancy is an RLock's point).
"""

import threading


class Safe:
    def __init__(self):
        self._lock = threading.Lock()
        self._rows = {}

    def put(self, key, value):
        with self._lock:
            self._put_locked(key, value)

    def flush(self):
        with self._lock:
            self._rows.clear()

    def _put_locked(self, key, value):
        self._rows[key] = value


class Reentrant:
    def __init__(self):
        self._lock = threading.RLock()
        self._rows = {}

    def put(self, key, value):
        with self._lock:
            self._rows[key] = value
            self.flush()

    def flush(self):
        with self._lock:
            self._rows.clear()
