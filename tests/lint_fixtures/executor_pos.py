"""Positive fixture: executors without a shutdown path.

A class-owned pool with no lifecycle method, and a function-local pool
that is never shut down (submitting futures out of it is use, not
ownership transfer).
"""

import concurrent.futures


class Leaky:
    def __init__(self):
        self._pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)

    def submit(self, fn):
        return self._pool.submit(fn)


def run_batch(items):
    pool = concurrent.futures.ThreadPoolExecutor(max_workers=2)
    return [pool.submit(it) for it in items]
