"""NEGATIVE fixture: static-metadata host math is fine in traced code."""

import jax
import jax.numpy as jnp


@jax.jit
def shape_math(x, y):
    n = int(x.shape[0])  # static: shapes are Python ints at trace time
    k = float(x.ndim + y.ndim)
    width = len(y)  # len of a traced array is its static leading dim
    return jnp.broadcast_to(jnp.float32(k), (n,))[:width]


def plan_cap(length, num_shards: int = 1, alpha: float = 2.0):
    # EAGER planning helper (never reached from a trace entry here):
    # host ints on config values are exactly what eager code should do
    return max(1, min(int(alpha * length) // num_shards, int(length)))


@jax.jit
def static_slice(x, y):
    cap = min(int(x.shape[0]), int(y.shape[0]))  # static shape math
    return x[:cap] + y[:cap]
