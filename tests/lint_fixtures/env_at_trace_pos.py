"""POSITIVE fixture: the pre-PR-3 ``models/layers.py`` QUIVER_COUNTS bug.

``occurrence_counts`` reads the env var on every call and is called from a
jitted model body, so the "switch" silently freezes at first trace. This
file is parsed by graftlint's self-tests, never imported."""

import os

import jax
import jax.numpy as jnp


def occurrence_counts(ids, valid, n: int):
    # the bug: an env read that executes at trace time but looks live
    how = os.environ.get("QUIVER_COUNTS", "scan")  # LINT: env-at-trace
    if how == "scan":
        sv = jnp.sort(jnp.where(valid, ids, n))
        edges = jnp.searchsorted(sv, jnp.arange(n + 1, dtype=ids.dtype))
        return (edges[1:] - edges[:-1]).astype(jnp.float32)
    return jax.ops.segment_sum(
        valid.astype(jnp.float32), jnp.where(valid, ids, n),
        num_segments=n + 1,
    )[:n]


@jax.jit
def model_step(ids, valid):
    deg = occurrence_counts(ids, valid, 64)
    return deg / jnp.maximum(deg.sum(), 1.0)
