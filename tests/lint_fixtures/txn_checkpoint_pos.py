"""Positive fixture: the pre-PR-7 save shapes, one per transaction rule.

The module name contains "checkpoint" so the transactional scope applies
(it also calls ``os.replace``, the self-declaring scope trigger).
"""

import os


def save_bare(state_dir, payload):
    # non-atomic-publish: direct write to the published path — a crash
    # mid-write leaves a torn file the next reader trusts
    path = os.path.join(state_dir, "arrays.bin")
    with open(path, "wb") as fh:
        fh.write(payload)


def save_marker_first(out, payload):
    # commit-marker-order: the COMMIT marker lands before the payload
    tmp = out + ".tmp-fixture"
    os.makedirs(tmp, exist_ok=True)
    with open(os.path.join(tmp, "COMMIT"), "w") as fh:
        fh.write("COMMIT\n")
    with open(os.path.join(tmp, "arrays.bin"), "wb") as fh:
        fh.write(payload)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, out)


def publish_unsynced(out, payload):
    # replace-without-fsync: atomic in the namespace, torn in the page
    # cache — a crash can surface a zero-length file at the FINAL name
    tmp = out + ".tmp-fixture2"
    with open(tmp, "wb") as fh:
        fh.write(payload)
    os.replace(tmp, out)
