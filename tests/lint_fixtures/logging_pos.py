"""POSITIVE fixture: per-call logging inside traced bodies."""

import logging

import jax
import jax.numpy as jnp


def get_logger():
    return logging.getLogger("fixture")


@jax.jit
def noisy_step(x):
    print("tracing step")  # LINT: per-call-logging-in-jit
    get_logger().info("gathered %d rows", x.shape[0])  # LINT
    return x * 2


def helper(x):
    logger = logging.getLogger("fixture")
    logger.warning("helper saw %s", x.shape)  # LINT (traced via call)
    return x + 1


@jax.jit
def outer(x):
    return helper(x)
