"""Negative fixture: the PR-7 atomic save discipline, honored.

Temp-dir writes through a fsyncing helper, COMMIT marker last, one
``os.replace`` publish; append-mode ledger streams are a different idiom
and exempt.
"""

import os


def _write_file(path, data):
    # write helper: the bare-parameter target moves the obligation to the
    # call sites (all of which pass temp-derived paths below)
    with open(path, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())


def save_atomic(out, payload):
    tmp = f"{out}.tmp-{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    _write_file(os.path.join(tmp, "arrays.bin"), payload)
    _write_file(os.path.join(tmp, "COMMIT"), b"COMMIT\n")
    os.replace(tmp, out)


def append_ledger(path, line):
    with open(path, "a") as fh:
        fh.write(line)
