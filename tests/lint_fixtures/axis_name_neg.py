"""NEGATIVE fixture: axis names through the shared constants only."""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from quiver_tpu.parallel.mesh import shard_map

DATA_AXIS = "data"
FEATURE_AXIS = "feature"


def make_gather(mesh):
    def body(table, ids):
        part = jnp.where(ids[:, None] >= 0, table[ids], 0)
        total = jax.lax.psum(part, FEATURE_AXIS)
        my = jax.lax.axis_index(FEATURE_AXIS)
        return total, my

    return shard_map(
        body, mesh=mesh,
        in_specs=(P(FEATURE_AXIS, None), P(DATA_AXIS)),
        out_specs=(P(DATA_AXIS), P()),
    )


def worker_count(mesh):
    return mesh.shape[DATA_AXIS] * mesh.shape[FEATURE_AXIS]
