"""NEGATIVE fixture: the sanctioned resolve-once idiom (the PR 3 fix).

The env read sits behind a module-global ``is None`` guard, so it runs
once per process — a barrier for the traced-reachability walk."""

import os

import jax
import jax.numpy as jnp

_counts_strategy = None


def resolve_counts_strategy() -> str:
    global _counts_strategy
    if _counts_strategy is None:
        _counts_strategy = os.environ.get("QUIVER_COUNTS", "scan")
    return _counts_strategy


def occurrence_counts(ids, valid, n: int):
    how = resolve_counts_strategy()
    if how == "scan":
        sv = jnp.sort(jnp.where(valid, ids, n))
        edges = jnp.searchsorted(sv, jnp.arange(n + 1, dtype=ids.dtype))
        return (edges[1:] - edges[:-1]).astype(jnp.float32)
    return jax.ops.segment_sum(
        valid.astype(jnp.float32), jnp.where(valid, ids, n),
        num_segments=n + 1,
    )[:n]


@jax.jit
def model_step(ids, valid):
    deg = occurrence_counts(ids, valid, 64)
    return deg / jnp.maximum(deg.sum(), 1.0)
