"""Positive fixture: a non-reentrant lock held across a re-entering call.

``put`` calls ``flush`` while holding ``self._lock`` and ``flush`` takes
the same lock — ``threading.Lock`` is not reentrant, so this deadlocks
the owner thread. ``drain`` hits the same bug one call deeper (the
acquisition fact propagates through same-class calls).
"""

import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._rows = {}

    def put(self, key, value):
        with self._lock:
            self._rows[key] = value
            self.flush()

    def flush(self):
        with self._lock:
            self._rows.clear()

    def drain(self):
        with self._lock:
            self.helper()

    def helper(self):
        self.flush()
