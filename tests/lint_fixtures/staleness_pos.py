"""Positive fixture: the PR-8 version-guard pattern, violated.

Miniature of the ``quiver_tpu.streaming`` consumer discipline: a device
placement captures the host state's committed ``version`` at build time;
every public read of the placed state must be dominated by the guard that
raises ``VersionMismatchError`` when the host has committed a newer
version. v1 graftlint (call-graph reachability only) cannot see either
violation below — ``lookup`` DOES call the guard (in one branch), and
``lookup_late`` calls it too (after the read). Only dominance catches
them.
"""


class VersionMismatchError(RuntimeError):
    pass


class PlacedFeature:
    def __init__(self, host):
        self.host = host
        self._rows = dict(host.rows)
        self._host_version = int(host.version)

    def check_version(self):
        if int(self.host.version) != self._host_version:
            raise VersionMismatchError("placement is stale; refresh()")

    def refresh(self):
        self._rows = dict(self.host.rows)
        self._host_version = int(self.host.version)

    def lookup(self, idx):
        # BUG: the guard runs in one branch only — idx == 0 reads stale
        if idx > 0:
            self.check_version()
        return self._rows[idx]

    def lookup_late(self, idx):
        # BUG: the guard runs after the read — theater, not protection
        row = self._rows[idx]
        self.check_version()
        return row
