"""graftaudit acceptance: the tree audits clean, every rule family
catches its seeded positive fixture, and the comm-budget rule reconciles
the lowered epoch body with the analytic model exactly at alpha 1 and 2.

Everything here is trace/lower only — no program executes a step (the
whole point: these invariants used to need slow execution differentials;
two of those are demoted to the slow lane in this PR)."""

import json

import pytest

from quiver_tpu.tools.audit import audit_targets
from quiver_tpu.tools.audit.audit_targets import REGISTRY, build, build_from
from quiver_tpu.tools.audit.cli import main
from quiver_tpu.tools.audit.rules import RULES, family_of, rule_docs
from quiver_tpu.tools.audit.runner import run_audit, select_targets

from audit_fixtures import (
    comm_fixtures,
    constant_fixtures,
    donation_fixtures,
    dtype_fixtures,
    hbm_fixtures,
    metrics_fixtures,
    padding_fixtures,
    parity_fixtures,
    replication_fixtures,
    vmem_fixtures,
)

_FIXTURES = {
    "collective-parity": parity_fixtures,
    "metrics-strip": metrics_fixtures,
    "donation-audit": donation_fixtures,
    "dtype-discipline": dtype_fixtures,
    "constant-bloat": constant_fixtures,
    "comm-budget": comm_fixtures,
    "peak-hbm-budget": hbm_fixtures,
    "no-silent-replication": replication_fixtures,
    "vmem-budget": vmem_fixtures,
    "padding-waste": padding_fixtures,
}


def _audit_fixture_set(rule, module):
    """Build a fixture module's targets and run one rule over each,
    resolving metrics pairs within the set."""
    pairs = [(t, build_from(t), fire) for t, fire in module.targets()]
    by_name = {t.name: b for t, b, _ in pairs}
    results = {}
    for t, built, fire in pairs:
        findings = RULES[rule](t, built, by_name.__getitem__)
        results[t.name] = (findings, fire)
    return results


@pytest.mark.parametrize("rule", sorted(_FIXTURES))
def test_rule_catches_its_positive_fixture(rule):
    for name, (findings, fire) in _audit_fixture_set(
            rule, _FIXTURES[rule]).items():
        if fire:
            assert findings, f"{rule} missed seeded positive {name}"
            assert all(f.rule == rule for f in findings)
        else:
            assert not findings, (
                f"{rule} false-positive on {name}: "
                f"{[f.message for f in findings]}"
            )


# slow lane: tracing + lowering all 14 registry programs is ~20s, and the
# CI audit job already gates the full registry twice per push (the
# authoritative `python -m quiver_tpu.tools.audit --sarif` run plus this
# file with no marker filter) — tier-1 keeps the per-rule fixture tests
# and the exactness differentials, which build only what they audit
@pytest.mark.slow
def test_repo_audits_clean():
    """The acceptance gate: every registered program upholds every rule
    family — 0 findings, nothing waived away silently."""
    result = run_audit()
    assert result.exit_code == 0
    assert result.findings == []
    assert set(result.targets) == set(REGISTRY)


def test_comm_budget_exact_at_alpha_1_and_2():
    """The lowered epoch body's all_to_all lanes == routed_lanes_per_hop
    EXACTLY at alpha in {1, 2} on the 2-device mesh — and the reconciled
    shapes are the ids + payload hops, not vacuous."""
    from quiver_tpu.control.cost import routed_lanes_per_hop
    from quiver_tpu.tools.audit.ir import collectives_of

    for name in ("epoch_body_alpha1", "epoch_body_alpha2"):
        built = build(name)
        comm = built.meta["comm"]
        model = routed_lanes_per_hop(
            comm["local_len"], comm["feature_shards"], comm["alpha"])
        a2a = [c for c in collectives_of(built.jaxpr)
               if c.prim == "all_to_all"]
        assert len(a2a) == 2, [str(c) for c in a2a]  # ids hop + payload hop
        for c in a2a:
            assert c.shape[:2] == (comm["feature_shards"],
                                   int(model["cap"]))
            assert c.lanes == int(model["lanes_per_hop"])
        assert not RULES["comm-budget"](REGISTRY[name], built, build)


def test_donating_epoch_donates_exactly_its_claim():
    """donate_epoch_state=True lowers a donation attr on every params+opt
    leaf (scan-carried state rides jax.buffer_donor) with zero
    unusable-donation warnings; the default epoch donates nothing."""
    from quiver_tpu.tools.audit.ir import main_arg_attrs

    donating = build("epoch_donating")
    attrs = main_arg_attrs(donating.mlir)
    donated = sum(1 for a in attrs if a["aliased"] or a["donor"])
    assert donated == REGISTRY["epoch_donating"].meta["donated_leaves"] > 0
    assert donating.donation_warnings == ()

    plain = build("epoch_body_alpha2")
    assert all(not (a["aliased"] or a["donor"])
               for a in main_arg_attrs(plain.mlir))


def test_donation_parser_pairs_operands_to_results():
    """main_arg_attrs against zero/partial/full donation: not just the
    donated COUNT but the operand<->result pairing — a pre-aliased arg's
    ``alias_output`` names the flattened result it writes into, tracking
    the matching result's POSITION, and an unusable donation leaves no
    attr (it surfaces as a warning only)."""
    import warnings

    import jax
    import jax.numpy as jnp

    from quiver_tpu.tools.audit.ir import main_arg_attrs

    a = jax.ShapeDtypeStruct((8, 4), jnp.float32)
    b = jax.ShapeDtypeStruct((16,), jnp.float32)

    def f(x, y):
        return x * 2.0, jnp.concatenate([y, y])

    def g(x, y):  # same programs, result order flipped
        return jnp.concatenate([y, y]), x * 2.0

    def attrs_of(fn, donate):
        with warnings.catch_warnings(record=True) as wlist:
            warnings.simplefilter("always")
            txt = jax.jit(fn, donate_argnums=donate).trace(
                a, b).lower().as_text()
        warned = [w for w in wlist if "donat" in str(w.message).lower()]
        return main_arg_attrs(txt), warned

    # zero donation: no attrs at all
    none, warned = attrs_of(f, ())
    assert len(none) == 2 and not warned
    assert all(not x["aliased"] and not x["donor"]
               and x["alias_output"] is None for x in none)

    # partial: x pre-aliases the same-shaped result — at index 0 in f,
    # index 1 in g: the parser reports the PAIRING, not a bare count
    part_f, warned = attrs_of(f, (0,))
    assert not warned
    assert (part_f[0]["aliased"], part_f[0]["alias_output"]) == (True, 0)
    assert part_f[1] == {"aliased": False, "donor": False,
                         "alias_output": None}
    part_g, _ = attrs_of(g, (0,))
    assert (part_g[0]["aliased"], part_g[0]["alias_output"]) == (True, 1)

    # full donation: y has no same-shaped result, so its donation is
    # UNUSABLE — no attr lowers for it, only the build warning (exactly
    # what the donation-audit rule counts on)
    full, warned = attrs_of(f, (0, 1))
    assert (full[0]["aliased"], full[0]["alias_output"]) == (True, 0)
    assert not full[1]["aliased"] and not full[1]["donor"]
    assert warned, "unusable donation must surface as a warning"


def test_changed_scoping_and_target_selection():
    assert select_targets(changed=set()) == []
    hit = select_targets(changed={"quiver_tpu/serving/ladder.py"})
    assert set(hit) == {"serve_forward", "serve_sample",
                        "serve_fleet_forward"}
    # PR 16-18 modules now scope to the targets that trace them
    assert "mmap_tiered_gather" in select_targets(
        changed={"quiver_tpu/ooc/store.py"})
    assert "serve_fleet_forward" in select_targets(
        changed={"quiver_tpu/serving/aot.py"})
    assert "pallas_fused_interp" in select_targets(
        changed={"quiver_tpu/ops/election.py"})
    # editing the auditor itself re-audits everything
    assert set(select_targets(
        changed={"quiver_tpu/tools/audit/rules.py"})) == set(REGISTRY)
    with pytest.raises(ValueError):
        select_targets(names=["nope"])


def test_waivers_suppress_with_reason():
    t = REGISTRY["pallas_fused_interp"]
    assert "constant-bloat" in t.waivers  # reasoned registry-side waiver
    result = run_audit(targets=["pallas_fused_interp"])
    assert result.exit_code == 0
    assert ("pallas_fused_interp", "constant-bloat",
            t.waivers["constant-bloat"]) in result.waivers


def test_cli_json_and_sarif(tmp_path, capsys):
    sarif = tmp_path / "audit.sarif"
    rc = main(["--targets", "routed_gather", "--json",
               "--sarif", str(sarif)])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["targets_audited"] == ["routed_gather"]
    assert payload["findings"] == []
    doc = json.loads(sarif.read_text())
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "graftaudit"
    assert {r["id"] for r in run["tool"]["driver"]["rules"]} == (
        set(RULES) | {"audit-error"})


def test_cli_usage_errors():
    assert main(["--select", "no-such-rule"]) == 2
    assert main(["--targets", "no-such-target"]) == 2


def test_sarif_merge(tmp_path):
    from quiver_tpu.tools.sarif import merge_sarif_files

    a = tmp_path / "lint.sarif"
    b = tmp_path / "audit.sarif"
    out = tmp_path / "analysis.sarif"
    doc = {"$schema": "s", "version": "2.1.0",
           "runs": [{"tool": {"driver": {"name": "graftlint"}},
                     "results": []}]}
    a.write_text(json.dumps(doc))
    doc["runs"][0]["tool"]["driver"]["name"] = "graftaudit"
    b.write_text(json.dumps(doc))
    merge_sarif_files([str(a), str(b), str(tmp_path / "missing.sarif")],
                      str(out))
    merged = json.loads(out.read_text())
    assert [r["tool"]["driver"]["name"] for r in merged["runs"]] == [
        "graftlint", "graftaudit"]


def test_rule_docs_cover_families():
    docs = rule_docs()
    for rule in RULES:
        assert docs[rule], f"{rule} has no doc"
        assert family_of(rule) != "meta"
    assert family_of("audit-error") == "meta"
