"""Tracing/timing/logging + debug-introspection subsystem tests.

Covers the observability parity layer (SURVEY §5): trace_scope gating
(reference TRACE_SCOPE, trace.hpp:6-14), Timer (timer.hpp:7-28), the
structured logger replacing LOG>>> prints, and show_tensor_info
(tensor.cpp:74-95).
"""

import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from quiver_tpu.utils import debug, trace


@pytest.fixture(autouse=True)
def _reset_trace_state():
    yield
    trace._enabled = None  # restore env-var-driven default


def test_trace_scope_disabled_is_noop(monkeypatch):
    monkeypatch.delenv("QUIVER_ENABLE_TRACE", raising=False)
    trace._enabled = None
    assert not trace.trace_enabled()
    with trace.trace_scope("x"):
        pass  # must not raise or require a profiler session


def test_trace_scope_env_enable(monkeypatch):
    monkeypatch.setenv("QUIVER_ENABLE_TRACE", "1")
    trace._enabled = None
    assert trace.trace_enabled()
    with trace.trace_scope("region"):
        y = jnp.arange(4) + 1
    assert int(y[0]) == 1


def test_enable_disable_override_env(monkeypatch):
    monkeypatch.setenv("QUIVER_ENABLE_TRACE", "1")
    trace.disable_trace()
    assert not trace.trace_enabled()
    trace.enable_trace()
    assert trace.trace_enabled()


def test_trace_scope_inside_jit():
    trace.enable_trace()

    @jax.jit
    def f(x):
        with trace.trace_scope("inner"):
            return x * 2

    assert int(f(jnp.int32(3))) == 6


def test_timer_measures_and_syncs():
    x = jnp.ones((64, 64))
    with trace.Timer("matmul", sync=x, quiet=True) as t:
        x = x @ x
    assert t.seconds > 0


def test_timer_logs(caplog):
    logger = trace.get_logger()
    with caplog.at_level(logging.INFO, logger="quiver_tpu"):
        logger.propagate = True
        try:
            with trace.Timer("scope"):
                pass
        finally:
            logger.propagate = False
    assert any("[scope]" in r.message for r in caplog.records)


def test_get_logger_invalid_level_falls_back(monkeypatch, capsys):
    """QUIVER_LOG_LEVEL=bogus must not crash the process at the first log
    call — the bootstrap falls back to the NullHandler path with a one-line
    stderr warning."""
    root = logging.getLogger("quiver_tpu")
    saved = root.handlers[:]
    saved_propagate, saved_level = root.propagate, root.level
    try:
        root.handlers = []
        monkeypatch.setenv("QUIVER_LOG_LEVEL", "bogus")
        logger = trace.get_logger()
        logger.info("still works")  # must not raise
        assert any(
            isinstance(h, logging.NullHandler) for h in root.handlers
        )
        err = capsys.readouterr().err
        assert "QUIVER_LOG_LEVEL" in err and "bogus" in err
    finally:
        root.handlers = saved
        root.propagate, root.level = saved_propagate, saved_level


def test_info_once_reset(caplog):
    logger = trace.get_logger()
    with caplog.at_level(logging.INFO, logger="quiver_tpu"):
        logger.propagate = True
        try:
            trace.info_once("k-reset-test", "once msg")
            trace.info_once("k-reset-test", "once msg")
            assert sum("once msg" in r.message for r in caplog.records) == 1
            trace.reset_once()  # the test-fixture hook (conftest autouse)
            trace.info_once("k-reset-test", "once msg")
            assert sum("once msg" in r.message for r in caplog.records) == 2
        finally:
            logger.propagate = False


def test_get_logger_singleton_handler():
    a, b = trace.get_logger(), trace.get_logger()
    root = logging.getLogger("quiver_tpu")
    assert a is b is root
    assert len(root.handlers) == 1
    assert trace.get_logger("feature").name == "quiver_tpu.feature"


def test_tensor_info_numpy_and_jax():
    s = debug.tensor_info(np.zeros((3, 4), np.float32))
    assert "numpy" in s and "(3, 4)" in s and "float32" in s
    arr = jnp.zeros((2, 5), jnp.int32)
    s = debug.tensor_info(arr)
    assert "jax.Array" in s and "(2, 5)" in s and "int32" in s


def test_show_tensor_info_prints(capsys):
    out = debug.show_tensor_info(jnp.ones(3))
    assert out in capsys.readouterr().out


def test_feature_placement_log(caplog):
    from quiver_tpu import Feature

    logger = trace.get_logger()
    feat = np.random.default_rng(0).normal(size=(100, 8)).astype(np.float32)
    with caplog.at_level(logging.INFO, logger="quiver_tpu"):
        logger.propagate = True
        try:
            Feature(device_cache_size=50 * 8 * 4).from_cpu_tensor(feat)
        finally:
            logger.propagate = False
    msgs = [r.message for r in caplog.records]
    assert any("cached in HBM" in m for m in msgs)


def test_sampler_works_with_tracing_enabled():
    from quiver_tpu import CSRTopo, GraphSageSampler

    trace.enable_trace()
    rng = np.random.default_rng(0)
    ei = rng.integers(0, 50, size=(2, 400)).astype(np.int64)
    topo = CSRTopo(edge_index=ei)
    sampler = GraphSageSampler(topo, [4, 3], seed=0)
    out = sampler.sample(np.arange(16))
    assert int(out.n_count) >= 16
