"""Temporal (time-windowed) neighbor sampling (quiver-hetero-dist).

``CSRTopo.set_edge_time`` re-sorts each row time-nondecreasing so a
``[lo, hi]`` window binary-searches to one contiguous slot range per row
(``ops.sample.temporal_window_counts``); every hop of a
``time_window=(lo, hi)`` sampler then draws only in-window edges.
Unsupported combinations fail loudly as ValueErrors, never silently.
"""

import numpy as np
import pytest

from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.utils.graphgen import generate_pareto_graph


def _timed_graph(n=300, deg=6.0, seed=0):
    topo = CSRTopo(edge_index=generate_pareto_graph(n, deg, seed=seed))
    topo.set_edge_time(np.random.default_rng(seed + 1).random(topo.edge_count))
    return topo


# -- attribute attachment ---------------------------------------------------


def test_set_edge_time_sorts_rows_and_keeps_alignment():
    ei = generate_pareto_graph(200, 5.0, seed=3)
    topo = CSRTopo(edge_index=ei)
    rng = np.random.default_rng(4)
    w = rng.random(topo.edge_count) + 0.1
    topo.set_edge_weight(w)
    pre = {
        r: sorted(zip(topo.indices[topo.indptr[r]:topo.indptr[r + 1]],
                      topo.edge_weight[topo.indptr[r]:topo.indptr[r + 1]]))
        for r in range(200)
    }
    topo.set_edge_time(rng.random(topo.edge_count))
    ip, t = np.asarray(topo.indptr), np.asarray(topo.edge_time)
    for r in range(200):
        seg = t[ip[r]:ip[r + 1]]
        assert (np.diff(seg) >= 0).all(), r  # time-nondecreasing per row
        # the (dst, weight) pairing must survive the per-row re-sort
        post = sorted(zip(topo.indices[ip[r]:ip[r + 1]],
                          topo.edge_weight[ip[r]:ip[r + 1]]))
        assert post == pre[r], r
    # weight prefix sums re-derived over the permuted slot order
    from quiver_tpu.core.topology import _row_prefix_weights
    assert np.array_equal(
        np.asarray(topo.cum_weights),
        _row_prefix_weights(np.asarray(topo.edge_weight, np.float64), ip),
    )


def test_set_edge_time_validation():
    topo = CSRTopo(edge_index=generate_pareto_graph(100, 4.0, seed=0))
    with pytest.raises(ValueError, match="entries"):
        topo.set_edge_time(np.zeros(3))
    with pytest.raises(ValueError, match="finite"):
        topo.set_edge_time(np.full(topo.edge_count, np.nan))


# -- windowed draw semantics ------------------------------------------------


def test_time_window_draws_only_in_window_edges():
    """With fanout >= max in-window degree, every hop must return EXACTLY
    each frontier node's in-window neighbor multiset — no out-of-window
    edge ever drawn, no in-window edge missed."""
    topo = _timed_graph(n=300)
    ip = np.asarray(topo.indptr)
    ix = np.asarray(topo.indices)
    t = np.asarray(topo.edge_time)
    lo, hi = 0.3, 0.7
    in_win = {
        r: sorted(ix[ip[r]:ip[r + 1]][(t[ip[r]:ip[r + 1]] >= lo)
                                      & (t[ip[r]:ip[r + 1]] <= hi)])
        for r in range(300)
    }
    k = max(max((len(v) for v in in_win.values()), default=1), 1)
    sampler = GraphSageSampler(topo, [k], seed_capacity=32,
                               time_window=(lo, hi))
    seeds = np.arange(32)
    out = sampler.sample(seeds)
    src, dst = (np.asarray(a).reshape(32, k)
                for a in out.adjs[0].edge_index)
    n_id = np.asarray(out.n_id)
    for i, s in enumerate(seeds):
        valid = src[i] >= 0
        assert sorted(n_id[src[i][valid]]) == in_win[s], s
        assert np.all(dst[i][valid] == i)


def test_time_window_degenerate_empty_window():
    """A window holding no edges yields all-invalid lanes, no crash."""
    topo = _timed_graph(n=120)
    sampler = GraphSageSampler(topo, [4], seed_capacity=16,
                               time_window=(2.0, 3.0))
    out = sampler.sample(np.arange(16))
    src = np.asarray(out.adjs[0].edge_index[0])
    assert np.all(src == -1)


# -- unsupported combinations fail loudly -----------------------------------


def test_time_window_guards():
    topo = _timed_graph(n=120)
    plain = CSRTopo(edge_index=generate_pareto_graph(120, 4.0, seed=0))
    with pytest.raises(ValueError, match="requires edge timestamps"):
        GraphSageSampler(plain, [4], time_window=(0.0, 1.0))
    with pytest.raises(ValueError, match="weighted"):
        topo.set_edge_weight(np.ones(topo.edge_count))
        GraphSageSampler(topo, [4], time_window=(0.0, 1.0), weighted=True)
    # temporal + pallas rides the fused engine now (PR 16) — no raise;
    # bitwise differentials live in test_fused_sampler.py
    s = GraphSageSampler(topo, [4], kernel="pallas", time_window=(0.0, 1.0))
    assert s.kernel in ("pallas", "xla")


def test_pallas_kernel_combination_guards():
    topo = _timed_graph(n=120)
    topo.set_edge_weight(np.ones(topo.edge_count))
    # weighted + pallas is a working combination on the fused engine;
    # only an unknown kernel name still raises
    s = GraphSageSampler(topo, [4], kernel="pallas", weighted=True)
    out = s.sample(np.arange(16))
    assert int(out.n_count) >= 16
    with pytest.raises(ValueError, match="kernel"):
        GraphSageSampler(topo, [4], kernel="nope")
