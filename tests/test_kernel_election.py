"""kernel=auto election: by measured throughput, not compile success
(VERDICT r3 item 4) — now one shared ``ops.election.KernelElection``
machinery behind both the gather (feature) and sample (fused megakernel)
elections, with one nested disk-cache file (ISSUE 16 satellite 2)."""

import json

import pytest

import quiver_tpu.ops.election as EL
from quiver_tpu.feature import feature as F
from quiver_tpu.sampling import sampler as S


@pytest.fixture(autouse=True)
def fresh_election(tmp_path, monkeypatch):
    # the election AND its env knobs are resolved once per process
    # (env-before-first-use); tests reset all the caches to re-resolve
    monkeypatch.setattr(EL, "_ELECTION_CACHE_PATH", None)
    monkeypatch.setenv("QUIVER_ELECTION_CACHE",
                       str(tmp_path / "election.json"))
    monkeypatch.delenv("QUIVER_GATHER_KERNEL", raising=False)
    monkeypatch.delenv("QUIVER_SAMPLE_KERNEL", raising=False)
    F.GATHER_ELECTION.reset()
    S.SAMPLE_ELECTION.reset()
    yield tmp_path / "election.json"
    # leave the module-level singletons as a fresh process would find them
    F.GATHER_ELECTION.reset()
    S.SAMPLE_ELECTION.reset()


def test_measure_gather_gbps_runs():
    gbps = F._measure_gather_gbps("xla", rows=512, dim=8, batch=64, reps=4)
    assert gbps > 0


def test_measure_sample_eps_runs():
    eps = S._measure_sample_eps("xla", nodes=64, edges=512, batch=16,
                                k=4, reps=2)
    assert eps > 0


@pytest.mark.parametrize("which", ["gather", "sample"])
def test_election_picks_measured_winner(which, fresh_election, monkeypatch):
    mod, elec = ((F, F.GATHER_ELECTION) if which == "gather"
                 else (S, S.SAMPLE_ELECTION))
    smoke = ("_pallas_gather_usable" if which == "gather"
             else "_pallas_sample_usable")
    meas = ("_measure_gather_gbps" if which == "gather"
            else "_measure_sample_eps")
    monkeypatch.setattr(mod, smoke, lambda: True)
    monkeypatch.setattr(mod, meas,
                        lambda k, **kw: {"xla": 10.0, "pallas": 4.0}[k])
    assert elec.elect() == "xla"
    assert elec.result["how"] == "measured"
    # and the loser would have won with the numbers flipped
    elec.reset()
    monkeypatch.setattr(EL, "_ELECTION_CACHE_PATH", None)
    monkeypatch.setenv("QUIVER_ELECTION_CACHE",
                       str(fresh_election.parent / "election2.json"))
    monkeypatch.setattr(mod, meas,
                        lambda k, **kw: {"xla": 4.0, "pallas": 10.0}[k])
    assert elec.elect() == "pallas"


def test_election_disk_cache_roundtrip(fresh_election, monkeypatch):
    monkeypatch.setattr(F, "_pallas_gather_usable", lambda: True)
    monkeypatch.setattr(
        F, "_measure_gather_gbps",
        lambda k, **kw: {"xla": 1.0, "pallas": 9.0}[k])
    assert F.GATHER_ELECTION.elect() == "pallas"
    blob = json.loads(fresh_election.read_text())
    cached = blob["gather"]  # nested by election name (one shared file)
    assert cached["kernel"] == "pallas" and cached["score"]["pallas"] == 9.0

    # a fresh process (reset memo) must trust the cache, not re-measure
    F.GATHER_ELECTION.reset()

    def boom(k, **kw):
        raise AssertionError("re-measured despite disk cache")

    monkeypatch.setattr(F, "_measure_gather_gbps", boom)
    assert F.GATHER_ELECTION.elect() == "pallas"
    assert F.GATHER_ELECTION.result["how"] == "disk cache"

    # ...but a different cache key (device kind / jax version / kernel
    # revision) invalidates it
    cached["key"] = "rev0-jaxother-chip"
    fresh_election.write_text(json.dumps({"gather": cached}))
    F.GATHER_ELECTION.reset()
    monkeypatch.setattr(
        F, "_measure_gather_gbps",
        lambda k, **kw: {"xla": 9.0, "pallas": 1.0}[k])
    assert F.GATHER_ELECTION.elect() == "xla"


def test_shared_cache_holds_both_elections(fresh_election, monkeypatch):
    """One file, nested by election name — the gather and sample entries
    coexist, and a pre-generalization FLAT gather cache pointed at by
    QUIVER_ELECTION_CACHE is tolerated (ignored, then rewritten nested)."""
    # legacy flat format from before the ops/election.py refactor
    fresh_election.write_text(json.dumps(
        {"kernel": "pallas", "gbps": {"pallas": 9.0, "xla": 1.0},
         "key": "rev1-jaxold-chip"}))
    monkeypatch.setattr(F, "_pallas_gather_usable", lambda: True)
    monkeypatch.setattr(
        F, "_measure_gather_gbps",
        lambda k, **kw: {"xla": 2.0, "pallas": 8.0}[k])
    monkeypatch.setattr(S, "_pallas_sample_usable", lambda: True)
    monkeypatch.setattr(
        S, "_measure_sample_eps",
        lambda k, **kw: {"xla": 7.0, "pallas": 3.0}[k])
    assert F.GATHER_ELECTION.elect() == "pallas"  # flat file not trusted
    assert F.GATHER_ELECTION.result["how"] == "measured"
    assert S.SAMPLE_ELECTION.elect() == "xla"
    blob = json.loads(fresh_election.read_text())
    assert blob["gather"]["kernel"] == "pallas"
    assert blob["sample"]["kernel"] == "xla"
    assert "gbps" not in blob  # legacy keys dropped on rewrite


def test_corrupt_cache_fails_safe_with_one_warning(fresh_election,
                                                   monkeypatch, caplog):
    """A corrupt/truncated shared cache file degrades to re-election with
    a single WARNING — never a raise on the gather/sample path — and the
    re-election's atomic republish heals the file (ISSUE 17 satellite:
    the serving AOT cache shares this tolerant loader)."""
    import logging

    fresh_election.write_text('{"gather": {"kernel": "pal')  # truncated
    monkeypatch.setattr(F, "_pallas_gather_usable", lambda: True)
    monkeypatch.setattr(
        F, "_measure_gather_gbps",
        lambda k, **kw: {"xla": 2.0, "pallas": 8.0}[k])
    with caplog.at_level(logging.WARNING, logger="quiver_tpu"):
        assert F.GATHER_ELECTION.elect() == "pallas"
    assert F.GATHER_ELECTION.result["how"] == "measured"
    warns = [r for r in caplog.records if "unreadable" in r.getMessage()]
    assert len(warns) == 1, [r.getMessage() for r in caplog.records]

    # the same corrupt read (load before store) happens again inside
    # _store's read-merge — still only ONE warning per process...
    # and the republish over the bad file is valid, nested JSON again
    blob = json.loads(fresh_election.read_text())
    assert blob["gather"]["kernel"] == "pallas"

    # a fresh process (reset) now trusts the healed cache
    F.GATHER_ELECTION.reset()

    def boom(k, **kw):
        raise AssertionError("re-measured despite healed disk cache")

    monkeypatch.setattr(F, "_measure_gather_gbps", boom)
    assert F.GATHER_ELECTION.elect() == "pallas"
    assert F.GATHER_ELECTION.result["how"] == "disk cache"
    # no temp residue from the atomic publish
    residue = [p.name for p in fresh_election.parent.iterdir()
               if ".tmp." in p.name]
    assert not residue, residue


def test_env_knobs_pinned_at_first_use(fresh_election, monkeypatch):
    """QUIVER_GATHER_KERNEL / QUIVER_ELECTION_CACHE resolve ONCE per
    process: flipping them after the first use is inert without a cache
    reset — the env-before-first-use contract graftlint's env-at-trace
    rule enforces repo-wide (chip-window forcing must precede the first
    gather)."""
    monkeypatch.setenv("QUIVER_GATHER_KERNEL", "xla")
    assert F.GATHER_ELECTION.forced() == "xla"
    first_path = EL._election_cache_path()
    assert first_path == str(fresh_election)
    # post-first-use flips are inert...
    monkeypatch.setenv("QUIVER_GATHER_KERNEL", "pallas")
    monkeypatch.setenv("QUIVER_ELECTION_CACHE",
                       str(fresh_election.parent / "other.json"))
    assert F.GATHER_ELECTION.forced() == "xla"
    assert EL._election_cache_path() == first_path
    # ...including through the election itself
    assert F.GATHER_ELECTION.elect() == "xla"
    assert F.GATHER_ELECTION.result["how"] == "env override"
    # a cache reset (= a fresh process) re-reads the env
    F.GATHER_ELECTION.reset()
    assert F.GATHER_ELECTION.forced() == "pallas"


def test_election_env_override_and_failsafes(fresh_election, monkeypatch):
    # the sample election rides the same failsafe ladder as gather
    monkeypatch.setenv("QUIVER_SAMPLE_KERNEL", "xla")
    assert S.SAMPLE_ELECTION.elect() == "xla"
    assert S.SAMPLE_ELECTION.result["how"] == "env override"

    # failed pallas smoke short-circuits to xla without measuring
    S.SAMPLE_ELECTION.reset()
    monkeypatch.delenv("QUIVER_SAMPLE_KERNEL")
    monkeypatch.setattr(S, "_pallas_sample_usable", lambda: False)

    def never(k, **kw):
        raise AssertionError("measured despite failed smoke")

    monkeypatch.setattr(S, "_measure_sample_eps", never)
    assert S.SAMPLE_ELECTION.elect() == "xla"
    assert S.SAMPLE_ELECTION.result["how"] == "pallas smoke failed"

    # a measurement crash degrades to xla instead of raising
    S.SAMPLE_ELECTION.reset()
    monkeypatch.setattr(S, "_pallas_sample_usable", lambda: True)

    def boom(k, **kw):
        raise RuntimeError("chip went away")

    monkeypatch.setattr(S, "_measure_sample_eps", boom)
    assert S.SAMPLE_ELECTION.elect() == "xla"
    assert S.SAMPLE_ELECTION.result["how"] == "election failed"


def test_resolve_passthrough_and_off_tpu(monkeypatch):
    """Explicit kernels bypass the election entirely; auto off-TPU is xla
    without running smoke or measure (the CPU interpret path is correct
    but slow)."""
    def never():
        raise AssertionError("smoke ran for an explicit/off-TPU resolve")

    monkeypatch.setattr(S, "_pallas_sample_usable", never)
    monkeypatch.setattr(S, "_measure_sample_eps",
                        lambda k, **kw: never())
    assert S.resolve_sample_kernel("pallas") == "pallas"
    assert S.resolve_sample_kernel("xla") == "xla"
    assert S.resolve_sample_kernel("auto") == "xla"  # CPU test runner
    with pytest.raises(ValueError, match="kernel"):
        S.resolve_sample_kernel("nope")
