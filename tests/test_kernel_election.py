"""kernel=auto election: by measured throughput, not compile success
(VERDICT r3 item 4)."""

import json

import pytest

from quiver_tpu.feature import feature as F


@pytest.fixture(autouse=True)
def fresh_election(tmp_path, monkeypatch):
    # the election AND its env knobs are resolved once per process
    # (env-before-first-use); tests reset all three caches to re-resolve
    monkeypatch.setattr(F, "_GATHER_ELECTION", None)
    monkeypatch.setattr(F, "_ELECTION_CACHE_PATH", None)
    monkeypatch.setattr(F, "_FORCED_GATHER_KERNEL", None)
    monkeypatch.setenv("QUIVER_ELECTION_CACHE",
                       str(tmp_path / "election.json"))
    monkeypatch.delenv("QUIVER_GATHER_KERNEL", raising=False)
    yield tmp_path / "election.json"


def test_measure_gather_gbps_runs():
    gbps = F._measure_gather_gbps("xla", rows=512, dim=8, batch=64, reps=4)
    assert gbps > 0


def test_election_picks_measured_winner(fresh_election, monkeypatch):
    monkeypatch.setattr(F, "_pallas_gather_usable", lambda: True)
    monkeypatch.setattr(
        F, "_measure_gather_gbps",
        lambda k, **kw: {"xla": 10.0, "pallas": 4.0}[k])
    assert F._elect_gather_kernel() == "xla"
    assert F._GATHER_ELECTION["how"] == "measured"
    # and the loser would have won with the numbers flipped
    monkeypatch.setattr(F, "_GATHER_ELECTION", None)
    monkeypatch.setattr(F, "_ELECTION_CACHE_PATH", None)
    monkeypatch.setenv("QUIVER_ELECTION_CACHE",
                       str(fresh_election.parent / "election2.json"))
    monkeypatch.setattr(
        F, "_measure_gather_gbps",
        lambda k, **kw: {"xla": 4.0, "pallas": 10.0}[k])
    assert F._elect_gather_kernel() == "pallas"


def test_election_disk_cache_roundtrip(fresh_election, monkeypatch):
    monkeypatch.setattr(F, "_pallas_gather_usable", lambda: True)
    monkeypatch.setattr(
        F, "_measure_gather_gbps",
        lambda k, **kw: {"xla": 1.0, "pallas": 9.0}[k])
    assert F._elect_gather_kernel() == "pallas"
    cached = json.loads(fresh_election.read_text())
    assert cached["kernel"] == "pallas" and cached["gbps"]["pallas"] == 9.0

    # a fresh process (reset global) must trust the cache, not re-measure
    monkeypatch.setattr(F, "_GATHER_ELECTION", None)

    def boom(k, **kw):
        raise AssertionError("re-measured despite disk cache")

    monkeypatch.setattr(F, "_measure_gather_gbps", boom)
    assert F._elect_gather_kernel() == "pallas"
    assert F._GATHER_ELECTION["how"] == "disk cache"

    # ...but a different cache key (device kind / jax version / kernel
    # revision) invalidates it
    cached["key"] = "rev0-jaxother-chip"
    fresh_election.write_text(json.dumps(cached))
    monkeypatch.setattr(F, "_GATHER_ELECTION", None)
    monkeypatch.setattr(
        F, "_measure_gather_gbps",
        lambda k, **kw: {"xla": 9.0, "pallas": 1.0}[k])
    assert F._elect_gather_kernel() == "xla"


def test_env_knobs_pinned_at_first_use(fresh_election, monkeypatch):
    """QUIVER_GATHER_KERNEL / QUIVER_ELECTION_CACHE resolve ONCE per
    process: flipping them after the first use is inert without a cache
    reset — the env-before-first-use contract graftlint's env-at-trace
    rule enforces repo-wide (chip-window forcing must precede the first
    gather)."""
    monkeypatch.setenv("QUIVER_GATHER_KERNEL", "xla")
    assert F._forced_gather_kernel() == "xla"
    first_path = F._election_cache_path()
    assert first_path == str(fresh_election)
    # post-first-use flips are inert...
    monkeypatch.setenv("QUIVER_GATHER_KERNEL", "pallas")
    monkeypatch.setenv("QUIVER_ELECTION_CACHE",
                       str(fresh_election.parent / "other.json"))
    assert F._forced_gather_kernel() == "xla"
    assert F._election_cache_path() == first_path
    # ...including through the election itself
    assert F._elect_gather_kernel() == "xla"
    assert F._GATHER_ELECTION["how"] == "env override"
    # a cache reset (= a fresh process) re-reads the env
    monkeypatch.setattr(F, "_FORCED_GATHER_KERNEL", None)
    assert F._forced_gather_kernel() == "pallas"


def test_election_env_override_and_failsafes(fresh_election, monkeypatch):
    monkeypatch.setenv("QUIVER_GATHER_KERNEL", "xla")
    assert F._elect_gather_kernel() == "xla"
    assert F._GATHER_ELECTION["how"] == "env override"

    # failed pallas smoke short-circuits to xla without measuring
    monkeypatch.setattr(F, "_GATHER_ELECTION", None)
    monkeypatch.setattr(F, "_FORCED_GATHER_KERNEL", None)
    monkeypatch.delenv("QUIVER_GATHER_KERNEL")
    monkeypatch.setattr(F, "_pallas_gather_usable", lambda: False)
    assert F._elect_gather_kernel() == "xla"

    # a measurement crash degrades to xla instead of raising
    monkeypatch.setattr(F, "_GATHER_ELECTION", None)
    monkeypatch.setattr(F, "_pallas_gather_usable", lambda: True)

    def boom(k, **kw):
        raise RuntimeError("chip went away")

    monkeypatch.setattr(F, "_measure_gather_gbps", boom)
    assert F._elect_gather_kernel() == "xla"
    assert F._GATHER_ELECTION["how"] == "election failed"
