"""Differential + property tests for order-preserving masked unique / reindex.

Oracle: the hash-map reference in ops/cpu_ref.py (parity with the reference's
reindex_group, quiver.cpp:39-84).
"""

import pytest
import numpy as np
import jax.numpy as jnp

from quiver_tpu.ops.reindex import masked_unique, reindex_layer
from quiver_tpu.ops.cpu_ref import reindex_layer_ref


def _first_occurrence_unique(xs):
    seen, out = set(), []
    for x in xs:
        if x not in seen:
            seen.add(x)
            out.append(x)
    return out


def test_masked_unique_basic():
    ids = jnp.array([5, 3, 5, 7, 3, 9])
    valid = jnp.ones(6, bool)
    uniq, n, local = masked_unique(ids, valid, size=8)
    assert n == 4
    assert list(uniq[:4]) == [5, 3, 7, 9]
    assert list(uniq[4:]) == [-1, -1, -1, -1]
    assert list(local) == [0, 1, 0, 2, 1, 3]


def test_masked_unique_with_invalid():
    ids = jnp.array([5, -1, 5, 7, -1, 5])
    valid = ids >= 0
    uniq, n, local = masked_unique(ids, valid, size=4)
    assert n == 2
    assert list(uniq[:2]) == [5, 7]
    assert list(local) == [0, -1, 0, 1, -1, 0]


def test_masked_unique_all_invalid():
    ids = jnp.full(5, -1)
    uniq, n, local = masked_unique(ids, ids >= 0, size=3)
    assert n == 0
    assert list(uniq) == [-1, -1, -1]
    assert list(local) == [-1] * 5


def test_masked_unique_overflow():
    ids = jnp.array([1, 2, 3, 4, 5])
    valid = jnp.ones(5, bool)
    uniq, n, local = masked_unique(ids, valid, size=3)
    assert n == 5  # total uniques reported even beyond capacity
    assert list(uniq) == [1, 2, 3]
    assert list(local) == [0, 1, 2, -1, -1]  # overflowed get -1


def test_masked_unique_random_vs_python():
    rng = np.random.default_rng(0)
    for trial in range(10):
        t = int(rng.integers(1, 200))
        ids = rng.integers(0, 50, t)
        valid = rng.random(t) < 0.8
        uniq, n, local = masked_unique(jnp.asarray(ids), jnp.asarray(valid), size=t)
        expect = _first_occurrence_unique(ids[valid].tolist())
        assert int(n) == len(expect)
        assert list(np.asarray(uniq[: len(expect)])) == expect
        # local ids consistent: uniq[local[p]] == ids[p] for valid p
        la = np.asarray(local)
        ua = np.asarray(uniq)
        for p in range(t):
            if valid[p]:
                assert ua[la[p]] == ids[p]
            else:
                assert la[p] == -1


@pytest.mark.slow  # 37s 3-way differential; map/scan spot checks stay fast
def test_masked_unique_alternatives_match_sort():
    """The sort-free dense-map dedup (node_bound) AND the zero-scatter scan
    dedup must be bit-identical to the sort path on every output, across
    duplicates, invalid lanes, forced (duplicated) seed lanes, and capacity
    overflow."""
    rng = np.random.default_rng(7)
    for trial in range(20):
        t = int(rng.integers(1, 300))
        bound = int(rng.integers(5, 80))
        ids = rng.integers(0, bound, t)
        valid = rng.random(t) < 0.8
        forced = int(rng.integers(0, min(t, 10)))
        size = int(rng.integers(1, t + 5))
        got = masked_unique(
            jnp.asarray(ids), jnp.asarray(valid), size=size,
            num_forced=forced,
        )
        for kw in ({"node_bound": bound}, {"scatter_free": True}):
            alt = masked_unique(
                jnp.asarray(ids), jnp.asarray(valid), size=size,
                num_forced=forced, **kw,
            )
            for a, b, name in zip(got, alt, ("uniq", "n", "local")):
                assert np.array_equal(np.asarray(a), np.asarray(b)), (
                    trial, kw, name, np.asarray(a), np.asarray(b)
                )


def test_masked_unique_scan_all_invalid_and_oversize():
    """Scan-strategy edge cases: every lane invalid, and size > T."""
    ids = jnp.asarray([5, 5, 2])
    none = jnp.zeros(3, bool)
    uniq, n, local = masked_unique(ids, none, size=6, scatter_free=True)
    assert int(n) == 0
    assert np.all(np.asarray(uniq) == -1) and np.all(np.asarray(local) == -1)
    uniq, n, local = masked_unique(ids, jnp.ones(3, bool), size=6,
                                   scatter_free=True)
    assert list(np.asarray(uniq)) == [5, 2, -1, -1, -1, -1]
    assert int(n) == 2 and list(np.asarray(local)) == [0, 0, 1]


def test_sampler_dedup_alternatives_match_sort():
    """End-to-end: GraphSageSampler(dedup='map'|'scan') reproduces
    dedup='sort' exactly (same seed, same key path)."""
    from quiver_tpu import CSRTopo, GraphSageSampler

    rng = np.random.default_rng(3)
    ei = np.stack([rng.integers(0, 500, 4000), rng.integers(0, 500, 4000)])
    topo = CSRTopo(edge_index=ei)
    seeds = rng.integers(0, topo.node_count, 64)
    outs = {}
    for dedup in ("sort", "map", "scan"):
        s = GraphSageSampler(topo, [5, 3], seed=11, dedup=dedup)
        outs[dedup] = s.sample(seeds)
    a = outs["sort"]
    for other in ("map", "scan"):
        b = outs[other]
        assert np.array_equal(np.asarray(a.n_id), np.asarray(b.n_id)), other
        for adj_a, adj_b in zip(a.adjs, b.adjs):
            assert np.array_equal(
                np.asarray(adj_a.edge_index), np.asarray(adj_b.edge_index)
            ), other


def test_sampler_device_topo_reuse():
    """Samplers sharing one prebuilt DeviceTopology must behave exactly like
    samplers that upload their own copy, and incompatible reuse is rejected."""
    import pytest

    from quiver_tpu import CSRTopo, GraphSageSampler
    from quiver_tpu.core.config import SampleMode

    rng = np.random.default_rng(11)
    ei = np.stack([rng.integers(0, 300, 2500), rng.integers(0, 300, 2500)])
    topo = CSRTopo(edge_index=ei)
    dev = topo.to_device(SampleMode.HBM)
    seeds = rng.integers(0, topo.node_count, 48)

    own = GraphSageSampler(topo, [4, 3], seed=5)
    shared = GraphSageSampler(topo, [4, 3], seed=5, device_topo=dev)
    a, b = own.sample(seeds), shared.sample(seeds)
    assert np.array_equal(np.asarray(a.n_id), np.asarray(b.n_id))
    for adj_a, adj_b in zip(a.adjs, b.adjs):
        assert np.array_equal(
            np.asarray(adj_a.edge_index), np.asarray(adj_b.edge_index)
        )

    with pytest.raises(ValueError, match="eid"):
        GraphSageSampler(topo, [4], seed=0, with_eid=True, device_topo=dev)


def test_reindex_layer_matches_reference():
    rng = np.random.default_rng(1)
    S, K = 16, 5
    num_seeds = 11
    seeds = np.full(S, -1, np.int64)
    seeds[:num_seeds] = rng.choice(100, num_seeds, replace=False)
    neighbors = rng.integers(0, 100, (S, K))
    neighbors[num_seeds:] = -1
    mask = rng.random((S, K)) < 0.7
    neighbors = np.where(mask, neighbors, -1)
    neighbors[num_seeds:] = -1

    frontier, n_frontier, col, overflow = reindex_layer(
        jnp.asarray(seeds), jnp.int32(num_seeds), jnp.asarray(neighbors), 128
    )
    ref_frontier, ref_col = reindex_layer_ref(seeds[:num_seeds], neighbors)
    assert int(overflow) == 0
    assert int(n_frontier) == len(ref_frontier)
    assert np.array_equal(np.asarray(frontier[: len(ref_frontier)]), ref_frontier)
    assert np.array_equal(np.asarray(col), ref_col)
    # seeds-first contract: frontier[:num_seeds] == seeds
    assert np.array_equal(np.asarray(frontier[:num_seeds]), seeds[:num_seeds])


def test_inverse_permutation_property():
    """Reference test_reindex.cu:187-247 analogue: q[p[i]] == i across sizes."""
    from quiver_tpu.ops.reindex import inverse_permutation

    for n in (1, 5, 100, 10000):
        p = np.random.default_rng(n).permutation(n).astype(np.int32)
        q = np.asarray(inverse_permutation(jnp.asarray(p)))
        assert np.array_equal(q[p], np.arange(n))
        # inverse of inverse is the original
        assert np.array_equal(
            np.asarray(inverse_permutation(jnp.asarray(q))), p
        )


def test_complete_permutation_property():
    """Partial prefix preserved verbatim; missing values appended ascending;
    result is a permutation (reference complete_permutation semantics,
    reindex.cu.hpp:277-300)."""
    from quiver_tpu.ops.reindex import complete_permutation

    rng = np.random.default_rng(0)
    for n, m in ((5, 3), (100, 40), (10000, 1234), (64, 0), (64, 64)):
        p = rng.permutation(n)[:m].astype(np.int32)
        full = np.asarray(complete_permutation(jnp.asarray(p), n))
        assert np.array_equal(np.sort(full), np.arange(n))  # is a permutation
        assert np.array_equal(full[:m], p)  # prefix preserved
        missing = np.setdiff1d(np.arange(n), p)
        assert np.array_equal(full[m:], missing)  # ascending completion


def test_complete_permutation_rejects_overlong():
    import pytest
    from quiver_tpu.ops.reindex import complete_permutation

    with pytest.raises(ValueError, match="longer"):
        complete_permutation(jnp.arange(10, dtype=jnp.int32), 5)


def test_resolve_dedup_platform_and_env(monkeypatch):
    """'auto' -> platform default (cpu->map here; tpu->scan by policy),
    QUIVER_DEDUP overrides, explicit names pass through untouched. The
    resolution is pinned ONCE per process (env-before-first-use — the
    resolver runs inside traced sampler bodies, graftlint env-at-trace);
    flipping the env mid-process requires a cache reset, which is exactly
    what a live model can NOT do."""
    from quiver_tpu.ops import reindex as R

    def reset():
        monkeypatch.setattr(R, "_forced_dedup", None)
        monkeypatch.setattr(R, "_auto_dedup", None)

    reset()
    monkeypatch.delenv("QUIVER_DEDUP", raising=False)
    assert R.resolve_dedup("sort") == "sort"  # explicit passthrough
    assert R.resolve_dedup("auto") == "map"  # tests pin JAX_PLATFORMS=cpu
    monkeypatch.setenv("QUIVER_DEDUP", "scan")
    # without a reset the pinned resolution stays — env after first use is
    # inert by contract
    assert R.resolve_dedup("auto") == "map"
    reset()
    assert R.resolve_dedup("auto") == "scan"
    import pytest

    reset()
    monkeypatch.setenv("QUIVER_DEDUP", "bogus")  # a typo'd FORCE must raise
    with pytest.raises(ValueError, match="QUIVER_DEDUP"):
        R.resolve_dedup("auto")
    with pytest.raises(ValueError, match="dedup"):
        R.resolve_dedup("hash")  # unknown explicit name rejected too
    reset()  # leave no pin for other tests


def test_sampler_dedup_auto_resolves(monkeypatch):
    from quiver_tpu import CSRTopo, GraphSageSampler

    monkeypatch.delenv("QUIVER_DEDUP", raising=False)
    rng = np.random.default_rng(0)
    topo = CSRTopo(edge_index=rng.integers(0, 50, (2, 400)).astype(np.int64))
    s = GraphSageSampler(topo, [3], seed_capacity=16)
    assert s.dedup == "map"  # resolved, never the literal "auto"
    out = s.sample(np.arange(16))
    assert int(out.n_count) >= 16
    import pytest

    with pytest.raises(ValueError, match="dedup"):
        GraphSageSampler(topo, [3], dedup="hash")
