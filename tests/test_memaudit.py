"""graftmem acceptance: the static per-device memory estimate reconciles
with XLA's buffer assignment on the audit mesh, every registered program
declares (and fits) an ``hbm_budget``, the CostModel carries the peaks
into controller-facing predictions, and the registry's ``sources``
claims actually cover the modules its builders trace.

Fast lane compiles exactly one tiny target (routed_gather) for the
arg/out exactness check; the full-registry XLA tolerance sweep — the
only part that compiles all fourteen programs — rides the slow lane.
"""

import ast
import pathlib

import pytest

from quiver_tpu.control.cost import CostModel
from quiver_tpu.tools.audit import mem
from quiver_tpu.tools.audit.audit_targets import REGISTRY, build

_ROOT = pathlib.Path(__file__).resolve().parent.parent

# graftmem's estimate is a fusion-blind upper-shape: XLA fuses
# intermediates away below it and pads/aligns small buffers above it.
# The band is the measured envelope across the 14-target registry on
# jax's CPU backend (min 0.39 routed_gather, max 1.96
# mmap_tiered_gather), with margin so only a real accounting regression
# trips it.
_PEAK_RATIO_BAND = (0.33, 2.2)

# Targets whose argument byte total reproduces XLA exactly (the rest
# differ by XLA's sub-8-byte scalar padding on epoch/metrics operands).
_ARG_EXACT = frozenset({
    "routed_gather", "sample_hop", "serve_forward", "serve_sample",
    "pallas_fused_interp", "serve_fleet_forward", "mmap_tiered_gather",
})


def _estimate(name):
    built = build(name)
    return mem.estimate_peak(built.jaxpr, built.mlir), built


def test_arg_and_out_bytes_exact_on_routed_gather():
    """Fast-lane exactness anchor: on the canonical routed gather the
    static accounting reproduces XLA's argument AND output totals to the
    byte, and the peak lands inside the stated band."""
    est, _ = _estimate("routed_gather")
    stats = mem.xla_memory_stats(REGISTRY["routed_gather"])
    assert stats is not None, "CPU backend stopped exposing memory_analysis"
    assert est.arg_bytes == stats["argument_bytes"]
    assert est.out_bytes == stats["output_bytes"]
    lo, hi = _PEAK_RATIO_BAND
    assert lo <= est.peak_bytes / stats["peak_bytes"] <= hi


@pytest.mark.slow
def test_peak_estimate_tracks_xla_across_registry():
    """The acceptance tolerance: every registry program's static peak is
    within the stated band of XLA's buffer-assignment peak; argument
    bytes are exact on the listed targets and output bytes are exact on
    ALL of them (the tuple-table correction included)."""
    lo, hi = _PEAK_RATIO_BAND
    for name, target in REGISTRY.items():
        est, _ = _estimate(name)
        stats = mem.xla_memory_stats(target)
        assert stats is not None, name
        ratio = est.peak_bytes / max(stats["peak_bytes"], 1)
        assert lo <= ratio <= hi, (
            f"{name}: est {est.peak_bytes} vs xla {stats['peak_bytes']} "
            f"(ratio {ratio:.2f} outside {lo}..{hi})")
        assert est.out_bytes == stats["output_bytes"], name
        if name in _ARG_EXACT:
            assert est.arg_bytes == stats["argument_bytes"], name
        # the donation discount must match XLA's aliased bytes when the
        # program donates at all
        if target.meta.get("donation") == "epoch_state":
            assert est.aliased_bytes == stats["alias_bytes"] > 0


def test_every_target_declares_hbm_budget():
    """Acceptance: no registry program enters unpriced — the
    peak-hbm-budget rule treats a missing budget as a finding, so this
    is the same invariant checked without building anything."""
    for name, target in REGISTRY.items():
        budget = target.meta.get("hbm_budget")
        assert isinstance(budget, int) and budget > 0, (
            f"{name} has no usable hbm_budget: {budget!r}")


def test_fleet_target_joined_warm_from_aot():
    """Satellite target contract: the serve_fleet_forward builder grows
    the fleet by a warm replica and records its cold-start ledger —
    every executable loaded from the AOT cache, zero compiles."""
    build("serve_fleet_forward")
    warm = REGISTRY["serve_fleet_forward"].meta["warm_join"]
    assert warm["loaded"] > 0
    assert warm["compiled"] == 0


def test_cost_model_hbm_surface():
    model = CostModel(local_len=16, num_shards=2)
    assert not model.hbm_calibrated
    assert model.calibrate_hbm({}) is False
    assert not model.hbm_calibrated

    assert model.calibrate_hbm({"serve_forward": 9384}) is True
    assert model.hbm_calibrated
    fits = model.predict_hbm("serve_forward", budget_bytes=24 * 1024)
    assert fits == {"target": "serve_forward", "known": True,
                    "peak_bytes": 9384, "budget_bytes": 24 * 1024,
                    "headroom_bytes": 24 * 1024 - 9384, "fits": True}
    tight = model.predict_hbm("serve_forward", budget_bytes=9000)
    assert tight["fits"] is False and tight["headroom_bytes"] < 0
    unknown = model.predict_hbm("nope", budget_bytes=1)
    assert unknown["known"] is False and unknown["fits"] is None
    # without a budget the peak is reported but nothing is judged
    bare = model.predict_hbm("serve_forward")
    assert bare["peak_bytes"] == 9384 and bare["fits"] is None


# slow lane: the budget table builds (traces) all 14 registry programs;
# the CI memory-audit job runs this file unfiltered on every push, and
# the peak-hbm-budget rule gates the same headroom in the audit job —
# tier-1 keeps the meta-only budgets-declared check above
@pytest.mark.slow
def test_peak_table_budgets_all_in_headroom():
    """The CLI/scoreboard budget table: every row priced, every row in
    positive headroom (the repo's own programs fit their declared
    budgets), and the rendered table carries one line per target."""
    rows = mem.peak_table()
    assert {r["target"] for r in rows} == set(REGISTRY)
    for r in rows:
        assert r["hbm_budget"] is not None, r["target"]
        assert r["headroom_bytes"] >= 0, r
    rendered = mem.format_peak_table(rows)
    assert len(rendered.splitlines()) == len(rows) + 1


# -- sources coverage (the --changed contract) --------------------------------

# Modules a builder's import closure reaches that no target lists as a
# source, each with a reason the --changed contract tolerates it:
# host-side construction/observability/controller code that shapes no
# lowered program (the traced surfaces — cost.py, obs/registry.py —
# ARE in sources), and the resilience/utils layers no registry program
# exercises. quiver_tpu/tools/** is excluded structurally: editing the
# auditor re-audits every target already (runner.select_targets).
_SOURCES_EXEMPT = frozenset({
    "quiver_tpu/control/controller.py",
    "quiver_tpu/control/freq.py",
    "quiver_tpu/core/config.py",
    "quiver_tpu/core/memory.py",
    "quiver_tpu/core/sharded_topology.py",
    "quiver_tpu/obs/endpoint.py",
    "quiver_tpu/obs/export.py",
    "quiver_tpu/obs/timeline.py",
    "quiver_tpu/obs/tracing.py",
    "quiver_tpu/ops/reindex.py",
    "quiver_tpu/resilience/elastic.py",
    "quiver_tpu/resilience/faults.py",
    "quiver_tpu/resilience/guard.py",
    "quiver_tpu/resilience/integrity.py",
    "quiver_tpu/serving/coalesce.py",
    "quiver_tpu/utils/checkpoint.py",
    "quiver_tpu/utils/reorder.py",
    "quiver_tpu/utils/trace.py",
})


def _module_file(parts):
    p = _ROOT.joinpath(*parts).with_suffix(".py")
    if p.is_file():
        return p
    p = _ROOT.joinpath(*parts) / "__init__.py"
    return p if p.is_file() else None


def _imports_of(path):
    """quiver_tpu module files imported anywhere in ``path`` — including
    the function-level imports the lazy builders use."""
    pkg = list(path.relative_to(_ROOT).parts[:-1])
    out = set()
    for node in ast.walk(ast.parse(path.read_text())):
        if isinstance(node, ast.Import):
            for alias in node.names:
                parts = alias.name.split(".")
                if parts[0] == "quiver_tpu":
                    f = _module_file(parts)
                    if f:
                        out.add(f)
        elif isinstance(node, ast.ImportFrom):
            if node.level:  # relative: level 1 is the containing package
                base = pkg[:len(pkg) - (node.level - 1)]
            else:
                base = []
            full = base + (node.module.split(".") if node.module else [])
            if not full or full[0] != "quiver_tpu":
                continue
            for alias in node.names:
                f = _module_file(full + [alias.name]) or _module_file(full)
                if f:
                    out.add(f)
    return out


def _builder_import_closure():
    seed = _ROOT / "quiver_tpu/tools/audit/audit_targets.py"
    seen, todo = set(), [seed]
    while todo:
        p = todo.pop()
        if p in seen:
            continue
        seen.add(p)
        todo.extend(_imports_of(p) - seen)
    rels = {str(p.relative_to(_ROOT)) for p in seen}
    return {r for r in rels
            if not r.endswith("__init__.py")
            and not r.startswith("quiver_tpu/tools/")}


def test_builder_import_closure_covered_by_sources():
    """Every quiver_tpu module a registry builder (transitively) traces
    appears in some target's ``sources`` — so ``--changed`` re-audits
    the right programs — except the explicitly reasoned exemptions. The
    newer subsystems must be covered, not exempted."""
    closure = _builder_import_closure()
    union = {s for t in REGISTRY.values() for s in t.sources
             if s.startswith("quiver_tpu/")}

    missing = closure - union - _SOURCES_EXEMPT
    assert not missing, (
        f"builder-traced modules invisible to --changed: {sorted(missing)}; "
        f"add them to a target's sources or (with a reason) to "
        f"_SOURCES_EXEMPT")
    # exemptions must not rot: anything now covered leaves the list
    stale = _SOURCES_EXEMPT & union
    assert not stale, f"exempt modules now in sources: {sorted(stale)}"
    # the PR 16-18 subsystems are load-bearing sources, never exemptions
    required = {
        "quiver_tpu/ops/election.py", "quiver_tpu/serving/aot.py",
        "quiver_tpu/serving/fleet.py", "quiver_tpu/ooc/store.py",
        "quiver_tpu/ooc/format.py", "quiver_tpu/ooc/stager.py",
    }
    assert required <= union
    assert not required & _SOURCES_EXEMPT
