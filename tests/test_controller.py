"""quiver-ctl control plane (quiver_tpu/control): differential tests.

Fast lane: two-sided tuner units with the no-oscillation regressions
(the legacy tuners' one-sided failure modes, pinned on a constant
workload), the frozen-decision bitwise-parity differential (an attached-
but-frozen controller must not change one bit of the loss trajectory,
params, or telemetry), repin-vs-dense-oracle exactness (f32 AND int8),
and the audited JSONL decision trail.

Slow lane: the skewed-trace placement differential (heat != degree —
measured-frequency L0 placement must beat the degree prefix at the same
budget) and the serve re-tier drill (serving traffic feeds the same
sketch, a repin re-tiers under the live server, and controller state
survives a streaming commit + refresh()).
"""

import json

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from quiver_tpu import (
    AlphaTuner,
    CacheController,
    CSRTopo,
    DeltaBatch,
    GraphSageSampler,
    InferenceServer,
    SplitTuner,
    StreamingGraph,
    VersionMismatchError,
)
from quiver_tpu.control.cost import CostModel, routed_lanes_per_hop
from quiver_tpu.control.freq import FreqSketch
from quiver_tpu.feature.shard import ShardedFeature
from quiver_tpu.models.sage import GraphSAGE
from quiver_tpu.obs.export import read_jsonl
from quiver_tpu.obs.registry import (
    CTRL_ALPHA_CHANGES,
    CTRL_DECISIONS,
    ROUTED_OVERFLOW,
    TIER_HITS,
)
from quiver_tpu.parallel.mesh import make_mesh
from quiver_tpu.parallel.trainer import DistributedTrainer


def _graph(n=400, e=3000, seed=5):
    rng = np.random.default_rng(seed)
    ei = np.stack([rng.integers(0, n, e), rng.integers(0, n, e)])
    return CSRTopo(edge_index=ei)


def _oracle(feat, ids):
    ref = feat[np.where(ids >= 0, ids, 0)].copy()
    ref[ids < 0] = 0
    return ref


ROW_B = 8 * 4  # float32 rows, dim 8


# -- tuner units: two-sided + no-oscillation ---------------------------------


def test_alpha_tuner_grows_and_shrinks():
    t = AlphaTuner(shrink_after=2, floor=0.25)
    # grow doubles on overflow, capped at the feature-axis ceiling
    assert t.decide(overflow=5, alpha=1.0, ceiling=4.0) == 2.0
    assert t.decide(overflow=5, alpha=4.0, ceiling=4.0) is None
    # the legacy tuner stopped here: alpha never came back down.
    # Sustained slack now halves it, bounded by the floor.
    assert t.decide(0, 4.0, 4.0) is None   # 1 clean batch: not yet
    assert t.decide(0, 4.0, 4.0) == 2.0    # 2 consecutive: shrink
    assert t.decide(0, 2.0, 4.0) is None
    assert t.decide(0, 2.0, 4.0) == 1.0
    assert t.decide(0, 0.5, 4.0) is None
    assert t.decide(0, 0.5, 4.0) == 0.25   # the floor itself is reachable
    assert t.decide(0, 0.25, 4.0) is None
    assert t.decide(0, 0.25, 4.0) is None  # below the floor: never


def test_alpha_tuner_no_oscillation_on_constant_workload():
    """A punished shrink raises the floor: a CONSTANT workload with
    intermittent overflow converges to a fixed alpha instead of cycling
    shrink/regrow forever (the naive two-sided tuner's failure mode)."""
    t = AlphaTuner(shrink_after=2, floor=0.25)
    alpha, trace = 2.0, []
    # overflow fires whenever alpha dips below the workload's true need
    for _ in range(12):
        overflow = 5 if alpha < 2.0 else 0
        new = t.decide(overflow, alpha, ceiling=8.0)
        if new is not None:
            alpha = new
        trace.append(alpha)
    # one probe shrink (2 -> 1), punished, floor pinned at 2 — the tail
    # must be flat at the converged value with no further probes
    assert t.floor == 2.0
    assert trace[-6:] == [2.0] * 6, trace
    assert trace.count(1.0) == 1  # exactly one punished probe, ever


def test_split_tuner_reversal_dead_band():
    t = SplitTuner(confirm=2)
    grow = dict(h0=10, h1=20)     # hit mass just beyond the boundary
    shrink = dict(h0=1, h1=100)   # L0 serving under 1/8 of device hits
    assert t.decide(rep_rows=16, ceiling=64, **shrink) == 8
    # same direction stays immediate
    assert t.decide(rep_rows=8, ceiling=64, **shrink) == 4
    # reversal (grow after shrink) needs the signal twice in a row
    assert t.decide(**grow, rep_rows=4, ceiling=64) is None
    assert t.decide(**grow, rep_rows=4, ceiling=64) == 8
    # a lone noisy batch between confirmations resets the pending count
    assert t.decide(h0=1, h1=100, rep_rows=8, ceiling=64) is None  # 1st
    assert t.decide(h0=50, h1=50, rep_rows=8, ceiling=64) is None  # calm
    assert t.decide(h0=1, h1=100, rep_rows=8, ceiling=64) is None  # 1st again
    assert t.decide(h0=1, h1=100, rep_rows=8, ceiling=64) == 4     # 2nd
    # reset() forgets direction history (manual resplit)
    t.reset()
    assert t.decide(**grow, rep_rows=4, ceiling=64) == 8  # immediate again


def test_split_tuner_no_oscillation_at_budget_ceiling():
    """The legacy rule pair could alternate grow/shrink every batch on a
    workload sitting near the h1 == h0 edge at the ceiling; the reversal
    dead-band caps direction changes on a CONSTANT alternating signal."""
    t = SplitTuner(confirm=2)
    rep, moves = 32, []
    for i in range(12):
        h0, h1 = (1, 100) if i % 2 == 0 else (8, 10)  # shrink / grow sig
        new = t.decide(h0, h1, rep, ceiling=64)
        if new is not None:
            moves.append((rep, new))
            rep = new
    # alternating signals never confirm a reversal: after the first
    # shrink run the boundary is monotone down, not ping-ponging
    assert all(b < a for a, b in moves), moves


def test_cost_model_lanes_and_calibration():
    m = routed_lanes_per_hop(local_len=96, num_shards=4, alpha=2.0)
    assert m["cap"] == 48 and m["lanes_per_hop"] == 192
    assert m["lanes_per_hop_uncapped"] == 384
    # measured L0 hit rate tightens the planned cap
    tighter = routed_lanes_per_hop(96, 4, 2.0, h0=0.5)
    assert tighter["cap"] == 24
    sk = FreqSketch(400, num_bins=100)  # 4 rows per bin
    hist = np.zeros(100, np.int64)
    hist[:10] = 5  # all heat mass on translated rows [0, 40)
    sk.observe_histogram(hist)
    cm = CostModel(local_len=96, num_shards=4)
    out = cm.predict(sk, rep_rows=40, hot_rows=100, alpha=2.0)
    assert out["hit_rep"] == pytest.approx(1.0)
    assert "est_step_s" not in out  # not calibrated yet


# -- frozen-decision bitwise parity ------------------------------------------


def _trainer_run(controller):
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 4, 400)
    feat = np.eye(4, dtype=np.float32)[labels] * 2.0
    feat += rng.normal(scale=0.8, size=(400, 4)).astype(np.float32)
    ei = np.stack([rng.integers(0, 400, 4000), rng.integers(0, 400, 4000)])
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    mesh = make_mesh(data=2, feature=4)
    feature = ShardedFeature(
        mesh, device_cache_size="1G", csr_topo=CSRTopo(edge_index=ei),
        replicate_budget=64 * 4 * 4,
    ).from_cpu_tensor(feat[:n])
    trainer = DistributedTrainer(
        mesh, GraphSageSampler(topo, [5, 5], seed=3), feature,
        GraphSAGE(hidden=16, num_classes=4, num_layers=2),
        optax.adam(5e-3), local_batch=32, seed_sharding="all",
        routed_alpha=1.0, controller=controller,
    )
    params, opt = trainer.init(jax.random.PRNGKey(0))
    labels_dev = jnp.asarray(labels[:n].astype(np.int32))
    srng = np.random.default_rng(0)
    losses = []
    for step in range(3):
        seeds = srng.integers(0, n, trainer.global_batch)
        params, opt, loss = trainer.step(
            params, opt, seeds, labels_dev, jax.random.PRNGKey(step)
        )
        losses.append(float(loss))
    telemetry = {
        name: np.asarray(trainer.metrics.snapshot(name).numpy)
        for name in (ROUTED_OVERFLOW, TIER_HITS)
    }
    return losses, params, telemetry


@pytest.mark.slow  # 19s; CI controller-smoke runs this by node id every push
def test_frozen_controller_bitwise_parity():
    """An attached-but-frozen controller observes everything and decides
    nothing: loss trajectory, final params, and the standard telemetry
    must be BITWISE identical to running with no controller at all."""
    base_losses, base_params, base_tel = _trainer_run(None)
    ctl = CacheController(frozen=True)
    losses, params, tel = _trainer_run(ctl)
    assert losses == base_losses
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        base_params, params,
    )
    for name in base_tel:
        np.testing.assert_array_equal(base_tel[name], tel[name])
    # it DID observe (heat histogram + host-visible seed ids)...
    assert ctl.sketch is not None and ctl.sketch.total_mass > 0
    assert ctl.stats()["observed"] > 0
    # ...and decided nothing
    assert ctl.stats()["decisions"] == 0 and not ctl.decisions


# -- repin vs the dense oracle -----------------------------------------------


def test_repin_matches_dense_oracle_f32():
    """An arbitrary (non-degree) hot set repinned into L0: tier sizes
    unchanged, pinned rows at the front of the translated space, and both
    gather paths still bitwise equal to the dense numpy oracle."""
    topo = _graph()
    n = topo.node_count
    feat = np.random.default_rng(1).normal(size=(n, 8)).astype(np.float32)
    mesh = make_mesh(data=2, feature=4)
    store = ShardedFeature(
        mesh, device_cache_size=(n // 4 // 4) * ROW_B, csr_topo=topo,
        replicate_budget=16 * ROW_B,
    ).from_cpu_tensor(feat)
    assert store.rep_rows == 16
    rng = np.random.default_rng(2)
    hot = rng.choice(n, 40, replace=False)  # arbitrary, not degree-sorted
    rows = np.concatenate([hot, hot[:5]])   # dups keep first occurrence
    sizes = (store.rep_rows, store.hot_rows)
    v0 = store.version
    store.repin(rows)
    assert store.version == v0 + 1
    assert (store.rep_rows, store.hot_rows) == sizes  # membership only
    order = np.asarray(store.feature_order)
    np.testing.assert_array_equal(order[hot], np.arange(hot.size))
    ids = rng.integers(0, n, 96).astype(np.int32)
    ids[:4] = -1
    ref = _oracle(feat, ids)
    assert np.array_equal(np.asarray(store[jnp.asarray(ids)]), ref)
    assert np.array_equal(
        np.asarray(store.gather(jnp.asarray(ids), routed=True)), ref
    )
    with pytest.raises(ValueError):
        store.repin([n])  # out-of-range ids must not silently drop


def test_repin_matches_dense_oracle_int8():
    """int8: rows move WITH their dequant scales, so a repin must not
    change a single output bit of the dequantized gathers."""
    topo = _graph(n=300, e=2000, seed=8)
    n = topo.node_count
    feat = np.random.default_rng(8).normal(size=(n, 16)).astype(np.float32)
    mesh = make_mesh(data=2, feature=4)
    store = ShardedFeature(
        mesh, device_cache_size="4K", csr_topo=topo, dtype="int8",
        replicate_budget=16 * 16,
    ).from_cpu_tensor(feat)
    assert store.rep_rows > 0
    ids = np.random.default_rng(9).integers(0, n, 64).astype(np.int32)
    before = np.asarray(store[jnp.asarray(ids)])
    hot = np.unique(ids)[:32][::-1].copy()  # reversed: genuinely re-ordered
    store.repin(hot)
    after = np.asarray(store[jnp.asarray(ids)])
    routed = np.asarray(store.gather(jnp.asarray(ids), routed=True))
    assert np.array_equal(after, before)
    assert np.array_equal(routed, before)


# -- audited decisions -------------------------------------------------------


def test_decision_audit_jsonl_round_trip(tmp_path):
    log = tmp_path / "decisions.jsonl"
    ctl = CacheController(decision_log=str(log))
    assert ctl.decide_alpha(overflow=7, alpha=1.0, ceiling=4.0) == 2.0
    for _ in range(3):
        assert ctl.decide_alpha(0, 2.0, 4.0) is None  # inside the band
    assert ctl.decide_alpha(0, 2.0, 4.0) == 1.0  # 4 consecutive clean
    recs = read_jsonl(str(log))
    assert [r.name for r in recs] == [CTRL_ALPHA_CHANGES] * 2
    lines = [json.loads(s) for s in log.read_text().splitlines()]
    assert lines[0]["decision"] == "alpha"
    assert lines[0]["direction"] == "grow" and lines[1]["direction"] == "shrink"
    assert ctl.stats()["alpha_changes"] == 2 and ctl.stats()["decisions"] == 2
    assert ctl.metrics.snapshot(CTRL_DECISIONS).last() == 2


def test_streaming_degree_prior_feeds_controller():
    """note_degree_update (the PR 8 streaming hook) lands in the attached
    controller's sketch as a prior instead of dead-ending in the legacy
    auto-split region cache."""
    topo = _graph(n=200, e=1200, seed=3)
    mesh = make_mesh(data=2, feature=4)
    feat = np.random.default_rng(3).normal(size=(200, 8)).astype(np.float32)
    store = ShardedFeature(
        mesh, device_cache_size="1M", csr_topo=topo,
        replicate_budget=8 * ROW_B,
    ).from_cpu_tensor(feat)
    ctl = CacheController().attach(store)
    assert store._controller is ctl and ctl.sketch is not None
    assert not ctl.sketch.state()["hitters"]
    store.note_degree_update(np.arange(200, dtype=np.int64))
    hitters = ctl.sketch.state()["hitters"]
    assert hitters and max(hitters) == 199  # top-degree ids seeded


# -- skewed-trace placement differential (slow) ------------------------------


@pytest.mark.slow
def test_measured_placement_beats_degree_prefix_on_skewed_trace():
    """heat != degree: when the traffic concentrates on LOW-degree rows,
    the controller's measured-frequency repin must serve strictly more of
    the trace from L0 than the static degree-prefix placement at the SAME
    replicate budget — the tentpole's headline claim."""
    topo = _graph(n=400, e=3000, seed=5)
    n = topo.node_count
    feat = np.random.default_rng(0).normal(size=(n, 8)).astype(np.float32)
    mesh = make_mesh(data=2, feature=4)
    rep_rows = 32
    store = ShardedFeature(
        mesh, device_cache_size="1M", csr_topo=topo,
        replicate_budget=rep_rows * ROW_B,
    ).from_cpu_tensor(feat)
    assert store.rep_rows == rep_rows
    # hot set = the LOWEST-degree rows: the degree prefix can't see it
    cold_by_degree = np.argsort(topo.degree.astype(np.int64),
                                kind="stable")[:rep_rows]
    rng = np.random.default_rng(7)
    trace = rng.choice(cold_by_degree, size=4000).astype(np.int64)
    trace = np.concatenate([trace, rng.integers(0, n, 1000)])  # 20% noise

    def l0_hits(s):
        return int((np.asarray(s.feature_order)[trace] < s.rep_rows).sum())

    static = l0_hits(store)  # degree-prefix placement
    ctl = CacheController().attach(store)
    ctl.observe_ids(trace)
    assert ctl.maybe_repin(store) is True
    measured = l0_hits(store)
    assert measured > static, (measured, static)
    # the measured placement catches essentially the whole skewed mass
    assert measured >= int(0.75 * trace.size)
    assert ctl.stats()["repins"] == 1
    # exactness survives the re-tier
    ids = rng.integers(0, n, 96).astype(np.int32)
    assert np.array_equal(np.asarray(store[jnp.asarray(ids)]),
                          _oracle(feat, ids))


# -- serve re-tier drill (slow) ----------------------------------------------


@pytest.mark.slow
def test_serve_retier_drill_and_state_survives_commit():
    """Serving traffic feeds the SAME sketch: a skewed serve workload
    re-tiers the live store (responses stay oracle-exact across the
    repin), and the controller's host-side state survives a streaming
    commit + refresh() untouched."""
    from quiver_tpu.parallel.train import empty_adjs, init_model

    topo = _graph(n=240, e=1600, seed=4)
    n = topo.node_count
    dim = 8
    feat = np.random.default_rng(4).normal(size=(n, dim)).astype(np.float32)
    mesh = make_mesh(data=2, feature=4)
    rep_rows = 16
    store = ShardedFeature(
        mesh, device_cache_size="1M", csr_topo=topo,
        replicate_budget=rep_rows * dim * 4,
    ).from_cpu_tensor(feat)
    sampler = GraphSageSampler(topo, [4, 3], seed=1)
    model = GraphSAGE(hidden=16, num_classes=5, num_layers=2)
    adjs = empty_adjs([4, 3], batch=4, node_count=n)
    params = init_model(
        model, jax.random.PRNGKey(1),
        np.zeros((adjs[0].size[0], dim), np.float32), adjs,
    )
    # the seam under test is serve -> sketch -> repin + state survival,
    # so the drill disables the other two knobs' dynamics: boundary
    # moves are held (the cold-skewed trace would legitimately shrink
    # the unearning L0 away before the epoch-end repin gets its turn)
    # and the hysteresis band is dropped (the sampled NEIGHBOR traffic
    # dilutes the seed skew; the dead-band default is unit-tested above)
    class HeldSplit(SplitTuner):
        def decide(self, *a, **k):
            return None

    ctl = CacheController(repin_min_gain=0.005, split_tuner=HeldSplit())
    server = InferenceServer(sampler, model, params, store, max_batch=4,
                             seed=3, controller=ctl)
    assert store._controller is ctl  # attached through the server
    server.warmup()
    # hammer FOUR lowest-degree nodes in every batch: they reach the
    # maximum per-batch count while the degree-hot neighbors cannot
    cold = np.argsort(topo.degree.astype(np.int64), kind="stable")[:4]
    nodes = np.tile(cold, 24)
    before = server.serve(nodes[:8])
    for r in before:
        np.testing.assert_array_equal(r.result, server.oracle(r.node, r.seq))
    server.serve(nodes[8:])
    assert ctl.sketch.observed > 0
    # epoch boundary: the serve-fed sketch re-tiers the store
    v0 = store.version
    ctl.end_epoch(store)
    assert ctl.stats()["repins"] == 1 and store.version == v0 + 1
    # feature reads are live per batch: serving continues, oracle-exact
    after = server.serve(nodes[:8])
    for r in after:
        np.testing.assert_array_equal(r.result, server.oracle(r.node, r.seq))
    # streaming commit -> stale ladder -> refresh(); controller state
    # (sketch mass, decision trail) is host-side and survives untouched
    observed, decisions = ctl.sketch.observed, list(ctl.decisions)
    sg = StreamingGraph(topo)
    src = np.repeat(np.arange(n), topo.degree)
    dst = np.asarray(topo.indices)[: src.size]
    live = set((src * n + dst).tolist())
    k = next(k for k in range(n * n) if k not in live)
    assert sg.ingest(DeltaBatch(edge_inserts=np.array([[k // n], [k % n]])))
    sg.commit()
    with pytest.raises(VersionMismatchError):
        server.pump(force=True)
    server.refresh()
    assert ctl.sketch.observed == observed
    assert ctl.decisions == decisions
    final = server.serve(nodes[:4])
    for r in final:
        np.testing.assert_array_equal(r.result, server.oracle(r.node, r.seq))


# -- FreqSketch eviction boundaries (satellite) -------------------------------


def test_sketch_capacity_exactly_k_never_exceeded():
    """At capacity exactly K, a new id evicts the minimum and INHERITS
    its count (SpaceSaving's overestimate-never-underestimate), and the
    hitter set never grows past K."""
    sk = FreqSketch(100, top_k=3)
    sk.observe_ids([10] * 5 + [11] * 3 + [12] * 2)  # fills exactly K=3
    assert len(sk.state()["hitters"]) == 3
    sk.observe_ids([13])  # K+1th distinct id
    h = sk.state()["hitters"]
    assert len(h) == 3  # capacity held
    assert 12 not in h  # the minimum (count 2) was evicted
    assert h[13] == 2 + 1  # newcomer inherited the victim's count
    assert h[10] == 5 and h[11] == 3  # survivors untouched


def test_sketch_equal_count_tie_breaks_by_id():
    """top_rows orders equal counts by ascending node id (the sort key
    is (-count, id)) — deterministic repin sets under uniform traffic."""
    sk = FreqSketch(100, top_k=8)
    sk.observe_ids([7, 3, 9, 1])  # all count 1
    np.testing.assert_array_equal(sk.top_rows(4), [1, 3, 7, 9])
    sk.observe_ids([9])  # 9 pulls ahead
    np.testing.assert_array_equal(sk.top_rows(4), [9, 1, 3, 7])
    # eviction respects the same floor: min of equal counts is a valid
    # victim and the set stays exactly top_k wide
    sk2 = FreqSketch(100, top_k=2)
    sk2.observe_ids([5, 6])
    sk2.observe_ids([4])
    assert len(sk2.state()["hitters"]) == 2
    assert sk2.state()["hitters"][4] == 2  # inherited 1 + own 1


def test_sketch_degree_prior_decays_to_zero_under_no_traffic():
    """A degree prior seeds the hitter set at low mass, and sustained
    zero traffic EMA-decays it toward zero — stale priors cannot pin
    rows forever once real traffic (or its absence) disagrees."""
    sk = FreqSketch(100, top_k=16, decay=0.5)
    sk.observe_prior(np.arange(100, dtype=np.float64))
    before = sum(sk.state()["hitters"].values())
    assert before > 0
    assert sk.state()["hitters"][99] == 1.0  # scaled by the max weight
    for _ in range(40):
        sk.decay()
    after = sum(sk.state()["hitters"].values())
    assert after < before * 1e-10  # geometric collapse, never negative
    assert after >= 0
    # the decayed prior no longer outranks ONE real observed hit
    sk.observe_ids([0])
    assert sk.top_rows(1)[0] == 0
