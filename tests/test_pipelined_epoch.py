"""Software-pipelined epoch tests (``DistributedTrainer(pipeline_depth=1)``).

Fast lane: constructor validation, the ``finalize(names=...)`` subset
contract the split halves rely on, and the headline bit-parity
differential — pipelined vs serial epoch_scan on a 2-device mesh with
routed seed exchange, comparing losses, final params, and the per-step
routed-overflow / tier-hit telemetry bitwise.
Slow lane: the resilience-seam differentials — checkpoint-chunked
pipelined runs (chunk boundaries re-issue the carried batch), a
killed-and-resumed pipelined run against the uninterrupted serial
oracle, and nonfinite_guard + injected-NaN FaultPlan under depth=1.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from quiver_tpu import CSRTopo, FaultPlan, GraphSageSampler, Preemption
from quiver_tpu.feature.shard import ShardedFeature
from quiver_tpu.models.sage import GraphSAGE
from quiver_tpu.obs.registry import (
    GUARD_SKIPPED,
    PIPELINE_REISSUES,
    MetricsRegistry,
    MetricsTape,
)
from quiver_tpu.parallel.mesh import make_mesh
from quiver_tpu.parallel.trainer import DistributedTrainer


def _tree_bitwise_equal(a, b) -> bool:
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(
            np.asarray(x).view(np.uint32), np.asarray(y).view(np.uint32)
        )
        for x, y in zip(la, lb)
    )


def _labeled_graph(n=256, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, classes, n)
    feat = np.eye(classes, dtype=np.float32)[labels] * 2.0
    feat += rng.normal(scale=0.8, size=(n, classes)).astype(np.float32)
    rows, cols = [], []
    for c in range(classes):
        members = np.where(labels == c)[0]
        rows.extend(rng.choice(members, 6 * len(members)))
        cols.extend(rng.choice(members, 6 * len(members)))
    ei = np.stack([np.asarray(rows), np.asarray(cols)])
    return ei, feat, labels


def _build_trainer(pipeline_depth=0, guard=False, plan=None,
                   checkpoint_dir=None, checkpoint_every=0):
    """Small 8-device trainer mirroring the resilience fixtures, with the
    pipeline knob exposed."""
    rng = np.random.default_rng(0)
    n = 96
    topo = CSRTopo(
        edge_index=rng.integers(0, n, size=(2, 800)).astype(np.int64)
    )
    feat = rng.normal(size=(n, 8)).astype(np.float32)
    mesh = make_mesh(data=2, feature=4)
    store = ShardedFeature(
        mesh, device_cache_size=n * 8, csr_topo=topo
    ).from_cpu_tensor(feat)
    sampler = GraphSageSampler(topo, [3, 2], seed=0, seed_capacity=8)
    model = GraphSAGE(hidden=8, num_classes=4, num_layers=2)
    kw = {}
    if checkpoint_dir is not None:
        kw = dict(checkpoint_dir=checkpoint_dir,
                  checkpoint_every=checkpoint_every)
    trainer = DistributedTrainer(
        mesh, sampler, store, model, optax.sgd(1e-2), local_batch=8,
        seed_sharding="all", nonfinite_guard=guard, fault_plan=plan,
        pipeline_depth=pipeline_depth, **kw
    )
    params, opt = trainer.init(jax.random.PRNGKey(0))
    labels = jnp.asarray(rng.integers(0, 4, n).astype(np.int32))
    return trainer, params, opt, labels


# -- constructor / registry contracts (fast) ----------------------------------


def test_pipeline_depth_validation():
    with pytest.raises(ValueError, match="pipeline_depth"):
        _build_trainer(pipeline_depth=2)
    with pytest.raises(ValueError, match="pipeline_depth"):
        _build_trainer(pipeline_depth=-1)


def test_finalize_names_subset_and_dropped_fed_guard():
    """The split halves finalize disjoint name subsets; a subset that
    would silently drop a FED metric must raise instead (a zero-filled
    half-merge would corrupt per-step telemetry)."""
    reg = MetricsRegistry()
    reg.counter("pipe.a", unit="x")
    reg.counter("pipe.b", unit="x")
    tape = MetricsTape(reg)
    tape.add("pipe.a", jnp.int32(3))
    out = tape.finalize(names=("pipe.a",))
    assert set(out) == {"pipe.a"} and int(out["pipe.a"]) == 3
    tape2 = MetricsTape(reg)
    tape2.add("pipe.a", jnp.int32(1))
    with pytest.raises(ValueError, match="drop fed"):
        tape2.finalize(names=("pipe.b",))
    # names not fed still zero-fill (the serial contract, subsetted)
    tape3 = MetricsTape(reg)
    out3 = tape3.finalize(names=("pipe.b",))
    assert set(out3) == {"pipe.b"} and int(out3["pipe.b"]) == 0


# -- headline bit-parity differential (the pipeline-smoke CI step) ------------


@pytest.mark.slow  # 16s; CI pipeline-smoke runs this by node id every push
def test_pipelined_epoch_bitwise_matches_serial():
    """Acceptance: pipeline_depth=1 epoch_scan reproduces the serial
    scan's losses, final params, and per-step routed-overflow / tier-hit
    vectors BITWISE on a 2-device routed mesh — the one-step skew changes
    the schedule, never the math."""
    ei, feat, labels_np = _labeled_graph()
    topo = CSRTopo(edge_index=ei)
    n = topo.node_count
    labels = jnp.asarray(labels_np[:n].astype(np.int32))
    model = GraphSAGE(hidden=16, num_classes=4, num_layers=2)
    results = {}
    for depth in (0, 1):
        mesh = make_mesh(n_devices=2, data=1, feature=2)
        sampler = GraphSageSampler(topo, [5, 5], seed=3)
        store = ShardedFeature(
            mesh, device_cache_size=n * 4 * 4 // 2
        ).from_cpu_tensor(feat[:n])
        trainer = DistributedTrainer(
            mesh, sampler, store, model, optax.adam(5e-3), local_batch=32,
            seed_sharding="all", routed_alpha=1.5, pipeline_depth=depth,
        )
        params, opt = trainer.init(jax.random.PRNGKey(0))
        train_idx = np.random.default_rng(0).integers(
            0, n, 6 * trainer.global_batch
        )
        seed_mat = trainer.pack_epoch(train_idx, seed=7)
        params, opt, losses = trainer.epoch_scan(
            params, opt, seed_mat, labels, jax.random.PRNGKey(42)
        )
        results[depth] = (
            np.asarray(losses),
            jax.tree_util.tree_map(np.asarray, params),
            np.asarray(trainer.last_routed_overflow),
            np.asarray(trainer.last_tier_hits),
        )
    l0, p0, ro0, th0 = results[0]
    l1, p1, ro1, th1 = results[1]
    np.testing.assert_array_equal(l0.view(np.uint32), l1.view(np.uint32))
    assert _tree_bitwise_equal(p0, p1)
    np.testing.assert_array_equal(ro0, ro1)
    np.testing.assert_array_equal(th0, th1)
    assert th0.sum() > 0  # telemetry is live, not trivially zero


# -- resilience-seam differentials (slow lane) --------------------------------


@pytest.mark.slow
def test_pipelined_chunked_epoch_bitwise_matches_serial(tmp_path):
    """Checkpoint chunking composes with the pipeline: each chunk
    re-issues its carried batch from the seed matrix, so a chunked
    pipelined epoch is bitwise-identical to the unchunked serial one —
    and the re-issues are counted."""
    trainer_s, ps, os_, labels = _build_trainer()
    seed_mat = trainer_s.pack_epoch(np.tile(np.arange(96), 6), seed=0)
    assert seed_mat.shape[0] == 9
    key = jax.random.PRNGKey(7)
    ps, os_, losses_s = trainer_s.epoch_scan(ps, os_, seed_mat, labels, key)

    trainer_p, pp, op, _ = _build_trainer(
        pipeline_depth=1, checkpoint_dir=tmp_path / "p", checkpoint_every=3
    )
    pp, op, losses_p = trainer_p.epoch_scan(pp, op, seed_mat, labels, key)
    np.testing.assert_array_equal(
        np.asarray(losses_p).view(np.uint32),
        np.asarray(losses_s).view(np.uint32),
    )
    assert _tree_bitwise_equal(ps, pp)
    # 9 steps / chunk 3 => chunks at [0,3) [3,6) [6,9): two re-issues
    assert int(trainer_p.metrics.value(PIPELINE_REISSUES)) == 2
    trainer_p.checkpointer.close()


@pytest.mark.slow
def test_pipelined_preempt_resume_bitwise_matches_serial(tmp_path):
    """Kill a pipelined run mid-epoch, resume(), and the remaining loss
    trajectory plus final params match the UNINTERRUPTED SERIAL run
    bitwise — the pipeline survives the full crash/replay seam without
    the carried batch ever being serialized."""
    trainer_s, ps, os_, labels = _build_trainer()
    seed_mat = trainer_s.pack_epoch(np.tile(np.arange(96), 6), seed=0)
    key = jax.random.PRNGKey(7)
    ps, os_, losses_s = trainer_s.epoch_scan(ps, os_, seed_mat, labels, key)
    losses_s = np.asarray(losses_s)

    trainer_p, pp, op, _ = _build_trainer(
        pipeline_depth=1, checkpoint_dir=tmp_path / "p", checkpoint_every=3,
        plan=FaultPlan(preempt_at_step=4),
    )
    p0, o0 = pp, op
    with pytest.raises(Preemption, match="step 4"):
        trainer_p.epoch_scan(pp, op, seed_mat, labels, key)
    pr, orr, key_r, step, epoch = trainer_p.resume(p0, o0)
    assert step == 3 and epoch == 0
    pr, orr, losses_r = trainer_p.epoch_scan(
        pr, orr, seed_mat, labels, key_r, epoch=epoch, start_step=step
    )
    np.testing.assert_array_equal(
        np.asarray(losses_r).view(np.uint32),
        losses_s[step:].view(np.uint32),
    )
    assert _tree_bitwise_equal(ps, pr)
    trainer_p.checkpointer.close()


@pytest.mark.slow
def test_pipelined_guard_skips_injected_nan_step():
    """nonfinite_guard composes with depth=1: the NaN rides the TRAIN
    half of the step it poisons (same op order as serial), the guard
    skips exactly that update, and the trajectory matches the serial
    guarded run bitwise."""
    plan = FaultPlan(nan_feature_steps=(2,), nan_rows=4)
    trainer_s, ps, os_, labels = _build_trainer(guard=True, plan=plan)
    seed_mat = trainer_s.pack_epoch(np.tile(np.arange(96), 4), seed=0)
    key = jax.random.PRNGKey(7)
    ps, os_, losses_s = trainer_s.epoch_scan(ps, os_, seed_mat, labels, key)

    plan_p = FaultPlan(nan_feature_steps=(2,), nan_rows=4)
    trainer_p, pp, op, _ = _build_trainer(
        pipeline_depth=1, guard=True, plan=plan_p
    )
    pp, op, losses_p = trainer_p.epoch_scan(pp, op, seed_mat, labels, key)
    np.testing.assert_array_equal(
        np.asarray(losses_p).view(np.uint32),
        np.asarray(losses_s).view(np.uint32),
    )
    assert _tree_bitwise_equal(ps, pp)
    skipped = np.asarray(trainer_p.metrics.value(GUARD_SKIPPED))
    expect = np.zeros(seed_mat.shape[0], np.int32)
    expect[2] = 1
    np.testing.assert_array_equal(skipped, expect)
    ls = np.asarray(losses_p)
    assert not np.isfinite(ls[2]) and np.isfinite(np.delete(ls, 2)).all()
