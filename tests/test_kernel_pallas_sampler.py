"""GraphSageSampler kernel='pallas' integration tests (interpret mode on CPU):
validity oracle, PyG contract, mode/weighted guards."""

import numpy as np
import pytest

from quiver_tpu import CSRTopo, GraphSageSampler


@pytest.fixture(scope="module")
def topo():
    rng = np.random.default_rng(3)
    ei = rng.integers(0, 400, size=(2, 6000)).astype(np.int64)
    return CSRTopo(edge_index=ei)


def _adjacency(topo):
    adj = {}
    indptr, indices = np.asarray(topo.indptr), np.asarray(topo.indices)
    for v in range(topo.node_count):
        adj[v] = set(indices[indptr[v]:indptr[v + 1]].tolist())
    return adj


def test_pallas_kernel_sample_validity(topo):
    s = GraphSageSampler(topo, [5, 4], seed_capacity=64, seed=0, kernel="pallas")
    seeds = np.random.default_rng(0).integers(0, topo.node_count, 64)
    out = s.sample(seeds)
    assert np.array_equal(np.asarray(out.n_id[:64]), seeds)
    assert int(out.overflow) == 0
    adj = _adjacency(topo)
    n_id = np.asarray(out.n_id)
    checked = 0
    for a in out.adjs:
        src, dst = np.asarray(a.edge_index)
        # per-hop targets are a prefix of n_id (forced-first property)
        for sl, dl in zip(src, dst):
            if sl < 0:
                continue
            u, v = int(n_id[sl]), int(n_id[dl])
            assert u in adj[v], f"sampled non-edge {u}->{v}"
            checked += 1
    assert checked > 100


def test_pallas_kernel_per_row_distinct(topo):
    s = GraphSageSampler(topo, [6], seed_capacity=32, seed=1, kernel="pallas")
    out = s.sample(np.arange(32))
    src, dst = np.asarray(out.adjs[0].edge_index)
    indptr = np.asarray(topo.indptr)
    deg = np.diff(indptr)
    n_id = np.asarray(out.n_id)
    indices = np.asarray(topo.indices)
    per_row = {}
    for sl, dl in zip(src, dst):
        if sl >= 0:
            per_row.setdefault(int(dl), []).append(int(n_id[sl]))
    for r, nbrs in per_row.items():
        v = int(n_id[r])
        assert len(nbrs) == min(deg[v], 6)
        row = indices[indptr[v]:indptr[v + 1]]
        if deg[v] > 6 and len(set(row.tolist())) == deg[v]:
            # draws are distinct CSR slots; on rows whose entries are all
            # distinct, id distinctness == slot distinctness
            assert len(set(nbrs)) == len(nbrs), f"row {v} repeated a slot"


def test_pallas_kernel_guards(topo):
    # the fused engine serves every VARIANT (weighted/temporal/with_eid);
    # only the structural constraints still raise on explicit pallas
    with pytest.raises(ValueError, match="HBM"):
        GraphSageSampler(topo, [3], mode="UVA", kernel="pallas")
    with pytest.raises(ValueError, match="kernel"):
        GraphSageSampler(topo, [3], kernel="cuda")
    # weighted + pallas constructs (and still validates its weight inputs)
    with pytest.raises(ValueError, match="edge weights"):
        GraphSageSampler(topo, [3], weighted=True, kernel="pallas")


def test_pallas_kernel_weighted_runs(topo):
    """The old capability-matrix raise is gone: weighted + kernel='pallas'
    samples (bitwise differentials live in test_fused_sampler.py)."""
    rng = np.random.default_rng(7)
    wtopo = CSRTopo(edge_index=np.stack([
        np.asarray(rng.integers(0, 400, 6000)),
        np.asarray(rng.integers(0, 400, 6000)),
    ]))
    wtopo.set_edge_weight(rng.random(6000).astype(np.float32))
    s = GraphSageSampler(wtopo, [4], seed_capacity=32, seed=0,
                         kernel="pallas", weighted=True)
    out = s.sample(np.arange(32))
    assert int(out.n_count) >= 32


def test_pallas_kernel_auto_caps_compose(topo):
    s = GraphSageSampler(topo, [5, 4], seed_capacity=64, seed=0,
                         kernel="pallas", frontier_caps="auto")
    out1 = s.sample(np.arange(64))
    assert s._frontier_caps is not None
    out2 = s.sample(np.arange(64))
    assert int(out2.overflow) == 0
    assert out2.n_id.shape[0] <= out1.n_id.shape[0]


def test_pallas_kernel_small_graph_fallback(caplog):
    """Graphs with fewer edges than the DMA window fall back to the XLA
    path — and say so ONCE (the silent trace-time switch grew an info_once
    signal, same discipline as the other degrade paths)."""
    import logging

    from quiver_tpu.utils.trace import reset_once

    reset_once()
    rng = np.random.default_rng(0)
    ei = rng.integers(0, 30, size=(2, 200)).astype(np.int64)  # E=200 < 2048
    small = CSRTopo(edge_index=ei)
    s = GraphSageSampler(small, [3], seed_capacity=16, seed=0, kernel="pallas")
    with caplog.at_level(logging.INFO, logger="quiver_tpu"):
        out = s.sample(np.arange(16))
        s.sample(np.arange(16))  # second call: the log must NOT repeat
    assert int(out.n_count) >= 16
    hits = [r for r in caplog.records
            if "falls back to the XLA path" in r.getMessage()]
    assert len(hits) == 1
