import time, numpy as np, jax, jax.numpy as jnp

E = 62_623_643
rng = np.random.default_rng(0)
indices = jnp.asarray(rng.integers(0, 2_450_000, E, dtype=np.int64))
indices32 = indices.astype(jnp.int32)

def bench(name, fn, *args, iters=10):
    out = jax.block_until_ready(jax.jit(fn)(*args))
    t0=time.time()
    for _ in range(iters):
        out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    print(f"{name}: {(time.time()-t0)/iters*1e3:.2f} ms")
    return out

epos = jnp.asarray(rng.integers(0, E, 1_802_240, dtype=np.int64))
epos32 = epos.astype(jnp.int32)
bench("gather 1.8M from 62.6M int64 tbl/int64 idx", lambda t,i: t[i], indices, epos)
bench("gather 1.8M from 62.6M int32 tbl/int32 idx", lambda t,i: t[i], indices32, epos32)

v = jnp.asarray(rng.integers(0, 2_450_000, 2_162_688, dtype=np.int32))
bench("argsort 2.16M int32 stable", lambda x: jnp.argsort(x, stable=True), v)
bench("sort 2.16M int32", lambda x: jnp.sort(x), v)
bench("cumsum 2.16M int32", lambda x: jnp.cumsum(x), v.astype(jnp.int32))
perm = jnp.asarray(rng.permutation(2_162_688).astype(np.int32))
bench("scatter-set 2.16M", lambda x,p: jnp.zeros(2_162_688, jnp.int32).at[p].set(x), v, perm)
bench("gather 2.16M from 2.16M", lambda x,p: x[p], v, perm)

v36 = v[:360_448]
bench("argsort 360k int32 stable", lambda x: jnp.argsort(x, stable=True), v36)

deg = jnp.asarray(rng.integers(0, 100, 360_448, dtype=np.int32))
from quiver_tpu.ops.sample import stratified_offsets, rotate_offsets
key = jax.random.PRNGKey(0)
def offs(key, deg):
    o, m = stratified_offsets(key, deg, 5)
    return rotate_offsets(key, o, deg, 5)
bench("stratified+rotate 360k x5", offs, key, deg)
