"""Distributed heterogeneous neighbor sampling over a mesh-sharded topology.

The scale-out counterpart of ``HeteroGraphSampler``: every relation's CSR
lives as a row-range partition
(:class:`~quiver_tpu.core.hetero_sharded.HeteroShardedTopology`) and every
device is a full sampling worker over its own seed block. Each hop runs
inside ``shard_map`` and reuses the homogeneous owner-routed hop
(``sampling.dist.dist_sample_layer``) per relation, with ONE twist that
makes the typed case cheap: all relations into the same destination type
share that type's row ranges, so they share ONE ``BucketRoute`` plan per
hop — the plan's id lanes are sent once and cached; every subsequent
relation's degree/offset/neighbor exchanges ride the same buckets.

Comm model per hop (S_t = per-device frontier width of dst type t, F =
shards, ``cap_t = ceil(alpha * S_t / F)``): the shared plan moves
``F*cap_t`` id lanes ONCE per (hop, dst type); each uniform relation then
adds ``F*cap_t`` (degrees back) + ``F*cap_t*k`` (offsets out) +
``F*cap_t*k`` (neighbors back) lanes, and each weighted relation adds one
more ``F*cap_t`` f32 hop (row weight totals back; its offsets-out hop
carries the f32 uniform block instead of int32 offsets).

Bit-parity contract: for the same seed block, fanouts, caps, and dedup
strategy, every per-worker output is bit-identical to the replicated
``HeteroGraphSampler``'s on that block with key ``fold_in(base_key,
worker_index)`` — the per-relation key schedule (one split per active
relation, plan order) and the per-type dedup are byte-for-byte the
replicated loop's; only the neighbor lookup is owner-routed.

Routed-bucket overflow is served exactly via the cond-gated psum fallback
and surfaced per (hop, edge type) on the graftscope registry
(``HETERO_SAMPLE_OVERFLOW``); relations sharing a destination type share
that hop's route plan, so they report the plan's overflow equally.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.config import SampleMode
from ..core.hetero import HeteroCSRTopo
from ..core.hetero_sharded import HeteroShardedTopology
from ..obs.registry import HETERO_SAMPLE_OVERFLOW, MetricsRegistry
from ..ops.election import validate_kernel_arg
from ..ops.reindex import masked_unique
from ..parallel.mesh import FEATURE_AXIS, shard_map
from ..parallel.routing import BucketRoute
from ..utils.trace import info_once, trace_scope
from .dist import _worker_index, dist_sample_layer, routed_sample_cap
from .hetero import HeteroGraphSampler, HeteroLayer, HeteroSampleOutput
from .sampler import Adj, _round_up, resolve_sample_kernel

__all__ = ["DistHeteroSampler", "dist_hetero_multilayer_sample"]


def dist_hetero_multilayer_sample(rel_blocks, seeds, num_seeds, key,
                                  input_type, layer_plans, *, axis: str,
                                  num_shards: int, rows_per_shard: dict,
                                  routed_alpha: float | None = 2.0,
                                  weighted_rels=frozenset(),
                                  search_iters=None, node_bounds=None,
                                  scatter_free: bool = False,
                                  pallas_rels=frozenset()):
    """The per-device distributed hetero loop (call inside ``shard_map``).

    Args:
      rel_blocks: {edge_type: (local_indptr, local_indices,
        local_cum_weights | None)} — this shard's rebased CSR blocks per
        relation (``HeteroShardedTopology`` layout).
      layer_plans: the STATIC per-hop plans of ``HeteroGraphSampler._plan``
        — sharing the replicated planner is part of the parity contract
        (same active sets, same caps, same key schedule).
      rows_per_shard: {node_type: rows per shard} owner geometry.
      search_iters: {edge_type: static binary-search bound} for weighted
        relations (from each relation's GLOBAL max degree).
      pallas_rels: relations whose owner-side hop runs on the fused
        Pallas engine (``dist_sample_layer`` ``kernel="pallas"``; bits on
        the wire unchanged). ``DistHeteroSampler._compiled`` gates each
        relation on slice size / max degree / fanout vs the DMA window.

    Returns ``(frontier, counts, ei_layers, overflow, frontier_counts,
    hop_overflows)`` where ``ei_layers`` is deepest-first, each hop a tuple
    of ``(2, S*k)`` edge_index arrays in sorted-relation order, and
    ``hop_overflows`` is seeds-outward, each hop a tuple of the shared
    route plan's fallback-served lane count per active relation (sorted
    order — the ``HETERO_SAMPLE_OVERFLOW`` slot layout).
    """
    search_iters = search_iters or {}
    frontier = {input_type: seeds}
    counts = {input_type: num_seeds}
    ei_layers = []
    frontier_counts = []
    hop_overflows = []
    overflow = jnp.zeros((), jnp.int32)

    for li, (rel_fanouts, caps_prev, caps_next) in enumerate(layer_plans):
        # 1) sample every active relation through ONE shared route per
        #    destination type; key schedule mirrors the replicated loop
        #    exactly (one split per relation, plan order)
        routes = {}
        samples = {}
        for et, k in rel_fanouts.items():
            _, _, d = et
            key, sub = jax.random.split(key)
            if d not in routes:
                S_d = frontier[d].shape[0]
                valid = (jnp.arange(S_d) < counts[d]) & (frontier[d] >= 0)
                s = jnp.where(valid, frontier[d], 0)
                routes[d] = BucketRoute(
                    s, valid, s // rows_per_shard[d], axis=axis,
                    num_shards=num_shards,
                    cap=routed_sample_cap(S_d, num_shards, routed_alpha),
                )
            ip, ix, cw = rel_blocks[et]
            with trace_scope(f"dist_hetero_layer_{li}"):
                nbr, _, _ = dist_sample_layer(
                    ip, ix, rows_per_shard[d], frontier[d], counts[d], k,
                    sub, axis=axis, num_shards=num_shards, cap=None,
                    weighted=et in weighted_rels, local_cum_weights=cw,
                    search_iters=search_iters.get(et, 0), route=routes[d],
                    kernel="pallas" if et in pallas_rels else "xla",
                )
            samples[et] = nbr
        hop_overflows.append(tuple(
            routes[et[2]].overflow for et in sorted(rel_fanouts, key=str)
        ))

        # 2) per-type dedup — byte-for-byte the replicated discipline
        #    (sampling.hetero.hetero_multilayer_sample): previous frontier
        #    forced first, then each relation's flat samples in sorted
        #    relation order
        new_frontier, new_counts, locals_per_rel = {}, {}, {}
        layer_uniques = {}
        for t, cap in caps_next.items():
            blocks, valids, spans = [], [], {}
            prev = frontier.get(t)
            n_prev = 0
            if prev is not None:
                n_prev = prev.shape[0]
                blocks.append(prev)
                valids.append(
                    (jnp.arange(n_prev) < counts[t]) & (prev >= 0)
                )
            for et in sorted(samples, key=str):
                if et[0] != t:
                    continue
                flat = samples[et].reshape(-1)
                spans[et] = (sum(b.shape[0] for b in blocks),
                             flat.shape[0])
                blocks.append(flat)
                valids.append(flat >= 0)
            ids = jnp.concatenate(blocks)
            valid = jnp.concatenate(valids)
            uniq, num_u, local = masked_unique(
                ids, valid, cap, num_forced=n_prev,
                node_bound=None if node_bounds is None else node_bounds[t],
                scatter_free=scatter_free,
            )
            new_frontier[t] = uniq
            new_counts[t] = jnp.minimum(num_u, cap)
            layer_uniques[t] = num_u
            overflow = overflow + jnp.maximum(num_u - cap, 0)
            for et, (off, ln) in spans.items():
                locals_per_rel[et] = local[off:off + ln]

        # 3) one padded edge_index per relation (col = new src-frontier
        #    local id, row = dst row position), sorted-relation order
        eis = []
        for et in sorted(rel_fanouts, key=str):
            k = rel_fanouts[et]
            d_t = et[2]
            S = frontier[d_t].shape[0]
            col = locals_per_rel[et].reshape(S, k)
            row = jnp.broadcast_to(
                jnp.arange(S, dtype=jnp.int32)[:, None], (S, k)
            )
            row = jnp.where(col >= 0, row, -1)
            eis.append(jnp.stack([col.reshape(-1), row.reshape(-1)]))
        ei_layers.append(tuple(eis))
        frontier_counts.append(layer_uniques)

        frontier, counts = new_frontier, new_counts

    return (frontier, counts, tuple(ei_layers[::-1]), overflow,
            tuple(frontier_counts), tuple(hop_overflows))


class DistHeteroSampler(HeteroGraphSampler):
    """K-hop typed sampler over a mesh-sharded heterogeneous topology.

    The typed member of the distributed sampler family
    (``DistGraphSageSampler`` is the homogeneous one): per-relation CSR
    partitions (~1/F topology bytes per chip), owner-routed hops through
    one shared ``BucketRoute`` plan per (hop, destination type), and the
    ``seed_sharding="all"`` worker discipline — every device samples its
    own seed block with key ``fold_in(key, worker_index)``, bit-identical
    to the replicated ``HeteroGraphSampler`` on that block (see the
    module docstring for the comm model and parity contract).

    Extra args over the replicated sampler: ``mesh`` (required), the
    ``routed_alpha`` capped-bucket budget (``cap = ceil(alpha * S / F)``
    lanes per destination per hop; ``None`` = uncapped), ``axis`` (the
    mesh axis the partitions live on), and ``kernel``
    ("auto"|"pallas"|"xla" — with pallas, eligible relations' owner-side
    hops run on the fused Pallas engine, per-relation compile-time gating
    with one INFO per degrade; bits on the wire unchanged). Constraints:
    HBM mode and no ``with_eid`` (the sharded relation slices do not
    carry eid — that path stays on the replicated sampler).

    After an eager :meth:`sample`, ``last_sample_overflow`` holds the
    fallback-served lane count per (hop, edge type) — an int32
    ``(num_slots,)`` device vector in :attr:`overflow_slots` order,
    registered on the graftscope registry as ``HETERO_SAMPLE_OVERFLOW``.
    """

    def __init__(self, topo: HeteroCSRTopo, sizes, input_type: str,
                 mode: str | SampleMode = SampleMode.HBM,
                 seed_capacity: int | None = None,
                 frontier_caps: str | None = None, seed: int = 0,
                 auto_margin: float = 1.25, weighted=False,
                 with_eid: bool = False, dedup: str = "auto", *,
                 mesh=None, routed_alpha: float | None = 2.0,
                 axis: str = FEATURE_AXIS, kernel: str = "auto"):
        if mesh is None:
            raise ValueError("DistHeteroSampler requires mesh=")
        # the request rides verbatim; resolution (which may run the
        # measured election) happens at first compile via the property
        self._kernel = validate_kernel_arg(str(kernel))
        if with_eid:
            raise ValueError(
                "with_eid over a sharded topology is not supported; the "
                "sharded relation slices do not carry eid — use the "
                "replicated HeteroGraphSampler"
            )
        if SampleMode.parse(mode) is not SampleMode.HBM:
            raise ValueError(
                "DistHeteroSampler requires mode='HBM': each shard's "
                "relation slice is device-resident (that is the point — "
                "per-chip bytes shrink 1/F instead of staging through host)"
            )
        if routed_alpha is not None and routed_alpha <= 0:
            raise ValueError(
                f"routed_alpha must be > 0 or None, got {routed_alpha}"
            )
        self.mesh = mesh
        self.axis = axis
        self.routed_alpha = (
            None if routed_alpha is None else float(routed_alpha)
        )
        super().__init__(
            topo, sizes, input_type, mode=mode,
            seed_capacity=seed_capacity, frontier_caps=frontier_caps,
            seed=seed, auto_margin=auto_margin, weighted=weighted,
            with_eid=with_eid, dedup=dedup,
        )
        # static (hop, edge_type) telemetry slot order — the active sets
        # depend only on schema reachability, never on cap values, so any
        # seed capacity plans the same slots
        self._overflow_slots = tuple(
            (li, et)
            for li, (active, _, _) in enumerate(self._plan(128))
            for et in sorted(active, key=str)
        )
        # graftscope registry: fallback-served lane counts per (hop, edge
        # type) of the last eager sample (``last_sample_overflow`` is a
        # thin view; None before any sample)
        self.metrics = MetricsRegistry()
        self.metrics.counter(
            HETERO_SAMPLE_OVERFLOW, shape=(len(self._overflow_slots),),
            unit="lanes",
            doc="fallback-served lanes per (hop, edge type) of the last "
                "distributed hetero sample (overflow_slots order)",
        )

    # -- topology placement (overrides the replicated upload) ---------------

    def _init_topo(self):
        return HeteroShardedTopology(
            self.mesh, self.topo, axis=self.axis,
            weighted_rels=self.weighted_rels,
        )

    @property
    def kernel(self) -> str:
        """The resolved sampler kernel ("pallas"|"xla") — same lazy
        election contract as ``GraphSageSampler.kernel``."""
        resolved = getattr(self, "_kernel_resolved", None)
        if resolved is None:
            resolved = resolve_sample_kernel(self._kernel)
            self._kernel_resolved = resolved
        return resolved

    @property
    def overflow_slots(self) -> tuple:
        """Static ``(hop, edge_type)`` order of the overflow vector."""
        return self._overflow_slots

    @property
    def last_sample_overflow(self):
        """Fallback-served lane counts of the last eager sample — int32
        ``(num_slots,)`` device vector in :attr:`overflow_slots` order
        (thin view of the ``HETERO_SAMPLE_OVERFLOW`` registry metric)."""
        return self.metrics.value(HETERO_SAMPLE_OVERFLOW)

    @property
    def last_sample_overflow_by_rel(self) -> dict | None:
        """``{(hop, edge_type): lanes}`` view of the last sample's
        overflow vector (host ints; None before any sample)."""
        v = self.metrics.value(HETERO_SAMPLE_OVERFLOW)
        if v is None:
            return None
        flat = np.asarray(v)
        return {
            slot: int(flat[i]) for i, slot in enumerate(self._overflow_slots)
        }

    @property
    def workers(self) -> int:
        """Seed-block workers: every device of the mesh."""
        w = 1
        for a in self.mesh.axis_names:
            w *= self.mesh.shape[a]
        return w

    def _topo_operands(self) -> tuple:
        """Per-shard relation arrays in the order the compiled body
        expects: for each relation (sorted), indptr, indices, then the
        prefix-weight slice if the relation draws weighted (all
        ``(F, ...)`` with ``P(axis, None)``)."""
        ops = []
        for et in sorted(self.dev_topos.rels, key=str):
            rel = self.dev_topos.rels[et]
            ops.append(rel.indptr)
            ops.append(rel.indices)
            if et in self.weighted_rels:
                ops.append(rel.cum_weights)
        return tuple(ops)

    def _scal_layout(self, plans):
        """Static layout of the per-worker scalar row: [frontier_overflow,
        final counts per type (sorted), per-hop unclipped uniques per type
        (hop-major, sorted within each hop)]."""
        out_types = tuple(sorted(plans[-1][2]))
        fc_slots = tuple(
            (li, t) for li, (_, _, caps_next) in enumerate(plans)
            for t in sorted(caps_next)
        )
        return out_types, fc_slots

    # -- compiled program ---------------------------------------------------

    def _compiled(self, seed_cap: int):
        ov = self._cap_overrides
        cache_key = (
            seed_cap,
            None if ov is None
            else tuple(tuple(sorted(layer.items())) for layer in ov),
        )
        if cache_key in self._compiled_cache:
            return self._compiled_cache[cache_key]
        plans = self._plan(
            seed_cap, self._cap_overrides if self._auto_caps else None
        )
        mesh, axis = self.mesh, self.axis
        F = int(mesh.shape[axis])
        ids_axes = tuple(mesh.axis_names)
        other_axes = tuple(a for a in mesh.axis_names if a != axis)
        rel_keys = tuple(sorted(self.dev_topos.rels, key=str))
        weighted_rels = self.weighted_rels
        rps = dict(self.dev_topos.rows_per_shard)
        iters = {
            et: self.dev_topos.rels[et].search_iters for et in rel_keys
        }
        alpha = self.routed_alpha
        input_type = self.input_type
        node_bounds = (
            {t: int(n) for t, n in self.topo.num_nodes.items()}
            if self.dedup == "map" else None
        )
        scatter_free = self.dedup == "scan"
        n_topo = len(self._topo_operands())
        out_types, fc_slots = self._scal_layout(plans)
        pallas_rels = frozenset()
        if self.kernel == "pallas":  # resolved (may run the election)
            from ..ops.pallas.fused import DEFAULT_WINDOW

            # per-relation compile-time eligibility for the fused
            # owner-side kernel (same gates as the homogeneous sampler,
            # applied to each relation's slice and global max degree)
            kmax = {}
            for active, _, _ in plans:
                for et, kf in active.items():
                    kmax[et] = max(kf, kmax.get(et, 0))
            ok, degraded = set(), []
            for et in rel_keys:
                E_local = int(self.dev_topos.rels[et].indices.shape[1])
                md = int(self.topo.relations[et].max_degree)
                if (DEFAULT_WINDOW <= E_local <= np.iinfo(np.int32).max
                        and md <= DEFAULT_WINDOW
                        and kmax.get(et, 0) <= DEFAULT_WINDOW):
                    ok.add(et)
                else:
                    degraded.append(et)
            if degraded:
                info_once(
                    "dist-hetero-pallas-degrade",
                    "kernel='pallas' falls back to the XLA path for "
                    "relations %s: each needs a per-shard slice of at "
                    "least %d edges (int32 range) with max_degree and "
                    "fanout within the DMA window",
                    sorted(degraded, key=str), DEFAULT_WINDOW,
                )
            pallas_rels = frozenset(ok)

        def body(*args):
            # args: per-relation (indptr, indices, [cum_weights]) blocks in
            # sorted relation order (self._topo_operands()), seeds, key
            topo_blks, (seeds, key) = args[:n_topo], args[n_topo:]
            blk = iter(topo_blks)
            rel_blocks = {}
            for et in rel_keys:
                ip = next(blk)[0]
                ix = next(blk)[0]
                cw = next(blk)[0] if et in weighted_rels else None
                rel_blocks[et] = (ip, ix, cw)
            key = jax.random.fold_in(key, _worker_index(mesh))
            num_seeds = jnp.sum((seeds >= 0).astype(jnp.int32))
            (frontier, counts, ei_layers, overflow, fcounts,
             hop_ovs) = dist_hetero_multilayer_sample(
                rel_blocks, seeds, num_seeds, key, input_type, plans,
                axis=axis, num_shards=F, rows_per_shard=rps,
                routed_alpha=alpha, weighted_rels=weighted_rels,
                search_iters=iters, node_bounds=node_bounds,
                scatter_free=scatter_free, pallas_rels=pallas_rels,
            )
            # per-worker scalar row in the _scal_layout order
            scal = jnp.stack(
                [overflow]
                + [counts[t] for t in out_types]
                + [fcounts[li][t] for li, t in fc_slots]
            ).astype(jnp.int32)
            hop_ov = jnp.concatenate(
                [jnp.stack(h) for h in hop_ovs]
            )  # (num_slots,) axis-group totals, overflow_slots order
            if other_axes:  # replicate the mesh-wide totals
                hop_ov = jax.lax.psum(hop_ov, other_axes)
            return frontier, ei_layers, scal, hop_ov

        run = jax.jit(
            shard_map(
                body,
                mesh=mesh,
                in_specs=(
                    (P(axis, None),) * n_topo + (P(ids_axes), P())
                ),
                out_specs=(
                    P(ids_axes),
                    tuple(P(None, ids_axes) for _ in plans),
                    P(ids_axes),
                    P(),
                ),
                check_vma=False,
            )
        )
        self._compiled_cache[cache_key] = (run, plans)
        return run, plans

    # -- public API ---------------------------------------------------------

    def shard_seeds(self, seeds, local_cap: int) -> np.ndarray:
        """Split a global seed array into per-worker valid-prefix blocks,
        padded to (workers, local_cap) with -1 (same packing as the
        homogeneous distributed sampler)."""
        seeds = np.asarray(seeds)
        blocks = np.array_split(seeds, self.workers)
        out = np.full((self.workers, local_cap), -1, np.int32)
        for i, b in enumerate(blocks):
            if len(b) > local_cap:
                raise ValueError(
                    f"per-worker block {len(b)} exceeds capacity {local_cap}"
                )
            out[i, : len(b)] = b
        return out

    def sample(self, input_nodes, key=None) -> HeteroSampleOutput:
        """Sample typed k-hop neighborhoods of a GLOBAL seed batch, split
        across every device of the mesh.

        Returns one worker-major global ``HeteroSampleOutput``: each
        ``n_id[t]`` is ``(workers * cap_t,)`` (each worker's block
        bit-identical to the replicated sampler's on that worker's seed
        block — see :meth:`sample_per_worker`), each relation's
        ``edge_index`` is ``(2, workers * S*k)`` with per-worker
        ``Adj.size``, ``batch_size`` is the per-worker padded block width,
        ``n_count``/``overflow`` are mesh totals and ``frontier_counts``
        per-layer/type worker maxima. ``key`` overrides the sampler's own
        PRNG stream (each worker folds in its flat worker index on top).
        """
        seeds = np.asarray(input_nodes)
        batch = int(seeds.shape[0])
        n = self.topo.num_nodes[self.input_type]
        if batch and (seeds.min() < 0 or seeds.max() >= n):
            raise ValueError(
                f"seed ids must be in [0, {n}); got "
                f"[{seeds.min()}, {seeds.max()}]"
            )
        W = self.workers
        per_worker = -(-batch // W) if batch else 1
        cap = self._seed_capacity or max(_round_up(per_worker, 128), 128)
        packed = self.shard_seeds(seeds, cap)
        if key is None:
            self._call += 1
            key = jax.random.fold_in(self._key, self._call)
        dev_seeds = jax.device_put(
            jnp.asarray(packed.reshape(-1)),
            NamedSharding(self.mesh, P(tuple(self.mesh.axis_names))),
        )
        run, plans = self._compiled(cap)
        n_id, eis, scal, hop_ov = run(
            *self._topo_operands(), dev_seeds, key
        )
        if self._auto_caps:
            # same regrow discipline as the replicated hetero sampler, fed
            # from the worker-MAX unclipped uniques (caps must cover the
            # worst worker — one uniform program across the mesh)
            first_plan = self._cap_overrides is None
            for _ in range(len(self.sizes) + 2):
                out_types, fc_slots = self._scal_layout(plans)
                sc = np.asarray(scal).reshape(
                    W, 1 + len(out_types) + len(fc_slots)
                )
                overflow = int(sc[:, 0].sum())
                if not first_plan and overflow == 0:
                    break
                off = 1 + len(out_types)
                observed = [dict() for _ in self.sizes]
                for j, (li, t) in enumerate(fc_slots):
                    observed[li][t] = int(sc[:, off + j].max())
                before = self._cap_overrides
                self._plan_auto(observed)
                if not first_plan and self._cap_overrides == before:
                    break  # saturated: clipped result + overflow stand
                if first_plan and overflow == 0:
                    first_plan = False
                    break  # worst-case first run was exact; keep it
                run, plans = self._compiled(cap)
                n_id, eis, scal, hop_ov = run(
                    *self._topo_operands(), dev_seeds, key
                )
                first_plan = False
        self.metrics.set(HETERO_SAMPLE_OVERFLOW, hop_ov)
        return self._assemble(n_id, eis, scal, cap, plans)

    def _assemble(self, n_id, eis, scal, seed_cap, plans):
        W = self.workers
        L = len(plans)
        out_types, fc_slots = self._scal_layout(plans)
        sc = np.asarray(scal).reshape(W, 1 + len(out_types) + len(fc_slots))
        n_count = {
            t: jnp.int32(int(sc[:, 1 + i].sum()))
            for i, t in enumerate(out_types)
        }
        layers = []
        for l, layer_eis in enumerate(eis):  # deepest-first
            active, caps_prev, caps_next = plans[L - 1 - l]
            adjs = {}
            for et, ei in zip(sorted(active, key=str), layer_eis):
                s_t, _, d_t = et
                adjs[et] = Adj(
                    ei, None, (caps_next[s_t], caps_prev[d_t]),
                    fanout=active[et],
                )
            layers.append(HeteroLayer(adjs, dict(caps_next), dict(caps_prev)))
        off = 1 + len(out_types)
        observed = [dict() for _ in range(L)]
        for j, (li, t) in enumerate(fc_slots):
            observed[li][t] = int(sc[:, off + j].max())
        return HeteroSampleOutput(
            n_id, n_count, seed_cap, layers,
            jnp.int32(int(sc[:, 0].sum())), tuple(observed),
        )

    def sample_per_worker(self, input_nodes, key=None):
        """:meth:`sample`, sliced into per-worker ``HeteroSampleOutput``s
        — each bit-comparable to the replicated ``HeteroGraphSampler``'s
        output on that worker's seed block with key
        ``fold_in(base_key, worker_index)``."""
        out = self.sample(np.asarray(input_nodes), key=key)
        W = self.workers
        per = []
        for w in range(W):
            n_id_w = {
                t: jnp.asarray(np.asarray(v).reshape(W, -1)[w])
                for t, v in out.n_id.items()
            }
            layers_w = []
            for layer in out.adjs:
                adjs_w = {}
                for et, a in layer.adjs.items():
                    E_l = a.edge_index.shape[1] // W
                    ei = jnp.asarray(
                        np.asarray(a.edge_index).reshape(2, W, E_l)[:, w]
                    )
                    adjs_w[et] = Adj(ei, None, a.size, fanout=a.fanout)
                layers_w.append(HeteroLayer(
                    adjs_w, dict(layer.src_caps), dict(layer.dst_caps)
                ))
            per.append(HeteroSampleOutput(
                n_id_w, {}, out.batch_size, layers_w, jnp.int32(0), ()
            ))
        return per
