"""GraphSAINT-style subgraph sampling, TPU-native.

The reference *planned* a GraphSAINT sampler — ``qv.saint_subgraph`` appears
only as a commented-out block in tests/python/cuda/test_saint.py and never
landed (SURVEY §2.5). Here it is a real feature: node-induced subgraph
extraction with static shapes, plus the three standard GraphSAINT samplers
(node, edge, random-walk) and loss/aggregation normalization estimation
(Zeng et al., "GraphSAINT: Graph Sampling Based Inductive Learning Method").

Static-shape design: a node budget ``C`` (padded, -1 sentinel) and a
per-node degree cap ``D``; the induced edge set is emitted as a (C*D,)
padded local edge list. Membership testing is a sort + binary search over
the node set — no hash tables, no atomics (SURVEY §7.1).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.topology import CSRTopo
from ..ops.reindex import masked_unique
from ..ops.sample import sample_layer, staged_gather

__all__ = [
    "SaintSubgraph",
    "saint_subgraph",
    "SAINTNodeSampler",
    "SAINTEdgeSampler",
    "SAINTRandomWalkSampler",
    "estimate_saint_norm",
]


class SaintSubgraph(NamedTuple):
    """Induced subgraph in local ids, padded with -1.

    node_id: (C,) global node ids (the subgraph's local id i is node_id[i]).
    edge_index: (2, C*D) [src, dst] local ids, -1 where invalid.
    num_nodes: scalar valid node count.
    num_edges: scalar valid edge count.
    """

    node_id: jax.Array
    edge_index: jax.Array
    num_nodes: jax.Array
    num_edges: jax.Array


def _membership(nodes, queries):
    """Local id of each query in ``nodes`` (or -1).

    nodes: (C,) ids, -1 padded, may contain duplicates (first wins).
    queries: (...,) ids (-1 lanes return -1).
    """
    C = nodes.shape[0]
    sent = jnp.iinfo(nodes.dtype).max
    keyed = jnp.where(nodes >= 0, nodes, sent)
    order = jnp.argsort(keyed)
    sorted_nodes = keyed[order]
    pos = jnp.searchsorted(sorted_nodes, queries)
    pos = jnp.minimum(pos, C - 1)
    hit = (sorted_nodes[pos] == queries) & (queries >= 0)
    local = jnp.where(hit, order[pos], -1)
    return local.astype(jnp.int32)


def saint_subgraph(topo, nodes, num_nodes, deg_cap: int):
    """Node-induced subgraph over a device CSR topology.

    For every valid node u in ``nodes``, scans up to ``deg_cap`` of u's
    neighbors (CSR order; edges beyond the cap are dropped — pick
    ``deg_cap >= max_degree`` for exactness) and keeps edges whose endpoint
    is also in ``nodes``. Jit-composable; all shapes static.

    Args:
      topo: DeviceTopology.
      nodes: (C,) node ids, -1 padded; valid entries occupy a prefix.
        Duplicate ids keep their first occurrence as the canonical local id.
      num_nodes: scalar count of valid entries.
      deg_cap: static per-node neighbor-scan window.

    Returns: SaintSubgraph.
    """
    C = nodes.shape[0]
    valid = (jnp.arange(C) < num_nodes) & (nodes >= 0)
    s = jnp.where(valid, nodes, 0)
    base = topo.indptr[s]
    deg = (topo.indptr[s + 1] - base).astype(jnp.int32)
    deg = jnp.where(valid, deg, 0)

    j = jnp.arange(deg_cap, dtype=jnp.int32)[None, :]
    in_window = j < jnp.minimum(deg, deg_cap)[:, None]
    epos = base[:, None] + jnp.where(in_window, j, 0).astype(base.dtype)
    nbr = staged_gather(topo.indices, epos, topo.host_indices)
    nbr = jnp.where(in_window, nbr, -1)

    dst_local = _membership(nodes, nbr)  # (C, D)
    src_local = jnp.broadcast_to(
        jnp.arange(C, dtype=jnp.int32)[:, None], (C, deg_cap)
    )
    keep = (dst_local >= 0) & in_window
    src_flat = jnp.where(keep, src_local, -1).reshape(-1)
    dst_flat = jnp.where(keep, dst_local, -1).reshape(-1)
    edge_index = jnp.stack([src_flat, dst_flat])
    return SaintSubgraph(
        node_id=nodes,
        edge_index=edge_index,
        num_nodes=jnp.sum(valid.astype(jnp.int32)),
        num_edges=jnp.sum(keep.astype(jnp.int32)),
    )


def _uniform_edge_positions(key, budget: int, edge_count: int, dtype):
    """(budget,) uniform draws in [0, edge_count). ``edge_count`` is static
    (an array shape), so the wide-graph branch resolves at trace time."""
    if edge_count < 2**31:
        return jax.random.randint(
            key, (budget,), 0, edge_count, dtype=jnp.int32
        ).astype(dtype)
    # >2^31 edges: compose two 16-bit draws into a 32-bit mantissa-safe
    # uniform and scale (float32 alone loses low bits past 2^24)
    hi = jax.random.randint(key, (budget,), 0, 1 << 16, dtype=jnp.int32)
    lo = jax.random.randint(
        jax.random.fold_in(key, 1), (budget,), 0, 1 << 16, dtype=jnp.int32
    )
    u = (hi.astype(jnp.float64) * (1 << 16) + lo) / float(1 << 32)
    return jnp.minimum((u * edge_count).astype(dtype), edge_count - 1)


def _degree_proportional_nodes(topo, key, budget: int):
    """Device-side degree-proportional node draw + first-occurrence dedup.

    P(node) ∝ degree is exactly a uniform edge draw mapped to its source row:
    ``indptr`` IS the degree CDF, so one ``searchsorted`` replaces the host
    ``rng.choice(p=deg/deg.sum())`` (VERDICT r2 item 5 — no host RNG, no
    per-batch ``np.unique``). A zero-edge graph degrades to uniform node
    draws (the degree law is undefined), matching the host path's p=None
    fallback; E is a static shape, so the branch resolves at trace time.
    """
    E = topo.indices.shape[0]
    if E == 0:
        n = topo.indptr.shape[0] - 1
        src = jax.random.randint(key, (budget,), 0, max(n, 1), dtype=jnp.int32)
    else:
        r = _uniform_edge_positions(key, budget, E, topo.indptr.dtype)
        src = (
            jnp.searchsorted(topo.indptr, r, side="right").astype(jnp.int32) - 1
        )
    nodes, num, _ = masked_unique(src, jnp.ones(budget, bool), budget)
    return nodes, jnp.minimum(num, budget)


def _uniform_edge_endpoints(topo, key, budget: int):
    """Device-side uniform edge draw -> dedup'd endpoint set (cap 2*budget)."""
    E = topo.indices.shape[0]
    eids = _uniform_edge_positions(key, budget, E, topo.indptr.dtype)
    dst = staged_gather(topo.indices, eids, topo.host_indices).astype(jnp.int32)
    src = (
        jnp.searchsorted(topo.indptr, eids, side="right").astype(jnp.int32) - 1
    )
    both = jnp.concatenate([src, dst])
    nodes, num, _ = masked_unique(both, both >= 0, 2 * budget)
    return nodes, jnp.minimum(num, 2 * budget)


@functools.partial(jax.jit, static_argnames=("budget", "deg_cap"))
def _saint_node_sample(topo, key, budget: int, deg_cap: int):
    nodes, num = _degree_proportional_nodes(topo, key, budget)
    return saint_subgraph(topo, nodes, num, deg_cap)


@functools.partial(jax.jit, static_argnames=("budget", "deg_cap"))
def _saint_edge_sample(topo, key, budget: int, deg_cap: int):
    nodes, num = _uniform_edge_endpoints(topo, key, budget)
    return saint_subgraph(topo, nodes, num, deg_cap)


@functools.partial(
    jax.jit, static_argnames=("roots", "walk_length", "deg_cap")
)
def _saint_rw_sample(topo, key, roots: int, walk_length: int, deg_cap: int):
    kr, kw = jax.random.split(key)
    n_nodes = topo.indptr.shape[0] - 1
    starts = jax.random.randint(kr, (roots,), 0, n_nodes, dtype=jnp.int32)
    visited = random_walk(topo, starts, walk_length, kw).reshape(-1)
    budget = roots * (walk_length + 1)
    nodes, num, _ = masked_unique(visited, visited >= 0, budget)
    return saint_subgraph(topo, nodes, jnp.minimum(num, budget), deg_cap)


class _SaintSamplerBase:
    """Shared machinery: node-budget padding, fully-fused jitted sampling.

    Each ``sample()`` is ONE compiled program — random draw, dedup
    (ops/reindex.masked_unique), and subgraph induction all on device; the
    host only advances the PRNG key (VERDICT r2 item 5: the original
    round-1 design re-entered the host for ``np.unique`` + RNG every batch,
    fine as preprocessing but a per-batch sync in a training loop).

    ``deg_cap`` defaults to the 99th-percentile degree (not max_degree: the
    subgraph extraction materializes (budget, deg_cap) blocks, and a
    power-law hub would blow that up by orders of magnitude for edges that
    overwhelmingly fail the membership test anyway). Pass
    ``deg_cap=csr_topo.max_degree`` for exact induced subgraphs.
    """

    def __init__(self, csr_topo: CSRTopo, budget: int, deg_cap: int | None = None,
                 seed: int = 0):
        self.csr_topo = csr_topo
        self.budget = int(budget)
        if deg_cap is None:
            deg = csr_topo.degree
            p99 = int(np.percentile(deg, 99)) if deg.size else 1
            deg_cap = min(max(p99, 1), max(csr_topo.max_degree, 1))
        self.deg_cap = int(deg_cap)
        self.topo = csr_topo.to_device()
        self._key = jax.random.PRNGKey(seed)
        self._call = 0

    def _next_key(self):
        self._call += 1
        return jax.random.fold_in(self._key, self._call)

    def sample(self) -> SaintSubgraph:
        raise NotImplementedError


class SAINTNodeSampler(_SaintSamplerBase):
    """GraphSAINT-Node: sample ``budget`` nodes with probability proportional
    to degree (the paper's importance distribution), induce the subgraph."""

    def sample(self) -> SaintSubgraph:
        return _saint_node_sample(
            self.topo, self._next_key(), self.budget, self.deg_cap
        )


class SAINTEdgeSampler(_SaintSamplerBase):
    """GraphSAINT-Edge: sample ``budget`` edges uniformly, take both
    endpoints as the node set, induce the subgraph. Node budget = 2*edges."""

    def __init__(self, csr_topo, budget, deg_cap=None, seed=0):
        if csr_topo.edge_count == 0:
            raise ValueError("SAINTEdgeSampler needs a graph with edges")
        super().__init__(csr_topo, budget, deg_cap, seed)

    def sample(self) -> SaintSubgraph:
        return _saint_edge_sample(
            self.topo, self._next_key(), self.budget, self.deg_cap
        )


class SAINTRandomWalkSampler(_SaintSamplerBase):
    """GraphSAINT-RW: ``roots`` uniform random roots, each walking
    ``walk_length`` uniform steps; the visited set induces the subgraph.

    Roots, walk, dedup, and induction are a single compiled program."""

    def __init__(self, csr_topo, roots: int, walk_length: int,
                 deg_cap=None, seed=0):
        budget = roots * (walk_length + 1)
        super().__init__(csr_topo, budget, deg_cap, seed)
        self.roots = int(roots)
        self.walk_length = int(walk_length)

    def sample(self) -> SaintSubgraph:
        return _saint_rw_sample(
            self.topo, self._next_key(), self.roots, self.walk_length,
            self.deg_cap,
        )


def random_walk(topo, starts, walk_length: int, key):
    """Uniform random walks: (R,) starts -> (R, walk_length+1) visited ids.

    Dead-end nodes (deg 0) stay in place (emit their own id), so every lane
    stays valid — a padded-shape-friendly convention.
    """
    R = starts.shape[0]
    cur = starts
    out = [starts]
    n = jnp.int32(R)
    for _ in range(walk_length):
        key, sub = jax.random.split(key)
        nbr, _ = sample_layer(topo, cur, n, 1, sub)
        step = nbr[:, 0]
        cur = jnp.where(step >= 0, step, cur)
        out.append(cur)
    return jnp.stack(out, axis=1)


def estimate_saint_norm(sampler, num_iters: int = 50):
    """Estimate GraphSAINT's loss normalization by pre-sampling.

    Runs ``num_iters`` subgraph draws and counts per-node appearances;
    returns (node_norm (N,), counts (N,)) where node_norm[v] ~ 1 / P(v in
    subgraph) scaled to mean 1 over appearing nodes — multiply each node's
    loss term by node_norm to unbias the estimator (GraphSAINT eq. 2's
    lambda). Nodes never sampled get norm 0.
    """
    N = sampler.csr_topo.node_count
    counts = np.zeros(N, dtype=np.int64)
    for _ in range(num_iters):
        sub = sampler.sample()
        ids = np.asarray(sub.node_id)
        counts[ids[ids >= 0]] += 1
    freq = counts / num_iters
    norm = np.zeros(N, dtype=np.float32)
    seen = freq > 0
    norm[seen] = 1.0 / freq[seen]
    if seen.any():
        norm /= norm[seen].mean()
    return norm, counts
