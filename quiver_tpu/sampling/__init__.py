"""Neighbor samplers: the package's public sampling surface.

One family, four members, one output contract:

* :class:`GraphSageSampler` — replicated-topology k-hop sampler
  (uniform, weighted, or temporal time-windowed draws; ``xla`` or
  ``pallas`` kernels).
* :class:`DistGraphSageSampler` — the same sampler over a mesh-sharded
  topology (``core.sharded_topology.ShardedTopology``): owner-routed
  hops, bit-identical per worker to the replicated sampler.
* :class:`HeteroGraphSampler` — typed (heterogeneous) relations over a
  ``HeteroCSRTopo``; per-relation fanouts and per-type frontiers.
* :class:`DistHeteroSampler` — the typed sampler over per-relation
  mesh partitions (``core.hetero_sharded.HeteroShardedTopology``), one
  shared route plan per (hop, destination type).

Plus the graph-sampling alternatives (:class:`SAINTNodeSampler` et al.)
and the shared output records (:class:`Adj`, :class:`SampleOutput`,
:class:`HeteroLayer`, :class:`HeteroSampleOutput`).
"""

from .dist import (
    DistGraphSageSampler,
    dist_multilayer_sample,
    dist_sample_layer,
    routed_sample_cap,
)
from .dist_hetero import DistHeteroSampler, dist_hetero_multilayer_sample
from .hetero import HeteroGraphSampler, HeteroLayer, HeteroSampleOutput
from .saint import (
    SAINTEdgeSampler,
    SAINTNodeSampler,
    SAINTRandomWalkSampler,
    saint_subgraph,
)
from .sampler import Adj, GraphSageSampler, SampleOutput

__all__ = [
    "Adj",
    "SampleOutput",
    "GraphSageSampler",
    "DistGraphSageSampler",
    "HeteroLayer",
    "HeteroSampleOutput",
    "HeteroGraphSampler",
    "DistHeteroSampler",
    "SAINTNodeSampler",
    "SAINTEdgeSampler",
    "SAINTRandomWalkSampler",
    "saint_subgraph",
    "dist_sample_layer",
    "dist_multilayer_sample",
    "dist_hetero_multilayer_sample",
    "routed_sample_cap",
]
