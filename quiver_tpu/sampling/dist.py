"""Distributed neighbor sampling over a mesh-sharded topology.

The scale-out counterpart of ``GraphSageSampler``: the graph lives as a
:class:`~quiver_tpu.core.sharded_topology.ShardedTopology` (contiguous
row ranges of the CSR across the mesh's ``feature`` axis, ~1/F topology
bytes per chip) and every device is a full sampling worker over its own
seed block. Each hop runs inside ``shard_map``:

1. route every frontier vertex to its owning shard with the PR 1
   capped-bucket ``all_to_all`` (``parallel/routing.py`` — the SAME
   audited code path the sharded feature gather uses);
2. the owner answers the vertex's degree (one capped hop back);
3. the requester draws the per-vertex sample offsets with the EXACT
   stratified+rotation scheme of the replicated kernel
   (``ops/sample.py`` ``stratified_offsets``/``rotate_offsets``, same key,
   same shapes — this is what makes the distributed sampler bit-identical
   to the replicated one);
4. the offsets ride the same buckets to the owner, which gathers the
   neighbor ids from its local CSR slice and routes the ``(cap, k)``
   neighbor blocks back.

Bucket overflow is detected in-program and served EXACTLY via the
cond-gated psum fallback (never silent, never wrong), counted, and
surfaced as ``last_sample_overflow`` — the sampling sibling of
``last_routed_overflow``/``last_tier_hits``.

Comm model (L = per-device frontier width, F = shards, k = fanout,
``cap = ceil(alpha * L / F)``): the four ``all_to_all`` hops move
``F*cap``, ``F*cap``, ``F*cap*k`` and ``F*cap*k`` lanes — ``~alpha * L *
(2 + 2k)`` total vs the exact-safe full-length ``F * L * (2 + 2k)``; the
id lanes of the second exchange are not re-sent (the route plan caches
them).

Weighted and temporal draws ride the SAME route plan. The weighted hop
adds one f32 exchange (per-row total weight back) and moves the
inverse-CDF binary search to the owner, which searches its routed
prefix-weight segment — bitwise identical f32 values to the replicated
array's row, so the draw is bit-identical too (+``F*cap`` f32 lanes; the
offsets-out hop carries the (S, k) f32 uniform block instead of int32
offsets). The temporal hop answers ``(first, deg_t)`` in-window slot
ranges in place of plain degrees (one int32 exchange with trailing dim
2, +``F*cap`` lanes over uniform).

Bit-parity contract: for the same seed block, PRNG key, fanouts, frontier
caps, and dedup strategy, every per-worker ``SampleOutput`` (n_id, adjs)
is bit-identical to the replicated ``GraphSageSampler``'s on that block
with key ``fold_in(key, worker_index)`` — capping and routing change which
wires the bits cross, never the bits.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.config import SampleMode
from ..core.sharded_topology import ShardedTopology
from ..core.topology import CSRTopo
from ..obs.registry import SAMPLE_OVERFLOW, MetricsRegistry
from ..ops.reindex import reindex_layer, resolve_dedup
from ..ops.sample import rotate_offsets, stratified_offsets
from ..parallel.mesh import FEATURE_AXIS, shard_map
from ..parallel.routing import BucketRoute
from ..utils.trace import info_once, trace_scope
from .sampler import Adj, GraphSageSampler, SampleOutput, _round_up

__all__ = [
    "DistGraphSageSampler",
    "dist_sample_layer",
    "dist_multilayer_sample",
    "routed_sample_cap",
]


def routed_sample_cap(length: int, num_shards: int,
                      alpha: float | None) -> int | None:
    """Per-destination bucket capacity for a frontier of width ``length``:
    ``ceil(alpha * L / F)`` clamped to [1, L]; ``None`` (or a cap >= L)
    means the exact-safe full-length buckets."""
    if alpha is None:
        return None
    if alpha <= 0:
        raise ValueError(f"alpha must be > 0, got {alpha}")
    # graftlint: disable=host-op-on-tracer -- L is the static lane width
    cap = -(-int(alpha * length) // max(num_shards, 1))
    # graftlint: disable=host-op-on-tracer -- L is the static lane width
    cap = max(1, min(cap, int(length)))
    return None if cap >= length else cap


def _worker_index(mesh):
    """Flat worker index over every mesh axis (axis-name order) — the same
    fold-in scheme the seed_sharding="all" trainer uses."""
    idx = jnp.zeros((), jnp.int32)
    for a in mesh.axis_names:
        idx = idx * mesh.shape[a] + jax.lax.axis_index(a)
    return idx


def dist_sample_layer(local_indptr, local_indices, rows_per_shard: int,
                      seeds, num_seeds, k: int, key, *, axis: str,
                      num_shards: int, cap: int | None,
                      weighted: bool = False, local_cum_weights=None,
                      time_window=None, local_edge_time=None,
                      search_iters: int = 0, route=None,
                      kernel: str = "xla"):
    """One distributed hop (per-device body; call inside ``shard_map``).

    Args:
      local_indptr: (rows_per_shard + 1,) this shard's rebased indptr.
      local_indices: (padded_edges,) this shard's CSR indices slice.
      seeds: (S,) node ids, -1 padded (valid entries occupy a prefix).
      num_seeds: scalar count of valid seeds.
      k: static fanout.
      key: PRNG key — consumed exactly like the replicated
        ``sample_layer`` (same splits over the same (S, k) shapes; the
        weighted draw consumes it unsplit, also matching), which is what
        makes results bit-identical.
      axis / num_shards: the mesh axis the topology is sharded over.
      cap: per-destination routed-bucket capacity (None = uncapped).
      weighted: inverse-CDF weighted draw against the owner's routed
        prefix-weight segments; requires ``local_cum_weights`` (this
        shard's (padded_edges,) slice of ``CSRTopo.cum_weights``).
      time_window: optional ``(lo, hi)`` scalar timestamps; the owner
        binary-searches each routed row's in-window slot range and the
        requester draws within it (masked degrees). Requires
        ``local_edge_time``; mutually exclusive with ``weighted``.
      search_iters: static binary-search bound for the weighted/temporal
        paths — MUST derive from the GLOBAL max degree so every shard
        (and the replicated oracle) runs the same loop.
      route: an existing ``BucketRoute`` built over this hop's ``seeds``
        (the hetero sampler shares ONE route per destination type across
        every relation into it — the plan's id lanes are sent once and
        cached). ``None`` builds a fresh route.
      kernel: "xla" or "pallas" — with "pallas" the OWNER-side neighbor
        gather and weighted CDF walk run on the fused Pallas engine
        (ops/pallas/fused.py ``fused_select_hop``/``fused_weighted_hop``;
        the same audited kernel as the replicated sampler), and every bit
        crossing the wires is unchanged, so the parity contract holds.
        Callers must guarantee ``window <= E_local <= int32 max`` and that
        every row fits one DMA window (global ``max_degree <= window``) —
        ``DistGraphSageSampler._compiled`` gates this and degrades to xla;
        direct callers that break it get a loud ValueError.

    Returns (neighbors (S, k) int32 -1-masked, counts (S,), overflow
    scalar — the axis-group total of fallback-served lanes).
    """
    from ..ops.sample import _cdf_search, temporal_window_counts

    S = seeds.shape[0]
    valid = (jnp.arange(S) < num_seeds) & (seeds >= 0)
    s = jnp.where(valid, seeds, 0)
    my = jax.lax.axis_index(axis)
    E_local = local_indices.shape[0]
    base_dtype = (
        jnp.int64 if E_local > np.iinfo(np.int32).max else jnp.int32
    )
    use_pallas = kernel == "pallas"
    if use_pallas:
        from ..ops.pallas.fused import (
            DEFAULT_WINDOW,
            fused_select_hop,
            fused_weighted_hop,
        )

        if (E_local < DEFAULT_WINDOW
                or E_local > np.iinfo(np.int32).max
                or k > DEFAULT_WINDOW):
            raise ValueError(
                f"kernel='pallas' needs {DEFAULT_WINDOW} <= local edge "
                f"count <= int32 max and fanout <= {DEFAULT_WINDOW} (got "
                f"E_local={E_local}, k={k}); DistGraphSageSampler gates "
                f"this at compile time — use kernel='xla' here"
            )

    def _mine_local(ids):
        # ownership-masked local row index — zero answers for lanes this
        # shard does not own make the route's psum fallback exact
        mine = (ids >= 0) & (ids // rows_per_shard == my)
        return mine, jnp.where(mine, ids - my * rows_per_shard, 0)

    def _local_row(r):
        base = local_indptr[r].astype(base_dtype)
        deg = (local_indptr[r + 1] - local_indptr[r]).astype(jnp.int32)
        return base, deg

    def serve_deg(ids):
        mine, r = _mine_local(ids)
        _, deg = _local_row(r)
        return jnp.where(mine, deg, 0)

    def serve_nbr(ids, offs):
        mine, r = _mine_local(ids)
        base, _ = _local_row(r)
        if use_pallas:
            # fused owner-side gather: one window DMA per routed lane +
            # in-kernel one-hot select. Callers guarantee every owned row
            # fits the window (global max_degree <= window) and offs <
            # deg, so start = clip(base) keeps base+offs in-window; lanes
            # this shard does not own read row 0's window (in-bounds, any
            # value) and are zero-masked below, exactly like the clipped
            # XLA gather — the bits after the mask are identical.
            start = jnp.clip(
                base, 0, E_local - DEFAULT_WINDOW).astype(jnp.int32)
            woffs = offs.astype(jnp.int32) + (
                base.astype(jnp.int32) - start)[:, None]
            (nbr,) = fused_select_hop(
                local_indices.astype(jnp.int32), start, woffs,
                window=DEFAULT_WINDOW)
        else:
            epos = base[:, None] + offs.astype(base.dtype)
            nbr = local_indices[jnp.clip(epos, 0, E_local - 1)]
        return jnp.where(mine[:, None], nbr, 0).astype(jnp.int32)

    if route is None:
        route = BucketRoute(
            s, valid, s // rows_per_shard, axis=axis, num_shards=num_shards,
            cap=cap,
        )

    if weighted:
        # weighted hop: (1) ids out / degrees back, (2) row weight totals
        # back (same buckets, f32 — one answer dtype per exchange), (3)
        # the requester's uniform block out / weight-drawn neighbor ids
        # back. The requester consumes the key UNSPLIT over the same
        # (S, k) uniform block as ops.sample.weighted_offsets, and the
        # owner's prefix slice is bitwise identical to the replicated
        # array's row segment — bit parity by construction.
        def serve_tot(ids):
            mine, r = _mine_local(ids)
            base, deg = _local_row(r)
            end = jnp.clip(base + deg.astype(base.dtype) - 1, 0, E_local - 1)
            tot = local_cum_weights[end]
            return jnp.where(mine & (deg > 0), tot, 0.0)

        def serve_wnbr(ids, u):
            mine, r = _mine_local(ids)
            base, deg = _local_row(r)
            if use_pallas:
                # the fused in-kernel CDF walk is the affine shift of
                # _cdf_search by the window start (see ops/pallas/fused.py
                # for the probe-parity proof); u arrives pre-scaled by the
                # tot exchange, so scale_u=False. The take-all override
                # (local deg equals global deg) runs in-kernel.
                start = jnp.clip(
                    base, 0, E_local - DEFAULT_WINDOW).astype(jnp.int32)
                off0 = (base - start.astype(base.dtype)).astype(jnp.int32)
                nbr, _ = fused_weighted_hop(
                    local_indices.astype(jnp.int32), local_cum_weights,
                    start, off0, deg, u, search_iters, scale_u=False,
                    window=DEFAULT_WINDOW)
            else:
                off = _cdf_search(
                    local_cum_weights, u, base, deg, search_iters)
                i = jnp.arange(k, dtype=jnp.int32)[None, :]
                degc = deg[:, None]
                # the replicated kernel's take-all override
                # (weighted_offsets): local deg equals global deg, so
                # this matches exactly
                off = jnp.where(
                    degc <= k, jnp.minimum(i, jnp.maximum(degc - 1, 0)), off
                )
                epos = base[:, None] + off.astype(base.dtype)
                nbr = local_indices[jnp.clip(epos, 0, E_local - 1)]
            return jnp.where(mine[:, None], nbr, 0).astype(jnp.int32)

        deg = route.exchange(serve_deg)
        tot = route.exchange(serve_tot)
        tot = jnp.where(deg > 0, tot, 1.0)
        u = jax.random.uniform(
            key, (S, k), dtype=local_cum_weights.dtype
        ) * tot[:, None]
        nbr = route.exchange(serve_wnbr, payload=u)
        i = jnp.arange(k, dtype=jnp.int32)[None, :]
        mask = valid[:, None] & (i < jnp.minimum(deg[:, None], k))
    elif time_window is not None:
        # temporal hop: the owner answers each routed row's in-window slot
        # range (first, deg_t) — both int32, so they ride ONE exchange —
        # and the requester draws the replicated scheme over the masked
        # degrees, rebasing offsets by `first` before the neighbor hop.
        lo_t, hi_t = time_window

        def serve_window(ids):
            mine, r = _mine_local(ids)
            base, deg = _local_row(r)
            first, deg_t = temporal_window_counts(
                local_edge_time, base, deg, lo_t, hi_t, search_iters
            )
            out = jnp.stack([first, deg_t], axis=-1)
            return jnp.where(mine[:, None], out, 0)

        win = route.exchange(serve_window)
        first, deg = win[:, 0], win[:, 1]
        kj, kr = jax.random.split(key)
        off_nr, mask_sel = stratified_offsets(kj, deg, k)
        off = rotate_offsets(kr, off_nr, deg, k)
        mask = valid[:, None] & mask_sel
        nbr = route.exchange(serve_nbr, payload=first[:, None] + off)
    else:
        # hop pair 1: ids out, degrees back — the requester needs deg to
        # draw the same offsets the replicated kernel would
        deg = route.exchange(serve_deg)
        # identical draw scheme/key discipline as ops.sample.sample_layer
        kj, kr = jax.random.split(key)
        off_nr, mask_sel = stratified_offsets(kj, deg, k)
        off = rotate_offsets(kr, off_nr, deg, k)
        mask = valid[:, None] & mask_sel
        # hop pair 2: offsets out (same buckets, ids not re-sent),
        # neighbor blocks back
        nbr = route.exchange(serve_nbr, payload=off)
    nbr = jnp.where(mask, nbr, -1).astype(jnp.int32)
    counts = jnp.where(valid, jnp.minimum(deg, k), 0)
    return nbr, counts, route.overflow


def dist_multilayer_sample(local_indptr, local_indices, rows_per_shard: int,
                           seeds, num_seeds, key, sizes, caps, *, axis: str,
                           num_shards: int, routed_alpha: float | None = 2.0,
                           dedup: str = "sort", node_count: int | None = None,
                           weighted: bool = False, local_cum_weights=None,
                           time_window=None, local_edge_time=None,
                           search_iters: int = 0, kernel: str = "xla"):
    """Multi-layer distributed sample+reindex loop (per-device body).

    The sharded-topology twin of ``sampling.sampler.multilayer_sample`` —
    the reindex/Adj assembly is byte-for-byte the same discipline; only the
    per-hop neighbor lookup is owner-routed. Returns the same tuple plus a
    trailing ``hop_overflows``: per-hop fallback-served lane counts
    (axis-group totals, seeds-outward order) — the ``last_sample_overflow``
    telemetry source.
    """
    dedup = resolve_dedup(dedup)
    adjs = []
    edge_counts = []
    frontier_counts = []
    hop_overflows = []
    cur, cur_n = seeds, num_seeds
    total_overflow = jnp.zeros((), jnp.int32)
    for l, k in enumerate(sizes):
        key, sub = jax.random.split(key)
        S = cur.shape[0]
        cap = routed_sample_cap(S, num_shards, routed_alpha)
        with trace_scope(f"dist_sample_layer_{l}"):
            nbr, counts, hop_ov = dist_sample_layer(
                local_indptr, local_indices, rows_per_shard, cur, cur_n, k,
                sub, axis=axis, num_shards=num_shards, cap=cap,
                weighted=weighted, local_cum_weights=local_cum_weights,
                time_window=time_window, local_edge_time=local_edge_time,
                search_iters=search_iters, kernel=kernel,
            )
        hop_overflows.append(hop_ov)
        with trace_scope(f"reindex_layer_{l}"):
            node_bound = node_count if dedup == "map" else None
            frontier, n_frontier, col, overflow = reindex_layer(
                cur, cur_n, nbr, caps[l], node_bound=node_bound,
                scatter_free=(dedup == "scan"),
            )
        row = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[:, None], (S, k))
        row = jnp.where(col >= 0, row, -1)
        edge_index = jnp.stack([col.reshape(-1), row.reshape(-1)])
        adjs.append(Adj(edge_index, None, (caps[l], S), fanout=k))
        del counts
        edge_counts.append(jnp.sum((col >= 0).astype(jnp.int32)))
        frontier_counts.append(n_frontier + overflow)
        cur, cur_n = frontier, n_frontier
        total_overflow = total_overflow + overflow
    return (cur, cur_n, adjs[::-1], total_overflow,
            tuple(edge_counts[::-1]), tuple(frontier_counts[::-1]),
            tuple(hop_overflows))


class DistGraphSageSampler(GraphSageSampler):
    """K-hop sampler over a mesh-sharded topology.

    Constructed directly or via ``GraphSageSampler(...,
    topo_sharding="mesh", mesh=mesh)``. Every device of the mesh is a full
    sampling worker over its own seed block (the ``seed_sharding="all"``
    discipline); per-hop neighbor lookups route frontier vertices to the
    shard owning their CSR row (see the module docstring for the comm
    model and the bit-parity contract).

    Supports the replicated sampler's ``weighted=True`` (the shards carry
    row-local prefix-weight slices and the owner answers inverse-CDF
    draws — see ``dist_sample_layer``) and ``time_window`` (owner-answered
    in-window slot ranges) biased draws, each bit-identical to its
    replicated counterpart. The ``kernel`` knob matches the replicated
    sampler too: with "pallas" (or an auto election landing there) the
    owner-side gathers and weighted CDF walks run on the fused Pallas
    engine — bits on the wire unchanged — degrading per compile to xla
    (one INFO) when a shard's slice cannot host the window DMA.
    Constraints vs the replicated sampler: HBM mode and no ``with_eid``
    (that path stays on the replicated ``GraphSageSampler``; the sharded
    CSR slices do not carry eid).
    ``routed_alpha`` is the shared capped-bucket routing budget —
    ``cap = ceil(alpha * L / F)`` lanes per destination per hop; ``None``
    = uncapped full-length buckets. The ``DistributedTrainer`` drives this
    sampler and the sharded feature store with ONE alpha (one budget, one
    tuner).

    After an eager :meth:`sample`, ``last_sample_overflow`` holds the
    per-hop fallback-served lane counts (int32 ``(num_layers,)`` device
    vector, seeds-outward) — same telemetry discipline as
    ``last_routed_overflow``.
    """

    def __init__(
        self,
        csr_topo: CSRTopo,
        sizes,
        device=None,
        mode: str | SampleMode = SampleMode.HBM,
        seed_capacity: int | None = None,
        frontier_caps=None,
        seed: int = 0,
        weighted: bool = False,
        time_window=None,
        auto_margin: float = 1.25,
        kernel: str = "auto",
        with_eid: bool = False,
        dedup: str = "auto",
        device_topo=None,
        topo_sharding: str = "mesh",
        mesh=None,
        routed_alpha: float | None = 2.0,
        axis: str = FEATURE_AXIS,
    ):
        if topo_sharding != "mesh":
            raise ValueError(
                f"DistGraphSageSampler is the topo_sharding='mesh' sampler; "
                f"got topo_sharding={topo_sharding!r}"
            )
        if mesh is None:
            raise ValueError("topo_sharding='mesh' requires mesh=")
        if with_eid:
            raise ValueError(
                "with_eid over a sharded topology is not supported; the "
                "sharded CSR slices do not carry eid — use the replicated "
                "GraphSageSampler"
            )
        if SampleMode.parse(mode) is not SampleMode.HBM:
            raise ValueError(
                "topo_sharding='mesh' requires mode='HBM': each shard's CSR "
                "slice is device-resident (that is the point — per-chip "
                "bytes shrink 1/F instead of staging through host)"
            )
        if device_topo is not None:
            raise ValueError(
                "device_topo cannot be combined with topo_sharding='mesh'"
            )
        if routed_alpha is not None and routed_alpha <= 0:
            raise ValueError(
                f"routed_alpha must be > 0 or None, got {routed_alpha}"
            )
        self.mesh = mesh
        self.axis = axis
        self.routed_alpha = (
            None if routed_alpha is None else float(routed_alpha)
        )
        # graftscope registry: per-hop fallback-served lane counts of the
        # last eager sample land here (``last_sample_overflow`` is a thin
        # view; int32 (num_layers,) device vector, seeds-outward; None
        # before any)
        self.metrics = MetricsRegistry()
        self.metrics.counter(
            SAMPLE_OVERFLOW, shape=(len(tuple(sizes)),), unit="lanes",
            doc="per-hop fallback-served lanes of the last distributed "
                "sample (seeds-outward)",
        )
        super().__init__(
            csr_topo, sizes, device=device, mode=mode,
            seed_capacity=seed_capacity, frontier_caps=frontier_caps,
            seed=seed, weighted=weighted, time_window=time_window,
            auto_margin=auto_margin, kernel=kernel, with_eid=with_eid,
            dedup=dedup,
        )
        self.topo_sharding = "mesh"

    @property
    def last_sample_overflow(self):
        """Per-hop fallback-served lane counts of the last eager sample
        (thin view of the ``sample.hop_overflow`` registry metric — new
        consumers should read ``self.metrics``)."""
        return self.metrics.value(SAMPLE_OVERFLOW)

    @last_sample_overflow.setter
    def last_sample_overflow(self, value):
        self.metrics.set(SAMPLE_OVERFLOW, value)

    # -- topology placement (overrides the replicated upload) ---------------

    def _init_topo(self, device_topo):
        return ShardedTopology(
            self.mesh, self.csr_topo, axis=self.axis,
            with_weights=self.weighted,
            with_times=self.time_window is not None,
        )

    def _topo_operands(self) -> tuple:
        """Per-shard topology arrays, in the order the compiled body
        expects them: indptr, indices, then whichever edge attributes this
        sampler's draw needs (all ``(F, ...)`` with ``P(axis, None)``)."""
        ops = [self.topo.indptr, self.topo.indices]
        if self.weighted:
            ops.append(self.topo.cum_weights)
        if self.time_window is not None:
            ops.append(self.topo.edge_time)
        return tuple(ops)

    def replan(self, mesh) -> "DistGraphSageSampler":
        """Re-partition the topology onto a different mesh (elastic
        resume) and drop the compiled-program cache (programs bake in the
        old mesh). Sampling parameters, the PRNG stream, and the
        bit-parity contract are untouched: per seed block and key, the
        re-planned sampler draws exactly what the old one would — only
        the owner routing changes shape."""
        self.mesh = mesh
        self.topo = self.topo.replan(mesh, axis=self.axis)
        self._compiled_cache.clear()
        return self

    @property
    def workers(self) -> int:
        """Seed-block workers: every device of the mesh."""
        w = 1
        for a in self.mesh.axis_names:
            w *= self.mesh.shape[a]
        return w

    # -- compiled program ---------------------------------------------------

    def _compiled(self, seed_cap: int):
        caps = self._caps_for(seed_cap)
        cache_key = (seed_cap, caps, self.routed_alpha)
        if cache_key in self._compiled_cache:
            return self._compiled_cache[cache_key]
        mesh, axis = self.mesh, self.axis
        F = mesh.shape[axis]
        sizes, dedup = self.sizes, self.dedup
        alpha = self.routed_alpha
        n = self.csr_topo.node_count
        rps = self.topo.rows_per_shard
        ids_axes = tuple(mesh.axis_names)
        other_axes = tuple(a for a in mesh.axis_names if a != axis)
        n_layers = len(sizes)
        weighted = self.weighted
        time_window = self.time_window
        iters = self.topo.search_iters
        n_topo = len(self._topo_operands())
        kernel = self.kernel  # resolved request (may run the election)
        if kernel == "pallas":
            from ..ops.pallas.fused import DEFAULT_WINDOW

            # compile-time eligibility for the fused owner-side kernel:
            # every shard's slice must host a full DMA window in int32
            # range, and every row (global max_degree — offsets route to
            # whichever shard owns the row) must fit one window
            E_local = int(self.topo.indices.shape[1])
            md = int(self.csr_topo.max_degree)
            bad = None
            if E_local < DEFAULT_WINDOW:
                bad = (f"per-shard edge slices hold {E_local} edges, fewer "
                       f"than the {DEFAULT_WINDOW}-edge DMA window")
            elif E_local > np.iinfo(np.int32).max:
                bad = f"per-shard edge slices exceed int32 range ({E_local})"
            elif md > DEFAULT_WINDOW:
                bad = (f"max_degree {md} exceeds the {DEFAULT_WINDOW}-slot "
                       f"window (owner-side rows must fit one window)")
            elif any(kf > DEFAULT_WINDOW for kf in sizes):
                bad = (f"a fanout in {sizes} exceeds the "
                       f"{DEFAULT_WINDOW}-slot window")
            if bad is not None:
                info_once(
                    "dist-sample-pallas-degrade",
                    "kernel='pallas' over the sharded topology falls back "
                    "to the XLA path: %s", bad,
                )
                kernel = "xla"

        def body(*args):
            # args: indptr, indices, [cum_weights], [edge_time], seeds, key
            # — the per-shard (1, ...) blocks of self._topo_operands()
            topo_blks, (seeds, key) = args[:n_topo], args[n_topo:]
            extra = list(topo_blks[2:])
            cum_blk = extra.pop(0)[0] if weighted else None
            time_blk = extra.pop(0)[0] if time_window is not None else None
            key = jax.random.fold_in(key, _worker_index(mesh))
            num_seeds = jnp.sum((seeds >= 0).astype(jnp.int32))
            (n_id, n_count, adjs, overflow, e_cnts, f_cnts,
             hop_ovs) = dist_multilayer_sample(
                topo_blks[0][0], topo_blks[1][0], rps, seeds, num_seeds, key,
                sizes, caps, axis=axis, num_shards=F, routed_alpha=alpha,
                dedup=dedup, node_count=n,
                weighted=weighted, local_cum_weights=cum_blk,
                time_window=time_window, local_edge_time=time_blk,
                search_iters=iters, kernel=kernel,
            )
            eis = tuple(a.edge_index for a in adjs)
            # per-worker scalar row: [n_count, frontier_overflow,
            # edge_counts (deepest-first), frontier_counts (deepest-first)]
            scal = jnp.stack(
                [n_count, overflow] + list(e_cnts) + list(f_cnts)
            ).astype(jnp.int32)
            hop_ov = jnp.stack(hop_ovs)  # (L,) axis-group totals
            if other_axes:  # replicate the mesh-wide totals
                hop_ov = jax.lax.psum(hop_ov, other_axes)
            return n_id, eis, scal, hop_ov

        run = jax.jit(
            shard_map(
                body,
                mesh=mesh,
                in_specs=(
                    (P(axis, None),) * n_topo + (P(ids_axes), P())
                ),
                out_specs=(
                    P(ids_axes),
                    tuple(P(None, ids_axes) for _ in range(n_layers)),
                    P(ids_axes),
                    P(),
                ),
                check_vma=False,
            )
        )
        self._compiled_cache[cache_key] = (run, caps)
        return run, caps

    # -- public API ---------------------------------------------------------

    def shard_seeds(self, seeds, local_cap: int) -> np.ndarray:
        """Split a global seed array into per-worker valid-prefix blocks,
        padded to (workers, local_cap) with -1 (same packing as the
        seed_sharding="all" trainer)."""
        seeds = np.asarray(seeds)
        blocks = np.array_split(seeds, self.workers)
        out = np.full((self.workers, local_cap), -1, np.int32)
        for i, b in enumerate(blocks):
            if len(b) > local_cap:
                raise ValueError(
                    f"per-worker block {len(b)} exceeds capacity {local_cap}"
                )
            out[i, : len(b)] = b
        return out

    def sample(self, input_nodes, key=None) -> SampleOutput:
        """Sample k-hop neighborhoods of a GLOBAL seed batch, split across
        every device of the mesh.

        Returns one worker-major global ``SampleOutput``: ``n_id`` is
        ``(workers * frontier_cap,)`` (each worker's block bit-identical
        to the replicated sampler's on that worker's seed block — see
        :meth:`sample_per_worker`), each ``adjs[l].edge_index`` is
        ``(2, workers * E_l)`` with per-worker ``Adj.size``/``fanout``,
        ``batch_size`` is the per-worker padded block width, ``n_count``/
        ``overflow``/``edge_counts`` are mesh totals and
        ``frontier_counts`` per-layer worker maxima. ``key`` overrides the
        sampler's own PRNG stream (each worker folds in its flat worker
        index on top).
        """
        self.check_topo_version()
        seeds = np.asarray(input_nodes)
        batch = int(seeds.shape[0])
        if batch and (seeds.min() < 0
                      or seeds.max() >= self.csr_topo.node_count):
            raise ValueError(
                f"seed ids must be in [0, {self.csr_topo.node_count}); "
                f"got range [{seeds.min()}, {seeds.max()}]"
            )
        W = self.workers
        per_worker = -(-batch // W) if batch else 1
        cap = self._seed_capacity or max(_round_up(per_worker, 128), 128)
        packed = self.shard_seeds(seeds, cap)
        if key is None:
            self._call += 1
            key = jax.random.fold_in(self._key, self._call)
        dev_seeds = jax.device_put(
            jnp.asarray(packed.reshape(-1)),
            NamedSharding(self.mesh, P(tuple(self.mesh.axis_names))),
        )
        run, used_caps = self._compiled(cap)
        n_id, eis, scal, hop_ov = run(
            *self._topo_operands(), dev_seeds, key
        )
        if self._auto_caps:
            n_layers = len(self.sizes)
            first_plan = self._frontier_caps is None
            for _ in range(n_layers + 2):
                sc = np.asarray(scal).reshape(W, 2 + 2 * n_layers)
                overflow = int(sc[:, 1].sum())
                if not first_plan and overflow == 0:
                    break
                # per-layer unclipped uniques, seeds-outward, worker max —
                # caps must cover the worst worker (one uniform program)
                observed = sc[:, 2 + n_layers:][:, ::-1].max(axis=0)
                before = self._frontier_caps
                self._plan_auto(cap, [int(o) for o in observed])
                if self._frontier_caps != before:
                    from ..utils.trace import get_logger

                    get_logger().info(
                        "dist auto caps %s: %s -> %s (recompile)",
                        "planned" if before is None else "regrown",
                        before, self._frontier_caps,
                    )
                if not first_plan and self._frontier_caps == before:
                    break  # saturated: clipped result + overflow stand
                if first_plan and overflow == 0:
                    first_plan = False
                    break
                run, used_caps = self._compiled(cap)
                n_id, eis, scal, hop_ov = run(
                    *self._topo_operands(), dev_seeds, key
                )
                first_plan = False
        self.last_sample_overflow = hop_ov
        return self._assemble(n_id, eis, scal, cap, used_caps, batch)

    def _assemble(self, n_id, eis, scal, seed_cap, caps, batch):
        W = self.workers
        n_layers = len(self.sizes)
        sc = np.asarray(scal).reshape(W, 2 + 2 * n_layers)
        # adjs deepest-first; per-layer frontier widths seeds-outward are
        # (seed_cap, caps[0], ..., caps[-2])
        widths = (seed_cap,) + tuple(caps[:-1])
        adjs = [
            Adj(ei, None, (caps[l], widths[l]), fanout=self.sizes[l])
            for l, ei in zip(range(n_layers - 1, -1, -1), eis)
        ]
        e_cnts = tuple(int(c) for c in sc[:, 2:2 + n_layers].sum(axis=0))
        f_cnts = tuple(int(c) for c in sc[:, 2 + n_layers:].max(axis=0))
        return SampleOutput(
            n_id, seed_cap, adjs,
            jnp.int32(int(sc[:, 0].sum())), jnp.int32(int(sc[:, 1].sum())),
            e_cnts, f_cnts,
        )

    def sample_per_worker(self, input_nodes, key=None) -> list[SampleOutput]:
        """:meth:`sample`, sliced into per-worker ``SampleOutput``s — each
        bit-comparable to the replicated ``GraphSageSampler``'s output on
        that worker's seed block with key
        ``fold_in(base_key, worker_index)``."""
        seeds = np.asarray(input_nodes)
        out = self.sample(seeds, key=key)
        W = self.workers
        n_layers = len(self.sizes)
        cap_last = out.n_id.shape[0] // W
        n_id = np.asarray(out.n_id).reshape(W, cap_last)
        blocks = np.array_split(seeds, W)
        per = []
        for w in range(W):
            adjs_w = []
            for a in out.adjs:
                E_l = a.edge_index.shape[1] // W
                ei = jnp.asarray(
                    np.asarray(a.edge_index).reshape(2, W, E_l)[:, w]
                )
                adjs_w.append(Adj(ei, None, a.size, fanout=a.fanout))
            per.append(SampleOutput(
                jnp.asarray(n_id[w]), len(blocks[w]), adjs_w,
                jnp.int32(0), jnp.int32(0), (), (),
            ))
        return per
