"""Multi-layer graph sampler with the PyG-compatible output contract.

Capability parity with the reference's ``quiver.pyg.GraphSageSampler``
(torch-quiver pyg/sage_sampler.py:22-133): a fanout list ``sizes``, per-layer
sample + reindex, ``Adj(edge_index, e_id, size)`` records returned deepest
layer first, and ``n_id[:batch_size] == seeds``. Differences forced by XLA
(SURVEY §7.1): all shapes are static — seeds are padded to ``seed_capacity``
and each layer's frontier to a precomputed cap — and the whole multi-layer
loop is one jitted program instead of one C++ call pair per hop
(sage_sampler.py:84-112).

No IPC/lazy-child-reinit machinery is needed (reference sage_sampler.py:71-79,
114-133): under single-controller SPMD there is exactly one process.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import NamedTuple, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..core.config import SampleMode
from ..core.topology import CSRTopo, DeviceTopology, VersionMismatchError
from ..ops.election import KernelElection, validate_kernel_arg
from ..ops.reindex import reindex_layer, resolve_dedup
from ..ops.sample import sample_layer
from ..utils.trace import get_logger, info_once, trace_scope

__all__ = ["Adj", "GraphSageSampler", "SampleOutput"]


@jax.tree_util.register_pytree_node_class
class Adj:
    """PyG-shaped adjacency record (mirrors reference Adj, sage_sampler.py:12-19).

    ``edge_index`` is (2, E_cap) with [0]=source (frontier-local neighbor id)
    and [1]=target (seed-local id); invalid edges have source == -1.
    ``size`` = (num_source_nodes_cap, num_target_nodes_cap) — static, so it
    survives jit boundaries as pytree metadata (models use it for
    ``num_segments``). Supports 3-tuple unpacking like PyG's Adj.

    ``fanout`` (static, None for hand-built Adjs): when set by the sampler
    it asserts the REGULAR edge layout — lane ``s*fanout + k`` targets seed
    ``s`` (or is invalid), so ``E_cap == size[1] * fanout``. Models use it
    to aggregate with dense (num_dst, fanout) reductions instead of
    segment scatters, which XLA serializes on TPU.
    """

    def __init__(self, edge_index, e_id, size: tuple[int, int],
                 fanout: int | None = None):
        self.edge_index = edge_index
        self.e_id = e_id
        self.size = tuple(size)
        self.fanout = fanout

    def __iter__(self):
        return iter((self.edge_index, self.e_id, self.size))

    def __repr__(self):
        return f"Adj(edge_index={self.edge_index.shape}, size={self.size})"

    def to(self, device):
        return Adj(
            jax.device_put(self.edge_index, device),
            None if self.e_id is None else jax.device_put(self.e_id, device),
            self.size,
            self.fanout,
        )

    def tree_flatten(self):
        return (self.edge_index, self.e_id), (self.size, self.fanout)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], *aux)


class SampleOutput(NamedTuple):
    n_id: jax.Array  # (frontier_cap,) node ids, seeds first, -1 padded
    batch_size: int
    adjs: list  # deepest layer first
    n_count: jax.Array  # scalar: valid entries in n_id
    overflow: jax.Array  # scalar: uniques dropped by frontier caps (0 = exact)
    edge_counts: tuple = ()  # per-layer valid-edge scalars, deepest first
    frontier_counts: tuple = ()  # per-layer UNCLIPPED unique counts, deepest first


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def multilayer_sample(topo, seeds, num_seeds, key, sizes, caps, weighted=False,
                      kernel="xla", with_eid=False, dedup="sort",
                      time_window=None):
    """The multi-layer sample+reindex loop (jit- and shard_map-composable).

    One trace covers all layers — the fused analogue of the reference's
    per-hop Python loop of C++ calls (sage_sampler.py:84-112). Shapes are
    fully static: ``sizes`` and ``caps`` are tuples of ints.

    With ``with_eid`` each Adj carries per-edge global edge ids aligned with
    its edge_index columns (-1 on invalid lanes) — the reference's per-hop
    ``e_id`` output (sage_sampler.py:100-109, reindex_single eid plumbing).
    Ids are original COO edge positions when the topology tracks ``eid``,
    raw CSR slots otherwise.

    Returns (n_id, n_count, adjs deepest-first, overflow, per-layer edge
    counts, per-layer unclipped frontier counts).
    """
    if kernel == "auto":
        kernel = resolve_sample_kernel(kernel)
    dedup = resolve_dedup(dedup)  # validates; maps "auto" per platform
    use_pallas = kernel == "pallas"
    if use_pallas:
        from ..ops.pallas.fused import DEFAULT_WINDOW, fused_sample_layer

        # trace-time eligibility for the fused kernel; every degrade is a
        # one-shot INFO (same info_once discipline as the other silent
        # fallback paths) and lands on the bitwise-identical XLA oracle
        E = int(topo.indices.shape[0])
        md = getattr(topo, "max_degree", None)
        if getattr(topo, "host_indices", False):
            info_once(
                "sample-pallas-host-topo",
                "kernel='pallas' needs an HBM-resident topology; this "
                "HOST-staged placement falls back to the XLA sampler",
            )
            use_pallas = False
        elif E < DEFAULT_WINDOW:
            # the kernel DMAs a full window per row; smaller graphs would
            # read past the edge array (trace-time constant)
            info_once(
                "sample-pallas-small-graph",
                "graph has %d edges, fewer than the Pallas sampler's "
                "%d-edge DMA window; kernel='pallas' falls back to the "
                "XLA path for this topology",
                E, DEFAULT_WINDOW,
            )
            use_pallas = False
        elif E - DEFAULT_WINDOW > np.iinfo(np.int32).max:
            info_once(
                "sample-pallas-int32-range",
                "edge count %d exceeds the fused kernel's int32 "
                "window-start range; falling back to the XLA sampler", E,
            )
            use_pallas = False
        elif weighted and (md is None or md > DEFAULT_WINDOW):
            # a truncated CDF segment would RE-WEIGHT the draw, not
            # attenuate it (unlike the accepted uniform hub-row policy),
            # so the weighted path refuses windowed rows outright
            info_once(
                "sample-pallas-weighted-window",
                "the fused weighted draw needs a known max_degree <= %d "
                "to keep each row's whole CDF segment in-window (got "
                "%s); falling back to the XLA draw", DEFAULT_WINDOW, md,
            )
            use_pallas = False
    adjs = []
    edge_counts = []
    frontier_counts = []
    cur, cur_n = seeds, num_seeds
    total_overflow = jnp.zeros((), jnp.int32)
    for l, k in enumerate(sizes):
        key, sub = jax.random.split(key)
        eids = None
        if use_pallas and k > DEFAULT_WINDOW:
            info_once(
                "sample-pallas-fanout",
                "fanout %d exceeds the %d-slot Pallas window; this hop "
                "falls back to the XLA sampler", k, DEFAULT_WINDOW,
            )
        with trace_scope(f"sample_layer_{l}"):
            if use_pallas and k <= DEFAULT_WINDOW:
                if with_eid:
                    nbr, counts, eids = fused_sample_layer(
                        topo, cur, cur_n, k, sub, weighted=weighted,
                        time_window=time_window, with_eid=True)
                else:
                    nbr, counts = fused_sample_layer(
                        topo, cur, cur_n, k, sub, weighted=weighted,
                        time_window=time_window)
            elif with_eid:
                nbr, counts, eids = sample_layer(topo, cur, cur_n, k, sub,
                                                 weighted=weighted, with_eid=True,
                                                 time_window=time_window)
            else:
                nbr, counts = sample_layer(topo, cur, cur_n, k, sub,
                                           weighted=weighted,
                                           time_window=time_window)
        with trace_scope(f"reindex_layer_{l}"):
            # dedup="map": sort-free scatter-min dedup over a dense
            # (node_count,) position map — the reference's hash-table
            # analogue (reindex.cu.hpp:120-139); node count is static
            # from the indptr shape
            node_bound = (
                int(topo.indptr.shape[0]) - 1 if dedup == "map" else None
            )
            frontier, n_frontier, col, overflow = reindex_layer(
                cur, cur_n, nbr, caps[l], node_bound=node_bound,
                scatter_free=(dedup == "scan"),
            )
        S = cur.shape[0]
        row = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[:, None], (S, k))
        row = jnp.where(col >= 0, row, -1)
        edge_index = jnp.stack([col.reshape(-1), row.reshape(-1)])
        if eids is not None:
            # re-mask with col: neighbors dropped by frontier-cap overflow
            # must not leak their edge ids
            eids = jnp.where(col >= 0, eids, -1).reshape(-1)
        adjs.append(Adj(edge_index, eids, (caps[l], S), fanout=k))
        # per-layer tallies in-program: benchmarks and the auto-cap planner
        # read scalars instead of reducing (2, E_cap) arrays on the host
        # path. Tallied POST-reindex (col >= 0), so overflow-dropped
        # neighbors are excluded — edge_counts[i] always equals the valid
        # edges actually present in adjs[i] (BASELINE.md honesty rule)
        del counts
        edge_counts.append(jnp.sum((col >= 0).astype(jnp.int32)))
        frontier_counts.append(n_frontier + overflow)
        cur, cur_n = frontier, n_frontier
        total_overflow = total_overflow + overflow
    return (cur, cur_n, adjs[::-1], total_overflow, tuple(edge_counts[::-1]),
            tuple(frontier_counts[::-1]))


# -- kernel=auto election (the gather precedent, ops/election.py) ------------

_PALLAS_SAMPLE_OK: bool | None = None


def _pallas_sample_usable() -> bool:
    """One-time differential smoke of the fused sampler (fail-safe for
    auto): the compiled fused kernel must return BITWISE the XLA oracle's
    output on a small synthetic graph before auto may elect pallas."""
    global _PALLAS_SAMPLE_OK
    if _PALLAS_SAMPLE_OK is None:
        try:
            from ..ops.pallas.fused import fused_sample_layer

            rng = np.random.default_rng(0)
            ei = rng.integers(0, 64, size=(2, 512))
            topo = CSRTopo(edge_index=ei).to_device()
            seeds = jnp.asarray(rng.integers(0, 64, 16), jnp.int32)
            key = jax.random.PRNGKey(0)
            want = sample_layer(topo, seeds, jnp.int32(16), 4, key)
            got = fused_sample_layer(topo, seeds, jnp.int32(16), 4, key,
                                     window=256)
            _PALLAS_SAMPLE_OK = all(
                np.array_equal(np.asarray(jax.block_until_ready(g)),
                               np.asarray(w))
                for g, w in zip(got, want)
            )
            if not _PALLAS_SAMPLE_OK:
                get_logger("sampler").warning(
                    "pallas sample smoke diverged from the XLA oracle; "
                    "kernel=auto degrades to xla"
                )
        except Exception as e:  # noqa: BLE001 — any compile failure degrades
            get_logger("sampler").warning(
                "pallas sample smoke failed (%s: %s); kernel=auto degrades "
                "to xla",
                type(e).__name__,
                str(e)[:200],
            )
            _PALLAS_SAMPLE_OK = False
    return _PALLAS_SAMPLE_OK


def _measure_sample_eps(kernel: str, nodes: int = 4096, edges: int = 1 << 18,
                        batch: int = 1024, k: int = 8, reps: int = 8) -> float:
    """Median sampled edges/s of one hop kernel over a fused seed-scan.

    Dispatch-clean by construction (the gather election's lesson): ONE
    program scans ``reps`` distinct seed batches — distinct keys so XLA
    cannot hoist the draw out of the scan — with a count-sum carry keeping
    every hop live, and one scalar readback ends the clock.
    """
    import time

    from jax import lax

    rng = np.random.default_rng(0)
    ei = rng.integers(0, nodes, size=(2, edges))
    topo = CSRTopo(edge_index=ei).to_device()
    seeds_mat = jax.random.randint(
        jax.random.PRNGKey(0), (reps, batch), 0, nodes, dtype=jnp.int32
    )
    if kernel == "pallas":
        from ..ops.pallas.fused import fused_sample_layer as hop
    else:
        hop = sample_layer
    key0 = jax.random.PRNGKey(1)

    @jax.jit
    def run(seeds_all):
        def step(carry, seeds):
            kcar, tot = carry
            kcar, sub = jax.random.split(kcar)
            _nbr, counts = hop(topo, seeds, jnp.int32(batch), k, sub)
            return (kcar, tot + jnp.sum(counts)), None
        (_, total), _ = lax.scan(step, (key0, jnp.int32(0)), seeds_all)
        return total

    jax.block_until_ready(run(seeds_mat))  # compile
    times = []
    for _ in range(3):
        t0 = time.time()
        jax.block_until_ready(run(seeds_mat))
        times.append(time.time() - t0)
    return reps * batch * k / sorted(times)[1]


# edges/s election between the fused Pallas megakernel (ops/pallas/fused.py)
# and the XLA stratified sampler — which stays forever as the bitwise
# differential oracle. The rev bumps when either sampler's implementation
# changes (same cache-invalidation discipline as feature.GATHER_ELECTION).
# smoke/measure defer module-global lookup so tests can monkeypatch them.
SAMPLE_ELECTION = KernelElection(
    "sample", env_var="QUIVER_SAMPLE_KERNEL", rev=1,
    smoke=lambda: _pallas_sample_usable(),  # noqa: PLW0108 — late binding
    measure=lambda kernel: _measure_sample_eps(kernel),
    unit="edges/s", log_child="sampler",
)


def resolve_sample_kernel(kernel: str) -> str:
    """Resolve the sampler kernel choice. Touches the backend, so callers
    defer this to first use (never the constructor).

    ``"auto"`` on TPU elects by measured throughput between the fused
    Pallas megakernel and the XLA sampler via the shared
    ``ops.election.KernelElection`` machinery: a one-time bitwise
    differential smoke gates Pallas (any divergence or compile failure
    degrades auto to xla with one warning), then a fused-scan micro-bench
    picks the faster kernel. The election is cached per process and in the
    shared ``QUIVER_ELECTION_CACHE`` disk file (keyed by device kind), and
    ``QUIVER_SAMPLE_KERNEL=pallas|xla`` overrides it — pinned at first
    use, same env-before-first-trace contract as the gather knob
    (tests/test_kernel_election.py). Off-TPU auto is xla (the interpret
    path is correct but slow). An explicit ``kernel="pallas"`` bypasses
    everything (fail loudly on request).
    """
    return SAMPLE_ELECTION.resolve_request(kernel)


class GraphSageSampler:
    """K-hop neighbor sampler over a device-resident CSR topology.

    Args:
      csr_topo: host CSRTopo.
      sizes: fanouts per layer, seeds outward; -1 = full neighborhood
        (capped at the graph's max degree, reference sage_sampler.py:67).
      mode: "HBM" (reference "GPU") or "HOST" (reference "UVA").
      seed_capacity: padded batch size; defaults to first sample() call's
        batch rounded up to a multiple of 128.
      frontier_caps: per-layer unique-node capacity; defaults to
        min(worst-case growth, node_count). Pass ``"auto"`` to right-size
        caps from the first batch's observed unique counts (×``auto_margin``)
        — worst-case caps vastly overshoot on power-law graphs (SURVEY
        §7.4.2), inflating every downstream gather/aggregate; auto mode
        trades one recompile (plus a rare recompile+resample when a later
        batch overflows the planned caps) for right-sized programs.
      seed: base PRNG seed (per-call keys derive from it + a call counter,
        like the reference's per-launch curand reseed, cuda_random.cu.hpp:21-23).
      time_window: optional ``(lo, hi)`` timestamp pair — every hop draws
        only from edges with ``lo <= t <= hi`` (masked degrees; expired
        edges never appear). Requires ``csr_topo.set_edge_time()``, HBM
        mode, kernel="xla", and is mutually exclusive with ``weighted``.
      auto_margin: headroom factor for "auto" caps (>= 1).
      kernel: "auto" (default — measured election, ``resolve_sample_kernel``),
        "xla" (exact stratified sampler), or "pallas" (the fused per-hop
        megakernel, ops/pallas/fused.py — HBM mode; every variant:
        uniform, weighted, temporal, with_eid — bitwise equal to the XLA
        oracle for rows with deg <= window, see the kernel's parity
        contract). ``QUIVER_SAMPLE_KERNEL`` overrides "auto" (pinned at
        first use). Ineligible topologies (graphs smaller than the DMA
        window, HOST placements, weighted graphs whose max_degree exceeds
        the window) degrade per hop to the XLA path with a one-shot INFO.
      with_eid: populate ``Adj.e_id`` with per-edge global edge ids
        (reference sage_sampler.py:100-109) — COO positions when the
        topology tracks ``eid``, CSR slots otherwise.
      dedup: reindex first-occurrence strategy — "sort" (stable sort +
        run scan), "map" (sort-free scatter-min into a dense (node_count,)
        position map, the reference hash-table analogue,
        reindex.cu.hpp:120-139), or "scan" (zero-scatter: sorts +
        cumulative max + gathers only — for backends where XLA scatter
        serializes). Identical results. Default "auto" picks per platform
        (ops.reindex.resolve_dedup: cpu->map measured, tpu->scan).
        ``QUIVER_DEDUP`` overrides the "auto" resolution ONLY — an
        explicit strategy here keeps what it names (the ignored force is
        logged once; see resolve_dedup).
      device_topo: advanced — reuse an existing DeviceTopology (built with
        compatible to_device flags) instead of uploading a fresh copy;
        lets many sampler configurations share one device-resident graph.
      device: accepted-and-INERT parity slot (the reference pins a CUDA
        ordinal, sage_sampler.py:26; under SPMD the mesh owns placement).
      topo_sharding: ``"replicated"`` (default — every chip holds the full
        CSR) or ``"mesh"`` — the graph itself is partitioned across the
        mesh's feature axis (~1/F topology bytes per chip) and sampling
        routes each frontier vertex to its owning shard over capped-bucket
        all_to_all collectives. ``"mesh"`` construction returns a
        :class:`~quiver_tpu.sampling.dist.DistGraphSageSampler` and
        requires ``mesh=``; results are bit-identical to the replicated
        sampler per worker block.
      compiled_cache_size: LRU bound on the per-instance compiled-program
        cache (keyed on (seed_cap, caps)); evictions are counted on
        ``compiled_cache_evictions``. Auto-cap replans and the serving
        ladder both grow this cache — unbounded, every superseded program
        stays pinned.
    """

    def __new__(cls, *args, **kwargs):
        # GraphSageSampler(topo_sharding="mesh", mesh=...) constructs the
        # sharded-topology sampler — one entry point, two placements
        if (cls is GraphSageSampler
                and kwargs.get("topo_sharding", "replicated") == "mesh"):
            from .dist import DistGraphSageSampler

            return super().__new__(DistGraphSageSampler)
        return super().__new__(cls)

    def __init__(
        self,
        csr_topo: CSRTopo,
        sizes: Sequence[int],
        device=None,
        mode: str | SampleMode = SampleMode.HBM,
        seed_capacity: int | None = None,
        frontier_caps: Sequence[int] | str | None = None,
        seed: int = 0,
        weighted: bool = False,
        time_window=None,
        auto_margin: float = 1.25,
        kernel: str = "auto",
        with_eid: bool = False,
        dedup: str = "auto",
        device_topo=None,
        topo_sharding: str = "replicated",
        compiled_cache_size: int = 8,
    ):
        if topo_sharding not in ("replicated", "mesh"):
            raise ValueError(
                f"topo_sharding must be 'replicated' or 'mesh', "
                f"got {topo_sharding!r}"
            )
        # "mesh" never reaches this __init__ (the __new__ dispatch hands
        # construction to DistGraphSageSampler, which overrides it)
        self.topo_sharding = "replicated"
        self.csr_topo = csr_topo
        self.mode = SampleMode.parse(mode)
        max_deg = csr_topo.max_degree
        self.sizes = tuple(int(k) if k != -1 else max_deg for k in sizes)
        if any(k < 1 for k in self.sizes):
            raise ValueError(f"fanouts must be >= 1 or -1, got {sizes}")
        self.weighted = bool(weighted)
        self.with_eid = bool(with_eid)
        if time_window is not None:
            lo_t, hi_t = time_window  # two scalars, baked into the program
            time_window = (float(lo_t), float(hi_t))
            if self.weighted:
                raise ValueError(
                    "time_window cannot be combined with weighted=True; "
                    "pick one biased draw per sampler"
                )
        self.time_window = time_window
        # the request rides verbatim; resolution (which may run the
        # measured election) happens at first use via the kernel property
        self._kernel = validate_kernel_arg(str(kernel))
        self.dedup = resolve_dedup(str(dedup))  # validates; "auto" -> platform
        if self._kernel == "pallas":
            # an explicit pallas request fails loudly on the one capability
            # the fused kernel cannot provide: the HBM-resident CSR it DMAs
            # from. Every sampler VARIANT (weighted/temporal/with_eid) now
            # runs on the fused engine — the old capability-matrix raises
            # are gone (ISSUE 16).
            if SampleMode.parse(mode) is not SampleMode.HBM:
                raise ValueError("kernel='pallas' requires mode='HBM' (GPU) topology")
        if self.weighted and csr_topo.cum_weights is None:
            raise ValueError(
                "weighted=True requires edge weights; call "
                "csr_topo.set_edge_weight() or pass edge_weight= to CSRTopo"
            )
        if self.time_window is not None and csr_topo.edge_time is None:
            raise ValueError(
                "time_window requires edge timestamps; call "
                "csr_topo.set_edge_time() or pass edge_time= to CSRTopo"
            )
        self.topo = self._init_topo(device_topo)
        # the committed mutation version the device placement reflects; a
        # streaming commit bumps csr_topo.version, after which sampling
        # raises VersionMismatchError until refresh_topology() re-places
        self._topo_version = int(getattr(csr_topo, "version", 0))
        self._seed_capacity = seed_capacity
        self._auto_caps = frontier_caps == "auto"
        self._auto_margin = float(auto_margin)
        if self._auto_margin < 1.0:
            raise ValueError(f"auto_margin must be >= 1.0, got {auto_margin}")
        if self._auto_caps:
            frontier_caps = None  # first call plans from worst case
        elif frontier_caps is not None:
            frontier_caps = tuple(int(c) for c in frontier_caps)
            if len(frontier_caps) != len(self.sizes):
                raise ValueError(
                    f"frontier_caps needs one entry per layer "
                    f"({len(self.sizes)}), got {len(frontier_caps)}"
                )
            if any(c < 1 for c in frontier_caps):
                raise ValueError(f"frontier_caps must be positive, got {frontier_caps}")
        self._frontier_caps = frontier_caps
        self._key = jax.random.PRNGKey(seed)
        self._call = 0
        self._device = device  # accepted for API parity; placement is implicit
        if device is not None:
            # reference-ported code gets a runtime signal that its CUDA
            # ordinal pinning did nothing (VERDICT r5 weak #7)
            info_once(
                "sampler-inert-device-arg",
                "GraphSageSampler(device=%r) accepted for reference API "
                "parity but INERT: under single-controller SPMD placement "
                "is implicit; nothing reads this argument",
                device,
            )
        if compiled_cache_size < 1:
            raise ValueError(
                f"compiled_cache_size must be >= 1, got {compiled_cache_size}"
            )
        self.compiled_cache_size = int(compiled_cache_size)
        self.compiled_cache_evictions = 0
        # LRU-bounded: the serving ladder and auto-cap replans key programs
        # on (seed_cap, caps), and an unbounded per-instance dict would pin
        # every superseded program (and its captured constants) forever
        self._compiled_cache = OrderedDict()

    @property
    def kernel(self) -> str:
        """The resolved sampler kernel ("pallas"|"xla"). ``_kernel`` holds
        the constructor request verbatim; resolution (which may run the
        measured election) is cached at first use — never the constructor
        (same lazy contract as feature.KernelChoice)."""
        resolved = getattr(self, "_kernel_resolved", None)
        if resolved is None:
            resolved = resolve_sample_kernel(self._kernel)
            self._kernel_resolved = resolved
        return resolved

    def _init_topo(self, device_topo):
        """Build (or adopt) the device-resident topology. The mesh-sharded
        sampler overrides this to partition the CSR instead of uploading a
        full replica."""
        if device_topo is not None:
            # advanced: share one DeviceTopology across samplers (the
            # reference shares one native quiver across sampler objects
            # too); must have been built with to_device flags compatible
            # with this sampler's mode/with_eid/weighted
            if self.with_eid and getattr(device_topo, "eid", None) is None:
                raise ValueError(
                    "device_topo lacks eid but with_eid=True; rebuild with "
                    "to_device(with_eid=True)"
                )
            if self.weighted and getattr(device_topo, "cum_weights", None) is None:
                raise ValueError(
                    "device_topo lacks cum_weights but weighted=True; "
                    "rebuild with to_device(with_weights=True)"
                )
            if (self.time_window is not None
                    and getattr(device_topo, "edge_time", None) is None):
                raise ValueError(
                    "device_topo lacks edge_time but time_window is set; "
                    "rebuild with to_device(with_times=True)"
                )
            return device_topo
        return self.csr_topo.to_device(
            self.mode, with_eid=self.with_eid, with_weights=self.weighted,
            with_times=self.time_window is not None,
        )

    # -- streaming-mutation versioning --------------------------------------

    def check_topo_version(self) -> None:
        """Raise :class:`VersionMismatchError` when the host CSR has been
        mutated (a ``quiver_tpu.streaming`` commit bumped its version)
        since this sampler's device topology was placed — sampling over
        the stale placement would silently draw from the pre-commit
        graph. Call :meth:`refresh_topology` to re-place."""
        current = int(getattr(self.csr_topo, "version", 0))
        if current != self._topo_version:
            raise VersionMismatchError(
                f"sampler topology placement is at version "
                f"{self._topo_version} but the host CSR has committed "
                f"version {current}; call refresh_topology() to re-place "
                f"the device topology before sampling"
            )

    def refresh_topology(self) -> "GraphSageSampler":
        """Re-place the device topology from the (possibly mutated) host
        CSR and adopt its committed version. The compiled-program cache is
        dropped — edge-array shapes changed with the edge count, and the
        mesh-sharded override bakes partition geometry into the program."""
        self.topo = self._init_topo(None)
        self._topo_version = int(getattr(self.csr_topo, "version", 0))
        self._compiled_cache.clear()
        return self

    # -- static-shape planning ---------------------------------------------

    def _worst_caps(self, seed_cap: int) -> tuple[int, ...]:
        caps = []
        cur = seed_cap
        n = self.csr_topo.node_count
        for k in self.sizes:
            # clamp growth at node_count but never below the previous cap:
            # forced (seeds-first) lanes keep duplicate seeds as distinct
            # slots, so each frontier must hold the whole previous one
            cur = max(min(cur * (k + 1), n), cur)
            cur = _round_up(cur, 8)
            caps.append(cur)
        return tuple(caps)

    def _caps_for(self, seed_cap: int) -> tuple[int, ...]:
        if self._frontier_caps is not None:
            return self._frontier_caps
        return self._worst_caps(seed_cap)

    def _plan_auto(self, seed_cap: int, observed: Sequence[int]) -> None:
        """Set frontier caps to margin × observed unclipped unique counts
        (seeds-outward order), never shrinking below already-planned caps."""
        worst = self._worst_caps(seed_cap)
        old = self._frontier_caps or (0,) * len(worst)
        caps, prev = [], seed_cap
        for w, o, c in zip(worst, observed, old):
            cap = _round_up(int(self._auto_margin * o), 128)
            cap = max(cap, prev, c, 128)
            cap = min(cap, w)
            caps.append(cap)
            prev = cap
        self._frontier_caps = tuple(caps)

    def _compiled(self, seed_cap: int):
        # instance-level memo keyed on the full static plan (a functools.cache
        # on a method would pin the sampler and its device arrays in a
        # class-level cache forever; auto mode re-plans caps per seed_cap)
        caps = self._caps_for(seed_cap)
        cache_key = (seed_cap, caps)
        hit = self._compiled_cache.get(cache_key)
        if hit is not None:
            self._compiled_cache.move_to_end(cache_key)
            return hit
        sizes = self.sizes
        weighted = self.weighted
        kernel = self.kernel
        with_eid = self.with_eid
        dedup = self.dedup
        time_window = self.time_window

        @jax.jit
        def run(topo, seeds, num_seeds, key):
            return multilayer_sample(topo, seeds, num_seeds, key, sizes, caps,
                                     weighted=weighted, kernel=kernel,
                                     with_eid=with_eid, dedup=dedup,
                                     time_window=time_window)

        self._compiled_cache[cache_key] = (run, caps)
        while len(self._compiled_cache) > self.compiled_cache_size:
            self._compiled_cache.popitem(last=False)
            self.compiled_cache_evictions += 1
        return run, caps

    # -- public API ----------------------------------------------------------

    def sample(self, input_nodes) -> SampleOutput:
        """Sample k-hop neighborhoods of ``input_nodes``.

        Returns a SampleOutput whose ``adjs`` is deepest-layer-first,
        matching the reference's ``adjs[::-1]`` return (sage_sampler.py:112);
        ``edge_counts``/``frontier_counts`` carry per-layer in-program tallies.
        """
        self.check_topo_version()
        seeds = np.asarray(input_nodes)
        batch = int(seeds.shape[0])
        if batch and (seeds.min() < 0 or seeds.max() >= self.csr_topo.node_count):
            raise ValueError(
                f"seed ids must be in [0, {self.csr_topo.node_count}); "
                f"got range [{seeds.min()}, {seeds.max()}]"
            )
        cap = self._seed_capacity or max(_round_up(batch, 128), 128)
        if batch > cap:
            raise ValueError(f"batch {batch} exceeds seed_capacity {cap}")
        padded = np.full(cap, -1, dtype=np.int32)
        padded[:batch] = seeds
        run, _ = self._compiled(cap)
        self._call += 1
        key = jax.random.fold_in(self._key, self._call)
        dev_seeds = jnp.asarray(padded)
        n_id, n_count, adjs, overflow, edge_counts, frontier_counts = run(
            self.topo, dev_seeds, jnp.int32(batch), key
        )
        if self._auto_caps:
            first_plan = self._frontier_caps is None
            # auto mode pays one scalar sync per call to watch for overflow.
            # Regrow converges in <= num_layers rounds (each round's caps
            # cover that round's observed counts); the bound guards the
            # saturation corner where duplicate forced seed lanes push
            # uniques past node_count and even worst-case caps overflow —
            # then the clipped result + overflow report stand, as in
            # fixed-caps mode.
            for _ in range(len(self.sizes) + 2):
                if not first_plan and int(overflow) == 0:
                    break
                observed = [int(c) for c in frontier_counts[::-1]]
                before = self._frontier_caps
                self._plan_auto(cap, observed)
                if self._frontier_caps != before:
                    from ..utils.trace import get_logger

                    get_logger().info(
                        "auto caps %s: %s -> %s (recompile)",
                        "planned" if before is None else "regrown",
                        before, self._frontier_caps,
                    )
                if not first_plan and self._frontier_caps == before:
                    # saturated: caps already at worst case and still
                    # overflowing — rerunning the identical program cannot
                    # help; return the clipped result + overflow report
                    break
                if first_plan and int(overflow) == 0:
                    # worst-case first run: result stands, later calls use
                    # the tight plan
                    first_plan = False
                    break
                run, _ = self._compiled(cap)
                n_id, n_count, adjs, overflow, edge_counts, frontier_counts = run(
                    self.topo, dev_seeds, jnp.int32(batch), key
                )
                first_plan = False
        return SampleOutput(
            n_id, batch, adjs, n_count, overflow, edge_counts, frontier_counts
        )

    def sample_padded(self, topo, seeds, num_seeds, key):
        """Jit-composable sampling on already-padded device seeds.

        For use inside larger jitted programs (e.g. a fused train step);
        shapes must match a previously planned capacity.
        """
        run, _ = self._compiled(int(seeds.shape[0]))
        return run(topo, seeds, num_seeds, key)

    # -- parity helpers ------------------------------------------------------

    def share_ipc(self):
        """Reference API parity (sage_sampler.py:114-120). Under
        single-controller SPMD there is nothing to share; returns the
        rebuild recipe for symmetry."""
        return (self.csr_topo, self.sizes, self.mode)

    @classmethod
    def lazy_from_ipc_handle(cls, handle):
        csr_topo, sizes, mode = handle
        return cls(csr_topo, sizes, mode=mode)
