"""Multi-layer graph sampler with the PyG-compatible output contract.

Capability parity with the reference's ``quiver.pyg.GraphSageSampler``
(torch-quiver pyg/sage_sampler.py:22-133): a fanout list ``sizes``, per-layer
sample + reindex, ``Adj(edge_index, e_id, size)`` records returned deepest
layer first, and ``n_id[:batch_size] == seeds``. Differences forced by XLA
(SURVEY §7.1): all shapes are static — seeds are padded to ``seed_capacity``
and each layer's frontier to a precomputed cap — and the whole multi-layer
loop is one jitted program instead of one C++ call pair per hop
(sage_sampler.py:84-112).

No IPC/lazy-child-reinit machinery is needed (reference sage_sampler.py:71-79,
114-133): under single-controller SPMD there is exactly one process.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..core.config import SampleMode
from ..core.topology import CSRTopo, DeviceTopology
from ..ops.reindex import reindex_layer
from ..ops.sample import sample_layer
from ..utils.trace import trace_scope

__all__ = ["Adj", "GraphSageSampler", "SampleOutput"]


@jax.tree_util.register_pytree_node_class
class Adj:
    """PyG-shaped adjacency record (mirrors reference Adj, sage_sampler.py:12-19).

    ``edge_index`` is (2, E_cap) with [0]=source (frontier-local neighbor id)
    and [1]=target (seed-local id); invalid edges have source == -1.
    ``size`` = (num_source_nodes_cap, num_target_nodes_cap) — static, so it
    survives jit boundaries as pytree metadata (models use it for
    ``num_segments``). Supports 3-tuple unpacking like PyG's Adj.
    """

    def __init__(self, edge_index, e_id, size: tuple[int, int]):
        self.edge_index = edge_index
        self.e_id = e_id
        self.size = tuple(size)

    def __iter__(self):
        return iter((self.edge_index, self.e_id, self.size))

    def __repr__(self):
        return f"Adj(edge_index={self.edge_index.shape}, size={self.size})"

    def to(self, device):
        return Adj(
            jax.device_put(self.edge_index, device),
            None if self.e_id is None else jax.device_put(self.e_id, device),
            self.size,
        )

    def tree_flatten(self):
        return (self.edge_index, self.e_id), (self.size,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0])


class SampleOutput(NamedTuple):
    n_id: jax.Array  # (frontier_cap,) node ids, seeds first, -1 padded
    batch_size: int
    adjs: list  # deepest layer first
    n_count: jax.Array  # scalar: valid entries in n_id
    overflow: jax.Array  # scalar: uniques dropped by frontier caps (0 = exact)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def multilayer_sample(topo, seeds, num_seeds, key, sizes, caps, weighted=False):
    """The multi-layer sample+reindex loop (jit- and shard_map-composable).

    One trace covers all layers — the fused analogue of the reference's
    per-hop Python loop of C++ calls (sage_sampler.py:84-112). Shapes are
    fully static: ``sizes`` and ``caps`` are tuples of ints.

    Returns (n_id, n_count, adjs deepest-first, overflow).
    """
    adjs = []
    cur, cur_n = seeds, num_seeds
    total_overflow = jnp.zeros((), jnp.int32)
    for l, k in enumerate(sizes):
        key, sub = jax.random.split(key)
        with trace_scope(f"sample_layer_{l}"):
            nbr, _ = sample_layer(topo, cur, cur_n, k, sub, weighted=weighted)
        with trace_scope(f"reindex_layer_{l}"):
            frontier, n_frontier, col, overflow = reindex_layer(cur, cur_n, nbr, caps[l])
        S = cur.shape[0]
        row = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[:, None], (S, k))
        row = jnp.where(col >= 0, row, -1)
        edge_index = jnp.stack([col.reshape(-1), row.reshape(-1)])
        adjs.append(Adj(edge_index, None, (caps[l], S)))
        cur, cur_n = frontier, n_frontier
        total_overflow = total_overflow + overflow
    return cur, cur_n, adjs[::-1], total_overflow


class GraphSageSampler:
    """K-hop neighbor sampler over a device-resident CSR topology.

    Args:
      csr_topo: host CSRTopo.
      sizes: fanouts per layer, seeds outward; -1 = full neighborhood
        (capped at the graph's max degree, reference sage_sampler.py:67).
      mode: "HBM" (reference "GPU") or "HOST" (reference "UVA").
      seed_capacity: padded batch size; defaults to first sample() call's
        batch rounded up to a multiple of 128.
      frontier_caps: per-layer unique-node capacity; defaults to
        min(worst-case growth, node_count).
      seed: base PRNG seed (per-call keys derive from it + a call counter,
        like the reference's per-launch curand reseed, cuda_random.cu.hpp:21-23).
    """

    def __init__(
        self,
        csr_topo: CSRTopo,
        sizes: Sequence[int],
        device=None,
        mode: str | SampleMode = SampleMode.HBM,
        seed_capacity: int | None = None,
        frontier_caps: Sequence[int] | None = None,
        seed: int = 0,
        weighted: bool = False,
    ):
        self.csr_topo = csr_topo
        self.mode = SampleMode.parse(mode)
        max_deg = csr_topo.max_degree
        self.sizes = tuple(int(k) if k != -1 else max_deg for k in sizes)
        if any(k < 1 for k in self.sizes):
            raise ValueError(f"fanouts must be >= 1 or -1, got {sizes}")
        self.weighted = bool(weighted)
        if self.weighted and csr_topo.cum_weights is None:
            raise ValueError(
                "weighted=True requires edge weights; call "
                "csr_topo.set_edge_weight() or pass edge_weight= to CSRTopo"
            )
        self.topo = csr_topo.to_device(self.mode, with_weights=self.weighted)
        self._seed_capacity = seed_capacity
        if frontier_caps is not None:
            frontier_caps = tuple(int(c) for c in frontier_caps)
            if len(frontier_caps) != len(self.sizes):
                raise ValueError(
                    f"frontier_caps needs one entry per layer "
                    f"({len(self.sizes)}), got {len(frontier_caps)}"
                )
            if any(c < 1 for c in frontier_caps):
                raise ValueError(f"frontier_caps must be positive, got {frontier_caps}")
        self._frontier_caps = frontier_caps
        self._key = jax.random.PRNGKey(seed)
        self._call = 0
        self._device = device  # accepted for API parity; placement is implicit
        self._compiled_cache = {}

    # -- static-shape planning ---------------------------------------------

    def _caps_for(self, seed_cap: int) -> tuple[int, ...]:
        if self._frontier_caps is not None:
            return self._frontier_caps
        caps = []
        cur = seed_cap
        n = self.csr_topo.node_count
        for k in self.sizes:
            # clamp growth at node_count but never below the previous cap:
            # forced (seeds-first) lanes keep duplicate seeds as distinct
            # slots, so each frontier must hold the whole previous one
            cur = max(min(cur * (k + 1), n), cur)
            cur = _round_up(cur, 8)
            caps.append(cur)
        return tuple(caps)

    def _compiled(self, seed_cap: int):
        # instance-level memo (a functools.cache on a method would pin the
        # sampler and its device arrays in a class-level cache forever)
        if seed_cap in self._compiled_cache:
            return self._compiled_cache[seed_cap]
        caps = self._caps_for(seed_cap)
        sizes = self.sizes
        weighted = self.weighted

        @jax.jit
        def run(topo, seeds, num_seeds, key):
            return multilayer_sample(topo, seeds, num_seeds, key, sizes, caps,
                                     weighted=weighted)

        self._compiled_cache[seed_cap] = (run, caps)
        return run, caps

    # -- public API ----------------------------------------------------------

    def sample(self, input_nodes) -> SampleOutput:
        """Sample k-hop neighborhoods of ``input_nodes``.

        Returns SampleOutput(n_id, batch_size, adjs, n_count, overflow) where
        ``adjs`` is deepest-layer-first, matching the reference's
        ``adjs[::-1]`` return (sage_sampler.py:112).
        """
        seeds = np.asarray(input_nodes)
        batch = int(seeds.shape[0])
        if batch and (seeds.min() < 0 or seeds.max() >= self.csr_topo.node_count):
            raise ValueError(
                f"seed ids must be in [0, {self.csr_topo.node_count}); "
                f"got range [{seeds.min()}, {seeds.max()}]"
            )
        cap = self._seed_capacity or max(_round_up(batch, 128), 128)
        if batch > cap:
            raise ValueError(f"batch {batch} exceeds seed_capacity {cap}")
        padded = np.full(cap, -1, dtype=np.int32)
        padded[:batch] = seeds
        run, _ = self._compiled(cap)
        self._call += 1
        key = jax.random.fold_in(self._key, self._call)
        n_id, n_count, adjs, overflow = run(
            self.topo, jnp.asarray(padded), jnp.int32(batch), key
        )
        return SampleOutput(n_id, batch, adjs, n_count, overflow)

    def sample_padded(self, topo, seeds, num_seeds, key):
        """Jit-composable sampling on already-padded device seeds.

        For use inside larger jitted programs (e.g. a fused train step);
        shapes must match a previously planned capacity.
        """
        run, _ = self._compiled(int(seeds.shape[0]))
        return run(topo, seeds, num_seeds, key)

    # -- parity helpers ------------------------------------------------------

    def share_ipc(self):
        """Reference API parity (sage_sampler.py:114-120). Under
        single-controller SPMD there is nothing to share; returns the
        rebuild recipe for symmetry."""
        return (self.csr_topo, self.sizes, self.mode)

    @classmethod
    def lazy_from_ipc_handle(cls, handle):
        csr_topo, sizes, mode = handle
        return cls(csr_topo, sizes, mode=mode)
