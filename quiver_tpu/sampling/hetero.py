"""Multi-layer heterogeneous neighbor sampler.

Extends the homogeneous padded-shape design (sampling/sampler.py) to typed
graphs: each hop samples every active relation ``(src_t, rel, dst_t)`` whose
destination type currently has frontier nodes, then deduplicates per *node
type* (seeds-first, first-occurrence order — the same masked_unique core the
homogeneous reindex uses). All per-hop/per-type capacities are planned
statically from the fanouts, so the whole multi-layer program jits once.

Output contract mirrors the homogeneous sampler (and thus PyG's hetero
NeighborSampler): ``adjs`` deepest-layer first; each layer is a
``HeteroLayer`` holding one padded Adj per relation plus the per-type
src/dst capacities a model needs for slicing and segment sizes;
``n_id[input_type][:batch_size] == seeds``.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..core.config import SampleMode
from ..core.hetero import HeteroCSRTopo
from ..ops.reindex import masked_unique, resolve_dedup
from ..ops.sample import sample_layer
from .sampler import Adj, _round_up

__all__ = ["HeteroLayer", "HeteroSampleOutput", "HeteroGraphSampler"]


@jax.tree_util.register_pytree_node_class
class HeteroLayer:
    """One hop's relation-wise adjacency: ``adjs`` maps each edge type to a
    padded Adj; ``src_caps``/``dst_caps`` are the per-type frontier
    capacities on the source/target side — static metadata (pytree aux), so
    models can use them as slice bounds and segment counts under jit."""

    def __init__(self, adjs: dict, src_caps: dict, dst_caps: dict):
        self.adjs = adjs
        self.src_caps = src_caps
        self.dst_caps = dst_caps

    def __repr__(self):
        return (
            f"HeteroLayer(rels={[f'{s}-{r}->{d}' for s, r, d in self.adjs]}, "
            f"src_caps={self.src_caps}, dst_caps={self.dst_caps})"
        )

    def tree_flatten(self):
        keys = tuple(sorted(self.adjs, key=str))
        children = tuple(self.adjs[k] for k in keys)
        aux = (
            keys,
            tuple(sorted(self.src_caps.items())),
            tuple(sorted(self.dst_caps.items())),
        )
        return children, aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        keys, src_caps, dst_caps = aux
        return cls(dict(zip(keys, children)), dict(src_caps), dict(dst_caps))


class HeteroSampleOutput(NamedTuple):
    n_id: dict  # node_type -> (cap,) global ids, -1 padded
    n_count: dict  # node_type -> scalar valid count
    batch_size: int
    adjs: list  # HeteroLayer records, deepest first
    overflow: jax.Array  # total uniques dropped by caps (0 = exact)
    # per-hop UNCLIPPED unique counts {type: scalar}, seeds-outward order —
    # what the auto-cap planner reads (homogeneous frontier_counts analogue)
    frontier_counts: tuple = ()


def _normalize_sizes(sizes, topo: HeteroCSRTopo):
    """Per-layer fanout spec: int (all relations) or {edge_type: k}.

    -1 means full neighborhood for that relation (its max in-degree),
    matching GraphSageSampler's convention; 0 (dict form) disables the
    relation for that hop; other non-positive fanouts are rejected.
    """
    edge_types = topo.edge_types

    def resolve(et, k):
        k = int(k)
        if k == -1:
            return max(topo.relations[et].max_degree, 1)
        if k < 1:
            raise ValueError(
                f"fanout for {et} must be >= 1, -1 (full), or 0 (disable, "
                f"dict form only); got {k}"
            )
        return k

    out = []
    for layer in sizes:
        if isinstance(layer, int):
            out.append({et: resolve(et, layer) for et in edge_types})
        else:
            unknown = set(layer) - set(edge_types)
            if unknown:
                raise ValueError(f"unknown edge types in sizes: {unknown}")
            out.append({
                et: resolve(et, k) for et, k in layer.items() if int(k) != 0
            })
    return out


def hetero_multilayer_sample(dev_topos, seeds, num_seeds, key, input_type,
                             layer_plans, weighted_rels=frozenset(),
                             with_eid: bool = False, node_bounds=None,
                             scatter_free: bool = False):
    """The jit-composable hetero sampling loop.

    ``layer_plans`` is a static tuple of per-hop plans, each
    ``(rel_fanouts, caps_prev, caps_next)`` where rel_fanouts maps active
    edge types to fanouts and caps_* map node types to static capacities.
    ``weighted_rels`` (static) names edge types whose draws are
    weight-proportional (their DeviceTopology must carry cum_weights);
    ``with_eid`` threads per-edge global edge ids into every Adj — the
    homogeneous contract (multilayer_sample, sampler.py) extended to typed
    relations: ids are COO positions within each relation's own edge list.
    ``node_bounds`` (static {type: node_count} or None) switches the
    per-type dedup to the sort-free dense-map scatter-min, matching the
    homogeneous ``dedup='map'`` option; ``scatter_free`` selects the
    zero-scatter scan strategy (homogeneous ``dedup='scan'``).
    Returns (frontier dict, counts dict, layers deepest-first, overflow).
    """
    frontier = {input_type: seeds}
    counts = {input_type: num_seeds}
    layers = []
    frontier_counts = []
    overflow = jnp.zeros((), jnp.int32)

    for rel_fanouts, caps_prev, caps_next in layer_plans:
        # 1) sample every active relation
        samples = {}  # edge_type -> (S, K) src-type global ids
        eids = {}  # edge_type -> (S, K) relation-local edge ids
        for et, k in rel_fanouts.items():
            _, _, d = et
            key, sub = jax.random.split(key)
            res = sample_layer(
                dev_topos[et], frontier[d], counts[d], k, sub,
                weighted=et in weighted_rels, with_eid=with_eid,
            )
            samples[et] = res[0]
            if with_eid:
                eids[et] = res[2]

        # 2) per-type dedup: previous frontier first (forced), then each
        #    relation's samples targeting this src type, concatenated in a
        #    deterministic relation order
        new_frontier, new_counts, locals_per_rel = {}, {}, {}
        layer_uniques = {}
        for t, cap in caps_next.items():
            blocks, valids, spans = [], [], {}
            prev = frontier.get(t)
            n_prev = 0
            if prev is not None:
                n_prev = prev.shape[0]
                blocks.append(prev)
                valids.append(
                    (jnp.arange(n_prev) < counts[t]) & (prev >= 0)
                )
            for et in sorted(samples, key=str):
                if et[0] != t:
                    continue
                flat = samples[et].reshape(-1)
                spans[et] = (sum(b.shape[0] for b in blocks),
                             flat.shape[0])
                blocks.append(flat)
                valids.append(flat >= 0)
            ids = jnp.concatenate(blocks)
            valid = jnp.concatenate(valids)
            uniq, num_u, local = masked_unique(
                ids, valid, cap, num_forced=n_prev,
                node_bound=None if node_bounds is None else node_bounds[t],
                scatter_free=scatter_free,
            )
            new_frontier[t] = uniq
            new_counts[t] = jnp.minimum(num_u, cap)
            layer_uniques[t] = num_u
            overflow = overflow + jnp.maximum(num_u - cap, 0)
            for et, (off, ln) in spans.items():
                locals_per_rel[et] = local[off:off + ln]

        # 3) build one padded Adj per relation: src = frontier-local id in
        #    the NEW src-type frontier, dst = row position in the PREVIOUS
        #    dst-type frontier (identical to its local id next layer, since
        #    previous nodes are forced first)
        adjs = {}
        for et, k in rel_fanouts.items():
            s_t, _, d_t = et
            S = frontier[d_t].shape[0]
            col = locals_per_rel[et].reshape(S, k)
            row = jnp.broadcast_to(
                jnp.arange(S, dtype=jnp.int32)[:, None], (S, k)
            )
            row = jnp.where(col >= 0, row, -1)
            edge_index = jnp.stack([col.reshape(-1), row.reshape(-1)])
            e_id = None
            if with_eid:
                # re-mask with col: neighbors dropped by frontier-cap
                # overflow must not leak their edge ids (same rule as the
                # homogeneous loop)
                e_id = jnp.where(col >= 0, eids[et], -1).reshape(-1)
            adjs[et] = Adj(edge_index, e_id, (caps_next[s_t], S), fanout=k)
        layers.append(HeteroLayer(adjs, dict(caps_next), dict(caps_prev)))
        frontier_counts.append(layer_uniques)

        frontier, counts = new_frontier, new_counts

    return frontier, counts, layers[::-1], overflow, tuple(frontier_counts)


class HeteroGraphSampler:
    """K-hop typed neighbor sampler over a HeteroCSRTopo.

    Args:
      topo: HeteroCSRTopo (relations stored as incoming adjacency).
      sizes: per-layer fanouts — each entry an int (applied to every
        relation) or a dict {edge_type: fanout} (omitted/0 disables the
        relation that hop).
      input_type: node type of the seeds.
      mode: topology placement, "GPU"/HBM or "UVA"/host.
      seed_capacity: padded seed batch; defaults to first batch rounded up.
      frontier_caps: ``"auto"`` right-sizes every per-hop/per-type capacity
        from the first batch's observed unique counts (x ``auto_margin``) —
        the homogeneous auto planner (sampler.py) ported to typed frontiers.
        Worst-case caps overshoot ~3x on power-law graphs (SURVEY §7.4.2),
        and R-GCN pays that in every gather/aggregate. Default: worst case.
      seed: PRNG seed.
      auto_margin: headroom factor for "auto" caps (>= 1).
      weighted: weight-proportional neighbor draws — ``True`` uses every
        relation that has weights attached (``set_edge_weight``; at least one
        required), or pass an iterable of edge types to weight exactly those
        (each must have weights). Unlisted relations sample uniformly.
      with_eid: populate every ``Adj.e_id`` with relation-local global edge
        ids (COO positions) — the homogeneous sampler's contract
        (sage_sampler.py:100-109 parity) extended to typed graphs.
      dedup: per-type frontier first-occurrence strategy — "sort" (stable
        sort + run scan), "map" (sort-free scatter-min into a dense
        per-type position map), or "scan" (zero-scatter sorts + cummax +
        gathers). Identical results. Default "auto" picks per platform
        (ops.reindex.resolve_dedup). Mirrors the homogeneous
        GraphSageSampler option.
    """

    def __init__(self, topo: HeteroCSRTopo, sizes: Sequence,
                 input_type: str, mode: str | SampleMode = SampleMode.HBM,
                 seed_capacity: int | None = None,
                 frontier_caps: str | None = None, seed: int = 0,
                 auto_margin: float = 1.25, weighted=False,
                 with_eid: bool = False, dedup: str = "auto"):
        if input_type not in topo.num_nodes:
            raise ValueError(f"unknown input_type {input_type!r}")
        self.dedup = resolve_dedup(str(dedup))  # validates; "auto" -> platform
        self.topo = topo
        self.input_type = input_type
        self.sizes = _normalize_sizes(sizes, topo)
        self.mode = SampleMode.parse(mode)
        self.with_eid = bool(with_eid)
        if weighted is True:
            weighted_rels = topo.weighted_edge_types
            if not weighted_rels:
                raise ValueError(
                    "weighted=True requires at least one relation with edge "
                    "weights; call topo.set_edge_weight() first"
                )
        elif weighted:
            # str-normalize components like HeteroCSRTopo does its keys
            weighted_rels = [tuple(str(t) for t in et) for et in weighted]
            missing = [
                et for et in weighted_rels
                if et not in topo.relations
                or topo.relations[et].cum_weights is None
            ]
            if missing:
                raise ValueError(
                    f"weighted relations need edge weights attached: {missing}"
                )
        else:
            weighted_rels = []
        self.weighted_rels = frozenset(weighted_rels)
        self.dev_topos = self._init_topo()
        self._seed_capacity = seed_capacity
        if frontier_caps not in (None, "auto"):
            raise ValueError(
                f"frontier_caps must be None or 'auto', got {frontier_caps!r}"
            )
        self._auto_caps = frontier_caps == "auto"
        self._auto_margin = float(auto_margin)
        if self._auto_margin < 1.0:
            raise ValueError(f"auto_margin must be >= 1.0, got {auto_margin}")
        # per-layer {type: cap} overrides planned from observed counts
        self._cap_overrides: tuple | None = None
        self._key = jax.random.PRNGKey(seed)
        self._call = 0
        self._compiled_cache = {}

    def _init_topo(self):
        """Place every relation's CSR on device. The mesh-sharded sampler
        (``sampling.dist_hetero.DistHeteroSampler``) overrides this to
        partition each relation across the mesh instead of replicating."""
        return self.topo.to_device(
            self.mode, with_eid=self.with_eid,
            weighted_rels=self.weighted_rels,
        )

    # -- static planning ----------------------------------------------------

    def _plan(self, seed_cap: int, overrides: tuple | None = None):
        """Per-hop (active relations, caps before, caps after).

        ``overrides`` (auto mode): per-layer {type: planned cap}; each is
        clamped into [previous hop's cap, worst case] so the seeds-first
        invariant and correctness bounds hold no matter what was observed.
        """
        caps = {self.input_type: seed_cap}
        plans = []
        for li, layer in enumerate(self.sizes):
            active = {
                et: k for et, k in layer.items()
                if caps.get(et[2], 0) > 0 and k > 0
            }
            caps_next = dict(caps)
            for et, k in active.items():
                s_t, _, d_t = et
                grow = caps[d_t] * k
                caps_next[s_t] = caps_next.get(s_t, 0) + grow
            for t in caps_next:
                # clamp growth at the type's node count, but never below the
                # previous hop's capacity: forced (seeds-first) lanes keep
                # duplicates as distinct slots, so the frontier must always
                # be able to hold the full previous frontier
                worst = _round_up(
                    max(min(caps_next[t], self.topo.num_nodes[t]),
                        caps.get(t, 0)),
                    8,
                )
                cap = worst
                if overrides is not None and t in overrides[li]:
                    cap = _round_up(int(overrides[li][t]), 128)
                    cap = max(cap, caps.get(t, 0), 128)
                    cap = min(cap, worst)
                caps_next[t] = cap
            plans.append((active, dict(caps), caps_next))
            caps = caps_next
        return tuple(plans)

    def _plan_auto(self, observed: Sequence[dict]) -> None:
        """Fold a run's per-layer unclipped unique counts into the cap
        overrides (margin headroom; never shrinking below a previous plan)."""
        old = self._cap_overrides or tuple({} for _ in observed)
        new = []
        for obs, prev in zip(observed, old):
            layer = dict(prev)
            for t, n in obs.items():
                want = int(self._auto_margin * int(n))
                layer[t] = max(want, prev.get(t, 0))
            new.append(layer)
        self._cap_overrides = tuple(new)

    def _compiled(self, seed_cap: int):
        ov = self._cap_overrides
        cache_key = (
            seed_cap,
            None if ov is None
            else tuple(tuple(sorted(layer.items())) for layer in ov),
        )
        if cache_key in self._compiled_cache:
            return self._compiled_cache[cache_key]
        plans = self._plan(
            seed_cap, self._cap_overrides if self._auto_caps else None
        )
        input_type = self.input_type
        weighted_rels = self.weighted_rels
        with_eid = self.with_eid
        node_bounds = (
            {t: int(n) for t, n in self.topo.num_nodes.items()}
            if self.dedup == "map" else None
        )
        scatter_free = self.dedup == "scan"

        @jax.jit
        def run(dev_topos, seeds, num_seeds, key):
            return hetero_multilayer_sample(
                dev_topos, seeds, num_seeds, key, input_type, plans,
                weighted_rels=weighted_rels, with_eid=with_eid,
                node_bounds=node_bounds, scatter_free=scatter_free,
            )

        self._compiled_cache[cache_key] = run
        return run

    # -- public API ----------------------------------------------------------

    def sample(self, input_nodes) -> HeteroSampleOutput:
        seeds = np.asarray(input_nodes)
        batch = int(seeds.shape[0])
        n = self.topo.num_nodes[self.input_type]
        if batch and (seeds.min() < 0 or seeds.max() >= n):
            raise ValueError(
                f"seed ids must be in [0, {n}); got "
                f"[{seeds.min()}, {seeds.max()}]"
            )
        cap = self._seed_capacity or max(_round_up(batch, 128), 128)
        if batch > cap:
            raise ValueError(f"batch {batch} exceeds seed_capacity {cap}")
        padded = np.full(cap, -1, dtype=np.int32)
        padded[:batch] = seeds
        run = self._compiled(cap)
        self._call += 1
        key = jax.random.fold_in(self._key, self._call)
        dev_seeds = jnp.asarray(padded)
        frontier, counts, layers, overflow, fcounts = run(
            self.dev_topos, dev_seeds, jnp.int32(batch), key
        )
        if self._auto_caps:
            # same discipline as the homogeneous sampler: one scalar sync per
            # call to watch for overflow; regrow is bounded and saturates at
            # worst-case caps (then the clipped result + report stand)
            first_plan = self._cap_overrides is None
            for _ in range(len(self.sizes) + 2):
                if not first_plan and int(overflow) == 0:
                    break
                observed = [
                    {t: int(v) for t, v in layer.items()} for layer in fcounts
                ]
                before = self._cap_overrides
                self._plan_auto(observed)
                if not first_plan and self._cap_overrides == before:
                    break  # saturated: rerunning the same program can't help
                if first_plan and int(overflow) == 0:
                    first_plan = False
                    break  # worst-case first run was exact; keep its result
                run = self._compiled(cap)
                frontier, counts, layers, overflow, fcounts = run(
                    self.dev_topos, dev_seeds, jnp.int32(batch), key
                )
                first_plan = False
        return HeteroSampleOutput(
            frontier, counts, batch, layers, overflow, fcounts
        )
