"""Device-mesh topology utilities.

Capability parity with the reference's p2p-clique machinery
(torch-quiver utils.py:8-104 ``Topo``/``find_cliques`` +
``init_p2p``/``can_device_access_peer``, quiver_feature.cu:363-413): the
reference discovers which GPUs share NVLink and partitions them into
cliques; on TPU the analogous structure is *given* — every device in a slice
is connected over ICI, and distinct slices talk over DCN. ``MeshTopo``
exposes the same queries (clique of a device, device list of a clique, info
string) over a ``jax.sharding.Mesh``, treating each ICI-connected slice as
one clique (single-slice = one all-device clique).
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = [
    "MeshTopo",
    "make_mesh",
    "shard_map",
    "init_p2p",
    "can_device_access_peer",
    "init_distributed",
]

DATA_AXIS = "data"
FEATURE_AXIS = "feature"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool | None = None):
    """Version-portable ``shard_map``.

    jax >= 0.7 exposes ``jax.shard_map`` with the replication check named
    ``check_vma``; older releases ship ``jax.experimental.shard_map.shard_map``
    with the same check named ``check_rep``. The repo targets both: the
    image's baked-in toolchain pins an older jax while dev boxes track
    HEAD, and an AttributeError here takes down every mesh test.
    """
    if hasattr(jax, "shard_map"):
        kwargs = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    kwargs = {} if check_vma is None else {"check_rep": check_vma}
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


def make_mesh(
    n_devices: int | None = None,
    data: int | None = None,
    feature: int = 1,
    devices=None,
) -> Mesh:
    """Build a (data, feature) mesh over the available devices.

    The ``data`` axis carries batch/data parallelism (the reference's one
    process per GPU, dist_sampling_ogb_products_quiver.py:85); the
    ``feature`` axis shards the hot feature cache (the NVLink-clique role).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = n_devices or len(devices)
    if data is None:
        data = n // feature
    if data * feature != n:
        raise ValueError(f"data*feature = {data}*{feature} != {n} devices")
    arr = np.asarray(devices[:n]).reshape(data, feature)
    return Mesh(arr, (DATA_AXIS, FEATURE_AXIS))


def _slice_index(device) -> int:
    """ICI-connected group of a device (slice index; 0 when not exposed)."""
    return getattr(device, "slice_index", 0) or 0


class MeshTopo:
    """Clique view of the device set (reference ``p2pCliqueTopo`` parity).

    Devices in the same TPU slice are one clique: any pair can reach each
    other over ICI, exactly the property ``can_device_access_peer``
    certified for NVLink pairs.
    """

    def __init__(self, devices=None):
        self.devices = list(devices if devices is not None else jax.devices())
        groups: dict[int, list[int]] = {}
        for i, d in enumerate(self.devices):
            groups.setdefault(_slice_index(d), []).append(i)
        self.cliques: list[list[int]] = [groups[k] for k in sorted(groups)]
        self.device2clique = {
            i: ci for ci, clique in enumerate(self.cliques) for i in clique
        }

    @property
    def p2p_clique(self) -> list[list[int]]:
        return self.cliques

    def get_clique_id(self, device_index: int) -> int:
        return self.device2clique[device_index]

    def p2p_clique_device_list(self, clique_id: int) -> list[int]:
        return self.cliques[clique_id]

    @property
    def info(self) -> str:
        lines = []
        for ci, clique in enumerate(self.cliques):
            lines.append(
                f"Clique {ci} (ICI-connected): devices {clique} "
                f"[{', '.join(str(self.devices[i]) for i in clique)}]"
            )
        return "\n".join(lines)

    def __repr__(self):
        return f"MeshTopo(cliques={self.cliques})"


def can_device_access_peer(a: int, b: int) -> bool:
    """True when devices a and b share an ICI domain (same slice).

    Parity with the reference binding (quiver_feature.cu:407-413).
    """
    devices = jax.devices()
    return _slice_index(devices[a]) == _slice_index(devices[b])


def init_p2p(device_list=None) -> None:
    """No-op parity shim (reference utils.py:234-240): ICI peer access needs
    no explicit enablement on TPU."""
    return None


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join the multi-host job (the reference's future-work story,
    docs/Introduction_en.md:171 "Distributed Quiver").

    Thin wrapper over ``jax.distributed.initialize``: on TPU pods every
    argument is auto-discovered from the environment, so a bare
    ``init_distributed()`` at program start is enough; after it,
    ``jax.devices()`` spans all hosts and :func:`make_mesh` builds
    DCN-spanning meshes transparently (ICI collectives within a slice, DCN
    across). Call once per host process, before any other jax use.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
