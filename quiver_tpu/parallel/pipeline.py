"""Input-pipeline prefetching: overlap sample+gather with model compute.

Capability parity with the reference's asynchrony story and SURVEY §7.4.4:
the reference overlaps stages with CUDA streams (stream_pool,
quiver_sample.cu:84-88, async launchers algorithm.cu.hpp:8-50) and ships a
(legacy) ``AsyncCudaNeighborSampler`` (async_cuda_sampler.py:24-58). On TPU
the device queue already executes asynchronously from Python; what needs
explicit overlap is the *host-side* work — seed prep, staged host-memory
gathers for the cold tier, dispatch latency. :class:`Prefetcher` keeps
``depth`` batches in flight on a worker thread so batch i+1's sample+gather
runs while the train step for batch i computes — the double-buffering that
replaces UVA's "kernel reads host RAM while computing" trick.

Single worker thread => sampler PRNG call order stays deterministic: the
prefetched stream is bit-identical to the sequential loop (tested).
"""

from __future__ import annotations

import collections
import concurrent.futures
from typing import Callable, Iterable, Iterator, NamedTuple

__all__ = ["Batch", "Prefetcher"]


class Batch(NamedTuple):
    """One ready-to-train batch: features + sampler output."""

    seeds: object  # the raw seed array this batch was built from
    out: object  # SampleOutput (n_id, batch_size, adjs, ...)
    x: object  # gathered feature rows for out.n_id


class Prefetcher:
    """Iterate (seeds -> Batch) with ``depth`` batches dispatched ahead.

    Args:
      sampler: GraphSageSampler (or any object with .sample(seeds)).
      feature: Feature/ShardedFeature (or any ids -> rows indexable); pass
        None to prefetch sampling only.
      depth: max batches in flight beyond the one being consumed (2 =
        double buffering).
      transform: optional host callback (seeds, out, x) -> Batch-like, run
        on the worker thread (e.g. label lookup).

    >>> for batch in Prefetcher(sampler, feature).run(seed_stream):
    ...     params, opt, loss = step(params, opt, batch.x, batch.out.adjs, ...)
    """

    def __init__(
        self,
        sampler,
        feature=None,
        depth: int = 2,
        transform: Callable | None = None,
    ):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self.sampler = sampler
        self.feature = feature
        self.depth = depth
        self.transform = transform

    def _dispatch(self, seeds) -> Batch:
        out = self.sampler.sample(seeds)
        x = None if self.feature is None else self.feature[out.n_id]
        if self.transform is not None:
            return self.transform(seeds, out, x)
        return Batch(seeds, out, x)

    def run(self, seed_stream: Iterable) -> Iterator[Batch]:
        """Yield Batches for each seed array in ``seed_stream``, keeping up
        to ``depth`` in flight. Exceptions from the worker surface at the
        yield for the offending batch, in order.

        A consumer that stops early (``break`` / ``gen.close()``) returns
        promptly: queued dispatches are cancelled and the pool is shut down
        WITHOUT joining the worker — an executor ``with``-block's exit
        would park the consumer behind the in-flight sample+gather, work
        nobody will read. The worker thread finishes that one dispatch in
        the background and exits on its own."""
        pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="quiver-prefetch"
        )
        inflight: collections.deque = collections.deque()
        it = iter(seed_stream)
        try:
            for seeds in it:
                inflight.append(pool.submit(self._dispatch, seeds))
                if len(inflight) > self.depth:
                    yield inflight.popleft().result()
            while inflight:
                yield inflight.popleft().result()
        finally:
            pool.shutdown(wait=False, cancel_futures=True)

    __call__ = run
