"""Input-pipeline prefetching: overlap sample+gather with model compute.

Capability parity with the reference's asynchrony story and SURVEY §7.4.4:
the reference overlaps stages with CUDA streams (stream_pool,
quiver_sample.cu:84-88, async launchers algorithm.cu.hpp:8-50) and ships a
(legacy) ``AsyncCudaNeighborSampler`` (async_cuda_sampler.py:24-58). On TPU
the device queue already executes asynchronously from Python; what needs
explicit overlap is the *host-side* work — seed prep, staged host-memory
gathers for the cold tier, dispatch latency. :class:`Prefetcher` keeps
``depth`` batches in flight on a worker thread so batch i+1's sample+gather
runs while the train step for batch i computes — the double-buffering that
replaces UVA's "kernel reads host RAM while computing" trick.

Single worker thread => sampler PRNG call order stays deterministic: the
prefetched stream is bit-identical to the sequential loop (tested).

Resilience (the reference fails the whole ``mp.spawn`` run on one worker
exception): transient host-side failures — a sampler/feature/transform
raising on a preempted host or flaky storage — are retried with bounded
exponential backoff + deterministic jitter (``retries=``/``backoff=``),
and a batch still failing after retries exhaust either surfaces (default)
or is skipped-and-counted (``skip_policy="skip"``) so one poisoned batch
cannot end a long run. Per-batch retry/skip telemetry rides any
StepTimeline-compatible registry passed as ``timeline=``.
"""

from __future__ import annotations

import collections
import concurrent.futures
import random
import time
from typing import Callable, Iterable, Iterator, NamedTuple

import numpy as np

from ..obs.registry import (
    PREFETCH_QUEUE_DEPTH,
    PREFETCH_RETRIES,
    PREFETCH_SKIPS,
)

__all__ = ["Batch", "PipelinedBatch", "Prefetcher"]

_SKIP_POLICIES = ("raise", "skip")


class Batch(NamedTuple):
    """One ready-to-train batch: features + sampler output."""

    seeds: object  # the raw seed array this batch was built from
    out: object  # SampleOutput (n_id, batch_size, adjs, ...)
    x: object  # gathered feature rows for out.n_id


class PipelinedBatch(NamedTuple):
    """One fully-materialized sample+gather result carried by the
    software-pipelined epoch scan (``DistributedTrainer`` with
    ``pipeline_depth=1``).

    Where :class:`Batch` is the HOST-side container the Prefetcher's
    worker thread hands to an unfused step, this is the IN-PROGRAM
    equivalent: the issue half of the fused step produces it, the scan
    carries it across the one-step skew, and the train half consumes it
    one step later — all inside one compiled epoch program, so XLA can
    overlap the next batch's sample/gather collectives with the current
    batch's forward/backward compute.

    Every array carries a leading per-device block axis (``blocks_per
    device``; 1 outside elastic mode). ``adjs`` is the sampler's
    deepest-first Adj tuple with that same leading axis stacked onto the
    edge_index leaves (the static size/fanout aux describes the
    UNstacked per-block shape — the train half unstacks before use).
    ``metrics`` is the issue half's finalized metrics pytree (routed
    overflow / tier hits / hop overflow, psum'd at their declared axes;
    ``{}`` when collection is off) so per-step telemetry stays attributed
    to the batch it measured, not the step that trained it.
    """

    n_id: object  # (bpd, total_cap) int32 gathered node ids per block
    x: object  # (bpd, cap, F) gathered feature rows per block
    adjs: object  # tuple of Adj, edge_index leaves stacked to (bpd, 2, E)
    num_seeds: object  # (bpd,) int32 valid-seed count per block
    metrics: object  # issue-half finalized metrics dict ({} when disabled)


class _Skipped(NamedTuple):
    """Worker-side marker for a batch dropped under skip_policy="skip"."""

    seeds: object
    error: BaseException


class Prefetcher:
    """Iterate (seeds -> Batch) with ``depth`` batches dispatched ahead.

    Args:
      sampler: GraphSageSampler (or any object with .sample(seeds)).
      feature: Feature/ShardedFeature (or any ids -> rows indexable); pass
        None to prefetch sampling only.
      depth: max batches in flight beyond the one being consumed (2 =
        double buffering).
      transform: optional host callback (seeds, out, x) -> Batch-like, run
        on the worker thread (e.g. label lookup).
      retries: max re-dispatches per batch after a raising
        sample/gather/transform (0 = fail fast, the pre-resilience
        behavior). Retries re-enter the whole dispatch, so a sampler that
        failed BEFORE drawing keeps its PRNG call order — the recovered
        stream is bit-identical to a fault-free one.
      backoff: first retry delay in seconds; doubles per attempt, capped
        at ``backoff_cap``.
      backoff_cap: upper bound on a single backoff sleep.
      jitter: fractional random pad on each sleep (delay *= 1 + U[0,1) *
        jitter), drawn from a PRNG seeded with ``retry_seed`` — runs are
        reproducible, but co-scheduled workers desynchronize.
      skip_policy: what to do when retries exhaust — ``"raise"`` surfaces
        the exception at the batch's yield (default); ``"skip"`` drops the
        poisoned batch, counts it (``skips_total``), and keeps streaming.
      timeline: optional StepTimeline-compatible registry
        (``observe(name, seconds)``) fed per-batch stages:
        ``prefetch.dispatch`` (successful dispatch wall time),
        ``prefetch.retry_wait`` (each backoff sleep), ``prefetch.skip``
        (each dropped batch).
      metrics: optional graftscope ``MetricsRegistry`` to land the
        lifetime retry/skip COUNTERS on (``prefetch.retries``,
        ``prefetch.skipped_batches``) — pass a trainer's registry and
        ``metrics_report()`` shows pipeline health alongside
        ``resilience.skipped_steps``. The timeline gets per-event
        timings; the registry gets the running totals.
      retry_seed: seed for the jitter PRNG.
      tracer: optional grafttrace :class:`~quiver_tpu.obs.tracing
        .Tracer` — every successful dispatch lands a
        ``prefetch.dispatch`` span (subsystem ``prefetch``) tagged with
        the batch's stream index and the causing ``trace`` id.
      trace: trace id the dispatch spans attach to (e.g. the trainer's
        ``train.epoch.<n>``).

    ``retries_total`` / ``skips_total`` count across the prefetcher's
    lifetime (single worker thread — no synchronization needed).

    >>> for batch in Prefetcher(sampler, feature).run(seed_stream):
    ...     params, opt, loss = step(params, opt, batch.x, batch.out.adjs, ...)
    """

    def __init__(
        self,
        sampler,
        feature=None,
        depth: int = 2,
        transform: Callable | None = None,
        retries: int = 0,
        backoff: float = 0.05,
        backoff_cap: float = 2.0,
        jitter: float = 0.5,
        skip_policy: str = "raise",
        timeline=None,
        metrics=None,
        retry_seed: int = 0,
        tracer=None,
        trace: str | None = None,
    ):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if retries < 0:
            raise ValueError(f"retries must be >= 0, got {retries}")
        if backoff < 0 or backoff_cap < 0 or jitter < 0:
            raise ValueError(
                f"backoff/backoff_cap/jitter must be >= 0, got "
                f"{backoff}/{backoff_cap}/{jitter}"
            )
        if skip_policy not in _SKIP_POLICIES:
            raise ValueError(
                f"skip_policy must be one of {_SKIP_POLICIES}, "
                f"got {skip_policy!r}"
            )
        self.sampler = sampler
        self.feature = feature
        self.depth = depth
        self.transform = transform
        self.retries = int(retries)
        self.backoff = float(backoff)
        self.backoff_cap = float(backoff_cap)
        self.jitter = float(jitter)
        self.skip_policy = skip_policy
        self.timeline = timeline
        self.metrics = metrics
        if metrics is not None:
            metrics.counter(
                PREFETCH_RETRIES, unit="dispatches",
                doc="prefetch batch re-dispatches after a raising "
                    "sample/gather/transform (lifetime total)",
            )
            metrics.counter(
                PREFETCH_SKIPS, unit="batches",
                doc="poisoned batches dropped after retries exhausted "
                    "(skip_policy='skip'; lifetime total)",
            )
            metrics.gauge(
                PREFETCH_QUEUE_DEPTH, unit="batches",
                doc="batches currently in flight on the prefetch worker "
                    "(pinned at `depth` while the pipeline keeps up; "
                    "sagging below it means dispatch is the bottleneck)",
            )
        self._jitter_rng = random.Random(retry_seed)
        self.tracer = tracer
        self.trace = trace
        self._batch_index = 0  # worker-thread only (single worker)
        self.retries_total = 0
        self.skips_total = 0

    def _observe(self, stage: str, seconds: float) -> None:
        if self.timeline is not None:
            self.timeline.observe(stage, seconds)
        if self.tracer is not None and self.tracer.enabled:
            self.tracer.observe(
                stage, seconds, trace=self.trace, subsystem="prefetch",
                batch=self._batch_index,
            )

    def _publish_counters(self) -> None:
        """Land the running totals on the registry (host write from the
        single worker thread — same thread that increments them)."""
        if self.metrics is not None:
            self.metrics.set(PREFETCH_RETRIES, np.int32(self.retries_total))
            self.metrics.set(PREFETCH_SKIPS, np.int32(self.skips_total))

    def _dispatch(self, seeds) -> Batch:
        out = self.sampler.sample(seeds)
        x = None if self.feature is None else self.feature[out.n_id]
        if self.transform is not None:
            return self.transform(seeds, out, x)
        return Batch(seeds, out, x)

    def _dispatch_resilient(self, seeds):
        """One batch with bounded retry; runs on the worker thread."""
        self._batch_index += 1
        attempt = 0
        while True:
            t0 = time.perf_counter()
            try:
                batch = self._dispatch(seeds)
            except Exception as e:  # noqa: BLE001 — bounded retry, then
                if attempt >= self.retries:  # surface or skip per policy
                    if self.skip_policy == "skip":
                        self.skips_total += 1
                        self._observe("prefetch.skip", 0.0)
                        self._publish_counters()
                        from ..utils.trace import get_logger

                        get_logger().warning(
                            "prefetch: batch dropped after %d retr%s "
                            "(skip_policy='skip'): %s: %s",
                            attempt, "y" if attempt == 1 else "ies",
                            type(e).__name__, e,
                        )
                        return _Skipped(seeds, e)
                    raise
                attempt += 1
                self.retries_total += 1
                self._publish_counters()
                delay = min(
                    self.backoff * 2.0 ** (attempt - 1), self.backoff_cap
                ) * (1.0 + self.jitter * self._jitter_rng.random())
                self._observe("prefetch.retry_wait", delay)
                if delay > 0:
                    time.sleep(delay)
            else:
                self._observe(
                    "prefetch.dispatch", time.perf_counter() - t0
                )
                return batch

    def run(self, seed_stream: Iterable) -> Iterator[Batch]:
        """Yield Batches for each seed array in ``seed_stream``, keeping up
        to ``depth`` in flight. Exceptions from the worker (after any
        retries) surface at the yield for the offending batch, in order;
        under ``skip_policy="skip"`` the failed batch is silently dropped
        from the stream instead (later batches keep their order).

        A consumer that stops early (``break`` / ``gen.close()``) returns
        promptly: queued dispatches are cancelled and the pool is shut down
        WITHOUT joining the worker — an executor ``with``-block's exit
        would park the consumer behind the in-flight sample+gather, work
        nobody will read. The worker thread finishes that one dispatch in
        the background and exits on its own."""
        pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="quiver-prefetch"
        )
        inflight: collections.deque = collections.deque()
        it = iter(seed_stream)

        def _note_depth() -> None:
            # consumer-thread write; the worker never touches this gauge
            if self.metrics is not None:
                self.metrics.set(
                    PREFETCH_QUEUE_DEPTH, np.int32(len(inflight))
                )

        try:
            for seeds in it:
                inflight.append(pool.submit(self._dispatch_resilient, seeds))
                _note_depth()
                if len(inflight) > self.depth:
                    batch = inflight.popleft().result()
                    _note_depth()
                    if not isinstance(batch, _Skipped):
                        yield batch
            while inflight:
                batch = inflight.popleft().result()
                _note_depth()
                if not isinstance(batch, _Skipped):
                    yield batch
        finally:
            pool.shutdown(wait=False, cancel_futures=True)

    __call__ = run
