from .mesh import MeshTopo, can_device_access_peer, init_p2p, make_mesh

__all__ = [
    "MeshTopo",
    "make_mesh",
    "init_p2p",
    "can_device_access_peer",
    "Batch",
    "Prefetcher",
    "init_model",
    "make_train_step",
    "make_eval_step",
    "DistributedTrainer",
]

_LAZY = {
    "Batch": "pipeline",
    "Prefetcher": "pipeline",
    "init_model": "train",
    "make_train_step": "train",
    "make_eval_step": "train",
    "DistributedTrainer": "trainer",
}


def __getattr__(name):
    # trainer/train/pipeline import feature.*, which imports parallel.mesh —
    # resolving them lazily keeps this package initializable from both sides
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(f".{_LAZY[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
