"""Fused SPMD training: sample + gather + forward/backward + update in one
jitted shard_map program over the device mesh.

This replaces the reference's entire multi-process runtime (mp.spawn + DDP +
NCCL allreduce + CUDA-IPC object sharing, dist_sampling_ogb_products_quiver.py:
82-163, reductions.py:5-32) with a single-controller SPMD program:

* ``data`` mesh axis = the reference's one-process-per-GPU data parallelism;
  per-device seed blocks mirror ``train_idx.split(world_size)[rank]``
  (dist_sampling_ogb_products_quiver.py:89).
* gradient ``pmean`` over the mesh = the DDP/NCCL allreduce (:100).
* ``feature`` mesh axis = the NVLink clique: the hot feature shard is
  gathered with a psum collective inside the same program (see
  feature/shard.py), so sampling, gathers, compute, and gradient sync all
  fuse into one XLA executable — there is no per-batch host round-trip at
  all, something the reference's CPU-driven loop cannot do.

Seed-block placement is selectable (``seed_sharding``): under ``"data"``
sampling runs redundantly across the ``feature`` axis (same seeds, same
fold-in key => identical results per replica) and the sharded gather is a
psum; under ``"all"`` every device is a full data worker over its own seed
block and the sharded gather routes requests to their owning shard with
all_to_all (ShardedTensor.routed_gather) — measured, the redundancy of
"data" costs ~linearly in the feature-axis width, so prefer "all" whenever
feature > 1 (docs/Introduction.md "Cost of redundant sampling").
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.config import parse_size_bytes
from ..feature.feature import Feature
from ..feature.shard import ShardedFeature
from ..control.freq import heat_num_bins, row_heat_histogram
from ..obs.registry import (
    FEATURE_ROW_HEAT,
    GUARD_NONFINITE,
    GUARD_SKIPPED,
    PIPELINE_REISSUES,
    ROUTED_OVERFLOW,
    SAMPLE_OVERFLOW,
    TIER_HITS,
    TRAIN_OVERLAP_EFFICIENCY,
    MetricsRegistry,
)
from ..obs.timeline import StepTimeline
from ..obs.tracing import Tracer
from ..resilience.elastic import validate_resume_meta, worker_ordered_mean
from ..resilience.faults import Preemption
from ..resilience.guard import guard_verdict, guarded_update
from ..utils.trace import info_once
from ..parallel.mesh import DATA_AXIS, FEATURE_AXIS, shard_map
from ..parallel.pipeline import PipelinedBatch, Prefetcher
from ..parallel.train import cross_entropy_on_seeds
from ..sampling.sampler import Adj, GraphSageSampler, multilayer_sample

__all__ = ["DistributedTrainer", "DataParallelTrainer"]


def _metrics_report(metrics: MetricsRegistry, timeline: StepTimeline,
                    empty_note: str = "") -> str:
    """Shared one-call telemetry summary: every recorded registry metric
    (totals + the most recent per-step value) plus the host StepTimeline's
    streaming percentiles."""
    lines = []
    snaps = metrics.snapshots()
    if snaps:
        lines.append("metrics:")
        for s in snaps:
            arr = s.numpy
            head = f"  {s.name} ({s.kind}"
            if s.steps is not None:
                head += f", {s.steps} steps"
            head += ")"
            if s.kind == "counter":
                head += f": total={int(arr.sum())}"
                if s.steps is not None:
                    head += f" last={np.asarray(s.last()).tolist()}"
            else:
                head += f": last={np.asarray(s.last()).tolist()}"
                if s.steps is not None:
                    head += f" total={arr.sum(axis=0).tolist()}"
            lines.append(head)
    else:
        lines.append(f"metrics: (none recorded{empty_note})")
    lines.append("timeline:")
    lines.extend("  " + ln for ln in timeline.report().splitlines())
    return "\n".join(lines)


class DistributedTrainer:
    """Owns the fused train step for a (sampler, feature, model) triple.

    Args:
      mesh: (data, feature) mesh from parallel.mesh.make_mesh.
      sampler: GraphSageSampler (its topology is replicated to all devices)
        or a ``topo_sharding="mesh"`` DistGraphSageSampler (the CSR itself
        partitioned over the feature axis — requires
        ``seed_sharding="all"``; per-hop neighbor lookups and the sharded
        feature gather then share ONE ``routed_alpha`` budget and, with
        ``auto_alpha=True``, one tuner).
      feature: Feature (device_replicate) or ShardedFeature (mesh_shard).
        Cold tiers are fused too: pinned-host rows ride as mesh-replicated
        operands and their staged gathers compose into the step program.
      model: flax module with (x, adjs, train=...) signature.
      tx: optax optimizer.
      local_batch: per-device seed-block size (padded).
      nonfinite_guard: compile the non-finite step guard into the step —
        a NaN/Inf loss or gradient cond-skips the optimizer update
        (params/opt_state pass through bit-unchanged) on a mesh-agreed
        verdict; skip/non-finite counters ride the metrics registry.
      fault_plan: a resilience.FaultPlan for deterministic chaos drills
        (in-program NaN feature rows at planned steps, simulated
        preemption); None = no injection compiled in.
      checkpoint_dir / checkpoint_every / checkpoint_keep: enable async
        checkpointing (utils/checkpoint.py: atomic manifest-based saves
        with per-array checksums) — epoch_scan saves (params, opt_state,
        step, PRNG key) every ``checkpoint_every`` steps (between scan
        chunks), keeping ``checkpoint_keep`` checkpoints; see
        :meth:`resume`.
      logical_workers: pin the LOGICAL seed-block worker count
        independently of the mesh (elastic mode; requires
        ``seed_sharding="all"`` and a multiple of the device count). Each
        device then runs ``logical_workers / devices`` blocks per step
        with the per-block PRNG key folded on the logical worker index,
        and the gradient/loss mean reduces in fixed logical-worker order
        (``resilience.elastic.worker_ordered_mean``) — the trajectory
        becomes bitwise independent of the mesh shape, which is what lets
        ``resume(mesh=)`` continue a run checkpointed at F=8 on an F=4
        mesh bit-identically. None (default) = one block per device with
        the plain pmean reduction (the non-elastic fast path).
      pipeline_depth: 0 (default) = the serial epoch scan (sample ->
        gather -> fwd/bwd -> update strictly in order each step); 1 =
        the software-pipelined epoch schedule: the scan carry becomes
        (params, opt_state, next_batch) with a ONE-STEP skew — the body
        trains the carried batch while issuing step t+1's sample+gather,
        so XLA can overlap the all_to_all / cold-tier gather collectives
        with the forward/backward compute (a prologue issues batch 0, an
        epilogue trains the final carried batch). Only the schedule
        moves: per-step keys stay the pre-split matrix and the two
        halves compose to the exact serial op sequence, so losses,
        params, and per-step telemetry are BITWISE identical to depth 0
        (tests/test_pipelined_epoch.py), including across checkpoint
        chunks — each chunk re-issues its first batch from the seed
        matrix (deterministic replay; counted in
        ``train.pipeline_reissues``) so chunk state never needs to
        serialize the in-flight batch. Affects epoch_scan only; step()
        stays the fused serial program.
      controller: a :class:`~quiver_tpu.control.CacheController` that
        owns the store's placement/routing decisions. The trainer
        attaches it to a ShardedFeature (L0/L1 boundary moves + measured
        ``repin`` re-tiering), registers its in-program row-heat
        histogram feed (``feature.row_heat`` — rides the metrics pytree,
        zero-cost when ``collect_metrics=False``), delegates the shared
        ``routed_alpha`` tuning to it, and drives its epoch hooks from
        :meth:`epoch_scan`. ``auto_alpha=True`` with no controller is a
        compat shim: a default alpha-only controller is created (grow on
        overflow as before, PLUS shrink on sustained slack). A frozen
        controller observes without deciding — the step program and
        trajectory stay bitwise those of ``controller=None``.
    """

    def __init__(
        self,
        mesh: Mesh,
        sampler: GraphSageSampler,
        feature: Feature | ShardedFeature,
        model,
        tx: optax.GradientTransformation,
        local_batch: int = 128,
        seed_sharding: str = "data",
        routed_alpha: float | None = 2.0,
        replicate_budget: int | str | None = None,
        auto_alpha: bool = False,
        collect_metrics: bool = True,
        nonfinite_guard: bool = False,
        fault_plan=None,
        checkpoint_dir=None,
        checkpoint_every: int = 0,
        checkpoint_keep: int = 3,
        logical_workers: int | None = None,
        pipeline_depth: int = 0,
        controller=None,
        donate_epoch_state: bool = False,
        tracer: Tracer | None = None,
        recorder=None,
    ):
        # beyond-HBM configs fuse too: HOST-mode topology and cold-tier
        # feature rows ride as mesh-replicated pinned-host operands, and the
        # staged host gathers (ops/sample.staged_gather — memory-SPACE
        # transfers, shard_map-safe) compose into the same one-program step
        # (reference equivalent: UVA training is its main papers100M path,
        # dist_sampling_ogb_paper100M_quiver.py:120-165).
        # seed_sharding: which mesh axes carry seed blocks.
        #   "data" — the original design: every member of a feature-axis
        #     group runs the SAME seed block (sampling + model work is
        #     duplicated feature-size times; the sharded-table gather is a
        #     cheap psum). Right when feature == 1.
        #   "all"  — every device is a full data worker over its own seed
        #     block; the sharded-table gather routes requests to owners
        #     with all_to_all (ShardedTensor.routed_gather) — the true
        #     NVLink-clique analogue (each reference GPU runs its own batch
        #     and loads peer HBM). Measured on the 8-dev CPU mesh the
        #     redundancy of "data" costs ~linearly in feature size
        #     (docs/Introduction.md), so prefer "all" whenever feature > 1.
        # routed_alpha: capped-bucket factor for the seed_sharding="all"
        # sharded-table gather — destination buckets carry
        # ceil(alpha * L / F) lanes, so each all_to_all hop moves ~alpha*L
        # lanes instead of the exact-safe F*L (feature/shard.py comm
        # model). Overflowed lanes are fallback-served in-program (results
        # stay exact); their count lands in ``last_routed_overflow`` after
        # each step so callers can grow alpha between epochs. None = the
        # uncapped full-length buckets.
        self.seed_sharding = str(seed_sharding)
        if self.seed_sharding not in ("data", "all"):
            raise ValueError(
                f"seed_sharding must be 'data' or 'all', got {seed_sharding!r}"
            )
        if routed_alpha is not None and routed_alpha <= 0:
            raise ValueError(
                f"routed_alpha must be > 0 or None, got {routed_alpha}"
            )
        self.routed_alpha = None if routed_alpha is None else float(routed_alpha)
        # one routing budget for the whole step: the SAME routed_alpha caps
        # the sharded-feature gather buckets AND (for a topo_sharding="mesh"
        # sampler) the per-hop neighbor-routing buckets. auto_alpha=True
        # turns on the shared tuner (a default control.CacheController —
        # see _maybe_grow_routed_alpha): overflow from an eager batch
        # doubles alpha (capped at F), sustained slack shrinks it back
        # (floor-bounded, no oscillation), and either change retraces.
        self.auto_alpha = bool(auto_alpha)
        # graftscope (obs/): ONE registry serves every telemetry stream the
        # step program produces. The traced body feeds a MetricsTape, the
        # resulting metrics pytree rides the shard_map/scan outputs (psum'd
        # once per step at each metric's declared axes), and step()/
        # epoch_scan() land it as typed MetricSnapshots. The legacy
        # ``last_*`` attributes below are thin views of the registry:
        #   feature.routed_overflow — fallback-served lane count of the
        #     step (scalar; (steps,) after epoch_scan; 0 when the gather
        #     is psum-flavored or uncapped)
        #   feature.tier_hits — per-tier hits [replicated, sharded, cold],
        #     psum'd mesh-wide (int32 (3,); (steps, 3) after epoch_scan) —
        #     what the eager split tuner consumes between batches
        #   sample.hop_overflow — the topo-sharded sampler's per-hop
        #     fallback lanes (int32 (num_layers,), seeds-outward;
        #     (steps, num_layers) after epoch_scan; zeros for replicated
        #     topologies)
        # collect_metrics=False disables collection at the PROGRAM level:
        # the compiled step carries zero metric values/collectives and the
        # loss trajectory is bit-identical (tests/test_obs.py differential).
        self.collect_metrics = bool(collect_metrics)
        self.metrics = MetricsRegistry(enabled=self.collect_metrics)
        self.metrics.counter(
            ROUTED_OVERFLOW, unit="lanes",
            doc="capped-bucket fallback-served lanes of the step's sharded "
                "feature gather",
        )
        self.metrics.gauge(
            TIER_HITS, shape=(3,), unit="hits",
            doc="mesh-total per-tier feature hits "
                "[replicated, sharded, cold]",
        )
        self.metrics.counter(
            SAMPLE_OVERFLOW, shape=(len(tuple(sampler.sizes)),),
            unit="lanes",
            doc="per-hop fallback-served lanes of the topo-sharded "
                "sampler (seeds-outward)",
        )
        # resilience (resilience/): nonfinite_guard=True compiles the
        # non-finite step guard into the step body — a NaN/Inf loss or
        # gradient cond-skips the optimizer update (params/opt_state pass
        # through bit-unchanged) with a mesh-psum'd verdict so every chip
        # takes the same branch. The guard's counters ride the registry
        # only when the guard is on: a guard-off program carries zero
        # extra values and its loss trajectory is the bit-identical
        # baseline (tests/test_resilience.py differential).
        self.nonfinite_guard = bool(nonfinite_guard)
        if self.nonfinite_guard:
            self.metrics.counter(
                GUARD_SKIPPED, unit="steps",
                doc="optimizer updates cond-skipped by the non-finite "
                    "step guard (mesh-agreed verdict)",
            )
            self.metrics.counter(
                GUARD_NONFINITE, unit="values",
                doc="non-finite loss/grad values detected before the "
                    "gradient pmean",
            )
        # software-pipelined epoch (pipeline_depth=1): epoch_scan runs the
        # one-step-skew schedule — train the carried batch while issuing
        # the next one — built from the same issue/train halves the serial
        # body composes, so the trajectory stays bitwise identical while
        # the sample/gather collectives overlap the fwd/bwd compute. The
        # pipeline telemetry registers only when the schedule exists: a
        # depth-0 registry is byte-for-byte the pre-pipeline one.
        self.pipeline_depth = int(pipeline_depth)
        if self.pipeline_depth not in (0, 1):
            raise ValueError(
                f"pipeline_depth must be 0 (serial) or 1 (one-step skew), "
                f"got {pipeline_depth}"
            )
        # donate_epoch_state=True marks the (params, opt_state) arguments
        # of the epoch program as donated: XLA reuses the incoming leaves
        # for the scan carry instead of double-buffering them, halving the
        # model-state HBM footprint of epoch_scan. CONSUME semantics — the
        # arrays the caller passes in are deleted after the call (on every
        # backend, including CPU), so it is opt-in: the differential tests
        # reuse their initial params across variants and must keep the
        # default. epoch_scan itself is donation-safe — its chunk loop
        # rebinds (params, opt_state) from each chunk's outputs. graftaudit
        # (tools/audit, donation-audit rule) verifies the claim on the
        # lowered IR: exactly the params+opt leaves carry donation attrs
        # and the trace emits no unused-donation warning.
        self.donate_epoch_state = bool(donate_epoch_state)
        self._pipeline_reissues = 0
        if self.pipeline_depth:
            self.metrics.counter(
                PIPELINE_REISSUES, unit="batches",
                doc="prologue batches re-issued from the seed matrix at "
                    "checkpoint-chunk/resume boundaries (the carried "
                    "batch is replayed, not serialized)",
            )
            self.metrics.gauge(
                TRAIN_OVERLAP_EFFICIENCY, dtype=jnp.float32, unit="x",
                doc="serial stage-sum over measured pipelined step time "
                    "(> 1.0 = sample/gather latency hidden under "
                    "compute; host-derived, see StepTimeline."
                    "overlap_efficiency)",
            )
        # fault_plan: deterministic chaos schedule (resilience/faults.py).
        # Step indices mean the epoch_scan row (or the eager step() call
        # count): planned steps get their gathered features NaN-poisoned
        # in-program, and the planned preemption raises Preemption once
        # the step has run but before its checkpoint lands.
        self.fault_plan = fault_plan
        self._fault_step = 0  # eager step() call counter the plan indexes
        self._preempt_fired = False
        # grafttrace: host-side span tracing (disabled tracer = zero work,
        # bitwise-identical trajectory — spans are taken OUTSIDE every
        # compiled program) + flight-recorder trigger on guard trips
        self.tracer = tracer if tracer is not None else Tracer(enabled=False)
        self.recorder = recorder
        self._guard_trips_seen = 0
        # checkpoint/auto-resume: checkpoint_dir= + checkpoint_every=
        # drive async atomic saves of (params, opt_state, step, PRNG key)
        # between scan chunks; resume() restores the latest and the
        # caller replays the packed seed stream from the saved step
        # (bit-identical trajectory — pack_epoch is deterministic per
        # seed, and the per-step keys are split from the saved key0).
        self.checkpoint_every = int(checkpoint_every)
        if checkpoint_dir is not None:
            if self.checkpoint_every < 1:
                raise ValueError(
                    "checkpoint_dir= requires checkpoint_every >= 1 "
                    f"(got {checkpoint_every})"
                )
            from ..utils.checkpoint import Checkpointer

            self.checkpointer = Checkpointer(
                checkpoint_dir, max_to_keep=checkpoint_keep,
                tracer=self.tracer,
            )
            latest = self.checkpointer.latest_step()
            # a pre-existing run directory: keep manager ids monotonic
            self._ckpt_seq = 0 if latest is None else latest + 1
        else:
            if self.checkpoint_every:
                raise ValueError(
                    "checkpoint_every= without checkpoint_dir= has "
                    "nothing to write to"
                )
            self.checkpointer = None
            self._ckpt_seq = 0
        # host-side stage timeline (streaming p50/p95/p99); step() and
        # epoch_scan() time their eager dispatch, callers can add their own
        # stages (or feed it via Timer(registry=trainer.timeline))
        self.timeline = StepTimeline()
        # replicate_budget: L0 super-hot tier override. A value re-splits a
        # ShardedFeature's replicated/sharded boundary BEFORE the program
        # is built (needs the store's retained host region); on a plain
        # Feature the hot tier is already a per-device replica, so the
        # argument is accepted-and-INERT (one-shot log). None = keep the
        # store's own split.
        if replicate_budget is not None:
            if isinstance(feature, ShardedFeature):
                feature.resplit_budget(replicate_budget)
            elif parse_size_bytes(replicate_budget):
                info_once(
                    "trainer-replicate-budget-inert",
                    "DistributedTrainer(replicate_budget=%r) on a "
                    "device_replicate Feature is INERT: its hot tier is "
                    "already replicated per device (zero-comm); size it "
                    "with device_cache_size",
                    replicate_budget,
                )
        if self.seed_sharding == "data" and mesh.shape[FEATURE_AXIS] > 1:
            from ..utils.trace import get_logger

            get_logger().info(
                "seed_sharding='data' on a feature=%d mesh duplicates "
                "sampling/model work %dx across the feature group; "
                "seed_sharding='all' removes that cost (measured ~linear, "
                "docs/Introduction.md)",
                mesh.shape[FEATURE_AXIS], mesh.shape[FEATURE_AXIS],
            )
        self.mesh = mesh
        self.sampler = sampler
        self.feature = feature
        self.model = model
        self.tx = tx
        self.local_batch = int(local_batch)
        # topo_sharding="mesh" sampler: the graph is partitioned over the
        # feature axis — the step routes frontier vertices to their owning
        # shard per hop (sampling/dist.py), so it REQUIRES every device to
        # be a seed-block worker ("all"); under "data" the feature-group
        # members would route the same frontier redundantly
        self.topo_sharded = (
            getattr(sampler, "topo_sharding", "replicated") == "mesh"
        )
        if self.topo_sharded:
            if self.seed_sharding != "all":
                raise ValueError(
                    "a topo_sharding='mesh' sampler requires "
                    "seed_sharding='all' (every device a full sampling "
                    "worker over its own seed block)"
                )
            if sampler.mesh is not mesh:
                raise ValueError(
                    "the sampler's mesh must be the trainer's mesh "
                    "(the topology partition and the step program must "
                    "agree on the feature axis)"
                )
            if sampler.axis != FEATURE_AXIS:
                raise ValueError(
                    f"topo_sharding='mesh' sampler must shard over the "
                    f"'{FEATURE_AXIS}' axis, got {sampler.axis!r}"
                )
            self.topo = (sampler.topo.indptr, sampler.topo.indices)
        else:
            self.topo = self._mesh_wide_topo(sampler.topo)
        self._cold = self._mesh_wide_host(feature.cold) if getattr(
            feature, "_cold_is_host", False) else feature.cold
        self.data_size = mesh.shape[DATA_AXIS]
        self.feature_size = mesh.shape[FEATURE_AXIS]
        # seed-block workers: every device under "all", one per data group
        # under "data". Elastic mode (logical_workers=) decouples the
        # LOGICAL worker count from the mesh: seed packing, the per-block
        # PRNG fold-in, and the fixed-order gradient reduction all follow
        # the logical count, so the same run continues bit-identically on
        # a differently-shaped mesh (resume(mesh=)).
        self._device_workers = self.data_size * (
            self.feature_size if self.seed_sharding == "all" else 1
        )
        self.elastic = logical_workers is not None
        if self.elastic:
            lw = int(logical_workers)
            if self.seed_sharding != "all":
                raise ValueError(
                    "logical_workers= (elastic mode) requires "
                    "seed_sharding='all': every device must be a full "
                    "seed-block worker for blocks to re-map across mesh "
                    "shapes"
                )
            if lw < self._device_workers or lw % self._device_workers:
                raise ValueError(
                    f"logical_workers={lw} must be a multiple of the "
                    f"device worker count {self._device_workers} (each "
                    f"device runs logical_workers/devices seed blocks)"
                )
            self.workers = lw
        else:
            self.workers = self._device_workers
        self.blocks_per_device = self.workers // self._device_workers
        self.global_batch = self.local_batch * self.workers
        # quiver-ctl (control/): one controller owns the placement and
        # routing decisions the legacy flags delegate to. auto_alpha with
        # no controller builds an alpha-only default (heat_bins=0, NOT
        # attached to the store — it must not start moving a split the
        # user never opted into); an explicit controller is attached to a
        # ShardedFeature and gets the in-program heat feed when it asks
        # for one (registered HERE, before the program builds, so the
        # histogram rides the step's metrics pytree).
        if controller is None and self.auto_alpha:
            from ..control import CacheController

            controller = CacheController(heat_bins=0)
        elif controller is not None and isinstance(feature, ShardedFeature):
            controller.attach(feature)
        self.controller = controller
        if (
            controller is not None
            and controller.wants_heat
            and self.collect_metrics
            and isinstance(feature, ShardedFeature)
            and feature.shape
        ):
            self.metrics.gauge(
                FEATURE_ROW_HEAT,
                shape=(heat_num_bins(feature.shape[0],
                                     controller.heat_bins),),
                unit="hits",
                doc="in-program per-row access-heat histogram (positional "
                    "bins over the store's translated row order, "
                    "mesh-total; feeds the controller's FreqSketch)",
            )
        _, self.caps = sampler._compiled(self.local_batch)
        self._step = self._build()
        self._epoch_fn = self._build_epoch()
        # streaming-mutation versions this program is bound to: the step
        # captured device operands (the topology arrays above, the
        # mesh-wide cold copy) from the host state as of THESE versions;
        # a quiver_tpu.streaming commit bumps them, after which
        # dispatching the captured program would silently read the
        # pre-commit graph/rows — step()/epoch_scan() raise instead
        # (refresh() re-captures and re-binds)
        self._bound_versions = self._current_versions()

    # -- telemetry views (API compatibility over the metrics registry) ------

    @property
    def last_routed_overflow(self):
        """Thin view of registry metric ``feature.routed_overflow``."""
        return self.metrics.value(ROUTED_OVERFLOW)

    @last_routed_overflow.setter
    def last_routed_overflow(self, value):
        self.metrics.set(ROUTED_OVERFLOW, value)

    @property
    def last_tier_hits(self):
        """Thin view of registry metric ``feature.tier_hits``."""
        return self.metrics.value(TIER_HITS)

    @last_tier_hits.setter
    def last_tier_hits(self, value):
        self.metrics.set(TIER_HITS, value)

    @property
    def last_sample_overflow(self):
        """Thin view of registry metric ``sample.hop_overflow``."""
        return self.metrics.value(SAMPLE_OVERFLOW)

    @last_sample_overflow.setter
    def last_sample_overflow(self, value):
        self.metrics.set(SAMPLE_OVERFLOW, value)

    def metrics_report(self) -> str:
        """One-call text summary of the trainer's telemetry: every recorded
        registry metric (totals + the most recent per-step value) plus the
        host StepTimeline's streaming percentiles."""
        return _metrics_report(
            self.metrics, self.timeline,
            "" if self.collect_metrics else "; collect_metrics=False",
        )

    def health(self) -> dict:
        """The ``/healthz`` summary: worker geometry, bound streaming
        versions, checkpoint progress, guard-trip count."""
        topo_v, feat_v = self._current_versions()
        return {
            "workers": int(self.workers),
            "global_batch": int(self.global_batch),
            "topology_version": topo_v,
            "feature_version": feat_v,
            "checkpoint_seq": int(self._ckpt_seq),
            "guard_trips": int(self._guard_trips_seen),
        }

    def serve_telemetry(self, host: str = "127.0.0.1",
                        port: int = 0):
        """Start (and return) a live telemetry endpoint over this
        trainer: ``/metrics`` from its registry, ``/traces`` from its
        tracer, ``/healthz`` from :meth:`health`. Off unless called —
        the endpoint reads host-side snapshots only, so serving it
        cannot perturb the compiled step."""
        from ..obs.endpoint import TelemetryEndpoint

        return TelemetryEndpoint(
            metrics=self.metrics, tracer=self.tracer, health=self.health,
            host=host, port=port,
        ).start()

    # -- streaming-mutation versioning --------------------------------------

    def _current_versions(self) -> tuple[int, int]:
        """(topology version, feature version) of the HOST state right
        now — what a streaming commit bumps."""
        return (
            int(getattr(self.sampler.csr_topo, "version", 0)),
            int(getattr(self.feature, "version", 0)),
        )

    def _check_versions(self) -> None:
        """Raise instead of dispatching a program whose captured operands
        predate a streaming commit (silent stale reads: the step would
        sample the pre-commit topology and gather the pre-commit cold
        rows)."""
        current = self._current_versions()
        if current != self._bound_versions:
            from ..core.topology import VersionMismatchError

            raise VersionMismatchError(
                f"trainer program is bound to (topology, feature) "
                f"versions {self._bound_versions} but the host state has "
                f"committed {current}; call trainer.refresh() to "
                f"re-capture the mutated state before training"
            )

    def refresh(self) -> "DistributedTrainer":
        """Re-capture the trainer's device operands from the (mutated)
        host state and rebuild the step/epoch programs — the consumer
        side of a ``quiver_tpu.streaming`` commit.

        Refreshes, in order: the sampler's device topology (via its own
        ``refresh_topology`` seam, when stale), the trainer's captured
        topology operands, the mesh-wide cold-tier copy, the compiled
        step/epoch programs, and the bound versions. The mesh, the model,
        the optimizer state layout, the seed packing, and the PRNG
        discipline are untouched — only the graph/feature bytes the
        programs read are re-pulled."""
        if int(getattr(self.sampler.csr_topo, "version", 0)) != \
                self.sampler._topo_version:
            self.sampler.refresh_topology()
        if self.topo_sharded:
            self.topo = (self.sampler.topo.indptr, self.sampler.topo.indices)
        else:
            self.topo = self._mesh_wide_topo(self.sampler.topo)
        self._cold = self._mesh_wide_host(self.feature.cold) if getattr(
            self.feature, "_cold_is_host", False) else self.feature.cold
        self._step = self._build()
        self._epoch_fn = self._build_epoch()
        self._bound_versions = self._current_versions()
        return self

    # -- program ------------------------------------------------------------

    def _mesh_wide_host(self, arr):
        """Replicate a single-device pinned-host array across the mesh's
        host space (one addressable copy per device; same-host devices share
        RAM). Required because shard_map operands must match the mesh."""
        if arr is None:
            return None
        return jax.device_put(
            arr, NamedSharding(self.mesh, P(), memory_kind="pinned_host")
        )

    def _mesh_wide_topo(self, topo):
        """HOST-mode topologies arrive single-device-placed; re-anchor their
        pinned-host arrays mesh-wide so the fused program can stage gathers
        on every device. HBM topologies pass through (jit auto-replicates
        plain device arrays)."""
        if not getattr(topo, "host_indices", False):
            return topo
        from ..core.topology import DeviceTopology

        return DeviceTopology(
            topo.indptr,
            self._mesh_wide_host(topo.indices),
            self._mesh_wide_host(topo.eid),
            self._mesh_wide_host(topo.cum_weights),
            host_indices=True,
            search_iters=topo.search_iters,
        )

    def _feature_parts(self):
        """The feature-store arrays handed to the shard_map program:
        (rep, hot, cold, feature_order, scale). ``rep`` is the L0
        replicated super-hot block (ShardedFeature only; None on a plain
        Feature, whose whole hot tier is already a per-device replica).
        Read fresh each step: an eager resplit between batches swaps the
        tier buffers, and the new shapes re-key the jit cache."""
        if isinstance(self.feature, ShardedFeature):
            rep = self.feature.rep
            hot = None if self.feature.hot is None else self.feature.hot.table
        else:
            rep = None
            hot = self.feature.hot
        return (rep, hot, self._cold, self.feature.feature_order,
                self.feature.scale)

    def _build(self):
        mesh = self.mesh
        sampler = self.sampler
        feature = self.feature
        model = self.model
        tx = self.tx
        caps = self.caps
        sizes = sampler.sizes
        sharded = isinstance(feature, ShardedFeature)
        cold_is_host = getattr(feature, "_cold_is_host", False)

        routed = self.seed_sharding == "all"
        routed_alpha = self.routed_alpha
        topo_sharded = self.topo_sharded
        metrics = self.metrics
        guard = self.nonfinite_guard
        # fault injection is compiled in ONLY when the plan schedules NaN
        # steps: a plan-free program is byte-for-byte the baseline
        inject_rows = (
            int(self.fault_plan.nan_rows)
            if self.fault_plan is not None and self.fault_plan.injects_nan()
            else 0
        )
        node_count = sampler.csr_topo.node_count
        rows_per_shard = (
            sampler.topo.rows_per_shard if topo_sharded else 0
        )
        # in-program heat feed: compiled in ONLY when a controller
        # registered feature.row_heat (so a controller-off program is
        # byte-for-byte the baseline, like the guard counters)
        heat_on = metrics.enabled and FEATURE_ROW_HEAT in metrics.names()
        heat_bins = (
            metrics.spec(FEATURE_ROW_HEAT).shape[0] if heat_on else 0
        )

        def gather_features(parts, n_id):
            """Three-tier gather; returns (rows, routed_overflow_count,
            tier_hits) — the count is the feature-group total of
            capped-bucket fallback lanes (0 for psum/uncapped/unsharded
            gathers), tier_hits the local int32 (3,) per-tier hit vector
            (the step body psums it mesh-wide)."""
            from ..feature.feature import tiered_lookup, wrap_dequant_gathers
            from ..ops.sample import staged_gather

            rep_table, hot_table, cold_table, order, scale = parts
            # tier boundaries read at TRACE time, not capture time: an
            # eager resplit between batches moves them, and the changed
            # table shapes force this retrace
            rep_rows = feature.rep_rows if sharded else 0
            hot_rows = feature.hot_rows
            ov_box = [jnp.zeros((), jnp.int32)]
            rep_g = (
                None if rep_table is None
                else lambda ids: rep_table[ids]
            )
            if hot_table is None:
                hot_g = None
            elif sharded and routed:
                # distinct ids per feature-group member: route to owners.
                # Bucket capacity is static per id-length (the tiered
                # lookup calls with the full n_id width). L0/cold lanes
                # arrive as -1 and occupy no bucket capacity.
                def hot_g(ids):
                    cap = (
                        None if routed_alpha is None
                        else feature.hot.routed_cap(
                            int(ids.shape[0]), routed_alpha
                        )
                    )
                    rows, ov = feature.hot.routed_gather(
                        hot_table, ids, cap=cap, with_overflow=True
                    )
                    ov_box[0] = ov_box[0] + ov
                    return rows
            elif sharded:
                hot_g = lambda ids: jax.lax.psum(
                    feature.hot.local_gather(hot_table, ids), feature.hot.axis
                )
            else:
                hot_g = lambda ids: hot_table[ids]
            cold_g = (
                None if cold_table is None
                else lambda ids: staged_gather(cold_table, ids, cold_is_host)
            )
            rep_g, hot_g, cold_g = wrap_dequant_gathers(
                scale, hot_rows, hot_g, cold_g, rep_g, rep_rows
            )
            x, hits = tiered_lookup(
                n_id, order, hot_rows, hot_g, cold_g,
                rep_rows=rep_rows, rep_gather=rep_g,
                hot_miss_id=-1 if sharded else 0, with_hits=True,
            )
            heat = (
                row_heat_histogram(n_id, order, node_count, heat_bins)
                if heat_on else None
            )
            return x, ov_box[0], hits, heat

        elastic = self.elastic
        bpd = self.blocks_per_device
        workers = self.workers
        S = self.local_batch  # per-block seed length (static everywhere)

        def issue_block(topo, parts, seeds, key):
            # the SCHEDULE-MOVABLE half of one logical seed block: sample +
            # three-tier gather. ``key`` arrives already folded on the
            # block's LOGICAL worker index; the sampling stream is the
            # first split of it — exactly the stream the fused serial body
            # always drew — so an issued batch is bitwise the serial one
            # no matter where in the schedule it runs (the prologue, the
            # skewed scan body, or a checkpoint-chunk re-issue).
            sample_key = jax.random.split(key)[0]
            num_seeds = jnp.sum((seeds >= 0).astype(jnp.int32))
            if topo_sharded:
                # sharded-topology sampling: per-hop owner routing over the
                # feature axis, SAME routing budget (routed_alpha) as the
                # sharded feature gather below
                from ..sampling.dist import dist_multilayer_sample

                indptr_blk, indices_blk = topo
                n_id, _, adjs, _, _, _, hop_ovs = dist_multilayer_sample(
                    indptr_blk[0], indices_blk[0], rows_per_shard, seeds,
                    num_seeds, sample_key, sizes, caps,
                    axis=FEATURE_AXIS, num_shards=mesh.shape[FEATURE_AXIS],
                    routed_alpha=routed_alpha, dedup=sampler.dedup,
                    node_count=node_count,
                )
                sample_ov = jnp.stack(hop_ovs)  # feature-group totals
            else:
                n_id, _, adjs, _, _, _ = multilayer_sample(
                    topo, seeds, num_seeds, sample_key, sizes, caps,
                    weighted=sampler.weighted, kernel=sampler.kernel,
                    dedup=sampler.dedup,
                )
                sample_ov = jnp.zeros((len(sizes),), jnp.int32)
            x, routed_ov, tier_hits, heat = gather_features(parts, n_id)
            return (n_id, x, adjs, num_seeds, routed_ov, tier_hits,
                    sample_ov, heat)

        def train_block(params, n_id, x, adjs, num_seeds, labels, key,
                        inject):
            # the COMPUTE half: fault injection, label/mask prep, loss +
            # grad. Draws the dropout stream — the second split of the
            # same block key issue_block split its sampling stream from.
            dropout_key = jax.random.split(key)[1]
            if inject_rows:
                # FaultPlan NaN injection: poison the leading rows of the
                # gathered block on planned steps (inject is the per-step
                # plan flag) — a corrupt batch reaching the loss, which
                # the non-finite guard below must absorb. Lives in the
                # train half so a pipelined carried batch is poisoned at
                # the same point in the op sequence as the serial body.
                if not jnp.issubdtype(x.dtype, jnp.inexact):
                    raise ValueError(
                        f"FaultPlan NaN injection needs float features, "
                        f"got {x.dtype}"
                    )
                rows = min(inject_rows, int(x.shape[0]))
                poison = jnp.full((rows, x.shape[1]), jnp.nan, x.dtype)
                x = x.at[:rows].set(jnp.where(inject, poison, x[:rows]))
            lab = labels[jnp.clip(n_id[:S], 0)]
            mask = jnp.arange(S) < num_seeds

            def loss_fn(p):
                logits = model.apply(
                    {"params": p}, x, adjs, train=True,
                    rngs={"dropout": dropout_key}
                )
                return cross_entropy_on_seeds(logits[:S], lab, mask)

            return jax.value_and_grad(loss_fn)(params)

        def one_block(params, topo, parts, seeds, labels, key, inject):
            # one logical seed block = the two halves composed in place
            # (the serial schedule; pipeline_depth=1 runs the same halves
            # as separate programs with a one-step skew between them)
            (n_id, x, adjs, num_seeds, routed_ov, tier_hits, sample_ov,
             heat) = issue_block(topo, parts, seeds, key)
            loss, grads = train_block(
                params, n_id, x, adjs, num_seeds, labels, key, inject
            )
            return loss, grads, routed_ov, tier_hits, sample_ov, heat

        # the step program's metric names, split by producing half: the
        # issue half owns the sample/gather telemetry, the train half the
        # guard counters. The serial body finalizes their union (exactly
        # the names the fused step always emitted — host-only metrics like
        # train.pipeline_reissues never enter the program), the pipelined
        # halves finalize their own subset so the merged per-step dict is
        # disjoint instead of zero-filled entries clobbering real values.
        issue_names = (ROUTED_OVERFLOW, TIER_HITS, SAMPLE_OVERFLOW) + (
            (FEATURE_ROW_HEAT,) if heat_on else ()
        )
        train_names = (GUARD_SKIPPED, GUARD_NONFINITE) if guard else ()
        program_names = issue_names + train_names

        def body(params, opt_state, topo, parts, seeds, labels, key, inject):
            # distinct key per seed-block worker; under "data" sharding the
            # feature-axis members share the key (identical redundant
            # sampling)
            widx = jax.lax.axis_index(DATA_AXIS)
            if routed:
                widx = widx * mesh.shape[FEATURE_AXIS] + jax.lax.axis_index(
                    FEATURE_AXIS
                )
            axes = (DATA_AXIS, FEATURE_AXIS)
            if not elastic:
                (loss, grads, routed_ov, tier_hits, sample_ov,
                 heat) = one_block(
                    params, topo, parts, seeds, labels,
                    jax.random.fold_in(key, widx), inject
                )
                if guard:
                    # verdict BEFORE the pmean (it spreads one worker's NaN
                    # mesh-wide); psum'd over both axes so every chip agrees
                    ok, local_bad = guard_verdict(loss, grads, axes)
                grads = jax.lax.pmean(grads, axes)
                loss = jax.lax.pmean(loss, axes)
            else:
                # elastic mode: this device runs ``bpd`` logical seed
                # blocks sequentially (every device runs the same
                # per-block program, so the per-block collectives stay
                # uniform and deadlock-free), each keyed on its LOGICAL
                # worker index — at bpd=1 the keys equal the non-elastic
                # fold exactly. The mean then reduces in fixed logical-
                # worker order (all_gather is device-major, blocks-minor
                # = worker order), making loss/grads bitwise independent
                # of how many devices the workers map onto: the seam
                # resume(mesh=) relies on.
                blocks = seeds.reshape(bpd, -1)
                outs = [
                    one_block(
                        params, topo, parts, blocks[b], labels,
                        jax.random.fold_in(key, widx * bpd + b), inject
                    )
                    for b in range(bpd)
                ]
                losses = jnp.stack([o[0] for o in outs])
                grads_blocks = jax.tree_util.tree_map(
                    lambda *g: jnp.stack(g), *[o[1] for o in outs]
                )
                routed_ov = sum(o[2] for o in outs)
                tier_hits = sum(o[3] for o in outs)
                sample_ov = sum(o[4] for o in outs)
                heat = sum(o[5] for o in outs) if heat_on else None
                if guard:
                    # stacked per-block values: one verdict for the whole
                    # step, still counted before any cross-worker mean
                    ok, local_bad = guard_verdict(losses, grads_blocks, axes)
                grads = worker_ordered_mean(grads_blocks, axes, workers)
                loss = worker_ordered_mean(losses, axes, workers)
            # graftscope: the step's telemetry rides ONE metrics pytree.
            # Each metric declares its own mesh reduction (applied once by
            # tape.finalize): the routed overflow and per-hop sample
            # overflow are feature-psum'd inside the route already, so the
            # data-axis psum makes them mesh-wide totals; tier hits under
            # "all" are distinct lanes per device (mesh-wide psum = batch
            # total) while under "data" the feature-group members process
            # the SAME lanes redundantly — summing them too would overcount
            # each lane F times. With collect_metrics=False the tape feeds
            # nothing and the program carries zero metric collectives.
            tape = metrics.tape()
            tape.add(ROUTED_OVERFLOW, routed_ov, psum=DATA_AXIS)
            tape.set(TIER_HITS, tier_hits,
                     psum=axes if routed else DATA_AXIS)
            if heat_on:
                # same reduction discipline as tier_hits: distinct lanes
                # per device under "all", redundant under "data"
                tape.set(FEATURE_ROW_HEAT, heat,
                         psum=axes if routed else DATA_AXIS)
            if topo_sharded:
                tape.add(SAMPLE_OVERFLOW, sample_ov, psum=DATA_AXIS)
            if guard:
                # local_bad counts this worker's non-finite values; under
                # "data" sharding the feature-group members recompute the
                # SAME grads, so summing them too would overcount F times
                # (same discipline as tier_hits). The skip flag is already
                # mesh-agreed (psum'd verdict) — no further reduction.
                tape.add(GUARD_NONFINITE, local_bad,
                         psum=axes if routed else DATA_AXIS)
                tape.add(GUARD_SKIPPED, (~ok).astype(jnp.int32))
                params, opt_state = guarded_update(
                    tx, grads, opt_state, params, ok
                )
            else:
                updates, opt_state = tx.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
            return params, opt_state, loss, tape.finalize(
                names=program_names
            )

        hot_spec = P(FEATURE_AXIS, None) if sharded else P()
        parts_spec = (P(), hot_spec, P(), P(), P())
        topo_spec = (
            (P(FEATURE_AXIS, None), P(FEATURE_AXIS, None))
            if topo_sharded else P()
        )
        # metric values come out replicated (psum'd at their declared axes)
        metric_specs = (
            {name: P() for name in program_names}
            if metrics.enabled else {}
        )
        fn = shard_map(
            body,
            mesh=mesh,
            in_specs=(
                P(), P(), topo_spec, parts_spec, self._seed_spec(), P(),
                P(), P(),
            ),
            out_specs=(P(), P(), P(), metric_specs),
            check_vma=False,
        )
        step = jax.jit(fn)
        if not self.pipeline_depth:
            self._issue = self._train = None
            return step

        # -- pipeline_depth=1: the two halves as standalone programs -------
        # Same mesh, same specs, same per-block key folds as the serial
        # body — only the SCHEDULE differs. The issue program materializes
        # a PipelinedBatch (per-block arrays stacked on a leading
        # blocks-per-device axis) plus its finalized sample/gather
        # telemetry; the train program consumes a carried batch one step
        # later and emits the guard counters. Composed serially they
        # reproduce the fused body's op sequence exactly, which is what
        # makes the pipelined trajectory bitwise identical.

        def issue_body(topo, parts, seeds, key):
            widx = jax.lax.axis_index(DATA_AXIS)
            if routed:
                widx = widx * mesh.shape[FEATURE_AXIS] + jax.lax.axis_index(
                    FEATURE_AXIS
                )
            axes = (DATA_AXIS, FEATURE_AXIS)
            blocks = seeds.reshape(bpd, -1)
            outs = [
                issue_block(
                    topo, parts, blocks[b],
                    jax.random.fold_in(key, widx * bpd + b)
                )
                for b in range(bpd)
            ]
            n_id = jnp.stack([o[0] for o in outs])
            x = jnp.stack([o[1] for o in outs])
            # Adj pytrees stack on their edge_index leaves; the static
            # size/fanout aux keeps describing the per-block shape (the
            # train half unstacks before the model consumes them)
            adjs = tuple(jax.tree_util.tree_map(
                lambda *leaves: jnp.stack(leaves), *[o[2] for o in outs]
            ))
            num_seeds = jnp.stack([o[3] for o in outs])
            routed_ov = sum(o[4] for o in outs)
            tier_hits = sum(o[5] for o in outs)
            sample_ov = sum(o[6] for o in outs)
            heat = sum(o[7] for o in outs) if heat_on else None
            # identical feeds (and psum axes) to the serial body — the
            # issue half owns the batch's telemetry so a carried batch's
            # metrics stay attributed to the step that SAMPLED it
            tape = metrics.tape()
            tape.add(ROUTED_OVERFLOW, routed_ov, psum=DATA_AXIS)
            tape.set(TIER_HITS, tier_hits,
                     psum=axes if routed else DATA_AXIS)
            if heat_on:
                tape.set(FEATURE_ROW_HEAT, heat,
                         psum=axes if routed else DATA_AXIS)
            if topo_sharded:
                tape.add(SAMPLE_OVERFLOW, sample_ov, psum=DATA_AXIS)
            return PipelinedBatch(
                n_id, x, adjs, num_seeds,
                tape.finalize(names=issue_names),
            )

        def train_body(params, opt_state, batch, labels, key, inject):
            widx = jax.lax.axis_index(DATA_AXIS)
            if routed:
                widx = widx * mesh.shape[FEATURE_AXIS] + jax.lax.axis_index(
                    FEATURE_AXIS
                )
            axes = (DATA_AXIS, FEATURE_AXIS)

            def block(b):
                adjs_b = jax.tree_util.tree_map(
                    lambda leaf: leaf[b], batch.adjs
                )
                return train_block(
                    params, batch.n_id[b], batch.x[b], adjs_b,
                    batch.num_seeds[b], labels,
                    jax.random.fold_in(key, widx * bpd + b), inject,
                )

            # mirror the serial body's reduction exactly: scalar verdict +
            # plain pmean outside elastic mode, stacked verdict + fixed
            # logical-worker-order mean inside it
            if not elastic:
                loss, grads = block(0)
                if guard:
                    ok, local_bad = guard_verdict(loss, grads, axes)
                grads = jax.lax.pmean(grads, axes)
                loss = jax.lax.pmean(loss, axes)
            else:
                outs = [block(b) for b in range(bpd)]
                losses = jnp.stack([o[0] for o in outs])
                grads_blocks = jax.tree_util.tree_map(
                    lambda *g: jnp.stack(g), *[o[1] for o in outs]
                )
                if guard:
                    ok, local_bad = guard_verdict(losses, grads_blocks, axes)
                grads = worker_ordered_mean(grads_blocks, axes, workers)
                loss = worker_ordered_mean(losses, axes, workers)
            tape = metrics.tape()
            if guard:
                tape.add(GUARD_NONFINITE, local_bad,
                         psum=axes if routed else DATA_AXIS)
                tape.add(GUARD_SKIPPED, (~ok).astype(jnp.int32))
                params, opt_state = guarded_update(
                    tx, grads, opt_state, params, ok
                )
            else:
                updates, opt_state = tx.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
            return params, opt_state, loss, tape.finalize(
                names=train_names
            )

        # the batch rides device-resident: every array keeps its producing
        # worker's shard (the same placement the seed blocks arrive with),
        # only the finalized metrics are replicated
        bspec = (
            P((DATA_AXIS, FEATURE_AXIS)) if routed else P(DATA_AXIS)
        )
        batch_spec = PipelinedBatch(
            n_id=bspec, x=bspec, adjs=bspec, num_seeds=bspec,
            metrics=(
                {name: P() for name in issue_names}
                if metrics.enabled else {}
            ),
        )
        train_metric_specs = (
            {name: P() for name in train_names}
            if metrics.enabled else {}
        )
        self._issue = jax.jit(shard_map(
            issue_body,
            mesh=mesh,
            in_specs=(topo_spec, parts_spec, self._seed_spec(), P()),
            out_specs=batch_spec,
            check_vma=False,
        ))
        self._train = jax.jit(shard_map(
            train_body,
            mesh=mesh,
            in_specs=(P(), P(), batch_spec, P(), P(), P()),
            out_specs=(P(), P(), P(), train_metric_specs),
            check_vma=False,
        ))
        return step

    # -- API ----------------------------------------------------------------

    def init(self, rng):
        """Initialize params/opt_state from one locally-sampled batch."""
        n = self.sampler.csr_topo.node_count
        m = min(self.local_batch, n)
        if self.topo_sharded:
            # no single-device program exists over a sharded topology, and
            # model init only consumes Adj SHAPES/fanout — build empty
            # (all-invalid) per-layer blocks with the planned caps
            caps = self.caps
            adjs = []
            prev = self.local_batch
            for cap, k in zip(caps, self.sampler.sizes):
                ei = jnp.full((2, prev * k), -1, jnp.int32)
                adjs.append(Adj(ei, None, (cap, prev), fanout=k))
                prev = cap
            adjs = adjs[::-1]
        else:
            padded = np.full(self.local_batch, -1, np.int32)
            padded[:m] = np.arange(m)
            run, caps = self.sampler._compiled(self.local_batch)
            _, _, adjs, _, _, _ = run(
                self.sampler.topo, jnp.asarray(padded), jnp.int32(m),
                jax.random.PRNGKey(0)
            )
        # the model sees what the tiered gather returns: dequantized f32 for
        # int8 storage, else the stored dtype (bf16/f32)
        dtype = (
            jnp.float32 if self.feature.scale is not None else self.feature.dtype
        )
        x = jnp.zeros((caps[-1], self.feature.shape[1]), dtype)
        params = self.model.init({"params": rng}, x, adjs)["params"]
        opt_state = self.tx.init(params)
        return params, opt_state

    def _seed_spec(self) -> P:
        if self.seed_sharding == "all":
            return P((DATA_AXIS, FEATURE_AXIS))
        return P(DATA_AXIS)

    def shard_seeds(self, seeds: np.ndarray):
        """Pack a global seed array into per-worker valid-prefix blocks,
        padded to (workers * local_batch,) with -1 (workers = every device
        under seed_sharding="all", one per data group under "data")."""
        seeds = np.asarray(seeds)
        blocks = np.array_split(seeds, self.workers)
        out = np.full((self.workers, self.local_batch), -1, np.int32)
        for i, b in enumerate(blocks):
            if len(b) > self.local_batch:
                raise ValueError(
                    f"per-device block {len(b)} exceeds local_batch {self.local_batch}"
                )
            out[i, : len(b)] = b
        return out.reshape(-1)

    def _check_guard_trip(self) -> None:
        """Flight-recorder trigger: a nonfinite-guard trip (the guard
        skipped >= 1 step since last checked) dumps a postmortem bundle
        naming the train stage while the explaining spans/metrics are
        still in the rings."""
        if self.recorder is None or not self.nonfinite_guard:
            return
        snap = self.metrics.snapshot(GUARD_SKIPPED)
        total = int(snap.total()) if snap is not None else 0
        if total > self._guard_trips_seen:
            self._guard_trips_seen = total
            self.recorder.trigger(
                "nonfinite_guard", stage="train", skipped_total=total,
            )

    def step(self, params, opt_state, seeds, labels, key):
        """One fused step. ``seeds``: global seed array (host). ``labels``:
        full (N,) label array (replicated).

        Batch metadata: after the call ``last_routed_overflow`` holds the
        step's capped-bucket fallback lane count (device scalar; 0 unless
        seed_sharding="all" with a sharded feature and a cap),
        ``last_tier_hits`` the mesh-total per-tier feature-hit vector
        (int32 (3,), [replicated, sharded, cold]), and
        ``last_sample_overflow`` the topo-sharded sampler's per-hop
        fallback lane counts (int32 (num_layers,), seeds-outward; zeros
        for replicated topologies). Persistent overflow means
        ``routed_alpha`` is too small for the id skew — pass
        ``auto_alpha=True`` (the shared tuner grows it between batches)
        or grow it yourself between epochs.

        A ShardedFeature built with ``auto_split=True`` consumes the hit
        vector here: the eager tuner moves its replicated/sharded boundary
        before the next step's dispatch (the changed tier shapes re-key
        the jit cache, so the program retraces on the new split).
        """
        self._check_versions()
        feature = self.feature
        plan = self.fault_plan
        step_idx = self._fault_step
        self._fault_step += 1
        with self.tracer.span("train.step", trace=f"train.step.{step_idx}",
                              subsystem="trainer", step=step_idx), \
                self.timeline.stage("step"):
            if isinstance(feature, ShardedFeature) and (
                feature.auto_split
                or getattr(feature, "_controller", None) is not None
            ):
                feature._maybe_auto_split()
            self._maybe_grow_routed_alpha()
            packed = self.shard_seeds(seeds)
            if self.controller is not None:
                # seeds are the host-visible slice of the step's gather
                # traffic — feed the controller's heavy-hitter set (the
                # in-program histogram covers the full id stream, but
                # only host-visible ids can NAME rows for a repin)
                self.controller.observe_ids(packed)
            packed = jax.device_put(
                jnp.asarray(packed),
                NamedSharding(self.mesh, self._seed_spec()),
            )
            inject = jnp.asarray(
                plan is not None and plan.nan_at(step_idx)
            )
            params, opt_state, loss, mtree = self._step(
                params, opt_state, self.topo, self._feature_parts(), packed,
                labels, key, inject
            )
        self.metrics.record(mtree)
        self._check_guard_trip()
        if mtree and isinstance(feature, ShardedFeature):
            # hand the batch totals to the store so its eager split tuner
            # sees the fused path's traffic too
            feature.last_tier_hits = mtree[TIER_HITS]
        if mtree and self.controller is not None:
            # fold the step's heat histogram into the controller's sketch
            # (no-op when the heat feed is off)
            self.controller.observe_histogram(mtree.get(FEATURE_ROW_HEAT))
        if (plan is not None and not self._preempt_fired
                and plan.preempts_in(step_idx, step_idx + 1)):
            # the step ran but its results are lost with the raise — the
            # caller resumes from the last checkpoint, like a real kill
            self._preempt_fired = True
            raise Preemption(f"simulated preemption at step {step_idx}")
        return params, opt_state, loss

    def pack_epoch(self, train_idx: np.ndarray, seed=None, key=None):
        """Shuffle ``train_idx`` and pack it into a (steps,
        workers*local_batch) seed matrix of per-worker valid-prefix blocks
        (-1 padded) — the xs of :meth:`epoch_scan`. Host-side preprocessing
        (the DataLoader shuffle of the reference's loop,
        dist_sampling_ogb_products:109)."""
        if seed is None:
            seed = key  # legacy name
        idx = np.asarray(train_idx)
        if seed is not None:
            # accept an int seed or a jax PRNGKey (typed or uint32 pair);
            # int() of a shape-(2,) key array would raise
            if hasattr(seed, "dtype") and jnp.issubdtype(
                    seed.dtype, jax.dtypes.prng_key):
                seed = jax.random.key_data(seed)
            if getattr(seed, "shape", ()) != ():
                seed = int(np.asarray(seed).ravel()[-1])
            idx = np.random.default_rng(int(seed)).permutation(idx)
        steps = -(-len(idx) // self.global_batch)
        return np.stack([
            self.shard_seeds(idx[s * self.global_batch: (s + 1) * self.global_batch])
            for s in range(steps)
        ])

    def _build_epoch(self):
        if self.pipeline_depth:
            return self._build_epoch_pipelined()
        step = self._step  # jitted shard_map; inlines under the outer jit

        # per-step keys arrive PRE-SPLIT (epoch_scan splits key0 eagerly —
        # a deterministic function of key0 and the FULL step count), so a
        # checkpoint-chunked epoch and a resumed one consume exactly the
        # slices an unchunked scan would have drawn: bit-identical keys
        # regardless of where the chunk/resume boundaries fall
        donate = (0, 1) if self.donate_epoch_state else ()

        @partial(jax.jit, donate_argnums=donate)
        def fn(params, opt_state, topo, parts, seed_mat, labels, keys,
               inject_vec):
            def body(carry, xs):
                p, o = carry
                seeds, k, inj = xs
                p, o, loss, mtree = step(
                    p, o, topo, parts, seeds, labels, k, inj
                )
                return (p, o), (loss, mtree)

            (p, o), (losses, mtrees) = jax.lax.scan(
                body, (params, opt_state), (seed_mat, keys, inject_vec)
            )
            # mtrees: each metric stacked to (steps,) + its per-step shape
            return p, o, losses, mtrees

        return fn  # jit's shape-keyed cache handles distinct step counts

    def _build_epoch_pipelined(self):
        """The software-pipelined epoch program (pipeline_depth=1).

        One-step skew: the scan carry is (params, opt_state, next_batch)
        where next_batch is step t's fully-materialized sample+gather
        result (a :class:`PipelinedBatch`). Iteration t trains the
        carried batch with step t's key/inject row, then issues step
        t+1's batch — two halves with NO data dependency between them,
        so XLA is free to overlap the issue half's all_to_all buckets
        and cold-tier host gathers with the train half's fwd/bwd
        compute. A prologue issues batch 0; an epilogue trains the final
        carried batch; scan's in-place carry aliasing keeps the double
        buffer allocation-free across iterations.

        Signature-compatible with the serial epoch fn, so epoch_scan's
        checkpoint chunking applies unchanged: each chunk's prologue
        re-issues its first batch from the seed matrix (per-step keys
        are pre-split from key0 over the FULL epoch — deterministic
        replay, bitwise the batch the previous chunk had in flight).
        """
        issue = self._issue
        train = self._train
        donate = (0, 1) if self.donate_epoch_state else ()

        @partial(jax.jit, donate_argnums=donate)
        def fn(params, opt_state, topo, parts, seed_mat, labels, keys,
               inject_vec):
            first = issue(topo, parts, seed_mat[0], keys[0])

            def body(carry, xs):
                p, o, batch = carry
                seeds_next, key_next, key_cur, inj_cur = xs
                p, o, loss, tmetrics = train(
                    p, o, batch, labels, key_cur, inj_cur
                )
                nxt = issue(topo, parts, seeds_next, key_next)
                # per-step telemetry = the TRAINED batch's issue metrics
                # (sampled possibly a chunk ago) + this step's guard
                # counters — disjoint dicts whose union is exactly the
                # serial step's metrics pytree
                return (p, o, nxt), (loss, {**batch.metrics, **tmetrics})

            # xs skewed by one: iteration t consumes step t's key/inject
            # for the train half and step t+1's seeds/key for the issue
            # half (length 0 for a single-step chunk — prologue+epilogue
            # alone cover it)
            xs = (seed_mat[1:], keys[1:], keys[:-1], inject_vec[:-1])
            (p, o, last), (losses, mtrees) = jax.lax.scan(
                body, (params, opt_state, first), xs
            )
            p, o, loss_last, tmetrics = train(
                p, o, last, labels, keys[-1], inject_vec[-1]
            )
            losses = jnp.concatenate([losses, loss_last[None]])
            last_m = {**last.metrics, **tmetrics}
            mtrees = {
                name: jnp.concatenate([mtrees[name], last_m[name][None]])
                for name in last_m
            }
            return p, o, losses, mtrees

        return fn

    def epoch_scan(self, params, opt_state, seed_mat, labels, key,
                   epoch: int = 0, start_step: int = 0):
        """A whole epoch as ONE compiled program: ``lax.scan`` over the
        packed per-step seed blocks with (params, opt_state) in the carry.

        This is the TPU-native epoch loop — the device never waits on the
        host between steps (the reference's per-batch Python loop pays a
        dispatch + sync round-trip per iteration; over a tunneled link
        that round-trip is ~90ms, dwarfing the step compute). One program
        per distinct step count; one loss-vector readback per epoch.

        Returns (params, opt_state, losses[steps]); the per-step
        capped-bucket fallback counts land in ``last_routed_overflow``
        (an int32[steps] device array) and the per-step per-tier feature
        hits in ``last_tier_hits`` (int32[steps, 3],
        [replicated, sharded, cold] mesh totals) — batch metadata for the
        auto-tuners and scoreboard. The split is frozen for the scanned
        epoch (one compiled program); the eager tuner moves it between
        epochs.

        Resilience: with ``checkpoint_dir=``/``checkpoint_every=`` set the
        epoch runs as scan CHUNKS of ``checkpoint_every`` steps, with an
        async save of (params, opt_state, step, PRNG key) after each chunk
        — the device still never waits on the host inside a chunk.
        ``start_step``/``epoch`` replay a resumed epoch: pass the SAME
        packed ``seed_mat`` (``pack_epoch`` with the same seed) and the
        key returned by :meth:`resume`, and the remaining trajectory is
        bit-identical to the uninterrupted run (per-step keys are split
        from key0 over the FULL step count, then sliced). A ``fault_plan``
        with ``preempt_at_step`` raises
        :class:`~quiver_tpu.resilience.Preemption` once that step's chunk
        has run but before its checkpoint lands (the drill's "kill").

        With ``pipeline_depth=1`` the same call runs the software-
        pipelined schedule (one-step skew, see
        :meth:`_build_epoch_pipelined`): identical signature, identical
        chunking/resume semantics, bitwise-identical losses, params, and
        per-step telemetry — each chunk re-issues its first batch from
        the seed matrix (``train.pipeline_reissues`` counts these), so
        the carried batch never needs to cross a chunk boundary as
        state.
        """
        self._check_versions()
        steps = int(np.shape(seed_mat)[0])
        start = int(start_step)
        if not 0 <= start <= steps:
            raise ValueError(
                f"start_step {start} outside [0, {steps}] for a "
                f"{steps}-step epoch"
            )
        plan = self.fault_plan
        losses_parts: list = []
        mtrees_parts: list = []
        # the epoch trace id is DETERMINISTIC (train.epoch.<n>): a
        # preempted run's resume records its chunks under the same id,
        # so the stitched timeline reads as one epoch across the restart
        etrace = self.tracer.trace(f"train.epoch.{int(epoch)}")
        with self.timeline.stage("epoch_scan"):
            if isinstance(self.feature, ShardedFeature) and getattr(
                    self.feature, "_controller", None) is not None:
                # actuate any pending split decision between epochs (the
                # legacy auto_split flag only ever consumed hits via
                # step()/gather(); a controller tunes the scanned path too)
                self.feature._maybe_auto_split()
            self._maybe_grow_routed_alpha()
            if self.controller is not None:
                # the epoch's seed matrix is its host-visible id stream
                # (see step(): only host-visible ids can name repin rows)
                self.controller.observe_ids(np.asarray(seed_mat))
            packed = jax.device_put(
                jnp.asarray(seed_mat),
                NamedSharding(self.mesh, P(None, *self._seed_spec())),
            )
            keys = jax.random.split(key, steps)
            if plan is not None and plan.injects_nan():
                inject_vec = jnp.asarray(plan.nan_mask(steps))
            else:
                inject_vec = jnp.zeros((steps,), bool)
            chunk = (
                self.checkpoint_every if self.checkpointer is not None
                else max(steps - start, 1)
            )
            lo = start
            while lo < steps:
                hi = min(lo + chunk, steps)
                t0 = self.tracer.now() if self.tracer.enabled else 0.0
                params, opt_state, losses, mtrees = self._epoch_fn(
                    params, opt_state, self.topo, self._feature_parts(),
                    packed[lo:hi], labels, keys[lo:hi], inject_vec[lo:hi]
                )
                # dispatch-timed (the device may still be running): under
                # pipelining the chunk span's issue half is this dispatch,
                # its train half drains inside the next blocking readback
                self.tracer.record(
                    "train.chunk", t0, self.tracer.now() - t0,
                    trace=etrace, subsystem="trainer", epoch=int(epoch),
                    start_step=lo, steps=hi - lo,
                    pipeline_depth=self.pipeline_depth,
                )
                if self.pipeline_depth and lo > start:
                    # pipelined chunks after the first re-issue their
                    # prologue batch (the previous chunk already had it in
                    # flight) — deterministic replay from the seed matrix
                    # instead of serializing the carried batch; count the
                    # overlap the boundary cost
                    self._pipeline_reissues += 1
                    self.metrics.set(
                        PIPELINE_REISSUES,
                        np.int32(self._pipeline_reissues),
                    )
                    self.tracer.event(
                        "train.reissue", trace=etrace,
                        subsystem="trainer", step=lo,
                    )
                losses_parts.append(losses)
                mtrees_parts.append(mtrees)
                if (plan is not None and not self._preempt_fired
                        and plan.preempts_in(lo, hi)):
                    # the chunk ran but dies un-checkpointed — resume()
                    # restores step `lo` and replays from there
                    self._preempt_fired = True
                    # land the partial epoch's telemetry before dying:
                    # the guard trips that explain the preempted run must
                    # reach the registry (and the flight recorder) even
                    # though the final record below never runs
                    if len(mtrees_parts) == 1:
                        self.metrics.record(mtrees_parts[0])
                    elif mtrees_parts:
                        self.metrics.record({
                            name: jnp.concatenate(
                                [m[name] for m in mtrees_parts]
                            )
                            for name in mtrees_parts[0]
                        })
                    self._check_guard_trip()
                    self.tracer.event(
                        "train.preempt", trace=etrace,
                        subsystem="trainer", step=plan.preempt_at_step,
                    )
                    if self.recorder is not None:
                        self.recorder.note(
                            "preemption", epoch=int(epoch),
                            step=int(plan.preempt_at_step),
                        )
                    raise Preemption(
                        f"simulated preemption at step "
                        f"{plan.preempt_at_step}: chunk [{lo}, {hi}) lost "
                        f"(last checkpoint at step {lo})"
                    )
                if self.checkpointer is not None:
                    self._save_checkpoint(
                        params, opt_state, key, epoch, hi,
                        steps_per_epoch=steps, trace=etrace,
                    )
                lo = hi
        if len(losses_parts) == 1:
            losses, mtrees = losses_parts[0], mtrees_parts[0]
        elif losses_parts:
            losses = jnp.concatenate(losses_parts)
            mtrees = {
                name: jnp.concatenate([m[name] for m in mtrees_parts])
                for name in mtrees_parts[0]
            }
        else:  # start == steps: a resumed, already-finished epoch
            losses, mtrees = jnp.zeros((0,), jnp.float32), {}
        self.metrics.record(mtrees)
        self._check_guard_trip()
        if self.controller is not None:
            # epoch-boundary controller hooks: fold the epoch's stacked
            # heat into the sketch, hand the epoch's tier-hit totals to
            # the store's split shim, then let the controller consider a
            # measured-hot repin and decay its sketch
            if mtrees:
                self.controller.observe_histogram(
                    mtrees.get(FEATURE_ROW_HEAT)
                )
                if isinstance(self.feature, ShardedFeature) and \
                        TIER_HITS in mtrees:
                    self.feature.last_tier_hits = np.asarray(
                        mtrees[TIER_HITS]
                    ).sum(axis=0)
            if isinstance(self.feature, ShardedFeature):
                self.controller.end_epoch(self.feature, self)
        return params, opt_state, losses

    # -- checkpoint / auto-resume -------------------------------------------

    def _save_checkpoint(self, params, opt_state, key, epoch, step,
                         steps_per_epoch: int | None = None,
                         trace: str | None = None) -> None:
        """Async atomic save between scan chunks. ``step`` counts completed
        rows of the CURRENT epoch's packed seed matrix; ``key`` is the
        epoch's key0 (stored as raw key data — restore re-splits it). The
        manifest metadata records the writer's mesh shape, logical worker
        count, and epoch geometry — what :meth:`resume` validates before
        trusting the state (and what makes the checkpoint
        topology-PORTABLE: an elastic resume onto a different mesh shape
        checks the logical facts, not the device layout)."""
        if hasattr(key, "dtype") and jnp.issubdtype(
                key.dtype, jax.dtypes.prng_key):
            key_data = jax.random.key_data(key)
        else:
            key_data = jnp.asarray(key)
        state = {
            "params": params,
            "opt_state": opt_state,
            "step": np.asarray(step, np.int32),
            "epoch": np.asarray(epoch, np.int32),
            "key": key_data,
        }
        meta = {
            "mesh": {DATA_AXIS: int(self.data_size),
                     FEATURE_AXIS: int(self.feature_size)},
            "workers": int(self.workers),
            "local_batch": int(self.local_batch),
            "seed_sharding": self.seed_sharding,
            "elastic": bool(self.elastic),
            "epoch": int(epoch),
            "step": int(step),
        }
        if steps_per_epoch is not None:
            meta["steps_per_epoch"] = int(steps_per_epoch)
        self.checkpointer.save(self._ckpt_seq, state, metadata=meta,
                               trace=trace)
        self._ckpt_seq += 1

    def resume(self, params, opt_state, mesh: Mesh | None = None,
               checkpoint_step: int | None = None):
        """Restore the newest VALID checkpoint, if any.

        ``checkpoint_step`` pins a specific checkpoint (the
        checkpointer's sequence id, see ``all_steps()``) instead of the
        newest valid one — e.g. rolling back past a bad data batch; a
        pinned checkpoint that fails verification raises
        ``CorruptCheckpoint`` instead of falling back.

        Returns ``(params, opt_state, key, step, epoch)`` — the restored
        train state, the saved epoch key0 (raw key data; feed it straight
        back to :meth:`epoch_scan`), and where training stopped. With no
        checkpoint on disk the inputs pass through with
        ``(key=None, step=0, epoch=0)``.

        Integrity: the checkpointer verifies per-array checksums and the
        COMMIT marker — a corrupt or half-written newest checkpoint is
        quarantined (one log line) and the newest VALID one restores
        instead; nothing resumes from garbage. The manifest metadata is
        then validated against this trainer: a logical-worker /
        local_batch mismatch, a restored step outside the saved epoch's
        ``steps_per_epoch``, or a mesh-shape change without the elastic
        opt-in below all raise instead of silently training a different
        run.

        **Elastic resume** (``mesh=``): restore onto a DIFFERENT mesh
        shape — preemption handed back a smaller slice. Requires the
        writing trainer to have pinned ``logical_workers=`` (the
        fixed-order reduction is what makes the trajectory mesh-shape
        independent). The trainer re-plans in place: the sharded topology
        and the three-tier feature store re-partition onto the new mesh
        via their ``replan`` seams, the step/epoch programs rebuild, and
        each device picks up ``logical_workers / devices`` seed blocks.
        A trainer freshly CONSTRUCTED on the new mesh (the real
        process-death flow) passes its own mesh explicitly —
        ``resume(mesh=trainer.mesh)`` — as the opt-in acknowledgment that
        the shape changed.

        To reproduce the uninterrupted run bit-identically, regenerate
        the SAME packed seed matrix (``pack_epoch`` with the same seed —
        the seed-stream replay) and call
        ``epoch_scan(..., key=key, epoch=epoch, start_step=step)``: the
        per-step keys are re-split from the saved key0 over the full
        epoch, so the remaining steps draw exactly the keys the
        preempted run would have.
        """
        if self.checkpointer is None:
            raise ValueError(
                "resume() needs checkpointing enabled "
                "(checkpoint_dir=/checkpoint_every= at construction)"
            )
        self.checkpointer.wait_until_finished()
        if checkpoint_step is None:
            latest = self.checkpointer.latest_valid_step()
            if latest is None:
                return params, opt_state, None, 0, 0
        else:
            latest = int(checkpoint_step)
        meta = self.checkpointer.metadata(latest)
        target = self.mesh if mesh is None else mesh
        target_shape = {DATA_AXIS: int(target.shape[DATA_AXIS]),
                        FEATURE_AXIS: int(target.shape[FEATURE_AXIS])}
        saved_mesh = meta.get("mesh")
        if (saved_mesh is not None and mesh is None
                and dict(saved_mesh) != target_shape):
            # satellite guard: the old path device_put a foreign-mesh
            # checkpoint blindly; a shape change must be an explicit
            # elastic opt-in
            raise ValueError(
                f"checkpoint was written on mesh {dict(saved_mesh)} but "
                f"this trainer's mesh is {target_shape}; pass "
                f"resume(mesh=) to opt into the elastic restore (requires "
                f"logical_workers= on the writing trainer)"
            )
        validate_resume_meta(
            meta, mesh_shape=target_shape, workers=self.workers,
            local_batch=self.local_batch,
        )
        if mesh is not None and mesh is not self.mesh:
            self._replan(mesh)
        template = {
            "params": params,
            "opt_state": opt_state,
            "step": np.zeros((), np.int32),
            "epoch": np.zeros((), np.int32),
            "key": np.zeros((2,), np.uint32),  # threefry2x32 key data
        }
        state = self.checkpointer.restore(latest, template=template)
        step = int(np.asarray(state["step"]))
        spe = meta.get("steps_per_epoch")
        if spe is not None and not 0 <= step <= int(spe):
            raise ValueError(
                f"restored step {step} is outside [0, {int(spe)}] for the "
                f"saved epoch — the checkpoint directory does not belong "
                f"to this run's seed packing"
            )
        # the restore hands back global host arrays; the step program
        # wants them mesh-replicated (in_spec P()) — anchor explicitly
        rep = NamedSharding(self.mesh, P())
        return (
            jax.device_put(state["params"], rep),
            jax.device_put(state["opt_state"], rep),
            jnp.asarray(np.asarray(state["key"])),
            step,
            int(np.asarray(state["epoch"])),
        )

    def _replan(self, mesh: Mesh) -> None:
        """Re-plan the trainer onto a new mesh shape (elastic resume).

        The logical worker count is FIXED (seed packing, per-block keys,
        and the fixed-order reduction all follow it); what changes is how
        many blocks each device runs. The sharded topology, the sharded
        feature store, and the sampler re-partition via their ``replan``
        seams — same bytes, new owners — and the compiled step/epoch
        programs rebuild against the new mesh.
        """
        if not self.elastic:
            raise ValueError(
                "resume(mesh=) needs an elastic trainer: construct with "
                "logical_workers=<the writing run's worker count> so the "
                "step reduction is mesh-shape independent"
            )
        dev_workers = int(mesh.shape[DATA_AXIS]) * int(
            mesh.shape[FEATURE_AXIS]
        )
        if dev_workers < 1 or self.workers % dev_workers:
            raise ValueError(
                f"cannot re-plan {self.workers} logical workers onto "
                f"{dev_workers} devices (must divide evenly)"
            )
        old = (int(self.data_size), int(self.feature_size))
        self.mesh = mesh
        self.data_size = mesh.shape[DATA_AXIS]
        self.feature_size = mesh.shape[FEATURE_AXIS]
        self._device_workers = dev_workers
        self.blocks_per_device = self.workers // dev_workers
        if self.topo_sharded:
            self.sampler.replan(mesh)
            self.topo = (self.sampler.topo.indptr, self.sampler.topo.indices)
        else:
            self.topo = self._mesh_wide_topo(self.sampler.topo)
        if isinstance(self.feature, ShardedFeature):
            self.feature.replan(mesh)
        self._cold = self._mesh_wide_host(self.feature.cold) if getattr(
            self.feature, "_cold_is_host", False) else self.feature.cold
        info_once(
            "trainer-elastic-replan",
            "elastic replan: mesh (data=%d, feature=%d) -> (data=%d, "
            "feature=%d); %d logical workers now run %d block(s)/device "
            "(trajectory stays bit-identical — fixed-order reduction)",
            old[0], old[1], int(self.data_size), int(self.feature_size),
            self.workers, self.blocks_per_device,
        )
        self._step = self._build()
        self._epoch_fn = self._build_epoch()
        # the replanned programs captured the CURRENT host state
        self._bound_versions = self._current_versions()

    # graftlint: eager -- between-batch tuner on host numpy telemetry; the
    def _maybe_grow_routed_alpha(self) -> None:  # step program never calls it
        """Shared eager routing tuner (compat shim over the controller's
        :class:`~quiver_tpu.control.AlphaTuner`): the sampler's per-hop
        routing and the feature gather draw on ONE budget, so one tuner
        reads both overflow telemetries. Overflow from the PREVIOUS eager
        batch doubles ``routed_alpha`` (capped at F — full-length
        buckets) as it always did; sustained SLACK (consecutive clean
        batches) now also shrinks it, bounded by a floor the tuner raises
        whenever a shrink is immediately punished, so a transient skew
        burst no longer inflates comm for the rest of the run. Either
        change rebuilds the step program (one retrace); overflow lanes
        were served exactly either way. ``auto_alpha=True`` builds the
        default controller this delegates to; pass ``controller=`` to
        share one with the split/repin decisions."""
        if self.controller is None or self.routed_alpha is None:
            return
        total = 0
        for v in (self.last_routed_overflow, self.last_sample_overflow):
            if v is None:
                continue
            try:
                total += int(np.asarray(v).sum())
            except Exception:  # noqa: BLE001 — a deleted/donated buffer
                continue  # must not break the next step
        new = self.controller.decide_alpha(
            total, self.routed_alpha, float(self.feature_size)
        )
        if new is None:
            return
        old = self.routed_alpha
        self.routed_alpha = float(new)
        from ..utils.trace import get_logger

        get_logger().info(
            "shared routing budget: %d lanes fallback-served last batch "
            "(feature gather + sampler hops); alpha %.2f -> %.2f "
            "(one retrace)",
            total, old, self.routed_alpha,
        )
        self.last_routed_overflow = None
        self.last_sample_overflow = None
        self._step = self._build()
        self._epoch_fn = self._build_epoch()


class DataParallelTrainer:
    """Unfused multi-chip training — the reference-shaped papers100M loop.

    Since r4 the fused :class:`DistributedTrainer` handles beyond-HBM
    configs too (staged host gathers compose into its one-program step);
    this trainer remains as the *unfused* alternative — host-driven
    sample/gather with prefetch overlap — mirroring the reference's
    flagship scale architecture
    exactly (benchmarks/ogbn-papers100M/dist_sampling_ogb_paper100M_quiver.py:
    120-165): each data-parallel worker samples its own seed block and
    gathers its own features (here: the single-controller sample/gather
    paths, which already stage host-resident topology and cold-tier rows
    through host compute), and only the model step runs as one SPMD program —
    a shard_map over the ``data`` axis with a gradient ``pmean``, the
    reference's DDP/NCCL allreduce (:133). :class:`Prefetcher` overlap makes
    batch i+1's sample+gather run under batch i's step — the role UVA's
    "kernel reads host RAM while computing" plays in the reference.

    Accepts ANY sampler/feature configuration (mode="HOST", cold tiers,
    weighted, auto caps); the feature store must be a replicated
    :class:`Feature` (the reference's papers100M config is device_replicate
    too; mesh-sharded hot tiers belong to the fused trainer).
    """

    def __init__(
        self,
        mesh: Mesh,
        sampler: GraphSageSampler,
        feature: Feature,
        model,
        tx: optax.GradientTransformation,
        local_batch: int = 128,
        prefetch_retries: int = 0,
        prefetch_backoff: float = 0.05,
        prefetch_skip_policy: str = "raise",
    ):
        if isinstance(feature, ShardedFeature):
            raise ValueError(
                "DataParallelTrainer replicates the feature store; use the "
                "fused DistributedTrainer for mesh-sharded hot tiers"
            )
        if mesh.shape.get(FEATURE_AXIS, 1) != 1:
            raise ValueError(
                "DataParallelTrainer is pure data parallelism; build the "
                "mesh with feature=1"
            )
        self.mesh = mesh
        self.sampler = sampler
        self.feature = feature
        self.model = model
        self.tx = tx
        self.local_batch = int(local_batch)
        self.data_size = mesh.shape[DATA_AXIS]
        self.global_batch = self.local_batch * self.data_size
        self._step_cache = {}
        # graftscope: the epoch loop's Prefetcher lands its retry/skip
        # counters here, so pipeline health is readable next to the rest
        # of the telemetry (metrics_report)
        self.metrics = MetricsRegistry()
        self.timeline = StepTimeline()
        # resilience knobs forwarded to the epoch loop's Prefetcher
        # (bounded retry + skip-and-count for transient host faults —
        # see parallel/pipeline.py; defaults keep the fail-fast behavior)
        self.prefetch_retries = int(prefetch_retries)
        self.prefetch_backoff = float(prefetch_backoff)
        self.prefetch_skip_policy = str(prefetch_skip_policy)
        self._pin_auto_caps()

    def _pin_auto_caps(self):
        """Pin auto frontier caps at construction (VERDICT r5 weak #6).

        ``frontier_caps="auto"`` replans caps whenever a batch overflows the
        observed plan — mid-epoch that makes stacked per-worker blocks
        disagree on static shapes and ``_stack`` can only raise. Plan ONCE
        here from a probe batch, then freeze: later skewed batches get the
        fixed-caps behavior (clipped frontier + overflow report) instead of
        a mid-epoch shape change. The probe advances the sampler's PRNG
        call counter by one.
        """
        if not getattr(self.sampler, "_auto_caps", False):
            return
        n = self.sampler.csr_topo.node_count
        probe = np.arange(min(self.local_batch, n))
        self.sampler.sample(probe)
        self.sampler._auto_caps = False
        from ..utils.trace import get_logger

        get_logger().info(
            "auto frontier caps planned from a probe batch and PINNED at "
            "%s for the epoch loop (mid-epoch replanning would make "
            "stacked blocks disagree; overflowing batches are clipped and "
            "reported instead)",
            self.sampler._frontier_caps,
        )

    # -- program ------------------------------------------------------------

    def _adj_sizes(self, caps) -> list[tuple[int, int]]:
        """Static Adj sizes, deepest layer first (sampler output order)."""
        sizes = []
        prev = self.local_batch
        for cap in caps:
            sizes.append((cap, prev))
            prev = cap
        return sizes[::-1]

    def _compiled_step(self, caps: tuple, fanouts: tuple, feat_dim: int):
        key_ = (caps, fanouts, feat_dim)
        if key_ in self._step_cache:
            return self._step_cache[key_]

        model, tx = self.model, self.tx
        S = self.local_batch
        adj_sizes = self._adj_sizes(caps)
        # deepest-first fanouts arrive from the prefetched batches' own Adj
        # metadata (_stack), not re-derived from sampler.sizes — restores
        # the regular layout the stacked arrays lost, so the step uses the
        # dense zero-scatter aggregation path (ADVICE trainer.py:446: the
        # sampler-ordering re-derivation was an implicit contract; the Adjs
        # already carry fanout through tree_flatten aux)

        def body(params, opt_state, x, eis, n_id, bsz, labels, key):
            # blocks arrive with a leading length-1 shard dim; squeeze it
            x_b = x[0]
            adjs = [
                Adj(ei[0], None, sz, fanout=f)
                for ei, sz, f in zip(eis, adj_sizes, fanouts)
            ]
            seed_ids = n_id[0][:S]
            lab = labels[jnp.clip(seed_ids, 0)]
            # mask by the block's true batch size: for a short block, lanes
            # [bsz, S) of n_id hold FRONTIER nodes (masked_unique compacts
            # first-occurrence order), not -1 — they must not be trained on
            mask = (jnp.arange(S) < bsz[0]) & (seed_ids >= 0)
            key = jax.random.fold_in(key, jax.lax.axis_index(DATA_AXIS))

            def loss_fn(p):
                logits = model.apply(
                    {"params": p}, x_b, adjs, train=True, rngs={"dropout": key}
                )
                return cross_entropy_on_seeds(logits[:S], lab, mask)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            grads = jax.lax.pmean(grads, DATA_AXIS)
            loss = jax.lax.pmean(loss, DATA_AXIS)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        n_layers = len(caps)
        fn = shard_map(
            body,
            mesh=self.mesh,
            in_specs=(
                P(),
                P(),
                P(DATA_AXIS),
                tuple([P(DATA_AXIS)] * n_layers),
                P(DATA_AXIS),
                P(DATA_AXIS),
                P(),
                P(),
            ),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )
        step = jax.jit(fn)
        self._step_cache[key_] = step
        return step

    # -- API ----------------------------------------------------------------

    def metrics_report(self) -> str:
        """One-call telemetry summary (prefetch retry/skip counters from
        the epoch loop's Prefetcher + host stage timeline)."""
        return _metrics_report(self.metrics, self.timeline)

    def init(self, rng):
        """Initialize params/opt_state from one sampled block."""
        n = self.sampler.csr_topo.node_count
        m = min(self.local_batch, n)
        out = self.sampler.sample(np.arange(m))
        x = self.feature[out.n_id]
        params = self.model.init({"params": rng}, x, out.adjs)["params"]
        return params, self.tx.init(params)

    def seed_blocks(self, seeds: np.ndarray):
        """Split a global seed array into per-device blocks
        (``train_idx.split(world_size)[rank]`` parity)."""
        seeds = np.asarray(seeds)
        blocks = np.array_split(seeds, self.data_size)
        for b in blocks:
            if len(b) > self.local_batch:
                raise ValueError(
                    f"block {len(b)} exceeds local_batch {self.local_batch}"
                )
        return blocks

    def _stack(self, batches):
        """Stack D per-worker (out, x) into data-sharded step inputs.

        Returns (caps, fanouts, x, n_id, eis, bsz) — per-layer batch
        metadata read off the blocks' own Adjs: caps in sizes order (seeds
        outward, what _adj_sizes expects), fanouts deepest-first (what the
        step body zips against the deepest-first eis).
        """
        caps = fanouts = None
        for b in batches:
            c = tuple(a.size[0] for a in b.out.adjs[::-1])
            f = tuple(a.fanout for a in b.out.adjs)
            if caps is None:
                caps, fanouts = c, f
            elif c != caps or f != fanouts:
                # unreachable for trainer-owned samplers (_pin_auto_caps
                # froze the plan); guards externally mutated samplers
                raise ValueError(
                    "sampled blocks disagree on frontier caps/fanouts "
                    f"({caps}/{fanouts} vs {c}/{f}); pin frontier_caps on "
                    "the sampler (auto caps may replan between blocks)"
                )
        n_layers = len(caps)
        x = self._shard_stack([b.x for b in batches])
        n_id = self._shard_stack([b.out.n_id for b in batches])
        eis = tuple(
            self._shard_stack([b.out.adjs[l].edge_index for b in batches])
            for l in range(n_layers)
        )
        bsz = self._shard_stack(
            [jnp.int32(b.out.batch_size) for b in batches]
        )
        return caps, fanouts, x, n_id, eis, bsz

    def _shard_stack(self, blocks):
        """Stack D per-worker arrays directly onto their target devices.

        Equivalent to ``device_put(jnp.stack(blocks), P(DATA_AXIS))`` but
        never materializes the full stacked batch on one device — each
        block hops straight to its shard's device (one transfer per block,
        no device-0 peak)."""
        devs = self.mesh.devices.reshape(self.data_size, -1)[:, 0]
        shards = [
            jax.device_put(jnp.asarray(b)[None], d)
            for b, d in zip(blocks, devs)
        ]
        shape = (self.data_size,) + tuple(shards[0].shape[1:])
        sharding = NamedSharding(self.mesh, P(DATA_AXIS))
        return jax.make_array_from_single_device_arrays(
            shape, sharding, shards
        )

    def step(self, params, opt_state, batches, labels, key):
        """One DP step from D prefetched batches (``Prefetcher`` Batch or
        anything with ``.out``/``.x``). ``labels``: full (N,) array."""
        if len(batches) != self.data_size:
            raise ValueError(
                f"need {self.data_size} batches (one per data shard), "
                f"got {len(batches)}"
            )
        caps, fanouts, x, n_id, eis, bsz = self._stack(batches)
        step = self._compiled_step(caps, fanouts, x.shape[-1])
        return step(params, opt_state, x, eis, n_id, bsz, labels, key)

    def train_epoch(self, params, opt_state, train_idx, labels, key,
                    rng=None, depth: int = 2):
        """One epoch with prefetch overlap: sample+gather for the next
        step's blocks runs while the current step computes.

        Returns (params, opt_state, mean_loss, num_steps).
        """
        rng = rng or np.random.default_rng(0)
        train_idx = np.asarray(train_idx)
        if train_idx.size == 0:
            # a silent float("nan") mean loss poisons every downstream
            # consumer (schedulers, early stopping, logs) — fail loudly
            raise ValueError(
                "train_epoch got an empty seed set (train_idx) — nothing "
                "to train on; check the split/filter that produced it"
            )
        perm = rng.permutation(len(train_idx))
        steps = max(len(train_idx) // self.global_batch, 1)
        blocks = []
        for s in range(steps):
            chunk = train_idx[perm[s * self.global_batch:(s + 1) * self.global_batch]]
            blocks.extend(self.seed_blocks(chunk))

        losses = []
        group = []
        prefetcher = Prefetcher(
            self.sampler, self.feature, depth=depth,
            retries=self.prefetch_retries, backoff=self.prefetch_backoff,
            skip_policy=self.prefetch_skip_policy,
            timeline=self.timeline, metrics=self.metrics,
        )
        for batch in prefetcher.run(blocks):
            group.append(batch)
            if len(group) == self.data_size:
                key, sub = jax.random.split(key)
                params, opt_state, loss = self.step(
                    params, opt_state, group, labels, sub
                )
                losses.append(loss)
                group = []
        mean_loss = float(jnp.mean(jnp.stack(losses))) if losses else float("nan")
        return params, opt_state, mean_loss, len(losses)
