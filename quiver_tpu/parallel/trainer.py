"""Fused SPMD training: sample + gather + forward/backward + update in one
jitted shard_map program over the device mesh.

This replaces the reference's entire multi-process runtime (mp.spawn + DDP +
NCCL allreduce + CUDA-IPC object sharing, dist_sampling_ogb_products_quiver.py:
82-163, reductions.py:5-32) with a single-controller SPMD program:

* ``data`` mesh axis = the reference's one-process-per-GPU data parallelism;
  per-device seed blocks mirror ``train_idx.split(world_size)[rank]``
  (dist_sampling_ogb_products_quiver.py:89).
* gradient ``pmean`` over the mesh = the DDP/NCCL allreduce (:100).
* ``feature`` mesh axis = the NVLink clique: the hot feature shard is
  gathered with a psum collective inside the same program (see
  feature/shard.py), so sampling, gathers, compute, and gradient sync all
  fuse into one XLA executable — there is no per-batch host round-trip at
  all, something the reference's CPU-driven loop cannot do.

Sampling runs redundantly across the ``feature`` axis (same seeds, same
fold-in key => identical results per replica) — cheaper than broadcasting
its outputs for the mesh sizes this targets.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..feature.feature import Feature
from ..feature.shard import ShardedFeature
from ..parallel.mesh import DATA_AXIS, FEATURE_AXIS
from ..parallel.train import cross_entropy_on_seeds
from ..sampling.sampler import GraphSageSampler, multilayer_sample

__all__ = ["DistributedTrainer"]


class DistributedTrainer:
    """Owns the fused train step for a (sampler, feature, model) triple.

    Args:
      mesh: (data, feature) mesh from parallel.mesh.make_mesh.
      sampler: GraphSageSampler (its topology is replicated to all devices).
      feature: Feature (device_replicate) or ShardedFeature (mesh_shard).
        The fused path requires the table fully device-resident; cold-tier
        configurations train via the unfused loop (sample -> feature -> step).
      model: flax module with (x, adjs, train=...) signature.
      tx: optax optimizer.
      local_batch: per-device seed-block size (padded).
    """

    def __init__(
        self,
        mesh: Mesh,
        sampler: GraphSageSampler,
        feature: Feature | ShardedFeature,
        model,
        tx: optax.GradientTransformation,
        local_batch: int = 128,
    ):
        if feature.cold is not None:
            raise ValueError(
                "fused SPMD training requires a fully device-resident feature "
                "table (cache covers all rows); use the unfused loop for "
                "cold-tier configs"
            )
        if getattr(sampler.topo, "host_indices", False):
            raise ValueError(
                "fused SPMD training requires an HBM-resident topology "
                "(mode='HBM'); HOST-mode staged gathers are single-device "
                "for now — use the unfused loop"
            )
        self.mesh = mesh
        self.sampler = sampler
        self.feature = feature
        self.model = model
        self.tx = tx
        self.local_batch = int(local_batch)
        self.data_size = mesh.shape[DATA_AXIS]
        self.global_batch = self.local_batch * self.data_size
        _, self.caps = sampler._compiled(self.local_batch)
        self._step = self._build()

    # -- program ------------------------------------------------------------

    def _build(self):
        mesh = self.mesh
        sampler = self.sampler
        feature = self.feature
        model = self.model
        tx = self.tx
        caps = self.caps
        sizes = sampler.sizes
        sharded = isinstance(feature, ShardedFeature)

        def gather_features(hot_table, n_id):
            valid = n_id >= 0
            ids = jnp.where(valid, n_id, 0)
            if feature.feature_order is not None:
                ids = feature.feature_order[ids]
            if sharded:
                part = feature.hot.local_gather(hot_table, ids)
                x = jax.lax.psum(part, feature.hot.axis)
            else:
                x = hot_table[ids]
            return jnp.where(valid[:, None], x, 0)

        def body(params, opt_state, topo, hot_table, seeds, labels, key):
            # distinct key per data index, shared across the feature axis;
            # separate streams for sampling vs dropout (use-once discipline)
            key = jax.random.fold_in(key, jax.lax.axis_index(DATA_AXIS))
            sample_key, dropout_key = jax.random.split(key)
            num_seeds = jnp.sum((seeds >= 0).astype(jnp.int32))
            n_id, _, adjs, _, _, _ = multilayer_sample(
                topo, seeds, num_seeds, sample_key, sizes, caps,
                weighted=sampler.weighted, kernel=sampler.kernel,
            )
            x = gather_features(hot_table, n_id)
            lab = labels[jnp.clip(n_id[: seeds.shape[0]], 0)]
            mask = jnp.arange(seeds.shape[0]) < num_seeds

            def loss_fn(p):
                logits = model.apply(
                    {"params": p}, x, adjs, train=True, rngs={"dropout": dropout_key}
                )
                return cross_entropy_on_seeds(logits[: seeds.shape[0]], lab, mask)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            axes = (DATA_AXIS, FEATURE_AXIS)
            grads = jax.lax.pmean(grads, axes)
            loss = jax.lax.pmean(loss, axes)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        hot_spec = P(FEATURE_AXIS, None) if sharded else P()
        fn = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(), P(), hot_spec, P(DATA_AXIS), P(), P()),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )
        return jax.jit(fn)

    # -- API ----------------------------------------------------------------

    def init(self, rng):
        """Initialize params/opt_state from one locally-sampled batch."""
        n = self.sampler.csr_topo.node_count
        m = min(self.local_batch, n)
        padded = np.full(self.local_batch, -1, np.int32)
        padded[:m] = np.arange(m)
        run, caps = self.sampler._compiled(self.local_batch)
        _, _, adjs, _, _, _ = run(
            self.sampler.topo, jnp.asarray(padded), jnp.int32(m), jax.random.PRNGKey(0)
        )
        hot = (
            self.feature.hot.table
            if isinstance(self.feature, ShardedFeature)
            else self.feature.hot
        )
        x = jnp.zeros((caps[-1], self.feature.shape[1]), hot.dtype)
        params = self.model.init({"params": rng}, x, adjs)["params"]
        opt_state = self.tx.init(params)
        return params, opt_state

    def shard_seeds(self, seeds: np.ndarray):
        """Pack a global seed array into per-device valid-prefix blocks,
        padded to (data_size * local_batch,) with -1."""
        seeds = np.asarray(seeds)
        blocks = np.array_split(seeds, self.data_size)
        out = np.full((self.data_size, self.local_batch), -1, np.int32)
        for i, b in enumerate(blocks):
            if len(b) > self.local_batch:
                raise ValueError(
                    f"per-device block {len(b)} exceeds local_batch {self.local_batch}"
                )
            out[i, : len(b)] = b
        return out.reshape(-1)

    def step(self, params, opt_state, seeds, labels, key):
        """One fused step. ``seeds``: global seed array (host). ``labels``:
        full (N,) label array (replicated)."""
        packed = self.shard_seeds(seeds)
        packed = jax.device_put(
            jnp.asarray(packed), NamedSharding(self.mesh, P(DATA_AXIS))
        )
        hot = (
            self.feature.hot.table
            if isinstance(self.feature, ShardedFeature)
            else self.feature.hot
        )
        return self._step(
            params, opt_state, self.sampler.topo, hot, packed, labels, key
        )
