"""Capped-bucket owner routing over a mesh axis — the shared comm core.

Extracted from the PR 1 capped-bucket routed gather (feature/shard.py) so
the two per-hop consumers — the sharded-feature gather and the distributed
neighbor sampler (sampling/dist.py) — drive ONE audited code path:

1. sort my per-device requests by owning shard (stable, so results can be
   unsorted with a gather through the inverse permutation — no scatter);
2. pack destination buckets CAPPED at ``cap`` lanes each and exchange them
   with one ``all_to_all`` over the mesh axis (``F x cap`` lanes per hop
   instead of the exact-safe worst case ``F x L``);
3. serve the received requests locally (the caller's ``serve`` closure) and
   return the answers with a second ``all_to_all``;
4. lanes past their bucket's capacity are DETECTED in-program, never
   silent: they are served exactly through a psum fallback (all_gather the
   <= L-cap overflow requests over the axis, every shard contributes the
   answers it owns, psum hands the full result to every member) gated
   behind a ``lax.cond`` whose predicate is the axis-psum of the overflow
   count — uniform across the participants, so the collective-inside-cond
   is deadlock-free, and a clean batch pays ZERO fallback comm.

Overflow budget (why the ``(L - cap,)`` fallback buffer is exact-safe): at
most ``L`` lanes are valid, and every bucket that overflows still keeps its
first ``cap`` lanes, so the total overflow across all buckets is at most
``L - cap``.

Results are bit-identical between capped and uncapped (``cap >= L``)
routing: capping changes how many lanes each hop carries, never which
answers come back.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.reindex import inverse_permutation_gather

__all__ = ["BucketRoute"]


class BucketRoute:
    """One planned owner-routing of a per-device request vector.

    Call inside ``shard_map``. The plan (owner sort, bucket bounds, overflow
    mask) is computed once; :meth:`exchange` can then route any number of
    request/payload exchanges through the same buckets — the distributed
    sampler uses this to route ids, then per-id sample offsets, without
    re-sorting.

    Args:
      ids: (L,) int request keys; invalid lanes may hold anything (they are
        sanitized to 0 and never routed).
      valid: (L,) bool. Invalid lanes are assigned to a sentinel bucket past
        the real ones, occupy zero bucket capacity, and come back as zeros.
      owner: (L,) int owning-shard index in [0, F) (any value on invalid
        lanes).
      axis: mesh axis name the ``all_to_all``/``psum`` collectives run over.
      num_shards: F, the axis size.
      cap: per-destination bucket capacity. ``None`` or ``>= L`` means
        full-length buckets — the exact-safe uncapped mode; no fallback
        machinery is traced and :attr:`overflow` is a constant 0.
      tape: optional ``obs.MetricsTape`` — the plan's :attr:`overflow`
        count is fed to it as counter ``metric`` (graftscope: routing
        telemetry rides the step's metrics pytree instead of inventing a
        surfacing convention; the metric must be registered on the tape's
        registry).
      metric: tape counter name; defaults to ``obs.ROUTED_OVERFLOW``.
    """

    def __init__(self, ids, valid, owner, *, axis: str, num_shards: int,
                 cap: int | None = None, tape=None, metric: str | None = None):
        F = int(num_shards)
        L = int(ids.shape[0])
        if cap is None or int(cap) >= L:
            cap = L  # full-length buckets ARE the uncapped exact-safe mode
        cap = int(cap)
        if cap < 1:
            raise ValueError(f"cap must be >= 1, got {cap}")
        self.axis = axis
        self.num_shards = F
        self.length = L
        self.cap = cap

        self._valid = valid
        safe = jnp.where(valid, ids, 0)
        # invalid lanes go to a sentinel bucket F past the real ones: they
        # are never routed, eat no bucket capacity, and cannot fake overflow
        owner = jnp.where(valid, jnp.clip(owner, 0, F - 1), F)
        order = jnp.argsort(owner, stable=True)
        self._order = order
        self._sorted_ids = safe[order]
        sorted_owner = owner[order]
        sorted_valid = valid[order]
        bounds = jnp.searchsorted(
            sorted_owner, jnp.arange(F + 1, dtype=sorted_owner.dtype)
        )
        self._start, ends = bounds[:F], bounds[1:]
        self._counts = ends - self._start
        self._owner_c = jnp.clip(sorted_owner, 0, F - 1)
        self._slot = jnp.arange(L, dtype=jnp.int32) - self._start[self._owner_c]

        # overflow bookkeeping (statically absent when cap == L)
        self.ov_budget = L - cap
        if self.ov_budget == 0:
            self._ov_mask = None
            self.overflow = jnp.zeros((), jnp.int32)
        else:
            self._ov_mask = sorted_valid & (self._slot >= cap)
            ov_local = jnp.sum(self._ov_mask.astype(jnp.int32))
            self._ov_local = ov_local
            # axis-psum'd: uniform across the axis group — the fallback
            # cond's deadlock-free predicate, and the count callers surface
            self.overflow = jax.lax.psum(ov_local, axis)
            # compact my overflow lanes to the static budget (overflow lanes
            # first in sorted order: False < True, stable)
            self._ov_take = jnp.argsort(~self._ov_mask, stable=True)[
                : self.ov_budget
            ]
            self._ov_rank = jnp.cumsum(self._ov_mask.astype(jnp.int32)) - 1
        # the routed request ids, cached after the first exchange: a second
        # exchange through the same plan (the sampler routes ids for the
        # degree hop, then offsets for the neighbor hop) skips re-sending
        # them. Plans live and die inside one traced body, so caching the
        # traced value is safe.
        self._recv_ids = None
        if tape is not None:
            from ..obs.registry import ROUTED_OVERFLOW

            # the psum'd overflow is uniform across the axis group, so the
            # tape value needs no further feature-axis reduction
            tape.add(metric or ROUTED_OVERFLOW, self.overflow)

    # -- internals ----------------------------------------------------------

    def _bucketize(self, sorted_vals, fill):
        """(L, ...) sorted per-lane values -> (F, cap, ...) send buckets:
        the first ``cap`` lanes per destination, ``fill`` elsewhere."""
        F, cap, L = self.num_shards, self.cap, self.length
        j = jnp.arange(cap, dtype=jnp.int32)[None, :]
        pos = jnp.clip(self._start[:, None] + j, 0, L - 1)
        live = j < jnp.minimum(self._counts, cap)[:, None]
        vals = sorted_vals[pos]  # (F, cap, ...)
        live = live.reshape(live.shape + (1,) * (vals.ndim - 2))
        return jnp.where(live, vals, fill)

    def _a2a(self, x):
        """Exchange (F, cap, ...) buckets: bucket f goes to shard f; the
        result's leading axis indexes the SENDING shard."""
        out = jax.lax.all_to_all(
            x, self.axis, split_axis=0, concat_axis=0, tiled=False
        )
        return out.reshape(x.shape)

    def _compact_overflow(self, sorted_vals, fill):
        """(L, ...) sorted values -> (ov_budget, ...) overflow lanes first,
        ``fill`` past the live count."""
        take = sorted_vals[self._ov_take]
        live = jnp.arange(self.ov_budget, dtype=jnp.int32) < self._ov_local
        live = live.reshape(live.shape + (1,) * (take.ndim - 1))
        return jnp.where(live, take, fill)

    # -- API ----------------------------------------------------------------

    def exchange(self, serve, payload=None):
        """Route the planned ids (and optional per-lane ``payload``) to
        their owners, serve, and return the per-lane answers in original
        lane order (zeros on invalid lanes).

        ``serve(ids[, payload])`` receives flat ``(n,)`` global ids (-1 on
        dead lanes) plus the matching payload slice and must return
        ``(n, ...)`` answers that are ZERO for lanes it does not own and
        for ``ids < 0`` — the ownership masking is what makes the psum
        fallback exact, and it is harmless on the main hop (routing already
        guarantees ownership there).
        """
        F, cap, L = self.num_shards, self.cap, self.length
        if self._recv_ids is None:
            self._recv_ids = self._a2a(
                self._bucketize(self._sorted_ids, fill=-1)
            )
        recv_ids = self._recv_ids
        if payload is not None:
            sorted_payload = payload[self._order]
            recv_payload = self._a2a(self._bucketize(sorted_payload, fill=0))
            served = serve(
                recv_ids.reshape(-1),
                recv_payload.reshape((F * cap,) + recv_payload.shape[2:]),
            )
        else:
            served = serve(recv_ids.reshape(-1))
        served = served.reshape((F, cap) + served.shape[1:])
        back = self._a2a(served)
        main = back[self._owner_c, jnp.clip(self._slot, 0, cap - 1)]

        if self.ov_budget == 0:
            answered = main
        else:
            L_ov = self.ov_budget
            ov_ids = self._compact_overflow(self._sorted_ids, fill=-1)
            ov_payload = (
                None if payload is None
                else self._compact_overflow(sorted_payload, fill=0)
            )
            trailing = main.shape[1:]
            dtype = main.dtype
            my = jax.lax.axis_index(self.axis)

            def _fallback(args):
                # psum fallback: everyone sees everyone's overflow requests
                # (cheap — id/payload lanes, no answers), each shard
                # contributes the answers it owns, the psum hands every
                # member the full result and it keeps its own slice
                ids_, pay_ = args
                allov = jax.lax.all_gather(
                    ids_, self.axis, tiled=False
                ).reshape(F, L_ov)
                if pay_ is None:
                    part = serve(allov.reshape(-1))
                else:
                    allpay = jax.lax.all_gather(
                        pay_, self.axis, tiled=False
                    ).reshape((F, L_ov) + pay_.shape[1:])
                    part = serve(
                        allov.reshape(-1),
                        allpay.reshape((F * L_ov,) + pay_.shape[1:]),
                    )
                part = part.reshape((F, L_ov) + trailing)
                return jax.lax.psum(part, self.axis)[my]

            def _no_overflow(args):
                return jnp.zeros((L_ov,) + trailing, dtype)

            ov_rows = jax.lax.cond(
                self.overflow > 0, _fallback, _no_overflow,
                (ov_ids, ov_payload),
            )
            mask = self._ov_mask.reshape(
                self._ov_mask.shape + (1,) * (main.ndim - 1)
            )
            answered = jnp.where(
                mask, ov_rows[jnp.clip(self._ov_rank, 0, L_ov - 1)], main
            )

        out = answered[inverse_permutation_gather(self._order)]
        vmask = self._valid.reshape(self._valid.shape + (1,) * (out.ndim - 1))
        return jnp.where(vmask, out, 0)
