"""Training-step factories.

The reference leaves the training loop to user scripts (PyG model + DDP +
NCCL allreduce, examples/multi_gpu/pyg/ogb-products/
dist_sampling_ogb_products_quiver.py:82-136). quiver-tpu ships the loop as a
library: a jitted step combining feature lookup, model forward/backward, and
optimizer update. Data parallelism is expressed with shardings on the same
step (see parallel/mesh.py) — gradient psum over ICI replaces the DDP
allreduce, inserted by XLA from the sharding annotations.

Label convention: only the first ``batch_size`` rows of ``n_id`` are labeled
seeds (reference ``n_id[:batch_size]``, dist_sampling_ogb_products_quiver.py:115);
padding rows get zero loss weight.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

__all__ = ["make_train_step", "make_eval_step", "empty_adjs", "init_model"]


def init_model(model, rng, x, adjs):
    variables = model.init({"params": rng}, x, adjs)
    return variables["params"]


def empty_adjs(sizes, batch: int, node_count: int | None = None):
    """Deepest-first all-invalid Adj records with the sampler's static
    shapes — parameter initialization needs only shapes, so
    ``init_model(model, rng, zeros((caps[-1], F)), empty_adjs(...))``
    builds params without constructing a sampler or drawing a sample
    (the DistributedTrainer's init path). Caps follow the sampler's
    worst-case growth plan: ``prev * (fanout + 1)`` clamped at
    ``node_count``, rounded up to 8."""
    from ..sampling.sampler import Adj, _round_up

    adjs, prev = [], int(batch)
    for k in sizes:
        k = int(k)
        cap = prev * (k + 1)
        if node_count is not None:
            cap = max(min(cap, int(node_count)), prev)
        cap = _round_up(cap, 8)
        ei = jnp.full((2, prev * k), -1, jnp.int32)
        adjs.append(Adj(ei, None, (cap, prev), fanout=k))
        prev = cap
    return adjs[::-1]


def cross_entropy_on_seeds(logits, labels, label_mask):
    """Mean NLL over valid seed rows (logits are log-probs)."""
    lab = jnp.clip(labels, 0)
    ll = jnp.take_along_axis(logits, lab[:, None], axis=1)[:, 0]
    w = label_mask.astype(logits.dtype)
    return -(ll * w).sum() / jnp.maximum(w.sum(), 1.0)


def make_train_step(model, tx: optax.GradientTransformation) -> Callable:
    """Build a jit-ready SGD step: (params, opt_state, x, adjs, labels,
    label_mask, rng) -> (params, opt_state, loss).

    Not jitted here so callers can wrap it with their own shardings
    (jax.jit / shard_map); ``jax.jit`` it directly for single-chip use.
    """

    def train_step(params, opt_state, x, adjs, labels, label_mask, rng):
        def loss_fn(p):
            logits = model.apply(
                {"params": p}, x, adjs, train=True, rngs={"dropout": rng}
            )
            return cross_entropy_on_seeds(logits, labels, label_mask)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


def make_eval_step(model) -> Callable:
    """(params, x, adjs, labels, label_mask) -> (num_correct, num_valid)."""

    def eval_step(params, x, adjs, labels, label_mask):
        logits = model.apply({"params": params}, x, adjs, train=False)
        pred = jnp.argmax(logits, axis=-1)
        correct = ((pred == labels) & label_mask).sum()
        return correct, label_mask.sum()

    return eval_step
