"""quiver-serve: low-latency online inference over resident graph state.

The north-star workload is "heavy traffic from millions of users" — an
online *serving* path next to the training loop. The reference's
analogue is its IPC-shared ``Feature``: many frontends, one resident
cache. Here the resident state is richer (device CSR topology, the
three-tier feature store, compiled programs), and the serving stack is
built from three pieces:

* :class:`ServeLadder` — per-bucket AOT-compiled sample/forward
  executables in a power-of-two batch-size ladder; steady state replays
  programs, never recompiles, never re-dispatches Python per request.
* :class:`DeadlineBatcher` — deadline-aware request coalescing with
  bounded-queue backpressure and SLO priority classes (gold/bronze
  per-class deadlines; the full-queue shed policy drops bronze before
  gold), deterministic under an injectable clock.
* :class:`EmbeddingRefresher` — a background lane keeping full-graph
  layer-wise embedding tables fresh across streaming commits (PR 8
  ``VersionMismatchError`` -> ``refresh()`` discipline).

:class:`InferenceServer` composes them, attributes every batch across
six graftscope timeline stages, and lands the ``serve.*`` counters on a
:class:`~quiver_tpu.obs.registry.MetricsRegistry`.

Scale-out rides two more pieces: :class:`AOTExecutableCache` persists
every compiled ladder program (serialized backend executable, fingerprint
-keyed, shared disk cache beside ``QUIVER_ELECTION_CACHE``) so a replica
— even in a fresh process — warms by *deserializing* instead of
compiling; :class:`ServingFleet` runs N replicas over one shared
store/controller/cache with least-depth routing and fleet-level
admission failover.
"""

from .aot import AOTExecutableCache, program_fingerprint
from .coalesce import (
    PRIORITIES,
    DeadlineBatcher,
    ServeQueueFull,
    ServeRequest,
    ladder_buckets,
)
from .fleet import ServingFleet
from .ladder import ServeLadder
from .refresh import EmbeddingRefresher
from .server import InferenceServer

__all__ = [
    "AOTExecutableCache",
    "DeadlineBatcher",
    "EmbeddingRefresher",
    "InferenceServer",
    "PRIORITIES",
    "ServeLadder",
    "ServeQueueFull",
    "ServeRequest",
    "ServingFleet",
    "ladder_buckets",
    "program_fingerprint",
]
