"""quiver-serve: low-latency online inference over resident graph state.

The north-star workload is "heavy traffic from millions of users" — an
online *serving* path next to the training loop. The reference's
analogue is its IPC-shared ``Feature``: many frontends, one resident
cache. Here the resident state is richer (device CSR topology, the
three-tier feature store, compiled programs), and the serving stack is
built from three pieces:

* :class:`ServeLadder` — per-bucket AOT-compiled sample/forward
  executables in a power-of-two batch-size ladder; steady state replays
  programs, never recompiles, never re-dispatches Python per request.
* :class:`DeadlineBatcher` — deadline-aware request coalescing with
  bounded-queue backpressure, deterministic under an injectable clock.
* :class:`EmbeddingRefresher` — a background lane keeping full-graph
  layer-wise embedding tables fresh across streaming commits (PR 8
  ``VersionMismatchError`` -> ``refresh()`` discipline).

:class:`InferenceServer` composes them, attributes every batch across
six graftscope timeline stages, and lands the ``serve.*`` counters on a
:class:`~quiver_tpu.obs.registry.MetricsRegistry`.
"""

from .coalesce import (
    DeadlineBatcher,
    ServeQueueFull,
    ServeRequest,
    ladder_buckets,
)
from .ladder import ServeLadder
from .refresh import EmbeddingRefresher
from .server import InferenceServer

__all__ = [
    "DeadlineBatcher",
    "EmbeddingRefresher",
    "InferenceServer",
    "ServeLadder",
    "ServeQueueFull",
    "ServeRequest",
    "ladder_buckets",
]
