"""Persisted AOT serving executables — compile-free replica cold start.

Every serving replica used to pay the full ladder walk
(``jit(...).lower().compile()`` twice per bucket) before it could answer
a single request — the exact setup cost the reference amortizes by
sharing one IPC-resident ``Feature`` across worker processes, and the
whole-program capture/replay pattern PyGraph (arxiv 2503.19779) applies
to CUDA graphs. Here the captured artifact is the *backend-compiled
executable itself*: :func:`jax.experimental.serialize_executable
.serialize` flattens a ``jax.stages.Compiled`` into bytes (the
motivating public API surface is ``jax.export``, but its artifacts hold
StableHLO and recompile on load — only the compiled-executable form
replays with ZERO compiles), and this module persists those bytes in a
shared disk cache so a new replica deserializes instead of compiling.

Cache discipline (shared with the kernel-election cache, ops/election.py):

* **Keying** — a :func:`program_fingerprint` over everything the
  compiled program closed over: the graftaudit-style target id
  (``serve.sample``/``serve.forward``), bucket size, ladder geometry
  (fanouts, lane caps), sampler config (kernel, dedup, weighted), the
  CSR's committed ``version`` *and* the topology leaf avals (a streaming
  commit that changes edge counts changes traced shapes), the
  model/param treedef + avals, feature dtype/width, and the toolchain
  (jax version, platform, device kind, device count — executables are
  backend artifacts). Any mismatch is a miss: fall back to
  compile-and-publish, never to a wrong executable.
* **Tolerant load** — a corrupt/truncated/unpicklable entry degrades to
  a miss with ONE warning per process
  (:func:`~quiver_tpu.ops.election.tolerant_cache_read`); the subsequent
  compile republishes over the bad file.
* **Atomic publish** — temp file + fsync + ``os.replace``
  (:func:`~quiver_tpu.ops.election.atomic_publish_bytes`), so replicas
  warming concurrently from the same directory never read a torn blob.

The entries are pickles (the executable payload rides inside one), so
the cache directory must be trusted — same threat model as the jit
compilation cache. ``QUIVER_AOT_CACHE`` overrides the default location
(beside ``QUIVER_ELECTION_CACHE``), resolved ONCE per process like every
env knob on a potentially-traced path (env-before-first-use).
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle

from ..ops.election import (
    _election_cache_path,
    atomic_publish_bytes,
    tolerant_cache_read,
)
from ..utils.trace import get_logger, warn_once

__all__ = ["AOTExecutableCache", "program_fingerprint"]

_BLOB_FORMAT = 1

_AOT_CACHE_DIR: str | None = None


def _aot_cache_dir() -> str:
    """Default cache directory (``QUIVER_AOT_CACHE``), resolved ONCE per
    process — beside the kernel-election cache so one knob
    (``QUIVER_ELECTION_CACHE``) relocates the whole persisted-decision
    family. Tests reset ``_AOT_CACHE_DIR`` to re-resolve."""
    global _AOT_CACHE_DIR
    if _AOT_CACHE_DIR is None:
        _AOT_CACHE_DIR = os.environ.get(
            "QUIVER_AOT_CACHE",
            os.path.join(
                os.path.dirname(_election_cache_path()), "aot_executables"
            ),
        )
    return _AOT_CACHE_DIR


def program_fingerprint(components: dict) -> str:
    """Content hash of a program's compile-relevant identity.

    ``components`` must be JSON-serializable (the ladder builds it from
    shapes/dtypes/versions/config scalars); the hash is over the
    canonical (sorted-key, no-whitespace) encoding, so dict ordering
    can't fork fingerprints between replicas.
    """
    canon = json.dumps(components, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode("utf-8")).hexdigest()[:32]


class AOTExecutableCache:
    """Disk cache of serialized backend-compiled serving executables.

    One file per program, named by its :func:`program_fingerprint`; a
    hit deserializes straight to a replayable ``jax.stages.Compiled``
    with zero compilation work. Both directions are fail-safe: ``load``
    never raises (corruption/version-skew = miss + one warning), and a
    failed ``store`` only costs the *next* replica a compile.

    ``hits``/``misses``/``stores``/``rejects`` are process-local
    counters for tests and the fleet benchmark (``rejects`` counts
    unreadable or mismatched entries that fell back to compile).
    """

    def __init__(self, path: str | None = None):
        self.path = str(path) if path is not None else _aot_cache_dir()
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.rejects = 0

    def entry_path(self, fingerprint: str) -> str:
        return os.path.join(self.path, f"{fingerprint}.aotx")

    # -- load ---------------------------------------------------------------

    def load(self, fingerprint: str):
        """The cached executable for ``fingerprint``, or ``None``.

        ``None`` covers every non-hit uniformly — absent entry, corrupt
        or truncated blob, format skew, a payload the current backend
        refuses to load — because the caller's fallback (compile and
        republish) is correct for all of them. Never raises.
        """
        path = self.entry_path(fingerprint)
        blob = tolerant_cache_read(
            path, pickle.load, what="AOT-executable", child="serving.aot"
        )
        if blob is None:
            self.misses += 1
            if os.path.exists(path):
                self.rejects += 1
            return None
        if (not isinstance(blob, dict)
                or blob.get("format") != _BLOB_FORMAT
                or blob.get("fingerprint") != fingerprint):
            # format/fingerprint skew: treat exactly like corruption —
            # the republish after the fallback compile self-heals it
            warn_once(
                f"cache-unreadable:{path}:skew",
                "AOT-executable cache entry %s does not match its "
                "fingerprint/format; recompiling and republishing",
                path, child="serving.aot",
            )
            self.misses += 1
            self.rejects += 1
            return None
        try:
            from jax.experimental.serialize_executable import (
                deserialize_and_load,
            )

            ex = deserialize_and_load(
                blob["payload"], blob["in_tree"], blob["out_tree"]
            )
        except Exception as e:  # noqa: BLE001 — a backend that refuses the
            # payload (driver/runtime skew the fingerprint can't see) must
            # degrade to a compile, not take the replica down
            warn_once(
                f"cache-unreadable:{path}:load",
                "AOT executable %s failed to deserialize (%s: %s); "
                "recompiling and republishing", path, type(e).__name__,
                str(e)[:200], child="serving.aot",
            )
            self.misses += 1
            self.rejects += 1
            return None
        self.hits += 1
        return ex

    # -- store --------------------------------------------------------------

    def store(self, fingerprint: str, compiled,
              components: dict | None = None) -> bool:
        """Serialize ``compiled`` and atomically publish it under
        ``fingerprint``; True on publish. Fail-safe: a backend whose
        executables don't serialize, or an unwritable cache directory,
        logs once and returns False — the replica serves from its
        in-memory executable either way."""
        try:
            from jax.experimental.serialize_executable import serialize

            payload, in_tree, out_tree = serialize(compiled)
            blob = pickle.dumps({
                "format": _BLOB_FORMAT,
                "fingerprint": fingerprint,
                "components": components,
                "payload": payload,
                "in_tree": in_tree,
                "out_tree": out_tree,
            })
        except Exception as e:  # noqa: BLE001 — serialization support is
            # backend-dependent; its absence must not fail the serve path
            warn_once(
                f"aot-store:{self.path}:serialize",
                "AOT executable serialization unavailable (%s: %s); "
                "replicas will compile instead of warming from %s",
                type(e).__name__, str(e)[:200], self.path,
                child="serving.aot",
            )
            return False
        try:
            atomic_publish_bytes(self.entry_path(fingerprint), blob)
        except OSError as e:
            warn_once(
                f"aot-store:{self.path}:write",
                "AOT cache %s unwritable (%s: %s); replicas will compile "
                "instead of warming from it", self.path,
                type(e).__name__, str(e)[:200], child="serving.aot",
            )
            return False
        self.stores += 1
        get_logger("serving.aot").info(
            "published AOT executable %s (%d bytes)", fingerprint, len(blob)
        )
        return True

    # -- introspection ------------------------------------------------------

    def __len__(self) -> int:
        try:
            return sum(1 for n in os.listdir(self.path)
                       if n.endswith(".aotx"))
        except OSError:
            return 0

    def stats(self) -> dict:
        return {"path": self.path, "entries": len(self), "hits": self.hits,
                "misses": self.misses, "stores": self.stores,
                "rejects": self.rejects}
