"""Persistent compiled micro-batch programs for online point queries.

PyGraph (arxiv 2503.19779) quantifies what every serving stack relearns:
at small batch sizes the per-request cost is dominated by dispatch and
(re)compilation, not math — the fix is to compile once and *replay*. The
:class:`ServeLadder` applies that to sampled k-hop GNN inference: for
each power-of-two bucket size ``B`` it AOT-compiles (``jit(...).lower(
...).compile()``) exactly two fixed-shape programs and replays the
executables directly — no jit cache lookup, no retrace, no Python per
request beyond array packing:

* **sample**: a ``lax.scan`` over the ``B`` lanes; each lane runs its own
  single-seed ``multilayer_sample`` under a per-request PRNG key
  ``fold_in(base_key, seq)`` with per-lane frontier caps planned for ONE
  seed. Lanes never share frontier state, so a request's neighborhood is
  a function of ``(node, seq)`` alone — independent of bucket size,
  padding, and co-batched requests. That independence is the bit-parity
  contract: ladder output == the direct single-query oracle, bitwise, at
  every bucket size.
* **forward**: a ``lax.scan`` applying the model per lane over the
  gathered feature block (donated — the (B, cap, F) buffer is the big
  per-batch allocation and is dead after the forward).

The host-side feature gather sits *between* the two programs on purpose:
that is where the three-tier store, the mesh-sharded store, and the
circuit-breaker's :class:`~quiver_tpu.resilience.elastic.DegradedFeature`
wrapper all live, so resilience wiring costs the serving path nothing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..sampling.sampler import Adj, GraphSageSampler, multilayer_sample

__all__ = ["ServeLadder"]


class ServeLadder:
    """Per-bucket AOT-compiled (sample, forward) executable pairs.

    Args:
      sampler: a *replicated* :class:`GraphSageSampler` — the ladder
        replays its device topology, fanouts, dedup and kernel choices.
        The mesh-sharded sampler is rejected: its per-hop collectives
        assume trainer-scale frontiers, not single-seed lanes (serve
        against a replicated topology; a mesh-sharded *feature* store is
        fully supported via the host gather stage).
      model: the trained module; ``model.apply`` must accept
        ``(x, adjs, train=False)`` and return per-seed log-probs.
      feature_dim: row width of the feature store (static forward shape).
      row_dtype: dtype the gather stage produces (the store's served row
        dtype — float32 for dequantized int8, bf16 for bf16 stores).
      lane_caps: per-layer frontier caps for ONE seed; defaults to the
        sampler's worst-case single-seed plan (tight for modest fanouts).
      on_compile: callback invoked once per program build — the server
        feeds ``serve.recompiles`` from it.
      aot_cache: optional :class:`~quiver_tpu.serving.aot
        .AOTExecutableCache`. When set, every program build consults the
        cache first (a hit deserializes the backend executable — ZERO
        compiles, ``on_compile`` not invoked) and every compile publishes
        its executable for the next replica. Keyed by
        :meth:`fingerprint`; any mismatch (new CSR commit, different
        toolchain, different geometry) falls back to compile-and-publish.
      on_cache_load: callback invoked once per cache-served program — the
        server feeds ``serve.aot_loads`` from it.
    """

    def __init__(self, sampler: GraphSageSampler, model, feature_dim: int,
                 row_dtype=jnp.float32, lane_caps=None, on_compile=None,
                 aot_cache=None, on_cache_load=None):
        if getattr(sampler, "topo_sharding", "replicated") != "replicated":
            raise NotImplementedError(
                "ServeLadder requires a replicated-topology sampler; the "
                "mesh-sharded DistGraphSageSampler's collective hops are "
                "planned for trainer-scale frontiers, not single-seed "
                "serving lanes (shard the FEATURE store instead — the "
                "host gather stage serves ShardedFeature unchanged)"
            )
        self.sampler = sampler
        self.model = model
        self.feature_dim = int(feature_dim)
        self.row_dtype = jnp.dtype(row_dtype)
        caps = tuple(lane_caps) if lane_caps is not None else (
            sampler._worst_caps(1)
        )
        if len(caps) != len(sampler.sizes):
            raise ValueError(
                f"lane_caps needs one entry per layer ({len(sampler.sizes)}), "
                f"got {caps}"
            )
        self.lane_caps = tuple(int(c) for c in caps)
        self.sizes = tuple(sampler.sizes)
        self._on_compile = on_compile
        # static Adj metadata per layer, sample order: layer l maps a
        # frontier of width src_w[l] onto dst_w[l] targets (dst_w[0] = 1,
        # the seed lane)
        widths = (1,) + self.lane_caps[:-1]
        self._adj_meta = tuple(
            (self.lane_caps[l], widths[l], self.sizes[l])
            for l in range(len(self.sizes))
        )
        self.aot_cache = aot_cache
        self._on_cache_load = on_cache_load
        self.compiles = 0
        self.cache_loads = 0
        self._sample_exec: dict[int, object] = {}
        self._forward_exec: dict[int, object] = {}
        self._params_struct = None

    # -- per-lane bodies (shared by every bucket AND the parity oracle) ------

    def _lane_sample(self, topo, seed, nvalid, seq, base_key):
        """One request's k-hop sample: seed (), nvalid (), seq () ->
        (n_id (cap_last,), edge_index per layer deepest-first, overflow)."""
        key = jax.random.fold_in(base_key, seq)
        s = self.sampler
        n_id, _n_count, adjs, overflow, _ec, _fc = multilayer_sample(
            topo, seed[None] if seed.ndim == 0 else seed, nvalid, key,
            self.sizes, self.lane_caps, weighted=s.weighted, kernel=s.kernel,
            with_eid=False, dedup=s.dedup,
        )
        return n_id, tuple(a.edge_index for a in adjs), overflow

    def _lane_forward(self, x, edge_indices, params):
        """One request's model forward: x (cap_last, F) + deepest-first
        edge_index arrays -> (num_classes,) log-probs for the seed lane."""
        adjs = [
            Adj(ei, None, (cap, dst), fanout=k)
            for ei, (cap, dst, k) in zip(
                edge_indices, reversed(self._adj_meta)
            )
        ]
        logits = self.model.apply({"params": params}, x, adjs, train=False)
        return logits[0]

    # -- bucket programs -----------------------------------------------------

    def trace_sample(self, bucket: int):
        """AOT-trace one bucket's sample program (no compile, no device
        work) — the shared front half of :meth:`_build_sample`, also the
        artifact graftaudit (``tools/audit``) walks."""
        def run(topo, seeds, nvalid, seqs, base_key):
            def lane(_, xs):
                seed, nv, seq = xs
                return _, self._lane_sample(topo, seed, nv, seq, base_key)

            _, out = jax.lax.scan(lane, 0, (seeds, nvalid, seqs))
            return out

        i32 = jnp.int32
        shp = jax.ShapeDtypeStruct((bucket,), i32)
        key = jax.ShapeDtypeStruct(
            jnp.shape(self.sampler._key), jnp.asarray(self.sampler._key).dtype
        )
        return jax.jit(run).trace(self.sampler.topo, shp, shp, shp, key)

    def trace_forward(self, bucket: int):
        """AOT-trace one bucket's forward program against the bound
        parameter structure. The gathered feature block is deliberately
        NOT donated: ``(bucket, lane_cap, F)`` rows can never alias the
        ``(bucket, classes)`` logits, so a ``donate_argnums=0`` here is an
        unusable donation — pure warning noise at every bucket compile and
        a standing invitation to believe memory is being saved when none
        is (graftaudit's donation-audit rule flags exactly this)."""
        def run(x, edge_indices, params):
            def lane(_, xs):
                xb, eis = xs
                return _, self._lane_forward(xb, eis, params)

            _, out = jax.lax.scan(lane, 0, (x, edge_indices))
            return out

        x = jax.ShapeDtypeStruct(
            (bucket, self.lane_caps[-1], self.feature_dim), self.row_dtype
        )
        eis = tuple(
            jax.ShapeDtypeStruct((bucket, 2, dst * k), jnp.int32)
            for (_cap, dst, k) in reversed(self._adj_meta)
        )
        params = self._params_struct
        if params is None:
            raise RuntimeError("call bind_params() before compiling forward")
        return jax.jit(run).trace(x, eis, params)

    # -- persisted-executable fingerprint ------------------------------------

    @staticmethod
    def _avals(tree) -> list:
        out = []
        for x in jax.tree_util.tree_leaves(tree):
            # leaves are arrays OR ShapeDtypeStructs (the bound params
            # struct) — both carry .shape/.dtype
            a = x if hasattr(x, "dtype") else jnp.asarray(x)
            out.append([list(map(int, a.shape)), str(a.dtype)])
        return out

    def fingerprint_components(self, kind: str, bucket: int) -> dict:
        """Everything the ``(kind, bucket)`` program's compiled artifact
        closed over, as a JSON-able dict (see :func:`~quiver_tpu.serving
        .aot.program_fingerprint`). The CSR committed ``version`` AND the
        topology leaf avals are both in the key: a streaming commit
        always forks the fingerprint (refresh re-checks the cache instead
        of trusting a pre-commit executable), and shape-changing commits
        are caught even if versions were ever reused."""
        s = self.sampler
        dev = jax.devices()[0]
        comp = {
            "target": f"serve.{kind}",  # graftaudit-style target id
            "bucket": int(bucket),
            "sizes": list(self.sizes),
            "lane_caps": list(self.lane_caps),
            "kernel": s.kernel,
            "dedup": bool(s.dedup),
            "weighted": bool(s.weighted),
            "csr_version": int(getattr(s.csr_topo, "version", 0)),
            "topo_avals": self._avals(s.topo),
            "key_aval": self._avals(s._key),
            "jax": jax.__version__,
            "platform": dev.platform,
            "device_kind": str(dev.device_kind),
            "n_devices": int(jax.device_count()),
        }
        if kind == "forward":
            if self._params_struct is None:
                raise RuntimeError(
                    "call bind_params() before fingerprinting forward"
                )
            comp["model"] = f"{type(self.model).__name__}:{self.model!r}"
            comp["params_treedef"] = str(
                jax.tree_util.tree_structure(self._params_struct)
            )
            comp["params_avals"] = self._avals(self._params_struct)
            comp["feature_dim"] = self.feature_dim
            comp["row_dtype"] = str(self.row_dtype)
        return comp

    def fingerprint(self, kind: str, bucket: int) -> str:
        from .aot import program_fingerprint

        return program_fingerprint(self.fingerprint_components(kind, bucket))

    # -- program builds (cache-first when an AOT cache is attached) ----------

    def _build(self, kind: str, bucket: int, trace_fn):
        fp = None
        if self.aot_cache is not None:
            fp = self.fingerprint(kind, bucket)
            ex = self.aot_cache.load(fp)
            if ex is not None:
                self.cache_loads += 1
                if self._on_cache_load is not None:
                    self._on_cache_load()
                return ex
        compiled = trace_fn(bucket).lower().compile()
        self._note_compile()
        if self.aot_cache is not None:
            self.aot_cache.store(
                fp, compiled, self.fingerprint_components(kind, bucket)
            )
        return compiled

    def _build_sample(self, bucket: int):
        return self._build("sample", bucket, self.trace_sample)

    def _build_forward(self, bucket: int):
        return self._build("forward", bucket, self.trace_forward)

    def _note_compile(self):
        self.compiles += 1
        if self._on_compile is not None:
            self._on_compile()

    def bind_params(self, params) -> None:
        """Record the parameter tree's structure/shapes (forward programs
        lower against it; the concrete tree is passed per call)."""
        self._params_struct = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(jnp.shape(a), jnp.asarray(a).dtype),
            params,
        )

    # -- replay --------------------------------------------------------------

    def sample_exec(self, bucket: int):
        ex = self._sample_exec.get(bucket)
        if ex is None:
            ex = self._sample_exec[bucket] = self._build_sample(bucket)
        return ex

    def forward_exec(self, bucket: int):
        ex = self._forward_exec.get(bucket)
        if ex is None:
            ex = self._forward_exec[bucket] = self._build_forward(bucket)
        return ex

    def warmup(self, buckets) -> int:
        """Compile every bucket's program pair up front; returns the
        number of compilations performed. After this, steady-state serving
        replays executables only (``serve.recompiles`` stays flat)."""
        before = self.compiles
        for b in buckets:
            self.sample_exec(int(b))
            self.forward_exec(int(b))
        return self.compiles - before

    def warm_from_cache(self, buckets) -> dict:
        """Warm every bucket's program pair, deserializing from the
        attached :class:`~quiver_tpu.serving.aot.AOTExecutableCache`
        wherever the fingerprint matches and compiling (then publishing)
        only the rest. Returns ``{"loaded": n, "compiled": m}`` — a
        replica warming from a populated cache reports ``compiled == 0``
        and its replayed executables answer bitwise-identically to a
        compile-from-scratch replica (same program, same backend
        artifact)."""
        before_c, before_l = self.compiles, self.cache_loads
        for b in buckets:
            self.sample_exec(int(b))
            self.forward_exec(int(b))
        return {"loaded": self.cache_loads - before_l,
                "compiled": self.compiles - before_c}

    # -- parity oracle -------------------------------------------------------

    @functools.cached_property
    def _oracle_sample_jit(self):
        return jax.jit(
            lambda topo, seed, nvalid, seq, base_key: self._lane_sample(
                topo, seed, nvalid, seq, base_key
            )
        )

    @functools.cached_property
    def _oracle_forward_jit(self):
        return jax.jit(
            lambda x, eis, params: self._lane_forward(x, eis, params)
        )

    def oracle_sample(self, topo, node: int, seq: int, base_key):
        """Direct (ladder-free) single-query sample at the same key —
        the reference half of the bit-parity differential."""
        return self._oracle_sample_jit(
            topo, jnp.int32(node), jnp.int32(1), jnp.int32(seq), base_key
        )

    def oracle_forward(self, x, edge_indices, params):
        return self._oracle_forward_jit(x, edge_indices, params)
