"""Background embedding-refresh lane for the serving path.

Point queries answered by sampled k-hop forward are fresh by
construction — they read the live topology and store. A second class of
serving reads wants *precomputed* embeddings: the full-graph layer-wise
tables that ``models/inference.py`` produces (the reference's
``model.inference`` path — each layer computed once over ALL nodes, far
cheaper per node than sampled forward at high query rates).

A precomputed table is a *placement* in the PR 8 sense: it captures the
host CSR at one committed version, and a ``StreamingGraph.commit()``
silently invalidates it. :class:`EmbeddingRefresher` applies the
streaming discipline to that table: lookups raise
:class:`~quiver_tpu.core.topology.VersionMismatchError` the moment the
committed version drifts from the table's, :meth:`refresh` recomputes
(layer-wise, whole graph) and atomically publishes table+version
together, and :meth:`start` runs that loop on a background thread so the
serving thread never blocks on a rebuild — it serves sampled answers (or
stale-raises) while the lane catches up.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..core.topology import VersionMismatchError
from ..models.inference import sage_layerwise_inference

__all__ = ["EmbeddingRefresher"]


class EmbeddingRefresher:
    """Versioned full-graph embedding table with a background refresh loop.

    Args:
      model / params: the trained module and weights (``infer_fn``
        consumes them).
      csr_topo: the HOST CSR the streaming layer mutates — its committed
        ``version`` is the staleness authority.
      features: (N, F) input features, or a zero-arg callable returning
        them — pass a callable bound to the live feature store so a
        commit's row updates reach the next refresh.
      infer_fn: layer-wise inference entry point
        (default :func:`sage_layerwise_inference`; any of the
        ``models/inference.py`` family fits).
      chunk / mode: forwarded to ``infer_fn``.
    """

    def __init__(self, model, params, csr_topo, features, *,
                 infer_fn=None, chunk: int = 1 << 21, mode: str = "HBM",
                 tracer=None):
        self.model = model
        self.params = params
        self.csr_topo = csr_topo
        self._features = features
        # grafttrace seam: each recompute lands a serve.refresh span
        # (subsystem "serve") tagged with the version it published
        self.tracer = tracer
        self.infer_fn = infer_fn if infer_fn is not None else (
            sage_layerwise_inference
        )
        self.chunk = int(chunk)
        self.mode = mode
        self.refreshes = 0
        self._table: np.ndarray | None = None
        self._table_version: int | None = None
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    def _features_now(self) -> np.ndarray:
        f = self._features
        return np.asarray(f() if callable(f) else f)

    # -- refresh seam --------------------------------------------------------

    def refresh(self) -> int:
        """Recompute the whole-graph table from the CURRENT committed
        state and publish table+version atomically; returns the version
        served. Safe to call from the background thread while lookups
        proceed against the old table."""
        version = int(getattr(self.csr_topo, "version", 0))
        t0 = (self.tracer.now()
              if self.tracer is not None and self.tracer.enabled else None)
        x = self._features_now()
        logp = self.infer_fn(
            self.model, self.params, self.csr_topo, x,
            chunk=self.chunk, mode=self.mode,
        )
        table = np.asarray(logp)
        with self._lock:
            self._table = table
            self._table_version = version
            self.refreshes += 1
        if t0 is not None:
            self.tracer.record(
                "serve.refresh", t0, self.tracer.now() - t0,
                subsystem="serve", version=version,
            )
        return version

    # -- versioned reads -----------------------------------------------------

    def check_version(self) -> None:
        """Raise :class:`VersionMismatchError` when the table is missing
        or built from a superseded commit — a stale embedding row is a
        silently wrong answer, not a cheap one."""
        with self._lock:
            ver = self._table_version
        current = int(getattr(self.csr_topo, "version", 0))
        if ver is None:
            raise VersionMismatchError(
                "no embedding table published yet; call refresh() (or "
                "start() the background lane) before lookup()"
            )
        if current != ver:
            raise VersionMismatchError(
                f"embedding table built from topology version {ver} but "
                f"the host CSR has committed version {current}; call "
                f"refresh() to recompute"
            )

    @property
    def version(self) -> int | None:
        """The committed version the published table reflects."""
        with self._lock:
            return self._table_version

    def lookup(self, ids) -> np.ndarray:
        """Rows of the published table for ``ids`` — raises
        :class:`VersionMismatchError` instead of serving stale rows."""
        self.check_version()
        with self._lock:
            table = self._table
        return table[np.asarray(ids)]

    # -- background lane -----------------------------------------------------

    def start(self, interval_s: float = 1.0) -> threading.Thread:
        """Run the refresh loop on a daemon thread: poll the committed
        version every ``interval_s`` and recompute when it drifts (the
        first iteration publishes the initial table)."""
        if self._thread is not None:
            raise RuntimeError("refresh lane already running; stop() first")
        self._stop.clear()
        t = threading.Thread(
            target=self._loop, args=(float(interval_s),),
            name="embedding-refresh", daemon=True,
        )
        self._thread = t
        t.start()
        return t

    def _loop(self, interval_s: float) -> None:
        while not self._stop.is_set():
            try:
                self.check_version()
            except VersionMismatchError:
                self.refresh()
            self._stop.wait(interval_s)

    def stop(self) -> None:
        """Stop and join the background lane (idempotent)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None

    def __enter__(self) -> "EmbeddingRefresher":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
