"""Serving fleet scale-out: N replicas, one resident state, zero-compile joins.

The reference scales serving by *sharing*, not copying: one IPC-resident
``Feature`` (shared CUDA tensors) behind many frontend processes, so a
new worker attaches to existing state instead of rebuilding it. The TPU
analogue here shares along both axes that matter:

* **data** — every :class:`~quiver_tpu.serving.server.InferenceServer`
  replica serves the SAME sampler topology, feature store (plain,
  sharded, or breaker-wrapped) and :class:`~quiver_tpu.control
  .CacheController` sketch, so fleet-wide serve traffic feeds one
  re-tiering decision stream;
* **programs** — every replica warms from the SAME
  :class:`~quiver_tpu.serving.aot.AOTExecutableCache`: the first replica
  compiles each ladder program once and publishes the serialized backend
  executable; each subsequent replica (including one in a *fresh
  process*) deserializes and replays it, joining the fleet with ZERO
  compiles and bitwise-identical responses for the same ``(node, seq)``
  stream (all replicas fold the same base seed).

Routing is least-queue-depth with full-queue failover, and admission
control is SLO-class aware per replica (gold/bronze per-class deadlines;
the shed policy under :class:`~quiver_tpu.serving.coalesce
.ServeQueueFull` drops bronze before gold — see ``serving/coalesce.py``).
"""

from __future__ import annotations

import time

import numpy as np

from ..obs.endpoint import TelemetryEndpoint
from ..obs.tracing import Tracer
from .aot import AOTExecutableCache
from .coalesce import PRIORITIES, ServeQueueFull, ServeRequest
from .server import InferenceServer

__all__ = ["ServingFleet"]


class ServingFleet:
    """N :class:`InferenceServer` replicas over one shared resident state.

    Args:
      sampler / model / params / feature: the shared serving state (see
        :class:`InferenceServer`); every replica serves the same store
        and topology.
      replicas: initial fleet size (``add_replica`` grows it later —
        e.g. mid-traffic, the chaos ``scale-out`` drill).
      aot_cache: the shared persisted-executable cache every replica
        warms from and publishes to — an :class:`AOTExecutableCache`, a
        directory path, or ``True`` (default) for the default location.
        ``None`` disables persistence (every replica compiles).
      controller: optional shared :class:`~quiver_tpu.control
        .CacheController`; all replicas feed one sketch.
      seed: base PRNG seed shared by ALL replicas, so a response is a
        function of ``(node, seq)`` alone — any replica answers any
        request identically, which is what makes least-depth routing
        transparent and the scale-out parity drill bitwise.
      warm: warm each constructed replica from the cache immediately
        (cold-start timings land in :attr:`cold_starts`).
      clock: injectable clock handed to every replica's batcher.
      tracer: optional shared grafttrace :class:`Tracer` handed to every
        replica — the fleet opens ONE trace per submitted request before
        routing, so a failover request's spans on both the rejecting and
        the accepting replica share a single trace id. Default: a
        disabled tracer.
      recorder: optional shared :class:`~quiver_tpu.obs.recorder
        .FlightRecorder` handed to every replica (shed-burst / breaker
        triggers carry the replica index).
      **server_kwargs: forwarded to every :class:`InferenceServer`
        (``max_batch``, ``buckets``, ``class_deadlines``, ``max_queue``,
        ``degraded``, ...).
    """

    def __init__(self, sampler, model, params, feature, *,
                 replicas: int = 1, aot_cache=True, controller=None,
                 seed: int = 0, warm: bool = True, clock=time.monotonic,
                 tracer: Tracer | None = None, recorder=None,
                 **server_kwargs):
        if aot_cache is not None and not isinstance(aot_cache,
                                                    AOTExecutableCache):
            aot_cache = AOTExecutableCache(
                None if aot_cache is True else aot_cache
            )
        self.sampler = sampler
        self.model = model
        self.params = params
        self.feature = feature
        self.aot_cache = aot_cache
        self.controller = controller
        self.seed = int(seed)
        self.clock = clock
        self.tracer = tracer if tracer is not None else Tracer(enabled=False)
        self.recorder = recorder
        self._server_kwargs = dict(server_kwargs)
        self.servers: list[InferenceServer] = []
        #: per-replica join records: ``{"seconds", "loaded", "compiled"}``
        #: — the cold-start-to-ready ledger the fleet benchmark reports
        #: (cache-cold joins show ``compiled > 0``, cache-warm joins
        #: ``compiled == 0``).
        self.cold_starts: list[dict] = []
        for _ in range(int(replicas)):
            self.add_replica(warm=warm)

    # -- membership ----------------------------------------------------------

    def add_replica(self, warm: bool = True) -> InferenceServer:
        """Construct one replica against the shared state and (by
        default) warm it from the shared AOT cache. Against a populated
        cache the join performs zero compiles — the scale-out latency is
        deserialization, not compilation."""
        t0 = time.perf_counter()
        srv = InferenceServer(
            self.sampler, self.model, self.params, self.feature,
            aot_cache=self.aot_cache, controller=self.controller,
            seed=self.seed, clock=self.clock, tracer=self.tracer,
            recorder=self.recorder, **self._server_kwargs,
        )
        srv.replica_index = len(self.servers)
        ws = {"loaded": 0, "compiled": 0}
        if warm:
            ws = srv.warm_from_cache() if self.aot_cache is not None \
                else {"loaded": 0, "compiled": srv.warmup()}
        self.cold_starts.append(
            {"seconds": time.perf_counter() - t0, **ws}
        )
        self.servers.append(srv)
        return srv

    # -- routing + serving ---------------------------------------------------

    def submit(self, node: int, deadline_s: float | None = None,
               priority: str = "gold") -> ServeRequest:
        """Admit one point query on the least-loaded replica; a replica
        at its bound runs its own shed policy (bronze before gold), and a
        hard rejection fails over to the next replica before propagating
        :class:`ServeQueueFull` — fleet-level admission control."""
        if not self.servers:
            raise RuntimeError("fleet has no replicas; call add_replica()")
        # one trace per request, opened BEFORE routing: every replica a
        # failover touches records its spans under this id
        tid = self.tracer.trace() if self.tracer.enabled else None
        last_err = None
        first = True
        for srv in sorted(self.servers, key=lambda s: s.batcher.depth):
            if tid is not None:
                self.tracer.event(
                    "fleet.route" if first else "fleet.failover",
                    trace=tid, subsystem="fleet",
                    replica=srv.replica_index, node=int(node),
                    depth=srv.batcher.depth,
                )
            first = False
            try:
                return srv.submit(node, deadline_s, priority, trace_id=tid)
            except ServeQueueFull as e:
                last_err = e
        if tid is not None:
            self.tracer.event(
                "fleet.rejected", trace=tid, subsystem="fleet",
                node=int(node),
            )
        raise last_err

    def pump(self, force: bool = False) -> list[ServeRequest]:
        """Serve at most one due batch per replica; returns the completed
        requests across the fleet."""
        done: list[ServeRequest] = []
        for srv in self.servers:
            done.extend(srv.pump(force=force))
        return done

    def serve(self, nodes, deadline_s: float | None = None,
              priority: str = "gold") -> list[ServeRequest]:
        """Closed-loop convenience: admit ``nodes`` across the fleet and
        drain every queue; returns the requests in admission order."""
        reqs = [self.submit(int(n), deadline_s, priority)
                for n in np.asarray(nodes)]
        while any(not r.done for r in reqs):
            self.pump(force=True)
        return reqs

    # -- streaming-mutation versioning --------------------------------------

    def check_version(self) -> None:
        for srv in self.servers:
            srv.check_version()

    def refresh(self, warmup: bool = True) -> "ServingFleet":
        """Re-place and rebuild every replica after a streaming commit.
        The first replica's rebuild compiles the new CSR version's
        programs and publishes them; every later replica's rebuild hits
        the cache — a fleet pays each post-commit compile once, not once
        per replica."""
        for srv in self.servers:
            srv.refresh(warmup=warmup)
        return self

    # -- introspection -------------------------------------------------------

    @property
    def recompiles(self) -> int:
        """Fleet-total ladder compilations."""
        return sum(s.recompiles for s in self.servers)

    @property
    def aot_loads(self) -> int:
        """Fleet-total programs warmed from the persisted cache."""
        return sum(s.aot_loads for s in self.servers)

    def health(self) -> dict:
        """The ``/healthz`` summary: per-replica queue depth, topology
        version, breaker state (when the store is breaker-wrapped)."""
        reps = []
        for srv in self.servers:
            breaker = getattr(srv.feature, "breaker", None)
            reps.append({
                "replica": srv.replica_index,
                "queue_depth": srv.batcher.depth,
                "topology_version": srv._topo_version,
                "breaker": breaker.state if breaker is not None else None,
            })
        return {
            "replicas": len(reps),
            "queue_depth": sum(r["queue_depth"] for r in reps),
            "per_replica": reps,
        }

    def serve_telemetry(self, host: str = "127.0.0.1",
                        port: int = 0) -> TelemetryEndpoint:
        """Start (and return) a live telemetry endpoint over the fleet:
        ``/metrics`` from replica 0's registry, ``/traces`` from the
        shared tracer, ``/healthz`` from :meth:`health`. Off unless
        called; caller stops it (or relies on the daemon thread dying
        with the process)."""
        metrics = self.servers[0].metrics if self.servers else None
        return TelemetryEndpoint(
            metrics=metrics, tracer=self.tracer, health=self.health,
            host=host, port=port,
        ).start()

    def oracle(self, node: int, seq: int) -> np.ndarray:
        """The fleet-wide parity reference: replicas share the base seed,
        so replica 0's direct (ladder-free) answer is THE answer every
        replica must reproduce bitwise for ``(node, seq)``."""
        return self.servers[0].oracle(node, seq)

    def stats(self) -> dict:
        """Fleet-aggregated serve counters (per-class shed/miss summed
        across replicas) plus the per-replica breakdown."""
        per = [s.stats() for s in self.servers]
        return {
            "replicas": len(per),
            "requests": sum(p["requests"] for p in per),
            "deadline_misses": sum(p["deadline_misses"] for p in per),
            "class_deadline_misses": {
                c: sum(p["class_deadline_misses"][c] for p in per)
                for c in PRIORITIES
            },
            "shed": {
                c: sum(p["shed"][c] for p in per) for c in PRIORITIES
            },
            "recompiles": sum(p["recompiles"] for p in per),
            "aot_loads": sum(p["aot_loads"] for p in per),
            "queue_depth": sum(p["queue_depth"] for p in per),
            "cold_starts": list(self.cold_starts),
            "aot_cache": (self.aot_cache.stats()
                          if self.aot_cache is not None else None),
            "per_replica": per,
        }
