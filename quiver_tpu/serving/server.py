"""quiver-serve: the online inference server over resident graph state.

Composes the three serving pieces into one low-latency path:

* :class:`~quiver_tpu.serving.coalesce.DeadlineBatcher` — admission,
  deadline-aware coalescing, bounded-queue backpressure;
* :class:`~quiver_tpu.serving.ladder.ServeLadder` — per-bucket AOT
  compiled sample/forward executables (steady state never recompiles);
* the host feature gather in between — :class:`~quiver_tpu.feature
  .feature.Feature`, mesh-sharded ``ShardedFeature``, or the circuit-
  breaker-wrapped ``DegradedFeature`` all serve it unchanged, so a
  cold-tier outage degrades responses instead of failing them.

Every batch walks six attributed stages — ``queue_wait``/``pad``/
``sample``/``gather``/``forward``/``readback`` — on a graftscope
:class:`~quiver_tpu.obs.timeline.StepTimeline` (P² p50/p95/p99 per
stage), and the serve counters land on a
:class:`~quiver_tpu.obs.registry.MetricsRegistry` under the
``serve.*`` constants.

Staleness follows the PR 8 streaming discipline: the server captures the
host CSR's committed ``version`` when it (re)builds its compiled ladder;
after a ``StreamingGraph.commit()`` every serve path raises
:class:`~quiver_tpu.core.topology.VersionMismatchError` until
:meth:`InferenceServer.refresh` re-places the topology and recompiles —
never a silently pre-commit answer.
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp

from ..core.topology import VersionMismatchError
from ..obs.registry import (
    SERVE_AOT_LOADS,
    SERVE_CLASS_MISSES,
    SERVE_DEADLINE_MISSES,
    SERVE_DEGRADED_LOOKUPS,
    SERVE_RECOMPILES,
    SERVE_REQUESTS,
    SERVE_SHED,
    MetricsRegistry,
)
from ..obs.timeline import StepTimeline
from ..obs.tracing import Tracer
from ..resilience.elastic import DegradedFeature
from .aot import AOTExecutableCache
from .coalesce import PRIORITIES, DeadlineBatcher, ServeRequest, ladder_buckets
from .ladder import ServeLadder

__all__ = ["InferenceServer"]


class _MarkedStage:
    """Context manager pairing one :class:`StepTimeline` stage with a
    grafttrace ``(name, t0, dur)`` mark on the server's tracer clock."""

    __slots__ = ("_server", "_name", "_marks", "_inner", "_t0")

    def __init__(self, server, name, marks):
        self._server = server
        self._name = name
        self._marks = marks
        self._inner = server.timeline.stage(name)

    def __enter__(self):
        self._t0 = self._server.tracer.now()
        return self._inner.__enter__()

    def __exit__(self, exc_type, exc, tb):
        self._marks.append(
            (self._name, self._t0, self._server.tracer.now() - self._t0)
        )
        return self._inner.__exit__(exc_type, exc, tb)


class InferenceServer:
    """Deadline-aware micro-batch serving over a resident sampler+store.

    Args:
      sampler: replicated :class:`~quiver_tpu.sampling.sampler
        .GraphSageSampler` holding the device topology to serve from.
      model: trained module (``apply(x, adjs, train=False)`` log-probs).
      params: trained parameter tree.
      feature: ids->rows store — ``Feature``, ``ShardedFeature``, or any
        ``DegradedFeature``-wrappable host lookup.
      max_batch: top of the power-of-two bucket ladder.
      buckets: explicit ladder override (ascending powers of two).
      default_deadline_s / budget_fraction / max_queue / clock: the
        :class:`DeadlineBatcher` knobs (clock is injectable — tests and
        the open-loop benchmark drive a fake one).
      lane_caps: per-layer single-seed frontier caps (default: the
        sampler's worst-case single-seed plan).
      seed: base PRNG seed; request ``seq`` folds into it, so responses
        are reproducible functions of (node, seq).
      degraded: ``None`` (store failures propagate), or ``"zeros"`` /
        ``"last-good"`` — wrap the store in a circuit-breaker
        :class:`DegradedFeature` so a cold-tier outage serves degraded
        rows instead of failing requests.
      breaker_failures / probe_every: breaker thresholds when wrapping.
      metrics / timeline: external graftscope sinks (private by default).
      controller: optional :class:`~quiver_tpu.control.CacheController`
        to feed serve-path gather frequencies into — every served
        batch's sampled node ids fold into the SAME sketch the training
        loop feeds, so the store can re-tier under serving traffic
        (``controller.end_epoch(store)`` between serving windows, then
        :meth:`refresh` if a repin bumped the version). Attached to the
        underlying store when it is a ``ShardedFeature``.
      class_deadlines: optional per-SLO-class default deadlines for the
        batcher, e.g. ``{"gold": 0.02, "bronze": 0.1}``; the shed policy
        under a full queue drops bronze before gold.
      aot_cache: optional persisted-executable cache — an
        :class:`~quiver_tpu.serving.aot.AOTExecutableCache`, a directory
        path, or ``True`` for the default location. When set, ladder
        program builds consult the cache before compiling and publish
        after compiling; :meth:`warm_from_cache` is the compile-free
        replica cold-start path.
      tracer: optional grafttrace :class:`~quiver_tpu.obs.tracing
        .Tracer` — every admitted request opens (or joins, when the
        fleet routed it) one trace, and the six batch stages land as
        child spans of that trace. Default: a disabled tracer (no
        overhead, bitwise-identical responses).
      recorder: optional :class:`~quiver_tpu.obs.recorder
        .FlightRecorder` — dumps a postmortem bundle on a shed burst
        (``shed_burst`` sheds since the last dump) and, when this server
        wraps its store in a ``DegradedFeature``, on breaker open.
      shed_burst: shed-count threshold for the recorder trigger.
    """

    STAGES = ("queue_wait", "pad", "sample", "gather", "forward", "readback")

    def __init__(self, sampler, model, params, feature, *,
                 max_batch: int = 8, buckets=None,
                 default_deadline_s: float = 0.05,
                 budget_fraction: float = 0.5, max_queue: int = 256,
                 clock=time.monotonic, lane_caps=None, seed: int = 0,
                 degraded: str | None = None, breaker_failures: int = 3,
                 probe_every: int = 8,
                 metrics: MetricsRegistry | None = None,
                 timeline: StepTimeline | None = None,
                 controller=None, class_deadlines: dict | None = None,
                 aot_cache=None, tracer: Tracer | None = None,
                 recorder=None, shed_burst: int = 8):
        self.sampler = sampler
        self.model = model
        self.params = params
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.timeline = timeline if timeline is not None else StepTimeline()
        self.tracer = tracer if tracer is not None else Tracer(enabled=False)
        self.recorder = recorder
        self.replica_index = 0
        self.shed_burst = int(shed_burst)
        self._shed_dumped = 0
        self.clock = clock
        if degraded is not None and not isinstance(feature, DegradedFeature):
            feature = DegradedFeature(
                feature, failures=breaker_failures, probe_every=probe_every,
                fallback=degraded, metrics=self.metrics, recorder=recorder,
            )
        self.feature = feature
        self.controller = controller
        if controller is not None:
            # the underlying store (unwrapping the breaker) is where
            # repin decisions land; plain Feature stores still feed the
            # sketch but have no tiers to move
            store = feature.feature if isinstance(feature, DegradedFeature) \
                else feature
            if hasattr(store, "_controller"):
                controller.attach(store)
        if aot_cache is not None and not isinstance(aot_cache,
                                                    AOTExecutableCache):
            aot_cache = AOTExecutableCache(
                None if aot_cache is True else aot_cache
            )
        self.aot_cache = aot_cache
        self.batcher = DeadlineBatcher(
            buckets=tuple(buckets) if buckets else ladder_buckets(max_batch),
            default_deadline_s=default_deadline_s,
            budget_fraction=budget_fraction,
            max_queue=max_queue, clock=clock,
            class_deadlines=class_deadlines,
        )
        self._base_key = jax.random.PRNGKey(seed)
        self._lane_caps = lane_caps
        self.metrics.counter(
            SERVE_REQUESTS, unit="requests",
            doc="point queries completed by the serving path",
        )
        self.metrics.counter(
            SERVE_DEADLINE_MISSES, unit="requests",
            doc="requests completed after their admission deadline",
        )
        self.metrics.counter(
            SERVE_DEGRADED_LOOKUPS, unit="lookups",
            doc="serve-batch feature gathers satisfied by the circuit "
                "breaker's degraded fallback instead of the real store",
        )
        self.metrics.counter(
            SERVE_RECOMPILES, unit="programs",
            doc="ladder program compilations (0 after warmup = the "
                "steady-state never-recompile contract)",
        )
        self.metrics.counter(
            SERVE_AOT_LOADS, unit="programs",
            doc="ladder programs warmed by deserializing a persisted AOT "
                "executable instead of compiling (a cache-warm replica "
                "reports recompiles == 0)",
        )
        self.metrics.counter(
            SERVE_SHED, shape=(len(PRIORITIES),), unit="requests",
            doc="requests shed at admission under a full queue, by SLO "
                "class (coalesce.PRIORITIES order: gold, bronze)",
        )
        self.metrics.counter(
            SERVE_CLASS_MISSES, shape=(len(PRIORITIES),), unit="requests",
            doc="deadline misses attributed by SLO class "
                "(coalesce.PRIORITIES order: gold, bronze)",
        )
        self._requests_total = 0
        self._misses_total = 0
        self._recompiles_total = 0
        self._aot_loads_total = 0
        self._class_misses = [0] * len(PRIORITIES)
        self._serve_degraded_total = 0
        self._degraded_seen = (
            feature.degraded_total if isinstance(feature, DegradedFeature)
            else 0
        )
        # row dtype/width probe: a single -1 (padding) id returns one
        # zero row of exactly the dtype the store serves (dequantized
        # int8 -> f32, bf16 stores -> bf16) without touching real rows
        probe = np.asarray(self.feature[np.full((1,), -1, np.int32)])
        self._row_dtype = probe.dtype
        self._feature_dim = int(probe.shape[1])
        self._ladder = self._make_ladder()
        self._topo_version = int(getattr(sampler.csr_topo, "version", 0))

    def _make_ladder(self) -> ServeLadder:
        ladder = ServeLadder(
            self.sampler, self.model, self._feature_dim,
            row_dtype=self._row_dtype, lane_caps=self._lane_caps,
            on_compile=self._on_ladder_compile,
            aot_cache=self.aot_cache,
            on_cache_load=self._on_ladder_cache_load,
        )
        ladder.bind_params(self.params)
        return ladder

    def _on_ladder_compile(self) -> None:
        self._recompiles_total += 1
        self.metrics.set(SERVE_RECOMPILES, np.int32(self._recompiles_total))

    def _on_ladder_cache_load(self) -> None:
        self._aot_loads_total += 1
        self.metrics.set(SERVE_AOT_LOADS, np.int32(self._aot_loads_total))

    def _sync_shed(self) -> None:
        shed = [self.batcher.shed_by_class[p] for p in PRIORITIES]
        self.metrics.set(SERVE_SHED, np.asarray(shed, np.int32))
        total = int(sum(shed))
        if self.recorder is not None:
            if total > self._shed_dumped:
                self.recorder.note(
                    "serve.shed", replica=self.replica_index,
                    shed_total=total,
                )
            if total - self._shed_dumped >= self.shed_burst:
                self._shed_dumped = total
                self.recorder.trigger(
                    "shed_burst", stage="queue",
                    replica=self.replica_index, shed_total=total,
                    queue_depth=self.batcher.depth,
                )

    # -- streaming-mutation versioning --------------------------------------

    def check_version(self) -> None:
        """Raise :class:`VersionMismatchError` when the host CSR has
        committed a version the compiled ladder was not built from —
        serving would silently answer from the pre-commit graph. Call
        :meth:`refresh` to re-place and recompile."""
        current = int(getattr(self.sampler.csr_topo, "version", 0))
        if current != self._topo_version:
            raise VersionMismatchError(
                f"serving ladder compiled against topology version "
                f"{self._topo_version} but the host CSR has committed "
                f"version {current}; call refresh() before serving"
            )

    def refresh(self, warmup: bool = True) -> "InferenceServer":
        """Re-place the device topology and rebuild the compiled ladder
        after a streaming commit. ``warmup`` rebuilds the buckets that
        were live before — with an attached AOT cache each rebuild
        RE-CHECKS the cache first (the committed CSR version and topology
        avals are in the fingerprint, so a replica that already compiled
        and published this version's programs hands them over; only a
        genuinely new program compiles, counted in ``serve.recompiles``
        — a mutation epoch pays its compiles at the boundary, not per
        request)."""
        live = sorted(
            set(self._ladder._sample_exec) | set(self._ladder._forward_exec)
        )
        self.sampler.refresh_topology()
        self._ladder = self._make_ladder()
        self._topo_version = int(getattr(self.sampler.csr_topo, "version", 0))
        if warmup and live:
            self._ladder.warmup(live)
        return self

    # -- serving -------------------------------------------------------------

    def submit(self, node: int, deadline_s: float | None = None,
               priority: str = "gold",
               trace_id: str | None = None) -> ServeRequest:
        """Admit one point query (see :meth:`DeadlineBatcher.submit`);
        the shed policy under a full queue drops bronze before gold, and
        shed counts land per class on ``serve.shed_requests``.
        ``trace_id`` joins the request to an existing trace (the fleet's
        routing/failover propagation seam); absent, a fresh trace opens
        per request when tracing is on."""
        try:
            req = self.batcher.submit(node, deadline_s, priority)
        finally:
            self._sync_shed()
        if self.tracer.enabled:
            req.trace_id = (trace_id if trace_id is not None
                            else self.tracer.trace())
            self.tracer.event(
                "serve.enqueue", trace=req.trace_id, subsystem="serve",
                node=int(node), seq=req.seq, priority=priority,
                replica=self.replica_index,
            )
        return req

    def warmup(self, buckets=None) -> int:
        """Pre-compile the ladder (all batcher buckets by default);
        returns the number of program compilations. Steady-state serving
        after warmup replays executables only."""
        self.check_version()
        return self._ladder.warmup(
            tuple(buckets) if buckets else self.batcher.buckets
        )

    def warm_from_cache(self, buckets=None) -> dict:
        """Compile-free cold start: warm the ladder (all batcher buckets
        by default) by deserializing persisted AOT executables wherever
        the fingerprint matches, compiling-and-publishing only the rest.
        Returns ``{"loaded": n, "compiled": m}`` — against a populated
        cache a new replica reports ``compiled == 0`` (``recompiles``
        stays 0) and serves responses bitwise-identical to the replica
        that compiled, for every bucket and padded tail."""
        self.check_version()
        return self._ladder.warm_from_cache(
            tuple(buckets) if buckets else self.batcher.buckets
        )

    def pump(self, force: bool = False) -> list[ServeRequest]:
        """Serve at most one due batch; returns the completed requests
        (empty when nothing is due). ``force`` flushes a partial bucket —
        the closed-loop drain path."""
        self.check_version()
        popped = self.batcher.pop(force=force)
        if popped is None:
            return []
        reqs, bucket = popped
        now = self.clock()
        for r in reqs:
            self.timeline.observe("queue_wait", now - r.t_admit)
        return self._run_batch(reqs, bucket)

    def serve(self, nodes, deadline_s: float | None = None,
              priority: str = "gold") -> list[ServeRequest]:
        """Closed-loop convenience: admit ``nodes`` and drain the queue;
        returns their completed requests in admission order."""
        reqs = [self.submit(int(n), deadline_s, priority)
                for n in np.asarray(nodes)]
        while any(not r.done for r in reqs):
            self.pump(force=True)
        return reqs

    @staticmethod
    def _host_rows(rows):
        # a mesh-sharded store's gather comes back with a multi-device
        # NamedSharding; the ladder executables are AOT-compiled for
        # single-device inputs, so de-shard before feeding forward
        sharding = getattr(rows, "sharding", None)
        if sharding is not None and len(sharding.device_set) > 1:
            return np.asarray(rows)
        return rows

    def _stage(self, name: str, marks):
        """One timed batch stage: always lands on the P² timeline; when
        tracing, also appends a ``(name, t0, dur)`` mark (tracer clock)
        for span attribution to every request in the batch."""
        if marks is None:
            return self.timeline.stage(name)
        return _MarkedStage(self, name, marks)

    def _emit_batch_spans(self, reqs, bucket, marks, t_batch0, t_pop):
        """Per-request trace assembly: one ``serve.request`` root from
        admission to completion, a ``serve.queue_wait`` child from the
        batcher clock, and the five measured batch stages as children
        (shared across co-batched requests — they ran fused)."""
        t_end = self.tracer.now()
        for r in reqs:
            qwait = max(t_pop - r.t_admit, 0.0)
            root = self.tracer.record(
                "serve.request", t_batch0 - qwait,
                (t_end - t_batch0) + qwait, trace=r.trace_id,
                subsystem="serve", node=int(r.node), seq=r.seq,
                priority=r.priority, bucket=bucket,
                replica=self.replica_index, missed=bool(r.missed),
            )
            self.tracer.record(
                "serve.queue_wait", t_batch0 - qwait, qwait,
                trace=r.trace_id, parent=root, subsystem="serve",
            )
            for name, t0, dur in marks:
                self.tracer.record(
                    f"serve.{name}", t0, dur, trace=r.trace_id,
                    parent=root, subsystem="serve", bucket=bucket,
                )

    def _run_batch(self, reqs, bucket: int) -> list[ServeRequest]:
        marks = [] if self.tracer.enabled else None
        t_batch0 = self.tracer.now() if marks is not None else 0.0
        t_pop = self.clock()
        capL = self._ladder.lane_caps[-1]
        with self._stage("pad", marks):
            seeds = np.full(bucket, -1, np.int32)
            nvalid = np.zeros(bucket, np.int32)
            seqs = np.zeros(bucket, np.int32)
            for i, r in enumerate(reqs):
                seeds[i] = r.node
                nvalid[i] = 1
                seqs[i] = r.seq
            seeds_d = jnp.asarray(seeds)
            nvalid_d = jnp.asarray(nvalid)
            seqs_d = jnp.asarray(seqs)
        sample_ex = self._ladder.sample_exec(bucket)
        with self._stage("sample", marks):
            n_ids, eis, overflow = sample_ex(
                self.sampler.topo, seeds_d, nvalid_d, seqs_d, self._base_key
            )
            jax.block_until_ready(n_ids)
        if self.controller is not None:
            # serve-path gather frequencies feed the same sketch the
            # training loop does (padding -1 lanes are filtered there)
            self.controller.observe_serve(np.asarray(n_ids).reshape(-1))
        with self._stage("gather", marks):
            rows = self._host_rows(self.feature[n_ids.reshape(-1)])
            x = jnp.asarray(rows, self._row_dtype).reshape(
                bucket, capL, self._feature_dim
            )
            jax.block_until_ready(x)
        forward_ex = self._ladder.forward_exec(bucket)
        with self._stage("forward", marks):
            out = forward_ex(x, eis, self.params)
            jax.block_until_ready(out)
        with self._stage("readback", marks):
            out_np = np.asarray(out)
            ovf_np = np.asarray(overflow)
        t_done = self.clock()
        misses = 0
        for i, r in enumerate(reqs):
            r.result = out_np[i]
            r.overflow = int(ovf_np[i])
            r.t_done = t_done
            r.missed = t_done > r.deadline_at
            misses += int(r.missed)
            if r.missed:
                self._class_misses[PRIORITIES.index(r.priority)] += 1
        self._requests_total += len(reqs)
        self._misses_total += misses
        self.metrics.set(SERVE_REQUESTS, np.int32(self._requests_total))
        self.metrics.set(SERVE_DEADLINE_MISSES, np.int32(self._misses_total))
        self.metrics.set(
            SERVE_CLASS_MISSES, np.asarray(self._class_misses, np.int32)
        )
        if isinstance(self.feature, DegradedFeature):
            delta = self.feature.degraded_total - self._degraded_seen
            if delta:
                self._degraded_seen = self.feature.degraded_total
                self._serve_degraded_total += delta
                self.metrics.set(
                    SERVE_DEGRADED_LOOKUPS,
                    np.int32(self._serve_degraded_total),
                )
        if marks is not None:
            self._emit_batch_spans(reqs, bucket, marks, t_batch0, t_pop)
        return reqs

    # -- parity oracle -------------------------------------------------------

    def oracle(self, node: int, seq: int) -> np.ndarray:
        """The direct (ladder-free) sampled-inference answer for
        ``(node, seq)`` — single-seed sample at ``fold_in(base_key,
        seq)``, the same host feature gather, a standalone model forward.
        The bit-parity differential asserts ladder == oracle at every
        bucket size and padded tail."""
        self.check_version()
        n_id, eis, _overflow = self._ladder.oracle_sample(
            self.sampler.topo, node, seq, self._base_key
        )
        rows = self._host_rows(self.feature[n_id])
        x = jnp.asarray(rows, self._row_dtype).reshape(
            self._ladder.lane_caps[-1], self._feature_dim
        )
        out = self._ladder.oracle_forward(x, eis, self.params)
        return np.asarray(out)

    # -- introspection -------------------------------------------------------

    @property
    def recompiles(self) -> int:
        """Cumulative ladder compilations (the ``serve.recompiles``
        counter; flat after :meth:`warmup` = steady-state contract)."""
        return self._recompiles_total

    @property
    def aot_loads(self) -> int:
        """Ladder programs warmed from the persisted AOT cache (the
        ``serve.aot_loads`` counter)."""
        return self._aot_loads_total

    def stats(self) -> dict:
        """Host-side serve counters + per-stage latency quantiles."""
        stages = {
            name: st.as_dict()
            for name, st in self.timeline.summary().items()
        }
        return {
            "requests": self._requests_total,
            "deadline_misses": self._misses_total,
            "class_deadline_misses": dict(
                zip(PRIORITIES, self._class_misses)
            ),
            "shed": dict(self.batcher.shed_by_class),
            "degraded_lookups": self._serve_degraded_total,
            "recompiles": self._recompiles_total,
            "aot_loads": self._aot_loads_total,
            "queue_depth": self.batcher.depth,
            "stages": stages,
        }
