"""Deadline-aware request coalescing for the online serving path.

Single-node point queries arrive one at a time; the compiled micro-batch
step (``serving/ladder.py``) wants power-of-two batches. The
:class:`DeadlineBatcher` bridges the two: it admits requests into a
bounded FIFO and releases them as a batch when either (a) enough requests
are pending to fill the largest ladder bucket, or (b) the *oldest*
pending request has spent its configured fraction of its deadline budget
waiting — the classic latency/throughput coalescing knob, here fully
deterministic under an injectable clock so the packing decision sequence
is a pure function of the arrival sequence (tests replay it bitwise).

Backpressure is a bounded queue: ``submit`` raises
:class:`ServeQueueFull` instead of growing without limit — an overloaded
server sheds load at admission, where the caller can still retry or
route elsewhere, not at completion where the work is already sunk.
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np

__all__ = ["DeadlineBatcher", "ServeQueueFull", "ServeRequest"]


class ServeQueueFull(RuntimeError):
    """Admission rejected: the serving queue is at its bound. The caller
    owns the retry/shed decision — an unbounded queue would convert
    overload into unbounded latency for every later request instead."""


@dataclasses.dataclass
class ServeRequest:
    """One admitted point query and (after completion) its outcome.

    ``seq`` is the admission sequence number — it is folded into the
    server's base PRNG key (``fold_in(base_key, seq)``), so a request's
    sampled neighborhood is a function of (node, seq) alone, independent
    of which bucket it lands in and of its co-batched neighbors. That
    independence is what makes ladder-served responses bitwise equal to
    the direct single-query oracle.
    """

    node: int
    seq: int
    t_admit: float
    deadline_s: float
    result: np.ndarray | None = None
    overflow: int = 0
    t_done: float | None = None
    missed: bool | None = None

    @property
    def deadline_at(self) -> float:
        return self.t_admit + self.deadline_s

    @property
    def done(self) -> bool:
        return self.t_done is not None

    def latency_s(self) -> float | None:
        return None if self.t_done is None else self.t_done - self.t_admit


def ladder_buckets(max_batch: int) -> tuple[int, ...]:
    """The power-of-two bucket ladder up to ``max_batch``: (1, 2, 4, ...).

    ``max_batch`` must itself be a power of two — a non-power-of-two top
    bucket would make the padded tail of full batches permanent.
    """
    m = int(max_batch)
    if m < 1 or (m & (m - 1)) != 0:
        raise ValueError(f"max_batch must be a power of two >= 1, got {max_batch}")
    out, b = [], 1
    while b <= m:
        out.append(b)
        b *= 2
    return tuple(out)


class DeadlineBatcher:
    """Bounded FIFO that packs point queries into ladder buckets.

    Args:
      buckets: ascending batch-size ladder (see :func:`ladder_buckets`);
        the last entry is the largest batch a flush releases.
      default_deadline_s: per-request deadline when ``submit`` gives none.
      budget_fraction: fraction of a request's deadline it may spend
        *queued* before its presence forces a flush (the rest of the
        budget is reserved for sample/gather/forward/readback).
      max_queue: admission bound; ``submit`` past it raises
        :class:`ServeQueueFull`.
      clock: injectable monotonic clock — tests drive a fake clock and
        the flush sequence becomes deterministic in the arrival sequence.
    """

    def __init__(self, buckets=(1, 2, 4, 8), default_deadline_s: float = 0.05,
                 budget_fraction: float = 0.5, max_queue: int = 256,
                 clock=time.monotonic):
        buckets = tuple(int(b) for b in buckets)
        if not buckets or list(buckets) != sorted(set(buckets)):
            raise ValueError(f"buckets must be ascending and unique, got {buckets}")
        if any(b < 1 or (b & (b - 1)) != 0 for b in buckets):
            raise ValueError(f"buckets must be powers of two, got {buckets}")
        if not 0.0 < budget_fraction <= 1.0:
            raise ValueError(
                f"budget_fraction must be in (0, 1], got {budget_fraction}"
            )
        if max_queue < buckets[-1]:
            raise ValueError(
                f"max_queue ({max_queue}) must hold at least one full "
                f"top bucket ({buckets[-1]})"
            )
        self.buckets = buckets
        self.default_deadline_s = float(default_deadline_s)
        self.budget_fraction = float(budget_fraction)
        self.max_queue = int(max_queue)
        self.clock = clock
        self._pending: list[ServeRequest] = []
        self._seq = 0
        self._lock = threading.Lock()

    # -- admission -----------------------------------------------------------

    def submit(self, node: int, deadline_s: float | None = None) -> ServeRequest:
        """Admit one point query; raises :class:`ServeQueueFull` at the
        bound. Returns the request handle the caller polls for results."""
        deadline = self.default_deadline_s if deadline_s is None else float(
            deadline_s
        )
        if deadline <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline}")
        now = self.clock()
        with self._lock:
            if len(self._pending) >= self.max_queue:
                raise ServeQueueFull(
                    f"serving queue at bound ({self.max_queue}); shed or "
                    f"retry after a drain"
                )
            req = ServeRequest(int(node), self._seq, now, deadline)
            self._seq += 1
            self._pending.append(req)
        return req

    # -- flush decision ------------------------------------------------------

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def ready(self) -> bool:
        """True when a flush is due: the top bucket would be full, or the
        oldest request has burned its queue-wait fraction of its deadline."""
        now = self.clock()
        with self._lock:
            return self._ready_locked(now)

    def _ready_locked(self, now: float) -> bool:
        if not self._pending:
            return False
        if len(self._pending) >= self.buckets[-1]:
            return True
        oldest = self._pending[0]
        return now >= oldest.t_admit + self.budget_fraction * oldest.deadline_s

    def bucket_for(self, count: int) -> int:
        """Smallest ladder bucket holding ``count`` requests."""
        for b in self.buckets:
            if count <= b:
                return b
        return self.buckets[-1]

    def pop(self, force: bool = False) -> tuple[list[ServeRequest], int] | None:
        """Release the next batch, FIFO: up to one top bucket of requests
        plus the smallest bucket that holds them. ``None`` when nothing is
        due (``force`` flushes whatever is pending — the closed-loop
        drain path). Deterministic: the decision uses only the injectable
        clock and the admission order."""
        now = self.clock()
        with self._lock:
            if not self._pending:
                return None
            if not force and not self._ready_locked(now):
                return None
            take = min(len(self._pending), self.buckets[-1])
            batch = self._pending[:take]
            del self._pending[:take]
        return batch, self.bucket_for(take)
