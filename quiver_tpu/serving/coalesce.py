"""Deadline-aware request coalescing for the online serving path.

Single-node point queries arrive one at a time; the compiled micro-batch
step (``serving/ladder.py``) wants power-of-two batches. The
:class:`DeadlineBatcher` bridges the two: it admits requests into a
bounded FIFO and releases them as a batch when either (a) enough requests
are pending to fill the largest ladder bucket, or (b) the *oldest*
pending request has spent its configured fraction of its deadline budget
waiting — the classic latency/throughput coalescing knob, here fully
deterministic under an injectable clock so the packing decision sequence
is a pure function of the arrival sequence (tests replay it bitwise).

Backpressure is a bounded queue: ``submit`` raises
:class:`ServeQueueFull` instead of growing without limit — an overloaded
server sheds load at admission, where the caller can still retry or
route elsewhere, not at completion where the work is already sunk.

Admission control is SLO-class aware (:data:`PRIORITIES`): every request
carries a priority class (``gold`` ahead of ``bronze``), each class can
have its own default deadline, and the shed policy under a full queue
drops bronze before gold — a gold arrival at the bound evicts the
newest pending bronze request (marked ``shed``, least sunk queue-wait)
instead of being rejected; only when no lower class is pending does
admission raise. Released batches pack gold first, so under mixed load
the scarce bucket lanes go to the tight-deadline class.
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np

__all__ = ["DeadlineBatcher", "PRIORITIES", "ServeQueueFull", "ServeRequest"]

#: SLO priority classes, best first — index order is the metric-vector
#: order of the per-class ``serve.*`` counters in the obs registry.
PRIORITIES = ("gold", "bronze")
_RANK = {p: i for i, p in enumerate(PRIORITIES)}


class ServeQueueFull(RuntimeError):
    """Admission rejected: the serving queue is at its bound. The caller
    owns the retry/shed decision — an unbounded queue would convert
    overload into unbounded latency for every later request instead."""


@dataclasses.dataclass
class ServeRequest:
    """One admitted point query and (after completion) its outcome.

    ``seq`` is the admission sequence number — it is folded into the
    server's base PRNG key (``fold_in(base_key, seq)``), so a request's
    sampled neighborhood is a function of (node, seq) alone, independent
    of which bucket it lands in and of its co-batched neighbors. That
    independence is what makes ladder-served responses bitwise equal to
    the direct single-query oracle.
    """

    node: int
    seq: int
    t_admit: float
    deadline_s: float
    priority: str = "gold"
    result: np.ndarray | None = None
    overflow: int = 0
    t_done: float | None = None
    missed: bool | None = None
    shed: bool = False
    trace_id: str = ""

    @property
    def deadline_at(self) -> float:
        return self.t_admit + self.deadline_s

    @property
    def done(self) -> bool:
        """Completed OR shed — either way the caller stops waiting (a
        shed request has ``shed=True`` and ``result is None``)."""
        return self.t_done is not None

    def latency_s(self) -> float | None:
        return None if self.t_done is None else self.t_done - self.t_admit


def ladder_buckets(max_batch: int) -> tuple[int, ...]:
    """The power-of-two bucket ladder up to ``max_batch``: (1, 2, 4, ...).

    ``max_batch`` must itself be a power of two — a non-power-of-two top
    bucket would make the padded tail of full batches permanent.
    """
    m = int(max_batch)
    if m < 1 or (m & (m - 1)) != 0:
        raise ValueError(f"max_batch must be a power of two >= 1, got {max_batch}")
    out, b = [], 1
    while b <= m:
        out.append(b)
        b *= 2
    return tuple(out)


class DeadlineBatcher:
    """Bounded FIFO that packs point queries into ladder buckets.

    Args:
      buckets: ascending batch-size ladder (see :func:`ladder_buckets`);
        the last entry is the largest batch a flush releases.
      default_deadline_s: per-request deadline when ``submit`` gives none.
      budget_fraction: fraction of a request's deadline it may spend
        *queued* before its presence forces a flush (the rest of the
        budget is reserved for sample/gather/forward/readback).
      max_queue: admission bound; ``submit`` past it sheds (bronze
        before gold) or raises :class:`ServeQueueFull`.
      clock: injectable monotonic clock — tests drive a fake clock and
        the flush sequence becomes deterministic in the arrival sequence.
      class_deadlines: optional per-priority-class default deadlines,
        e.g. ``{"gold": 0.02, "bronze": 0.1}`` — consulted when
        ``submit`` gives no explicit deadline, before the global
        ``default_deadline_s``.
    """

    def __init__(self, buckets=(1, 2, 4, 8), default_deadline_s: float = 0.05,
                 budget_fraction: float = 0.5, max_queue: int = 256,
                 clock=time.monotonic, class_deadlines: dict | None = None):
        buckets = tuple(int(b) for b in buckets)
        if not buckets or list(buckets) != sorted(set(buckets)):
            raise ValueError(f"buckets must be ascending and unique, got {buckets}")
        if any(b < 1 or (b & (b - 1)) != 0 for b in buckets):
            raise ValueError(f"buckets must be powers of two, got {buckets}")
        if not 0.0 < budget_fraction <= 1.0:
            raise ValueError(
                f"budget_fraction must be in (0, 1], got {budget_fraction}"
            )
        if max_queue < buckets[-1]:
            raise ValueError(
                f"max_queue ({max_queue}) must hold at least one full "
                f"top bucket ({buckets[-1]})"
            )
        class_deadlines = dict(class_deadlines or {})
        for p, d in class_deadlines.items():
            if p not in PRIORITIES:
                raise ValueError(
                    f"class_deadlines keys must be in {PRIORITIES}, got {p!r}"
                )
            if float(d) <= 0:
                raise ValueError(
                    f"class_deadlines[{p!r}] must be > 0, got {d}"
                )
        self.buckets = buckets
        self.default_deadline_s = float(default_deadline_s)
        self.budget_fraction = float(budget_fraction)
        self.max_queue = int(max_queue)
        self.clock = clock
        self.class_deadlines = {p: float(d) for p, d in class_deadlines.items()}
        self.shed_by_class = dict.fromkeys(PRIORITIES, 0)
        self._pending: list[ServeRequest] = []
        self._seq = 0
        self._lock = threading.Lock()

    # -- admission -----------------------------------------------------------

    def submit(self, node: int, deadline_s: float | None = None,
               priority: str = "gold") -> ServeRequest:
        """Admit one point query; returns the request handle the caller
        polls for results. At the bound the shed policy runs: a request
        evicts the NEWEST pending request of a strictly lower priority
        class (bronze drops before any gold — the victim is marked
        ``shed`` with no result, and chosen newest-first so the least
        sunk queue-wait is discarded); with nothing lower-class pending,
        admission raises :class:`ServeQueueFull`."""
        if priority not in PRIORITIES:
            raise ValueError(
                f"priority must be in {PRIORITIES}, got {priority!r}"
            )
        if deadline_s is not None:
            deadline = float(deadline_s)
        else:
            deadline = self.class_deadlines.get(
                priority, self.default_deadline_s
            )
        if deadline <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline}")
        now = self.clock()
        with self._lock:
            if len(self._pending) >= self.max_queue:
                victim = self._shed_victim_locked(_RANK[priority])
                if victim is None:
                    self.shed_by_class[priority] += 1
                    raise ServeQueueFull(
                        f"serving queue at bound ({self.max_queue}) with "
                        f"nothing below class {priority!r} to shed; retry "
                        f"after a drain or route elsewhere"
                    )
                victim.shed = True
                victim.t_done = now
                self.shed_by_class[victim.priority] += 1
                self._pending.remove(victim)
            req = ServeRequest(int(node), self._seq, now, deadline,
                               priority=priority)
            self._seq += 1
            self._pending.append(req)
        return req

    def _shed_victim_locked(self, rank: int) -> ServeRequest | None:
        """The newest pending request of a class strictly below ``rank``
        (None when every pending request is at or above it)."""
        for r in reversed(self._pending):
            if _RANK[r.priority] > rank:
                return r
        return None

    # -- flush decision ------------------------------------------------------

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def ready(self) -> bool:
        """True when a flush is due: the top bucket would be full, or
        some pending request has burned its queue-wait fraction of its
        deadline (with per-class deadlines a later-admitted gold request
        can come due before the oldest bronze — the check is a min over
        pending, which reduces to the oldest when deadlines are
        uniform)."""
        now = self.clock()
        with self._lock:
            return self._ready_locked(now)

    def _ready_locked(self, now: float) -> bool:
        if not self._pending:
            return False
        if len(self._pending) >= self.buckets[-1]:
            return True
        due = min(r.t_admit + self.budget_fraction * r.deadline_s
                  for r in self._pending)
        return now >= due

    def bucket_for(self, count: int) -> int:
        """Smallest ladder bucket holding ``count`` requests."""
        for b in self.buckets:
            if count <= b:
                return b
        return self.buckets[-1]

    def pop(self, force: bool = False) -> tuple[list[ServeRequest], int] | None:
        """Release the next batch: up to one top bucket of requests plus
        the smallest bucket that holds them, packed gold-first then by
        admission order (pure FIFO when a single class is in play).
        ``None`` when nothing is due (``force`` flushes whatever is
        pending — the closed-loop drain path). Deterministic: the
        decision uses only the injectable clock and the admission
        order."""
        now = self.clock()
        with self._lock:
            if not self._pending:
                return None
            if not force and not self._ready_locked(now):
                return None
            take = min(len(self._pending), self.buckets[-1])
            batch = sorted(
                self._pending, key=lambda r: (_RANK[r.priority], r.seq)
            )[:take]
            chosen = {id(r) for r in batch}
            self._pending = [r for r in self._pending if id(r) not in chosen]
        return batch, self.bucket_for(take)
