"""Backend-selection plumbing shared by examples and scripts.

Some deployment images register an accelerator plugin from
``sitecustomize`` at interpreter start — BEFORE user env vars are read —
which silently overrides ``JAX_PLATFORMS=cpu``. Backend init is lazy, so
an explicit ``jax.config`` update still wins as long as it happens before
the first device touch. The benchmark harness applies this itself
(benchmarks/common.init_backend); examples call :func:`honor_forced_platform`.
"""

from __future__ import annotations

import os

__all__ = ["honor_forced_platform"]


def honor_forced_platform() -> bool:
    """Apply an explicit ``JAX_PLATFORMS=cpu`` request via jax.config.

    Exact match only — a priority list like ``"tpu,cpu"`` is jax's business,
    not a forced-CPU request. Must run before the first backend touch.
    Returns True when CPU was forced.
    """
    plats = [
        p.strip().lower()
        for p in os.environ.get("JAX_PLATFORMS", "").split(",")
        if p.strip()
    ]
    if plats == ["cpu"]:
        import jax

        jax.config.update("jax_platforms", "cpu")
        return True
    return False
